"""fabric_tpu benchmark driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline metric (per BASELINE.json): validated tx/s on the peer commit
path — endorsement-signature verification plus MVCC read-set checks for
1000-tx blocks.  Until the full pipeline lands this measures the widest
slice currently built, against a single-thread CPU baseline measured
in-process (the reference publishes no absolute numbers; see
BASELINE.md — baseline = the same work done serially on host CPU).
"""

from __future__ import annotations

import json
import time


def _bench_p256_verify():
    """Batched ECDSA-P256 endorsement-signature verification vs host CPU.

    The unit of work of the reference's block-commit hot loop: ~2-3
    endorsement verifies per tx at a 2-of-3 policy on 1000-tx blocks
    (statebased/validator_keylevel.go:244-260) → a 2048-signature batch.
    CPU baseline: single-thread OpenSSL via `cryptography` (the
    reference's SW BCCSP equivalent).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature, encode_dss_signature,
    )

    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256

    B = 2048
    rng = np.random.default_rng(11)
    keys = [cec.generate_private_key(cec.SECP256R1()) for _ in range(8)]
    items, der_sigs = [], []
    for i in range(B):
        key = keys[i % len(keys)]
        msg = b"proposal-response-%d-" % i + rng.bytes(64)
        sig = key.sign(msg, cec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(sig)
        if s > p256.HALF_N:
            s = p256.N - s
        pub = key.public_key().public_numbers()
        items.append((ec_ref.digest_int(msg), r, s, pub.x, pub.y))
        der_sigs.append((key.public_key(), msg, encode_dss_signature(r, s)))

    # CPU baseline: serial verify via OpenSSL.
    t0 = time.perf_counter()
    for pub, msg, sig in der_sigs:
        pub.verify(sig, msg, cec.ECDSA(hashes.SHA256()))
    cpu_s = time.perf_counter() - t0

    # verify_host dispatches to the default kernel (v3 RNS/Cox-Rower
    # unless FABRIC_TPU_P256 selects v2/v1) — measure exactly what the
    # commit path runs, end to end including host-side preparation.
    out = p256.verify_host(items)  # compile
    assert all(out), "TPU verify rejected valid signatures"
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = p256.verify_host(items)
    tpu_s = (time.perf_counter() - t0) / reps

    tpu_rate = B / tpu_s
    cpu_rate = B / cpu_s
    return {
        "metric": "ecdsa_p256_verifies_per_sec_batch2048",
        "value": round(tpu_rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }


def _bench_endorse_sign():
    """Endorsement SIGNING: proposals/s at 1000-proposal batches — the
    upstream half of the transaction flow (ISSUE 13).

    CPU baseline: the production ``crypto/identity.py`` serial signing
    path (OpenSSL ECDSA via `cryptography`, one sign per proposal —
    what every endorsement pays today).  Device lane: RFC 6979 nonces
    + the fixed-base batch sign kernel (ops/p256sign), measured both
    as one raw 1000-lane dispatch and through the SignBatcher ingest
    path with 8 concurrent feeder threads (the gateway shape), with
    the batcher's occupancy/wait stats in extras.

    ``FABTPU_BENCH_SIGN=0`` reports the CPU baseline only (knob in
    extras); default 1 measures the device lane.  Skips cleanly
    without `cryptography` (main() gates it with the other
    crypto-dependent scenarios)."""
    import os
    import threading

    import numpy as np
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature, encode_dss_signature,
    )

    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256sign
    from fabric_tpu.peer import signlane

    B = 1000
    sign_on = os.environ.get("FABTPU_BENCH_SIGN", "1") == "1"
    rng = np.random.default_rng(13)
    key = cec.generate_private_key(cec.SECP256R1())
    d = key.private_numbers().private_value
    msgs = [b"proposal-response-payload-%d-" % i + rng.bytes(192)
            for i in range(B)]
    digests = [ec_ref.digest_int(m) for m in msgs]

    # CPU baseline: the serial identity.py path (sign + low-S + DER)
    t0 = time.perf_counter()
    for m in msgs:
        der = key.sign(m, cec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > ec_ref.HALF_N:
            s = ec_ref.N - s
        encode_dss_signature(r, s)
    cpu_s = time.perf_counter() - t0
    cpu_rate = B / cpu_s

    result = {
        "metric": "endorse_sign_proposals_per_sec_batch1000",
        "unit": "proposals/s",
        "extras": {"sign_device": int(sign_on), "cpu_serial_per_sec":
                   round(cpu_rate, 1)},
    }
    if not sign_on:
        result["value"] = round(cpu_rate, 1)
        result["vs_baseline"] = 1.0
        return result

    # raw device lane: one 1000-proposal batch per dispatch
    out = p256sign.sign_digests(digests, d)  # compile + correctness
    oracle = ec_ref.SigningKey(d)
    for e, (r, s) in zip(digests[:8], out[:8]):
        assert (r, s) == oracle.sign_digest(e), "device ≠ RFC6979 oracle"
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        p256sign.sign_digests(digests, d)
    dev_s = (time.perf_counter() - t0) / reps
    dev_rate = B / dev_s

    # ingest path: 8 concurrent feeders through the SignBatcher (the
    # gateway's concurrent-client shape) — includes digest + DER +
    # coalescing overhead, occupancy observable in stats()
    batcher = signlane.SignBatcher(
        signlane.device_sign_backend(d),
        batch_max=int(os.environ.get("FABTPU_BENCH_SIGN_BATCH", "256")),
        wait_ms=2.0,
    ).start()
    from fabric_tpu.observe import txflow as txflow_mod

    if txflow_mod.enabled():
        # the journal's sign_wait stage trail rides the lane's
        # observer hook, exactly as a sign_device peer wires it
        batcher.observer = txflow_mod.sign_observer()
    feeders = 8
    per = B // feeders

    def feed(lo):
        for m in msgs[lo:lo + per]:
            batcher.sign(m)

    t0 = time.perf_counter()
    ths = [threading.Thread(target=feed, args=(i * per,))
           for i in range(feeders)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    ingest_s = time.perf_counter() - t0
    st = batcher.stats()
    batcher.stop()

    result["value"] = round(dev_rate, 1)
    result["vs_baseline"] = round(dev_rate / cpu_rate, 3)
    result["extras"].update({
        "ingest_proposals_per_sec": round(feeders * per / ingest_s, 1),
        "sign_batch_occupancy": st["occupancy"],
        "sign_batch_wait_ms": st["wait_ms"],
        "sign_batches_total": st["batches_total"],
        "sign_busy_total": st["busy_total"],
    })
    return result


def _bench_sha256():
    """Batched block-payload hashing vs hashlib single-thread."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fabric_tpu.ops import sha256

    rng = np.random.default_rng(7)
    n = 4096
    msgs = [rng.bytes(200) for _ in range(n)]  # ~proposal-response size

    # CPU baseline: serial hashlib (C implementation).
    t0 = time.perf_counter()
    for m in msgs:
        hashlib.sha256(m).digest()
    cpu_s = time.perf_counter() - t0

    blocks, nb = sha256.pad_messages(msgs)
    db, dn = jnp.asarray(blocks), jnp.asarray(nb)
    out = sha256.sha256_blocks_jit(db, dn)  # compile
    # raw-kernel microbench: the whole wall IS the measurement — no
    # commit-path launch ledger exists to attribute it to
    jax.block_until_ready(out)  # fabtpu: noqa(FT016)
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        out = sha256.sha256_blocks_jit(db, dn)
    jax.block_until_ready(out)  # fabtpu: noqa(FT016)
    tpu_s = (time.perf_counter() - t0) / reps

    tpu_rate = n / tpu_s
    cpu_rate = n / cpu_s
    return {
        "metric": "sha256_hashes_per_sec_batch4096",
        "value": round(tpu_rate, 1),
        "unit": "hashes/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }


def _build_commit_network(n_tx: int, n_blocks: int = 1,
                          invalid_frac: float = 0.0,
                          validator_kwargs: dict | None = None,
                          block_plan: list | None = None,
                          hot_readonly: bool = False):
    """3 orgs, 2-of-3 endorsement policy, a STREAM of ``n_blocks``
    blocks of n_tx signed txs each, reading seeded keys and writing
    fresh ones — the BASELINE.json config-#2 workload (1000-tx blocks
    through the validator, 2-of-3 ECDSA-P256).

    ``invalid_frac``: fraction of txs made invalid (half broken
    creator signatures, half stale reads) — the commit path pays for
    failures too, and the perf number must survive adversarial
    traffic.

    ``block_plan``: optional per-block [(n_tx, invalid_frac)] — the
    bursty bench's mixed block sizes + seeded invalid-sig storms;
    overrides ``n_tx``/``n_blocks``/``invalid_frac`` and makes the
    returned ``n_invalid`` a PER-BLOCK list.

    ``hot_readonly`` (env ``FABTPU_BENCH_HOT=1``): the per-tx
    read-only key becomes BLOCK-INDEPENDENT (``ro{i}`` instead of
    ``ro{b}_{i}``) — a hot working set re-read by every block, the
    realistic traffic shape the device-resident state cache
    (``FABTPU_BENCH_RESIDENT=1``) exists for.  Run the resident A/B
    with the SAME hot-workload setting on both sides."""
    from fabric_tpu import protoutil as pu
    from fabric_tpu.crypto import cryptogen, policy as pol
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.ledger.rwset import TxRWSet
    from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
    from fabric_tpu.peer import txassembly as txa
    from fabric_tpu.peer.validator import (
        BlockValidator, NamespaceInfo, PolicyProvider,
    )

    CHANNEL, CC = "benchchan", "benchcc"
    orgs = [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.example.com", peers=1, users=1)
        for i in (1, 2, 3)
    ]
    mgr = MSPManager({o.msp().msp_id: o.msp() for o in orgs})
    peers = [
        cryptogen.signing_identity(o, f"peer0.org{i}.example.com")
        for i, o in zip((1, 2, 3), orgs)
    ]
    client = cryptogen.signing_identity(orgs[0], "User1@org1.example.com")
    policy = pol.from_dsl(
        "OutOf(2, 'Org1MSP.peer', 'Org2MSP.peer', 'Org3MSP.peer')"
    )
    prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})

    import math

    if block_plan is None:
        plan = [(n_tx, invalid_frac)] * n_blocks
    else:
        plan = [(int(t), float(f)) for t, f in block_plan]
        n_blocks = len(plan)

    seed = UpdateBatch()
    for b, (b_tx, _f) in enumerate(plan):
        for i in range(b_tx):
            seed.put(CC, f"seed{b}_{i:05d}", b"genesis", (1, 0))
            if not hot_readonly:
                seed.put(CC, f"ro{b}_{i:05d}", b"genesis", (1, 0))
    if hot_readonly:
        for i in range(max(t for t, _f in plan)):
            seed.put(CC, f"ro{i:05d}", b"genesis", (1, 0))

    def _stride(frac):
        return math.inf if frac <= 0 else max(2, round(1 / frac))

    n_invalid_list = [
        0 if _stride(f) == math.inf
        else len(range(0, t, int(_stride(f))))
        for t, f in plan
    ]
    n_invalid_per_block = (
        n_invalid_list if block_plan is not None else n_invalid_list[0]
    )
    blocks, prev = [], b""
    for b, (b_tx, b_frac) in enumerate(plan):
        stride = _stride(b_frac)
        envs = []
        for i in range(b_tx):
            _, _, prop = txa.create_signed_proposal(client, CHANNEL, CC, [b"invoke"])
            tx = TxRWSet()
            ns = tx.ns_rwset(CC)
            bad = stride != math.inf and i % int(stride) == 0
            # alternate the failure mode by slot (i is a stride
            # multiple, so parity of i itself would never alternate)
            bad_stale = bad and (i // int(stride)) % 2 == 1
            if bad_stale:
                ns.reads[f"seed{b}_{i:05d}"] = (9, 9)  # stale → conflict
            else:
                ns.reads[f"seed{b}_{i:05d}"] = (1, 0)
            # never written in-block; hot mode re-reads ONE working
            # set across every block (the residency cache's hit lane)
            ro_key = (f"ro{i:05d}" if hot_readonly
                      else f"ro{b}_{i:05d}")
            ns.reads[ro_key] = (1, 0)
            ns.writes[f"w{b}_{i:05d}"] = b"value-%d" % i
            ns.writes[f"seed{b}_{i:05d}"] = b"updated"
            rw = tx.to_proto().SerializeToString()
            two = (peers[i % 3], peers[(i + 1) % 3])  # rotating 2-of-3
            resps = [txa.create_proposal_response(prop, rw, e, CC) for e in two]
            env = txa.assemble_transaction(prop, resps, client)
            if bad and not bad_stale:
                env.signature = env.signature[:-4] + bytes(4)  # bad creator
            envs.append(env)
        blk = pu.new_block(b, prev)
        for env in envs:
            blk.data.data.append(env.SerializeToString())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)

    def fresh_state():
        db = MemVersionedDB()
        db.apply_updates(seed, (1, 0))
        return db

    created: list = []

    def fresh_validator(state):
        # microbatched device verify (ops/p256v3.py): set e.g. 1024
        # for ~3 chunks per 1000-tx block so chunk k's device compute
        # overlaps chunk k+1's host staging.  Default 0 (monolithic):
        # on a CPU-only host the "device" shares the cores with the
        # staging, so chunking only adds dispatch overhead (measured
        # +23% on the 2-core container — see CHANGES.md PR 2); enable
        # on real-TPU rounds where the overlap is real.
        # host_stage_workers / recode_device (ops/p256v3 + hostpool):
        # shard the host staging over cores and shrink the H2D frame —
        # the one knob pair that can win on a multi-core CPU host too,
        # since it parallelizes the HOST side, not the device.
        k = _bench_knobs()
        v = BlockValidator(
            mgr, prov, state, verify_chunk=k["verify_chunk"],
            mesh_devices=k["shards"] or k["mesh_devices"],
            host_stage_workers=k["host_stage_workers"],
            recode_device=bool(k["recode_device"]),
            state_resident=bool(k["state_resident"]),
            state_resident_mb=k["state_resident_mb"],
            **(validator_kwargs or {}),
        )
        created.append(v)  # the bench reads pool stats off the last one
        return v

    fresh_validator.created = created
    return blocks, fresh_state, fresh_validator, mgr, prov, CC, n_invalid_per_block


def _bench_knobs() -> dict:
    """Commit-path knobs under bench, from env — all default OFF so the
    CPU-only container measures the unsharded monolithic path (like
    verify_chunk, mesh sharding and launch coalescing only win on a
    real accelerator; a 1-device mesh resolves to None and a
    coalesce < 2 never groups)."""
    import os

    return {
        "verify_chunk": int(os.environ.get("FABTPU_BENCH_VERIFY_CHUNK", "0")),
        "mesh_devices": int(os.environ.get("FABTPU_BENCH_MESH", "0")),
        # shard-count A/B (parallel/mesh partition rules): overrides
        # FABTPU_BENCH_MESH when set, so `FABTPU_BENCH_SHARDS=4` vs
        # `=8` sweeps the data-axis width with one knob; the JSON's
        # extras.shard_balance attributes the skew either way
        "shards": int(os.environ.get("FABTPU_BENCH_SHARDS", "0")),
        "coalesce_blocks": int(os.environ.get("FABTPU_BENCH_COALESCE", "0")),
        # host staging pool workers (0 = serial staging, so CPU-only
        # containers measure the unpooled path unregressed; -1 = cores)
        "host_stage_workers": int(
            os.environ.get("FABTPU_BENCH_HOST_WORKERS", "0")
        ),
        # 1 = ship u1/u2 as limbs and recode windows on device
        "recode_device": int(os.environ.get("FABTPU_BENCH_RECODE", "0")),
        # commit-pipeline depth (peer/pipeline.py): 2 = the classic
        # overlap (default — CPU containers keep the exact current
        # path); 3+ = deep window with merged overlays + deferred
        # fsyncs, the real-TPU knob.  Sweep it (2, 3, 4) on accelerator
        # rounds so BENCH_*.json attributes the win to the depth.
        "pipeline_depth": int(os.environ.get("FABTPU_BENCH_DEPTH", "2")),
        # device-resident MVCC state (fabric_tpu/state): 1 = the fused
        # stage-2 reads committed versions from the resident LRU cache
        # and the host state_fill shrinks to the miss set.  Measure it
        # BOTH WAYS with FABTPU_BENCH_HOT=1 on both sides (a hot
        # working set is what residency caches; the default per-block
        # cold keys miss every time by construction).
        "state_resident": int(
            os.environ.get("FABTPU_BENCH_RESIDENT", "0")
        ),
        "state_resident_mb": int(
            os.environ.get("FABTPU_BENCH_RESIDENT_MB", "64")
        ),
        # 1 = block-independent read-only working set (see
        # _build_commit_network hot_readonly)
        "hot_readonly": int(os.environ.get("FABTPU_BENCH_HOT", "0")),
        # decoupled commit engine (ledger/committer.py): 1 = block-store
        # append stays on the critical path, state-DB apply drains on
        # the background applier (default — the peer node's production
        # setting); 0 = the serial engine for the A/B.  The A/B number
        # to watch is per_block_ms.ledger_commit: async ON removes the
        # state_apply portion from the submit→commit critical path.
        "async_commit": int(
            os.environ.get("FABTPU_BENCH_ASYNC_COMMIT", "1")
        ),
    }


def _bench_async_commit() -> bool:
    """FABTPU_BENCH_ASYNC_COMMIT=0 pins the serial commit engine for
    the A/B; default 1 benches the decoupled committer."""
    import os

    return os.environ.get("FABTPU_BENCH_ASYNC_COMMIT", "1") == "1"


def _vitals_capture(interval_s: float = 0.25):
    """``FABTPU_BENCH_VITALS=1``: arm a run-local flight-data sampler
    (fabric_tpu.observe.timeseries.MetricsSampler) over the process
    registry — short interval, deep ring — for the scenario's whole
    duration.  Returns None (and costs nothing) when the knob is off,
    so default bench runs keep the recorder-less hot path."""
    import os

    if os.environ.get("FABTPU_BENCH_VITALS", "0") != "1":
        return None
    from fabric_tpu.observe.timeseries import MetricsSampler

    s = MetricsSampler(interval_s=float(
        os.environ.get("FABTPU_BENCH_VITALS_INTERVAL_S", interval_s)
    ), retention=4096)
    s.start()
    return s


def _vitals_extras(sampler) -> dict | None:
    """Stop a :func:`_vitals_capture` sampler and dump its FULL metric
    trails for the BENCH_*.json extras (delta-aware series per metric
    and label variant — the attribution record)."""
    if sampler is None:
        return None
    sampler.stop()
    sampler.sample()  # final pass so the scenario's tail lands
    rep = sampler.report()
    return {
        "interval_s": sampler.interval_s,
        "samples": rep["samples"],
        "series_count": rep["series_count"],
        "series": sampler.series(),
    }


def _ledger_capture():
    """Arm the process-global launch ledger for the scenario — every
    bench then ships ``extras.device_ledger`` (per-kernel compile/
    queue/execute/transfer decomposition, cache hit rates, HBM
    watermarks) in its JSON line, so BENCH_r06's ``device_wait``
    arrives pre-decomposed.  Default ON; ``FABTPU_BENCH_LEDGER=0``
    keeps the ledger-less hot path for overhead measurement."""
    import os

    if os.environ.get("FABTPU_BENCH_LEDGER", "1") != "1":
        return None
    from fabric_tpu.observe import ledger as ledger_mod

    return ledger_mod.configure()


def _txflow_capture():
    """Arm the process-global tx-flow journal for the scenario —
    block-commit benches then ship ``extras.tx_flow`` (per-stage and
    e2e percentiles, visibility lag, last completed flows) and
    endorse_sign ships its sign-wait trail.  Default ON;
    ``FABTPU_BENCH_TXFLOW=0`` keeps the journal-less hot path — the
    overhead A/B for the <2% tx/s acceptance gate."""
    import os

    if os.environ.get("FABTPU_BENCH_TXFLOW", "1") != "1":
        return None
    from fabric_tpu.observe import txflow as txflow_mod

    return txflow_mod.configure()


def _txflow_extras(j) -> dict | None:
    """Snapshot the tx-flow journal for the BENCH_*.json extras."""
    if j is None:
        return None
    return j.report(rows=8)


def _ledger_extras(led) -> dict | None:
    """Snapshot the launch ledger for the BENCH_*.json extras,
    including a ground-truth ``jax.live_arrays()`` HBM sample."""
    if led is None:
        return None
    from fabric_tpu.observe.ledger import live_device_bytes

    out = led.report(rows=8)
    live = live_device_bytes()
    if live is not None:
        out["live_device_bytes"] = live
    return out


def _host_stage_extras(fresh_validator) -> dict | None:
    """host_stage sub-breakdown for the JSON extras: resolved worker
    count, per-shard p50, and the recode location — read off the last
    validator the run built (None when the pool knob is off)."""
    created = getattr(fresh_validator, "created", None)
    if not created:
        return None
    v = created[-1]
    if v.host_pool is None and not v.recode_device:
        return None
    out = {"recode": "device" if v.recode_device else "host"}
    if v.host_pool is not None:
        out.update(v.host_pool.stats())
    else:
        out["workers"] = 0
    return out


def _resident_extras(fresh_validator) -> dict | None:
    """Device-resident state sub-breakdown for the JSON extras (the
    BENCH_r06 attribution numbers): hit rate, evictions, uploaded
    state bytes — read off the last validator the run built; None
    when the resident knob is off."""
    created = getattr(fresh_validator, "created", None)
    if not created:
        return None
    res = getattr(created[-1], "resident", None)
    if res is None:
        return None
    return res.stats()


def _shard_balance_extras(fresh_validator) -> dict | None:
    """extras.shard_balance: per-shard occupancy skew of the key-range
    resident table plus the mesh data-axis width and the silent
    single-device fallback counts (parallel/mesh
    ``mesh_shard_fallback_total``) — read off the last validator the
    run built; None when no mesh resolved (the CPU-only default)."""
    created = getattr(fresh_validator, "created", None)
    if not created:
        return None
    v = created[-1]
    mesh = getattr(v, "mesh", None)
    if mesh is None:
        return None
    from fabric_tpu.parallel import mesh as pmesh

    out = {"data_axis": pmesh.data_axis_size(mesh)}
    fb = pmesh.fallback_stats()
    if fb:
        out["fallbacks"] = fb
    res = getattr(v, "resident", None)
    if res is not None:
        out.update(res.shard_balance())
    return out


def _close_validators(fresh_validator) -> None:
    """Shut every run's staging pool down once its stats are read —
    the `created` list pins the validators, so GC alone would leak the
    worker threads across the bench's multiple runs."""
    for v in getattr(fresh_validator, "created", ()):
        v.close()


def _serial_baseline_validate(blk, mgr, prov, state):
    """The reference's commit path re-done serially on host CPU: per tx
    parse → creator sig (OpenSSL) → endorsement sigs (OpenSSL) →
    consumption policy walk → serial MVCC with write application
    (v20/validator.go:180 + validation/validator.go:81, one thread)."""
    import numpy as np

    from fabric_tpu import protoutil as pu
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.ledger.rwset import TxRWSet
    from fabric_tpu.protos import common_pb2, transaction_pb2

    C = transaction_pb2.TxValidationCode
    codes = []
    updates: dict = {}
    plan_cache: dict = {}  # compile once per namespace, like the reference
    for env_bytes in blk.data.data:
        env = pu.unmarshal(common_pb2.Envelope, env_bytes)
        try:
            ch, sh, cap, prp, cca = pu.extract_action(env)
        except pu.TxParseError as e:
            codes.append(e.code)
            continue
        creator = mgr.deserialize_identity(sh.creator)
        if not creator.is_valid or not creator.verify(env.payload, env.signature):
            codes.append(C.BAD_CREATOR_SIGNATURE)
            continue
        idents, valid = [], []
        prp_bytes = cap.action.proposal_response_payload
        for e in cap.action.endorsements:
            ident = mgr.deserialize_identity(e.endorser)
            idents.append(ident)
            valid.append(
                ident.is_valid
                and ident.verify(prp_bytes + e.endorser, e.signature)
            )
        rwset = TxRWSet.from_bytes(cca.results)
        ok = True
        for ns_name in rwset.ns:
            info = prov.info(ns_name)
            if info is None:
                ok = False
                break
            plan = plan_cache.get(ns_name)
            if plan is None:
                plan = plan_cache[ns_name] = pol.compile_plan(info.policy)
            m = pol.match_matrix(idents, plan.principals)
            m = m & np.asarray(valid, bool)[:, None]
            if not pol.evaluate(info.policy, m):
                ok = False
                break
        if not ok:
            codes.append(C.ENDORSEMENT_POLICY_FAILURE)
            continue
        # serial MVCC vs committed state + in-block updates
        conflict = False
        for ns_name, n in rwset.ns.items():
            for k, ver in n.reads.items():
                if (ns_name, k) in updates:
                    conflict = True
                    break
                cv = state.get_version(ns_name, k)
                if cv != ver:
                    conflict = True
                    break
            if conflict:
                break
        if conflict:
            codes.append(C.MVCC_READ_CONFLICT)
            continue
        for ns_name, n in rwset.ns.items():
            for k in n.writes:
                updates[(ns_name, k)] = True
        codes.append(C.VALID)
    return bytes(codes), updates


def _bench_block_commit(n_tx: int = 1000, n_blocks: int = 5,
                        invalid_frac: float = 0.0):
    """North-star metric (BASELINE.json): sustained validated tx/s per
    peer on a stream of 1000-tx blocks with a 2-of-3 ECDSA-P256
    endorsement policy, through BlockValidator + KVLedger.commit_block,
    vs the same stream done serially on one host CPU thread.

    The TPU path pipelines like the real peer (deliver prefetch,
    gossip/state/state.go:540): block n+1's host parse + device launch
    overlaps block n's device verify + commit."""
    import shutil
    import tempfile

    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2

    bk = _bench_knobs()
    (blocks, fresh_state, fresh_validator, mgr, prov, _,
     n_invalid) = _build_commit_network(
        n_tx, n_blocks, invalid_frac=invalid_frac,
        hot_readonly=bool(bk["hot_readonly"]),
    )
    expected_valid = (n_tx - n_invalid) * n_blocks
    depth = bk["pipeline_depth"]

    def copy_blocks():
        out = []
        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            out.append(b)
        return out

    engine_stats: dict | None = None

    def run_tpu(timings=None):
        nonlocal engine_stats
        state = fresh_state()
        stream = copy_blocks()
        tmp = tempfile.mkdtemp(prefix="benchledger")
        lg = KVLedger(tmp, state_db=state, enable_history=True,
                      async_commit=_bench_async_commit())
        # the validator reads through lg.state: under the async engine
        # that is the pending-batch overlay, so MVCC preloads see
        # queued-but-unapplied batches exactly like committed state
        v = fresh_validator(lg.state)
        v.timings = timings
        n_valid = 0

        def commit_fn(res):
            t0 = time.perf_counter()
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)
            if timings is not None:
                timings["ledger_commit"] = (
                    timings.get("ledger_commit", 0.0)
                    + time.perf_counter() - t0
                )
                # critical-path decomposition: block-store append vs
                # state apply (under async the latter is submit cost)
                for tk, tv in lg.last_commit_timings.items():
                    timings[tk] = timings.get(tk, 0.0) + tv

        # the production CommitPipeline (peer/pipeline.py — the same
        # subsystem the peer node's deliver loop commits through):
        # while block n sits on device (verify+policy+MVCC) and up to
        # depth−1 predecessors' ledger commits drain on the committer
        # thread, the prefetch thread parses block n+1; the in-flight
        # predecessors' UpdateBatches ride as a merged launch overlay
        # so launch(n) never waits for any predecessor's fsync.
        # FABTPU_BENCH_DEPTH sweeps the window (default 2).
        t0 = time.perf_counter()
        with CommitPipeline(v, commit_fn, depth=depth) as pipe:
            for b in stream:
                res = pipe.submit(b)
                if res is not None:
                    n_valid += res.n_valid
            res = pipe.flush()
            if res is not None:
                n_valid += res.n_valid
            dt = time.perf_counter() - t0
        if lg.engine is not None:
            engine_stats = lg.engine.stats()
        lg.close()
        shutil.rmtree(tmp, ignore_errors=True)
        return dt, n_valid

    run_tpu()  # compile + warm every cache
    runs = []
    for _ in range(3):  # min-of-3: tunnel jitter
        tm: dict = {}
        dt, nv = run_tpu(timings=tm)
        runs.append((dt, nv, tm))
    tpu_s = min(dt for dt, _, _ in runs)
    total = n_tx * n_blocks
    assert runs[0][1] == expected_valid, (
        f"expected {expected_valid} valid, got {runs[0][1]}"
    )

    # tracer cost + trace artifact: the runs above ran with the span
    # tracer at its always-on default; FABTPU_BENCH_TRACE exports their
    # flight recorder as Perfetto-loadable Chrome JSON, and a
    # trace_ring_blocks=0 re-run measures the tracer's overhead so a
    # regression in its cost is visible in BENCH_*.json
    trace_extras = None
    overlap_cov = None
    if invalid_frac == 0.0:
        import os

        from fabric_tpu import observe

        tracer = observe.global_tracer()
        trace_path = os.environ.get("FABTPU_BENCH_TRACE", "")
        if trace_path:
            tracer.export_chrome(trace_path)
        # pipeline overlap coverage off the traced runs' flight
        # recorder (observe/overlap.py): what fraction of each block's
        # device_wait the k±(depth−1) neighbors' host stages hid — the
        # ROADMAP's deep-pipelining acceptance as a tracked number.
        # Computed BEFORE the ring=0 overhead re-run truncates the
        # ring.
        overlap_cov = observe.coverage_from_roots(
            tracer.recent_roots(), window=max(1, depth - 1)
        )
        overlap_cov.pop("per_block", None)
        prev_ring = tracer.ring_blocks
        observe.configure(ring_blocks=0)
        try:
            # same sample count as the traced side (min-of-3): an
            # asymmetric min would let run-to-run jitter masquerade as
            # (often negative) tracer overhead
            off_s = min(run_tpu()[0] for _ in range(3))
        finally:
            observe.configure(ring_blocks=prev_ring)
        trace_extras = {
            "trace_overhead_pct": round((tpu_s - off_s) / off_s * 100, 2),
            "traced_s": round(tpu_s, 4),
            "untraced_s": round(off_s, 4),
            "ring_blocks": prev_ring,
        }

    # per-phase breakdown artifact (ms/block of the fastest run) so the
    # next bottleneck is measured, not guessed; the mixed variant must
    # not clobber the clean run's file
    best_tm = min(runs, key=lambda r: r[0])[2]
    per_block_ms = {
        k: round(1000.0 * v / n_blocks, 2)
        for k, v in sorted(best_tm.items())
    }
    if invalid_frac == 0.0:
        try:
            import os

            with open(
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_breakdown.json"), "w"
            ) as f:
                json.dump({
                    "n_tx": n_tx, "n_blocks": n_blocks,
                    "total_s": round(tpu_s, 4),
                    "per_block_ms": per_block_ms,
                }, f, indent=1)
        except OSError:
            pass

    # serial host baseline (same stream, same storage, one thread)
    def run_cpu():
        state = fresh_state()
        stream = copy_blocks()
        tmp = tempfile.mkdtemp(prefix="benchledgercpu")
        lg = KVLedger(tmp, state_db=state, enable_history=True)
        from fabric_tpu.ledger.statedb import UpdateBatch

        n_valid = 0
        t0 = time.perf_counter()
        for b in stream:
            codes, updates = _serial_baseline_validate(b, mgr, prov, state)
            batch = UpdateBatch()
            for (ns_name, k) in updates:
                batch.put(ns_name, k, b"x", (b.header.number, 0))
            lg.commit_block(b, codes, batch, [])
            n_valid += sum(1 for c in codes if c == 0)
        dt = time.perf_counter() - t0
        lg.close()
        shutil.rmtree(tmp, ignore_errors=True)
        return dt, n_valid

    cpu_runs = [run_cpu() for _ in range(2)]
    cpu_s = min(dt for dt, _ in cpu_runs)
    assert cpu_runs[0][1] == expected_valid

    tpu_rate = total / tpu_s
    cpu_rate = total / cpu_s
    host_stage = _host_stage_extras(fresh_validator)
    resident = _resident_extras(fresh_validator)
    shard_balance = _shard_balance_extras(fresh_validator)
    _close_validators(fresh_validator)
    return {
        "metric": f"validated_tx_per_sec_block{n_tx}" + ("_mixed" if invalid_frac else ""),
        "value": round(tpu_rate, 1),
        "unit": "tx/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
        "per_block_ms": per_block_ms,
        "host_stage": host_stage,
        # the resident A/B record: hit rate / evictions / uploaded
        # state bytes next to the state_fill ms in per_block_ms
        "resident_state": resident,
        # per-shard lane counts / key-range occupancy skew when a mesh
        # resolved (FABTPU_BENCH_SHARDS or FABTPU_BENCH_MESH)
        "shard_balance": shard_balance,
        # apply-queue telemetry of the final timed run (None when the
        # serial engine ran, i.e. FABTPU_BENCH_ASYNC_COMMIT=0)
        "commit_engine": engine_stats,
        "trace": trace_extras,
        "pipeline_overlap_coverage": overlap_cov,
    }


def _bench_block_commit_sustained(n_tx: int = 1000, n_blocks: int = 50):
    """Sustained commit-path run (VERDICT Missing #1): ≥ 50 blocks
    streamed through the depth-2 CommitPipeline, reporting p50/p99
    BLOCK-COMMIT LATENCY (submit → ledger commit complete, per block)
    alongside tx/s.  The long stream keeps the blockstore's
    group-commit fsync windows (default: every 8 blocks) INSIDE the
    measurement — a 5-block sprint amortizes durability away.

    Knobs ride env (reported in the JSON): FABTPU_BENCH_VERIFY_CHUNK,
    FABTPU_BENCH_MESH (mesh_devices), FABTPU_BENCH_COALESCE
    (CommitPipeline.submit_many group size)."""
    import shutil
    import tempfile

    import numpy as np

    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2

    knobs = _bench_knobs()
    (blocks, fresh_state, fresh_validator, mgr, prov, _,
     n_invalid) = _build_commit_network(
        n_tx, n_blocks, hot_readonly=bool(knobs["hot_readonly"])
    )
    expected_valid = (n_tx - n_invalid) * n_blocks

    state = fresh_state()
    stream = []
    for blk in blocks:
        b = common_pb2.Block()
        b.CopyFrom(blk)
        stream.append(b)
    tmp = tempfile.mkdtemp(prefix="benchsustained")
    lg = KVLedger(tmp, state_db=state, enable_history=True,
                  async_commit=_bench_async_commit())
    v = fresh_validator(lg.state)
    n_valid = 0
    submit_t: dict[int, float] = {}
    commit_t: dict[int, float] = {}
    commit_path: dict[str, float] = {}

    def commit_fn(res):
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids, res.pend.hd_bytes)
        commit_t[res.block.header.number] = time.perf_counter()
        for tk, tv in lg.last_commit_timings.items():
            commit_path[tk] = commit_path.get(tk, 0.0) + tv

    coalesce = knobs["coalesce_blocks"]
    t0 = time.perf_counter()
    with CommitPipeline(v, commit_fn, depth=knobs["pipeline_depth"],
                        coalesce_blocks=coalesce) as pipe:
        if coalesce >= 2:
            for lo in range(0, len(stream), coalesce):
                group = stream[lo:lo + coalesce]
                now = time.perf_counter()
                for b in group:
                    submit_t[b.header.number] = now
                for res in pipe.submit_many(group):
                    n_valid += res.n_valid
        else:
            for b in stream:
                submit_t[b.header.number] = time.perf_counter()
                res = pipe.submit(b)
                if res is not None:
                    n_valid += res.n_valid
        res = pipe.flush()
        if res is not None:
            n_valid += res.n_valid
        dt = time.perf_counter() - t0
    group_commit = lg.blocks.group_commit
    engine_stats = lg.engine.stats() if lg.engine is not None else None
    lg.close()
    shutil.rmtree(tmp, ignore_errors=True)
    assert n_valid == expected_valid, (n_valid, expected_valid)

    # deep-pipelining acceptance number off the run's flight recorder:
    # device_wait(k) coverage by k±(depth−1) neighbor host stages
    from fabric_tpu import observe

    overlap_cov = observe.coverage_from_roots(
        observe.global_tracer().recent_roots(),
        window=max(1, knobs["pipeline_depth"] - 1),
    )
    overlap_cov.pop("per_block", None)

    host_stage = _host_stage_extras(fresh_validator)
    resident = _resident_extras(fresh_validator)
    shard_balance = _shard_balance_extras(fresh_validator)
    _close_validators(fresh_validator)
    # per-block commit latency; the first 3 blocks eat the compiles
    # and cache warms — excluded from the percentiles, stated as such
    lats = sorted(
        commit_t[n] - submit_t[n]
        for n in commit_t if n in submit_t and n >= 3
    )
    arr = np.asarray(lats)
    total = n_tx * n_blocks
    rate = total / dt
    return {
        "metric": f"sustained_tx_per_sec_block{n_tx}x{n_blocks}",
        "value": round(rate, 1),
        "unit": "tx/s",
        "vs_baseline": 1.0,  # self-contained: no serial re-run at 50 blocks
        "extras": {
            "latency_ms": {
                "p50": round(float(np.percentile(arr, 50)) * 1000, 2),
                "p99": round(float(np.percentile(arr, 99)) * 1000, 2),
                "max": round(float(arr.max()) * 1000, 2),
                "n_measured": int(len(arr)),
                "warmup_blocks_excluded": 3,
            },
            "knobs": knobs,
            "host_stage": host_stage,
            "resident_state": resident,
            "shard_balance": shard_balance,
            "group_commit": group_commit,
            # submit→commit critical-path decomposition (ms/block):
            # under async the state_apply row is the queue submit cost
            "commit_path_ms": {
                tk: round(1000.0 * tv / n_blocks, 3)
                for tk, tv in sorted(commit_path.items())
            },
            "commit_engine": engine_stats,
            "pipeline_overlap_coverage": overlap_cov,
        },
    }


def _bench_block_commit_chaos(n_tx: int = 200, n_blocks: int = 24,
                              seed: int = 20260803):
    """Chaos soak (ISSUE 6): a SEEDED FaultPlan — probabilistic
    device-launch faults plus one mid-stream disconnect injected at
    the pipeline's prefetch stage (the in-process stand-in for a
    deliver-stream cut; the real ``deliver.read`` point needs a live
    orderer, which a bench host doesn't have) — against the depth-2
    CommitPipeline with the device-lane guard armed (retry → degraded
    CPU fallback → recovery probe) and the deliver driver's
    containment loop (stage failure → drain pipe → resume from
    committed height).  The run must commit EVERY block
    exactly once with the fault-free accept set; the JSON reports the
    recovery economics: degraded-mode seconds, device retries,
    CPU-fallback blocks, pipe restarts, injected-fault stats, and
    p50/p99 block-commit latency UNDER chaos."""
    import shutil
    import tempfile

    import numpy as np

    from fabric_tpu import faults
    from fabric_tpu.faults import FaultPlan
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.ops_metrics import global_registry
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2

    guard_kwargs = {
        "device_fail_threshold": 2,
        "device_retries": 1,
        "device_recovery_s": 0.2,
        "channel": "chaos",
    }
    (blocks, fresh_state, fresh_validator, mgr, prov, _,
     n_invalid) = _build_commit_network(
        n_tx, n_blocks, validator_kwargs=guard_kwargs
    )
    expected_valid = (n_tx - n_invalid) * n_blocks

    state = fresh_state()
    stream = []
    for blk in blocks:
        b = common_pb2.Block()
        b.CopyFrom(blk)
        stream.append(b)
    tmp = tempfile.mkdtemp(prefix="benchchaos")
    lg = KVLedger(tmp, state_db=state, enable_history=True,
                  async_commit=_bench_async_commit())
    v = fresh_validator(lg.state)

    height = [0]
    submit_t: dict[int, float] = {}
    commit_t: dict[int, float] = {}

    def commit_fn(res):
        num = res.block.header.number
        assert num == height[0], "commit out of order under chaos"
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids,
                        res.pend.hd_bytes)
        commit_t[num] = time.perf_counter()
        height[0] = num + 1

    plan = FaultPlan(
        "validator.verify_launch:raise:p=0.35;"
        f"pipeline.prefetch:disconnect:n=1:after={n_blocks // 2}",
        seed=seed,
    )
    reg = global_registry()
    retries_ctr = reg.counter("device_verify_retries_total")
    fallback_ctr = reg.counter("fallback_blocks_total")
    retries0 = retries_ctr.value(channel="chaos")
    fallback0 = fallback_ctr.value(channel="chaos")

    faults.install(plan)
    restarts = 0
    t0 = time.perf_counter()
    try:
        # the deliver driver's containment loop, in miniature: a stage
        # exception fails the pipe closed; rebuild and resume from the
        # last committed height (the replay check skips what landed)
        pipe = CommitPipeline(v, commit_fn, depth=2)
        while True:
            try:
                for b in stream[height[0]:]:
                    if b.header.number < height[0]:
                        continue
                    submit_t[b.header.number] = time.perf_counter()
                    pipe.submit(b)
                pipe.flush()
                break
            except Exception:
                restarts += 1
                assert restarts < 100, "chaos bench cannot converge"
                pipe.close(flush=False)
                # the accept-set check recounts from the committed
                # ledger below — res handoffs would miscount across
                # restarts
                pipe = CommitPipeline(v, commit_fn, depth=2)
        dt = time.perf_counter() - t0
        pipe.close()
    finally:
        faults.reset()
    degraded_s = (
        v.device_guard.degraded_seconds() if v.device_guard else 0.0
    )
    # accept-set check straight off the committed ledger (restart-safe)
    from fabric_tpu import protoutil as pu

    got_valid = 0
    for n in range(lg.height):
        flt = pu.get_tx_filter(lg.blocks.get_block(n))
        got_valid += sum(1 for c in flt if c == 0)
    assert lg.height == n_blocks, (lg.height, n_blocks)
    assert got_valid == expected_valid, (got_valid, expected_valid)
    group_commit = lg.blocks.group_commit
    lg.close()
    shutil.rmtree(tmp, ignore_errors=True)
    host_stage = _host_stage_extras(fresh_validator)
    _close_validators(fresh_validator)

    # -- sidecar-kill phase (ISSUE 8): the same network streamed
    # through a loopback validation sidecar that is KILLED mid-stream
    # and restarted later — blocks must route through the local
    # fallback latch (liveness) and the client must re-attach via the
    # recovery probe, converging to the fault-free accept set
    sidecar_kill = None
    try:
        sidecar_kill = _chaos_sidecar_kill(
            blocks[:12], fresh_state, mgr, prov, n_tx
        )
    except Exception as e:  # the headline chaos number must still print
        sidecar_kill = {"error": f"{type(e).__name__}: {str(e)[:200]}"}

    lats = sorted(
        commit_t[n] - submit_t[n]
        for n in commit_t if n in submit_t and n >= 3
    )
    arr = np.asarray(lats)
    total = n_tx * n_blocks
    return {
        "metric": f"chaos_tx_per_sec_block{n_tx}x{n_blocks}",
        "value": round(total / dt, 1),
        "unit": "tx/s",
        "vs_baseline": 1.0,  # self-contained: correctness + recovery run
        "extras": {
            "faults_injected": plan.stats(),
            "fault_seed": seed,
            "degraded_mode_s": round(degraded_s, 4),
            "device_verify_retries": int(
                retries_ctr.value(channel="chaos") - retries0
            ),
            "fallback_blocks": int(
                fallback_ctr.value(channel="chaos") - fallback0
            ),
            "pipe_restarts": restarts,
            "latency_ms": {
                "p50": round(float(np.percentile(arr, 50)) * 1000, 2),
                "p99": round(float(np.percentile(arr, 99)) * 1000, 2),
                "max": round(float(arr.max()) * 1000, 2),
                "n_measured": int(len(arr)),
                "warmup_blocks_excluded": 3,
            },
            "accept_set": "matches fault-free expectation "
                          f"({expected_valid} valid tx)",
            "guard": guard_kwargs,
            "group_commit": group_commit,
            "knobs": _bench_knobs(),
            "sidecar_kill": sidecar_kill,
        },
    }


def _chaos_sidecar_kill(blocks, fresh_state, mgr, prov, n_tx) -> dict:
    """See ``_bench_block_commit_chaos``: kill the sidecar after block
    3 commits, restart it before block 8, assert the committed accept
    set equals the fault-free expectation and the lane re-armed."""
    import shutil
    import tempfile

    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.ops_metrics import global_registry
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2
    from fabric_tpu.sidecar.validator import SidecarValidator

    n_blocks = len(blocks)
    host = _SidecarHost(queue_blocks=8, coalesce=2)
    state = fresh_state()
    tmp = tempfile.mkdtemp(prefix="benchsidecarkill")
    lg = KVLedger(tmp, state_db=state, enable_history=True,
                  async_commit=_bench_async_commit())
    v = SidecarValidator(
        mgr, prov, lg.state,
        sidecar_endpoint=f"127.0.0.1:{host.port}",
        channel="sidecar-kill",
        sidecar_fail_threshold=1, sidecar_recovery_s=0.05,
        sidecar_timeout_s=5.0,
    )
    stream = []
    for blk in blocks:
        b = common_pb2.Block()
        b.CopyFrom(blk)
        stream.append(b)
    fallback_ctr = global_registry().counter("fallback_blocks_total")
    fallback0 = fallback_ctr.value(channel="sidecar-kill")

    def commit_fn(res):
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids, res.pend.hd_bytes)

    try:
        with CommitPipeline(v, commit_fn, depth=2) as pipe:
            for b in stream:
                n = b.header.number
                if n == 4:
                    host.stop_server()      # mid-stream kill
                if n == 8:
                    host.restart_server()   # sidecar returns, same port
                pipe.submit(b)
            pipe.flush()
        from fabric_tpu import protoutil as pu

        got_valid = 0
        for n in range(lg.height):
            flt = pu.get_tx_filter(lg.blocks.get_block(n))
            got_valid += sum(1 for c in flt if c == 0)
        assert lg.height == n_blocks, (lg.height, n_blocks)
        assert got_valid == n_tx * n_blocks, (got_valid, n_tx * n_blocks)
        return {
            "blocks": n_blocks,
            "killed_at_block": 4,
            "restarted_at_block": 8,
            "accept_set": "matches fault-free expectation",
            "fallback_blocks": int(
                fallback_ctr.value(channel="sidecar-kill") - fallback0
            ),
            "degraded_mode_s": round(
                v.sidecar_guard.degraded_seconds(), 4
            ),
            "reattached": not v.sidecar_guard.degraded,
        }
    finally:
        v.close()
        lg.close()
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            host.stop_server()
        except Exception:  # fabtpu: noqa(FT005)
            # already stopped by the kill when the run failed early
            pass
        host.close()


class _SidecarHost:
    """A loopback validation sidecar on a private event-loop thread —
    the bench's stand-in for the standalone ``sidecar-serve`` process,
    running the REAL server/scheduler/device-dispatch stack."""

    def __init__(self, **kw):
        import asyncio
        import threading

        from fabric_tpu.sidecar.server import SidecarServer

        self._asyncio = asyncio
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, name="bench-sidecar",
            daemon=True,
        )
        self.thread.start()
        self.server = SidecarServer(**kw)
        self.run(self.server.start())
        self.port = self.server.port
        self._kw = kw

    def run(self, coro, timeout=60.0):
        return self._asyncio.run_coroutine_threadsafe(
            coro, self.loop
        ).result(timeout)

    def stop_server(self):
        self.run(self.server.stop())

    def restart_server(self):
        from fabric_tpu.sidecar.server import SidecarServer

        kw = dict(self._kw)
        kw["port"] = self.port
        self.server = SidecarServer(**kw)
        self.run(self.server.start())

    def close(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5.0)


def _bench_block_commit_sidecar(n_tx: int = 200, n_blocks: int = 12):
    """The multi-tenant story as a tracked number (ISSUE 8): TWO
    tenant peers (weights 1 and 3) stream blocks concurrently through
    ONE loopback validation sidecar — the real
    server/scheduler/link/SidecarValidator stack, cross-tenant batches
    coalesced into shared device dispatches.  Reports aggregate
    validated tx/s, per-tenant p50/p99 block-commit latency, and a
    weighted Jain fairness index over served-signature shares (1.0 =
    shares exactly track weights)."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2
    from fabric_tpu.sidecar.validator import SidecarValidator

    (blocks, fresh_state, _fresh_validator, mgr, prov, _,
     n_invalid) = _build_commit_network(n_tx, n_blocks)
    expected_valid = (n_tx - n_invalid) * n_blocks
    knobs = _bench_knobs()

    host = _SidecarHost(
        mesh_devices=knobs["shards"] or knobs["mesh_devices"],
        verify_chunk=knobs["verify_chunk"],
        recode_device=bool(knobs["recode_device"]),
        queue_blocks=8, coalesce=4,
    )
    tenants = [("tenant0", 1.0), ("tenant1", 3.0)]
    results: dict = {}
    errors: list = []

    def drive(name: str, weight: float):
        state = fresh_state()
        tmp = tempfile.mkdtemp(prefix=f"benchsidecar-{name}")
        lg = KVLedger(tmp, state_db=state, enable_history=True,
                      async_commit=_bench_async_commit())
        v = SidecarValidator(
            mgr, prov, lg.state,
            sidecar_endpoint=f"127.0.0.1:{host.port}",
            sidecar_weight=weight, channel=name,
            sidecar_fail_threshold=2, sidecar_recovery_s=0.5,
            sidecar_timeout_s=60.0,
        )
        stream = []
        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            stream.append(b)
        submit_t: dict[int, float] = {}
        commit_t: dict[int, float] = {}
        n_valid = [0]

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)
            commit_t[res.block.header.number] = time.perf_counter()
            n_valid[0] += res.n_valid

        try:
            t0 = time.perf_counter()
            with CommitPipeline(v, commit_fn, depth=2,
                                channel=name) as pipe:
                for b in stream:
                    submit_t[b.header.number] = time.perf_counter()
                    pipe.submit(b)
                pipe.flush()
            dt = time.perf_counter() - t0
            lats = sorted(
                commit_t[n] - submit_t[n]
                for n in commit_t if n in submit_t and n >= 2
            )
            results[name] = {
                "dt": dt, "n_valid": n_valid[0], "lats": lats,
                "fallback": v.sidecar_guard.degraded_seconds(),
            }
        except Exception as e:  # surfaced after join
            errors.append(f"{name}: {type(e).__name__}: {e}")
        finally:
            v.close()
            lg.close()
            shutil.rmtree(tmp, ignore_errors=True)

    # end-of-run SLO burn snapshot (ISSUE 9): a local engine rides the
    # global tracer's finished-block stream for the run's duration —
    # per-tenant block-commit latency burn + sidecar BUSY burn become
    # tracked numbers, so a fairness regression that starves one
    # tenant shows up as that tenant's burn rate, not just a Jain dip
    from fabric_tpu import observe as _observe
    from fabric_tpu.observe import slo as _slo
    from fabric_tpu.ops_metrics import Registry as _Registry

    slo_engine = _slo.SloEngine(
        _slo.parse_slos(
            "block_commit:latency:ms=2000:target=0.95:windows=1200;"
            "sidecar_busy:busy:pct=20:windows=1200"
        ),
        registry=_Registry(),
    )
    _observe.global_tracer().add_listener(slo_engine.on_block)
    # cold compiles land on the first dispatches; like the sustained
    # bench, the first 2 blocks are excluded from the percentiles and
    # the persistent .jax_cache covers repeat rounds
    try:
        threads = [
            threading.Thread(target=drive, args=t, daemon=True)
            for t in tenants
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200.0)
        hung = [t.name for t in threads if t.is_alive()]
        dt = time.perf_counter() - t0
        sched_stats = host.server.scheduler.stats()
        host.stop_server()
    finally:
        _observe.global_tracer().remove_listener(slo_engine.on_block)
        host.close()
    assert not hung, f"tenant drive thread(s) timed out: {hung}"
    assert not errors, errors
    for name, _w in tenants:
        assert results[name]["n_valid"] == expected_valid, (
            name, results[name]["n_valid"], expected_valid
        )

    # weighted Jain fairness over served-signature shares: x_i =
    # share_i / weight_i, J = (Σx)² / (n·Σx²) — 1.0 means shares track
    # weights exactly.  The scheduler retains disconnected tenants'
    # totals, so reading after the drive threads closed is safe.
    xs = [
        sched_stats[name]["share"] / w
        for name, w in tenants if name in sched_stats
    ]
    jain = (
        round(sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs)), 4)
        if xs and sum(xs) else None
    )

    def pcts(name):
        arr = np.asarray(results[name]["lats"])
        return {
            "p50": round(float(np.percentile(arr, 50)) * 1000, 2),
            "p99": round(float(np.percentile(arr, 99)) * 1000, 2),
            "n_measured": int(len(arr)),
        }

    total = 2 * n_tx * n_blocks
    return {
        "metric": f"sidecar_tx_per_sec_2tenants_block{n_tx}x{n_blocks}",
        "value": round(total / dt, 1),
        "unit": "tx/s",
        "vs_baseline": 1.0,  # self-contained multi-tenant scenario
        "extras": {
            "tenants": {
                name: {
                    "weight": w,
                    "latency_ms": pcts(name),
                    "tx_per_sec": round(
                        n_tx * n_blocks / results[name]["dt"], 1
                    ),
                    # per-tenant fairness signals off the scheduler:
                    # time-in-queue percentiles + BUSY pushback rate
                    "queue_age_ms": sched_stats.get(name, {}).get(
                        "queue_age_ms"
                    ),
                    "busy_rate": sched_stats.get(name, {}).get(
                        "busy_rate"
                    ),
                }
                for name, w in tenants
            },
            "fairness_jain_weighted": jain,
            "scheduler": sched_stats,
            "slo": slo_engine.report(),
            "coalesce": 4,
            "queue_blocks": 8,
            "knobs": knobs,
        },
    }


def _bench_block_commit_bursty(n_blocks: int = 18,
                               seed: int = 20260804):
    """p99 UNDER OVERLOAD as a tracked number (ISSUE 11): an
    OPEN-LOOP bursty stream — block arrivals ride a fixed schedule
    that does NOT wait for the server, so backlog shows up as latency
    (arrival → commit), exactly what a closed-loop bench hides —
    through the loopback validation sidecar, with:

    * **mixed block sizes** (alternating large/small blocks);
    * **seeded invalid-sig storms**: a ``faults/`` FaultPlan decides
      which blocks arrive with ~half their creator signatures broken
      (deterministic replay per seed) — invalid lanes cost the full
      verify + reject path;
    * **config churn**: scripted mid-stream runtime re-knob pulses
      (pipeline depth up then back, coalesce toggled) through the new
      block-boundary setters — the safe-re-knobbing path under load;
    * ``FABTPU_BENCH_AUTOPILOT=1``: a live traffic autopilot
      (fabric_tpu/control) reads the run's SLO burns + scheduler
      telemetry and actuates shed/weights/coalesce — ON-vs-OFF is one
      env flip, and the end-of-run actuation log lands in extras.

    Reports per-tenant p50/p99/max ARRIVAL→commit latency, shed/BUSY
    counts off the scheduler, the SLO burn snapshot, and asserts the
    committed accept set equals the build plan's fault-free
    expectation for every block (shed requests fall back to the local
    CPU lane — liveness and verdicts are never traded)."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from fabric_tpu import observe as _observe
    from fabric_tpu.control import Autopilot
    from fabric_tpu.faults import FaultPlan, InjectedFault
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.observe import slo as _slo
    from fabric_tpu.ops_metrics import Registry as _Registry
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2
    from fabric_tpu.sidecar.validator import SidecarValidator

    import os

    autopilot_on = os.environ.get("FABTPU_BENCH_AUTOPILOT", "0") == "1"
    knobs = _bench_knobs()

    # seeded storm plan: which blocks arrive as an invalid-sig storm
    # (the faults registry supplies the deterministic replay; the
    # corruption itself is real broken creator signatures)
    storm_plan = FaultPlan("bursty.storm:raise:p=0.35:after=3",
                          seed=seed)
    block_plan = []
    storm_blocks = []
    for b in range(n_blocks):
        storm = False
        try:
            storm_plan.fire("bursty.storm", block=b)
        except InjectedFault:
            storm = True
            storm_blocks.append(b)
        n_tx = 600 if b % 3 == 0 else 150  # mixed block sizes
        block_plan.append((n_tx, 0.5 if storm else 0.0))
    (blocks, fresh_state, _fv, mgr, prov, _,
     n_invalid) = _build_commit_network(0, block_plan=block_plan)
    expected_valid = sum(
        t - bad for (t, _f), bad in zip(block_plan, n_invalid)
    )

    host = _SidecarHost(queue_blocks=4, coalesce=4)
    # open-loop arrival schedule: the bursty tenant fires well above
    # the 2-core container's service rate during storms; the steady
    # tenant paces modestly — its p99 is the collateral-damage number
    arrivals = {
        "bursty": [0.05 * b for b in range(n_blocks)],
        "steady": [0.40 * b for b in range(n_blocks)],
    }
    results: dict = {}
    errors: list = []
    pipes: dict = {}
    validators: dict = {}

    def drive(name: str, weight: float):
        state = fresh_state()
        tmp = tempfile.mkdtemp(prefix=f"benchbursty-{name}")
        lg = KVLedger(tmp, state_db=state, enable_history=True,
                      async_commit=_bench_async_commit())
        v = SidecarValidator(
            mgr, prov, lg.state,
            sidecar_endpoint=f"127.0.0.1:{host.port}",
            sidecar_weight=weight, channel=name,
            sidecar_fail_threshold=1, sidecar_recovery_s=0.5,
            sidecar_timeout_s=30.0,
        )
        validators[name] = v
        stream = []
        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            stream.append(b)
        commit_t: dict[int, float] = {}
        arrive_t: dict[int, float] = {}

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)
            commit_t[res.block.header.number] = time.perf_counter()

        try:
            with CommitPipeline(v, commit_fn, depth=2,
                                channel=name) as pipe:
                pipes[name] = pipe
                t0 = time.perf_counter()
                for b in stream:
                    n = b.header.number
                    # OPEN LOOP: wait for the schedule, never for the
                    # server — a backlog shows up as latency
                    delay = t0 + arrivals[name][n] - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    arrive_t[n] = time.perf_counter()
                    # config churn: scripted runtime re-knob pulses at
                    # fixed stream positions exercise the
                    # block-boundary setters under load (the autopilot
                    # layers its own actuations on top when armed)
                    if name == "bursty" and n == n_blocks // 3:
                        pipe.set_depth(3)
                        v.set_verify_chunk(1024)
                    if name == "bursty" and n == 2 * n_blocks // 3:
                        pipe.set_depth(2)
                        v.set_verify_chunk(0)
                    pipe.submit(b)
                pipe.flush()
            # ledger accept set ≡ the build plan's fault-free
            # expectation: overload machinery must shed REQUESTS
            # (to BUSY + CPU fallback), never correctness
            from fabric_tpu import protoutil as pu

            got_valid = 0
            for n in range(lg.height):
                flt = pu.get_tx_filter(lg.blocks.get_block(n))
                got_valid += sum(1 for c in flt if c == 0)
            assert lg.height == n_blocks, (name, lg.height, n_blocks)
            assert got_valid == expected_valid, (
                name, got_valid, expected_valid
            )
            lats = sorted(
                commit_t[n] - arrive_t[n]
                for n in commit_t if n in arrive_t and n >= 2
            )
            results[name] = {
                "lats": lats,
                "fallback_s": v.sidecar_guard.degraded_seconds(),
            }
        except Exception as e:  # surfaced after join
            errors.append(f"{name}: {type(e).__name__}: {e}")
        finally:
            pipes.pop(name, None)
            validators.pop(name, None)
            v.close()
            lg.close()
            shutil.rmtree(tmp, ignore_errors=True)

    slo_engine = _slo.SloEngine(
        _slo.parse_slos(
            "commit:latency:ms=1500:target=0.9:windows=600:"
            "min_events=3;"
            "busy:busy:pct=10:windows=600:min_events=3"
        ),
        registry=_Registry(),
    )
    _observe.global_tracer().add_listener(slo_engine.on_block)
    pilot = None
    if autopilot_on:
        def _apply(knob, value):
            if knob == "verify_chunk":
                for v in list(validators.values()):
                    v.set_verify_chunk(value)
                return
            for pipe in list(pipes.values()):
                if knob == "coalesce_blocks":
                    pipe.set_coalesce_blocks(value)
                elif knob == "pipeline_depth":
                    pipe.set_depth(value)

        pilot = Autopilot(
            None, _apply,
            set_weight=host.server.scheduler.set_weight,
            set_shed=host.server.scheduler.set_shed,
            slo=slo_engine, scheduler=host.server.scheduler,
            tick_s=0.25, registry=_Registry(),
            bands={"shed_hi": 2.0, "burn_hi": 1.2},
        )
        host.server.autopilot = pilot
        pilot.start()
    tenants = [("bursty", 1.0), ("steady", 1.0)]
    try:
        threads = [
            threading.Thread(target=drive, args=t, daemon=True)
            for t in tenants
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200.0)
        hung = [t.name for t in threads if t.is_alive()]
        dt = time.perf_counter() - t0
        sched_stats = host.server.scheduler.stats()
        host.stop_server()
    finally:
        if pilot is not None:
            pilot.stop()
        _observe.global_tracer().remove_listener(slo_engine.on_block)
        host.close()
    assert not hung, f"tenant drive thread(s) timed out: {hung}"
    assert not errors, errors

    def pcts(name):
        arr = np.asarray(results[name]["lats"])
        if not len(arr):
            return None
        return {
            "p50": round(float(np.percentile(arr, 50)) * 1000, 2),
            "p99": round(float(np.percentile(arr, 99)) * 1000, 2),
            "max": round(float(arr.max()) * 1000, 2),
            "n_measured": int(len(arr)),
        }

    total = 2 * sum(t for t, _f in block_plan)
    return {
        "metric": f"bursty_tx_per_sec_2tenants_{n_blocks}blocks",
        "value": round(total / dt, 1),
        "unit": "tx/s",
        "vs_baseline": 1.0,  # self-contained overload scenario
        "extras": {
            "autopilot": autopilot_on,
            "open_loop_arrival_s": {
                k: v[1] - v[0] for k, v in arrivals.items()
            },
            "storm_blocks": storm_blocks,
            "storm_plan": storm_plan.stats(),
            "storm_seed": seed,
            "block_sizes": [t for t, _f in block_plan],
            "latency_arrival_to_commit_ms": {
                name: pcts(name) for name, _w in tenants
            },
            "shed_busy": {
                name: {
                    "shed_count": sched_stats.get(name, {}).get(
                        "shed_count", 0
                    ),
                    "rejected": sched_stats.get(name, {}).get(
                        "rejected", 0
                    ),
                    "busy_rate": sched_stats.get(name, {}).get(
                        "busy_rate", 0.0
                    ),
                    "local_fallback_s": round(
                        results[name]["fallback_s"], 4
                    ),
                }
                for name, _w in tenants
            },
            "slo": slo_engine.report(),
            "actuations": (
                [d.to_dict() for d in pilot.decisions]
                if pilot is not None else []
            ),
            "scheduler": sched_stats,
            "knobs": knobs,
        },
    }


def _bench_host_stage_micro(B: int = 3072, n_keys: int = 2048,
                            reps: int = 15):
    """Standalone stage micro-bench for the host-cycle-elimination
    levers — CRYPTO-FREE (synthetic byte columns / synthetic state),
    so it runs on containers without ``cryptography`` and isolates the
    two stages the depth-N PR vectorized:

    * ``sig_prepare``: the two-phase HEAD path (allocating
      ``prepare_cols`` + ``pack_cols``) vs the single-pass
      ``prepare_cols_packed`` (native strided window writes, no
      intermediate eight-array staging) at the production 3072-lane
      batch;
    * ``state_fill``: the HEAD committed-version fill (dict-building
      ``get_versions_bulk`` + per-unique-key Python loop) vs the fused
      ``get_versions_cols`` column gather, at a production-like
      unique-read-key count.

    Reports per-stage p50 ms over ``reps`` runs plus the combined p50
    delta — the PR's acceptance number."""
    import numpy as np

    from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
    from fabric_tpu.ops import p256v3 as v3
    from fabric_tpu.ops import rns

    rng = np.random.default_rng(20260804)
    digest_b = rng.integers(0, 256, (B, 32), np.uint8)
    r_b = rng.integers(0, 256, (B, 32), np.uint8)
    s_b = rng.integers(0, 256, (B, 32), np.uint8)
    s_b[:, 0] &= 0x3F  # keep most lanes admissible (s ≤ n/2-ish)
    r_b[:, 0] &= 0x7F
    qx = rng.integers(0, 4096, (B, 2 * rns.N_CH)).astype(np.int32)
    qy = rng.integers(0, 4096, (B, 2 * rns.N_CH)).astype(np.int32)
    pub_ok = np.ones(B, bool)
    cols = (digest_b, r_b, s_b, qx, qy, pub_ok)
    pad = v3._bucket(B)

    def p50(fn):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1000.0

    two_phase = p50(lambda: v3.pack_cols(
        *v3.prepare_cols(*cols, pad_to=pad)
    ))
    packed = p50(lambda: v3.prepare_cols_packed(*cols, pad_to=pad))
    # equivalence sanity inside the bench itself
    assert np.array_equal(
        v3.pack_cols(*v3.prepare_cols(*cols, pad_to=pad)),
        v3.prepare_cols_packed(*cols, pad_to=pad),
    ), "packed staging diverged from the two-phase path"

    # -- state_fill: committed-version fill over unique read keys ----
    state = MemVersionedDB()
    seed = UpdateBatch()
    for i in range(n_keys):
        seed.put("ns", f"k{i:06d}", b"v", (1, i))
    state.apply_updates(seed, (1, 0))
    # 75% present / 25% absent, shuffled — the realistic miss mix
    pairs = [("ns", f"k{i:06d}") for i in range(n_keys)]
    pairs += [("ns", f"miss{i:06d}") for i in range(n_keys // 3)]
    rng.shuffle(pairs)
    pairs = [tuple(p) for p in pairs]
    U = len(pairs)

    def head_fill():
        up = np.zeros(U, bool)
        uv = np.zeros((U, 2), np.uint32)
        vers = state.get_versions_bulk(pairs)
        vget = vers.get
        for ui, pr in enumerate(pairs):
            v = vget(pr)
            if v is not None:
                up[ui] = True
                uv[ui] = v
        return up, uv

    dict_path = p50(head_fill)
    cols_path = p50(lambda: state.get_versions_cols(pairs))
    a = head_fill()
    b = state.get_versions_cols(pairs)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])

    try:
        from fabric_tpu.native import ecprep_lib

        lib = ecprep_lib()
        native = lib is not None and hasattr(lib, "ec_prepare_pack")
    except Exception:
        native = False
    combined_head = two_phase + dict_path
    combined_new = packed + cols_path
    return {
        "metric": f"host_stage_micro_b{B}",
        "value": round(combined_new, 3),
        "unit": "ms",
        # <1.0 = the new combined path is faster than HEAD's
        "vs_baseline": round(combined_new / combined_head, 3)
        if combined_head else 1.0,
        "extras": {
            "sig_prepare_ms": {
                "two_phase_p50": round(two_phase, 3),
                "packed_p50": round(packed, 3),
            },
            "state_fill_ms": {
                "dict_path_p50": round(dict_path, 3),
                "cols_path_p50": round(cols_path, 3),
                "unique_keys": U,
            },
            "combined_p50_ms": {
                "head": round(combined_head, 3),
                "new": round(combined_new, 3),
            },
            "lanes": B,
            "native_ec_prepare_pack": native,
            "reps": reps,
        },
    }


def _bench_chain_replay(n_tx: int = 1000, n_blocks: int = 12):
    """ISSUE 18 catch-up ceiling (the BENCH_r06 full-occupancy
    workload): a staged chain replayed from a real ``BlockStore``
    through ``peer/replay.py`` at the configured depth — zero
    inter-block think time, block read + proto decode prefetched on
    the driver's reader thread — vs the OPEN-LOOP feed (the
    ``block_commit`` shape: the same store iterated on the submit
    thread, so each block's read + decode sits on the critical path).

    The delta between the two IS the driver's contribution; the
    replay side's ``pipeline_overlap_coverage`` (extras) is the
    ROADMAP acceptance — ≈ 1.0 means the window never drains and any
    residual ``device_wait`` queue time is real pipeline headroom."""
    import os
    import shutil
    import tempfile

    from fabric_tpu import observe
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.peer.replay import replay_into
    from fabric_tpu.protos import common_pb2

    bk = _bench_knobs()
    depth = bk["pipeline_depth"]
    (blocks, fresh_state, fresh_validator, _mgr, _prov, _,
     n_invalid) = _build_commit_network(
        n_tx, n_blocks, hot_readonly=bool(bk["hot_readonly"]),
    )
    expected_valid = (n_tx - n_invalid) * n_blocks
    tmp_root = tempfile.mkdtemp(prefix="benchreplay")

    # stage the SOURCE chain once: a real block store holding the
    # whole stream (this pass also warms every compile cache)
    src_lg = KVLedger(os.path.join(tmp_root, "src"),
                      state_db=fresh_state(), enable_history=True,
                      async_commit=_bench_async_commit())
    v0 = fresh_validator(src_lg.state)

    def src_commit(res):
        src_lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)

    with CommitPipeline(v0, src_commit, depth=depth) as pipe:
        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            pipe.submit(b)
        pipe.flush()
    assert src_lg.height == n_blocks

    def run_replay(i: int):
        """One full catch-up into a fresh destination ledger."""
        dest = os.path.join(tmp_root, f"replay{i}")
        lg = KVLedger(dest, state_db=fresh_state(), enable_history=True,
                      async_commit=_bench_async_commit())
        v = fresh_validator(lg.state)
        stats = replay_into(
            lg, v, src_lg.blocks, depth=depth,
            checkpoint=os.path.join(dest, "replay_checkpoint.json"),
            coalesce_blocks=bk["coalesce_blocks"],
            tracer=observe.global_tracer(),
        )
        lg.close()
        return stats

    def run_open_loop(i: int):
        """The block_commit shape over the SAME store: read + decode
        inline on the submit thread, no prefetch-ahead."""
        dest = os.path.join(tmp_root, f"open{i}")
        lg = KVLedger(dest, state_db=fresh_state(), enable_history=True,
                      async_commit=_bench_async_commit())
        v = fresh_validator(lg.state)
        n_valid = [0]

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)
            n_valid[0] += res.n_valid

        t0 = time.perf_counter()
        with CommitPipeline(v, commit_fn, depth=depth) as pipe:
            for blk in src_lg.blocks.iter_blocks(0):
                pipe.submit(blk)
            pipe.flush()
        dt = time.perf_counter() - t0
        lg.close()
        return dt, n_valid[0]

    replay_runs = [run_replay(i) for i in range(3)]
    best = min(replay_runs, key=lambda s: s["seconds"])
    assert best["txs_valid"] == expected_valid, (
        f"expected {expected_valid} valid, got {best['txs_valid']}"
    )
    assert best["height"] == n_blocks
    open_runs = [run_open_loop(i) for i in range(2)]
    open_s = min(dt for dt, _ in open_runs)
    assert open_runs[0][1] == expected_valid

    total = n_tx * n_blocks
    replay_rate = total / best["seconds"]
    open_rate = total / open_s
    src_lg.close()
    _close_validators(fresh_validator)
    shutil.rmtree(tmp_root, ignore_errors=True)
    return {
        "metric": f"chain_replay_tx_per_sec_block{n_tx}",
        "value": round(replay_rate, 1),
        "unit": "tx/s",
        # the driver's contribution over the open-loop feed at the
        # SAME depth — >1.0 means prefetch-ahead decode paid
        "vs_baseline": round(replay_rate / open_rate, 3),
        "extras": {
            "knobs": _bench_knobs(),
            "replay": {
                "blocks_per_s": best["blocks_per_s"],
                "seconds": best["seconds"],
                "depth": best["depth"],
            },
            "open_loop": {
                "tx_per_s": round(open_rate, 1),
                "blocks_per_s": round(n_blocks / open_s, 2),
                "seconds": round(open_s, 4),
            },
            "pipeline_overlap_coverage": best.get(
                "pipeline_overlap_coverage"
            ),
        },
    }


def _bench_snapshot_join(n_tx: int = 1000, n_blocks: int = 12,
                         join_at: int = 6):
    """ISSUE 18 snapshot-then-replay join: export Fabric-shaped state
    at height ``join_at``, bootstrap a fresh peer from it (state DB +
    resident-cache warm, no genesis→H replay), replay ``join_at``..end
    from the serving store — vs the full replay-from-genesis oracle.
    The joined ledger must be byte-identical to the oracle (state
    digest + commit hash), and the headline number is the wall-clock
    speedup of joining over full replay."""
    import os
    import shutil
    import tempfile

    from fabric_tpu import observe
    from fabric_tpu.ledger import snapshot as snaplib
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.peer.replay import replay_into
    from fabric_tpu.protos import common_pb2

    bk = _bench_knobs()
    depth = bk["pipeline_depth"]
    (blocks, fresh_state, fresh_validator, _mgr, _prov, _,
     _n_invalid) = _build_commit_network(
        n_tx, n_blocks, hot_readonly=bool(bk["hot_readonly"]),
    )
    tmp_root = tempfile.mkdtemp(prefix="benchsnapjoin")

    # stage the serving peer: commit to join_at, snapshot, commit on
    src_lg = KVLedger(os.path.join(tmp_root, "src"),
                      state_db=fresh_state(), enable_history=True,
                      async_commit=_bench_async_commit())
    v0 = fresh_validator(src_lg.state)

    def src_commit(res):
        src_lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)

    snap_dir = os.path.join(tmp_root, "snap")
    with CommitPipeline(v0, src_commit, depth=depth) as pipe:
        for blk in blocks[:join_at]:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            pipe.submit(b)
        pipe.flush()
        meta = snaplib.generate_snapshot(src_lg, snap_dir,
                                         channel_id="bench")
        for blk in blocks[join_at:]:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            pipe.submit(b)
        pipe.flush()
    assert meta["height"] == join_at and src_lg.height == n_blocks

    def run_full(i: int) -> float:
        dest = os.path.join(tmp_root, f"full{i}")
        lg = KVLedger(dest, state_db=fresh_state(), enable_history=True,
                      async_commit=_bench_async_commit())
        v = fresh_validator(lg.state)
        t0 = time.perf_counter()
        replay_into(lg, v, src_lg.blocks, depth=depth,
                    tracer=observe.global_tracer())
        dt = time.perf_counter() - t0
        digest = lg.state_digest()
        chash = lg.commit_hash
        lg.close()
        if i == 0:
            run_full.oracle = (digest, chash)
        return dt

    def run_join(i: int):
        dest = os.path.join(tmp_root, f"join{i}")
        t0 = time.perf_counter()
        # the import applies snapshot state in bulk — no validation,
        # no per-block commits, an EMPTY state DB to land in
        lg, _meta = snaplib.create_from_snapshot(
            snap_dir, dest, state_db=MemVersionedDB(),
            async_commit=_bench_async_commit(),
        )
        import_s = time.perf_counter() - t0
        v = fresh_validator(lg.state)
        # resident warm straight from the snapshot's key ranges
        # (FABTPU_BENCH_RESIDENT=1): the first replayed block starts
        # with the working set already device-resident
        t0 = time.perf_counter()
        warmed = snaplib.warm_resident(
            getattr(v, "resident", None), snap_dir
        )
        warm_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        stats = replay_into(lg, v, src_lg.blocks, depth=depth,
                            tracer=observe.global_tracer())
        replay_s = time.perf_counter() - t0
        digest = lg.state_digest()
        chash = lg.commit_hash
        height = lg.height
        lg.close()
        return {
            "total_s": import_s + warm_s + replay_s,
            "import_s": import_s, "warm_s": warm_s,
            "replay_s": replay_s, "warmed_keys": warmed,
            "digest": digest, "commit_hash": chash,
            "height": height, "replayed_blocks": stats["blocks"],
        }

    full_s = min(run_full(i) for i in range(2))
    joins = [run_join(i) for i in range(2)]
    best = min(joins, key=lambda j: j["total_s"])
    oracle_digest, oracle_hash = run_full.oracle
    # the acceptance pin: snapshot-then-replay ≡ replay-from-genesis
    assert best["height"] == n_blocks
    assert best["digest"] == oracle_digest, "joined state diverged"
    assert best["commit_hash"] == oracle_hash, "commit chain diverged"
    src_lg.close()
    _close_validators(fresh_validator)
    shutil.rmtree(tmp_root, ignore_errors=True)
    return {
        "metric": f"snapshot_join_speedup_block{n_tx}",
        # join wall-clock vs full replay: > 1.0 means skipping
        # genesis→H validation paid (grows with chain length — the
        # replayed suffix is the only validated work)
        "value": round(full_s / best["total_s"], 3),
        "unit": "x",
        "vs_baseline": round(full_s / best["total_s"], 3),
        "extras": {
            "knobs": _bench_knobs(),
            "full_replay_s": round(full_s, 4),
            "join": {k: (round(vv, 4) if isinstance(vv, float) else vv)
                     for k, vv in best.items()
                     if k not in ("digest", "commit_hash")},
            "byte_identical": True,
            "snapshot_height": join_at,
        },
    }


_BENCHES = {
    "block_commit": _bench_block_commit,
    # VERDICT Missing #1: sustained ≥50-block stream with p50/p99
    # block-commit latency (group-commit fsync windows included)
    "block_commit_sustained": _bench_block_commit_sustained,
    # adversarial-traffic variant: ~10% invalid lanes (bad creator
    # sigs + stale reads) — the throughput number must survive
    # failure-bearing blocks, not just happy-path streams
    "block_commit_mixed": lambda: _bench_block_commit(invalid_frac=0.1),
    # ISSUE 6 chaos soak: seeded FaultPlan (device faults + one
    # mid-stream prefetch-stage disconnect) through
    # retry/fallback/containment — degraded seconds, retries,
    # fallback blocks, p99 under chaos
    "block_commit_chaos": _bench_block_commit_chaos,
    # ISSUE 8 multi-tenant story: 2 tenant peers through one loopback
    # validation sidecar — aggregate tx/s, per-tenant p50/p99, and a
    # weighted fairness index
    "block_commit_sidecar": _bench_block_commit_sidecar,
    # ISSUE 11 overload story: OPEN-LOOP bursty arrivals + seeded
    # invalid-sig storms + config churn through the sidecar, with
    # FABTPU_BENCH_AUTOPILOT=0/1 flipping the traffic autopilot —
    # p99-under-overload, shed/BUSY counts, and the actuation log
    "block_commit_bursty": _bench_block_commit_bursty,
    # crypto-free standalone stage micro-bench: the host-cycle
    # elimination acceptance numbers (sig_prepare packed single-pass
    # vs two-phase; state_fill fused column gather vs dict path)
    "host_stage_micro": _bench_host_stage_micro,
    # ISSUE 13 endorsement story: device-batched ECDSA SIGNING
    # (fixed-base comb + RFC 6979) vs the serial OpenSSL signer, raw
    # batch AND through the SignBatcher ingest path with concurrent
    # feeders — FABTPU_BENCH_SIGN=0/1, occupancy in extras
    "endorse_sign": _bench_endorse_sign,
    "p256_verify": _bench_p256_verify,
    # ISSUE 18 catch-up path: closed-loop chain replay through
    # peer/replay.py at full depth vs the open-loop feed (ceiling
    # tx/s + pipeline_overlap_coverage in extras), and the
    # snapshot-then-replay join vs full replay-from-genesis with the
    # byte-identity differential asserted inline
    "chain_replay": _bench_chain_replay,
    "snapshot_join": _bench_snapshot_join,
    "sha256": _bench_sha256,
}


def main():
    import os
    import sys

    # persistent XLA compile cache: the driver launches this script
    # fresh every round — the verify/MVCC graphs must not recompile
    # (shared with the sidecar server/CLI via utils.xla_env)
    from fabric_tpu.utils.xla_env import enable_compile_cache

    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))

    name = sys.argv[1] if len(sys.argv) > 1 else "block_commit"
    if name in ("block_commit", "block_commit_mixed",
                "block_commit_sustained", "block_commit_chaos",
                "block_commit_sidecar", "block_commit_bursty",
                "chain_replay", "snapshot_join",
                "p256_verify", "endorse_sign"):
        # these benches need the `cryptography` package for the
        # OpenSSL CPU baseline and the cert-based test network — on
        # containers without it, report a skip instead of crashing at
        # import so the bench driver sees a well-formed JSON line
        try:
            import cryptography  # noqa: F401
        except ImportError as e:
            print(json.dumps({
                "skipped": True,
                "reason": f"cryptography unavailable: {e}",
                "metric": name,
            }))
            return
    # FABTPU_BENCH_VITALS=1: arm a run-local flight-data sampler
    # (observe/timeseries.py) over the process registry for the whole
    # scenario — every bench then ships its full metric trails into
    # BENCH_*.json extras, turning end-number snapshots into
    # attributed per-stage trajectories (the BENCH_r06 runbook knob)
    vitals = _vitals_capture()
    # the device-time launch ledger is ON for every scenario (default;
    # FABTPU_BENCH_LEDGER=0 disarms): extras.device_ledger decomposes
    # the run's device_wait into compile/queue/execute/transfer
    led = _ledger_capture()
    # the per-tx flow journal is ON for every scenario (default;
    # FABTPU_BENCH_TXFLOW=0 disarms — the armed-overhead A/B):
    # extras.tx_flow carries stage/e2e percentiles + visibility lag
    txj = _txflow_capture()
    result = _BENCHES[name]()
    if name == "block_commit":
        # self-contained round artifact: the headline clean number
        # carries the per-phase breakdown AND the adversarial-traffic
        # (10% invalid) variant in the same JSON line
        breakdown = result.pop("per_block_ms", None)
        extras = {"per_block_ms": breakdown, "knobs": _bench_knobs()}
        host_stage = result.pop("host_stage", None)
        if host_stage is not None:
            extras["host_stage"] = host_stage
        trace = result.pop("trace", None)
        if trace is not None:
            extras["trace_overhead_pct"] = trace.pop("trace_overhead_pct")
            extras["trace"] = trace
        cov = result.pop("pipeline_overlap_coverage", None)
        if cov is not None:
            extras["pipeline_overlap_coverage"] = cov
        try:
            mixed = _bench_block_commit(invalid_frac=0.1)
            extras["mixed_10pct_invalid"] = {
                "value": mixed["value"],
                "vs_baseline": mixed["vs_baseline"],
            }
        except Exception as e:  # the headline number must still print
            extras["mixed_10pct_invalid"] = {"error": str(e)[:200]}
        result["extras"] = extras
    else:
        result.pop("per_block_ms", None)
        result.pop("host_stage", None)
        result.pop("trace", None)
        result.pop("pipeline_overlap_coverage", None)
    trails = _vitals_extras(vitals)
    if trails is not None:
        result.setdefault("extras", {})["vitals"] = trails
    ledger_rep = _ledger_extras(led)
    if ledger_rep is not None:
        result.setdefault("extras", {})["device_ledger"] = ledger_rep
    txflow_rep = _txflow_extras(txj)
    if txflow_rep is not None:
        result.setdefault("extras", {})["tx_flow"] = txflow_rep
    print(json.dumps(result))


if __name__ == "__main__":
    main()

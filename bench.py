"""fabric_tpu benchmark driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline metric (per BASELINE.json): validated tx/s on the peer commit
path — endorsement-signature verification plus MVCC read-set checks for
1000-tx blocks.  Until the full pipeline lands this measures the widest
slice currently built, against a single-thread CPU baseline measured
in-process (the reference publishes no absolute numbers; see
BASELINE.md — baseline = the same work done serially on host CPU).
"""

from __future__ import annotations

import json
import time


def _bench_p256_verify():
    """Batched ECDSA-P256 endorsement-signature verification vs host CPU.

    The unit of work of the reference's block-commit hot loop: ~2-3
    endorsement verifies per tx at a 2-of-3 policy on 1000-tx blocks
    (statebased/validator_keylevel.go:244-260) → a 2048-signature batch.
    CPU baseline: single-thread OpenSSL via `cryptography` (the
    reference's SW BCCSP equivalent).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature, encode_dss_signature,
    )

    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256

    B = 2048
    rng = np.random.default_rng(11)
    keys = [cec.generate_private_key(cec.SECP256R1()) for _ in range(8)]
    items, der_sigs = [], []
    for i in range(B):
        key = keys[i % len(keys)]
        msg = b"proposal-response-%d-" % i + rng.bytes(64)
        sig = key.sign(msg, cec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(sig)
        if s > p256.HALF_N:
            s = p256.N - s
        pub = key.public_key().public_numbers()
        items.append((ec_ref.digest_int(msg), r, s, pub.x, pub.y))
        der_sigs.append((key.public_key(), msg, encode_dss_signature(r, s)))

    # CPU baseline: serial verify via OpenSSL.
    t0 = time.perf_counter()
    for pub, msg, sig in der_sigs:
        pub.verify(sig, msg, cec.ECDSA(hashes.SHA256()))
    cpu_s = time.perf_counter() - t0

    cols = list(zip(*items))
    e, r, s, qx, qy = (jnp.asarray(p256.ints_to_limbs(c)) for c in cols)
    out = p256.verify_batch_jit(e, r, s, qx, qy)  # compile
    jax.block_until_ready(out)
    assert bool(np.asarray(out).all()), "TPU verify rejected valid signatures"
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = p256.verify_batch_jit(e, r, s, qx, qy)
    jax.block_until_ready(out)
    tpu_s = (time.perf_counter() - t0) / reps

    tpu_rate = B / tpu_s
    cpu_rate = B / cpu_s
    return {
        "metric": "ecdsa_p256_verifies_per_sec_batch2048",
        "value": round(tpu_rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }


def _bench_sha256():
    """Batched block-payload hashing vs hashlib single-thread."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fabric_tpu.ops import sha256

    rng = np.random.default_rng(7)
    n = 4096
    msgs = [rng.bytes(200) for _ in range(n)]  # ~proposal-response size

    # CPU baseline: serial hashlib (C implementation).
    t0 = time.perf_counter()
    for m in msgs:
        hashlib.sha256(m).digest()
    cpu_s = time.perf_counter() - t0

    blocks, nb = sha256.pad_messages(msgs)
    db, dn = jnp.asarray(blocks), jnp.asarray(nb)
    out = sha256.sha256_blocks_jit(db, dn)  # compile
    jax.block_until_ready(out)
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        out = sha256.sha256_blocks_jit(db, dn)
    jax.block_until_ready(out)
    tpu_s = (time.perf_counter() - t0) / reps

    tpu_rate = n / tpu_s
    cpu_rate = n / cpu_s
    return {
        "metric": "sha256_hashes_per_sec_batch4096",
        "value": round(tpu_rate, 1),
        "unit": "hashes/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }


_BENCHES = {
    "p256_verify": _bench_p256_verify,
    "sha256": _bench_sha256,
}


def main():
    import sys

    name = sys.argv[1] if len(sys.argv) > 1 else "p256_verify"
    result = _BENCHES[name]()
    print(json.dumps(result))


if __name__ == "__main__":
    main()

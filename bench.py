"""fabric_tpu benchmark driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline metric (per BASELINE.json): validated tx/s on the peer commit
path — endorsement-signature verification plus MVCC read-set checks for
1000-tx blocks.  Until the full pipeline lands this measures the widest
slice currently built, against a single-thread CPU baseline measured
in-process (the reference publishes no absolute numbers; see
BASELINE.md — baseline = the same work done serially on host CPU).
"""

from __future__ import annotations

import json
import time


def _bench_sha256():
    """Batched block-payload hashing vs hashlib single-thread."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fabric_tpu.ops import sha256

    rng = np.random.default_rng(7)
    n = 4096
    msgs = [rng.bytes(200) for _ in range(n)]  # ~proposal-response size

    # CPU baseline: serial hashlib (C implementation).
    t0 = time.perf_counter()
    for m in msgs:
        hashlib.sha256(m).digest()
    cpu_s = time.perf_counter() - t0

    blocks, nb = sha256.pad_messages(msgs)
    db, dn = jnp.asarray(blocks), jnp.asarray(nb)
    out = sha256.sha256_blocks_jit(db, dn)  # compile
    jax.block_until_ready(out)
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        out = sha256.sha256_blocks_jit(db, dn)
    jax.block_until_ready(out)
    tpu_s = (time.perf_counter() - t0) / reps

    tpu_rate = n / tpu_s
    cpu_rate = n / cpu_s
    return {
        "metric": "sha256_hashes_per_sec_batch4096",
        "value": round(tpu_rate, 1),
        "unit": "hashes/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }


def main():
    result = _bench_sha256()
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Traffic autopilot: closed-loop overload control over the SLO engine.

The reference peer survives overload by queueing and stalling; a
device fabric serving many tenants must instead *adapt*.  Every error
signal the loop needs already exists — ``observe/slo.py`` turns the
tracer's finished-block stream into rolling burn rates, the sidecar
scheduler exports per-tenant queue-age/deficit/BUSY telemetry, and
``observe/overlap.py`` scores how much of the pipeline window is
actually hidden — but until this module nothing *acted* on any of it.

:class:`Autopilot` is a periodic controller (injectable clock, like
the SLO engine) that reads those trailing signals each tick and
actuates the existing commit-path knobs through their new runtime
setters:

* **raise** ``coalesce_blocks`` when tenant queues back up (trailing
  queue-age p99 above the high band) — more blocks per device
  dispatch amortizes launch overhead exactly when there is backlog to
  amortize over;
* **shrink** ``verify_chunk`` when the p99 launch latency grows
  (smaller chunks start the device sooner and bound per-dispatch
  stall), and grow it back toward monolithic when launches are fast;
* **step** ``pipeline_depth`` down when overlap coverage says the
  deep window is wasted (host stages are not hiding device_wait, so
  the extra in-flight state buys nothing but durability lag), back up
  when coverage is high;
* **resize** ``host_stage_workers`` up when the trailing prefetch
  (host parse + staging) p99 grows — the feeder is slower than its
  device, exactly the case the staging pool exists for — and back
  toward serial staging when the feeder runs comfortably ahead
  (HostStagePool.set_workers: drain-and-rebuild at a task boundary);
* **grow** ``sign_batch_max`` when the endorsement sign lane bounces
  requests with BUSY (trailing busy rate above its band) — bigger
  batches per device flush absorb the arrival rate — and back down
  when the lane is quiet and draining fast (small batches keep the
  first-proposal latency tight);
* **tune** ``sign_batch_wait_ms`` alongside it: shrink the lane's
  coalescing window when the wait p99 says the linger has become the
  endorsement latency, and stretch it when a flowing lane keeps
  flushing nearly-empty batches (occupancy fill under its band) —
  the max-wait half of the max-batch/max-wait contract, closed-loop;
* **re-weight or BUSY-shed** tenants on fast burn: a tenant whose
  latency budget burns past the shed band is put in *shed mode* —
  the scheduler answers its arrivals with typed BUSY + retry-after
  (bounded, exactly accounted) until the backlog drains and its burn
  recovers; moderate burn halves the tenant's scheduler weight
  instead, restored once the burn clears.

Every decision is governed so the controller can never flap or drive
a knob out of its validated range:

* **hysteresis bands** — each rule actuates only above its high or
  below its low threshold; the dead band between them holds, so a
  steady signal converges to ZERO actuations;
* **per-knob cooldowns** — a knob that just moved cannot move again
  for ``cool`` seconds (spec key; default
  :data:`DEFAULT_COOLDOWN_S`), so one slow signal cannot ratchet a
  knob across its whole range inside one incident;
* **max one step per tick** — rules are evaluated in priority order
  (shed > re-weight > coalesce > chunk > depth > host workers >
  restore) and the first eligible actuation wins the tick;
* **hard clamps** — knob values move along a per-knob ladder derived
  from the operator's min/max spec; the ladder ends ARE the clamp,
  there is no code path that steps past them.

Knob bounds ride a faults-style spec string (the nodeconfig
``autopilot_knobs`` knob)::

    name[:min=..][:max=..][:cool=..] [; more knobs]

known names: ``coalesce_blocks``, ``verify_chunk``,
``pipeline_depth``, ``host_stage_workers``, ``sign_batch_max``,
``sign_batch_wait_ms``, ``weight``, ``shed`` (shed takes only
``cool=``).
Omitting a knob from the spec keeps its default bounds
(:data:`DEFAULT_KNOB_SPECS`); an empty spec means all defaults.

Observability: every actuation bumps
``autopilot_actuations_total{knob,direction}``, lands as a finished
root in the tracer's ``autopilot`` flight-recorder namespace
(``/trace?ns=autopilot``), and appends to the bounded decision log
the ``/autopilot`` operations endpoint serves next to the current
knob vector and the ``autopilot_enabled`` gauge.

Default OFF (nodeconfig ``autopilot=false``): tier-1 and CPU hosts
keep the exact static path — the controller object is never built.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_log = logging.getLogger("fabric_tpu.control.autopilot")

#: knob names the spec parser accepts — an operator typo must be a
#: config error, not a silently-ignored bound
KNOWN_KNOBS = ("coalesce_blocks", "verify_chunk", "pipeline_depth",
               "host_stage_workers", "sign_batch_max",
               "sign_batch_wait_ms", "weight", "shed")

#: default per-knob bounds (overridable per knob via the spec string)
DEFAULT_KNOB_SPECS = (
    "coalesce_blocks:min=0:max=8;"
    "verify_chunk:min=512:max=4096;"
    "pipeline_depth:min=2:max=4;"
    "host_stage_workers:min=0:max=4;"
    "sign_batch_max:min=64:max=4096;"
    "sign_batch_wait_ms:min=0.5:max=16;"
    "weight:min=0.125:max=8;"
    "shed"
)

#: seconds a knob rests after an actuation (spec key ``cool=``)
DEFAULT_COOLDOWN_S = 10.0

#: decisions retained for /autopilot
DECISION_LOG = 64

#: default hysteresis bands — the dead band between each (lo, hi)
#: pair is the no-flap guarantee
DEFAULT_BANDS = {
    "queue_hi_ms": 50.0,   # queue-age p99 above → coalesce up
    "queue_lo_ms": 5.0,    # below → coalesce down
    "launch_hi_ms": 250.0,  # launch p99 above → shrink verify_chunk
    "launch_lo_ms": 50.0,   # below → grow it back
    "devq_hi_ms": 25.0,    # ledger device-queue p99 above → shrink
                           # verify_chunk (launches queue behind each
                           # other on the device lane)
    "devq_lo_ms": 2.0,     # below → grow it back toward monolithic
    "coverage_lo": 0.25,   # overlap coverage below → depth down
    "coverage_hi": 0.85,   # above → depth up
    "prefetch_hi_ms": 150.0,  # prefetch (host parse) p99 above →
                              # host_stage_workers up
    "prefetch_lo_ms": 20.0,   # below → back toward serial staging
    "sign_busy_hi": 0.05,   # sign-lane BUSY rate above → batch up
    "sign_busy_lo": 0.005,  # below (and waits short) → batch down
    "sign_wait_lo_ms": 5.0,  # waits must also sit below this for a
                             # step down (a draining lane, not a
                             # momentarily idle one)
    "sign_wait_hi_ms": 10.0,  # wait p99 above → shrink the
                              # coalescing window (wait_ms down)
    "sign_fill_lo": 0.25,   # occupancy p50 / batch_max below (lane
                            # flowing) → linger longer (wait_ms up)
    "apply_hi_ms": 100.0,  # state-apply queue age above → the
                           # applier is the bottleneck: coalesce
                           # DOWN (bigger groups only grow the lag)
    "apply_lo_ms": 10.0,   # below → the apply lane is keeping up;
                           # the coalesce rule is free to act on
                           # admission-queue age again
    "burn_hi": 1.5,        # tenant burn above → halve its weight
    "burn_lo": 0.5,        # below → restore toward its hello weight
    "shed_hi": 4.0,        # tenant fast burn above → shed mode ON
    "shed_lo": 1.0,        # burn below (or aged out) → shed mode OFF
}


class KnobSpecError(ValueError):
    """A malformed autopilot knob spec, phrased for the operator."""


@dataclass(frozen=True)
class KnobSpec:
    """One knob's validated actuation range (see module docstring)."""

    name: str
    lo: float = 0.0
    hi: float = 0.0
    cooldown_s: float = DEFAULT_COOLDOWN_S

    def ladder(self) -> tuple:
        """The ordered value ladder a step moves ±1 along — index 0 is
        the least-adapted end, the last index the most.  The ladder
        ends ARE the hard clamps."""
        if self.name == "coalesce_blocks":
            # 1 is meaningless (a group of one never coalesces)
            return (int(self.lo),) + tuple(
                n for n in range(max(2, int(self.lo) + 1), int(self.hi) + 1)
            )
        if self.name == "verify_chunk":
            # 0 = monolithic; "up" (adapt) moves to ever-smaller
            # chunks: 0 → hi → hi/2 → ... → lo
            out = [0]
            c = int(self.hi)
            while c >= max(1, int(self.lo)):
                out.append(c)
                c //= 2
            return tuple(out)
        if self.name == "pipeline_depth":
            return tuple(range(int(self.lo), int(self.hi) + 1))
        if self.name == "host_stage_workers":
            # 0 = serial staging (pool off); 1 is meaningless (a
            # 1-worker pool is queue overhead — resolve_host_pool
            # returns None below 2), so the ladder jumps 0 → 2
            return (int(self.lo),) + tuple(
                n for n in range(max(2, int(self.lo) + 1),
                                 int(self.hi) + 1)
            )
        if self.name == "sign_batch_max":
            # doubling rungs min → max ("up" = bigger sign batches per
            # device flush); the max is always a rung so the operator
            # cap is reachable exactly
            out = []
            c = int(self.lo)
            while c < int(self.hi):
                out.append(c)
                c *= 2
            out.append(int(self.hi))
            return tuple(out)
        if self.name == "sign_batch_wait_ms":
            # doubling float rungs min → max ("up" = linger longer in
            # the coalescing window so batches actually fill); the
            # operator's max is always a rung
            out = []
            c = float(self.lo)
            while c < float(self.hi):
                out.append(c)
                c *= 2
            out.append(float(self.hi))
            return tuple(out)
        return ()  # weight/shed are not ladder knobs


def parse_knob_specs(spec: str | None) -> dict[str, KnobSpec]:
    """``'coalesce_blocks:min=0:max=8;weight:min=0.5:max=4'`` →
    {name: KnobSpec}, defaults filled for every unnamed knob."""
    out: dict[str, KnobSpec] = {}
    for source in (DEFAULT_KNOB_SPECS, spec or ""):
        for part in str(source).split(";"):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            name = fields[0].strip()
            if name not in KNOWN_KNOBS:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: unknown knob "
                    f"{name!r} (expected one of {', '.join(KNOWN_KNOBS)})"
                )
            kw: dict = {}
            for f in fields[1:]:
                k, sep, v = f.partition("=")
                k = k.strip()
                if not sep:
                    raise KnobSpecError(
                        f"autopilot knob spec {part!r}: expected k=v, "
                        f"got {f!r}"
                    )
                try:
                    if k == "min":
                        kw["lo"] = float(v)
                    elif k == "max":
                        kw["hi"] = float(v)
                    elif k == "cool":
                        kw["cooldown_s"] = float(v)
                    else:
                        raise KnobSpecError(
                            f"autopilot knob spec {part!r}: unknown key "
                            f"{k!r} (expected min/max/cool)"
                        )
                except ValueError as e:
                    if isinstance(e, KnobSpecError):
                        raise
                    raise KnobSpecError(
                        f"autopilot knob spec {part!r}: cannot parse "
                        f"{f!r}"
                    ) from None
            base = out.get(name)
            if base is not None:  # operator spec overrides defaults
                kw.setdefault("lo", base.lo)
                kw.setdefault("hi", base.hi)
                kw.setdefault("cooldown_s", base.cooldown_s)
            ks = KnobSpec(name=name, **kw)
            if name == "shed":
                ks = KnobSpec(name=name, cooldown_s=ks.cooldown_s)
            elif ks.hi < ks.lo:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: max < min"
                )
            elif name == "pipeline_depth" and ks.lo < 2:
                # depth 1 is the serial oracle; the controller must
                # never cross the pipelined/serial boundary at runtime
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: pipeline_depth "
                    "min must be >= 2 (depth 1 is the serial oracle, "
                    "not a runtime target)"
                )
            elif name == "host_stage_workers" and ks.lo == 1:
                # a 1-worker pool is queue overhead with no
                # parallelism (resolve_host_pool returns None below
                # 2), and a ladder rung at 1 would actuate the
                # serial-close path while reporting a pool of one
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: "
                    "host_stage_workers min must be 0 (serial "
                    "staging) or >= 2 — a 1-worker pool does not "
                    "exist"
                )
            elif name == "sign_batch_max" and ks.lo < 1:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: sign_batch_max "
                    "min must be >= 1 (a 0-lane sign batch does not "
                    "exist)"
                )
            elif name == "sign_batch_wait_ms" and ks.lo <= 0:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: sign_batch_wait_ms "
                    "min must be > 0 ms (a doubling ladder cannot "
                    "leave a 0 floor; wait_ms=0 is the static "
                    "flush-immediately config, not a runtime rung)"
                )
            elif name == "weight" and ks.lo <= 0:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: weight min must "
                    "be > 0 (the scheduler rejects non-positive "
                    "weights)"
                )
            if ks.cooldown_s < 0:
                raise KnobSpecError(
                    f"autopilot knob spec {part!r}: cool must be >= 0"
                )
            out[name] = ks
    return out


def host_clamped_specs(knob_specs: dict, cores: int | None = None,
                       ) -> dict:
    """Clamp the ``host_stage_workers`` ladder to the machine: the
    pool itself clamps resizes to the core count, so ladder rungs
    above it would charge cooldowns and log decisions for actuations
    that can never change anything.  Returns the dict with that one
    spec replaced (a ≤1-rung result leaves the knob structurally
    inert — correct on a 1-core host)."""
    if cores is None:
        import os

        cores = os.cpu_count() or 1
    spec = knob_specs.get("host_stage_workers")
    if spec is None or spec.hi <= cores:
        return knob_specs
    out = dict(knob_specs)
    out["host_stage_workers"] = KnobSpec(
        name="host_stage_workers", lo=min(spec.lo, float(cores)),
        hi=float(cores), cooldown_s=spec.cooldown_s,
    )
    return out


def resolve_host_workers_initial(configured: int,
                                 cores: int | None = None) -> int:
    """The configured ``host_stage_workers`` knob → the worker count
    the validator actually resolved (mirrors ``resolve_host_pool``:
    −1 = one per core, clamped to cores, < 2 = serial/0) — the value
    the controller's ladder snap must start from.  Passing the raw
    −1 would snap to 0 and INVERT the knob: the first slow-feeder
    'up' step would shrink a per-core pool to 2 workers."""
    if cores is None:
        import os

        cores = os.cpu_count() or 1
    n = cores if configured < 0 else min(int(configured), cores)
    return n if n >= 2 else 0


@dataclass
class Signals:
    """One tick's trailing-signal snapshot (every field optional: an
    absent source — no scheduler attached, empty flight recorder —
    reads as None/{} and its rules simply skip)."""

    #: {(objective_name, channel): fast-window burn | None}
    burn: dict = field(default_factory=dict)
    #: {tenant: trailing queue-age p99 ms} (scheduler stats)
    queue_age_p99_ms: dict = field(default_factory=dict)
    #: {tenant: CURRENT admission-queue depth} (scheduler stats) —
    #: the live-pressure signal: trailing ages say how bad it WAS,
    #: depth says whether it still is
    queue_depth: dict = field(default_factory=dict)
    #: {tenant: served-signature share} (scheduler stats) — the
    #: consumption signal: a serial-submitting offender never builds
    #: queue depth (it waits on each verdict), but it does dominate
    #: the served share
    share: dict = field(default_factory=dict)
    #: {tenant: BUSY pushback fraction} (scheduler stats)
    busy_rate: dict = field(default_factory=dict)
    launch_p99_ms: float | None = None
    #: trailing device-lane queue-wait p99 ms off the launch ledger
    #: (observe/ledger.py) — the honest device-pressure signal: a
    #: launch that waited behind its predecessor on the device lane,
    #: measured, not inferred from launch-span p99.  None = no ledger
    #: armed (or no synced rows in the window): the chunk rule falls
    #: back to launch_p99_ms.
    device_queue_p99_ms: float | None = None
    overlap_coverage: float | None = None
    #: trailing prefetch-span (host parse + staging) p99 ms — the
    #: host_stage_workers signal: a feeder slower than its device
    #: shows up here, not in launch_p99
    prefetch_p99_ms: float | None = None
    #: sign-lane signals (SignBatcher.stats()): trailing BUSY bounce
    #: rate and submit→flush wait p99 — the sign_batch_max knob's
    #: pressure/drain pair.  None = no sign lane armed: the rule
    #: skips, so a sign-less peer charges no cooldowns and logs no
    #: phantom decisions.
    sign_busy_rate: float | None = None
    sign_wait_p99_ms: float | None = None
    #: age of the OLDEST batch waiting in the async commit engine's
    #: state-apply queue (AsyncApplyEngine.stats()) — the trailing-
    #: apply pressure signal: blocks are durable and acked, but the
    #: state DB lags by this much.  None = serial commit engine (or
    #: no channel yet): the apply rule skips entirely.
    apply_queue_age_ms: float | None = None
    #: trailing batch-occupancy p50 as a fraction of batch_max — the
    #: sign_batch_wait_ms knob's efficiency signal: a flowing lane
    #: flushing nearly-empty batches wastes device dispatches
    sign_fill: float | None = None
    clock_s: float = 0.0

    def tenant_burn(self, tenant: str) -> float | None:
        """Worst fast-window burn across objectives for one tenant's
        sidecar channel — the shed/re-weight signal."""
        chan = f"sidecar:{tenant}"
        vals = [b for (_n, c), b in self.burn.items()
                if c == chan and b is not None]
        return max(vals) if vals else None

    def worst_burn(self) -> float | None:
        vals = [b for b in self.burn.values() if b is not None]
        return max(vals) if vals else None


@dataclass
class Decision:
    """One actuation, with the signal that triggered it — the
    /autopilot decision log entry and the tracer event payload."""

    t: float
    knob: str
    direction: str        # "up" | "down" | "on" | "off"
    old: object
    new: object
    signal: str           # which trailing signal triggered it
    value: float | None   # the signal's reading
    threshold: float      # the band edge it crossed
    tenant: str = ""

    def to_dict(self) -> dict:
        d = {
            "t_s": round(self.t, 3), "knob": self.knob,
            "direction": self.direction, "from": self.old,
            "to": self.new, "signal": self.signal,
            "value": (round(self.value, 4)
                      if isinstance(self.value, float) else self.value),
            "threshold": self.threshold,
        }
        if self.tenant:
            d["tenant"] = self.tenant
        return d


def _p99(sorted_vals: list) -> float | None:
    if not sorted_vals:
        return None
    rank = math.ceil(0.99 * len(sorted_vals))
    return sorted_vals[max(0, min(len(sorted_vals) - 1, rank - 1))]


class Autopilot:
    """See module docstring.

    ``apply_knob(name, value)`` actuates the ladder knobs
    (coalesce_blocks / verify_chunk / pipeline_depth) on the live
    commit path; ``set_weight(tenant, w)`` / ``set_shed(tenant, on)``
    actuate the scheduler (None = that rule is disabled).  ``slo`` is
    the burn-rate engine, ``scheduler`` anything with the
    WeightedScheduler ``stats()`` shape, ``tracer`` the span tracer
    whose flight recorder supplies launch-latency and
    overlap-coverage trails.  Tests drive :meth:`tick` directly with
    a prebuilt :class:`Signals`; production calls :meth:`start` for
    the background thread."""

    def __init__(self, knob_specs=None, apply_knob=None, *,
                 set_weight=None, set_shed=None, slo=None,
                 scheduler=None, tracer=None, sign_source=None,
                 commit_source=None, initial=None,
                 tick_s: float = 1.0, clock=time.monotonic,
                 registry=None, enabled: bool = True, bands=None):
        if knob_specs is None or isinstance(knob_specs, str):
            knob_specs = parse_knob_specs(knob_specs)
        self.specs: dict[str, KnobSpec] = dict(knob_specs)
        self.apply_knob = apply_knob or (lambda name, value: None)
        self.set_weight = set_weight
        self.set_shed = set_shed
        self.slo = slo
        self.scheduler = scheduler
        # anything with the SignBatcher stats() shape (busy_rate +
        # wait_ms percentiles) — None on peers without a sign lane
        self.sign_source = sign_source
        # anything with the AsyncApplyEngine stats() shape
        # (oldest_age_ms) — None on serial-commit peers
        self.commit_source = commit_source
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        self.tracer = tracer
        self.tick_s = float(tick_s)
        self.clock = clock
        self.bands = {**DEFAULT_BANDS, **(bands or {})}
        self._lock = threading.Lock()
        # current ladder-knob values, snapped onto each ladder (the
        # configured starting point may sit between rungs)
        self.values: dict[str, object] = {}
        initial = dict(initial or {})
        for name, spec in self.specs.items():
            ladder = spec.ladder()
            if not ladder:
                continue
            want = initial.get(name, ladder[0])
            self.values[name] = min(
                ladder, key=lambda v: (abs(v - want), v)
            )
        # tenant state: live weights (first sight records the hello
        # weight as the restore target) and the shed set
        self._hello_weight: dict[str, float] = {}
        self._weights: dict[str, float] = {}
        self._shed: set[str] = set()
        self._last_act: dict[tuple, float] = {}
        # throughput-mode holds (peer/replay.py): while any are live
        # the overload knives (shed/BUSY, weight halving) stay
        # sheathed — a closed-loop replay feed keeps every queue full
        # by DESIGN, and those rules would misread full occupancy as
        # an open-loop overload incident.  Refcounted: concurrent
        # replays on different channels each take one hold.
        self._throughput_hold = 0
        self.decisions: deque = deque(maxlen=DECISION_LOG)
        self._last_signals: Signals | None = None
        self._seq = 0
        self._enabled = bool(enabled)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._act_ctr = registry.counter(
            "autopilot_actuations_total",
            "autopilot knob actuations by knob and direction",
        )
        self._enabled_gauge = registry.gauge(
            "autopilot_enabled",
            "1 while the traffic autopilot is actuating, 0 otherwise",
        )
        self._enabled_gauge.set(1 if self._enabled else 0)

    # -- enable/disable ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)
        self._enabled_gauge.set(1 if self._enabled else 0)

    # -- throughput mode (closed-loop replay) ------------------------------

    def hold_throughput(self) -> None:
        """Enter throughput mode: suppress the shed/BUSY and
        weight-halving overload rules while a closed-loop feed
        (chain replay) intentionally saturates the commit path.  The
        efficiency ladder rules (coalesce, verify_chunk, depth, host
        workers) keep actuating — they are exactly what tunes the
        replay toward the ceiling."""
        with self._lock:
            self._throughput_hold += 1

    def release_throughput(self) -> None:
        with self._lock:
            if self._throughput_hold > 0:
                self._throughput_hold -= 1

    @property
    def throughput_mode(self) -> bool:
        with self._lock:
            return self._throughput_hold > 0

    # -- signal acquisition ------------------------------------------------

    def read_signals(self) -> Signals:
        """Build one tick's snapshot from the live sources; each source
        is independently contained — a broken reader yields an absent
        signal, never a dead controller."""
        now = self.clock()
        s = Signals(clock_s=now)
        if self.slo is not None:
            try:
                s.burn = self.slo.burns()
            except Exception as e:
                _log.debug("autopilot: slo signal read failed: %s", e)
        if self.scheduler is not None:
            try:
                for tenant, row in self.scheduler.stats().items():
                    age = (row.get("queue_age_ms") or {})
                    if age.get("n"):
                        s.queue_age_p99_ms[tenant] = float(
                            age.get("p99", 0.0)
                        )
                    s.queue_depth[tenant] = int(row.get("depth", 0))
                    s.share[tenant] = float(row.get("share", 0.0))
                    s.busy_rate[tenant] = float(row.get("busy_rate", 0.0))
            except Exception as e:
                _log.debug("autopilot: scheduler signal read failed: %s",
                           e)
        if self.sign_source is not None:
            try:
                st = self.sign_source.stats()
                s.sign_busy_rate = float(st.get("busy_rate", 0.0))
                wait = st.get("wait_ms") or {}
                if wait.get("n"):
                    s.sign_wait_p99_ms = float(wait.get("p99") or 0.0)
                occ = st.get("occupancy") or {}
                bm = int(st.get("batch_max") or 0)
                if occ.get("n") and bm > 0:
                    s.sign_fill = float(occ.get("p50") or 0.0) / bm
            except Exception as e:
                _log.debug("autopilot: sign signal read failed: %s", e)
        if self.commit_source is not None:
            try:
                st = self.commit_source.stats()
                age = st.get("oldest_age_ms")
                if age is not None:
                    s.apply_queue_age_ms = float(age)
            except Exception as e:
                _log.debug("autopilot: commit signal read failed: %s", e)
        try:
            from fabric_tpu.observe import ledger as _ledger

            led = _ledger.global_ledger()
            if led is not None:
                s.device_queue_p99_ms = led.queue_p99_ms()
        except Exception as e:
            _log.debug("autopilot: ledger signal read failed: %s", e)
        try:
            roots = self.tracer.recent_roots()
        except Exception as e:
            _log.debug("autopilot: tracer signal read failed: %s", e)
            roots = []
        if roots:
            launches = sorted(
                c.dur * 1000.0 for r in roots for c in r.children
                if c.name == "launch" and c.t1 is not None
            )
            s.launch_p99_ms = _p99(launches)
            prefetches = sorted(
                c.dur * 1000.0 for r in roots for c in r.children
                if c.name == "prefetch" and c.t1 is not None
            )
            s.prefetch_p99_ms = _p99(prefetches)
            depth = int(self.values.get("pipeline_depth", 2) or 2)
            try:
                from fabric_tpu.observe import coverage_from_roots

                cov = coverage_from_roots(
                    roots, window=max(1, depth - 1)
                )
                s.overlap_coverage = cov.get("mean")
            except Exception as e:
                _log.debug("autopilot: coverage read failed: %s", e)
        return s

    # -- the control loop --------------------------------------------------

    def tick(self, signals: Signals | None = None) -> Decision | None:
        """One controller step: read (or accept) the trailing signals,
        pick at most ONE actuation per the rule priority order, apply
        it through the runtime setters.  Disabled ⇒ zero actuations,
        always."""
        if not self._enabled:
            return None
        s = signals if signals is not None else self.read_signals()
        now = s.clock_s if signals is not None else self.clock()
        with self._lock:
            self._last_signals = s
            d = self._decide(s, now)
            if d is not None:
                self._actuate(d, now)
        if d is not None and d.knob == "shed" and d.direction == "on":
            # incident edge: a shed decision IS an incident — freeze
            # the trailing series, decision log and scheduler stats so
            # the overload attributes itself.  OUTSIDE the controller
            # lock: the bundle reads this controller's own report()
            # (rare branch; the import costs nothing on ordinary
            # ticks).
            from fabric_tpu.observe import blackbox

            blackbox.notify(
                "autopilot_shed", tenant=d.tenant,
                burn=d.value, threshold=d.threshold,
            )
        return d

    def _cool(self, knob: str, tenant: str, now: float) -> bool:
        spec = self.specs.get(knob)
        cool = spec.cooldown_s if spec is not None else DEFAULT_COOLDOWN_S
        last = self._last_act.get((knob, tenant), float("-inf"))
        return now - last >= cool

    def _step(self, knob: str, direction: int):
        """(old, new) one ladder step in ``direction``; None at the
        clamp — the ladder ends are unsteppable by construction."""
        ladder = self.specs[knob].ladder()
        cur = self.values[knob]
        i = ladder.index(cur)
        j = i + direction
        if j < 0 or j >= len(ladder):
            return None
        return cur, ladder[j]

    def _decide(self, s: Signals, now: float) -> Decision | None:
        b = self.bands
        # throughput mode (replay hold; caller holds self._lock so
        # read the counter raw): the overload knives below (rules 1
        # and 2) are suppressed — a closed-loop replay keeps queues
        # full on purpose, and shedding/penalizing its tenant would
        # throttle exactly the catch-up it is trying to finish.  The
        # efficiency rules (3+) still run.
        tput = self._throughput_hold > 0
        # 1) emergency shed: a tenant burning past the shed band gets
        #    BUSY + retry-after instead of queue space — but ONLY the
        #    tenant actually applying the pressure.  Under one shared
        #    device lane an overload victim burns too (its requests
        #    wait behind the offender's), so the rule requires the
        #    candidate to hold the deepest admission queue: shedding
        #    the victim would bound nothing.
        if (not tput and self.set_shed is not None
                and "shed" in self.specs and not self._shed):
            # ONE knife at a time: while a shed is active the incident
            # is already being bounded, and every other tenant's burn
            # is contaminated by it (a victim's lingering bad window +
            # its rising share would make it shed-eligible exactly as
            # the offender's bound starts working).  A second offender
            # is re-evaluated the moment the current shed lifts.
            for tenant in sorted(set(s.queue_age_p99_ms)
                                 | set(s.busy_rate)
                                 | set(s.queue_depth)
                                 | {c.split(":", 1)[1]
                                    for (_n, c) in s.burn if
                                    c.startswith("sidecar:")}):
                burn = s.tenant_burn(tenant)
                my_depth = s.queue_depth.get(tenant, 0)
                deeper_elsewhere = any(
                    d > my_depth for t2, d in s.queue_depth.items()
                    if t2 != tenant
                )
                # depth 0 does not acquit: a SERIAL offender waits on
                # each verdict and never builds a queue, yet it still
                # dominates the served share.  A burning tenant with
                # an empty queue AND someone else out-consuming it is
                # a victim remembering an incident — shedding it
                # bounds nothing.  (Depth/share-less signals skip the
                # pressure test: no scheduler means burn is all we
                # have.)
                no_pressure = (
                    tenant in s.queue_depth and my_depth == 0
                    and tenant in s.share
                    and any(v > s.share[tenant] + 1e-9
                            for t2, v in s.share.items()
                            if t2 != tenant)
                )
                if (burn is not None and burn >= b["shed_hi"]
                        and not deeper_elsewhere and not no_pressure
                        and self._cool("shed", tenant, now)):
                    return Decision(
                        t=now, knob="shed", direction="on",
                        old=False, new=True, signal="burn",
                        value=burn, threshold=b["shed_hi"],
                        tenant=tenant,
                    )
        # 2) moderate burn: halve the tenant's scheduler weight
        if (not tput and self.set_weight is not None
                and "weight" in self.specs):
            spec = self.specs["weight"]
            for tenant in sorted(set(self._weights)
                                 | {c.split(":", 1)[1]
                                    for (_n, c) in s.burn
                                    if c.startswith("sidecar:")}):
                if tenant in self._shed:
                    continue
                burn = s.tenant_burn(tenant)
                cur = self._weights.get(
                    tenant, self._hello_weight.get(tenant, 1.0)
                )
                if (burn is not None and burn >= b["burn_hi"]
                        and cur / 2.0 >= spec.lo
                        and self._cool("weight", tenant, now)):
                    return Decision(
                        t=now, knob="weight", direction="down",
                        old=cur, new=cur / 2.0, signal="burn",
                        value=burn, threshold=b["burn_hi"],
                        tenant=tenant,
                    )
        # 3) queue backlog: coalesce more blocks per dispatch — UNLESS
        #    the async commit engine's state-apply queue is itself
        #    aging past its band: then the applier (not dispatch
        #    overhead) is the bottleneck, and bigger groups only grow
        #    the trailing lag.  High apply age instead steps coalesce
        #    DOWN, shrinking the batches the applier must absorb.
        ages = [v for v in s.queue_age_p99_ms.values()]
        age_p99 = max(ages) if ages else None
        apply_age = s.apply_queue_age_ms
        apply_hot = (apply_age is not None
                     and apply_age > b["apply_hi_ms"])
        if "coalesce_blocks" in self.values and apply_hot:
            if self._cool("coalesce_blocks", "", now):
                step = self._step("coalesce_blocks", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="coalesce_blocks", direction="down",
                        old=step[0], new=step[1],
                        signal="apply_queue_age_ms", value=apply_age,
                        threshold=b["apply_hi_ms"],
                    )
        elif "coalesce_blocks" in self.values and age_p99 is not None:
            if (age_p99 > b["queue_hi_ms"]
                    and self._cool("coalesce_blocks", "", now)):
                step = self._step("coalesce_blocks", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="coalesce_blocks", direction="up",
                        old=step[0], new=step[1],
                        signal="queue_age_p99_ms", value=age_p99,
                        threshold=b["queue_hi_ms"],
                    )
            elif (age_p99 < b["queue_lo_ms"]
                    and self._cool("coalesce_blocks", "", now)):
                step = self._step("coalesce_blocks", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="coalesce_blocks", direction="down",
                        old=step[0], new=step[1],
                        signal="queue_age_p99_ms", value=age_p99,
                        threshold=b["queue_lo_ms"],
                    )
        # 4) device pressure: smaller verify chunks.  The launch
        #    ledger's trailing queue-wait p99 is the HONEST signal
        #    (launch-span p99 mixes host staging and compile time into
        #    what it calls device pressure) — when the ledger is armed
        #    its reading drives this rule; the launch-span p99 stays
        #    as the ledger-less fallback.
        if "verify_chunk" in self.values:
            if s.device_queue_p99_ms is not None:
                sig, val = "device_queue_p99_ms", s.device_queue_p99_ms
                hi, lo = b["devq_hi_ms"], b["devq_lo_ms"]
            else:
                sig, val = "launch_p99_ms", s.launch_p99_ms
                hi, lo = b["launch_hi_ms"], b["launch_lo_ms"]
        else:
            val = None
        if "verify_chunk" in self.values and val is not None:
            if val > hi and self._cool("verify_chunk", "", now):
                step = self._step("verify_chunk", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="verify_chunk", direction="up",
                        old=step[0], new=step[1],
                        signal=sig, value=val, threshold=hi,
                    )
            elif val < lo and self._cool("verify_chunk", "", now):
                step = self._step("verify_chunk", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="verify_chunk", direction="down",
                        old=step[0], new=step[1],
                        signal=sig, value=val, threshold=lo,
                    )
        # 5) wasted window: step pipeline depth down (up on recovery)
        if ("pipeline_depth" in self.values
                and s.overlap_coverage is not None):
            if (s.overlap_coverage < b["coverage_lo"]
                    and self._cool("pipeline_depth", "", now)):
                step = self._step("pipeline_depth", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="pipeline_depth", direction="down",
                        old=step[0], new=step[1],
                        signal="overlap_coverage",
                        value=s.overlap_coverage,
                        threshold=b["coverage_lo"],
                    )
            elif (s.overlap_coverage > b["coverage_hi"]
                    and self._cool("pipeline_depth", "", now)):
                step = self._step("pipeline_depth", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="pipeline_depth", direction="up",
                        old=step[0], new=step[1],
                        signal="overlap_coverage",
                        value=s.overlap_coverage,
                        threshold=b["coverage_hi"],
                    )
        # 6) slow feeder: more host staging workers when the prefetch
        #    (host parse + staging) p99 grows — the ROADMAP-named PR-10
        #    follow-up, actuatable now that the pool can resize at a
        #    task boundary; back toward serial staging when the feeder
        #    is comfortably ahead of the device
        if ("host_stage_workers" in self.values
                and s.prefetch_p99_ms is not None):
            if (s.prefetch_p99_ms > b["prefetch_hi_ms"]
                    and self._cool("host_stage_workers", "", now)):
                step = self._step("host_stage_workers", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="host_stage_workers",
                        direction="up", old=step[0], new=step[1],
                        signal="prefetch_p99_ms",
                        value=s.prefetch_p99_ms,
                        threshold=b["prefetch_hi_ms"],
                    )
            elif (s.prefetch_p99_ms < b["prefetch_lo_ms"]
                    and self._cool("host_stage_workers", "", now)):
                step = self._step("host_stage_workers", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="host_stage_workers",
                        direction="down", old=step[0], new=step[1],
                        signal="prefetch_p99_ms",
                        value=s.prefetch_p99_ms,
                        threshold=b["prefetch_lo_ms"],
                    )
        # 6b) sign-lane pressure: BUSY bounces mean the admission
        #     window (2 × batch_max) is too small for the endorsement
        #     arrival rate — bigger batches per flush absorb it; step
        #     back down only when the lane is both quiet (busy ≈ 0)
        #     AND draining fast (wait p99 under its band), so a
        #     momentarily idle lane doesn't shrink into the next burst
        if ("sign_batch_max" in self.values
                and s.sign_busy_rate is not None):
            if (s.sign_busy_rate > b["sign_busy_hi"]
                    and self._cool("sign_batch_max", "", now)):
                step = self._step("sign_batch_max", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="sign_batch_max", direction="up",
                        old=step[0], new=step[1],
                        signal="sign_busy_rate",
                        value=s.sign_busy_rate,
                        threshold=b["sign_busy_hi"],
                    )
            elif (s.sign_busy_rate < b["sign_busy_lo"]
                    and s.sign_wait_p99_ms is not None
                    and s.sign_wait_p99_ms < b["sign_wait_lo_ms"]
                    and self._cool("sign_batch_max", "", now)):
                step = self._step("sign_batch_max", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="sign_batch_max",
                        direction="down", old=step[0], new=step[1],
                        signal="sign_busy_rate",
                        value=s.sign_busy_rate,
                        threshold=b["sign_busy_lo"],
                    )
        # 6c) sign-lane coalescing window (the wait_ms twin of 6b):
        #     waits stretching past their band mean the linger IS the
        #     endorsement latency — shrink the window; a flowing lane
        #     flushing nearly-empty batches (occupancy fill under its
        #     band) wastes device dispatches — linger longer so
        #     batches actually fill.  Wait p99 wins when both fire
        #     (latency rules efficiency), and both ride the usual
        #     cooldown / dead-band / clamp-ladder governance.
        if ("sign_batch_wait_ms" in self.values
                and s.sign_wait_p99_ms is not None):
            if (s.sign_wait_p99_ms > b["sign_wait_hi_ms"]
                    and self._cool("sign_batch_wait_ms", "", now)):
                step = self._step("sign_batch_wait_ms", -1)
                if step is not None:
                    return Decision(
                        t=now, knob="sign_batch_wait_ms",
                        direction="down", old=step[0], new=step[1],
                        signal="sign_wait_p99_ms",
                        value=s.sign_wait_p99_ms,
                        threshold=b["sign_wait_hi_ms"],
                    )
            elif (s.sign_fill is not None
                    and s.sign_fill < b["sign_fill_lo"]
                    and self._cool("sign_batch_wait_ms", "", now)):
                step = self._step("sign_batch_wait_ms", +1)
                if step is not None:
                    return Decision(
                        t=now, knob="sign_batch_wait_ms",
                        direction="up", old=step[0], new=step[1],
                        signal="sign_fill", value=s.sign_fill,
                        threshold=b["sign_fill_lo"],
                    )
        # 7) recovery: restore a halved weight toward its hello value
        if self.set_weight is not None and "weight" in self.specs:
            spec = self.specs["weight"]
            for tenant, cur in sorted(self._weights.items()):
                target = self._hello_weight.get(tenant, 1.0)
                if cur >= target or tenant in self._shed:
                    continue
                burn = s.tenant_burn(tenant)
                if ((burn is None or burn < b["burn_lo"])
                        and self._cool("weight", tenant, now)):
                    new = min(target, min(cur * 2.0, spec.hi))
                    return Decision(
                        t=now, knob="weight", direction="up",
                        old=cur, new=new, signal="burn",
                        value=burn, threshold=b["burn_lo"],
                        tenant=tenant,
                    )
        # 8) recovery: lift shed once the burn cleared and the queue
        #    drained (a shed tenant produces few latency samples, so
        #    an aged-out window — burn None — also counts as clear;
        #    CURRENT depth is the drain signal — trailing ages keep
        #    remembering the incident long after it ends)
        if self.set_shed is not None and "shed" in self.specs:
            for tenant in sorted(self._shed):
                burn = s.tenant_burn(tenant)
                depth = s.queue_depth.get(tenant, 0)
                if ((burn is None or burn < b["shed_lo"])
                        and depth == 0
                        and self._cool("shed", tenant, now)):
                    return Decision(
                        t=now, knob="shed", direction="off",
                        old=True, new=False, signal="burn",
                        value=burn, threshold=b["shed_lo"],
                        tenant=tenant,
                    )
        return None

    def _actuate(self, d: Decision, now: float) -> None:
        if d.knob == "shed":
            if d.new:
                self._shed.add(d.tenant)
            else:
                self._shed.discard(d.tenant)
            self.set_shed(d.tenant, bool(d.new))
        elif d.knob == "weight":
            self._weights[d.tenant] = float(d.new)
            self._hello_weight.setdefault(d.tenant, float(d.old))
            self.set_weight(d.tenant, float(d.new))
        else:
            spec = self.specs[d.knob]
            ladder = spec.ladder()
            assert d.new in ladder, (d.knob, d.new, ladder)
            self.values[d.knob] = d.new
            self.apply_knob(d.knob, d.new)
        self._last_act[(d.knob, d.tenant)] = now
        self.decisions.append(d)
        self._act_ctr.add(1, knob=d.knob, direction=d.direction)
        # the actuation trail rides its own flight-recorder namespace
        # (/trace?ns=autopilot) so decisions line up with the block
        # timeline without colliding with block numbers
        self._seq += 1
        root = self.tracer.begin_block(
            self._seq, ns="autopilot", **d.to_dict()
        )
        self.tracer.finish_block(root)
        _log.info(
            "autopilot: %s %s %s -> %s (%s=%s, threshold %s%s)",
            d.knob, d.direction, d.old, d.new, d.signal,
            d.value if d.value is not None else "n/a", d.threshold,
            f", tenant {d.tenant}" if d.tenant else "",
        )

    def observe_hello(self, tenant: str, weight: float) -> None:
        """Record a tenant's declared weight as its restore target
        (the sidecar server calls this at hello)."""
        with self._lock:
            self._hello_weight[tenant] = float(weight)
            self._weights.setdefault(tenant, float(weight))

    # -- background driver -------------------------------------------------

    def start(self) -> "Autopilot":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.tick_s):
                try:
                    self.tick()
                except Exception as e:  # the loop must never die
                    _log.warning("autopilot tick failed: %s", e)

        self._thread = threading.Thread(
            target=run, name="fabtpu-autopilot", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- /autopilot --------------------------------------------------------

    def report(self) -> dict:
        """JSON-able snapshot for the operations endpoint: current
        knob vector, clamp ranges, tenant shed/weight state, and the
        last N decisions with their triggering signals."""
        with self._lock:
            sigs = self._last_signals
            out = {
                "enabled": self._enabled,
                "throughput_mode": self._throughput_hold > 0,
                "tick_s": self.tick_s,
                "knobs": {
                    name: {
                        "value": self.values.get(name),
                        "min": spec.lo, "max": spec.hi,
                        "ladder": list(spec.ladder()),
                        "cooldown_s": spec.cooldown_s,
                    }
                    for name, spec in sorted(self.specs.items())
                    if spec.ladder()
                },
                "tenants": {
                    "shed": sorted(self._shed),
                    "weights": dict(sorted(self._weights.items())),
                    "hello_weights": dict(
                        sorted(self._hello_weight.items())
                    ),
                },
                "decisions": [d.to_dict() for d in self.decisions],
            }
        if sigs is not None:
            out["signals"] = {
                "burn": {
                    f"{n}/{c or '-'}": (round(v, 4)
                                        if v is not None else None)
                    for (n, c), v in sorted(sigs.burn.items())
                },
                "queue_age_p99_ms": dict(
                    sorted(sigs.queue_age_p99_ms.items())
                ),
                "busy_rate": dict(sorted(sigs.busy_rate.items())),
                "launch_p99_ms": sigs.launch_p99_ms,
                "device_queue_p99_ms": sigs.device_queue_p99_ms,
                "apply_queue_age_ms": sigs.apply_queue_age_ms,
                "overlap_coverage": sigs.overlap_coverage,
                "prefetch_p99_ms": sigs.prefetch_p99_ms,
                "clock_s": round(sigs.clock_s, 3),
            }
        return out


# -- process-global handle (what /autopilot serves by default) --------------

_global: Autopilot | None = None


def global_autopilot() -> Autopilot | None:
    return _global


def set_global(ap: Autopilot | None) -> None:
    global _global
    _global = ap

"""fabric_tpu.control — the traffic autopilot: closed-loop overload
control over the SLO burn-rate engine and the scheduler telemetry
(autopilot.py)."""

from fabric_tpu.control.autopilot import (  # noqa: F401
    DEFAULT_BANDS,
    DEFAULT_KNOB_SPECS,
    Autopilot,
    Decision,
    KnobSpec,
    KnobSpecError,
    Signals,
    global_autopilot,
    host_clamped_specs,
    parse_knob_specs,
    resolve_host_workers_initial,
    set_global,
)

"""Device-resident MVCC version cache: an LRU key-range residency
manager with delta scatter commits.

Why this exists: every block used to re-gather its committed read
versions on host (the ``state_fill`` stage — ``get_versions_cols``
over the block's unique keys) and ship the result up inside the launch
frame, because the device forgot the world between blocks.  But the
commit pipeline already COMPUTES the exact per-block change to that
world — the committed ``UpdateBatch`` (and at depth N, the merged
overlay machinery proves those deltas compose).  Keeping a version
table resident in device memory turns the per-block cost from
O(unique read keys) host work + upload into:

* a host dict probe per unique key (the residency directory),
* ONE small launch upload — slot ids plus host-provided lanes for the
  misses and the in-flight-overlay overrides,
* one scatter per committed block applying its write-set delta.

Millions of keys won't fit, so residency is an **LRU key-range
cache**: keys hash into ``2^range_bits`` ranges and ranges are the
admission/eviction unit — hot-key working sets (the realistic traffic
shape) stay pinned while cold ranges age out.  A missed key rides the
host path for ITS block (the shrunken ``state_fill``) and is admitted
for the next one.

Coherence with the depth-N pipeline (peer/pipeline.py): the table
always holds committed state as of some prefix of the chain, and every
launch overlays the in-flight commit window on top — exactly the
contract the host ``state_fill`` already satisfies:

* the commit scatter (:meth:`apply_batch`) runs inside the pipeline's
  commit boundary, BEFORE the block's commit future resolves, so a
  launch whose overlay no longer covers block k has happens-before
  ordering with k's scatter;
* a launch whose overlay still covers k forces k's keys onto host
  lanes carrying the overlay values — whether the scatter landed or
  not, the override wins (and the scatter writes the same values);
* jax arrays are immutable, so a launch captures a consistent table
  SNAPSHOT (:meth:`lookup` returns slots and table atomically under
  the lock); later scatters/evictions produce new arrays and can
  never tear an in-flight dispatch.

Admission never persists a racy read: keys covered by the launch
overlay are NOT admitted at launch time (their committed read races
the in-flight apply) — the commit scatter lands them with the
authoritative value instead.

Key-range mesh sharding (fabric_tpu/parallel partition rules): on a
device mesh, the table's pow2 slot space splits into one contiguous
slot block per data-axis shard, and every key range is OWNED by the
shard its range id's top bits select (``_shard_of``).  Admission,
eviction and commit scatters allocate/free slots only inside the
owning shard's block, so the ``state_table`` rule's axis-0 partition
physically places each key range on its owner device — a multi-host
fabric partitions the committed-version table without replication.
Eviction pressure is per shard (a hot shard evicts its own LRU
ranges, never a neighbor's), which is what the bench
``extras.shard_balance`` skew numbers watch.  Mesh resize goes
through :meth:`reshard` — disable-latch → cold rebuild, the safe
fallback: verdicts never change, the working set re-faults in.

Failure containment: any device error inside the manager latches it
DISABLED (:meth:`disable`) — every lookup then misses and blocks ride
the host oracle path; verdicts never change, only time does.  Nothing
here is durable: a crash rebuilds residency cold from the reopened
ledger's traffic (pinned by the differential battery).

Default OFF (nodeconfig ``state_resident``): CPU/tier-1 hosts keep
the exact existing ``state_fill`` path and never construct a manager.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from collections import OrderedDict, deque

import numpy as np

_log = logging.getLogger("fabric_tpu.state.residency")

#: bytes per table slot: (present, ver_block, ver_txnum) int32
SLOT_BYTES = 12

#: smallest table the capacity knob can produce — below this the
#: directory overhead dwarfs the cache
MIN_SLOTS = 256

#: scatter row-count buckets (pow2) so the jitted update kernel
#: compiles a bounded family of shapes
_MIN_SCATTER = 16

#: trailing lookups the hit-rate gauge aggregates over
_HIT_WINDOW = 256


def _mesh_shards(mesh) -> int:
    """Data-axis shard count of a mesh WITHOUT importing jax (the
    manager must stay constructible on jax-free hosts): the Mesh
    object carries its own axis sizes."""
    if mesh is None:
        return 1
    try:
        return int(dict(mesh.shape).get("data", mesh.size))
    except Exception:
        return int(getattr(mesh, "size", 1) or 1)


def _ver_i32(block: int, txnum: int) -> tuple[int, int]:
    """(block, txnum) version → int32 bit patterns (the table stores
    uint32 versions as raw int32 bits; every consumer compares for
    EQUALITY only, so the reinterpretation is exact)."""
    return (
        int(np.uint32(block).view(np.int32)),
        int(np.uint32(txnum).view(np.int32)),
    )


def build_launch_pack(res: "ResidencyManager", pairs: list, state,
                      overlay=None, u_index: dict | None = None):
    """One block's resident-state launch operands:
    ``(table_snapshot, u_pack [Ub, 4] int32)`` — or None when the
    block must take the host oracle path (working set larger than the
    whole table, cache latched off mid-way).

    * hits reference table slots captured ATOMICALLY with the table
      snapshot (:meth:`ResidencyManager.lookup`);
    * misses ride host lanes (slot −1) carrying the SHRUNKEN
      ``state_fill`` gather (``state.get_versions_cols`` over the miss
      set only) and are admitted for future blocks;
    * keys the in-flight overlay window touches are FORCED onto host
      lanes with the overlay values — the same override rule the host
      ``_flat_ver_ok`` applies, so resident ≡ host by construction —
      and are never admitted from the (racy) committed read: their
      commit scatter lands the authoritative value at the commit
      boundary.

    ``u_pack`` pads to a pow2 bucket so the stage-2 program cache
    compiles one variant per bucket, and its upload bytes (plus the
    admit scatter) feed the per-block h2d accounting."""
    U = len(pairs)
    if U > res.capacity:
        return None  # guaranteed eviction thrash: host path
    # overlay override set FIRST: lookup accounts forced lanes on
    # their own counter (neither hit nor miss — the A/B attribution
    # must not credit the table for reads served from the overlay)
    over_vals: dict[int, tuple] = {}
    forced: set | None = None
    if overlay is not None and getattr(overlay, "updates", None):
        if u_index is None:
            u_index = dict(zip(pairs, range(U)))
        iget = u_index.get
        for pr, vv in overlay.updates.items():
            ui = iget(pr)
            if ui is None:
                continue
            if vv.value is None:  # in-flight delete
                over_vals[ui] = (0, 0, 0)
            else:
                vb, vt = _ver_i32(int(vv.version[0]),
                                  int(vv.version[1]))
                over_vals[ui] = (1, vb, vt)
        if over_vals:
            forced = {pairs[ui] for ui in over_vals}
    slots, table = res.lookup(pairs, forced_pairs=forced)
    if table is None:
        return None  # latched off under the lookup
    host_pack = np.zeros((U, 3), np.int32)
    nbytes = 0
    # misses = host lanes that really gather from the state DB (the
    # forced overlay lanes came back −1 too, but their values come
    # from the overlay below and they are never admitted from the
    # racy committed read — the commit scatter lands them)
    miss_rows = [
        i for i in np.flatnonzero(slots < 0).tolist()
        if i not in over_vals
    ]
    if miss_rows:
        miss_pairs = [pairs[i] for i in miss_rows]
        # THE shrunken state_fill: only the miss set hits the backend
        up, uv = state.get_versions_cols(miss_pairs)
        rows = np.asarray(miss_rows)
        host_pack[rows, 0] = up
        host_pack[rows, 1:3] = uv.view(np.int32)
        nbytes += res.admit(miss_pairs, up, uv)
    for ui, row in over_vals.items():
        host_pack[ui] = row
    Ub = max(_MIN_SCATTER, 1 << max(U - 1, 0).bit_length())
    u_pack = np.full((Ub, 4), -1, np.int32)
    u_pack[:, 1:4] = 0
    if U:
        u_pack[:U, 0] = slots
        u_pack[:U, 1:4] = host_pack
    res.note_upload(u_pack.nbytes)
    res.observe_block(nbytes + u_pack.nbytes)
    return table, u_pack


def resolve_residency(state_resident: bool, mb: int, range_bits: int,
                      mesh=None, channel: str = ""):
    """Production knob triple → a :class:`ResidencyManager` or None
    (the nodeconfig ``state_resident`` / ``state_resident_mb`` /
    ``state_resident_range_bits`` flow) — mirrors ``resolve_mesh`` /
    ``resolve_host_pool``: OFF costs nothing, not even the import of
    the device stack (the table builds lazily)."""
    if not state_resident:
        return None
    return ResidencyManager(capacity_mb=mb, range_bits=range_bits,
                            mesh=mesh, channel=channel)


class ResidencyManager:
    """See module docstring.

    Locking: ONE lock guards the directory (key → slot), the range
    LRU, the free-slot pool and the table pointer.  Table mutation is
    a functional scatter (``table.at[idx].set``) producing a NEW
    array, so readers holding an older snapshot are never torn; the
    lock only serializes the read-modify-write of the pointer (a
    commit scatter on the committer thread vs an admission on the
    launch thread would otherwise lose one of the two updates).
    """

    def __init__(self, capacity_mb: int = 64, range_bits: int = 12,
                 mesh=None, channel: str = "", registry=None,
                 slots: int | None = None,
                 write_admit_budget: int = 2):
        if capacity_mb < 1:
            raise ValueError("state_resident_mb must be >= 1")
        if not (1 <= int(range_bits) <= 24):
            raise ValueError(
                "state_resident_range_bits must be in [1, 24]"
            )
        if int(write_admit_budget) < 0:
            raise ValueError("write_admit_budget must be >= 0")
        if slots is not None:
            # explicit slot count — the test seam that makes eviction
            # churn drivable without a megabyte working set
            if slots < 4:
                raise ValueError("slots must be >= 4")
            self.capacity = 1 << (int(slots).bit_length() - 1)
        else:
            want = (int(capacity_mb) * (1 << 20)) // SLOT_BYTES
            # pow2 slot count: mesh shards divide it exactly and the
            # stage-2 program cache keys on the table shape
            self.capacity = max(
                MIN_SLOTS, 1 << (max(want, 1).bit_length() - 1)
            )
        self.range_bits = int(range_bits)
        # per-apply_batch cap on BRAND-NEW ranges a block's write-set
        # may open in the table (free slots only, never evicting):
        # write-once traffic shapes (serial keys, audit logs) would
        # otherwise open a new range every block and starve the
        # read-tuned LRU of free slots
        self.write_admit_budget = int(write_admit_budget)
        self.mesh = mesh
        self.channel = channel
        self._lock = threading.Lock()
        self._table = None  # lazy [capacity, 3] int32 on device
        # key-range mesh sharding (module docstring): one contiguous
        # slot block per data-axis shard, ranges owned by the shard
        # their id's top bits select.  A mesh whose data axis does not
        # divide the pow2 capacity (or exceeds it) degrades to one
        # logical shard — the table still shards on device, only the
        # range→shard routing is off.
        self._n_shards = self._resolve_shards(mesh)
        self._slots_per_shard = self.capacity // self._n_shards
        # (ns, key) → (slot, range_id): the range id is immutable per
        # key, so caching it here keeps every post-admission path — the
        # launch-critical lookup especially — a pure dict probe (no
        # per-hit blake2b under the lock)
        self._dir: dict[tuple, tuple] = {}
        self._ranges: OrderedDict[int, list] = OrderedDict()  # LRU
        self._free: list[list[int]] = self._fresh_free()
        self._enabled = True
        self._reshards_total = 0
        self._scatter_fns: dict[int, object] = {}
        self._recent: deque[tuple[int, int]] = deque(maxlen=_HIT_WINDOW)
        self._hits_total = 0
        self._misses_total = 0
        self._overlay_forced_total = 0
        self._evictions_total = 0
        self._write_admits_total = 0
        self._h2d_bytes_total = 0
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._hits_ctr = registry.counter(
            "state_resident_hits_total",
            "unique read keys served from the device-resident table",
        )
        self._miss_ctr = registry.counter(
            "state_resident_misses_total",
            "unique read keys that fell back to the host state gather",
        )
        self._forced_ctr = registry.counter(
            "state_resident_overlay_forced_total",
            "unique read keys routed onto overlay-valued host lanes "
            "(neither a resident hit nor a state-gather miss)",
        )
        self._evict_ctr = registry.counter(
            "state_resident_evictions_total",
            "key ranges evicted from the device-resident table (LRU)",
        )
        self._write_admit_ctr = registry.counter(
            "state_resident_write_admits_total",
            "brand-new key ranges the commit write path admitted into "
            "the resident table (budgeted per block, free slots only)",
        )
        self._hit_gauge = registry.gauge(
            "state_resident_hit_rate",
            "trailing resident hit rate over unique read keys",
        )
        self._enabled_gauge = registry.gauge(
            "state_resident_enabled",
            "1 while the device-resident state cache is serving lookups",
        )
        self._h2d_hist = registry.histogram(
            "h2d_state_bytes_per_block",
            "state bytes uploaded per block on the resident path "
            "(miss fill + launch slot frame + write-set delta)",
            buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576,
                     float("inf")),
        )
        self._enabled_gauge.set(1, channel=self.channel)

    # -- key-range shard geometry ------------------------------------------

    def _resolve_shards(self, mesh) -> int:
        n = _mesh_shards(mesh)
        if n < 2 or n > self.capacity or self.capacity % n:
            return 1
        return n

    def _fresh_free(self) -> list:
        """Per-shard free-slot pools, each descending so ``pop()``
        hands out the lowest slot in the shard's block first."""
        sps = self._slots_per_shard
        return [
            list(range((s + 1) * sps - 1, s * sps - 1, -1))
            for s in range(self._n_shards)
        ]

    def _shard_of(self, rid: int) -> int:
        """Owning shard of a key range: the top bits of the range id
        (``floor(rid * n / 2^range_bits)``) — contiguous range blocks
        map to contiguous shards, matching the table's contiguous
        slot blocks under the axis-0 ``state_table`` partition."""
        return (rid * self._n_shards) >> self.range_bits

    # -- state -------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def disable(self, reason: str = "") -> None:
        """Latch the cache OFF — every subsequent lookup misses, so
        blocks ride the host ``state_fill`` oracle.  Called on any
        device error inside the manager (and by the validator when a
        resident launch path throws): the latch changes time, never
        verdicts."""
        with self._lock:
            already = not self._enabled
            self._enabled = False
            self._table = None
            self._dir.clear()
            self._ranges.clear()
            self._free = self._fresh_free()
        if not already:
            self._enabled_gauge.set(0, channel=self.channel)
            _log.warning(
                "%s: device-resident state cache DISABLED (%s) — "
                "blocks take the host state_fill path",
                self.channel or "validator", reason or "unspecified",
            )

    def range_of(self, ns: str, key: str) -> int:
        """Stable hash range id for a key — the top ``range_bits``
        bits of a 64-bit digest of ``ns \\0 key``."""
        h = hashlib.blake2b(
            f"{ns}\x00{key}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") >> (64 - self.range_bits)

    # -- the device table --------------------------------------------------

    def _ensure_table(self):
        """Lazy table build (first armed lookup): jax is imported here
        and nowhere at module level, so constructing a manager on a
        jax-free host costs nothing until the device path engages."""
        if self._table is None:
            import jax.numpy as jnp

            from fabric_tpu.parallel.mesh import shard_state_table

            self._table = shard_state_table(
                self.mesh, jnp.zeros((self.capacity, 3), jnp.int32)
            )
            # HBM owner tag (observe/ledger.py): the resident table
            # pins capacity*12 bytes of device memory once built
            from fabric_tpu.observe import ledger as _ledger

            _ledger.account_hbm("resident_table",
                                self.capacity * SLOT_BYTES)
        return self._table

    def _scatter(self, idx: np.ndarray, rows: np.ndarray) -> None:
        """table[idx] = rows, functionally, under the caller-held
        lock.  Rows pad to a pow2 bucket with out-of-bounds indices
        (== capacity), which jax scatter DROPS — one compiled update
        program per bucket size, not per row count."""
        import jax
        import jax.numpy as jnp

        k = len(idx)
        if k == 0:
            return
        bucket = max(_MIN_SCATTER, 1 << (k - 1).bit_length())
        pidx = np.full(bucket, self.capacity, np.int32)
        prows = np.zeros((bucket, 3), np.int32)
        pidx[:k] = idx
        prows[:k] = rows
        fn = self._scatter_fns.get(bucket)
        compiled = fn is None
        if compiled:
            fn = self._scatter_fns[bucket] = jax.jit(
                lambda t, i, r: t.at[i].set(r)
            )
        # launch ledger: scatters are enqueue-only (functional update,
        # never awaited) — the row records compile + h2d, not execute
        from fabric_tpu.observe import ledger as _ledger

        rec = _ledger.launch("resident_scatter", compiled=compiled,
                             lanes=k,
                             h2d_bytes=pidx.nbytes + prows.nbytes)
        self._table = fn(self._ensure_table(), jnp.asarray(pidx),
                         jnp.asarray(prows))
        if rec is not None:
            rec.complete()

    # -- lookups (launch path) ---------------------------------------------

    def lookup(self, pairs: list, forced_pairs: set | None = None):
        """Unique read keys → ``(slots [U] int32, table_snapshot)``.

        ``slots[i] == -1`` means miss (the caller fills a host lane
        and may :meth:`admit` the key for future blocks).  The table
        snapshot and the slot vector are taken atomically under the
        lock, so a concurrent commit scatter or admission eviction can
        never remap a returned slot out from under the dispatch —
        functional arrays keep the snapshot's rows intact forever.

        ``forced_pairs``: keys the caller will route onto host lanes
        REGARDLESS of residency (the in-flight overlay override set) —
        they come back −1 and are accounted on the dedicated
        overlay-forced counter, NOT as hits or misses: a block whose
        whole read set rides overlay lanes must not report hit_rate
        1.0 when zero reads were served from the device table (the
        bench A/B attribution would lie).  Their ranges still touch
        the LRU when resident — the keys stay hot.

        Touches the LRU for every HIT range (the working set stays
        pinned while it is actually read)."""
        U = len(pairs)
        slots = np.full(U, -1, np.int32)
        if not self._enabled:
            return slots, None
        with self._lock:
            if not self._enabled:
                return slots, None
            get = self._dir.get
            touched: set[int] = set()
            hits = 0
            forced = 0
            for i, pr in enumerate(pairs):
                e = get(pr)
                if forced_pairs is not None and pr in forced_pairs:
                    forced += 1
                    if e is not None and e[1] not in touched:
                        touched.add(e[1])
                        self._ranges.move_to_end(e[1])
                    continue  # slot stays −1: host lane by contract
                if e is not None:
                    slots[i] = e[0]
                    hits += 1
                    if e[1] not in touched:
                        touched.add(e[1])
                        self._ranges.move_to_end(e[1])
            # the table is part of the snapshot even on an all-miss
            # lookup: the resident dispatch variant needs the operand
            # regardless, and building it here keeps snapshot+slots
            # atomic under the one lock
            table = self._ensure_table()
            misses = U - hits - forced
            self._hits_total += hits
            self._misses_total += misses
            self._overlay_forced_total += forced
            if hits or misses:
                self._recent.append((hits, hits + misses))
            wh = sum(h for h, _t in self._recent)
            wt = sum(t for _h, t in self._recent)
        if hits:
            self._hits_ctr.add(hits, channel=self.channel)
        if misses:
            self._miss_ctr.add(misses, channel=self.channel)
        if forced:
            self._forced_ctr.add(forced, channel=self.channel)
        if wt:
            self._hit_gauge.set(round(wh / wt, 4), channel=self.channel)
        return slots, table

    # -- admission + eviction ----------------------------------------------

    def admit(self, pairs: list, present: np.ndarray,
              vers: np.ndarray, evict: bool = True) -> int:
        """Admit missed keys with their host-gathered committed
        (present, version) values — the miss path's partial range
        upload.  Absent keys are admitted too (``present`` False →
        table row 0): cached absence is exactly as load-bearing as a
        cached version for the MVCC compare.

        Evicts LRU ranges (never ones being admitted by THIS call)
        when the free pool runs dry; keys that still cannot get a slot
        are simply skipped — they stay misses.  ``evict=False`` admits
        into free slots only (the bulk warm path must not thrash what
        it just loaded).  Returns the bytes scattered to device (h2d
        accounting)."""
        if not self._enabled or not pairs:
            return 0
        idx: list[int] = []
        rows: list[tuple] = []
        with self._lock:
            if not self._enabled:
                return 0
            admitting: set[int] = set()
            # shards whose pool ran dry AND had nothing evictable this
            # call — later keys routed there stay misses without
            # rescanning the LRU per key
            dead: set[int] = set()
            for i, pr in enumerate(pairs):
                if pr in self._dir:
                    continue
                rid = self.range_of(pr[0], pr[1])
                sh = self._shard_of(rid)
                if sh in dead:
                    continue
                if not self._free[sh] and not (
                        evict and self._evict_locked(
                            protect=admitting | {rid}, shard=sh)):
                    dead.add(sh)  # nothing evictable on the owner
                    continue
                if not self._free[sh]:
                    dead.add(sh)
                    continue
                slot = self._free[sh].pop()
                self._dir[pr] = (slot, rid)
                admitting.add(rid)
                if rid in self._ranges:
                    self._ranges[rid].append(pr)
                    self._ranges.move_to_end(rid)
                else:
                    self._ranges[rid] = [pr]
                idx.append(slot)
                p = bool(present[i])
                vb, vt = (
                    _ver_i32(int(vers[i][0]), int(vers[i][1]))
                    if p else (0, 0)
                )
                rows.append((int(p), vb, vt))
            if not idx:
                return 0
            arr_idx = np.asarray(idx, np.int32)
            arr_rows = np.asarray(rows, np.int32).reshape(-1, 3)
            try:
                self._scatter(arr_idx, arr_rows)
            except Exception as e:
                self._disable_locked()
                _log.warning(
                    "%s: resident admit scatter failed (%s) — cache "
                    "disabled", self.channel or "validator", e,
                )
                return 0
            nbytes = len(idx) * SLOT_BYTES
            self._h2d_bytes_total += nbytes
        self._enabled_gauge.set(1 if self._enabled else 0,
                                channel=self.channel)
        return nbytes

    def warm(self, items, limit: int | None = None) -> int:
        """Bulk-admit committed ``(ns, key, (block, txnum))`` triples —
        the snapshot-join warm path (ledger/snapshot.py
        ``warm_resident``): instead of faulting the working set in
        miss-by-miss over the first replayed blocks, the importer
        streams the snapshot's key ranges straight into free slots.

        Never evicts (``admit(evict=False)``) and stops at capacity —
        warming must fill the cache, not churn it.  Returns the number
        of keys admitted."""
        if not self._enabled:
            return 0
        admitted = 0
        pairs: list = []
        vers: list = []
        slab = 8192

        def flush() -> bool:
            nonlocal admitted
            if not pairs:
                return True
            nb = self.admit(
                pairs, np.ones(len(pairs), np.bool_),
                np.asarray(vers, np.int64).reshape(-1, 2), evict=False,
            )
            got = nb // SLOT_BYTES
            admitted += got
            full = got < len(pairs)
            pairs.clear()
            vers.clear()
            return not full

        for ns, key, ver in items:
            pairs.append((ns, key))
            vers.append((int(ver[0]), int(ver[1])))
            if len(pairs) >= slab:
                if not flush():
                    return admitted
            if limit is not None and admitted + len(pairs) >= limit:
                break
        flush()
        return admitted

    def _evict_locked(self, protect: set, shard: int | None = None) -> bool:
        """Evict the least-recently-touched range not in ``protect``
        (owned by ``shard`` when given — eviction pressure is routed
        to the shard that needs the slots, never a neighbor); caller
        holds the lock.  Returns True when slots were freed.  Evicted
        rows need no device clear — the directory is authoritative,
        and slot reuse always scatters the new value before any
        launch frame can reference it."""
        for rid in self._ranges:
            if rid in protect:
                continue
            sh = self._shard_of(rid)
            if shard is not None and sh != shard:
                continue
            keys = self._ranges.pop(rid)
            for pr in keys:
                e = self._dir.pop(pr, None)
                if e is not None:
                    self._free[sh].append(e[0])
            self._evictions_total += 1
            self._evict_ctr.add(1, channel=self.channel)
            return True
        return False

    # -- the commit boundary -----------------------------------------------

    def apply_batch(self, batch) -> int:
        """Apply one committed block's write-set as a device scatter —
        the delta the PR-9 merged-overlay machinery already computes.
        Runs at the pipeline's commit boundary (committer thread, or
        inline for barriers/serial commits), BEFORE the block leaves
        the in-flight overlay window — see the module docstring's
        coherence argument.

        Keys with a slot are updated in place (deletes scatter
        present=0 — cached absence).  A written key WITHOUT a slot is
        admitted into a free slot when its range is already resident
        (the value is known, so admission is free); a write touching a
        BRAND-NEW range may open it, but only within
        ``write_admit_budget`` new ranges per call and only into free
        slots — commits never evict, eviction pressure belongs to the
        read path, and an unbudgeted write-shaped working set must not
        drain the free pool out from under read admissions.  Returns
        the bytes scattered (h2d accounting).  Idempotent: replaying
        a batch scatters the same values."""
        if not self._enabled or batch is None:
            return 0
        updates = getattr(batch, "updates", None)
        if not updates:
            return 0
        with self._lock:
            if not self._enabled:
                return 0
            idx: list[int] = []
            rows: list[tuple] = []
            new_rids: set[int] = set()
            for (ns, key), vv in updates.items():
                pr = (ns, key)
                e = self._dir.get(pr)
                if e is None:
                    rid = self.range_of(ns, key)
                    sh = self._shard_of(rid)
                    if not self._free[sh]:
                        continue  # owner's pool dry: stays a miss
                    if rid not in self._ranges:
                        # brand-new range discovered by a write:
                        # admit within this call's budget only
                        if len(new_rids) >= self.write_admit_budget:
                            continue
                        new_rids.add(rid)
                        self._ranges[rid] = []
                    slot = self._free[sh].pop()
                    self._dir[pr] = (slot, rid)
                    self._ranges[rid].append(pr)
                else:
                    slot = e[0]
                if vv.value is None:
                    rows.append((0, 0, 0))
                else:
                    vb, vt = _ver_i32(int(vv.version[0]),
                                      int(vv.version[1]))
                    rows.append((1, vb, vt))
                idx.append(slot)
            if not idx:
                return 0
            try:
                self._scatter(np.asarray(idx, np.int32),
                              np.asarray(rows, np.int32))
            except Exception as e:
                self._disable_locked()
                _log.warning(
                    "%s: resident commit scatter failed (%s) — cache "
                    "disabled", self.channel or "validator", e,
                )
                return 0
            nbytes = len(idx) * SLOT_BYTES
            self._h2d_bytes_total += nbytes
            self._write_admits_total += len(new_rids)
        if new_rids:
            self._write_admit_ctr.add(len(new_rids),
                                      channel=self.channel)
        return nbytes

    def invalidate_keys(self, pairs) -> None:
        """Drop keys from residency (the invalidation hook FT015
        polices): a committed-store write that bypasses
        :meth:`apply_batch` MUST at least invalidate, or a stale
        resident version silently corrupts MVCC verdicts."""
        with self._lock:
            for pr in pairs:
                e = self._dir.pop(tuple(pr), None)
                if e is None:
                    continue
                slot, rid = e
                keys = self._ranges.get(rid)
                if keys is not None:
                    try:
                        keys.remove(tuple(pr))
                    except ValueError:
                        pass
                    if not keys:
                        self._ranges.pop(rid, None)
                self._free[self._shard_of(rid)].append(slot)

    def _disable_locked(self) -> None:
        self._enabled = False
        self._table = None
        self._dir.clear()
        self._ranges.clear()
        self._free = self._fresh_free()
        self._enabled_gauge.set(0, channel=self.channel)

    # -- mesh resize -------------------------------------------------------

    def reshard(self, mesh) -> dict:
        """Mesh-resize resharding: disable-latch → cold rebuild, the
        safe fallback path.  The directory and device table drop
        atomically under the lock, the shard geometry recomputes for
        the new mesh, and the manager re-arms — the next launch
        rebuilds the table lazily under the new ``state_table``
        sharding and the working set re-faults in miss-by-miss (or
        via :meth:`warm`).  Verdicts never change across a reshard:
        every key simply rides the host oracle until readmitted.
        Counters survive (the A/B attribution spans the resize);
        ``reshards_total`` records the event.  Returns a stats
        snapshot of the fresh geometry."""
        with self._lock:
            self.mesh = mesh
            self._n_shards = self._resolve_shards(mesh)
            self._slots_per_shard = self.capacity // self._n_shards
            self._dir.clear()
            self._ranges.clear()
            self._table = None
            self._free = self._fresh_free()
            self._enabled = True
            self._reshards_total += 1
        self._enabled_gauge.set(1, channel=self.channel)
        _log.info(
            "%s: resident table resharded to %d shard(s) "
            "(%d slots each) — cold rebuild",
            self.channel or "validator", self._n_shards,
            self._slots_per_shard,
        )
        return self.stats()

    # -- accounting --------------------------------------------------------

    def note_upload(self, nbytes: int) -> None:
        """Count launch-frame bytes (the per-block slot/host-lane
        pack) toward the h2d total; the validator calls this once per
        resident block and then observes :meth:`block_bytes`."""
        with self._lock:
            self._h2d_bytes_total += int(nbytes)

    def observe_block(self, nbytes: int) -> None:
        """One block's total state upload (miss fill + slot frame +
        any admit scatter) → the ``h2d_state_bytes_per_block``
        histogram, folded into the launch ledger's per-kernel h2d
        accounting too (the ``state`` lane on /launches)."""
        self._h2d_hist.observe(int(nbytes), channel=self.channel)
        from fabric_tpu.observe import ledger as _ledger

        _ledger.note_h2d("state", nbytes)

    def shard_balance(self) -> dict:
        """Key-range occupancy per shard — the bench
        ``extras.shard_balance`` payload and the dryrun balance
        assertion: per-shard resident key/range counts, free slots,
        and the max/mean occupancy imbalance (1.0 = perfectly even;
        blake2b range hashing keeps it close at realistic working-set
        sizes)."""
        with self._lock:
            n = self._n_shards
            keys = [0] * n
            ranges = [0] * n
            for rid, ks in self._ranges.items():
                sh = self._shard_of(rid)
                ranges[sh] += 1
                keys[sh] += len(ks)
            free = [len(f) for f in self._free]
            sps = self._slots_per_shard
        mean = sum(keys) / n if n else 0.0
        mx = max(keys) if keys else 0
        return {
            "shards": n,
            "slots_per_shard": sps,
            "per_shard_keys": keys,
            "per_shard_ranges": ranges,
            "per_shard_free_slots": free,
            "occupancy_max": mx,
            "occupancy_mean": round(mean, 2),
            "imbalance_max_over_mean": (
                round(mx / mean, 4) if mean else None
            ),
        }

    def stats(self) -> dict:
        """Snapshot for bench extras and tests."""
        with self._lock:
            wh = sum(h for h, _t in self._recent)
            wt = sum(t for _h, t in self._recent)
            return {
                "enabled": self._enabled,
                "capacity_slots": self.capacity,
                "range_bits": self.range_bits,
                "shards": self._n_shards,
                "slots_per_shard": self._slots_per_shard,
                "reshards_total": self._reshards_total,
                "resident_keys": len(self._dir),
                "resident_ranges": len(self._ranges),
                "hits_total": self._hits_total,
                "misses_total": self._misses_total,
                "overlay_forced_total": self._overlay_forced_total,
                "hit_rate": round(wh / wt, 4) if wt else None,
                "evictions_total": self._evictions_total,
                "write_admits_total": self._write_admits_total,
                "write_admit_budget": self.write_admit_budget,
                "h2d_bytes_total": self._h2d_bytes_total,
            }

"""Device-resident MVCC state: the LRU key-range residency cache.

The subsystem that lets the fused stage-2 program read committed
versions from DEVICE memory instead of re-gathering them on host every
block (``fabric_tpu/state/residency.py``).  The host ``state_fill``
path stays intact as the bit-equal oracle and the per-block fallback.
"""

from fabric_tpu.state.residency import (  # noqa: F401
    ResidencyManager,
    build_launch_pack,
    resolve_residency,
)

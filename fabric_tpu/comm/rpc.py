"""Framed-message RPC over asyncio TCP with optional mutual TLS.

The control-plane transport of the framework — the analog of the
reference's gRPC/mTLS plumbing (internal/pkg/comm/server.go:45,
connection cache internal/peer/node/start.go:279-290).  The image
ships no grpcio, so this speaks a minimal multiplexed-stream protocol
with the same shape as gRPC (unary and bidi-streaming methods, one TCP
connection per peer pair, TLS client auth):

    frame   := u32 length | u32 stream_id | u8 kind | payload
    kind    := CALL (payload = method name utf-8)
             | MSG  (payload = one message, caller-defined bytes)
             | END  (half-close)
             | ERR  (payload = utf-8 error text)

Handlers are ``async def handler(recv, send)`` where ``recv`` is an
async iterator of request payloads and ``send`` awaits response
payloads; unary sugar wraps that.  Protobuf (de)serialization stays at
the call site — the transport moves bytes.
"""

from __future__ import annotations

import asyncio
import ssl
import struct

from fabric_tpu import faults as _faults

KIND_CALL = 1
KIND_MSG = 2
KIND_END = 3
KIND_ERR = 4

_HDR = struct.Struct(">IIB")
MAX_FRAME = 64 * 1024 * 1024


class RpcError(Exception):
    pass


class FrameTooLargeError(RpcError):
    """A frame exceeding ``MAX_FRAME``, rejected on the SEND side.

    The read path always bounded frames; without the send-side check a
    caller handing an oversized payload (a runaway signature batch, a
    snapshot that outgrew its cap) only learned about it when the
    REMOTE tore the connection down — an unattributable disconnect
    instead of a typed error at the call site."""


async def _write_frame(writer, stream_id: int, kind: int, payload: bytes = b""):
    if len(payload) > MAX_FRAME:
        raise FrameTooLargeError(
            f"frame too large to send: {len(payload)} bytes exceeds "
            f"MAX_FRAME ({MAX_FRAME})"
        )
    # chaos hook: a FaultPlan can cut or delay any framed-RPC link
    # (the sidecar stream included); afire so an armed latency fault
    # slows THIS stream instead of freezing the whole event loop
    if _faults.plan() is not None:
        await _faults.afire("rpc.frame", kind=kind, stream=stream_id)
    writer.write(_HDR.pack(len(payload), stream_id, kind) + payload)
    await writer.drain()


async def _read_frame(reader):
    hdr = await reader.readexactly(_HDR.size)
    length, stream_id, kind = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    payload = await reader.readexactly(length) if length else b""
    return stream_id, kind, payload


class _Stream:
    """One logical RPC stream (either side).  ``method`` is the call
    name the stream was opened with — ERR frames carry it so a
    client-side stream failure names the RPC that died instead of an
    anonymous error string."""

    def __init__(self, conn: "_Conn", stream_id: int, method: str = ""):
        self.conn = conn
        self.id = stream_id
        self.method = method
        self.inbox: asyncio.Queue = asyncio.Queue()
        self.closed = False

    async def send(self, payload: bytes):
        await _write_frame(self.conn.writer, self.id, KIND_MSG, payload)

    async def end(self):
        if not self.closed:
            self.closed = True
            await _write_frame(self.conn.writer, self.id, KIND_END)

    async def error(self, msg: str):
        if not self.closed:
            self.closed = True
            if self.method and not msg.startswith(self.method):
                msg = f"{self.method}: {msg}"
            await _write_frame(self.conn.writer, self.id, KIND_ERR, msg.encode())

    def dispose(self):
        """Drop routing for this stream — required for fire-and-forget
        streams the remote never answers (no END frame will ever prune
        them from conn.streams)."""
        self.conn.streams.pop(self.id, None)

    def __aiter__(self):
        return self

    async def __anext__(self):
        item = await self.inbox.get()
        if item is _END:
            raise StopAsyncIteration
        if isinstance(item, RpcError):
            raise item
        return item


_END = object()


class _Conn:
    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.streams: dict[int, _Stream] = {}
        self.next_id = 1
        self.lock = asyncio.Lock()
        self.closed = asyncio.Event()
        # asyncio holds tasks only weakly: fire-and-forget dispatch
        # tasks must be strongly referenced or the GC can destroy them
        # mid-handler (observed as aclose()-while-running errors)
        self._tasks: set = set()

    async def pump(self, dispatch=None):
        """Read frames and route to streams; ``dispatch`` handles new
        CALL frames (server side)."""
        try:
            while True:
                stream_id, kind, payload = await _read_frame(self.reader)
                if kind == KIND_CALL:
                    if dispatch is None:
                        continue
                    st = _Stream(self, stream_id, method=payload.decode())
                    self.streams[stream_id] = st
                    t = asyncio.ensure_future(dispatch(st.method, st))
                    self._tasks.add(t)
                    t.add_done_callback(self._tasks.discard)
                elif stream_id in self.streams:
                    st = self.streams[stream_id]
                    if kind == KIND_MSG:
                        st.inbox.put_nowait(payload)
                    elif kind == KIND_END:
                        st.inbox.put_nowait(_END)
                        self.streams.pop(stream_id, None)  # remote done
                    elif kind == KIND_ERR:
                        st.inbox.put_nowait(RpcError(payload.decode()))
                        self.streams.pop(stream_id, None)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.closed.set()
            for st in self.streams.values():
                st.inbox.put_nowait(_END)
            try:
                self.writer.close()
            except (OSError, RuntimeError):
                pass  # transport already torn down


class RpcServer:
    """method name → async handler(stream).  A handler reads requests
    by iterating the stream and replies via stream.send()."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ssl_ctx: ssl.SSLContext | None = None):
        self.host, self.port = host, port
        self.ssl_ctx = ssl_ctx
        self.handlers: dict[str, object] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[_Conn] = set()

    def register(self, method: str, handler):
        self.handlers[method] = handler

    def register_unary(self, method: str, fn):
        """fn: async (request_bytes) -> response_bytes."""

        async def handler(stream: _Stream):
            try:
                req = await stream.__anext__()
                resp = await fn(req)
                await stream.send(resp)
                await stream.end()
            except RpcError as e:
                await stream.error(str(e))
            except Exception as e:  # handler bug → client sees error
                await stream.error(f"{type(e).__name__}: {e}")

        self.register(method, handler)

    async def start(self):
        async def on_conn(reader, writer):
            conn = _Conn(reader, writer)
            self._conns.add(conn)

            async def dispatch(method: str, st: _Stream):
                h = self.handlers.get(method)
                if h is None:
                    await st.error(f"unknown method {method}")
                    st.dispose()
                    return
                try:
                    await h(st)
                except RpcError as e:
                    await st.error(str(e))
                except (ConnectionError, OSError):
                    pass
                except Exception as e:
                    try:
                        await st.error(f"{type(e).__name__}: {e}")
                    except (ConnectionError, OSError, RuntimeError):
                        pass  # client went away before the error did
                finally:
                    st.dispose()  # handler finished: stop routing

            try:
                await conn.pump(dispatch)
            finally:
                self._conns.discard(conn)

        self._server = await asyncio.start_server(
            on_conn, self.host, self.port, ssl=self.ssl_ctx
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        if self._server is not None:
            self._server.close()
            for conn in list(self._conns):
                try:
                    conn.writer.close()
                except (OSError, RuntimeError):
                    pass  # already closed
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


class RpcClient:
    """One connection to a server; open_stream()/unary() per call."""

    def __init__(self, host: str, port: int,
                 ssl_ctx: ssl.SSLContext | None = None):
        self.host, self.port = host, port
        self.ssl_ctx = ssl_ctx
        self.conn: _Conn | None = None
        self._pump_task = None

    async def connect(self):
        reader, writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_ctx
        )
        self.conn = _Conn(reader, writer)
        self._pump_task = asyncio.ensure_future(self.conn.pump())
        return self

    async def open_stream(self, method: str) -> _Stream:
        if self.conn is None or self.conn.closed.is_set():
            await self.connect()
        async with self.conn.lock:
            stream_id = self.conn.next_id
            self.conn.next_id += 1
        st = _Stream(self.conn, stream_id, method=method)
        self.conn.streams[stream_id] = st
        await _write_frame(self.conn.writer, stream_id, KIND_CALL, method.encode())
        return st

    async def unary(self, method: str, request: bytes, timeout: float = 10.0) -> bytes:
        st = await self.open_stream(method)
        try:
            await st.send(request)
            await st.end()
            return await asyncio.wait_for(st.__anext__(), timeout)
        except StopAsyncIteration:
            raise RpcError(f"{method}: stream closed without response")
        finally:
            st.dispose()

    async def close(self):
        if self.conn is not None:
            try:
                self.conn.writer.close()
            except (OSError, RuntimeError):
                pass  # already closed
            self.conn = None
        if self._pump_task:
            self._pump_task.cancel()


def make_server_tls(cert_pem: bytes, key_pem: bytes, ca_pem: bytes | None = None):
    """Server-side mTLS context (client certs required when ca given)."""
    import tempfile

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
         tempfile.NamedTemporaryFile(suffix=".pem") as kf:
        cf.write(cert_pem); cf.flush()
        kf.write(key_pem); kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)
    if ca_pem:
        ctx.load_verify_locations(cadata=ca_pem.decode())
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def make_client_tls(ca_pem: bytes, cert_pem: bytes | None = None,
                    key_pem: bytes | None = None):
    import tempfile

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.load_verify_locations(cadata=ca_pem.decode())
    if cert_pem and key_pem:
        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
             tempfile.NamedTemporaryFile(suffix=".pem") as kf:
            cf.write(cert_pem); cf.flush()
            kf.write(key_pem); kf.flush()
            ctx.load_cert_chain(cf.name, kf.name)
    return ctx


class TlsProfile:
    """One node's TLS material: its certificate/key plus the CA bundle
    it trusts (the comm.SecureOptions analog, internal/pkg/comm).  The
    assemblies pass this through so EVERY listener requires client
    certs and every outbound dial presents one — mutual TLS end to end.
    """

    def __init__(self, cert_pem: bytes, key_pem: bytes, ca_pem: bytes):
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.ca_pem = ca_pem
        self._server = None
        self._client = None

    @classmethod
    def load(cls, cert_path: str, key_path: str, ca_path: str) -> "TlsProfile":
        with open(cert_path, "rb") as f:
            cert = f.read()
        with open(key_path, "rb") as f:
            key = f.read()
        with open(ca_path, "rb") as f:
            ca = f.read()
        return cls(cert, key, ca)

    def server_ctx(self) -> ssl.SSLContext:
        if self._server is None:
            self._server = make_server_tls(
                self.cert_pem, self.key_pem, self.ca_pem
            )
        return self._server

    def client_ctx(self) -> ssl.SSLContext:
        if self._client is None:
            self._client = make_client_tls(
                self.ca_pem, self.cert_pem, self.key_pem
            )
        return self._client

from fabric_tpu.comm.rpc import (  # noqa: F401
    RpcClient,
    RpcError,
    RpcServer,
    make_client_tls,
    make_server_tls,
)

"""Weighted deficit round-robin admission for the validation sidecar.

One device fabric serving N channels × M peers needs an explicit
answer to two questions the in-process validator never faced: *who
goes next* when several tenants have batches waiting, and *what
happens* when one tenant outruns the fabric.  This module answers
both with the classic DRR discipline (Shreedhar & Varghese), costed
in SIGNATURES rather than requests — a 3000-signature block must not
count the same as a 30-signature one:

* every tenant registers with a ``weight``; each scheduling round
  credits its deficit counter ``weight × quantum`` and drains whole
  requests while the deficit covers their cost, so long-run served
  signature shares converge to the weight ratio whenever tenants have
  backlog (the fairness half);
* every tenant's admission queue is bounded (``queue_limit``
  requests): ``submit`` returns False when full and the server turns
  that into a typed BUSY frame — backpressure is explicit and
  per-tenant, one storming channel can neither wedge the dispatcher
  nor grow server memory without bound (the backpressure half).

The structure is plain locked data — no asyncio — so the server's
event loop drives it and tests drive it deterministically.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from fabric_tpu.utils.stats import nearest_rank

#: default deficit credit per unit weight per round — roughly one
#: 1000-tx block's 2-of-3 signature batch, so a weight-1 tenant moves
#: a whole typical block per round instead of head-blocking on it
DEFAULT_QUANTUM = 4096


@dataclass
class Request:
    """One queued signature batch (the scheduler only reads ``cost``;
    everything else rides through untouched for the server)."""

    tenant: str
    seq: int
    items: list
    stream: object = None
    root: object = None          # tracer span root (server-side)
    trace: dict | None = None    # propagated peer trace context
    t_enqueue: float = 0.0
    cost: int = field(default=0)

    def __post_init__(self):
        if not self.cost:
            self.cost = max(1, len(self.items))


class _Tenant:
    __slots__ = ("name", "weight", "queue", "deficit", "served_cost",
                 "enqueued", "rejected", "shed_count", "refs", "ages")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = float(weight)
        self.queue: deque = deque()
        self.deficit = 0.0
        self.served_cost = 0
        self.enqueued = 0
        self.rejected = 0
        self.shed_count = 0  # arrivals turned away by shed mode
        self.refs = 1  # connections sharing this tenant entry
        # trailing queue ages (seconds spent waiting before dispatch):
        # stats() turns these into the p50/p99 the bench tracks
        self.ages: deque = deque(maxlen=256)


# the ONE percentile convention every autopilot-read stats surface
# shares (utils/stats.py) — kept under the historical local name
_pct = nearest_rank


class WeightedScheduler:
    """See module docstring.  Thread-safe; every public method takes
    the one lock briefly (queue moves and counter bumps only — never
    the device work)."""

    def __init__(self, queue_limit: int = 8, quantum: int = DEFAULT_QUANTUM,
                 registry=None, clock=time.perf_counter):
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        if quantum < 1:
            # quantum 0 would credit nothing per visit and spin
            # next_batch forever inside the lock
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        self.queue_limit = int(queue_limit)
        self.quantum = int(quantum)
        # queue ages subtract Request.t_enqueue stamps, so the clock
        # must be the SAME one the server stamps with (the tracer's —
        # injectable for skew tests)
        self.clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._order: list[str] = []   # registration order = DRR rotation
        self._rr = 0
        self._carry: str | None = None  # tenant parked mid-credit
        # tenants in SHED mode (the traffic autopilot's bounded
        # load-shedding actuator): their arrivals are answered BUSY +
        # retry-after at admission — keyed by NAME, independent of
        # registration, so a shed survives the tenant's reconnect
        self._shed: set[str] = set()
        # served/enqueued/rejected totals of fully-disconnected tenants:
        # restored on re-register (share continuity across reconnects)
        # and merged into stats() so the fairness picture survives the
        # stream teardown that reads it (bench, /healthz)
        self._retired: dict[str, dict] = {}
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._depth_gauge = registry.gauge(
            "sidecar_queue_depth",
            "requests waiting in a tenant's sidecar admission queue",
        )
        self._share_gauge = registry.gauge(
            "sidecar_tenant_share",
            "tenant's fraction of signatures served by the sidecar",
        )
        self._age_hist = registry.histogram(
            "sidecar_queue_age_seconds",
            "time a request waited in its tenant's admission queue "
            "before the DRR drain picked it",
        )
        self._deficit_gauge = registry.gauge(
            "sidecar_tenant_deficit",
            "tenant's current deficit credit (signatures) in the "
            "weighted-deficit-round-robin rotation",
        )
        self._busy_ctr = registry.counter(
            "sidecar_busy_total",
            "requests rejected at a full tenant admission queue "
            "(answered with a typed BUSY frame)",
        )
        self._shed_ctr = registry.counter(
            "sidecar_shed_total",
            "requests turned away by autopilot shed mode (answered "
            "with a typed BUSY frame + retry-after)",
        )

    # -- tenant lifecycle --------------------------------------------------

    def register(self, name: str, weight: float = 1.0) -> None:
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                # a second peer on the same channel shares the tenant
                # entry; the freshest weight wins (config rotation)
                t.refs += 1
                t.weight = float(weight)
                return
            t = _Tenant(name, weight)
            old = self._retired.pop(name, None)
            if old is not None:
                t.served_cost = old["served_cost"]
                t.enqueued = old["enqueued"]
                t.rejected = old["rejected"]
                t.shed_count = old.get("shed_count", 0)
                t.ages.extend(old.get("_ages", ()))
            self._tenants[name] = t
            self._order.append(name)

    def set_weight(self, name: str, weight: float) -> bool:
        """Update a LIVE registration's weight in place — deficit
        credit and trailing stats (ages, served totals) are preserved,
        so a re-hello with a changed weight (or an autopilot re-weight)
        never costs the tenant its scheduling position the way a
        disconnect/re-register would.  False when the tenant is not
        currently registered (a retired entry's weight is updated for
        its next registration)."""
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                old = self._retired.get(name)
                if old is not None:
                    old["weight"] = float(weight)
                return False
            t.weight = float(weight)
            return True

    def weight(self, name: str) -> float | None:
        with self._lock:
            t = self._tenants.get(name)
            return t.weight if t is not None else None

    # -- shed mode (the autopilot's bounded load-shedding actuator) --------

    def set_shed(self, name: str, shed: bool) -> None:
        """Enter/leave shed mode for one tenant: while shed, every
        arrival is turned away at admission (``submit`` returns False
        and the server answers a typed BUSY + retry-after).  Queued
        requests are NOT dropped — shedding bounds NEW work; what was
        admitted still completes, so the shed set is exactly the
        arrivals counted on ``sidecar_shed_total``."""
        with self._lock:
            if shed:
                self._shed.add(name)
            else:
                self._shed.discard(name)

    def is_shed(self, name: str) -> bool:
        with self._lock:
            return name in self._shed

    def unregister(self, name: str) -> list:
        """Drop one connection's claim; when the last goes, the tenant
        leaves the rotation and its queued requests come back (the
        server fails them — their reply stream is gone)."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                return []
            t.refs -= 1
            if t.refs > 0:
                return []
            del self._tenants[name]
            self._order.remove(name)
            self._rr %= max(1, len(self._order))
            if self._carry == name:
                self._carry = None
            self._retired[name] = {
                "weight": t.weight,
                "served_cost": t.served_cost,
                "enqueued": t.enqueued,
                "rejected": t.rejected,
                "shed_count": t.shed_count,
                "_ages": list(t.ages),
            }
            orphans = list(t.queue)
            t.queue.clear()
        self._depth_gauge.set(0, tenant=name)
        return orphans

    # -- admission ---------------------------------------------------------

    def submit(self, req: Request) -> bool:
        """Admit one request to its tenant's bounded queue; False =
        queue full OR the tenant is in shed mode (the caller answers
        BUSY; ``is_shed`` distinguishes the two for retry-after)."""
        shed = False
        with self._lock:
            t = self._tenants.get(req.tenant)
            if t is None:
                raise KeyError(f"tenant {req.tenant!r} is not registered")
            if req.tenant in self._shed:
                t.rejected += 1
                t.shed_count += 1
                shed = True
                depth = None
            elif len(t.queue) >= self.queue_limit:
                t.rejected += 1
                depth = None
            else:
                if not req.t_enqueue:
                    req.t_enqueue = self.clock()
                t.queue.append(req)
                t.enqueued += 1
                depth = len(t.queue)
        # metric bumps outside the scheduler lock (lock discipline:
        # never nest the registry lock under it)
        if depth is None:
            self._busy_ctr.add(1, tenant=req.tenant)
            if shed:
                self._shed_ctr.add(1, tenant=req.tenant)
            return False
        self._depth_gauge.set(depth, tenant=req.tenant)
        return True

    # -- the DRR drain -----------------------------------------------------

    def next_batch(self, max_requests: int) -> list:
        """Pop up to ``max_requests`` requests across tenants by
        weighted deficit round-robin — the batch the server coalesces
        into ONE padded device dispatch.  Empty only when nothing is
        queued (a head request costlier than one round's credit just
        takes extra rounds, it is never starved)."""
        out: list = []
        touched: set = set()
        now = self.clock()
        with self._lock:
            # incremental DRR: the rotation cursor walks tenant by
            # tenant, each BACKLOGGED visit credits weight×quantum and
            # drains whole requests while the deficit covers them.  A
            # batch that fills while the tenant still holds credit
            # PARKS the cursor there (``_carry`` — the next call
            # resumes without re-crediting), so weighted shares hold
            # across calls even at coalesce=1 instead of degrading to
            # unweighted round-robin.
            while len(out) < max_requests:
                order = self._order
                n = len(order)
                if n == 0:
                    break
                t = None
                for k in range(n):
                    idx = (self._rr + k) % n
                    cand = self._tenants[order[idx]]
                    if cand.queue:
                        t = cand
                        self._rr = idx
                        break
                if t is None:
                    break  # nothing queued anywhere
                if self._carry == t.name:
                    self._carry = None  # resume: credit already given
                else:
                    t.deficit += t.weight * self.quantum
                while (t.queue and len(out) < max_requests
                       and t.deficit >= t.queue[0].cost):
                    req = t.queue.popleft()
                    t.deficit -= req.cost
                    t.served_cost += req.cost
                    if req.t_enqueue:
                        t.ages.append(max(0.0, now - req.t_enqueue))
                    out.append(req)
                    touched.add(t.name)
                if not t.queue:
                    # an emptied tenant banks no credit (classic DRR:
                    # deficit persists across rounds only while
                    # backlogged)
                    t.deficit = 0.0
                    self._rr = (self._rr + 1) % n
                elif t.deficit < t.queue[0].cost:
                    # this round's credit is spent: next tenant.  (A
                    # head costlier than one round's credit just takes
                    # extra visits — deficit strictly grows, so it is
                    # reached in bounded rounds, never starved.)
                    self._rr = (self._rr + 1) % n
                else:
                    # batch full mid-credit: park here for the next call
                    self._carry = t.name
            total = sum(t.served_cost for t in self._tenants.values())
            shares = {
                name: (self._tenants[name].served_cost / total
                       if total else 0.0)
                for name in touched
            }
            depths = {name: len(self._tenants[name].queue)
                      for name in touched}
            deficits = {name: self._tenants[name].deficit
                        for name in touched}
        for name in touched:
            self._depth_gauge.set(depths[name], tenant=name)
            self._share_gauge.set(round(shares[name], 4), tenant=name)
            self._deficit_gauge.set(round(deficits[name], 1), tenant=name)
        for req in out:
            if req.t_enqueue:
                self._age_hist.observe(max(0.0, now - req.t_enqueue),
                                       tenant=req.tenant)
        return out

    # -- introspection -----------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return sum(len(t.queue) for t in self._tenants.values())

    def depth(self, name: str) -> int:
        with self._lock:
            t = self._tenants.get(name)
            return len(t.queue) if t else 0

    def stats(self) -> dict:
        """{tenant: {weight, depth, served_cost, share, enqueued,
        rejected, busy_rate, deficit, queue_age_ms}} — bench extras
        and /healthz read this.  Retired (fully-disconnected) tenants
        keep their totals at depth 0, so the fairness picture survives
        the stream teardown.  ``queue_age_ms`` carries the trailing
        p50/p99 time-in-queue; ``busy_rate`` is the fraction of
        arrivals pushed back BUSY."""
        with self._lock:
            rows = {}
            ages = {}
            for name, t in self._tenants.items():
                rows[name] = {
                    "weight": t.weight,
                    "depth": len(t.queue),
                    "served_cost": t.served_cost,
                    "enqueued": t.enqueued,
                    "rejected": t.rejected,
                    "shed_count": t.shed_count,
                    "shed": name in self._shed,
                    "deficit": round(t.deficit, 1),
                }
                ages[name] = list(t.ages)
            for name, old in self._retired.items():
                if name not in rows:
                    row = {k: v for k, v in old.items()
                           if not k.startswith("_")}
                    rows[name] = {"depth": 0, "deficit": 0.0,
                                  "shed_count": 0,
                                  "shed": name in self._shed, **row}
                    ages[name] = list(old.get("_ages", ()))
            total = sum(r["served_cost"] for r in rows.values())
        for name, r in rows.items():
            r["share"] = (
                round(r["served_cost"] / total, 4) if total else 0.0
            )
            arrivals = r["enqueued"] + r["rejected"]
            r["busy_rate"] = (
                round(r["rejected"] / arrivals, 4) if arrivals else 0.0
            )
            a = sorted(ages.get(name, ()))
            r["queue_age_ms"] = {
                "p50": round(_pct(a, 50) * 1000.0, 3),
                "p99": round(_pct(a, 99) * 1000.0, 3),
                "n": len(a),
            }
        return dict(sorted(rows.items()))

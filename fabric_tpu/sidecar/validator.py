"""``SidecarValidator``: the ``BlockValidator`` whose device lane
lives in a remote validation sidecar.

Drop-in for ``BlockValidator`` on the verify surface —
``preprocess`` / ``preprocess_many`` / ``validate_launch`` /
``validate_finish`` are inherited untouched, so ``PeerChannel`` and
``CommitPipeline`` need NO pipeline changes.  Only the two dispatch
hooks are overridden: instead of launching the local device kernel,
the block's signature batch ships over the tenant's
:class:`~fabric_tpu.sidecar.client.SidecarLink` and ``preprocess``
returns a handle whose verdicts arrive over the stream.  The handle
exposes no ``device_out``, so sidecar-validated blocks take the host
MVCC path — verdict-identical to the fused stage-2
(the ``_HostVerifyHandle`` equivalence tests/test_faults.py pins).

Failure semantics reuse ``peer/degrade.py`` wholesale: the sidecar
lane runs under a :class:`DeviceLaneGuard` (aliased onto
``self.device_guard`` so ``/healthz`` and ``validator_degraded``
surface it), so sidecar loss latches the CPU/local fallback after
``sidecar_fail_threshold`` consecutive failures and the periodic
recovery probe re-attaches the stream when the sidecar returns — a
sidecar restart degrades latency, never liveness.
"""

from __future__ import annotations

from fabric_tpu.peer.degrade import DeviceLaneGuard
from fabric_tpu.peer.validator import (
    BlockValidator,
    _GuardedHandle,
    _HostVerifyHandle,
)
from fabric_tpu.sidecar.client import (  # noqa: F401  (re-export)
    SidecarLink,
    parse_endpoint,
)


class SidecarValidator(BlockValidator):
    """See module docstring.  Extra knobs over ``BlockValidator``:

    * ``sidecar_endpoint`` — 'host:port' of the validation sidecar;
    * ``sidecar_weight`` — this tenant's fair-share weight;
    * ``sidecar_fail_threshold`` / ``sidecar_retries`` /
      ``sidecar_recovery_s`` — the degrade latch (same semantics as
      the ``device_*`` knobs, applied to the remote lane; threshold
      is forced ≥ 1 because a sidecar client without a fallback latch
      would turn every sidecar restart into a dead channel);
    * ``sidecar_timeout_s`` — per-batch response deadline;
    * ``sidecar_ssl`` — client TLS context (mTLS when the peer has
      node TLS material);
    * ``link`` — an injected :class:`SidecarLink` (tests).

    ``mesh_devices`` is forced to 0 (the SERVER owns the device fabric
    and its sharding knobs — a tenant must not grab the accelerator a
    co-located sidecar serves from); the host-staging knobs keep their
    meaning, since parse/policy staging stays on the peer."""

    def __init__(self, msp_manager, policy_provider, state_db,
                 sidecar_endpoint: str = "", sidecar_weight: float = 1.0,
                 sidecar_fail_threshold: int = 2, sidecar_retries: int = 0,
                 sidecar_recovery_s: float = 5.0,
                 sidecar_timeout_s: float = 30.0, sidecar_ssl=None,
                 link: SidecarLink | None = None, **kw):
        # the LOCAL device guard stays off: the sidecar guard below is
        # the one latch, and double-wrapping would double-count
        kw["device_fail_threshold"] = 0
        # never resolve a local device mesh: both dispatch hooks are
        # overridden, so a tenant peer grabbing the accelerator its
        # co-located sidecar owns would be pure contention
        kw["mesh_devices"] = 0
        kw["mesh_topology"] = None
        super().__init__(msp_manager, policy_provider, state_db, **kw)
        if link is None:
            host, port = parse_endpoint(sidecar_endpoint)
            link = SidecarLink(
                host, port, tenant=self.channel or "chan",
                weight=sidecar_weight, ssl_ctx=sidecar_ssl,
                timeout_s=sidecar_timeout_s,
            )
        self.link = link
        self.sidecar_guard = DeviceLaneGuard(
            retries=sidecar_retries,
            fail_threshold=max(1, int(sidecar_fail_threshold)),
            recovery_s=sidecar_recovery_s,
            # verify_deadline_ms keeps its meaning on the remote lane:
            # a sidecar that answers successfully but consistently
            # slower than the deadline counts toward the latch
            deadline_ms=float(kw.get("verify_deadline_ms", 0.0)),
            channel=self.channel,
        )
        # /healthz's device_verify_lane check and the bench's degraded
        # accounting read this attribute
        self.device_guard = self.sidecar_guard

    @staticmethod
    def _tuples(items) -> list:
        return items.tuples() if hasattr(items, "tuples") else list(items)

    def _verify_launch_guarded(self, items):
        tuples = self._tuples(items)
        out = self.sidecar_guard.run_launch(
            lambda: self.link.submit(tuples),
            lambda: self._host_verify_handle(items),
        )
        if isinstance(out, _HostVerifyHandle):
            return out
        return _GuardedHandle(out, self.sidecar_guard, self, items)

    def _verify_launch_many_guarded(self, itemsets, pool=None):
        tuple_sets = [self._tuples(it) for it in itemsets]
        out = self.sidecar_guard.run_launch(
            lambda: self.link.submit_many(tuple_sets),
            lambda: [self._host_verify_handle(it) for it in itemsets],
            fallback_count=len(itemsets),
        )
        return [
            h if isinstance(h, _HostVerifyHandle)
            else _GuardedHandle(h, self.sidecar_guard, self, it)
            for h, it in zip(out, itemsets)
        ]

    def close(self) -> None:
        super().close()
        self.link.close()

"""Wire format of the validation sidecar's ``validate`` stream.

The paper's north-star deployment ships *signature batches* to the
device fabric ("a new BCCSP-style provider shipping signature batches
over gRPC") — so the unit on the wire is one block's signature batch:
a list of ``(e, r, s, qx, qy)`` integer tuples (digest, DER-split
signature halves, public-key affine coordinates — exactly what
``ops/p256.verify_host`` consumes), and the reply is that batch's
boolean verdict vector.  Parse, policy evaluation and MVCC stay on
the peer, which owns the state they read.

Frames ride ``comm.rpc`` MSG payloads:

    hello    := JSON {"tenant": str, "weight": float}
    welcome  := JSON {"ok": true, "coalesce": int}
    request  := u32 hdr_len | JSON {"seq": int, "n": int
                [, "trace": {"block", "root", "tenant"}]} | items
    response := u32 hdr_len | JSON {"seq": int [, "status", "error",
                "retry_ms"] [, "remote": {"spans", "t_rx", "t_tx"}]}
                | verdict bytes (one 0/1 byte per item)

The optional ``trace`` request field propagates the peer's trace
context (its block number, root span id and tenant) so the sidecar
roots its queue_wait/dispatch spans under it; the optional ``remote``
response field ships the finished remote subtree back — ``spans`` is
the ``Span.to_dict(0.0)`` tree with ABSOLUTE times on the sidecar's
clock, and ``t_rx``/``t_tx`` (request receive / response send, same
clock) let the client estimate the clock offset NTP-style from the
request/response timestamp midpoints and stitch the subtree onto its
own timeline.

``items`` packs each tuple as five 32-byte big-endian integers — the
natural width of P-256 scalars/field elements.  A component that does
not fit (a malformed DER signature can carry an arbitrary-precision
integer) is replaced by the all-zero item, which every verifier
rejects (r = 0 is never a valid ECDSA signature), so an unpackable
lane degrades to "invalid", never to a protocol error.

A response with ``status == "BUSY"`` is the sidecar's typed
backpressure signal: the tenant's admission queue is full, retry after
backoff.  ``status == "ERROR"`` means the dispatch itself failed —
the client re-verifies that batch locally.
"""

from __future__ import annotations

import json
import struct

INT_BYTES = 32
ITEM_BYTES = 5 * INT_BYTES
_LEN = struct.Struct(">I")

#: the item every unpackable tuple degrades to — rejected by every
#: verifier (r = 0), so wire-layer sanitation can only turn a lane
#: invalid, never valid
INVALID_ITEM = (0, 0, 0, 0, 0)

_MAX = 1 << (8 * INT_BYTES)


def pack_items(tuples) -> bytes:
    """[(e, r, s, qx, qy)] → packed item bytes (see module docstring)."""
    out = bytearray()
    for item in tuples:
        vals = tuple(int(v) for v in item)
        if len(vals) != 5 or any(v < 0 or v >= _MAX for v in vals):
            vals = INVALID_ITEM
        for v in vals:
            out += v.to_bytes(INT_BYTES, "big")
    return bytes(out)


def unpack_items(buf: bytes) -> list:
    if len(buf) % ITEM_BYTES:
        raise ValueError(
            f"packed item buffer of {len(buf)} bytes is not a multiple "
            f"of {ITEM_BYTES}"
        )
    out = []
    for off in range(0, len(buf), ITEM_BYTES):
        out.append(tuple(
            int.from_bytes(buf[off + i * INT_BYTES:off + (i + 1) * INT_BYTES],
                           "big")
            for i in range(5)
        ))
    return out


def _frame(hdr: dict, body: bytes = b"") -> bytes:
    raw = json.dumps(hdr).encode()
    return _LEN.pack(len(raw)) + raw + body


def _unframe(payload: bytes) -> tuple[dict, bytes]:
    (n,) = _LEN.unpack_from(payload)
    hdr = json.loads(payload[_LEN.size:_LEN.size + n])
    return hdr, payload[_LEN.size + n:]


def encode_request(seq: int, tuples, trace: dict | None = None) -> bytes:
    hdr = {"seq": int(seq), "n": len(tuples)}
    if trace:
        hdr["trace"] = trace
    return _frame(hdr, pack_items(tuples))


def decode_request(payload: bytes) -> tuple[dict, list]:
    hdr, body = _unframe(payload)
    items = unpack_items(body)
    if len(items) != int(hdr.get("n", len(items))):
        raise ValueError(
            f"request {hdr.get('seq')}: header says {hdr.get('n')} items, "
            f"payload carries {len(items)}"
        )
    return hdr, items


def encode_response(seq: int, verdicts, remote: dict | None = None) -> bytes:
    hdr = {"seq": int(seq)}
    if remote:
        hdr["remote"] = remote
    return _frame(hdr, bytes(1 if v else 0 for v in verdicts))


def encode_busy(seq: int, retry_ms: float) -> bytes:
    return _frame({"seq": int(seq), "status": "BUSY",
                   "retry_ms": round(float(retry_ms), 3)})


def encode_error(seq: int, msg: str) -> bytes:
    return _frame({"seq": int(seq), "status": "ERROR", "error": msg[:500]})


def decode_response(payload: bytes) -> tuple[dict, list]:
    """→ (header, verdicts); verdicts empty for BUSY/ERROR headers."""
    hdr, body = _unframe(payload)
    return hdr, [bool(b) for b in body]

"""The validation sidecar service: one device fabric, many peers.

PAPER.md's north-star deployment shape — the TPU commit path behind a
pluggable-validation boundary, "a new BCCSP-style provider shipping
signature batches over gRPC" — realized over the repo's framed-RPC
transport (``comm.rpc``, the gRPC analog, mTLS included).  Before
this module every ``PeerChannel`` owned its own validator device
lane, so N channels × M peers meant N×M lanes contending for one
chip; the sidecar inverts that: ONE process owns the mesh-resolved
device machinery and serves ``validate`` bidi-streams to any number
of peer processes.

Flow per connection:

* the client's first frame registers a **tenant** (channel id +
  weight); the server answers a welcome frame;
* every subsequent frame is one block's signature batch
  (``sidecar/wire.py``), admitted to the tenant's BOUNDED queue in
  the weighted-deficit-round-robin scheduler
  (``sidecar/scheduler.py``) — a full queue answers a typed BUSY
  frame, never a dropped request or an unbounded buffer;
* a single dispatcher task drains cross-tenant batches of up to
  ``coalesce`` requests and launches them as ONE padded device
  dispatch through ``ops.p256.verify_launch_many`` — the first time
  the coalescing path merges genuinely concurrent traffic — then
  streams each batch's verdict vector back on its tenant's stream.

A dispatch failure answers each affected request with a typed ERROR
frame (the peer re-verifies those blocks locally and latches its
degrade machinery); it never tears the stream down.  ``verify_fn``
is injectable so crypto-free tests and toy fabrics reuse the whole
service unchanged.

Observability: ``sidecar_queue_depth{tenant}`` /
``sidecar_tenant_share{tenant}`` / ``sidecar_tenant_deficit{tenant}``
gauges and ``sidecar_queue_age_seconds{tenant}`` /
``sidecar_busy_total{tenant}`` (scheduler),
``sidecar_request_seconds{tenant,stage}`` histograms (queue_wait /
dispatch / total), ``sidecar_requests_total{tenant,status}``,
``sidecar_coalesce_occupancy{unit}``, tracer span trees per request
(queue_wait + dispatch children) in the ``sidecar`` flight-recorder
NAMESPACE — their own ring, so request numbering never collides with
peer block numbers in a colocated process
(``/trace?ns=sidecar&block=N``) — and ``health_check`` for
``/healthz``.  When a request carries a ``trace`` context
(``wire.py``), the finished subtree ships back in the response
header and the client stitches it under the peer's block root with
clock-offset alignment — one block's waterfall spans both processes.

Chaos hooks: ``sidecar.request`` fires at admission,
``sidecar.dispatch`` inside the coalesced device dispatch, and every
frame send passes ``rpc.frame`` (comm.rpc) — a seeded FaultPlan can
cut, delay or fail the link end to end.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor

from fabric_tpu import faults as _faults
from fabric_tpu.comm.rpc import RpcServer
from fabric_tpu.sidecar import wire
from fabric_tpu.sidecar.scheduler import Request, WeightedScheduler

_log = logging.getLogger("fabric_tpu.sidecar")

#: suggested client backoff base when BUSY (advisory; the client's
#: utils.backoff.Backoff owns the actual cadence)
BUSY_RETRY_MS = 20.0

#: suggested retry-after while a tenant is in autopilot SHED mode —
#: much longer than a transient queue-full: the controller is telling
#: this tenant to back off until its burn clears
SHED_RETRY_MS = 250.0


class SidecarServer:
    """See module docstring.

    ``verify_fn(itemsets) -> list[list[bool]]`` runs on the device
    executor thread; the default routes through the mesh-resolved
    ``ops.p256`` production dispatch (``mesh_devices`` /
    ``verify_chunk`` / ``recode_device`` mean exactly what they mean
    on ``BlockValidator``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 mesh_devices: int = 0, verify_chunk: int = 0,
                 recode_device: bool = False, queue_blocks: int = 8,
                 coalesce: int = 4, quantum: int | None = None,
                 ssl_ctx=None, verify_fn=None, registry=None,
                 tracer=None, autopilot=None, mesh_topology=None):
        self.host, self.port = host, port
        self.mesh_devices = int(mesh_devices)
        # declarative mesh topology (parallel.topology.MeshTopology):
        # when configured it wins over the bare mesh_devices count and
        # may span jax.distributed processes
        self.mesh_topology = mesh_topology
        self.verify_chunk = int(verify_chunk)
        self.recode_device = bool(recode_device)
        self.coalesce = max(1, int(coalesce))
        self.mesh = None
        self._verify_fn = verify_fn
        # optional traffic autopilot (fabric_tpu/control): hellos
        # report tenant weights so the controller knows each tenant's
        # declared restore target for its re-weight rule
        self.autopilot = autopilot
        self._rpc = RpcServer(host, port, ssl_ctx=ssl_ctx)
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        self.tracer = tracer
        kw = {} if quantum is None else {"quantum": int(quantum)}
        self.scheduler = WeightedScheduler(
            queue_limit=queue_blocks, registry=registry,
            clock=tracer.clock, **kw
        )
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._req_hist = registry.histogram(
            "sidecar_request_seconds",
            "per-request sidecar time (s) by tenant and stage",
        )
        self._req_ctr = registry.counter(
            "sidecar_requests_total",
            "sidecar validate requests by tenant and outcome",
        )
        self._tenants_gauge = registry.gauge(
            "sidecar_tenants", "tenant connections currently attached"
        )
        self._coalesce_hist = registry.histogram(
            "sidecar_coalesce_occupancy",
            "cross-tenant batches merged per device dispatch "
            "(unit=requests) and their total cost (unit=signatures)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 256, 1024, 4096,
                     float("inf")),
        )
        # ONE device lane: the chip serializes dispatches anyway, and a
        # single executor thread keeps verify_launch_many calls ordered
        self._device = ThreadPoolExecutor(
            1, thread_name_prefix="fabtpu-sidecar-dev"
        )
        self._work = asyncio.Event()
        self._dispatcher: asyncio.Task | None = None
        self._conns = 0
        self._req_counter = 0  # tracer "block" numbers for requests
        self._stopped = False
        # runtime re-knobbing (the sidecar-local autopilot's
        # actuators): latched here, applied at the next
        # dispatcher-drain boundary — a coalesced group is always
        # built AND dispatched under one knob vector.  The latch is
        # LOCKED: a bare read-then-clear would drop a set_* landing
        # from the controller thread between the dispatcher's read
        # and its None store, leaving the controller's knob state and
        # the live dispatch permanently disagreeing.
        self._knob_lock = threading.Lock()
        self._pending_coalesce: int | None = None
        self._pending_verify_chunk: int | None = None

    # -- runtime re-knobbing (autopilot actuators) -------------------------

    def set_coalesce(self, n: int) -> None:
        """Request a new cross-tenant coalescing cap, applied at the
        next dispatcher-drain boundary (before the next
        ``next_batch`` pop — never between a batch's pop and its
        dispatch).  Values < 1 clamp to 1 (a dispatch always carries
        at least one request)."""
        with self._knob_lock:
            self._pending_coalesce = max(1, int(n))

    def set_verify_chunk(self, n: int) -> None:
        """Request a new device microbatch chunk for the sidecar's OWN
        dispatch, applied at the same drain boundary.  0 =
        monolithic."""
        with self._knob_lock:
            self._pending_verify_chunk = max(0, int(n))

    def _apply_pending_knobs(self) -> None:
        with self._knob_lock:
            c, self._pending_coalesce = self._pending_coalesce, None
            v, self._pending_verify_chunk = (
                self._pending_verify_chunk, None,
            )
        if c is not None and c != self.coalesce:
            _log.info("sidecar coalesce re-knobbed %d -> %d",
                      self.coalesce, c)
            self.coalesce = c
        if v is not None and v != self.verify_chunk:
            _log.info("sidecar verify_chunk re-knobbed %d -> %d",
                      self.verify_chunk, v)
            self.verify_chunk = v

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "SidecarServer":
        if self._verify_fn is None:
            topo = self.mesh_topology
            if topo is not None and topo.configured:
                self.mesh = topo.resolve()
            elif self.mesh_devices:
                from fabric_tpu.parallel.mesh import resolve_mesh

                self.mesh = resolve_mesh(self.mesh_devices)
        self._rpc.register("validate", self._on_validate)
        await self._rpc.start()
        self.port = self._rpc.port
        self._stopped = False
        # strong ref + cancelled on stop (FT008 discipline)
        self._dispatcher = asyncio.ensure_future(self._dispatch_loop())
        _log.info("validation sidecar serving on %s:%d (coalesce=%d, "
                  "queue_blocks=%d)", self.host, self.port,
                  self.coalesce, self.scheduler.queue_limit)
        return self

    async def stop(self) -> None:
        self._stopped = True
        if self._dispatcher is not None:
            self._dispatcher.cancel()
            await asyncio.gather(self._dispatcher, return_exceptions=True)
            self._dispatcher = None
        await self._rpc.stop()
        self._device.shutdown(wait=False)

    def health_check(self):
        """/healthz checker: None while serving, a reason otherwise.
        ANY tenant pinned at its queue bound is reported — that tenant
        is riding BUSY→CPU-fallback right now, and one idle neighbor
        must not mask a wedged fabric."""
        if self._stopped or self._rpc._server is None:
            return "sidecar rpc server down"
        limit = self.scheduler.queue_limit
        pinned = [
            name for name, s in self.scheduler.stats().items()
            if s["depth"] >= limit
        ]
        if pinned:
            return (
                f"tenant queue(s) full ({', '.join(pinned)}) — device "
                "fabric saturated or wedged; affected tenants are "
                "being pushed back (BUSY)"
            )
        return None

    # -- the validate stream ----------------------------------------------

    async def _on_validate(self, stream) -> None:
        try:
            hello_raw = await stream.__anext__()
        except StopAsyncIteration:
            return  # opened and closed without a hello
        try:
            hello = json.loads(hello_raw)
            tenant = str(hello["tenant"])
            weight = float(hello.get("weight", 1.0))
        except (ValueError, KeyError, TypeError) as e:
            await stream.error(f"bad hello: {e}")
            return
        try:
            self.scheduler.register(tenant, weight)  # raises on w <= 0
        except ValueError as e:
            await stream.error(f"bad hello: {e}")
            return
        if self.autopilot is not None:
            self.autopilot.observe_hello(tenant, weight)
        self._conns += 1
        self._tenants_gauge.set(self._conns)
        # everything past registration runs under the unregister
        # finally — a welcome send that dies (client gone, injected
        # rpc.frame fault) must not leak the tenant ref
        try:
            await stream.send(json.dumps(
                {"ok": True, "tenant": tenant, "coalesce": self.coalesce}
            ).encode())
            async for payload in stream:
                if _faults.plan() is not None:
                    await _faults.afire("sidecar.request", tenant=tenant)
                if payload[:1] == b"{":
                    # in-stream RE-HELLO (request frames always lead
                    # with a u32 header length, whose first byte is 0
                    # for any sane header — a raw JSON object cannot
                    # collide): a weight change updates the live
                    # registration in place, deficit and trailing
                    # stats preserved, no disconnect required
                    err = self._re_hello(tenant, payload)
                    if err is not None:
                        await stream.error(err)
                        return
                    await stream.send(json.dumps(
                        {"ok": True, "tenant": tenant,
                         "weight": self.scheduler.weight(tenant),
                         "rehello": True}
                    ).encode())
                    continue
                try:
                    hdr, items = wire.decode_request(payload)
                except (ValueError, KeyError) as e:
                    await stream.error(f"bad request: {e}")
                    return
                seq = int(hdr["seq"])
                trace = hdr.get("trace")
                extra = {}
                if isinstance(trace, dict):
                    # propagated peer trace context: root this
                    # request's queue_wait/dispatch story under it so
                    # the finished subtree ships back stitchable
                    extra = {
                        "peer_block": trace.get("block"),
                        "peer_root": trace.get("root"),
                    }
                # ns="sidecar": request trees live in their own
                # flight-recorder ring, so a colocated deployment's
                # request numbering can neither evict real block trees
                # nor collide with them at /trace?block=N
                root = self.tracer.begin_block(
                    self._next_req_id(), ns="sidecar",
                    channel=f"sidecar:{tenant}", seq=seq, **extra,
                )
                req = Request(tenant=tenant, seq=seq, items=items,
                              stream=stream, root=root,
                              trace=trace if isinstance(trace, dict)
                              else None,
                              t_enqueue=self.tracer.clock())
                if not self.scheduler.submit(req):
                    shed = self.scheduler.is_shed(tenant)
                    self._req_ctr.add(
                        1, tenant=tenant,
                        status="shed" if shed else "busy",
                    )
                    self.tracer.set_attrs(root, busy=True,
                                          **({"shed": True} if shed
                                             else {}))
                    self.tracer.finish_block(root)
                    # shed mode's retry-after is deliberately long —
                    # the autopilot is telling this tenant to back off
                    # until its burn clears, not to hammer a full queue
                    await stream.send(wire.encode_busy(
                        seq, SHED_RETRY_MS if shed else BUSY_RETRY_MS
                    ))
                    continue
                self._work.set()
        finally:
            self._conns -= 1
            self._tenants_gauge.set(self._conns)
            orphans = self.scheduler.unregister(tenant)
            for req in orphans:
                # their reply stream is gone; account them so a storm
                # of disappearing tenants is visible
                self._req_ctr.add(1, tenant=req.tenant, status="dropped")
                self.tracer.finish_block(req.root)

    def _re_hello(self, tenant: str, payload: bytes) -> str | None:
        """In-stream weight update; → error text or None on success.
        The tenant name must match the stream's registration — one
        connection cannot re-weight another tenant."""
        try:
            hello = json.loads(payload)
            who = str(hello["tenant"])
            weight = float(hello.get("weight", 1.0))
        except (ValueError, KeyError, TypeError) as e:
            return f"bad re-hello: {e}"
        if who != tenant:
            return (
                f"bad re-hello: stream is registered as {tenant!r}, "
                f"not {who!r}"
            )
        try:
            self.scheduler.set_weight(tenant, weight)
        except ValueError as e:
            return f"bad re-hello: {e}"
        if self.autopilot is not None:
            self.autopilot.observe_hello(tenant, weight)
        return None

    def _next_req_id(self) -> int:
        self._req_counter += 1
        return self._req_counter

    # -- the dispatcher ----------------------------------------------------

    async def _dispatch_loop(self) -> None:
        loop = asyncio.get_event_loop()
        while True:
            await self._work.wait()
            self._work.clear()
            while True:
                # drain boundary: adopt any latched knob values before
                # the next batch is built (set_coalesce /
                # set_verify_chunk — the sidecar-local autopilot's
                # actuation point)
                self._apply_pending_knobs()
                batch = self.scheduler.next_batch(self.coalesce)
                if not batch:
                    break
                self._coalesce_hist.observe(len(batch), unit="requests")
                self._coalesce_hist.observe(
                    sum(r.cost for r in batch), unit="signatures"
                )
                t0 = self.tracer.clock()
                try:
                    verdicts = await loop.run_in_executor(
                        self._device, self._dispatch_traced,
                        [r.items for r in batch], batch[0].root,
                    )
                    t1 = self.tracer.clock()
                    await self._answer(batch, verdicts, t0, t1)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # a dispatch failure answers typed errors (clients
                    # re-verify locally); anything unexpected escaping
                    # the ANSWER path must not kill this task either —
                    # a dead dispatcher would silently halt every
                    # tenant until process restart
                    _log.warning(
                        "sidecar dispatch of %d batch(es) failed: %s — "
                        "answering typed errors (clients re-verify "
                        "locally)", len(batch), e,
                    )
                    try:
                        await self._answer_error(batch, e)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e2:
                        _log.warning(
                            "sidecar error-answer path failed too (%s) "
                            "— dropping %d response(s); affected "
                            "clients time out and fall back locally",
                            e2, len(batch),
                        )
                        for req in batch:
                            self._req_ctr.add(1, tenant=req.tenant,
                                              status="dropped")
                            self.tracer.finish_block(req.root)

    def _dispatch_traced(self, itemsets: list, root) -> list:
        """Executor-thread shim: adopt the coalesced group's LEADER
        request tree as the thread-current span for the device verify,
        so the launch ledger's ``dev:*`` child spans (and its
        histogram exemplars) attach to the request the dispatch was
        built for — the sidecar's /trace?ns=sidecar waterfall then
        carries the device lane too."""
        tok = self.tracer.attach(root) if root is not None else None
        try:
            return self._verify_batch(itemsets)
        finally:
            if root is not None:
                self.tracer.detach(tok)

    def _verify_batch(self, itemsets: list) -> list:
        _faults.fire("sidecar.dispatch", n=len(itemsets))
        if self._verify_fn is not None:
            return self._verify_fn(itemsets)
        return self._device_verify(itemsets)

    def _device_verify(self, itemsets: list) -> list:
        """The production path: ONE coalesced padded dispatch over the
        mesh for the whole cross-tenant group, then per-batch fetches."""
        from fabric_tpu.ops import p256

        handles = p256.verify_launch_many(
            itemsets, chunk=self.verify_chunk or None, mesh=self.mesh,
            recode_device=self.recode_device,
        )
        return [[bool(v) for v in h()] for h in handles]

    async def _answer(self, batch: list, verdicts: list,
                      t0: float, t1: float) -> None:
        for req, ok in zip(batch, verdicts):
            self._req_hist.observe(t0 - req.t_enqueue, tenant=req.tenant,
                                  stage="queue_wait")
            self._req_hist.observe(t1 - t0, tenant=req.tenant,
                                  stage="dispatch")
            self._req_hist.observe(t1 - req.t_enqueue, tenant=req.tenant,
                                  stage="total")
            self.tracer.add("queue_wait", req.t_enqueue, t0,
                            parent=req.root)
            self.tracer.add("dispatch", t0, t1, parent=req.root,
                            coalesced=len(batch), n_sigs=req.cost)
            sent = await self._send(
                req, wire.encode_response(req.seq, ok,
                                          remote=self._remote(req))
            )
            self._req_ctr.add(1, tenant=req.tenant,
                              status="ok" if sent else "dropped")
            self.tracer.finish_block(req.root)

    def _remote(self, req: Request) -> dict | None:
        """The finished request subtree + send/receive timestamps the
        client stitches from — only built when the request carried a
        trace context (the peer asked) and tracing is on here."""
        if req.trace is None or req.root is None:
            return None
        # close the root NOW so the shipped tree has a complete
        # window; finish_block tolerates a pre-set t1 (ring append
        # and watchdog run there as usual)
        self.tracer.end(req.root)
        return {
            "spans": req.root.to_dict(0.0),
            "t_rx": round(req.t_enqueue * 1000.0, 3),
            "t_tx": round(self.tracer.clock() * 1000.0, 3),
        }

    async def _answer_error(self, batch: list, err: Exception) -> None:
        msg = f"{type(err).__name__}: {err}"
        for req in batch:
            await self._send(req, wire.encode_error(req.seq, msg))
            self._req_ctr.add(1, tenant=req.tenant, status="error")
            self.tracer.set_attrs(req.root, error=msg[:120])
            self.tracer.finish_block(req.root)

    @staticmethod
    async def _send(req: Request, payload: bytes) -> bool:
        try:
            await req.stream.send(payload)
            return True
        except (ConnectionError, OSError, RuntimeError, EOFError) as e:
            _log.debug("tenant %s went away before its response (%s)",
                       req.tenant, e)
            return False

"""fabric_tpu.sidecar — the multi-tenant validation sidecar: one
device fabric serving many peer processes over ``comm.rpc``, with
weighted-deficit-round-robin fairness and typed backpressure.

Crypto-free surface (server, scheduler, client link, wire codec)
imports eagerly; :class:`SidecarValidator` lives in
``sidecar.validator`` and is imported lazily because it subclasses
the real ``BlockValidator`` (which needs the ``cryptography``
package).
"""

from fabric_tpu.sidecar.client import (  # noqa: F401
    RemoteVerifyHandle,
    SidecarLink,
    SidecarUnavailable,
)
from fabric_tpu.sidecar.scheduler import (  # noqa: F401
    Request,
    WeightedScheduler,
)
from fabric_tpu.sidecar.server import SidecarServer  # noqa: F401

"""Client side of the validation sidecar: the link a peer's validator
rides.

``SidecarLink`` owns ONE connection to a sidecar server per tenant
(channel): a daemon thread runs a private asyncio loop hosting the
``comm.rpc`` client, the ``validate`` bidi stream, and a reader task
that correlates responses to in-flight requests by sequence number.
The validator-facing surface is synchronous and thread-safe —
``submit(tuples)`` returns a :class:`RemoteVerifyHandle` immediately
(the async-dispatch shape ``BlockValidator`` already expects from a
device launch) and the verdicts materialize at ``fetch()``.

Contract with the degrade machinery (``peer/degrade.py``):

* a BUSY frame (the server's typed backpressure) is retried
  transparently with capped-exponential backoff
  (``utils.backoff.Backoff``) up to ``busy_retries`` times — sustained
  saturation then surfaces as :class:`SidecarUnavailable`;
* connection loss, a typed ERROR frame, or a response timeout raise
  :class:`SidecarUnavailable` from ``fetch()`` — the caller's
  ``DeviceLaneGuard`` counts it toward the degraded latch and routes
  the block through the local CPU fallback;
* every ``submit`` while detached attempts a fresh connect, so the
  guard's periodic recovery probe IS the re-attach path: when the
  sidecar comes back, one probe block reconnects and re-arms the lane.

The module is crypto-free and JAX-free on purpose: toy validators in
tests and the real ``SidecarValidator`` share it unchanged.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading

from fabric_tpu.comm.rpc import RpcClient, RpcError
from fabric_tpu.sidecar import wire
from fabric_tpu.utils.backoff import Backoff

_log = logging.getLogger("fabric_tpu.sidecar.client")

#: seconds granted to connect + hello before a submit gives up
CONNECT_TIMEOUT_S = 5.0


class SidecarUnavailable(RuntimeError):
    """The sidecar could not serve this batch (down, saturated past
    the busy-retry budget, or errored) — verify locally."""


def parse_endpoint(endpoint: str) -> tuple[str, int]:
    """'host:port' (or ':port' / 'port') → (host, port)."""
    host, _, port = str(endpoint).rpartition(":")
    if not port.isdigit():
        raise ValueError(
            f"sidecar endpoint {endpoint!r}: expected 'host:port'"
        )
    return host or "127.0.0.1", int(port)


class RemoteVerifyHandle:
    """One in-flight batch's verdict future, quacking like a device
    VerifyHandle: no ``device_out`` (sidecar blocks take the host MVCC
    path, verdict-identical), ``fetch()``/``__call__`` block until the
    response frame lands or raise :class:`SidecarUnavailable`."""

    __slots__ = ("_fut", "_timeout", "n_real")

    def __init__(self, fut, timeout_s: float, n_real: int = 0):
        self._fut = fut
        self._timeout = timeout_s
        self.n_real = n_real

    def fetch(self) -> list:
        try:
            return self._fut.result(timeout=self._timeout)
        except SidecarUnavailable:
            raise
        except Exception as e:  # timeout, cancelled, loop torn down
            raise SidecarUnavailable(f"sidecar fetch failed: {e}") from e

    def __call__(self) -> list:
        return self.fetch()


class SidecarLink:
    """See module docstring."""

    def __init__(self, host: str, port: int, tenant: str,
                 weight: float = 1.0, ssl_ctx=None,
                 timeout_s: float = 30.0, busy_retries: int = 6,
                 backoff: Backoff | None = None, registry=None,
                 tracer=None):
        self.host, self.port = host, int(port)
        self.tenant = tenant
        self.weight = float(weight)
        self.ssl_ctx = ssl_ctx
        self.timeout_s = float(timeout_s)
        self.busy_retries = int(busy_retries)
        self._backoff_proto = backoff
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        # trace stitching: submit() reads the CALLER thread's current
        # span off this tracer, ships its block context on the wire,
        # and hangs the sidecar's returned subtree under the block
        # root with NTP-style clock-offset alignment
        self.tracer = tracer
        self._client: RpcClient | None = None
        self._stream = None
        self._reader_task: asyncio.Task | None = None
        self._conn_lock: asyncio.Lock | None = None  # created on-loop
        self._pending: dict[int, asyncio.Future] = {}
        self._hello_ack: asyncio.Future | None = None
        self._seq = 0
        self._closed = False
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._busy_ctr = registry.counter(
            "sidecar_client_busy_total",
            "BUSY backpressure frames absorbed by client backoff",
        )
        self._reattach_ctr = registry.counter(
            "sidecar_client_attach_total",
            "sidecar stream (re)attachments by tenant",
        )
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name=f"fabtpu-sidecar-{tenant}",
            daemon=True,
        )
        self._thread.start()

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    # -- sync surface (validator threads) ----------------------------------

    @property
    def attached(self) -> bool:
        return self._stream is not None

    def submit(self, tuples) -> RemoteVerifyHandle:
        """Queue one signature batch; raises
        :class:`SidecarUnavailable` only when the link is closed —
        connect/transport errors surface at ``fetch()`` so the launch
        keeps its async-dispatch shape."""
        if self._closed or not self._thread.is_alive():
            raise SidecarUnavailable("sidecar link is closed")
        tuples = list(tuples)
        # capture the caller thread's trace context HERE — the async
        # internals run on the link loop thread, whose thread-local
        # current span is never ours
        cur = self.tracer.current()
        stitch_root = None
        trace = None
        if cur is not None:
            stitch_root = cur.root if cur.root is not None else cur
            trace = {
                "block": stitch_root.attrs.get("block"),
                "root": id(stitch_root) & 0xFFFFFFFF,
                "tenant": self.tenant,
            }
        fut = asyncio.run_coroutine_threadsafe(
            self._asubmit(tuples, trace, stitch_root), self._loop
        )
        # worst case: every attempt burns its own response timeout plus
        # the busy backoff between — bound the caller's wait to that
        bound = (self.busy_retries + 1) * self.timeout_s + 10.0
        return RemoteVerifyHandle(fut, bound, n_real=len(tuples))

    def submit_many(self, tuple_sets) -> list:
        """One handle per batch; the server's scheduler coalesces them
        (cross-tenant included) into shared device dispatches."""
        return [self.submit(t) for t in tuple_sets]

    def set_weight(self, weight: float, timeout_s: float = 5.0) -> bool:
        """Change this tenant's fair-share weight IN PLACE via an
        in-stream re-hello: the server updates the live registration
        (deficit credit and trailing stats preserved — no
        disconnect/re-register).  Returns True on a server ack; False
        when detached (the new weight still rides the next hello, so
        the change survives a reconnect either way)."""
        # GIL-atomic float publish read by the loop at the next
        # (re)hello; a one-frame-stale weight is the documented
        # semantics, not corruption
        self.weight = float(weight)  # fabtpu: noqa(FT017)
        if self._closed or self._stream is None:
            return False
        try:
            return bool(asyncio.run_coroutine_threadsafe(
                self._arehello(self.weight), self._loop
            ).result(timeout_s))
        except Exception as e:
            _log.debug("re-hello for %s failed (%s) — weight rides "
                       "the next hello", self.tenant, e)
            return False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._thread.is_alive():
            asyncio.run_coroutine_threadsafe(
                self._aclose(), self._loop
            ).result(timeout=5.0)
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)

    # -- async internals (link loop only) ----------------------------------

    async def _asubmit(self, tuples: list, trace: dict | None = None,
                       stitch_root=None) -> list:
        bo = self._backoff_proto or Backoff(base=0.02, cap=0.5, jitter=0.5)
        busy = 0
        while True:
            st = await self._ensure_attached()
            self._seq += 1
            seq = self._seq
            fut = self._loop.create_future()
            self._pending[seq] = fut
            try:
                t_send = self.tracer.clock()
                await st.send(wire.encode_request(seq, tuples,
                                                  trace=trace))
                resp = await asyncio.wait_for(fut, self.timeout_s)
                t_recv = self.tracer.clock()
            except (RpcError, ConnectionError, OSError,
                    asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as e:
                # drop OUR future before detaching: _detach fails every
                # remaining pending future, and failing this one (whose
                # error is about to be raised here) would leave an
                # unretrieved exception for the loop to log
                self._pending.pop(seq, None)
                self._detach()
                raise SidecarUnavailable(
                    f"sidecar {self.host}:{self.port}: {e}"
                ) from e
            finally:
                self._pending.pop(seq, None)
            hdr, verdicts = resp
            status = hdr.get("status")
            if status == "BUSY":
                busy += 1
                self._busy_ctr.add(1, tenant=self.tenant)
                if busy > self.busy_retries:
                    raise SidecarUnavailable(
                        f"sidecar still BUSY after {busy} attempts — "
                        "tenant queue saturated"
                    )
                await asyncio.sleep(bo.next())
                continue
            if status is not None:  # typed ERROR: dispatch failed
                raise SidecarUnavailable(
                    f"sidecar dispatch error: {hdr.get('error', status)}"
                )
            if len(verdicts) != len(tuples):
                # the sidecar is a remote trust boundary: a short (or
                # long) verdict vector must trigger the local
                # re-verify, not index past the end in validate_finish
                raise SidecarUnavailable(
                    f"sidecar answered {len(verdicts)} verdicts for a "
                    f"{len(tuples)}-signature batch"
                )
            if stitch_root is not None:
                self._stitch(stitch_root, hdr.get("remote"),
                             t_send, t_recv)
            return verdicts

    def _stitch(self, root, remote, t_send: float, t_recv: float) -> None:
        """Hang the sidecar's finished request subtree under the
        peer's block root, aligned onto the local timeline.

        The offset estimate is NTP's: the server's receive/send
        timestamps should straddle the same midpoint as our
        send/receive pair, so offset = ((t_rx−t_send)+(t_tx−t_recv))/2
        (server clock − local clock).  The residual error is bounded
        by half the round-trip asymmetry — recorded on the stitched
        root (``clock_offset_ms``/``rtt_ms``) so readers know the
        alignment tolerance."""
        if not isinstance(remote, dict) or "spans" not in remote:
            return
        try:
            t_rx = float(remote["t_rx"]) / 1000.0
            t_tx = float(remote["t_tx"]) / 1000.0
            offset = ((t_rx - t_send) + (t_tx - t_recv)) / 2.0
            from fabric_tpu.observe import span_from_dict

            sp = span_from_dict(remote["spans"], offset_s=offset,
                                proc="sidecar")
            sp.name = "sidecar_request"  # "block" would read wrong here
            # the sidecar's request id must not shadow the PEER block
            # number this subtree now belongs to
            if "block" in sp.attrs:
                sp.attrs["req"] = sp.attrs.pop("block")
            sp.attrs["clock_offset_ms"] = round(offset * 1000.0, 3)
            sp.attrs["rtt_ms"] = round(
                max(0.0, (t_recv - t_send) - (t_tx - t_rx)) * 1000.0, 3
            )
            sp.root = root
            root.children.append(sp)  # GIL-atomic; root may be live
        except (TypeError, ValueError, KeyError, AttributeError) as e:
            # the remote payload is trust-boundary metadata: a
            # malformed tree (non-dict spans/children from a skewed
            # sidecar) must never fail the verify path or feed the
            # caller's degrade latch — verdicts already validated
            _log.debug("sidecar trace stitch failed: %s", e)

    async def _ensure_attached(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._stream is not None:
                return self._stream
            cli = RpcClient(self.host, self.port, ssl_ctx=self.ssl_ctx)
            try:
                await asyncio.wait_for(cli.connect(), CONNECT_TIMEOUT_S)
                st = await cli.open_stream("validate")
                await st.send(json.dumps(
                    {"tenant": self.tenant, "weight": self.weight}
                ).encode())
                welcome = json.loads(await asyncio.wait_for(
                    st.__anext__(), CONNECT_TIMEOUT_S
                ))
            except (RpcError, ConnectionError, OSError,
                    asyncio.TimeoutError, StopAsyncIteration,
                    asyncio.IncompleteReadError, ValueError) as e:
                await self._close_client(cli)
                raise SidecarUnavailable(
                    f"sidecar {self.host}:{self.port} unreachable: {e}"
                ) from e
            if not welcome.get("ok"):
                await self._close_client(cli)
                raise SidecarUnavailable(f"sidecar refused hello: {welcome}")
            self._client, self._stream = cli, st
            # strong ref; detached (and awaited) on connection loss
            self._reader_task = asyncio.ensure_future(self._reader(st))
            self._reattach_ctr.add(1, tenant=self.tenant)
            _log.info("tenant %s attached to sidecar %s:%d",
                      self.tenant, self.host, self.port)
            return st

    async def _arehello(self, weight: float) -> bool:
        st = self._stream
        if st is None:
            return False
        ack = self._loop.create_future()
        self._hello_ack = ack
        try:
            await st.send(json.dumps(
                {"tenant": self.tenant, "weight": weight}
            ).encode())
            got = await asyncio.wait_for(ack, CONNECT_TIMEOUT_S)
            return bool(got.get("ok"))
        finally:
            self._hello_ack = None

    async def _reader(self, st) -> None:
        try:
            async for payload in st:
                if payload[:1] == b"{":
                    # re-hello ack (request frames lead with a u32
                    # header length whose first byte is 0 — see
                    # wire.py; a raw JSON object cannot collide)
                    ack = self._hello_ack
                    if ack is not None and not ack.done():
                        try:
                            ack.set_result(json.loads(payload))
                        except ValueError:
                            ack.set_result({})
                    continue
                hdr, verdicts = wire.decode_response(payload)
                fut = self._pending.pop(int(hdr.get("seq", -1)), None)
                if fut is not None and not fut.done():
                    fut.set_result((hdr, verdicts))
        except (RpcError, ConnectionError, OSError,
                asyncio.IncompleteReadError) as e:
            _log.debug("sidecar reader for %s ended: %s", self.tenant, e)
        finally:
            if self._stream is st:
                self._detach()

    def _detach(self) -> None:
        """Drop the dead connection and fail everything in flight —
        callers fall back locally and the NEXT submit reconnects."""
        cli, self._client = self._client, None
        # GIL-atomic pointer clear; the sync surface's only unlocked
        # access is the `attached` liveness peek, where a one-frame
        # stale answer is indistinguishable from losing the
        # connection a microsecond later
        self._stream = None  # fabtpu: noqa(FT017)
        task, self._reader_task = self._reader_task, None
        if task is not None and not task.done():
            task.cancel()
        pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    SidecarUnavailable("sidecar connection lost")
                )
        ack, self._hello_ack = self._hello_ack, None
        if ack is not None and not ack.done():
            ack.set_exception(
                SidecarUnavailable("sidecar connection lost")
            )
        if cli is not None:
            t = asyncio.ensure_future(self._close_client(cli))
            t.add_done_callback(lambda _t: None)  # close is best-effort

    @staticmethod
    async def _close_client(cli) -> None:
        try:
            await cli.close()
        except (OSError, RuntimeError):
            pass  # transport already gone

    async def _aclose(self) -> None:
        self._detach()

"""Batched ECDSA P-256 verification as a JAX/XLA TPU kernel.

This is the hot loop of the reference's block-commit path: every
endorsement on every transaction is an ECDSA-P256 signature verified on
the host CPU one at a time (reference: msp/identities.go:170-199 →
bccsp.Verify; low-S rule in bccsp/sw/ecdsa.go:41-58; ~2-3 verifies per
tx at a 2-of-3 policy, validator fan-out in
core/committer/txvalidator/v20/validator.go:193-208).  Here the whole
block's signatures are verified in ONE batched TPU dispatch.

TPU-first design (not a port — the reference has no batch crypto):

* 256-bit field elements are 16 little-endian limbs of 16 bits held in
  uint32 lanes, so a limb product fits exactly in a uint32 and the MXU/
  VPU never needs 64-bit integers (TPUs have none).
* Modular multiplication is Montgomery CIOS with 16-bit words: the
  schoolbook product accumulates split lo/hi halves into 33 uint32
  columns (≤2^22 per column — no overflow), then 16 sequential REDC
  steps.  One code path serves both moduli (field prime p, group
  order n).
* Point arithmetic is Jacobian with *complete* branchless formulas:
  every add also computes the doubling and the identity cases and
  selects — no data-dependent control flow, so XLA sees one straight-
  line loop body.
* u1·G + u2·Q uses Shamir's trick: one shared double-and-add ladder
  over the joint bits, table {∞, G, Q, G+Q}.
* The final affine check avoids a per-lane inversion: accept iff
  X ≡ r·Z² or X ≡ (r+n)·Z² (mod p), the standard trick.
* The batch dimension maps onto VPU lanes; everything is elementwise
  over [B, 16] arrays inside a single `lax.fori_loop` — static shapes,
  compiled once per batch bucket.

Inputs are raw integers as limb arrays; digests come from
`fabric_tpu.ops.sha256` (device) or the host.  Bit-exact against
`fabric_tpu.crypto.ec_ref` (pure-Python oracle) incl. the low-S rule.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import ec_ref
from fabric_tpu.utils.batching import next_pow2

LIMBS = 16
LIMB_BITS = 16
MASK = jnp.uint32(0xFFFF)

P = ec_ref.P
N = ec_ref.N
B_COEF = ec_ref.B
GX, GY = ec_ref.GX, ec_ref.GY
HALF_N = ec_ref.HALF_N


# ---------------------------------------------------------------------------
# Host-side limb conversion helpers


def int_to_limbs(x: int) -> np.ndarray:
    """256-bit int → [16] uint32 little-endian 16-bit limbs."""
    return np.array([(x >> (16 * i)) & 0xFFFF for i in range(LIMBS)], dtype=np.uint32)


def ints_to_limbs(xs) -> np.ndarray:
    """[B] ints → [B, 16] uint32 limbs."""
    return np.stack([int_to_limbs(int(x)) for x in xs]) if len(xs) else np.zeros((0, LIMBS), np.uint32)


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(a[i]) << (16 * i) for i in range(LIMBS))


def limbs_to_ints(arr) -> list[int]:
    return [limbs_to_int(row) for row in np.asarray(arr)]


class _Mod:
    """Host-precomputed Montgomery constants for one modulus."""

    def __init__(self, m: int):
        self.m = m
        self.limbs = int_to_limbs(m)
        self.n0 = np.uint32((-pow(m, -1, 1 << LIMB_BITS)) & 0xFFFF)
        self.r2 = int_to_limbs((1 << 512) % m)  # R^2 mod m, R = 2^256
        self.one_mont = int_to_limbs((1 << 256) % m)
        self.one = int_to_limbs(1)

    def to_mont_int(self, x: int) -> int:
        return (x << 256) % self.m


MODP = _Mod(P)
MODN = _Mod(N)


# ---------------------------------------------------------------------------
# Limb arithmetic (all device fns operate on uint32 [..., 16], limbs < 2^16)


def _add_raw(a, b):
    """(a + b) over 16 limbs → (sum [...,16], carry [...])."""
    outs = []
    carry = jnp.zeros(a.shape[:-1], jnp.uint32)
    for i in range(LIMBS):
        t = a[..., i] + b[..., i] + carry
        outs.append(t & MASK)
        carry = t >> 16
    return jnp.stack(outs, axis=-1), carry


def _sub_raw(a, b):
    """(a - b) mod 2^256 over 16 limbs → (diff, borrow [...] ∈ {0,1})."""
    outs = []
    borrow = jnp.zeros(a.shape[:-1], jnp.uint32)
    for i in range(LIMBS):
        t = a[..., i] + jnp.uint32(1 << 16) - b[..., i] - borrow
        outs.append(t & MASK)
        borrow = jnp.uint32(1) - (t >> 16)
    return jnp.stack(outs, axis=-1), borrow


def _lt(a, b):
    """a < b as bool [...]."""
    _, borrow = _sub_raw(a, b)
    return borrow == 1


def _is_zero(a):
    return jnp.all(a == 0, axis=-1)


def _eq(a, b):
    return jnp.all(a == b, axis=-1)


def _select(cond, a, b):
    """where over limb arrays; cond is [...]."""
    return jnp.where(cond[..., None], a, b)


def _add_mod(a, b, mod: _Mod):
    s, carry = _add_raw(a, b)
    ml = jnp.asarray(mod.limbs)
    d, br = _sub_raw(s, ml)
    use_d = (carry == 1) | (br == 0)
    return _select(use_d, d, s)


def _sub_mod(a, b, mod: _Mod):
    d, br = _sub_raw(a, b)
    ml = jnp.asarray(mod.limbs)
    d2, _ = _add_raw(d, ml)
    return _select(br == 1, d2, d)


def _mont_mul(a, b, mod: _Mod):
    """Montgomery product a*b*R^-1 mod m (R = 2^256).  CIOS, 16-bit words.

    Inputs/outputs fully reduced (< m), limbs < 2^16.
    """
    nl = jnp.asarray(mod.limbs)
    n0 = mod.n0
    shape = a.shape[:-1]

    # Schoolbook product into 33 columns of uint32 (each ≤ 32·(2^16-1) < 2^22).
    cols = jnp.zeros(shape + (2 * LIMBS + 1,), jnp.uint32)
    for i in range(LIMBS):
        prod = a[..., i : i + 1] * b  # full 32-bit products
        lo = prod & MASK
        hi = prod >> 16
        cols = cols.at[..., i : i + LIMBS].add(lo)
        cols = cols.at[..., i + 1 : i + LIMBS + 1].add(hi)

    # 16 REDC steps; column i is annihilated at step i.
    carry = jnp.zeros(shape, jnp.uint32)
    for i in range(LIMBS):
        t = cols[..., i] + carry
        m = (t * n0) & MASK
        prod = m[..., None] * nl
        lo = prod & MASK
        hi = prod >> 16
        cols = cols.at[..., i + 1 : i + LIMBS + 1].add(hi)
        # After adding lo[0], column i ≡ 0 (mod 2^16) by choice of m.
        carry = (t + lo[..., 0]) >> 16
        cols = cols.at[..., i + 1 : i + LIMBS].add(lo[..., 1:])

    # Propagate carries over the result columns 16..32 (17 limbs, < 2m).
    outs = []
    for i in range(LIMBS, 2 * LIMBS + 1):
        t = cols[..., i] + carry
        outs.append(t & MASK)
        carry = t >> 16
    res17 = jnp.stack(outs, axis=-1)  # top limb ∈ {0,1}, carry now 0

    # Conditional subtract m (result < 2m).
    ml17 = jnp.concatenate([jnp.asarray(mod.limbs), jnp.zeros((1,), jnp.uint32)])
    d = []
    borrow = jnp.zeros(shape, jnp.uint32)
    for i in range(LIMBS + 1):
        t = res17[..., i] + jnp.uint32(1 << 16) - ml17[i] - borrow
        d.append(t & MASK)
        borrow = jnp.uint32(1) - (t >> 16)
    d17 = jnp.stack(d, axis=-1)
    use_d = borrow == 0
    out = _select(use_d, d17, res17)
    return out[..., :LIMBS]


def _to_mont(a, mod: _Mod):
    return _mont_mul(a, jnp.asarray(mod.r2), mod)


def _from_mont(a, mod: _Mod):
    return _mont_mul(a, jnp.asarray(mod.one), mod)


def _mont_pow_const(base, exponent: int, mod: _Mod):
    """base^exponent (Montgomery domain) for a compile-time exponent."""
    bits = np.array([(exponent >> (255 - k)) & 1 for k in range(256)], np.uint32)
    bits_dev = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(mod.one_mont), base.shape)

    def body(k, acc):
        acc = _mont_mul(acc, acc, mod)
        acc2 = _mont_mul(acc, base, mod)
        return _select(bits_dev[k] == 1, acc2, acc)

    return jax.lax.fori_loop(0, 256, body, one)


# ---------------------------------------------------------------------------
# Jacobian point arithmetic mod p (Montgomery domain; Z == 0 encodes ∞)


def _pt_double(X, Y, Z):
    """dbl-2001-b for a = -3.  3M + 5S + adds.  ∞ stays ∞ (Z3 = 0)."""
    mp = MODP
    delta = _mont_mul(Z, Z, mp)
    gamma = _mont_mul(Y, Y, mp)
    beta = _mont_mul(X, gamma, mp)
    t1 = _sub_mod(X, delta, mp)
    t2 = _add_mod(X, delta, mp)
    t3 = _add_mod(t2, _add_mod(t2, t2, mp), mp)  # 3*(X+delta)
    alpha = _mont_mul(t1, t3, mp)
    beta4 = _add_mod(_add_mod(beta, beta, mp), _add_mod(beta, beta, mp), mp)
    X3 = _sub_mod(_mont_mul(alpha, alpha, mp), _add_mod(beta4, beta4, mp), mp)
    yz = _add_mod(Y, Z, mp)
    Z3 = _sub_mod(_sub_mod(_mont_mul(yz, yz, mp), gamma, mp), delta, mp)
    g2 = _mont_mul(gamma, gamma, mp)
    g8 = _add_mod(_add_mod(g2, g2, mp), _add_mod(g2, g2, mp), mp)
    g8 = _add_mod(g8, g8, mp)
    Y3 = _sub_mod(_mont_mul(alpha, _sub_mod(beta4, X3, mp), mp), g8, mp)
    return X3, Y3, Z3


def _pt_add(X1, Y1, Z1, X2, Y2, Z2):
    """Complete Jacobian + Jacobian addition via branchless selects.

    Handles P1 = ∞, P2 = ∞, P1 = P2 (doubling) and P1 = -P2 (→ ∞).
    """
    mp = MODP
    z1z = _mont_mul(Z1, Z1, mp)
    z2z = _mont_mul(Z2, Z2, mp)
    u1 = _mont_mul(X1, z2z, mp)
    u2 = _mont_mul(X2, z1z, mp)
    s1 = _mont_mul(_mont_mul(Y1, Z2, mp), z2z, mp)
    s2 = _mont_mul(_mont_mul(Y2, Z1, mp), z1z, mp)
    h = _sub_mod(u2, u1, mp)
    rr = _sub_mod(s2, s1, mp)
    hh = _mont_mul(h, h, mp)
    hhh = _mont_mul(h, hh, mp)
    v = _mont_mul(u1, hh, mp)
    x3 = _sub_mod(_sub_mod(_mont_mul(rr, rr, mp), hhh, mp), _add_mod(v, v, mp), mp)
    y3 = _sub_mod(
        _mont_mul(rr, _sub_mod(v, x3, mp), mp), _mont_mul(s1, hhh, mp), mp
    )
    z3 = _mont_mul(_mont_mul(Z1, Z2, mp), h, mp)

    p1_inf = _is_zero(Z1)
    p2_inf = _is_zero(Z2)
    same = _is_zero(h) & _is_zero(rr) & ~p1_inf & ~p2_inf
    dX, dY, dZ = _pt_double(X1, Y1, Z1)

    X3 = _select(same, dX, x3)
    Y3 = _select(same, dY, y3)
    Z3 = _select(same, dZ, z3)  # P1 = -P2 ⇒ h = 0, z3 = 0 ⇒ ∞ already
    X3 = _select(p2_inf, X1, _select(p1_inf, X2, X3))
    Y3 = _select(p2_inf, Y1, _select(p1_inf, Y2, Y3))
    Z3 = _select(p2_inf, Z1, _select(p1_inf, Z2, Z3))
    return X3, Y3, Z3


def _bit_of(a, j):
    """Bit j (traced index) of limb array a → uint32 [...] ∈ {0,1}."""
    limb = jax.lax.dynamic_index_in_dim(a, j // LIMB_BITS, axis=-1, keepdims=False)
    return (limb >> (j % LIMB_BITS).astype(jnp.uint32)) & jnp.uint32(1)


# ---------------------------------------------------------------------------
# The verify kernel


def verify_batch(e, r, s, qx, qy):
    """Batched ECDSA P-256 verify with the low-S rule.

    e, r, s, qx, qy: uint32 [B, 16] little-endian 16-bit limb arrays.
    e is the full 256-bit SHA-256 digest as an integer (reduced mod n
    here); (qx, qy) the endorser's public key (affine).

    Returns bool [B]: True iff the signature verifies AND s ≤ n/2 AND
    r, s ∈ [1, n-1] AND Q is a valid curve point — the exact accept set
    of the reference SW verifier (bccsp/sw/ecdsa.go:41-58).
    """
    mp, mn = MODP, MODN
    nl = jnp.asarray(mn.limbs)
    pl = jnp.asarray(mp.limbs)

    # --- scalar-range and low-S admission checks
    r_ok = ~_is_zero(r) & _lt(r, nl)
    s_ok = ~_is_zero(s) & _lt(s, nl)
    half_n = jnp.asarray(int_to_limbs(HALF_N))
    low_s = ~_lt(half_n, s)  # s <= n/2

    # --- public-key sanity: coordinates < p, on curve, not ∞
    q_range = _lt(qx, pl) & _lt(qy, pl) & ~(_is_zero(qx) & _is_zero(qy))
    qxm = _to_mont(qx, mp)
    qym = _to_mont(qy, mp)
    y2 = _mont_mul(qym, qym, mp)
    x2 = _mont_mul(qxm, qxm, mp)
    x3 = _mont_mul(x2, qxm, mp)
    three_x = _add_mod(qxm, _add_mod(qxm, qxm, mp), mp)
    b_mont = jnp.broadcast_to(jnp.asarray(int_to_limbs(mp.to_mont_int(B_COEF))), qx.shape)
    rhs = _add_mod(_sub_mod(x3, three_x, mp), b_mont, mp)
    on_curve = _eq(y2, rhs) & q_range

    # --- u1 = e·s⁻¹ mod n, u2 = r·s⁻¹ mod n
    e_red = _select(_lt(e, nl), e, _sub_raw(e, nl)[0])  # e < 2^256 < 2n
    sm = _to_mont(s, mn)
    w = _mont_pow_const(sm, N - 2, mn)  # to_mont(s⁻¹) (garbage if s=0: masked)
    u1 = _from_mont(_mont_mul(_to_mont(e_red, mn), w, mn), mn)
    u2 = _from_mont(_mont_mul(_to_mont(r, mn), w, mn), mn)

    # --- Shamir ladder over {∞, G, Q, G+Q}
    shape = e.shape
    gx_m = jnp.broadcast_to(jnp.asarray(int_to_limbs(mp.to_mont_int(GX))), shape)
    gy_m = jnp.broadcast_to(jnp.asarray(int_to_limbs(mp.to_mont_int(GY))), shape)
    one_m = jnp.broadcast_to(jnp.asarray(mp.one_mont), shape)
    zero = jnp.zeros(shape, jnp.uint32)
    gqX, gqY, gqZ = _pt_add(gx_m, gy_m, one_m, qxm, qym, one_m)

    def body(k, acc):
        X, Y, Z = acc
        X, Y, Z = _pt_double(X, Y, Z)
        j = jnp.int32(255 - k)
        b1 = _bit_of(u1, j)
        b2 = _bit_of(u2, j)
        idx = b1 + 2 * b2
        tX = _select(idx == 3, gqX, _select(idx == 2, qxm, gx_m))
        tY = _select(idx == 3, gqY, _select(idx == 2, qym, gy_m))
        tZ = _select(idx == 3, gqZ, one_m)
        tZ = _select(idx == 0, zero, tZ)
        return _pt_add(X, Y, Z, tX, tY, tZ)

    Xr, Yr, Zr = jax.lax.fori_loop(0, 256, body, (zero, zero, zero))

    # --- accept iff R ≠ ∞ and x(R) ≡ r (mod n):  X ≡ r·Z² or (r+n)·Z² mod p
    not_inf = ~_is_zero(Zr)
    z2 = _mont_mul(Zr, Zr, mp)
    rm = _to_mont(r, mp)
    cmp1 = _eq(Xr, _mont_mul(rm, z2, mp))
    rpn, carry = _add_raw(r, jnp.broadcast_to(nl, shape))
    rpn_lt_p = (carry == 0) & _lt(rpn, pl)
    rm2 = _to_mont(rpn, mp)
    cmp2 = _eq(Xr, _mont_mul(rm2, z2, mp)) & rpn_lt_p

    return r_ok & s_ok & low_s & on_curve & not_inf & (cmp1 | cmp2)


verify_batch_jit = jax.jit(verify_batch)


def digest_words_to_limbs(words):
    """SHA-256 digest words (ops.sha256 output, [B, 8] uint32
    big-endian) → [B, 16] little-endian 16-bit limbs, on device.

    Lets the fused block pipeline keep digests on the TPU between the
    hash and verify kernels (no host round-trip)."""
    w = words[..., ::-1]  # little-endian word order
    lo = w & MASK
    hi = w >> 16
    return jnp.stack([lo, hi], axis=-1).reshape(*words.shape[:-1], 16)


# ---------------------------------------------------------------------------
# Host convenience wrappers


MIN_BUCKET = 16

# kernel selection: v3 (RNS/Cox-Rower, ops.p256v3) is the default;
# FABRIC_TPU_P256=v2 selects the signed-digit MXU kernel (ops.p256v2),
# =v1 this module's Montgomery-limb ladder — kept for comparison
import os as _os

_KERNEL = _os.environ.get("FABRIC_TPU_P256", "v3")


def verify_host(items) -> list[bool]:
    """items: iterable of (digest_int, r, s, qx, qy) Python ints.

    Dispatches to the default kernel (v3 RNS/Cox-Rower) unless
    FABRIC_TPU_P256 selects a comparison kernel.  The v1 path pads the
    batch to a power of two, floored at MIN_BUCKET, and runs the
    jitted limb kernel.
    """
    if hasattr(items, "tuples"):  # SigCollector column form
        if _KERNEL in ("v1", "v2"):
            items = items.tuples()
        else:
            from fabric_tpu.ops import p256v3

            return p256v3.verify_launch(items)()
    items = list(items)
    if not items:
        return []
    if _KERNEL == "v2":
        from fabric_tpu.ops import p256v2

        return p256v2.verify_host(items)
    if _KERNEL != "v1":
        # v3 is the default; unknown values must not silently fall
        # back to the slow comparison ladder
        from fabric_tpu.ops import p256v3

        return p256v3.verify_host(items)
    return _verify_host_v1(items)


def verify_launch(items, chunk: int | None = None, mesh=None, pool=None,
                  recode_device: bool = False):
    """Async launch + fetch() (see p256v3.verify_launch); the v1/v2
    comparison kernels evaluate eagerly (no device handle — the fused
    device pipeline requires the v3 kernel, and the ``chunk`` /
    ``mesh`` / ``pool`` / ``recode_device`` knobs only apply there)."""
    if _KERNEL not in ("v1", "v2"):
        from fabric_tpu.ops import p256v3

        return p256v3.verify_launch(items, chunk=chunk, mesh=mesh,
                                    pool=pool,
                                    recode_device=recode_device)
    if hasattr(items, "tuples"):
        items = items.tuples()
    result = verify_host(items)
    return lambda: result


def verify_launch_many(batches, chunk: int | None = None, mesh=None,
                       pool=None, recode_device: bool = False):
    """Coalesced multi-block launch (see p256v3.verify_launch_many);
    v1/v2 comparison kernels degrade to independent eager launches."""
    if _KERNEL not in ("v1", "v2"):
        from fabric_tpu.ops import p256v3

        return p256v3.verify_launch_many(batches, chunk=chunk, mesh=mesh,
                                         pool=pool,
                                         recode_device=recode_device)
    return [verify_launch(b) for b in batches]


def _verify_host_v1(items) -> list[bool]:
    n = len(items)
    bsz = max(MIN_BUCKET, next_pow2(n))
    pad = [(0, 0, 0, 0, 0)] * (bsz - n)
    cols = list(zip(*(items + pad)))
    e, r, s, qx, qy = (jnp.asarray(ints_to_limbs(c)) for c in cols)
    # the v1 kernel's ONE intended readback: this helper IS the sync
    # point callers block on
    out = np.asarray(verify_batch_jit(e, r, s, qx, qy))  # fabtpu: noqa(FT003)
    return [bool(v) for v in out[:n]]

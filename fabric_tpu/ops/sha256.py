"""Batched SHA-256 as a JAX/XLA TPU kernel.

In the reference (Hyperledger Fabric) every signature verification first
hashes the signed payload with SHA-256 on the host CPU
(msp/identities.go:170-199 -> bccsp hash, bccsp/sw/ecdsa.go).  Here the
whole block's worth of payloads is hashed in one batched TPU dispatch:
the batch dimension maps onto VPU lanes, the 64 compression rounds are a
statically unrolled dataflow graph that XLA fuses into a handful of
kernels.

Layout: messages are pre-padded on the host (standard SHA-256 padding)
into ``[batch, max_blocks, 16]`` big-endian uint32 words plus a per-item
block count.  Multi-block messages iterate the compression function with
a mask so a single dispatch handles ragged lengths.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.utils.batching import next_pow2

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state, block):
    """One SHA-256 compression. state: [..., 8] u32, block: [..., 16] u32."""
    w = [block[..., t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)

    a, b, c, d, e, f, g, h = [state[..., i] for i in range(8)]
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + jnp.uint32(int(_K[t])) + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=-1)
    return state + out


def sha256_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Hash a batch of pre-padded messages.

    blocks: [B, M, 16] uint32 big-endian words (SHA-256 padded).
    nblocks: [B] int32, number of valid 64-byte blocks per message.
    Returns digests [B, 8] uint32.
    """
    B, M, _ = blocks.shape
    state = jnp.broadcast_to(jnp.asarray(_H0), (B, 8))

    def body(i, st):
        new = _compress(st, blocks[:, i, :])
        keep = (i < nblocks)[:, None]
        return jnp.where(keep, new, st)

    return jax.lax.fori_loop(0, M, body, state)


sha256_blocks_jit = jax.jit(sha256_blocks)


def pad_messages(msgs: list[bytes], max_blocks: int | None = None):
    """Host-side SHA-256 padding into the kernel layout.

    Returns (blocks [B, M, 16] uint32, nblocks [B] int32).
    """
    nb = [(len(m) + 8) // 64 + 1 for m in msgs]
    M = max_blocks if max_blocks is not None else (max(nb) if nb else 1)
    if M < 1:
        raise ValueError(f"max_blocks must be >= 1, got {M}")
    if max(nb, default=0) > M:
        raise ValueError(f"message needs {max(nb)} blocks > max_blocks={M}")
    B = len(msgs)
    out = np.zeros((B, M, 16), dtype=np.uint32)
    for i, m in enumerate(msgs):
        padded = m + b"\x80" + b"\x00" * ((55 - len(m)) % 64) + (8 * len(m)).to_bytes(8, "big")
        words = np.frombuffer(padded, dtype=">u4").reshape(-1, 16)
        out[i, : words.shape[0]] = words
    return out, np.asarray(nb, dtype=np.int32)


def digests_to_bytes(digests) -> list[bytes]:
    d = np.asarray(digests, dtype=np.uint32)
    return [d[i].astype(">u4").tobytes() for i in range(d.shape[0])]


def sha256_host(msgs: list[bytes], max_blocks: int | None = None) -> list[bytes]:
    """Convenience end-to-end: pad on host, hash on device, bytes out.

    Batch and block dims are bucketed to powers of two so the jitted
    kernel compiles once per bucket rather than once per distinct
    (tx count, payload length) combination on the block-commit path.
    """
    if not msgs:
        return []
    n = len(msgs)
    need = max((len(m) + 8) // 64 + 1 for m in msgs)
    M = next_pow2(max_blocks if max_blocks is not None else need)
    B = next_pow2(n)
    blocks, nb = pad_messages(msgs + [b""] * (B - n), M)
    out = digests_to_bytes(sha256_blocks_jit(jnp.asarray(blocks), jnp.asarray(nb)))
    return out[:n]

"""Batched ECDSA P-256 verification, MXU-first ("v2") kernel.

Replaces the depth-bound Montgomery ladder of fabric_tpu.ops.p256 (the
round-2 bench ran at 0.406× one CPU thread) with a design whose serial
depth is ~8× shorter and whose inner multiplications ride the MXU:

* Field arithmetic: signed-digit base-2^6 form (fabric_tpu.ops.digits)
  — poly-mul and modular reduction are f32 matmuls, carries are a
  short certified settle schedule, add/sub are carry-free.
* Point arithmetic: Renes–Costello–Batina 2016 COMPLETE projective
  formulas for a = -3 (add 12M+2mb, doubling 8M+3S+2mb).  Complete
  means NO exceptional cases — ∞ = (0:1:0), doubling, and inverses all
  flow through the same straight-line code, so the ladder needs no
  zero-tests or per-lane branches even for adversarial signatures
  (P-256 has prime order: the formulas are total).
* Scalar ladder: 4-bit windows, 64 iterations of [4 doublings + one
  table add for u2·Q + one mixed add for u1·G] instead of 256
  double-and-add rounds.  The u2·Q window table (15 multiples) is
  built in-kernel with the same complete adds; the u1·G table is a
  host-precomputed constant (G is fixed).
* Division s⁻¹ mod n: Fermat via a 256-round fori_loop (square +
  bit-masked multiply), on the whole batch at once.

Reference semantics matched exactly (bccsp/sw/ecdsa.go:41-58, the SW
BCCSP verifier): accept iff r,s ∈ [1, n-1], s ≤ n/2 (low-S), Q on
curve, R = u1·G + u2·Q ≠ ∞, and x(R) ≡ r (mod n).  Bit-exact against
the pure-Python oracle fabric_tpu.crypto.ec_ref (tests/test_p256v2.py).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import digits as dg
from fabric_tpu.utils.batching import next_pow2

K = dg.K
P = ec_ref.P
N = ec_ref.N
B_COEF = ec_ref.B
GX, GY = ec_ref.GX, ec_ref.GY
HALF_N = ec_ref.HALF_N

MODP = dg.DigitMod(P)
MODN = dg.DigitMod(N)

WINDOW = 4
STEPS = 64  # 256 bits / WINDOW

_F32_SUM_LIMIT = (1 << 24) // dg.K  # pairing bound: |a|*|b| must stay under


class FV:
    """Field value with a trace-time |digit| bound.

    The bound rides along symbolic tracing (plain Python ints), so
    pairing-limit violations are caught — and fixed by condensing the
    fatter operand — while BUILDING the graph, with zero runtime cost
    for the bookkeeping itself."""

    __slots__ = ("arr", "bound", "mod")

    def __init__(self, arr, bound: int, mod: dg.DigitMod):
        self.arr = arr
        self.bound = int(bound)
        self.mod = mod

    def __add__(self, other):
        return FV(self.arr + other.arr, self.bound + other.bound, self.mod)

    def __sub__(self, other):
        return FV(self.arr - other.arr, self.bound + other.bound, self.mod)

    def condensed(self) -> "FV":
        return FV(self.mod.settle(self.arr), _SETTLED[id(self.mod)], self.mod)

    def __mul__(self, other):
        a, b = self, other
        if a.bound * b.bound >= _F32_SUM_LIMIT:
            # condense the fatter side first (trace-time decision)
            if a.bound >= b.bound:
                a = a.condensed()
            else:
                b = b.condensed()
            if a.bound * b.bound >= _F32_SUM_LIMIT:
                a, b = a.condensed(), b.condensed()
        return FV(a.mod.mul(a.arr, b.arr), _SETTLED[id(a.mod)], a.mod)


# certify mul+settle at the largest legal pairing (624^2 * 43 < 2^24);
# FV.__mul__ never exceeds it, so these settled bounds hold everywhere
_MAX_SIDE = int((( 1 << 24) / dg.K) ** 0.5)  # 624
_SETTLED = {
    id(MODP): MODP.bound_check(a_bound=_MAX_SIDE, b_bound=_MAX_SIDE),
    id(MODN): MODN.bound_check(a_bound=_MAX_SIDE, b_bound=_MAX_SIDE),
}


def _const_fv(x: int, shape_like, mod: dg.DigitMod) -> FV:
    d = jnp.asarray(dg.int_to_digits(x))
    return FV(jnp.broadcast_to(d, shape_like.shape), 63, mod)


# ---------------------------------------------------------------------------
# RCB complete point ops (projective X:Y:Z, a = -3).  Every variable is
# an FV; bound tracking inserts settles exactly where the certified
# pairing limit requires.


def _pt(x, y, z):
    return (x, y, z)


def pt_add(p1, p2, b_fv):
    """Complete projective addition (RCB16 algorithm 4, a = -3)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    t0 = X1 * X2
    t1 = Y1 * Y2
    t2 = Z1 * Z2
    t3 = X1 + Y1
    t4 = X2 + Y2
    t3 = t3 * t4
    t4 = t0 + t1
    t3 = t3 - t4
    t4 = Y1 + Z1
    X3 = Y2 + Z2
    t4 = t4 * X3
    X3 = t1 + t2
    t4 = t4 - X3
    X3 = X1 + Z1
    Y3 = X2 + Z2
    X3 = X3 * Y3
    Y3 = t0 + t2
    Y3 = X3 - Y3
    Z3 = b_fv * t2
    X3 = Y3 - Z3
    Z3 = X3 + X3
    X3 = X3 + Z3
    Z3 = t1 - X3
    X3 = t1 + X3
    Y3 = b_fv * Y3
    t1 = t2 + t2
    t2 = t1 + t2
    Y3 = Y3 - t2
    Y3 = Y3 - t0
    t1 = Y3 + Y3
    Y3 = t1 + Y3
    t1 = t0 + t0
    t0 = t1 + t0
    t0 = t0 - t2
    t1 = t4 * Y3
    t2 = t0 * Y3
    Y3 = X3 * Z3
    Y3 = Y3 + t2
    X3 = t3 * X3
    X3 = X3 - t1
    Z3 = t4 * Z3
    t1 = t3 * t0
    Z3 = Z3 + t1
    return _pt(X3, Y3, Z3)


def pt_add_mixed(p1, x2, y2, b_fv):
    """Complete mixed addition (RCB16 algorithm 5, Z2 = 1): P2 is an
    affine point — complete in P1 (incl. ∞) but P2 must NOT be ∞; the
    comb handles digit-0 (∞) table slots with a select in the caller."""
    X1, Y1, Z1 = p1
    X2, Y2 = x2, y2
    t0 = X1 * X2
    t1 = Y1 * Y2
    t3 = X2 + Y2
    t4 = X1 + Y1
    t3 = t3 * t4
    t4 = t0 + t1
    t3 = t3 - t4
    t4 = Y2 * Z1
    t4 = t4 + Y1
    Y3 = X2 * Z1
    Y3 = Y3 + X1
    Z3 = b_fv * Z1
    X3 = Y3 - Z3
    Z3 = X3 + X3
    X3 = X3 + Z3
    Z3 = t1 - X3
    X3 = t1 + X3
    Y3 = b_fv * Y3
    t1 = Z1 + Z1
    t2 = t1 + Z1
    Y3 = Y3 - t2
    Y3 = Y3 - t0
    t1 = Y3 + Y3
    Y3 = t1 + Y3
    t1 = t0 + t0
    t0 = t1 + t0
    t0 = t0 - t2
    t1 = t4 * Y3
    t2 = t0 * Y3
    Y3 = X3 * Z3
    Y3 = Y3 + t2
    X3 = t3 * X3
    X3 = X3 - t1
    Z3 = t4 * Z3
    t1 = t3 * t0
    Z3 = Z3 + t1
    return _pt(X3, Y3, Z3)


def pt_double(p, b_fv):
    """Complete projective doubling (RCB16 algorithm 6, a = -3)."""
    X, Y, Z = p
    t0 = X * X
    t1 = Y * Y
    t2 = Z * Z
    t3 = X * Y
    t3 = t3 + t3
    Z3 = X * Z
    Z3 = Z3 + Z3
    Y3 = b_fv * t2
    Y3 = Y3 - Z3
    X3 = Y3 + Y3
    Y3 = X3 + Y3
    X3 = t1 - Y3
    Y3 = t1 + Y3
    Y3 = X3 * Y3
    X3 = X3 * t3
    t3 = t2 + t2
    t2 = t2 + t3
    Z3 = b_fv * Z3
    Z3 = Z3 - t2
    Z3 = Z3 - t0
    t3 = Z3 + Z3
    Z3 = Z3 + t3
    t3 = t0 + t0
    t0 = t3 + t0
    t0 = t0 - t2
    t0 = t0 * Z3
    Y3 = Y3 + t0
    t0 = Y * Z
    t0 = t0 + t0
    Z3 = t0 * Z3
    X3 = X3 - Z3
    Z3 = t0 * t1
    Z3 = Z3 + Z3
    Z3 = Z3 + Z3
    return _pt(X3, Y3, Z3)


# ---------------------------------------------------------------------------
# Host-precomputed u1·G window table: TG[d] = d·G affine, d = 1..15
# (digit 0 = ∞ handled by a select).

_TG = np.zeros((16, 2, K), np.int32)
for _d in range(1, 16):
    _px, _py = ec_ref.pt_mul(_d, (GX, GY))
    _TG[_d, 0] = dg.int_to_digits(_px)
    _TG[_d, 1] = dg.int_to_digits(_py)
_TG_J = jnp.asarray(_TG)


def _settled_fv(arr, mod):
    return FV(arr, _SETTLED[id(mod)], mod)


def _window_digits(scalar_digits):
    """Canonical base-64 digits [B,K] → 4-bit window digits [B, 64],
    most-significant window first."""
    bits = (scalar_digits[..., :, None] >> jnp.arange(dg.W, dtype=jnp.int32)) & 1
    bits = bits.reshape(*scalar_digits.shape[:-1], K * dg.W)[..., :256]
    w = bits.reshape(*scalar_digits.shape[:-1], STEPS, WINDOW)
    weights = jnp.asarray([1, 2, 4, 8], jnp.int32)
    digs = jnp.sum(w * weights, axis=-1)  # [..., STEPS] little-endian windows
    return digs[..., ::-1]  # MSB window first


def verify_batch(e, r, s, rpn, rpn_ok, qx, qy, pre_ok):
    """Batched ECDSA P-256 verify on digit-form inputs.

    e, r, s, qx, qy: [B, K] canonical base-2^6 digit arrays.
    rpn: digits of r+n; rpn_ok: [B] bool, r+n < p (host precomputed).
    pre_ok: [B] bool host-side admission results (r,s in [1,n-1],
        s <= n/2, qx,qy < p, (qx,qy) != (0,0)) — cheap exact integer
        checks on values the host already holds as Python ints.

    Returns [B] bool, the exact accept set of the reference verifier.
    """
    B = e.shape[0]

    # --- on-curve check (mod p): y^2 == x^3 - 3x + b
    qx_p = FV(qx, 63, MODP)
    qy_p = FV(qy, 63, MODP)
    b_p = _const_fv(B_COEF, qx, MODP)
    y2 = qy_p * qy_p
    x2 = qx_p * qx_p
    x3 = x2 * qx_p
    three_x = qx_p + qx_p + qx_p
    rhs = x3 - three_x + b_p
    on_curve = MODP.eq_zero((y2 - rhs).arr)

    # --- u1 = e/s, u2 = r/s (mod n) via Fermat
    s_n = FV(s, 63, MODN)
    n_minus_2_bits = jnp.asarray(
        np.array([(N - 2) >> (255 - i) & 1 for i in range(256)], np.int32)
    )
    one_n = jnp.broadcast_to(jnp.asarray(dg.int_to_digits(1)), s.shape)

    def inv_body(i, acc):
        acc_fv = _settled_fv(acc, MODN)
        sq = acc_fv * acc_fv
        mulres = sq * s_n
        bit = n_minus_2_bits[i]
        return jnp.where(bit == 1, mulres.arr, sq.arr)

    s_inv = jax.lax.fori_loop(0, 256, inv_body, one_n)
    s_inv_fv = _settled_fv(s_inv, MODN)
    u1 = MODN.canonical((FV(e, 63, MODN) * s_inv_fv).arr)
    u2 = MODN.canonical((FV(r, 63, MODN) * s_inv_fv).arr)

    # --- u2·Q window table: T[d] = d·Q, d = 0..15, T[0] = ∞
    b_fv = b_p
    zero = jnp.zeros_like(qx)
    one_digits = jnp.broadcast_to(jnp.asarray(dg.int_to_digits(1)), qx.shape)
    inf = _pt(FV(zero, 0, MODP), FV(one_digits, 63, MODP), FV(zero, 0, MODP))
    q1 = _pt(qx_p, qy_p, FV(one_digits, 63, MODP))

    table = [inf, q1]
    acc = q1
    for _d in range(2, 16):
        acc = pt_add(acc, q1, b_fv)
        table.append(acc)
    # stack: [B, 16, 3, K]
    tq = jnp.stack(
        [jnp.stack([pt[0].arr, pt[1].arr, pt[2].arr], axis=-2) for pt in table],
        axis=-3,
    )
    tq_bound = max(max(pt[0].bound, pt[1].bound, pt[2].bound) for pt in table)

    w1 = _window_digits(u1)  # [B, 64] MSB-first
    w2 = _window_digits(u2)

    tg_flat = _TG_J.reshape(16, 2 * K).astype(jnp.float32)  # constants

    def ladder_body(i, state):
        Xa, Ya, Za = state
        R = _pt(_settled_fv(Xa, MODP), _settled_fv(Ya, MODP), _settled_fv(Za, MODP))
        for _ in range(WINDOW):
            R = pt_double(R, b_fv)
        # add T_Q[w2[i]] (one-hot gather; complete add handles ∞ slot)
        d2 = jax.lax.dynamic_index_in_dim(w2, i, axis=1, keepdims=False)  # [B]
        oh2 = (d2[:, None] == jnp.arange(16)[None, :]).astype(jnp.float32)
        sel = jnp.einsum(
            "bt,btck->bck", oh2, tq.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        T2 = _pt(
            FV(sel[:, 0], tq_bound, MODP),
            FV(sel[:, 1], tq_bound, MODP),
            FV(sel[:, 2], tq_bound, MODP),
        )
        R = pt_add(R, T2, b_fv)
        # add T_G[w1[i]] (affine constants; skip when digit == 0)
        d1 = jax.lax.dynamic_index_in_dim(w1, i, axis=1, keepdims=False)
        oh1 = (d1[:, None] == jnp.arange(16)[None, :]).astype(jnp.float32)
        selg = (oh1 @ tg_flat).astype(jnp.int32).reshape(-1, 2, K)
        Rg = pt_add_mixed(
            R, FV(selg[:, 0], 63, MODP), FV(selg[:, 1], 63, MODP), b_fv
        )
        skip = (d1 == 0)[:, None]
        X3 = jnp.where(skip, R[0].arr, Rg[0].arr)
        Y3 = jnp.where(skip, R[1].arr, Rg[1].arr)
        Z3 = jnp.where(skip, R[2].arr, Rg[2].arr)
        # settle to keep the loop-carried bound static across iterations
        return (MODP.settle(X3), MODP.settle(Y3), MODP.settle(Z3))

    state0 = (zero, one_digits, zero)
    Xr, Yr, Zr = jax.lax.fori_loop(0, STEPS, ladder_body, state0)

    # --- accept iff R != ∞ and x(R) = X/Z ≡ r (mod n):
    # X ≡ r·Z (mod p), or X ≡ (r+n)·Z (mod p) when r+n < p
    Z_fv = _settled_fv(Zr, MODP)
    X_fv = _settled_fv(Xr, MODP)
    not_inf = ~MODP.eq_zero(Zr)
    rz = FV(r, 63, MODP) * Z_fv
    cmp1 = MODP.eq_zero((X_fv - rz).arr)
    rpnz = FV(rpn, 63, MODP) * Z_fv
    cmp2 = MODP.eq_zero((X_fv - rpnz).arr) & rpn_ok
    return pre_ok & on_curve & not_inf & (cmp1 | cmp2)


verify_batch_jit = jax.jit(verify_batch)


# ---------------------------------------------------------------------------
# Host wrappers (drop-in for ops.p256.verify_host)

MIN_BUCKET = 16


def verify_host(items) -> list[bool]:
    """items: iterable of (digest_int, r, s, qx, qy) Python ints —
    same interface as ops.p256.verify_host, same accept set."""
    items = list(items)
    if not items:
        return []
    n = len(items)
    bsz = max(MIN_BUCKET, next_pow2(n))
    pad = [(0, 1, 1, 0, 0)] * (bsz - n)  # padded lanes fail pre_ok anyway
    full = items + pad

    pre_ok, rpn, rpn_ok = [], [], []
    for (ei, ri, si, xi, yi) in full:
        ok = (
            0 < ri < N and 0 < si <= HALF_N
            and 0 <= xi < P and 0 <= yi < P and not (xi == 0 and yi == 0)
        )
        pre_ok.append(ok)
        rp = ri + N
        rpn_ok.append(rp < P)
        rpn.append(rp if rp < P else 0)

    cols = list(zip(*full))
    arrs = [
        dg.ints_to_digits([int(x) % (1 << 258) for x in col])
        for col in (cols[0], cols[1], cols[2], rpn, cols[3], cols[4])
    ]
    e_d, r_d, s_d, rpn_d, qx_d, qy_d = (jnp.asarray(a) for a in arrs)
    out = verify_batch_jit(
        e_d, r_d, s_d, rpn_d,
        jnp.asarray(np.array(rpn_ok)), qx_d, qy_d,
        jnp.asarray(np.array(pre_ok)),
    )
    return [bool(v) for v in np.asarray(out)[:n]]

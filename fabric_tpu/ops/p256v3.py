"""Batched ECDSA P-256 verification over RNS field arithmetic ("v3",
the Cox-Rower kernel) — the flagship data-plane kernel.

Design deltas vs ops.p256v2 (digit-polynomial "v2"):

* Field core: fabric_tpu.ops.rns — Montgomery multiplication whose only
  non-elementwise work is two DENSE [B,46]@[46,72] bf16 MXU matmuls
  (exact by 6-bit chunking), ~25× less matmul volume per modmul than
  v2's one-hot contraction, at DEFAULT (single-pass) precision.
* Scalar recoding moved to the HOST: u1 = e·s⁻¹, u2 = r·s⁻¹ (mod n)
  are computed with one Montgomery-batched inversion over the whole
  batch (3(B−1) 256-bit mults + ONE modular inversion, microseconds of
  numpy/Python work) — RNS has no cheap positional form, and the
  device has no business running a 256-round Fermat loop when the host
  does the whole batch in milliseconds.  The device receives 4-bit
  window digits.
* Point arithmetic: unchanged mathematics — Renes–Costello–Batina 2016
  COMPLETE projective formulas (a = −3), 64 ladder steps of
  [4 doublings + u2·Q table add + u1·G mixed add], in-kernel Q window
  table, host-precomputed Montgomery-form G table.
* Ladder body lives in a fori_loop with a FIXED loop-state bound
  contract (≤ 6p, asserted at trace time via rns.RV bound tracking),
  keeping the HLO graph ~64× smaller than a fully unrolled ladder.

Reference accept set matched exactly (bccsp/sw/ecdsa.go:41-58):
r,s ∈ [1, n−1], s ≤ n/2 (low-S), Q on curve and ≠ ∞,
R = u1·G + u2·Q ≠ ∞, x(R) ≡ r (mod n).  Bit-exact against
fabric_tpu.crypto.ec_ref (tests/test_p256v3.py).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import rns
from fabric_tpu.utils.batching import next_pow2

P = ec_ref.P
N = ec_ref.N
B_COEF = ec_ref.B
GX, GY = ec_ref.GX, ec_ref.GY
HALF_N = ec_ref.HALF_N

WINDOW = 4
STEPS = 64

# fixed bound contract for ladder-carried coordinates
_BND_STATE = 9 * P


def _ctx() -> rns.MontCtx:
    return rns.ctx_for(P)


def _const_rv(x: int) -> rns.RV:
    return rns.to_rns(x)


# ---------------------------------------------------------------------------
# RCB16 complete point ops (projective X:Y:Z, a = -3) over rns.RV.
# Identical op schedules to ops.p256v2 (alg. 4/5/6); the field layer
# changed, the mathematics did not.


def pt_add(p1, p2, b_rv, ctx):
    """RCB16 algorithm 4 restaged into 3 stacked-mul dispatches:
    6 independent muls, then the 2 b-muls, then the 6 output muls —
    identical mathematics to the sequential schedule (the staging is
    checked mul-for-mul against it in tests)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, t2, s1, s2, s3 = rns.mont_mul_many(
        [(X1, X2), (Y1, Y2), (Z1, Z2),
         (X1 + Y1, X2 + Y2), (Y1 + Z1, Y2 + Z2), (X1 + Z1, X2 + Z2)],
        ctx,
    )
    t3 = sub(s1, t0 + t1)
    t4 = sub(s2, t1 + t2)
    y3a = sub(s3, t0 + t2)
    bz, by = rns.mont_mul_many([(b_rv, t2), (b_rv, y3a)], ctx)
    x3a = sub(y3a, bz)
    x3b = x3a + x3a + x3a
    z3a = sub(t1, x3b)
    x3c = t1 + x3b
    t2b = t2 + t2 + t2
    y3b = sub(sub(by, t2b), t0)
    y3c = y3b + y3b + y3b
    t0c = sub(t0 + t0 + t0, t2b)
    m1, m2, m3, m4, m5, m6 = rns.mont_mul_many(
        [(t4, y3c), (t0c, y3c), (x3c, z3a), (t3, x3c), (t4, z3a), (t3, t0c)],
        ctx,
    )
    return (sub(m4, m1), m3 + m2, m5 + m6)


def pt_add_mixed(p1, x2, y2, b_rv, ctx):
    """RCB16 algorithm 5 (Z2 = 1): P2 affine, must not be ∞.
    Staged: 6 + 1 + 6 muls in 3 stacked dispatches."""
    X1, Y1, Z1 = p1
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, s1, myz, mxz, bz1 = rns.mont_mul_many(
        [(X1, x2), (Y1, y2), (x2 + y2, X1 + Y1),
         (y2, Z1), (x2, Z1), (b_rv, Z1)],
        ctx,
    )
    t3 = sub(s1, t0 + t1)
    t4 = myz + Y1
    y3a = mxz + X1
    x3a = sub(y3a, bz1)
    x3b = x3a + x3a + x3a
    z3a = sub(t1, x3b)
    x3c = t1 + x3b
    (by,) = rns.mont_mul_many([(b_rv, y3a)], ctx)
    t2b = Z1 + Z1 + Z1
    y3b = sub(sub(by, t2b), t0)
    y3c = y3b + y3b + y3b
    t0c = sub(t0 + t0 + t0, t2b)
    m1, m2, m3, m4, m5, m6 = rns.mont_mul_many(
        [(t4, y3c), (t0c, y3c), (x3c, z3a), (t3, x3c), (t4, z3a), (t3, t0c)],
        ctx,
    )
    return (sub(m4, m1), m3 + m2, m5 + m6)


def pt_double(p, b_rv, ctx):
    """RCB16 algorithm 6 (a = −3) restaged: 6 + 2 + 2 + 3 muls in 4
    stacked dispatches."""
    X, Y, Z = p
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, t2, xy, xz, yz = rns.mont_mul_many(
        [(X, X), (Y, Y), (Z, Z), (X, Y), (X, Z), (Y, Z)], ctx
    )
    t3 = xy + xy
    zz2 = xz + xz
    bt2, bz = rns.mont_mul_many([(b_rv, t2), (b_rv, zz2)], ctx)
    y3a = sub(bt2, zz2)
    y3b = y3a + y3a + y3a
    x3a = sub(t1, y3b)
    y3c = t1 + y3b
    y3m, x3m = rns.mont_mul_many([(x3a, y3c), (x3a, t3)], ctx)
    t2b = t2 + t2 + t2
    z3a = sub(sub(bz, t2b), t0)
    z3b = z3a + z3a + z3a
    t0c = sub(t0 + t0 + t0, t2b)
    yz2 = yz + yz
    a1, a2, a3 = rns.mont_mul_many(
        [(t0c, z3b), (yz2, z3b), (yz2, t1)], ctx
    )
    Z3 = a3 + a3
    return (sub(x3m, a2), y3m + a1, Z3 + Z3)


# ---------------------------------------------------------------------------
# Host-precomputed u1·G window table in Montgomery-RNS form:
# TG[d] = d·G affine, d = 1..15 (slot 0 unused; digit-0 is skipped).

_TG = np.zeros((16, 2, 2 * rns.N_CH), np.int32)
for _d in range(1, 16):
    _px, _py = ec_ref.pt_mul(_d, (GX, GY))
    _TG[_d, 0] = rns.ints_to_rns([(_px * rns.M_A) % P])[0]
    _TG[_d, 1] = rns.ints_to_rns([(_py * rns.M_A) % P])[0]
_TG_J = None  # jnp-ified lazily inside the traced fn

_MONT_ONE = (rns.M_A % P)


def _clamp(rv: rns.RV, bound: int) -> rns.RV:
    assert rv.bound <= bound, (rv.bound, bound)
    return rns.RV(rv.arr, bound)


def verify_batch(qx, qy, rr, rpn, w1, w2, rpn_ok, pre_ok):
    """Batched verify on RNS-residue inputs.

    qx, qy, rr, rpn: [B, 2n] canonical residues of Q.x, Q.y, r, r+n
        (plain domain, values < p).
    w1, w2: [B, 64] int32 4-bit window digits of u1, u2, MSB-first.
    rpn_ok: [B] bool, r+n < p.  pre_ok: [B] bool host admission checks.
    → [B] bool, the exact accept set of the reference verifier.
    """
    ctx = _ctx()
    mul = lambda a, b: rns.mont_mul(a, b, ctx)
    sub = lambda a, b: rns.rv_sub(a, b, ctx)

    def RVp(arr):
        return rns.RV(arr, P)

    qx_m = rns.to_mont(RVp(qx), ctx)
    qy_m = rns.to_mont(RVp(qy), ctx)
    b_m = _const_rv((B_COEF * rns.M_A) % P)

    # on-curve: y² == x³ − 3x + b   (Montgomery domain throughout)
    y2 = mul(qy_m, qy_m)
    x2 = mul(qx_m, qx_m)
    x3 = mul(x2, qx_m)
    three_x = qx_m + qx_m + qx_m
    rhs = sub(x3 + b_m, three_x)
    on_curve = rns.eq_const_mod_p(sub(y2, rhs), ctx)

    # u2·Q window table T[d] = d·Q, T[0] = ∞ = (0 : 1̃ : 0)
    zero = jnp.zeros_like(qx)
    one_m = jnp.broadcast_to(
        jnp.asarray(rns._to_res(_MONT_ONE, rns.BASE_A + rns.BASE_B)), qx.shape
    )
    inf = (rns.RV(zero, 0), rns.RV(one_m, _MONT_ONE), rns.RV(zero, 0))
    q1 = (qx_m, qy_m, rns.RV(one_m, _MONT_ONE))
    table = [inf, q1]
    acc = q1
    for _d in range(2, 16):
        acc = tuple(_clamp(c, _BND_STATE) for c in pt_add(acc, q1, b_m, ctx))
        table.append(acc)
    tq = jnp.stack(
        [jnp.stack([pt[0].arr, pt[1].arr, pt[2].arr], axis=-2) for pt in table],
        axis=-3,
    )  # [B, 16, 3, 2n]

    tg = jnp.asarray(_TG)  # [16, 2, 2n] constants

    def ladder_body(i, state):
        Xa, Ya, Za = state
        R = (rns.RV(Xa, _BND_STATE), rns.RV(Ya, _BND_STATE),
             rns.RV(Za, _BND_STATE))
        for _ in range(WINDOW):
            R = tuple(
                _clamp(c, _BND_STATE) for c in pt_double(R, b_m, ctx)
            )
        # add T_Q[w2[i]] — integer gather; complete add handles ∞ slot
        d2 = jax.lax.dynamic_index_in_dim(w2, i, axis=1, keepdims=False)
        sel = jnp.take_along_axis(
            tq, d2[:, None, None, None], axis=-3
        )[..., 0, :, :]
        T2 = (rns.RV(sel[..., 0, :], _BND_STATE),
              rns.RV(sel[..., 1, :], _BND_STATE),
              rns.RV(sel[..., 2, :], _BND_STATE))
        R = tuple(_clamp(c, _BND_STATE) for c in pt_add(R, T2, b_m, ctx))
        # add T_G[w1[i]] — affine constants, skipped when digit == 0
        d1 = jax.lax.dynamic_index_in_dim(w1, i, axis=1, keepdims=False)
        selg = jnp.take_along_axis(
            tg[None], d1[:, None, None, None], axis=-3
        )[..., 0, :, :]
        Rg = pt_add_mixed(
            R, rns.RV(selg[..., 0, :], P), rns.RV(selg[..., 1, :], P),
            b_m, ctx,
        )
        Rg = tuple(_clamp(c, _BND_STATE) for c in Rg)
        skip = (d1 == 0)[:, None]
        return (
            jnp.where(skip, R[0].arr, Rg[0].arr),
            jnp.where(skip, R[1].arr, Rg[1].arr),
            jnp.where(skip, R[2].arr, Rg[2].arr),
        )

    state0 = (zero, one_m, zero)
    Xr, Yr, Zr = jax.lax.fori_loop(0, STEPS, ladder_body, state0)
    X_rv = rns.RV(Xr, _BND_STATE)
    Z_rv = rns.RV(Zr, _BND_STATE)

    not_inf = ~rns.eq_const_mod_p(Z_rv, ctx)
    # x(R) ≡ r (mod n) ⟺ X ≡ r·Z or (r+n)·Z (mod p), r+n only if < p
    r_m = rns.to_mont(RVp(rr), ctx)
    rpn_m = rns.to_mont(RVp(rpn), ctx)
    cmp1 = rns.eq_const_mod_p(sub(X_rv, mul(r_m, Z_rv)), ctx)
    cmp2 = rns.eq_const_mod_p(sub(X_rv, mul(rpn_m, Z_rv)), ctx) & rpn_ok
    return pre_ok & on_curve & not_inf & (cmp1 | cmp2)


verify_batch_jit = jax.jit(verify_batch)


# ---------------------------------------------------------------------------
# Host side: admission checks, batched inversion, recoding, residues

MIN_BUCKET = 16


def _bucket(n: int) -> int:
    """Batch bucket: powers of two up to 512, then multiples of 512 —
    a 1000-tx block's ~3000 signatures pad to 3072, not 4096 (the
    padding lanes are pure wasted MXU work).  Few distinct shapes keep
    the persistent compile cache small."""
    if n <= 512:
        return max(MIN_BUCKET, next_pow2(n))
    return -(-n // 512) * 512


def _batch_inv_mod_n(ss: list[int]) -> list[int]:
    """Montgomery's simultaneous inversion: one pow(·,−1,n) for the
    whole batch + 3(B−1) modmuls (the v20 validator's per-tx goroutine
    fan-out, collapsed into prefix products)."""
    B = len(ss)
    pref = [1] * (B + 1)
    for i, s in enumerate(ss):
        pref[i + 1] = (pref[i] * s) % N
    inv_all = pow(pref[B], -1, N)
    out = [0] * B
    for i in range(B - 1, -1, -1):
        out[i] = (pref[i] * inv_all) % N
        inv_all = (inv_all * ss[i]) % N
    return out


def _windows(us: list[int]) -> np.ndarray:
    """[B] ints → [B, 64] 4-bit window digits, MSB-first."""
    if not us:
        return np.zeros((0, STEPS), np.int32)
    raw = np.frombuffer(
        b"".join(int(u).to_bytes(32, "big") for u in us), np.uint8
    ).reshape(len(us), 32)
    hi, lo = raw >> 4, raw & 0xF
    return np.stack([hi, lo], axis=-1).reshape(len(us), 64).astype(np.int32)


def prepare(items, pad_to: int | None = None):
    """Host-side preparation for verify_batch: admission checks,
    batched s⁻¹, scalar recoding, residue conversion.  Returns the
    verify_batch argument tuple (jnp arrays).  ``pad_to`` pads the
    batch with always-rejected lanes."""
    items = list(items)
    if pad_to is not None:
        items = items + [(0, 1, 1, 0, 0)] * (pad_to - len(items))

    pre_ok, rpn_ok, rpns, u1s, u2s, ss = [], [], [], [], [], []
    for (e, r, s, qx, qy) in items:
        ok = (
            0 < r < N and 0 < s <= HALF_N
            and 0 <= qx < P and 0 <= qy < P and not (qx == 0 and qy == 0)
        )
        pre_ok.append(ok)
        rp = r + N
        rpn_ok.append(rp < P)
        rpns.append(rp if rp < P else 0)
        ss.append(s if 0 < s < N else 1)
    s_inv = _batch_inv_mod_n(ss)
    for (e, r, s, qx, qy), si in zip(items, s_inv):
        u1s.append((e * si) % N)
        u2s.append((r * si) % N)

    cols = list(zip(*items))
    return (
        jnp.asarray(rns.ints_to_rns(cols[3])),
        jnp.asarray(rns.ints_to_rns(cols[4])),
        jnp.asarray(rns.ints_to_rns(cols[1])),
        jnp.asarray(rns.ints_to_rns(rpns)),
        jnp.asarray(_windows(u1s)),
        jnp.asarray(_windows(u2s)),
        jnp.asarray(np.array(rpn_ok)),
        jnp.asarray(np.array(pre_ok)),
    )


class VerifyHandle:
    """An in-flight verify batch: the device-resident validity vector
    plus a fetch() that syncs to host.  Downstream device stages
    (policy + MVCC fusion) consume ``device_out`` directly so the
    signature bits never cross the device boundary on the critical
    path."""

    __slots__ = ("device_out", "n_real")

    def __init__(self, device_out, n_real: int):
        self.device_out = device_out
        self.n_real = n_real

    def fetch(self) -> list[bool]:
        return [bool(v) for v in np.asarray(self.device_out)[: self.n_real]]

    def __call__(self) -> list[bool]:
        return self.fetch()


def verify_launch(items) -> VerifyHandle:
    """Asynchronously dispatch a verify batch; returns a VerifyHandle
    (callable as a zero-arg fetch for list[bool]).  The jax dispatch is
    non-blocking, so the device crunches while the caller's host thread
    moves on — the pipeline primitive the block validator builds on."""
    items = list(items)
    if not items:
        return VerifyHandle(jnp.zeros((0,), bool), 0)
    n_real = len(items)
    args = prepare(items, pad_to=_bucket(n_real))
    out = verify_batch_jit(*args)  # async under jax's deferred execution
    if hasattr(out, "copy_to_host_async"):
        # start the D2H as soon as compute finishes: device→host
        # readback latency is substantial on tunneled devices and must
        # overlap the caller's host work, not serialize behind it
        out.copy_to_host_async()
    return VerifyHandle(out, n_real)


def verify_host(items) -> list[bool]:
    """items: iterable of (digest_int, r, s, qx, qy) Python ints —
    same interface and accept set as ops.p256.verify_host."""
    return verify_launch(items)()

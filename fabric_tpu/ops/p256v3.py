"""Batched ECDSA P-256 verification over RNS field arithmetic ("v3",
the Cox-Rower kernel) — the flagship data-plane kernel.

Design deltas vs ops.p256v2 (digit-polynomial "v2"):

* Field core: fabric_tpu.ops.rns — Montgomery multiplication whose only
  non-elementwise work is two DENSE [B,46]@[46,72] bf16 MXU matmuls
  (exact by 6-bit chunking), ~25× less matmul volume per modmul than
  v2's one-hot contraction, at DEFAULT (single-pass) precision.
* Scalar recoding moved to the HOST: u1 = e·s⁻¹, u2 = r·s⁻¹ (mod n)
  are computed with one Montgomery-batched inversion over the whole
  batch (3(B−1) 256-bit mults + ONE modular inversion, microseconds of
  numpy/Python work) — RNS has no cheap positional form, and the
  device has no business running a 256-round Fermat loop when the host
  does the whole batch in milliseconds.  The device receives 4-bit
  window digits.
* Point arithmetic: unchanged mathematics — Renes–Costello–Batina 2016
  COMPLETE projective formulas (a = −3), 64 ladder steps of
  [4 doublings + u2·Q table add + u1·G mixed add], in-kernel Q window
  table, host-precomputed Montgomery-form G table.
* Ladder body lives in a fori_loop with a FIXED loop-state bound
  contract (≤ 6p, asserted at trace time via rns.RV bound tracking),
  keeping the HLO graph ~64× smaller than a fully unrolled ladder.

Reference accept set matched exactly (bccsp/sw/ecdsa.go:41-58):
r,s ∈ [1, n−1], s ≤ n/2 (low-S), Q on curve and ≠ ∞,
R = u1·G + u2·Q ≠ ∞, x(R) ≡ r (mod n).  Bit-exact against
fabric_tpu.crypto.ec_ref (tests/test_p256v3.py).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu import faults as _faults
from fabric_tpu.crypto import ec_ref
from fabric_tpu.observe import ledger as _ledger
from fabric_tpu.ops import rns
from fabric_tpu.utils.batching import next_pow2

P = ec_ref.P
N = ec_ref.N
B_COEF = ec_ref.B
GX, GY = ec_ref.GX, ec_ref.GY
HALF_N = ec_ref.HALF_N

WINDOW = 4
STEPS = 64

# fixed bound contract for ladder-carried coordinates
_BND_STATE = 9 * P


def _ctx() -> rns.MontCtx:
    return rns.ctx_for(P)


def _const_rv(x: int) -> rns.RV:
    return rns.to_rns(x)


# ---------------------------------------------------------------------------
# RCB16 complete point ops (projective X:Y:Z, a = -3) over rns.RV.
# Identical op schedules to ops.p256v2 (alg. 4/5/6); the field layer
# changed, the mathematics did not.


def pt_add(p1, p2, b_rv, ctx):
    """RCB16 algorithm 4 restaged into 3 stacked-mul dispatches:
    6 independent muls, then the 2 b-muls, then the 6 output muls —
    identical mathematics to the sequential schedule (the staging is
    checked mul-for-mul against it in tests)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, t2, s1, s2, s3 = rns.mont_mul_many(
        [(X1, X2), (Y1, Y2), (Z1, Z2),
         (X1 + Y1, X2 + Y2), (Y1 + Z1, Y2 + Z2), (X1 + Z1, X2 + Z2)],
        ctx,
    )
    t3 = sub(s1, t0 + t1)
    t4 = sub(s2, t1 + t2)
    y3a = sub(s3, t0 + t2)
    bz, by = rns.mont_mul_many([(b_rv, t2), (b_rv, y3a)], ctx)
    x3a = sub(y3a, bz)
    x3b = x3a + x3a + x3a
    z3a = sub(t1, x3b)
    x3c = t1 + x3b
    t2b = t2 + t2 + t2
    y3b = sub(sub(by, t2b), t0)
    y3c = y3b + y3b + y3b
    t0c = sub(t0 + t0 + t0, t2b)
    m1, m2, m3, m4, m5, m6 = rns.mont_mul_many(
        [(t4, y3c), (t0c, y3c), (x3c, z3a), (t3, x3c), (t4, z3a), (t3, t0c)],
        ctx,
    )
    return (sub(m4, m1), m3 + m2, m5 + m6)


def pt_add_mixed(p1, x2, y2, b_rv, ctx):
    """RCB16 algorithm 5 (Z2 = 1): P2 affine, must not be ∞.
    Staged: 6 + 1 + 6 muls in 3 stacked dispatches."""
    X1, Y1, Z1 = p1
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, s1, myz, mxz, bz1 = rns.mont_mul_many(
        [(X1, x2), (Y1, y2), (x2 + y2, X1 + Y1),
         (y2, Z1), (x2, Z1), (b_rv, Z1)],
        ctx,
    )
    t3 = sub(s1, t0 + t1)
    t4 = myz + Y1
    y3a = mxz + X1
    x3a = sub(y3a, bz1)
    x3b = x3a + x3a + x3a
    z3a = sub(t1, x3b)
    x3c = t1 + x3b
    (by,) = rns.mont_mul_many([(b_rv, y3a)], ctx)
    t2b = Z1 + Z1 + Z1
    y3b = sub(sub(by, t2b), t0)
    y3c = y3b + y3b + y3b
    t0c = sub(t0 + t0 + t0, t2b)
    m1, m2, m3, m4, m5, m6 = rns.mont_mul_many(
        [(t4, y3c), (t0c, y3c), (x3c, z3a), (t3, x3c), (t4, z3a), (t3, t0c)],
        ctx,
    )
    return (sub(m4, m1), m3 + m2, m5 + m6)


def pt_double(p, b_rv, ctx):
    """RCB16 algorithm 6 (a = −3) restaged: 6 + 2 + 5 muls in 3
    stacked dispatches.  The (x3a·y3c, x3a·t3) pair depends only on
    bt2 and the (·z3b, ·t1) triple only on bz, so the former stages 3
    and 4 are mutually independent and fuse into ONE stacked dispatch —
    with 4 doublings per ladder step this cuts 4 sequential Montgomery
    rounds (and their 2 matmuls each) from every step's critical path."""
    X, Y, Z = p
    sub = lambda a, b: rns.rv_sub(a, b, ctx)
    t0, t1, t2, xy, xz, yz = rns.mont_mul_many(
        [(X, X), (Y, Y), (Z, Z), (X, Y), (X, Z), (Y, Z)], ctx
    )
    t3 = xy + xy
    zz2 = xz + xz
    bt2, bz = rns.mont_mul_many([(b_rv, t2), (b_rv, zz2)], ctx)
    y3a = sub(bt2, zz2)
    y3b = y3a + y3a + y3a
    x3a = sub(t1, y3b)
    y3c = t1 + y3b
    t2b = t2 + t2 + t2
    z3a = sub(sub(bz, t2b), t0)
    z3b = z3a + z3a + z3a
    t0c = sub(t0 + t0 + t0, t2b)
    yz2 = yz + yz
    y3m, x3m, a1, a2, a3 = rns.mont_mul_many(
        [(x3a, y3c), (x3a, t3), (t0c, z3b), (yz2, z3b), (yz2, t1)], ctx
    )
    Z3 = a3 + a3
    return (sub(x3m, a2), y3m + a1, Z3 + Z3)


# ---------------------------------------------------------------------------
# Host-precomputed u1·G window table in Montgomery-RNS form:
# TG[d] = d·G affine, d = 1..15 (slot 0 unused; digit-0 is skipped).

_TG = np.zeros((16, 2, 2 * rns.N_CH), np.int32)
for _d in range(1, 16):
    _px, _py = ec_ref.pt_mul(_d, (GX, GY))
    _TG[_d, 0] = rns.ints_to_rns([(_px * rns.M_A) % P])[0]
    _TG[_d, 1] = rns.ints_to_rns([(_py * rns.M_A) % P])[0]
_TG_J = None  # jnp-ified lazily inside the traced fn

_MONT_ONE = (rns.M_A % P)


def _clamp(rv: rns.RV, bound: int) -> rns.RV:
    assert rv.bound <= bound, (rv.bound, bound)
    return rns.RV(rv.arr, bound)


def verify_batch(qx, qy, rr, rpn, w1, w2, rpn_ok, pre_ok):
    """Batched verify on RNS-residue inputs.

    qx, qy, rr, rpn: [B, 2n] canonical residues of Q.x, Q.y, r, r+n
        (plain domain, values < p).
    w1, w2: [B, 64] int32 4-bit window digits of u1, u2, MSB-first.
    rpn_ok: [B] bool, r+n < p.  pre_ok: [B] bool host admission checks.
    → [B] bool, the exact accept set of the reference verifier.
    """
    ctx = _ctx()
    mul = lambda a, b: rns.mont_mul(a, b, ctx)
    sub = lambda a, b: rns.rv_sub(a, b, ctx)

    def RVp(arr):
        return rns.RV(arr, P)

    qx_m = rns.to_mont(RVp(qx), ctx)
    qy_m = rns.to_mont(RVp(qy), ctx)
    b_m = _const_rv((B_COEF * rns.M_A) % P)

    # on-curve: y² == x³ − 3x + b   (Montgomery domain throughout)
    y2 = mul(qy_m, qy_m)
    x2 = mul(qx_m, qx_m)
    x3 = mul(x2, qx_m)
    three_x = qx_m + qx_m + qx_m
    rhs = sub(x3 + b_m, three_x)
    on_curve = rns.eq_const_mod_p(sub(y2, rhs), ctx)

    # u2·Q window table T[d] = d·Q, T[0] = ∞ = (0 : 1̃ : 0)
    zero = jnp.zeros_like(qx)
    one_m = jnp.broadcast_to(
        jnp.asarray(rns._to_res(_MONT_ONE, rns.BASE_A + rns.BASE_B)), qx.shape
    )
    inf = (rns.RV(zero, 0), rns.RV(one_m, _MONT_ONE), rns.RV(zero, 0))
    q1 = (qx_m, qy_m, rns.RV(one_m, _MONT_ONE))
    table = [inf, q1]
    acc = q1
    for _d in range(2, 16):
        acc = tuple(_clamp(c, _BND_STATE) for c in pt_add(acc, q1, b_m, ctx))
        table.append(acc)
    tq = jnp.stack(
        [jnp.stack([pt[0].arr, pt[1].arr, pt[2].arr], axis=-2) for pt in table],
        axis=-3,
    )  # [B, 16, 3, 2n]

    tg = jnp.asarray(_TG)  # [16, 2, 2n] constants

    def ladder_body(i, state):
        Xa, Ya, Za = state
        R = (rns.RV(Xa, _BND_STATE), rns.RV(Ya, _BND_STATE),
             rns.RV(Za, _BND_STATE))
        for _ in range(WINDOW):
            R = tuple(
                _clamp(c, _BND_STATE) for c in pt_double(R, b_m, ctx)
            )
        # add T_Q[w2[i]] — integer gather; complete add handles ∞ slot
        d2 = jax.lax.dynamic_index_in_dim(w2, i, axis=1, keepdims=False)
        sel = jnp.take_along_axis(
            tq, d2[:, None, None, None], axis=-3
        )[..., 0, :, :]
        T2 = (rns.RV(sel[..., 0, :], _BND_STATE),
              rns.RV(sel[..., 1, :], _BND_STATE),
              rns.RV(sel[..., 2, :], _BND_STATE))
        R = tuple(_clamp(c, _BND_STATE) for c in pt_add(R, T2, b_m, ctx))
        # add T_G[w1[i]] — affine constants, skipped when digit == 0
        d1 = jax.lax.dynamic_index_in_dim(w1, i, axis=1, keepdims=False)
        selg = jnp.take_along_axis(
            tg[None], d1[:, None, None, None], axis=-3
        )[..., 0, :, :]
        Rg = pt_add_mixed(
            R, rns.RV(selg[..., 0, :], P), rns.RV(selg[..., 1, :], P),
            b_m, ctx,
        )
        Rg = tuple(_clamp(c, _BND_STATE) for c in Rg)
        skip = (d1 == 0)[:, None]
        return (
            jnp.where(skip, R[0].arr, Rg[0].arr),
            jnp.where(skip, R[1].arr, Rg[1].arr),
            jnp.where(skip, R[2].arr, Rg[2].arr),
        )

    state0 = (zero, one_m, zero)
    Xr, Yr, Zr = jax.lax.fori_loop(0, STEPS, ladder_body, state0)
    X_rv = rns.RV(Xr, _BND_STATE)
    Z_rv = rns.RV(Zr, _BND_STATE)

    not_inf = ~rns.eq_const_mod_p(Z_rv, ctx)
    # x(R) ≡ r (mod n) ⟺ X ≡ r·Z or (r+n)·Z (mod p), r+n only if < p
    r_m = rns.to_mont(RVp(rr), ctx)
    rpn_m = rns.to_mont(RVp(rpn), ctx)
    cmp1 = rns.eq_const_mod_p(sub(X_rv, mul(r_m, Z_rv)), ctx)
    cmp2 = rns.eq_const_mod_p(sub(X_rv, mul(rpn_m, Z_rv)), ctx) & rpn_ok
    return pre_ok & on_curve & not_inf & (cmp1 | cmp2)


verify_batch_jit = jax.jit(verify_batch)


# ---------------------------------------------------------------------------
# Host side: admission checks, batched inversion, recoding, residues

MIN_BUCKET = 16


def _bucket(n: int) -> int:
    """Batch bucket: powers of two up to 512, then multiples of 512 —
    a 1000-tx block's ~3000 signatures pad to 3072, not 4096 (the
    padding lanes are pure wasted MXU work).  Few distinct shapes keep
    the persistent compile cache small."""
    if n <= 512:
        return max(MIN_BUCKET, next_pow2(n))
    return -(-n // 512) * 512


def _batch_inv_mod_n(ss: list[int]) -> list[int]:
    """Montgomery's simultaneous inversion: one pow(·,−1,n) for the
    whole batch + 3(B−1) modmuls (the v20 validator's per-tx goroutine
    fan-out, collapsed into prefix products)."""
    B = len(ss)
    pref = [1] * (B + 1)
    for i, s in enumerate(ss):
        pref[i + 1] = (pref[i] * s) % N
    inv_all = pow(pref[B], -1, N)
    out = [0] * B
    for i in range(B - 1, -1, -1):
        out[i] = (pref[i] * inv_all) % N
        inv_all = (inv_all * ss[i]) % N
    return out


def _windows(us: list[int]) -> np.ndarray:
    """[B] ints → [B, 64] 4-bit window digits, MSB-first."""
    if not us:
        return np.zeros((0, STEPS), np.int32)
    raw = np.frombuffer(
        b"".join(int(u).to_bytes(32, "big") for u in us), np.uint8
    ).reshape(len(us), 32)
    hi, lo = raw >> 4, raw & 0xF
    return np.stack([hi, lo], axis=-1).reshape(len(us), 64).astype(np.int32)


# window recoding ON DEVICE: u1/u2 ship as 16 big-endian 16-bit limbs
# (32 int16 columns for the pair) instead of 128 window-digit columns —
# 4× less H2D for the window planes, ~1.4× for the whole packed frame —
# and the [B, 64] digits are derived in the stage-1 kernel with pure
# shift/mask lanes.  Bit-equality vs host _windows is pinned by
# tests/test_p256v3.py across random scalars and edge cases.
_PK_LIMBS = 16


def _limbs16(us) -> np.ndarray:
    """[B] ints (< 2^256) → [B, 16] int16 BIG-endian 16-bit limbs.
    Values ≥ 2^15 wrap into the sign bit (same bit pattern); the
    device re-masks with ``& 0xFFFF`` after widening."""
    if not len(us):
        return np.zeros((0, _PK_LIMBS), np.int16)
    raw = np.frombuffer(
        b"".join(int(u).to_bytes(32, "big") for u in us), np.uint8
    ).reshape(len(us), 32).astype(np.uint16)
    return ((raw[:, 0::2] << 8) | raw[:, 1::2]).astype(np.int16)


def windows_to_limbs(w: np.ndarray) -> np.ndarray:
    """[B, 64] window digits → [B, 16] int16 limbs — packs the native
    ec_prepare path's C-computed windows into the limb wire form (the
    exact inverse of the device recode; each digit < 16)."""
    if not len(w):
        return np.zeros((0, _PK_LIMBS), np.int16)
    d = w.astype(np.uint16).reshape(len(w), _PK_LIMBS, 4)
    return ((d[..., 0] << 12) | (d[..., 1] << 8) | (d[..., 2] << 4)
            | d[..., 3]).astype(np.int16)


def device_recode_windows(limbs):
    """[B, 16] int16 big-endian limbs → [B, 64] int32 window digits,
    ON DEVICE — limb j carries digits 4j..4j+3 MSB-first, matching the
    host ``_windows`` layout bit for bit."""
    l = limbs.astype(jnp.int32) & 0xFFFF
    d = (l[..., None] >> jnp.asarray([12, 8, 4, 0], jnp.int32)) & 0xF
    return d.reshape(*limbs.shape[:-1], STEPS)


def prepare(items, pad_to: int | None = None):
    """Host-side preparation for verify_batch: admission checks,
    batched s⁻¹, scalar recoding, residue conversion.  Returns the
    verify_batch argument tuple (jnp arrays).  ``pad_to`` pads the
    batch with always-rejected lanes."""
    items = list(items)
    if pad_to is not None:
        items = items + [(0, 1, 1, 0, 0)] * (pad_to - len(items))

    pre_ok, rpn_ok, rpns, u1s, u2s, ss = [], [], [], [], [], []
    for (e, r, s, qx, qy) in items:
        ok = (
            0 < r < N and 0 < s <= HALF_N
            and 0 <= qx < P and 0 <= qy < P and not (qx == 0 and qy == 0)
        )
        pre_ok.append(ok)
        rp = r + N
        rpn_ok.append(rp < P)
        rpns.append(rp if rp < P else 0)
        ss.append(s if 0 < s < N else 1)
    s_inv = _batch_inv_mod_n(ss)
    for (e, r, s, qx, qy), si in zip(items, s_inv):
        u1s.append((e * si) % N)
        u2s.append((r * si) % N)

    cols = list(zip(*items))
    return (
        jnp.asarray(rns.ints_to_rns(cols[3])),
        jnp.asarray(rns.ints_to_rns(cols[4])),
        jnp.asarray(rns.ints_to_rns(cols[1])),
        jnp.asarray(rns.ints_to_rns(rpns)),
        jnp.asarray(_windows(u1s)),
        jnp.asarray(_windows(u2s)),
        jnp.asarray(np.array(rpn_ok)),
        jnp.asarray(np.array(pre_ok)),
    )


class SigCollector:
    """Column-form signature batch for the commit path.

    Fast rows reference the native pre-parser's [., 32] byte arrays by
    row index — no per-item Python-int materialisation; slow rows carry
    legacy (digest, r, s, qx, qy) int tuples for envelopes the Python
    parser handled.  ``assemble`` gathers the byte columns with numpy
    fancy indexing, converts residues with one dgemm
    (rns.bytes_to_rns), and reuses per-identity cached pubkey residues
    (Identity.rns_pub) — the host cost the round-3 bench paid per item
    (~265 ms/block of bigint→limb conversion) collapses to a few ms."""

    __slots__ = ("entries", "slow", "n")

    def __init__(self):
        self.entries = []  # (arrs=(digest,r,s), row, ident, pos)
        self.slow = []     # (pos, (e, r, s, qx, qy))
        self.n = 0

    def add_fast(self, arrs, row: int, ident) -> int:
        pos = self.n
        self.entries.append((arrs, int(row), ident, pos))
        self.n += 1
        return pos

    def add_slow(self, item) -> int:
        pos = self.n
        self.slow.append((pos, item))
        self.n += 1
        return pos

    def __len__(self) -> int:
        return self.n

    def tuples(self) -> list:
        """Legacy (digest, r, s, qx, qy) int tuples — the v1/v2
        comparison kernels and host fallbacks consume these."""
        out = [None] * self.n
        for arrs, row, ident, pos in self.entries:
            d, r, s = arrs
            qx, qy = ident.public_numbers
            out[pos] = (
                int.from_bytes(bytes(d[row]), "big"),
                int.from_bytes(bytes(r[row]), "big"),
                int.from_bytes(bytes(s[row]), "big"),
                qx, qy,
            )
        for pos, item in self.slow:
            out[pos] = item
        return out


class ColumnarSigBatch:
    """A signature batch ALREADY in column form — the validator's
    fully vectorized fast path assembles digest/r/s byte columns and
    per-identity cached pubkey residues straight from the native
    pre-parser's arrays with numpy gathers, so no per-item Python runs
    at all.  Slow rows (config-tx creators, host fallbacks) append as
    legacy int tuples after the fast block."""

    __slots__ = ("digest_b", "r_b", "s_b", "qx_res", "qy_res",
                 "pub_ok", "slow", "n_fast", "ident_of", "idents")

    def __init__(self, digest_b, r_b, s_b, qx_res, qy_res, pub_ok,
                 ident_of=None, idents=None):
        self.digest_b, self.r_b, self.s_b = digest_b, r_b, s_b
        self.qx_res, self.qy_res, self.pub_ok = qx_res, qy_res, pub_ok
        self.slow = []
        self.n_fast = len(digest_b)
        # per-fast-item identity (uid array + pool) — only for the
        # v1/v2 tuples() compatibility path
        self.ident_of = ident_of
        self.idents = idents

    @property
    def n(self) -> int:
        return self.n_fast + len(self.slow)

    def __len__(self) -> int:
        return self.n

    def add_slow(self, item) -> int:
        pos = self.n
        self.slow.append(item)
        return pos

    def assemble(self):
        """→ the six prepare_cols arrays with slow rows appended."""
        if not self.slow:
            return (self.digest_b, self.r_b, self.s_b,
                    self.qx_res, self.qy_res, self.pub_ok)
        k = len(self.slow)
        pad = lambda a: np.concatenate(
            [a, np.zeros((k,) + a.shape[1:], a.dtype)]
        )
        digest_b, r_b, s_b = (pad(self.digest_b), pad(self.r_b),
                              pad(self.s_b))
        qx_res, qy_res = pad(self.qx_res), pad(self.qy_res)
        pub_ok = pad(self.pub_ok)
        for j, (e, r, s, qx, qy) in enumerate(self.slow):
            pos = self.n_fast + j
            if not (0 <= r < (1 << 256) and 0 <= s < (1 << 256)):
                continue  # row stays zero, pub_ok False (reject)
            digest_b[pos] = np.frombuffer(int(e).to_bytes(32, "big"), np.uint8)
            r_b[pos] = np.frombuffer(int(r).to_bytes(32, "big"), np.uint8)
            s_b[pos] = np.frombuffer(int(s).to_bytes(32, "big"), np.uint8)
            res = rns.ints_to_rns([qx, qy])
            qx_res[pos], qy_res[pos] = res[0], res[1]
            pub_ok[pos] = (
                0 <= qx < P and 0 <= qy < P and not (qx == 0 and qy == 0)
            )
        return digest_b, r_b, s_b, qx_res, qy_res, pub_ok

    def tuples(self) -> list:
        """Legacy int-tuple form (v1/v2 comparison kernels only);
        pubkey ints come from the identity pool, not the residues."""
        out = []
        for i in range(self.n_fast):
            ident = self.idents[int(self.ident_of[i])]
            qx, qy = ident.public_numbers
            out.append((
                int.from_bytes(bytes(self.digest_b[i]), "big"),
                int.from_bytes(bytes(self.r_b[i]), "big"),
                int.from_bytes(bytes(self.s_b[i]), "big"),
                qx, qy,
            ))
        out.extend(self.slow)
        return out


def _assemble_cols(c: SigCollector):
    """SigCollector → (digest_b, r_b, s_b [B,32] u8; qx_res, qy_res
    [B,2n] i32; pub_ok [B] bool)."""
    B = c.n
    digest_b = np.zeros((B, 32), np.uint8)
    r_b = np.zeros((B, 32), np.uint8)
    s_b = np.zeros((B, 32), np.uint8)
    qx_res = np.zeros((B, 2 * rns.N_CH), np.int32)
    qy_res = np.zeros((B, 2 * rns.N_CH), np.int32)
    pub_ok = np.zeros(B, bool)

    groups: dict = {}  # id(digest array) → (arrs, [pos], [row])
    pool: dict = {}    # id(ident) → pool row
    pool_rows: list = []
    idx = np.zeros(B, np.int32)
    fast_pos: list = []
    for arrs, row, ident, pos in c.entries:
        g = groups.get(id(arrs[0]))
        if g is None:
            g = groups[id(arrs[0])] = (arrs, [], [])
        g[1].append(pos)
        g[2].append(row)
        k = id(ident)
        i = pool.get(k)
        if i is None:
            i = pool[k] = len(pool_rows)
            pool_rows.append(ident.rns_pub)
        idx[pos] = i
        fast_pos.append(pos)
    for arrs, poss, rows in groups.values():
        p = np.asarray(poss, np.intp)
        rr = np.asarray(rows, np.intp)
        digest_b[p] = arrs[0][rr]
        r_b[p] = arrs[1][rr]
        s_b[p] = arrs[2][rr]
    if pool_rows:
        qx_pool = np.stack([a for a, _ in pool_rows])
        qy_pool = np.stack([b for _, b in pool_rows])
        fp = np.asarray(fast_pos, np.intp)
        qx_res[fp] = qx_pool[idx[fp]]
        qy_res[fp] = qy_pool[idx[fp]]
        pub_ok[fp] = True  # cert-derived keys are real curve points
    for pos, (e, r, s, qx, qy) in c.slow:
        if not (0 <= r < (1 << 256) and 0 <= s < (1 << 256)):
            # r/s outside 256 bits can never satisfy 0 < · < n —
            # reject rather than wrap (wrapping would WIDEN the accept
            # set vs the legacy int path: consensus divergence)
            continue  # row stays all-zero with pub_ok False
        digest_b[pos] = np.frombuffer(int(e).to_bytes(32, "big"), np.uint8)
        r_b[pos] = np.frombuffer(int(r).to_bytes(32, "big"), np.uint8)
        s_b[pos] = np.frombuffer(int(s).to_bytes(32, "big"), np.uint8)
        res = rns.ints_to_rns([qx, qy])
        qx_res[pos], qy_res[pos] = res[0], res[1]
        pub_ok[pos] = (
            0 <= qx < P and 0 <= qy < P and not (qx == 0 and qy == 0)
        )
    return digest_b, r_b, s_b, qx_res, qy_res, pub_ok


def prepare_cols(digest_b, r_b, s_b, qx_res, qy_res, pub_ok,
                 pad_to: int | None = None, recode_device: bool = False,
                 out=None):
    """Column-form host preparation: same outputs (and accept set) as
    ``prepare`` but residues come from one dgemm over the byte columns
    and cached identity rows; only the admission checks and the
    batched inversion touch Python ints.

    ``recode_device``: skip host window recoding — the w1/w2 slots of
    the returned tuple carry [B, 16] int16 scalar LIMBS instead of
    [B, 64] digits, for the ``verify_batch_packed_limbs`` kernel that
    derives the digits on device (4× less H2D for the window planes).

    ``out``: optional 8-tuple of preallocated destinations (qx, qy,
    r_res, rpn_res, w1, w2, rpn_ok, pre_ok) with leading dim == the
    padded batch — every staged lane writes IN PLACE (the native
    ec_prepare digit planes and the residue dgemm land directly in the
    caller's row slabs), and the pad tail is zeroed.  This is how the
    pooled workers (``_prepare_cols_pooled``) avoid the
    allocate-then-copy that made pooled host-recode copy-bound; the
    result is bit-equal to the allocating form (tests/test_p256v3.py).
    Returns ``out`` when given, fresh arrays otherwise."""
    import ctypes

    B0 = len(r_b)
    Bp = pad_to if pad_to is not None else max(B0, 1)
    if out is not None:
        o_qx, o_qy, _o_r, o_rpn, o_w1, o_w2, o_rpn_ok, o_pre = out
        if len(o_pre) != Bp:
            raise ValueError(
                f"out arrays must have leading dim {Bp}, got {len(o_pre)}"
            )
        if Bp != B0:
            for a in out:  # pad tail = all-zero rejected lanes
                a[B0:] = 0
        o_qx[:B0] = qx_res
        o_qy[:B0] = qy_res
        pre_ok, rpn_ok = o_pre, o_rpn_ok
    else:
        o_w1 = o_w2 = None
        pre_ok = np.zeros(Bp, bool)
        rpn_ok = np.zeros(Bp, bool)
    full = lambda a: np.concatenate(
        [a, np.zeros((Bp - B0,) + a.shape[1:], a.dtype)]
    ) if Bp != B0 else a

    w1 = w2 = None
    done = False
    if B0:
        try:
            from fabric_tpu.native import ecprep_lib

            lib = ecprep_lib()
        except Exception:
            lib = None
        if lib is not None:
            # the native path needs NO Python bigints at all — the
            # admission flags, inversion, and windows all come from C
            # one GIL-releasing C call: admission flags + batch
            # inversion + window recoding for the whole batch
            eb = np.ascontiguousarray(digest_b)
            rb = np.ascontiguousarray(r_b)
            sb = np.ascontiguousarray(s_b)
            direct = False
            if out is not None and not recode_device:
                # C writes the digit planes straight into the
                # destination slabs (row-slab views stay contiguous)
                w1, w2 = o_w1[:B0], o_w2[:B0]
                direct = (w1.flags.c_contiguous and w2.flags.c_contiguous
                          and w1.dtype == np.int32)
            if not direct:
                w1 = np.zeros((B0, STEPS), np.int32)
                w2 = np.zeros((B0, STEPS), np.int32)
            flags = np.zeros(B0, np.uint8)
            ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
            lib.ec_prepare(
                ptr(eb), ptr(rb), ptr(sb), ctypes.c_int64(B0),
                ptr(w1), ptr(w2), ptr(flags),
            )
            pre_ok[:B0] = pub_ok & (flags & 1).astype(bool)
            rpn_ok[:B0] = (flags & 2).astype(bool)
            if recode_device:
                # the C path hands back digits; pack them to limbs so
                # the wire form (and kernel) match the Python lane
                w1, w2 = windows_to_limbs(w1), windows_to_limbs(w2)
            if out is not None:
                if not direct:
                    o_w1[:B0] = w1
                    o_w2[:B0] = w2
            else:
                w1, w2 = full(w1), full(w2)
            done = True

    if B0 and not done:  # pure-Python fallback (no toolchain)
        ebuf, rbuf, sbuf = digest_b.tobytes(), r_b.tobytes(), s_b.tobytes()
        es = [int.from_bytes(ebuf[32 * i:32 * i + 32], "big") for i in range(B0)]
        rints = [int.from_bytes(rbuf[32 * i:32 * i + 32], "big") for i in range(B0)]
        sints = [int.from_bytes(sbuf[32 * i:32 * i + 32], "big") for i in range(B0)]
        ss = [1] * B0
        for i, (r, s) in enumerate(zip(rints, sints)):
            pre_ok[i] = bool(pub_ok[i]) and 0 < r < N and 0 < s <= HALF_N
            rpn_ok[i] = (r + N) < P
            ss[i] = s if 0 < s < N else 1
        s_inv = _batch_inv_mod_n(ss)
        u1s = [(e * si) % N for e, si in zip(es, s_inv)]
        u2s = [(r * si) % N for r, si in zip(rints, s_inv)]
        w1, w2 = ((_limbs16(u1s), _limbs16(u2s)) if recode_device
                  else (_windows(u1s), _windows(u2s)))
        if out is not None:
            o_w1[:B0] = w1
            o_w2[:B0] = w2
        else:
            w1, w2 = full(w1), full(w2)
    elif not B0 and out is None:
        wcols = _PK_LIMBS if recode_device else STEPS
        wdt = np.int16 if recode_device else np.int32
        w1 = np.zeros((Bp, wcols), wdt)
        w2 = np.zeros((Bp, wcols), wdt)

    primes = np.array(rns.BASE_A + rns.BASE_B, np.int32)
    n_res = rns._to_res(N, rns.BASE_A + rns.BASE_B)
    if out is not None:
        rv = rns.bytes_to_rns(r_b, out=_o_r[:B0]) if B0 else _o_r[:0]
        np.mod(rv + n_res[None, :], primes, out=o_rpn[:B0])
        o_rpn[~rpn_ok] = 0
        return out
    r_res = full(rns.bytes_to_rns(r_b))
    rpn_res = (r_res + n_res[None, :]) % primes
    rpn_res[~rpn_ok] = 0
    return (
        full(qx_res), full(qy_res), r_res, rpn_res, w1, w2, rpn_ok, pre_ok,
    )


# packed launch form: every residue is < 2^12 (the RNS primes) and
# every window digit < 16, so the WHOLE batch ships as ONE int16
# array — a single H2D transfer instead of eight (each device_put has
# ~1 ms of fixed host overhead on top of the tunnel latency).
_PK_R = 2 * rns.N_CH
_PK_COLS = 4 * _PK_R + 2 * STEPS + 2


def _pack_rows(out, args, lo, hi, w_cols: int) -> None:
    """Pack rows [lo, hi) of the eight staged columns into the int16
    launch frame ``out`` in place — the unit the host pool shards."""
    view = out[lo:hi]
    o = 0
    for a in args[:4]:
        view[:, o:o + _PK_R] = a[lo:hi]
        o += _PK_R
    for a in args[4:6]:
        view[:, o:o + w_cols] = a[lo:hi]
        o += w_cols
    view[:, o] = args[6][lo:hi]
    view[:, o + 1] = args[7][lo:hi]


def pack_cols(qx, qy, r_res, rpn_res, w1, w2, rpn_ok, pre_ok) -> np.ndarray:
    B = len(qx)
    out = np.empty((B, _PK_COLS), np.int16)
    _pack_rows(out, (qx, qy, r_res, rpn_res, w1, w2, rpn_ok, pre_ok),
               0, B, STEPS)
    return out


def _unpack_cols(packed):
    o = 0
    res = []
    for _ in range(4):
        res.append(packed[:, o:o + _PK_R].astype(jnp.int32))
        o += _PK_R
    w1 = packed[:, o:o + STEPS].astype(jnp.int32)
    o += STEPS
    w2 = packed[:, o:o + STEPS].astype(jnp.int32)
    o += STEPS
    return (*res, w1, w2, packed[:, o] != 0, packed[:, o + 1] != 0)


def verify_batch_packed(packed):
    return verify_batch(*_unpack_cols(packed))


verify_batch_packed_jit = jax.jit(verify_batch_packed)


# recode-on-device packed form: the two 64-digit window planes shrink
# to 16 limbs each — 218 int16 columns per lane instead of 314.
_PKL_COLS = 4 * _PK_R + 2 * _PK_LIMBS + 2


def pack_cols_limbs(qx, qy, r_res, rpn_res, l1, l2, rpn_ok, pre_ok) -> np.ndarray:
    """Packed launch frame with u1/u2 as [B, 16] int16 limbs (the
    ``prepare_cols(recode_device=True)`` outputs) — consumed by
    ``verify_batch_packed_limbs`` which recodes on device."""
    B = len(qx)
    out = np.empty((B, _PKL_COLS), np.int16)
    _pack_rows(out, (qx, qy, r_res, rpn_res, l1, l2, rpn_ok, pre_ok),
               0, B, _PK_LIMBS)
    return out


def _unpack_cols_limbs(packed):
    o = 0
    res = []
    for _ in range(4):
        res.append(packed[:, o:o + _PK_R].astype(jnp.int32))
        o += _PK_R
    w1 = device_recode_windows(packed[:, o:o + _PK_LIMBS])
    o += _PK_LIMBS
    w2 = device_recode_windows(packed[:, o:o + _PK_LIMBS])
    o += _PK_LIMBS
    return (*res, w1, w2, packed[:, o] != 0, packed[:, o + 1] != 0)


def verify_batch_packed_limbs(packed):
    return verify_batch(*_unpack_cols_limbs(packed))


verify_batch_packed_limbs_jit = jax.jit(verify_batch_packed_limbs)


def _pack_launch(args, recode_device: bool, pool=None) -> np.ndarray:
    """Staged columns → int16 launch frame; with a host pool the row
    slabs pack in parallel (the pack is a multi-MB strided copy that
    otherwise serializes behind the pooled staging)."""
    if pool is None:
        return (pack_cols_limbs(*args) if recode_device
                else pack_cols(*args))
    B = len(args[0])
    w_cols = _PK_LIMBS if recode_device else STEPS
    out = np.empty((B, _PKL_COLS if recode_device else _PK_COLS),
                   np.int16)
    bounds = pool.slice_bounds(B, align=MIN_BUCKET)
    if len(bounds) <= 1:
        _pack_rows(out, args, 0, B, w_cols)
        return out
    pool.map_slices(B, lambda lo, hi: _pack_rows(out, args, lo, hi,
                                                 w_cols),
                    stage="pack", align=MIN_BUCKET)
    return out


def _packed_kernel(recode_device: bool):
    return (verify_batch_packed_limbs_jit if recode_device
            else verify_batch_packed_jit)


def prepare_cols_packed(digest_b, r_b, s_b, qx_res, qy_res, pub_ok,
                        pad_to: int | None = None,
                        recode_device: bool = False,
                        out=None) -> np.ndarray:
    """Single-pass host staging STRAIGHT into the packed int16 launch
    frame — ``pack_cols(prepare_cols(...))`` collapsed into one pass.

    The two-phase form allocates eight full-size staging arrays, fills
    them, and then copies every plane AGAIN into the int16 frame; this
    writes each plane exactly once:

    * the native ``ec_prepare_pack`` emits the window digit (or limb)
      planes int16 and STRIDED, directly into the frame's window
      columns (no int32 digit temps, no pack copy),
    * the residue dgemm lands in one int32 scratch that casts straight
      into the frame's r/rpn columns,
    * qx/qy/flags are single cast-assignments.

    Byte-identical to ``pack_cols(prepare_cols(...))`` /
    ``pack_cols_limbs(...)`` — pinned by tests/test_p256v3.py — and
    ~2× less memory traffic per staged batch, which is most of what
    the serial ``sig_prepare_launch`` stage still paid in host cycles.
    ``out``: optional preallocated [Bp, cols] C-contiguous int16 frame
    (reused across blocks by callers that want zero allocation)."""
    import ctypes

    B0 = len(r_b)
    R = _PK_R
    wcols = _PK_LIMBS if recode_device else STEPS
    ncols = _PKL_COLS if recode_device else _PK_COLS
    Bp = pad_to if pad_to is not None else max(B0, 1)
    if out is not None:
        frame = out
        if (frame.shape != (Bp, ncols) or frame.dtype != np.int16
                or not frame.flags.c_contiguous):
            raise ValueError(
                f"out must be a C-contiguous int16 [{Bp}, {ncols}] "
                f"frame, got {frame.dtype} {frame.shape}"
            )
    else:
        frame = np.empty((Bp, ncols), np.int16)
    if Bp != B0:
        frame[B0:] = 0  # pad tail: all-zero always-rejected lanes
    if not B0:
        frame[:] = 0
        return frame

    o_w1 = 4 * R
    o_w2 = o_w1 + wcols
    o_rpn_ok = o_w2 + wcols

    eb = np.ascontiguousarray(digest_b)
    rb = np.ascontiguousarray(r_b)
    sb = np.ascontiguousarray(s_b)
    try:
        from fabric_tpu.native import ecprep_lib

        lib = ecprep_lib()
    except Exception:
        lib = None
    pre_ok = rpn_ok = None
    if lib is not None and hasattr(lib, "ec_prepare_pack"):
        flags = np.zeros(B0, np.uint8)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        # strided C writes: row i's plane lands at base + i*row_width
        lib.ec_prepare_pack(
            ptr(eb), ptr(rb), ptr(sb), ctypes.c_int64(B0),
            ptr(frame[:B0, o_w1:]), ptr(frame[:B0, o_w2:]),
            ctypes.c_int64(frame.strides[0] // 2),
            ctypes.c_int32(1 if recode_device else 0), ptr(flags),
        )
        pre_ok = pub_ok & (flags & 1).astype(bool)
        rpn_ok = (flags & 2).astype(bool)
    elif lib is not None:
        # native without the strided symbol (stale cached .so): int32
        # digit temps + one cast into the frame — still no Python ints
        flags = np.zeros(B0, np.uint8)
        w1 = np.zeros((B0, STEPS), np.int32)
        w2 = np.zeros((B0, STEPS), np.int32)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        lib.ec_prepare(ptr(eb), ptr(rb), ptr(sb), ctypes.c_int64(B0),
                       ptr(w1), ptr(w2), ptr(flags))
        pre_ok = pub_ok & (flags & 1).astype(bool)
        rpn_ok = (flags & 2).astype(bool)
        if recode_device:
            w1, w2 = windows_to_limbs(w1), windows_to_limbs(w2)
        frame[:B0, o_w1:o_w2] = w1
        frame[:B0, o_w2:o_rpn_ok] = w2
    else:  # pure-Python fallback (no toolchain)
        ebuf, rbuf, sbuf = eb.tobytes(), rb.tobytes(), sb.tobytes()
        es = [int.from_bytes(ebuf[32 * i:32 * i + 32], "big")
              for i in range(B0)]
        rints = [int.from_bytes(rbuf[32 * i:32 * i + 32], "big")
                 for i in range(B0)]
        sints = [int.from_bytes(sbuf[32 * i:32 * i + 32], "big")
                 for i in range(B0)]
        pre_ok = np.zeros(B0, bool)
        rpn_ok = np.zeros(B0, bool)
        ss = [1] * B0
        for i, (r, s) in enumerate(zip(rints, sints)):
            pre_ok[i] = bool(pub_ok[i]) and 0 < r < N and 0 < s <= HALF_N
            rpn_ok[i] = (r + N) < P
            ss[i] = s if 0 < s < N else 1
        s_inv = _batch_inv_mod_n(ss)
        u1s = [(e * si) % N for e, si in zip(es, s_inv)]
        u2s = [(r * si) % N for r, si in zip(rints, s_inv)]
        if recode_device:
            frame[:B0, o_w1:o_w2] = _limbs16(u1s)
            frame[:B0, o_w2:o_rpn_ok] = _limbs16(u2s)
        else:
            frame[:B0, o_w1:o_w2] = _windows(u1s)
            frame[:B0, o_w2:o_rpn_ok] = _windows(u2s)

    frame[:B0, :R] = qx_res
    frame[:B0, R:2 * R] = qy_res
    primes = np.array(rns.BASE_A + rns.BASE_B, np.int32)
    n_res = rns._to_res(N, rns.BASE_A + rns.BASE_B)  # int32 already
    scratch = rns.bytes_to_rns(rb)  # [B0, R] int32
    frame[:B0, 2 * R:3 * R] = scratch
    np.mod(scratch + n_res[None, :], primes, out=scratch)
    scratch[~rpn_ok] = 0
    frame[:B0, 3 * R:4 * R] = scratch
    frame[:B0, o_rpn_ok] = rpn_ok
    frame[:B0, o_rpn_ok + 1] = pre_ok
    return frame


def _prepare_cols_pooled(cols, pad_to, pool, recode_device: bool = False):
    """``prepare_cols`` sharded over the host staging pool along the
    lane axis at MIN_BUCKET boundaries.  Bit-equal to the serial call:
    every staged lane is independent (admission flags, window
    recoding, residue dgemm are per-row, and Montgomery batch
    inversion yields the exact per-lane modular inverse regardless of
    how the batch is grouped), so shard outputs ARE the serial output
    rows; the tail pad rows are all-zero/rejected in both forms.
    Pinned by tests/test_p256v3.py.

    The full-size output arrays are preallocated HERE and each worker
    stages its row slab IN PLACE through ``prepare_cols(out=...)`` —
    the admission flags, digit planes and residue dgemm land directly
    in the slab views, so no worker allocates shard outputs and then
    copies them over (the allocate-then-copy made pooled host-recode
    copy-bound on small hosts: one full extra frame copy per batch)."""
    B0 = len(cols[1])
    bounds = pool.slice_bounds(B0, align=MIN_BUCKET)
    if len(bounds) <= 1:
        return prepare_cols(*cols, pad_to=pad_to,
                            recode_device=recode_device)
    Bp = pad_to if pad_to is not None else B0
    R = 2 * rns.N_CH
    wcols = _PK_LIMBS if recode_device else STEPS
    wdt = np.int16 if recode_device else np.int32
    out = (
        np.zeros((Bp, R), np.int32),   # qx_res
        np.zeros((Bp, R), np.int32),   # qy_res
        np.zeros((Bp, R), np.int32),   # r_res
        np.zeros((Bp, R), np.int32),   # rpn_res
        np.zeros((Bp, wcols), wdt),    # w1 digits | u1 limbs
        np.zeros((Bp, wcols), wdt),    # w2 digits | u2 limbs
        np.zeros(Bp, bool),            # rpn_ok
        np.zeros(Bp, bool),            # pre_ok
    )

    def stage(lo, hi):
        prepare_cols(*(c[lo:hi] for c in cols),
                     recode_device=recode_device,
                     out=tuple(d[lo:hi] for d in out))

    pool.map_slices(B0, stage, stage="sig_prepare", align=MIN_BUCKET)
    return out


def _h2d_hist():
    from fabric_tpu.ops_metrics import global_registry

    return global_registry().histogram(
        "h2d_bytes_per_block",
        "packed verify-batch H2D bytes per launch",
        buckets=(1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22,
                 float("inf")),
    )


def _trc():
    from fabric_tpu.observe import global_tracer

    return global_tracer()


def _dev_ann(name: str):
    from fabric_tpu.observe import device_annotation

    return device_annotation(name)


class VerifyHandle:
    """An in-flight verify batch: the device-resident validity vector
    plus a fetch() that syncs to host.  Downstream device stages
    (policy + MVCC fusion) consume ``device_out`` directly so the
    signature bits never cross the device boundary on the critical
    path."""

    __slots__ = ("device_out", "n_real", "rec")

    def __init__(self, device_out, n_real: int, rec=None):
        self.device_out = device_out
        self.n_real = n_real
        # launch-ledger record (observe/ledger.py): fetch() brackets
        # the device sync so the ledger can attribute the wait
        self.rec = rec

    def fetch(self) -> list[bool]:
        rec = self.rec
        if rec is not None:
            rec.sync_begin()
        out = np.asarray(self.device_out)
        if rec is not None:
            rec.sync_end(d2h_bytes=out.nbytes)
        return [bool(v) for v in out[: self.n_real]]

    def __call__(self) -> list[bool]:
        return self.fetch()


def _chunk_metrics():
    from fabric_tpu.ops_metrics import global_registry

    reg = global_registry()
    return (
        reg.histogram(
            "verify_chunk_stage_seconds",
            "per-chunk host staging / dispatch time (s)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, float("inf")),
        ),
        reg.histogram(
            "verify_chunks_per_batch",
            "microbatch chunks per verify batch",
            buckets=(1, 2, 4, 8, 16, 32, float("inf")),
        ),
    )


def _coalesce_metric():
    from fabric_tpu.ops_metrics import global_registry

    return global_registry().histogram(
        "coalesced_blocks_per_launch",
        "signature batches (blocks) concatenated per verify dispatch",
        buckets=(1, 2, 3, 4, 6, 8, float("inf")),
    )


def _shard(mesh, arr):
    """Axis-0 shard one verify dispatch input over the data mesh via the
    ``"verify_lanes"`` partition rule (no-op when mesh is None; ragged
    shapes fall back to single-device and are counted in
    ``mesh_shard_fallback_total``)."""
    if mesh is None:
        return arr
    from fabric_tpu.parallel.mesh import shard

    return shard(mesh, "verify_lanes", arr)


def _chunk_bounds(n_real: int, chunk: int) -> list[tuple[int, int, int]]:
    """[(lo, hi, pad)] microbatch slicing: every chunk except the last
    is EXACTLY ``chunk`` lanes and the last pads the total out to
    ``_bucket(n_real)`` — so item i lives at device index i of the
    concatenated output (no remapping for stage-2 gathers / creator /
    endorsement item indices) AND the concatenated length stays in the
    same bucket family as a monolithic launch, so chunking multiplies
    neither the tail's verify-kernel shapes nor the fused stage-2
    program shapes keyed on it."""
    bounds = []
    off = 0
    total = _bucket(n_real)
    while off < n_real:
        k = min(chunk, n_real - off)
        # intermediate chunks stay exact so global indices hold; the
        # tail absorbs all padding (total - off ≥ k since
        # _bucket(n_real) ≥ n_real)
        pad = chunk if off + k < n_real else total - off
        bounds.append((off, off + k, pad))
        off += k
    return bounds


def _launch_chunked(n_real: int, chunk: int, stage_fn,
                    dispatch_fn=None, pool=None, rec=None) -> VerifyHandle:
    """Microbatched double-buffered dispatch.

    Legacy form (``dispatch_fn`` None): ``stage_fn(lo, hi, pad)``
    stages [lo:hi) on the host AND dispatches it, returning the
    chunk's device output.  Because jax dispatch is asynchronous,
    staging chunk k+1 on the host overlaps chunk k's device compute —
    but only AFTER chunk k's H2D and dispatch were issued from the
    same thread.

    Split form (``dispatch_fn`` given): ``stage_fn(lo, hi, pad)`` is
    host-only (returns the packed launch frame) and ``dispatch_fn``
    ships+launches it.  With a host ``pool``, chunk k+1's staging is
    submitted to a pool worker BEFORE chunk k's dispatch runs on the
    caller thread — one-chunk lookahead, so chunk k+1's staging
    genuinely rides under chunk k's H2D + device compute instead of
    serializing behind the dispatch call (the lookahead worker stages
    its chunk serially; the parallelism comes from the overlap, which
    is why the pipelined commit path finally gives the double
    buffering something to hide).  Without a pool the split form
    degrades to the legacy serial order — CPU-only hosts unchanged.
    """
    stage_hist, chunks_hist = _chunk_metrics()
    bounds = _chunk_bounds(n_real, chunk)
    outs = []
    lookahead = pool is not None and dispatch_fn is not None
    nxt = (pool.submit(stage_fn, *bounds[0], stage="chunk_stage")
           if lookahead else None)
    for i, (lo, hi, pad) in enumerate(bounds):
        t0 = time.perf_counter()
        if dispatch_fn is None:
            out = stage_fn(lo, hi, pad)
        else:
            frame = nxt.result() if lookahead else stage_fn(lo, hi, pad)
            if lookahead and i + 1 < len(bounds):
                # stage k+1 NOW — it overlaps chunk k's H2D + dispatch
                # below and whatever device compute is already queued
                nxt = pool.submit(stage_fn, *bounds[i + 1],
                                  stage="chunk_stage")
            out = dispatch_fn(frame)
        t1 = time.perf_counter()
        stage_hist.observe(t1 - t0, stage="stage_dispatch")
        # per-chunk span on the block timeline (no-op off traced paths)
        _trc().add("verify_chunk", t0, t1, chunk=i, lanes=int(hi - lo))
        outs.append(out)
    chunks_hist.observe(len(bounds))
    dev = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    if hasattr(dev, "copy_to_host_async"):
        dev.copy_to_host_async()
    if rec is not None:
        rec.dispatched()
    return VerifyHandle(dev, n_real, rec)


def _stage_packed(cols, lo, hi, pad, pool, recode_device) -> np.ndarray:
    """Rows [lo, hi) of a column set → the packed int16 launch frame.
    Serial staging takes the single-pass ``prepare_cols_packed`` route
    (no intermediate eight-array allocation, native strided window
    writes); pooled staging keeps the slab-sharded two-phase form
    whose workers already write in place."""
    sl = cols if (lo == 0 and hi == len(cols[1])) else tuple(
        c[lo:hi] for c in cols
    )
    if pool is not None:
        args = _prepare_cols_pooled(sl, pad, pool,
                                    recode_device=recode_device)
        return _pack_launch(args, recode_device, pool=pool)
    return prepare_cols_packed(*sl, pad_to=pad,
                               recode_device=recode_device)


def _launch_cols(n_real, cols, chunk, mesh, pool, recode_device,
                 rec=None):
    """Column-form launch: stage straight into the packed wire frame
    (single-pass serial path, or slab-sharded over the host pool),
    dispatch (sharded), with the H2D frame size observed per
    dispatch."""
    kern = _packed_kernel(recode_device)
    rc = "device" if recode_device else "host"

    def dispatch(packed):
        _h2d_hist().observe(packed.nbytes, recode=rc)
        if rec is not None:
            rec.note_h2d(packed.nbytes)
            # re-anchor at the FIRST actual dispatch: the host
            # wire-frame staging above must not be booked as compile
            # (miss) or dispatch overhead (hit)
            rec.begin_dispatch()
        # the TraceAnnotation lines this dispatch up with the XLA
        # timeline when a jax profiler capture runs (real-TPU rounds)
        with _dev_ann("fabtpu.verify_dispatch"):
            return kern(_shard(mesh, packed))

    if chunk and n_real > chunk:
        # split stage/dispatch: with a host pool, _launch_chunked
        # stages chunk k+1 on a worker while chunk k dispatches (the
        # lookahead overlap the pipelined path needs).  The lookahead
        # worker may still SHARD its chunk across the pool when there
        # are ≥ 2 workers (map_slices from inside a worker completes
        # on the remaining slots); a 1-worker pool would deadlock on
        # itself, so it stages serially there.
        inner = pool if (pool is not None
                         and getattr(pool, "workers", 1) >= 2) else None

        def stage(lo, hi, pad):
            return _stage_packed(cols, lo, hi, pad, inner, recode_device)

        return _launch_chunked(n_real, chunk, stage, dispatch_fn=dispatch,
                               pool=pool, rec=rec)
    packed = _stage_packed(cols, 0, n_real, _bucket(n_real), pool,
                           recode_device)
    out = dispatch(packed)
    if hasattr(out, "copy_to_host_async"):
        out.copy_to_host_async()
    if rec is not None:
        rec.dispatched()
    return VerifyHandle(out, n_real, rec)


def verify_launch(items, chunk: int | None = None, mesh=None, pool=None,
                  recode_device: bool = False) -> VerifyHandle:
    """Asynchronously dispatch a verify batch; returns a VerifyHandle
    (callable as a zero-arg fetch for list[bool]).  The jax dispatch is
    non-blocking, so the device crunches while the caller's host thread
    moves on — the pipeline primitive the block validator builds on.

    Accepts either legacy (digest, r, s, qx, qy) int tuples or a
    SigCollector (the commit path's zero-bigint column form).

    ``chunk``: microbatch size — batches larger than this split into
    chunks dispatched back to back (double-buffered: chunk k+1's host
    staging overlaps chunk k's device compute).  None/0 = one
    monolithic launch.  The accept set is identical either way
    (tests/test_p256v3.py pins chunked ≡ monolithic).

    ``mesh``: a parallel.mesh data mesh — the packed batch is device_put
    with axis 0 sharded over it, so XLA partitions the whole ladder
    across the chips (the verify is per-lane independent: bit-equal to
    single-device, pinned by tests/test_multidevice.py).  None =
    default single-device placement.

    ``pool``: a parallel.hostpool.HostStagePool — the per-signature
    host staging (admission checks, Montgomery batch inversion, window
    recoding, residue dgemm) shards over its workers along the lane
    axis at bucket boundaries; bit-equal to serial staging (pinned by
    tests/test_p256v3.py).  None = serial staging.

    ``recode_device``: ship u1/u2 as 16-bit scalar limbs and derive
    the 4-bit window digits on device (``verify_batch_packed_limbs``),
    shrinking the packed H2D frame (the window planes drop 4×, the
    whole frame ~1.4×); bit-equal to host recoding."""
    # chaos hook (fabric_tpu.faults): a FaultPlan can fail/slow the
    # ops-level dispatch itself — no-op when no plan is armed
    _faults.fire("p256v3.verify_launch")
    chunk = max(int(chunk), MIN_BUCKET) if chunk else 0
    if isinstance(items, (ColumnarSigBatch, SigCollector)):
        if not items.n:
            return VerifyHandle(jnp.zeros((0,), bool), 0)
        n_real = items.n
        cols = (items.assemble() if isinstance(items, ColumnarSigBatch)
                else _assemble_cols(items))
        return _launch_cols(n_real, cols, chunk, mesh, pool,
                            recode_device, rec=_verify_rec(n_real, chunk,
                                                           mesh,
                                                           recode_device))
    items = list(items)
    if not items:
        return VerifyHandle(jnp.zeros((0,), bool), 0)
    n_real = len(items)
    rec = _verify_rec(n_real, chunk, mesh, recode_device)
    if pool is not None or recode_device:
        # pooled staging and device recoding are COLUMN lanes: lift
        # legacy tuples into the column form (accept-set equal — the
        # chunked/coalesced differentials already pin this route)
        n_real, cols = _to_cols(items)
        return _launch_cols(n_real, cols, chunk, mesh, pool,
                            recode_device, rec=rec)
    if chunk and n_real > chunk:
        def stage(lo, hi, pad):
            return verify_batch_jit(
                *(_shard(mesh, a) for a in prepare(items[lo:hi], pad_to=pad))
            )

        return _launch_chunked(n_real, chunk, stage, rec=rec)
    args = prepare(items, pad_to=_bucket(n_real))
    if rec is not None:
        rec.note_h2d(sum(a.nbytes for a in args))
        rec.begin_dispatch()  # prepare() above was host staging
    if mesh is not None:
        args = tuple(_shard(mesh, a) for a in args)
    with _dev_ann("fabtpu.verify_dispatch"):
        out = verify_batch_jit(*args)  # async under deferred execution
    if hasattr(out, "copy_to_host_async"):
        # start the D2H as soon as compute finishes: device→host
        # readback latency is substantial on tunneled devices and must
        # overlap the caller's host work, not serialize behind it
        out.copy_to_host_async()
    if rec is not None:
        rec.dispatched()
    return VerifyHandle(out, n_real, rec)


def _verify_rec(n_real: int, chunk: int, mesh, recode_device: bool):
    """Open a launch-ledger record for one verify dispatch (None when
    the ledger is disarmed — a single global read + None check).  The
    structural key drives the ledger's first-seen compile inference:
    the jitted kernel retraces per (padded bucket or chunk shape,
    recode variant, mesh layout)."""
    shape = chunk if (chunk and n_real > chunk) else _bucket(n_real)
    return _ledger.launch(
        "verify",
        key=(shape, bool(recode_device),
             mesh.size if mesh is not None else 0),
        lanes=n_real,
    )


def _to_cols(items):
    """Any verify_launch input form → (n_real, six prepare_cols
    column arrays)."""
    if isinstance(items, ColumnarSigBatch):
        return items.n, items.assemble()
    if isinstance(items, SigCollector):
        return items.n, _assemble_cols(items)
    c = SigCollector()
    for it in items:
        c.add_slow(it)
    return c.n, _assemble_cols(c)


def verify_launch_many(batches, chunk: int | None = None,
                       mesh=None, pool=None,
                       recode_device: bool = False) -> list[VerifyHandle]:
    """Coalesced dispatch of SEVERAL blocks' signature batches as ONE
    device launch, amortizing the 64-step ladder's dispatch latency
    across the blocks the pipeline has in flight.

    Layout: block b's items occupy device indices
    [off_b, off_b + _bucket(n_b)) of the concatenated batch — each
    block keeps the exact lane layout a solo ``verify_launch`` would
    give it (item i at local index i, padded to its own bucket), so the
    returned per-block VerifyHandles expose ``device_out`` slices that
    stage-2 and the committer consume unchanged, with unchanged
    program-cache shapes.  The total is padded out to
    ``_bucket(Σ buckets)`` so the coalesced dispatch stays inside the
    same bucket family as monolithic launches.

    Composes with ``chunk`` (the concatenated batch microbatches like
    any other), ``mesh`` (axis-0 sharding), ``pool`` (host staging
    sharded over cores) and ``recode_device`` (limb wire form + device
    window recoding).  Accept-set-equivalence vs per-block launches is
    pinned by tests/test_p256v3.py."""
    batches = [
        b if isinstance(b, (ColumnarSigBatch, SigCollector)) else list(b)
        for b in batches
    ]
    sizes, colsets = [], []
    for b in batches:
        n, cols = (0, None) if _batch_len(b) == 0 else _to_cols(b)
        sizes.append(n)
        colsets.append(cols)
    live = [(n, cols) for n, cols in zip(sizes, colsets) if n]
    if not live:
        return [VerifyHandle(jnp.zeros((0,), bool), 0) for _ in batches]
    if len(live) == 1:
        # nothing to coalesce: solo launch for the one non-empty block
        _coalesce_metric().observe(1)
        out = []
        for b, n in zip(batches, sizes):
            out.append(
                verify_launch(b, chunk=chunk, mesh=mesh, pool=pool,
                              recode_device=recode_device) if n
                else VerifyHandle(jnp.zeros((0,), bool), 0)
            )
        return out

    # chaos hook — fired here (not at function entry) so the solo
    # delegation above doesn't double-count against a fault budget
    _faults.fire("p256v3.verify_launch")
    # concatenate per-block columns, each padded to its own bucket
    offs, total = [], 0
    for n in sizes:
        offs.append(total)
        total += _bucket(n) if n else 0
    grand = _bucket(total)
    cat = []
    for ci in range(6):
        ref = live[0][1][ci]
        col = np.zeros((grand,) + ref.shape[1:], ref.dtype)
        for off, n, cols in zip(offs, sizes, colsets):
            if n:
                col[off:off + n] = cols[ci]
        cat.append(col)
    _coalesce_metric().observe(len(live))

    chunk = max(int(chunk), MIN_BUCKET) if chunk else 0
    # all `grand` lanes are "real" to the chunker (padding lanes are
    # pre-rejected rows); its tail invariant pads to
    # _bucket(grand) == grand
    inner = _launch_cols(grand, tuple(cat), chunk, mesh, pool,
                         recode_device,
                         rec=_verify_rec(grand, chunk, mesh,
                                         recode_device))
    dev = inner.device_out
    out = [
        VerifyHandle(dev[off:off + _bucket(n)], n) if n
        else VerifyHandle(jnp.zeros((0,), bool), 0)
        for off, n in zip(offs, sizes)
    ]
    # ONE ledger record covers the coalesced dispatch: the first live
    # block's fetch closes it (slices sync the shared computation)
    for h in out:
        if h.n_real:
            h.rec = inner.rec
            break
    return out


def _batch_len(items) -> int:
    if isinstance(items, (ColumnarSigBatch, SigCollector)):
        return items.n
    return len(items)


def verify_host(items) -> list[bool]:
    """items: iterable of (digest_int, r, s, qx, qy) Python ints —
    same interface and accept set as ops.p256.verify_host."""
    return verify_launch(items)()

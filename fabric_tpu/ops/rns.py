"""Residue-number-system (RNS) modular arithmetic for the MXU — the
"Cox-Rower" design (Kawamura et al., CHES 2000) that dedicated ECC
hardware uses, re-expressed as TPU matmuls.

Why RNS beats digit-polynomial arithmetic (ops.digits) on TPU: in RNS a
256-bit value is its residues modulo ~23 small coprime primes, so a
big-int multiply is an ELEMENTWISE lane-wise product — no convolution
at all.  The only non-elementwise step is Montgomery reduction's base
extension, which is a DENSE [B, 2n] @ [2n, 3n+…] matmul against a
constant matrix — exactly the shape the MXU wants.  Contrast
ops.digits.mul: a [B, K²=1849] @ [1849, 85] one-hot contraction that
wastes ~99% of its MXU flops on structural zeros and needs HIGHEST
(multi-pass) precision.  Here every matmul input is a 6-bit chunk, so
single-pass bf16×bf16→f32 MXU arithmetic is EXACT by construction:
products ≤ 63·63 < 2^12, accumulated over ≤ 2n=46 rows < 2^18 « 2^24.

Representation.  Two bases A = {m_1..m_n}, B = {m'_1..m'_n} of 12-bit
primes, M = ΠA, M' = ΠB (each ≈ 2^276 » 4·2^256).  A value v (a
non-negative integer with a TRACKED Python-int bound, far below M·M')
is carried as its 2n canonical residues [..., 2n] int32.  Montgomery
multiplication (x, y) → x·y·M⁻¹ mod p follows Kawamura:

  t   = x ⊙ y                     (lane products, both bases)
  q   = t ⊙ (−p⁻¹) mod m_i        (base A lanes)
  q̂   : A → B base extension with a DOWN-BIASED rank α̂ = ⌊s − ε⌋ —
        q̂ ∈ {q, q+M}; the slack only adds one p to the result
  r   = (t + q̂·p) · M⁻¹ mod m'_j  (base B lanes) — r < 2p + 1
  r   : B → A base extension with an EXACT rank α = ⌊s + ¼⌋, exact
        because r < 3p « M'/4 (Kawamura's condition with margin ½)

Base extension v → ξ_i = v_i·(M/m_i)⁻¹ mod m_i, then
v = Σ ξ_i·(M/m_i) − α·M where α = ⌊Σ ξ_i/m_i⌋ computed in f32 (error
≈ n·2⁻²³ « ¼).  The Σ ξ_i·(M/m_i) mod m'_j term is the dense matmul:
inputs are ξ split into 6-bit chunks, weights are (M/m_i mod m'_j)
split into 6-bit chunks, three output columns per target prime
(lo·lo | lo·hi+hi·lo | hi·hi) recombined with shifts in int32.

Per-lane modular reduction by the prime vector uses the float
reciprocal trick (t < 2^24 exact in f32; quotient error ≤ 1 fixed by
one conditional add/sub), so there is no integer division anywhere.

Reference semantics anchored: this module exists to make
bccsp/sw/ecdsa.go:41-58's accept set fast; bit-exactness is enforced
by tests/test_rns.py property tests against Python ints (CRT
reconstruction of every result).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Base construction (module constants: both ECDSA moduli share the bases)

N_CH = 23          # primes per base
CHUNK = 6          # bits per matmul chunk
CMASK = (1 << CHUNK) - 1


def _primes_below(limit: int, count: int) -> list[int]:
    sieve = np.ones(limit, dtype=bool)
    sieve[:2] = False
    for i in range(2, int(limit ** 0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = False
    ps = np.nonzero(sieve)[0][::-1]  # descending
    return [int(p) for p in ps[:count]]


_ALL = _primes_below(1 << 12, 2 * N_CH)  # largest 46 primes under 2^12
BASE_A = _ALL[0::2]
BASE_B = _ALL[1::2]
M_A = 1
for _p in BASE_A:
    M_A *= _p
M_B = 1
for _p in BASE_B:
    M_B *= _p
assert M_A > 1 << 270 and M_B > 1 << 270

_EPS_DOWN = 32 * N_CH / (1 << 23)  # conservative f32 rank-sum error bound


def _to_res(x: int, primes) -> np.ndarray:
    return np.array([x % m for m in primes], np.int32)


class _Ext:
    """Constants for one direction of base extension src → dst."""

    def __init__(self, src: list[int], dst: list[int]):
        n = len(src)
        M = 1
        for m in src:
            M *= m
        self.M = M
        # ξ_i = v_i · (M/m_i)^{-1} mod m_i
        self.inv_w = np.array(
            [pow(M // m, -1, m) for m in src], np.int32
        )
        # W[i, j] = (M/m_i) mod dst_j, 6-bit chunked into the
        # (lo·lo | lo·hi + hi·lo | hi·hi) three-block weight matrix
        C = np.array([[(M // mi) % mj for mj in dst] for mi in src], np.int64)
        c_lo, c_hi = C & CMASK, C >> CHUNK
        nd = len(dst)
        W = np.zeros((2 * n, 3 * nd), np.float32)
        W[:n, 0:nd] = c_lo          # ξ_lo · c_lo
        W[:n, nd:2 * nd] = c_hi     # ξ_lo · c_hi
        W[n:, nd:2 * nd] = c_lo     # ξ_hi · c_lo
        W[n:, 2 * nd:] = c_hi       # ξ_hi · c_hi
        self.W = jnp.asarray(W, jnp.bfloat16)
        # α correction: M mod dst_j, plus a non-negativity offset
        self.M_mod_dst = np.array([M % mj for mj in dst], np.int64)
        self.alpha_max = n + 1
        self.inv_src_f32 = jnp.asarray(
            np.array([1.0 / m for m in src], np.float32)
        )


class Modulus:
    """Per-channel constants for one base (or both stacked)."""

    def __init__(self, primes: list[int]):
        self.primes = list(primes)
        self.m = jnp.asarray(np.array(primes, np.int32))
        self.m_f32 = self.m.astype(jnp.float32)
        self.inv_f32 = jnp.asarray(np.array([1.0 / m for m in primes], np.float32))
        self.c20 = jnp.asarray(
            np.array([(1 << 20) % m for m in primes], np.int32)
        )

    def rem24(self, t):
        """t int32 in [0, 2^24) → t mod m, exact (float reciprocal +
        one-step correction)."""
        q = jnp.floor(t.astype(jnp.float32) * self.inv_f32).astype(jnp.int32)
        r = t - q * self.m
        r = r + jnp.where(r < 0, self.m, 0)
        return r - jnp.where(r >= self.m, self.m, 0)

    def rem30(self, t):
        """t int32 in [0, 2^30) → t mod m (one 2^20 fold, then rem24)."""
        folded = (t >> 20) * self.c20 + (t & ((1 << 20) - 1))
        return self.rem24(folded)

    def mulmod_const(self, a, c_i32):
        """a canonical [.., n] times per-channel constant < m."""
        return self.rem24(a * c_i32)


MOD_A = Modulus(BASE_A)
MOD_B = Modulus(BASE_B)
MOD_ALL = Modulus(BASE_A + BASE_B)

EXT_AB = _Ext(BASE_A, BASE_B)
EXT_BA = _Ext(BASE_B, BASE_A)


def _extend(v, ext: _Ext, dst: Modulus, exact: bool):
    """Base extension: v [..., n] canonical residues of an integer
    < ext.M (exact mode: < ext.M/4) → [..., n_dst] canonical residues.

    exact=False: rank down-biased; result represents v or v + ext.M.
    exact=True:  result represents v exactly (caller guarantees the
    bound margin)."""
    n = v.shape[-1]
    xi = _xi(v, ext)
    s = jnp.sum(xi.astype(jnp.float32) * ext.inv_src_f32, axis=-1)
    if exact:
        alpha = jnp.floor(s + 0.25).astype(jnp.int32)
    else:
        alpha = jnp.floor(s - _EPS_DOWN).astype(jnp.int32)
        alpha = jnp.maximum(alpha, 0)
    chunks = jnp.concatenate([xi & CMASK, xi >> CHUNK], axis=-1)
    out3 = jax.lax.dot_general(
        chunks.astype(jnp.bfloat16), ext.W,
        (((chunks.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    nd = len(dst.primes)
    raw = out3[..., :nd] + (out3[..., nd:2 * nd] << CHUNK) + (
        out3[..., 2 * nd:] << (2 * CHUNK)
    )
    # keep raw − α·(M mod m_j) non-negative: add α_max·m_j (≡ 0 mod m_j)
    corr = jnp.asarray(
        (ext.alpha_max * np.array(dst.primes, np.int64)).astype(np.int32)
    )
    raw = raw + corr - alpha[..., None] * jnp.asarray(
        ext.M_mod_dst.astype(np.int32)
    )
    return dst.rem30(raw)


def _xi(v, ext: _Ext):
    """ξ_i = v_i · (M/m_i)^{-1} mod m_i on the SOURCE channels."""
    src_mod = MOD_A if ext is EXT_AB else MOD_B
    return src_mod.mulmod_const(v, jnp.asarray(ext.inv_w))


# ---------------------------------------------------------------------------
# Montgomery context for one odd modulus p (p or the group order n)


class MontCtx:
    """Montgomery-RNS context: x̃ = x·M_A mod p domain over BASE_A."""

    def __init__(self, p: int):
        # all constants numpy (concrete): a MontCtx may be constructed
        # lazily inside a jit trace and cached across traces — jnp
        # arrays created there would be leaked tracers
        self.p = p
        self.neg_p_inv_A = np.array(
            [(-pow(p, -1, m)) % m for m in BASE_A], np.int32
        )
        self.p_B = _to_res(p, BASE_B)
        self.invMA_B = np.array(
            [pow(M_A % m, -1, m) for m in BASE_B], np.int32
        )
        self.RR = to_rns((M_A * M_A) % p)        # Montgomery entry constant
        self.ONE = to_rns(1)
        self.p_res = np.concatenate([_to_res(p, BASE_A), _to_res(p, BASE_B)])
        self._lam_cache: dict[int, jnp.ndarray] = {}

    def lam_p(self, lam: int) -> np.ndarray:
        """Canonical residues of λ·p (subtraction offsets)."""
        got = self._lam_cache.get(lam)
        if got is None:
            # numpy (concrete), NOT jnp: this cache outlives traces —
            # a jnp array created inside a jit trace is a tracer and
            # leaking it across traces is an error
            got = np.concatenate([
                _to_res(lam * self.p, BASE_A), _to_res(lam * self.p, BASE_B)
            ])
            self._lam_cache[lam] = got
        return got


CTX_CACHE: dict[int, MontCtx] = {}


def ctx_for(p: int) -> MontCtx:
    if p not in CTX_CACHE:
        CTX_CACHE[p] = MontCtx(p)
    return CTX_CACHE[p]


# ---------------------------------------------------------------------------
# RV: residues + trace-time integer bound


class RV:
    """An RNS value: [..., 2n] int32 canonical residues (base A ‖ B)
    plus a Python-int bound on the represented non-negative integer.
    The bound rides along tracing, so Montgomery/extension preconditions
    are asserted while BUILDING the jaxpr (cf. ops.p256v2.FV)."""

    __slots__ = ("arr", "bound")

    def __init__(self, arr, bound: int):
        self.arr = arr
        self.bound = int(bound)

    def __add__(self, other: "RV") -> "RV":
        t = self.arr + other.arr
        m = MOD_ALL.m
        return RV(t - jnp.where(t >= m, m, 0), self.bound + other.bound)


def rv_sub(x: RV, y: RV, ctx: MontCtx) -> RV:
    """x − y (mod p) kept non-negative by adding ⌈y.bound/p⌉·p."""
    lam = -(-y.bound // ctx.p)
    t = x.arr + ctx.lam_p(lam) - y.arr
    m = MOD_ALL.m
    t = t - jnp.where(t >= m, m, 0)
    t = t + jnp.where(t < 0, m, 0)
    return RV(t, x.bound + lam * ctx.p)


def _mont_mul_arr(xa, ya, ctx: MontCtx):
    """Array-level Montgomery pipeline on [..., 2n] canonical residues
    (leading dims arbitrary — the stacked-mul path rides them)."""
    t = MOD_ALL.rem24(xa * ya)
    n = N_CH
    tA, tB = t[..., :n], t[..., n:]
    q = MOD_A.mulmod_const(tA, ctx.neg_p_inv_A)
    qB = _extend(q, EXT_AB, MOD_B, exact=False)   # q or q + M_A
    u = MOD_B.mulmod_const(qB, ctx.p_B)
    num = MOD_B.rem24(tB + u)
    rB = MOD_B.mulmod_const(num, ctx.invMA_B)
    rA = _extend(rB, EXT_BA, MOD_A, exact=True)
    return jnp.concatenate([rA, rB], axis=-1)


def _mul_bound(x: RV, y: RV, ctx: MontCtx) -> int:
    T = x.bound * y.bound
    # extension-margin preconditions (trace-time)
    assert T // M_A + ctx.p < M_B // 4, "r-extension margin violated"
    assert T < M_A * M_B // 8, "product overflows the RNS range"
    return T // M_A + 2 * ctx.p + 1


def mont_mul(x: RV, y: RV, ctx: MontCtx) -> RV:
    """x·y·M_A⁻¹ mod p (Montgomery step); output bound
    x.b·y.b/M_A + 2p + 1 < 3p for all sane inputs."""
    out_bound = _mul_bound(x, y, ctx)
    return RV(_mont_mul_arr(x.arr, y.arr, ctx), out_bound)


def mont_mul_many(pairs, ctx: MontCtx) -> list:
    """k independent Montgomery muls as ONE stacked pipeline.

    The point formulas have 2-6 independent muls per stage; stacking
    them turns k tiny [B,46]@[46,72] matmuls into one [k·B,46]@[46,72]
    — same flops, ~k× fewer dispatches and better MXU occupancy.
    Operands are broadcast to a common shape before stacking
    (constants ride along as [2n] rows)."""
    bounds = [_mul_bound(x, y, ctx) for x, y in pairs]
    shape = np.broadcast_shapes(*(
        np.shape(v.arr) for pair in pairs for v in pair
    ))
    xs = jnp.stack([jnp.broadcast_to(x.arr, shape) for x, _ in pairs])
    ys = jnp.stack([jnp.broadcast_to(y.arr, shape) for _, y in pairs])
    out = _mont_mul_arr(xs, ys, ctx)
    return [RV(out[i], b) for i, b in enumerate(bounds)]


def to_mont(x: RV, ctx: MontCtx) -> RV:
    return mont_mul(x, ctx.RR, ctx)


def from_mont(x: RV, ctx: MontCtx) -> RV:
    return mont_mul(x, ctx.ONE, ctx)


def eq_const_mod_p(x: RV, ctx: MontCtx):
    """x ≡ 0 (mod p) for x = a Montgomery-domain value: reduce with a
    mont-by-one (strips M_A, bound < 3p) then compare residues against
    0, p and 2p exactly."""
    w = from_mont(x, ctx)
    assert w.bound <= 3 * ctx.p
    hits = jnp.all(w.arr == 0, axis=-1)
    for k in (1, 2):
        cres = _to_res(k * ctx.p, BASE_A + BASE_B)
        hits = hits | jnp.all(w.arr == cres, axis=-1)
    return hits


# ---------------------------------------------------------------------------
# Host conversions (numpy, vectorized — no per-digit Python loops)

_POW8 = None


def _pow8_table() -> np.ndarray:
    """[40, 2n] float64: 2^(8k) mod m for the limb contraction."""
    global _POW8
    if _POW8 is None:
        primes = BASE_A + BASE_B
        _POW8 = np.array(
            [[pow(2, 8 * k, m) for m in primes] for k in range(40)], np.float64
        )
    return _POW8


def ints_to_rns(xs) -> np.ndarray:
    """[B] Python ints (< 2^320) → [B, 2n] canonical residues.

    The limb contraction runs in float64 (BLAS dgemm — numpy's int64
    matmul is a scalar loop): 8-bit limbs × 12-bit table entries summed
    over 40 limbs stay < 2^43, exact in f64's 53-bit mantissa."""
    if not len(xs):
        return np.zeros((0, 2 * N_CH), np.int32)
    limbs = np.frombuffer(
        b"".join(int(x).to_bytes(40, "little") for x in xs), np.uint8
    ).reshape(len(xs), 40).astype(np.float64)
    acc = limbs @ _pow8_table()  # [B, 2n] exact in f64
    primes = np.array(BASE_A + BASE_B, np.int64)
    return (acc.astype(np.int64) % primes).astype(np.int32)


def bytes_to_rns(be: np.ndarray, out: np.ndarray | None = None) -> np.ndarray:
    """[B, 32] uint8 BIG-endian 256-bit values → [B, 2n] canonical
    residues — the zero-Python-int fast lane for values the native
    pre-parser already delivers as byte arrays (r, s, digests).  Same
    f64 dgemm as ints_to_rns; bytes reverse to little-endian limbs.

    ``out``: optional [B, 2n] int32 destination written in place (the
    pooled staging path hands row-slab views here so the residues land
    directly in the preallocated launch columns); returned either way."""
    if not len(be):
        return out if out is not None else np.zeros((0, 2 * N_CH), np.int32)
    le = be[:, ::-1].astype(np.float64)  # [B, 32] little-endian limbs
    acc = le @ _pow8_table()[:32]  # [B, 2n] exact in f64
    primes = np.array(BASE_A + BASE_B, np.int64)
    res = acc.astype(np.int64) % primes
    if out is not None:
        out[:] = res  # same values, cast into the caller's int32 slab
        return out
    return res.astype(np.int32)


def to_rns(x: int) -> RV:
    """Single constant → broadcastable RV (numpy-backed: constants
    must stay concrete across jit traces)."""
    return RV(_to_res(x, BASE_A + BASE_B), x)


def rv_to_ints(arr) -> list[int]:
    """CRT reconstruction over all 2n channels (tests/oracles only)."""
    primes = BASE_A + BASE_B
    Mall = M_A * M_B
    coeffs = [(Mall // m) * pow(Mall // m, -1, m) for m in primes]
    a = np.asarray(arr).reshape(-1, 2 * N_CH)
    return [
        sum(int(r) * c for r, c in zip(row, coeffs)) % Mall for row in a
    ]

"""Modular big-int arithmetic in a signed-digit representation built
for the TPU's MXU, shared by the ECDSA kernels (mod p and mod n).

Why not the classic Montgomery-limb form (fabric_tpu.ops.p256): CIOS
REDC is a 16-step *serial* dependency chain of tiny vector ops, so the
ladder's depth — not the batch width — dominates wall-clock on TPU
(round-2 bench: 0.406× one CPU thread).  This module reformulates
field multiplication so the heavy lifting is matrix multiplies:

* A value is K=43 little-endian signed base-2^6 digits in int32 lanes
  (canonical digits are 0..63; intermediates may run negative or above
  64 — the representation is redundant, only the value mod m matters).
* Digit products stay well under 2^24, so polynomial multiplication is
  EXACT in float32 — outer product + one [B,K²]@[K²,2K-1] one-hot
  contraction (MXU) per mul.
* Modular reduction is LINEAR over the high columns: column k carries
  c_k·2^(6k) and 2^(6k) mod m is a constant — so reduction is one
  [B,·]@[·,K] matmul against a precomputed chunk matrix (MXU again),
  not a serial REDC chain.
* Carry normalization ("settle") is a short fixed schedule of
  shift/mask passes and sparse balanced-digit folds (VPU elementwise);
  addition/subtraction are plain elementwise ± with NO carries.

Exactness discipline: float32 represents integers exactly up to 2^24;
`bound_check()` runs interval arithmetic over the exact op schedule and
certifies (a) every f32 matmul's worst-case |partial sum| < 2^24 and
(b) settled digits meet the documented bounds.  The property tests
(tests/test_p256v2.py) additionally drive adversarial max-magnitude
inputs and compare bit-exactly against Python ints.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

W = 6                      # bits per digit
BASE = 1 << W              # 64
DMASK = BASE - 1
K = 43                     # digits per 256-bit value (43*6 = 258 bits)
PRODCOLS = 2 * K - 1       # columns of a KxK digit product
F32_EXACT = (1 << 24) - 1  # largest guaranteed-exact f32 integer range

# settle schedule: rounds of (passes, chunked fold) plus a final
# (pass, fold) tidy stage; certified by bound_check()
SETTLE_PASSES = 3
SETTLE_ROUNDS = 3

# |digit| bound contract for mul inputs: |a|_inf * |b|_inf * K < 2^24
# is sufficient (columns sum at most K products).  SETTLED <= 96 is
# certified by bound_check(); inputs may be sums of up to 6 settled
# values on either side ((6*96)^2 * 43 < 2^24).
SETTLED_MAX = 96
assert (6 * SETTLED_MAX) ** 2 * K < 1 << 24


def int_to_digits(x: int) -> np.ndarray:
    return np.array([(x >> (W * i)) & DMASK for i in range(K)], np.int32)


def ints_to_digits(xs) -> np.ndarray:
    if not len(xs):
        return np.zeros((0, K), np.int32)
    return np.stack([int_to_digits(int(x)) for x in xs])


def digits_to_int(row) -> int:
    return sum(int(d) << (W * i) for i, d in enumerate(np.asarray(row)))


def _balanced_digits(x: int, n: int) -> np.ndarray:
    """n signed digits in [-32, 32] representing x (minimizes |digit|,
    so folds re-inject as little magnitude as possible)."""
    out = np.zeros(n, np.int64)
    for i in range(n):
        d = x & DMASK
        if d > BASE // 2:
            d -= BASE
        out[i] = d
        x = (x - d) >> W
    assert x == 0, "balanced_digits overflow"
    return out.astype(np.int32)


class DigitMod:
    """Precomputed reduction/fold matrices for one modulus m < 2^257."""

    def __init__(self, m: int):
        self.m = m
        self.digits = jnp.asarray(int_to_digits(m))
        # product-column reduction: high cols K..PRODCOLS-1 are split
        # into 6-bit chunks lo/mid/hi; row (c*H + k) holds the balanced
        # digits of 2^(6(K+k+c)) mod m.
        H = PRODCOLS - K
        self._H = H
        R = np.zeros((3 * H, K), np.float32)
        for k in range(H):
            for c in range(3):
                R[c * H + k] = _balanced_digits(pow(2, W * (K + k + c), m), K)
        self._R = jnp.asarray(R)
        # settle fold rows: balanced digits of 2^(6(K+j)) mod m for the
        # carry-out columns a settle round accumulates
        F = np.stack([
            _balanced_digits(pow(2, W * (K + j), m), K)
            for j in range(SETTLE_PASSES + 1)
        ])
        self._F = jnp.asarray(F.astype(np.int32))
        self._Fnp = F.astype(np.int64)
        self._Rnp = np.asarray(R, np.int64)

    # -- core ops (all shapes [..., K] int32) -----------------------------

    def mul(self, a, b):
        """a*b mod m value-wise; output settled (|d| <= SETTLED_MAX).

        Caller contract: |a|_inf * |b|_inf * K < 2^24 (e.g. both
        operands are settled values or <= 3-way sums of them)."""
        af = a.astype(jnp.float32)
        bf = b.astype(jnp.float32)
        o = (af[..., :, None] * bf[..., None, :]).reshape(*a.shape[:-1], K * K)
        cols = jax.lax.dot_general(
            o, _SHIFT_ONEHOT,
            (((o.ndim - 1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        low, high = cols[..., :K], cols[..., K:]
        hlo = high & DMASK
        hmid = (high >> W) & DMASK
        hhi = high >> (2 * W)
        chunks = jnp.concatenate([hlo, hmid, hhi], axis=-1).astype(jnp.float32)
        red = jax.lax.dot_general(
            chunks, self._R,
            (((chunks.ndim - 1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        return self.settle(low + red)

    def settle(self, t):
        """Carry-normalize [..., K] int32 (|d| < 2^24) to
        |d| <= SETTLED_MAX, value preserved mod m (schedule certified
        by bound_check).

        Each pass drops every digit to [0,63] plus an incoming carry;
        pass carry-outs all have weight 2^(6K) (the width stays K), so
        their sum folds back via the F rows.  The fold CHUNKS the top
        into 6-bit pieces first — folding a large top directly would
        re-inject ~top·32 magnitude and never converge."""
        for _ in range(SETTLE_ROUNDS):
            top = None
            for _p in range(SETTLE_PASSES):
                lo = t & DMASK
                carry = t >> W
                t = lo + jnp.pad(carry[..., :-1], _pad_width(t.ndim))
                top = carry[..., -1] if top is None else top + carry[..., -1]
            t0 = (top & DMASK)[..., None]
            t1 = ((top >> W) & DMASK)[..., None]
            t2 = (top >> (2 * W))[..., None]
            t = t + t0 * self._F[0] + t1 * self._F[1] + t2 * self._F[2]
        # tidy stage: by now digits are small enough that one pass
        # leaves a |top| <= 1ish carry, folded without chunking
        lo = t & DMASK
        carry = t >> W
        t = lo + jnp.pad(carry[..., :-1], _pad_width(t.ndim))
        t = t + carry[..., -1:] * self._F[0]
        return t

    def condense(self, t):
        """Settle for values accumulated by adds/subs between muls."""
        return self.settle(t)

    def canonical(self, t):
        """Fully canonical digits of (value mod m): digits in [0,63],
        value in [0, m).  Kernel edges only (final compare, infinity
        test) — one sequential sweep over K digits, not the hot loop."""
        t = self.settle(t)

        def sweep(carry, d):
            v = d + carry
            return v >> W, v & DMASK

        carry0 = jnp.zeros(t.shape[:-1], jnp.int32)
        # negative or >=2^(6K) values need the top carry folded back
        # in; settled values have |value| < 8*2^258, and the worst
        # quotient chain (7 -> 1 -> 1 -> 0) dies within three
        # fold+sweep rounds
        for _ in range(3):
            over, dig = jax.lax.scan(sweep, carry0, jnp.moveaxis(t, -1, 0))
            t = jnp.moveaxis(dig, 0, -1) + over[..., None] * self._F[0]
        over, dig = jax.lax.scan(sweep, carry0, jnp.moveaxis(t, -1, 0))
        t = jnp.moveaxis(dig, 0, -1)
        # value in [0, 2^258); subtract m up to 4 times (2^258 < 5m for
        # both P-256 moduli)
        for _ in range(4):
            ge = self._geq(t, self.digits)
            t = t - jnp.where(ge[..., None], self.digits, 0)
            _, dig = jax.lax.scan(sweep, carry0, jnp.moveaxis(t, -1, 0))
            t = jnp.moveaxis(dig, 0, -1)
        return t

    @staticmethod
    def _geq(a, b):
        """a >= b over canonical digit arrays (b broadcastable)."""
        bb = jnp.broadcast_to(b, a.shape)

        def step(state, pair):
            ai, bi = pair
            gt, lt = state
            gt_new = gt | (~gt & ~lt & (ai > bi))
            lt_new = lt | (~gt & ~lt & (ai < bi))
            return (gt_new, lt_new), 0.0

        init = (
            jnp.zeros(a.shape[:-1], bool),
            jnp.zeros(a.shape[:-1], bool),
        )
        (gt, lt), _ = jax.lax.scan(
            step, init,
            (jnp.moveaxis(a[..., ::-1], -1, 0), jnp.moveaxis(bb[..., ::-1], -1, 0)),
        )
        return gt | ~lt

    def eq_zero(self, t):
        """value ≡ 0 (mod m), any representation."""
        return jnp.all(self.canonical(t) == 0, axis=-1)

    # -- bound certification (numpy interval arithmetic) ------------------

    def bound_check(self, a_bound: int = SETTLED_MAX * 3,
                    b_bound: int = SETTLED_MAX * 3):
        """Interval-arithmetic certification of the mul+settle schedule.

        Walks the exact op sequence of `mul` with per-digit magnitude
        bounds and asserts every f32 contraction stays under 2^24 and
        the settled output meets SETTLED_MAX.  a_bound/b_bound are the
        largest |digit| the caller feeds each operand (default: 3-way
        sums of settled values)."""
        prod = a_bound * b_bound
        assert prod * K < (1 << 24), ("f32 product contraction", prod * K)
        colbound = prod * K
        H = self._H
        Rabs = np.abs(self._Rnp)
        hi_max = colbound >> (2 * W)
        per_digit = (
            63 * Rabs[:H].sum(axis=0)
            + 63 * Rabs[H:2 * H].sum(axis=0)
            + hi_max * Rabs[2 * H:].sum(axis=0)
        )
        worst_col = int(per_digit.max())
        assert worst_col < (1 << 24), ("f32 reduction contraction", worst_col)
        t = np.full(K, colbound + worst_col, np.int64)  # low + red
        out = self._settle_bound(t)
        assert out <= SETTLED_MAX, ("settled bound", out)
        return out

    def _settle_bound(self, t) -> int:
        """Interval image of settle() for a per-digit bound vector."""
        Fabs = np.abs(self._Fnp)
        for _ in range(SETTLE_ROUNDS):
            top = 0
            for _p in range(SETTLE_PASSES):
                carry = t >> W
                t = np.concatenate([[0], carry[:-1]]) + DMASK
                top = top + int(carry[-1])
            fold = (
                min(top, DMASK) * Fabs[0]
                + min(top >> W, DMASK) * Fabs[1]
                + (top >> (2 * W)) * Fabs[2]
            )
            t = t + fold
        carry = t >> W
        t = np.concatenate([[0], carry[:-1]]) + DMASK
        t = t + int(carry[-1]) * Fabs[0]
        return int(t.max())

def _pad_width(ndim):
    return [(0, 0)] * (ndim - 1) + [(1, 0)]


def _build_shift_onehot() -> jnp.ndarray:
    """[K*K, 2K-1] one-hot: product term (i,j) lands in column i+j."""
    S = np.zeros((K * K, PRODCOLS), np.float32)
    for i in range(K):
        for j in range(K):
            S[i * K + j, i + j] = 1.0
    return jnp.asarray(S)


_SHIFT_ONEHOT = _build_shift_onehot()

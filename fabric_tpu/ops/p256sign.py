"""Batched ECDSA P-256 SIGNING on device — the endorsement lane.

The verify kernel (ops/p256v3) accelerates the validate/commit half of
execute-order-validate; this module opens the other half: the endorser
ECDSA-signs every proposal response before ordering ever sees it, and
at millions of clients that signing is the upstream bottleneck.

Signing is the EASY half of the ladder machinery v3 already has:

* ``R = k·G`` is a FIXED-BASE scalar multiplication — the base point
  never changes, so the 64 × [4 doublings + table add] verify ladder
  collapses to 64 MIXED ADDS against a host-precomputed comb table
  ``T[j][d] = d · 16^(63−j) · G`` staged once in Montgomery-RNS form
  (affine coordinates, so every step is one ``pt_add_mixed`` — no
  in-kernel doubling at all, ~6× fewer Montgomery rounds per step
  than verify).
* Everything else is host arithmetic the verify path already
  amortizes: the RFC 6979 nonce derivation (HMAC-SHA256 — see
  ``crypto/ec_ref.rfc6979_candidates``: deterministic, so the device
  lane has a bit-equal serial CPU oracle), ONE Montgomery batch
  inversion for the whole batch's ``k⁻¹`` lane (the ``prepare_cols``
  trick, here mod n), and a second batch inversion mod p to
  affinize the device's projective outputs.

Division of labor per batch of B digests:

  host:   k_i = RFC6979(d_i, e_i);  k⁻¹ batch-inverted mod n;
          k → [B, 16] int16 big-endian limbs (the verify wire form)
  device: R_i = k_i·G over the comb table → projective (X̃ : Ỹ : Z̃)
          in Montgomery-RNS; X̃, Z̃ ship back ([B, 2, 2n] int32)
  host:   CRT-reconstruct X̃, Z̃; x = X̃·Z̃⁻¹ mod p (Montgomery factors
          cancel in the ratio — no from_mont needed); r = x mod n;
          s = k⁻¹(e + r·d) mod n; low-S normalization

The accept-set contract: (r, s) is BIT-EQUAL to
``ec_ref.SigningKey(d).sign_digest(e)`` for every lane (pinned across
edge scalars by tests/test_p256sign.py), and an optional
verify-after-sign lane routes each fresh signature back through
``p256v3.verify_launch`` before it leaves the peer.

Batches pad to the same ``MIN_BUCKET``/``_bucket`` family as verify —
pad lanes carry k = 1 (a real scalar: the comb table has no ∞ row to
gather) — so the ``chunk``/``mesh``/``pool`` knobs compose exactly as
they do on the verify side.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import ec_ref
from fabric_tpu.observe import ledger as _ledger
from fabric_tpu.ops import rns
from fabric_tpu.ops.p256v3 import (
    MIN_BUCKET,
    STEPS,
    _BND_STATE,
    _bucket,
    _chunk_bounds,
    _clamp,
    _const_rv,
    _ctx,
    _dev_ann,
    _limbs16,
    _shard,
    _MONT_ONE,
    device_recode_windows,
    pt_add_mixed,
)

P = ec_ref.P
N = ec_ref.N
GX, GY = ec_ref.GX, ec_ref.GY
HALF_N = ec_ref.HALF_N

# ---------------------------------------------------------------------------
# Fixed-base comb table: T[j][d] = d · 16^(63−j) · G, affine, Montgomery
# form, j indexed MSB-first to match the _windows/_limbs16 digit order.
# Slot d = 0 is unused (digit-0 steps skip the add — same ∞-avoidance
# as the verify ladder's T_G lane).  Built lazily on first sign (≈1k
# ec_ref point adds of host Python, double-checked-locked) and cached
# for the process lifetime — it is a pure constant of the curve.

_FB: np.ndarray | None = None
_FB_LOCK = threading.Lock()


def _fb_table() -> np.ndarray:
    """[STEPS, 16, 2, 2n] int32 — the comb table described above."""
    global _FB
    tab = _FB
    if tab is not None:
        return tab
    with _FB_LOCK:
        if _FB is not None:
            return _FB
        tab = np.zeros((STEPS, 16, 2, 2 * rns.N_CH), np.int32)
        base = (GX, GY)  # weight 16^0 → step index STEPS−1 (LSB digit)
        for step in range(STEPS - 1, -1, -1):
            pts = []
            p_ = base
            for _d in range(1, 16):
                pts.append(p_)
                p_ = ec_ref.pt_add(p_, base)
            # after the loop p_ = 16·base: the next (more significant)
            # step's base point
            tab[step, 1:, 0] = rns.ints_to_rns(
                [(pt[0] * rns.M_A) % P for pt in pts]
            )
            tab[step, 1:, 1] = rns.ints_to_rns(
                [(pt[1] * rns.M_A) % P for pt in pts]
            )
            base = p_
        _FB = tab
        # HBM owner tag: the staged comb table pins its bytes on
        # device for the process lifetime once the kernel captures it
        _ledger.account_hbm("comb_table", tab.nbytes)
    return tab


# ---------------------------------------------------------------------------
# Device kernel


def sign_batch_limbs(limbs):
    """[B, 16] int16 big-endian nonce limbs → [B, 2, 2n] int32: the
    projective (X̃, Z̃) Montgomery-RNS coordinates of R = k·G.

    64 ladder steps of ONE complete mixed add each against the comb
    table — no doublings (the table carries the 16^j weights), no
    in-kernel window table build (the base is constant).  Digit-0
    steps keep the running point unchanged (``pt_add_mixed`` requires
    an affine, non-∞ addend, exactly like the verify ladder's u1·G
    lane).  k ∈ [1, n−1] ⇒ R ≠ ∞, so Z̃ is never ≡ 0 for real lanes.
    """
    ctx = _ctx()
    b_m = _const_rv((ec_ref.B * rns.M_A) % P)
    w = device_recode_windows(limbs)  # [B, 64] int32 digits, MSB-first
    tg = jnp.asarray(_fb_table())     # [64, 16, 2, 2n] constants
    B = limbs.shape[0]
    zero = jnp.zeros((B, 2 * rns.N_CH), jnp.int32)
    one_m = jnp.broadcast_to(
        jnp.asarray(rns._to_res(_MONT_ONE, rns.BASE_A + rns.BASE_B)),
        zero.shape,
    )

    def body(i, state):
        X, Y, Z = state
        R = (rns.RV(X, _BND_STATE), rns.RV(Y, _BND_STATE),
             rns.RV(Z, _BND_STATE))
        d = jax.lax.dynamic_index_in_dim(w, i, axis=1, keepdims=False)
        tgi = jax.lax.dynamic_index_in_dim(tg, i, axis=0, keepdims=False)
        sel = jnp.take_along_axis(
            tgi[None], d[:, None, None, None], axis=-3
        )[..., 0, :, :]  # [B, 2, 2n]
        Rg = pt_add_mixed(
            R, rns.RV(sel[..., 0, :], P), rns.RV(sel[..., 1, :], P),
            b_m, ctx,
        )
        Rg = tuple(_clamp(c, _BND_STATE) for c in Rg)
        skip = (d == 0)[:, None]
        return (
            jnp.where(skip, X, Rg[0].arr),
            jnp.where(skip, Y, Rg[1].arr),
            jnp.where(skip, Z, Rg[2].arr),
        )

    X, _Y, Z = jax.lax.fori_loop(0, STEPS, body, (zero, one_m, zero))
    return jnp.stack([X, Z], axis=-2)


sign_batch_limbs_jit = jax.jit(sign_batch_limbs)


# ---------------------------------------------------------------------------
# Host side: CRT reconstruction, batch inversions, the (r, s) algebra

_CRT_COEFFS: list[int] | None = None
_M_ALL = rns.M_A * rns.M_B


def _crt_coeffs() -> list[int]:
    """Cached CRT basis over all 2n channels: c_i = (M/m_i)·
    ((M/m_i)⁻¹ mod m_i) — ``rns.rv_to_ints`` recomputes these per
    call; the sign fetch path runs per block, so cache once."""
    global _CRT_COEFFS
    if _CRT_COEFFS is None:
        primes = rns.BASE_A + rns.BASE_B
        _CRT_COEFFS = [
            (_M_ALL // m) * pow(_M_ALL // m, -1, m) for m in primes
        ]
    return _CRT_COEFFS


def _rows_to_ints_mod_p(arr: np.ndarray) -> list[int]:
    """[B, 2n] canonical residues (values < 9p « M) → [B] ints mod p."""
    coeffs = _crt_coeffs()
    out = []
    for row in np.asarray(arr, np.int64):
        v = 0
        for r, c in zip(row, coeffs):
            v += int(r) * c
        out.append(v % _M_ALL % P)
    return out


def _batch_inv(xs: list[int], mod: int) -> list[int]:
    """Montgomery's simultaneous inversion mod ``mod`` (one pow(·,−1)
    for the whole batch) — ``prepare_cols``' trick, reused for the
    k⁻¹ lane (mod n) and the projective-Z affinization (mod p)."""
    B = len(xs)
    pref = [1] * (B + 1)
    for i, x in enumerate(xs):
        pref[i + 1] = (pref[i] * x) % mod
    inv_all = pow(pref[B], -1, mod)
    out = [0] * B
    for i in range(B - 1, -1, -1):
        out[i] = (pref[i] * inv_all) % mod
        inv_all = (inv_all * xs[i]) % mod
    return out


def _lanes_hist():
    from fabric_tpu.ops_metrics import global_registry

    return global_registry().histogram(
        "device_sign_lanes_per_launch",
        "signature lanes (incl. bucket padding) per sign dispatch",
        buckets=(16, 64, 256, 1024, 3072, float("inf")),
    )


def derive_nonces(digests, ds, pool=None) -> list[int]:
    """Per-lane RFC 6979 nonces for (digest, scalar) pairs.  The HMAC
    walk is ~6 SHA-256 per lane — the one host stage worth sharding,
    so a ``parallel.hostpool`` pool splits the lane range exactly like
    the verify staging does."""
    ks: list[int | None] = [None] * len(digests)

    def stage(lo, hi):
        for i in range(lo, hi):
            ks[i] = ec_ref.rfc6979_k(ds[i], digests[i])

    if pool is not None and len(digests) >= 2 * MIN_BUCKET:
        pool.map_slices(len(digests), stage, stage="sign_nonce",
                        align=MIN_BUCKET)
    else:
        stage(0, len(digests))
    return ks  # type: ignore[return-value]


class SignHandle:
    """An in-flight sign batch: the device-resident (X̃, Z̃) plus the
    host context needed to finish the algebra at fetch time.  Mirrors
    ``VerifyHandle`` — the dispatch is async, so the caller's host
    thread keeps staging while the device walks the comb ladder."""

    __slots__ = ("device_out", "n_real", "es", "ds", "k_invs",
                 "verify_after", "rec")

    def __init__(self, device_out, n_real: int, es, ds, k_invs,
                 verify_after: bool = False, rec=None):
        self.device_out = device_out
        self.n_real = n_real
        self.es = es
        self.ds = ds
        self.k_invs = k_invs
        self.verify_after = verify_after
        # launch-ledger record (observe/ledger.py): fetch() brackets
        # the device sync so the ledger can attribute the wait
        self.rec = rec

    def fetch(self) -> list[tuple[int, int]]:
        """→ [(r, s)] low-S normalized, bit-equal to the serial
        RFC 6979 oracle."""
        if not self.n_real:
            return []
        rec = self.rec
        if rec is not None:
            rec.sync_begin()
        out = np.asarray(self.device_out)
        if rec is not None:
            rec.sync_end(d2h_bytes=out.nbytes)
        out = out[: self.n_real]
        xs = _rows_to_ints_mod_p(out[:, 0])
        zs = _rows_to_ints_mod_p(out[:, 1])
        # k ∈ [1, n−1] ⇒ R ≠ ∞ ⇒ Z ≢ 0; guard anyway so one corrupt
        # lane poisons its own signature, not the whole batch's
        # prefix products
        z_safe = [z if z else 1 for z in zs]
        z_inv = _batch_inv(z_safe, P)
        sigs: list[tuple[int, int]] = []
        for e, d, kinv, X, Z, zi in zip(
            self.es, self.ds, self.k_invs, xs, zs, z_inv
        ):
            if Z == 0:
                raise ValueError("device sign lane returned ∞")
            x_aff = (X * zi) % P
            r = x_aff % N
            s = (kinv * (e + r * d)) % N
            if r == 0 or s == 0:
                # 2⁻²⁵⁶ territory — the serial oracle walks to the
                # next RFC 6979 candidate; delegate the lane to it so
                # both lanes stay bit-equal even here
                r, s = ec_ref.SigningKey(d).sign_digest(e)
            elif s > HALF_N:
                s = N - s
            sigs.append((r, s))
        if self.verify_after:
            _self_check(self.es, self.ds, sigs)
        return sigs

    def __call__(self) -> list[tuple[int, int]]:
        return self.fetch()


_PUB_CACHE: dict[int, tuple[int, int]] = {}


def _pub_of(d: int) -> tuple[int, int]:
    pub = _PUB_CACHE.get(d)
    if pub is None:
        if len(_PUB_CACHE) > 64:  # a peer signs with a handful of keys
            _PUB_CACHE.clear()
        pub = _PUB_CACHE[d] = ec_ref.pt_mul(d, ec_ref.G)
    return pub


def _self_check(es, ds, sigs) -> None:
    """Verify-after-sign: route the fresh batch back through the
    existing device verify lane (p256v3.verify_launch) and refuse to
    release a batch with any rejected lane — a bit-flip anywhere in
    the sign path is caught before a signature leaves the peer."""
    from fabric_tpu.ops import p256v3

    items = [
        (e, r, s, *_pub_of(d)) for e, d, (r, s) in zip(es, ds, sigs)
    ]
    ok = p256v3.verify_launch(items)()
    if not all(ok):
        bad = [i for i, v in enumerate(ok) if not v]
        raise RuntimeError(
            f"verify-after-sign rejected lanes {bad[:8]} "
            f"({len(bad)}/{len(items)} bad)"
        )


def sign_launch(digests, key, ks=None, chunk: int | None = None,
                mesh=None, pool=None,
                verify_after: bool = False) -> SignHandle:
    """Asynchronously dispatch a sign batch; returns a SignHandle
    (callable as a zero-arg fetch for [(r, s)]).

    ``digests``: [B] digest ints (``ec_ref.digest_int`` values).
    ``key``: the private scalar d, or a [B] list for per-lane keys
    (the fixed-base table only bakes in G, so d is free per lane).
    ``ks``: explicit nonces (tests/vectors ONLY — production nonces
    are RFC 6979, derived here when None).  ``chunk``/``mesh``/
    ``pool`` compose exactly like ``verify_launch``: microbatched
    back-to-back dispatches, axis-0 mesh sharding, host-pool-sharded
    nonce derivation.  ``verify_after`` routes the finished batch
    through the device verify lane before fetch() returns it."""
    digests = [int(e) for e in digests]
    B0 = len(digests)
    if not B0:
        return SignHandle(None, 0, [], [], [])
    ds = ([int(key)] * B0 if isinstance(key, int)
          else [int(d) for d in key])
    if len(ds) != B0:
        raise ValueError("per-lane key list length mismatch")
    for d in ds:
        if not (1 <= d < N):
            raise ValueError("private scalar out of range")
    if ks is None:
        ks = derive_nonces(digests, ds, pool=pool)
    else:
        ks = [int(k) for k in ks]
        if len(ks) != B0:
            raise ValueError("explicit nonce list length mismatch")
        for k in ks:
            if not (1 <= k < N):
                raise ValueError("nonce out of range")
    k_invs = _batch_inv(ks, N)

    total = _bucket(B0)
    limbs = np.zeros((total, 16), np.int16)
    limbs[:B0] = _limbs16(ks)
    limbs[B0:, -1] = 1  # pad lanes sign with k = 1 (discarded rows)

    chunk = max(int(chunk), MIN_BUCKET) if chunk else 0
    _lanes_hist().observe(total)
    # launch-ledger record (observe/ledger.py): the comb-ladder kernel
    # retraces per (chunk or bucket shape, mesh layout)
    rec = _ledger.launch(
        "sign",
        key=(chunk if (chunk and B0 > chunk) else total,
             mesh.size if mesh is not None else 0),
        lanes=B0, h2d_bytes=limbs.nbytes,
    )

    def dispatch(rows):
        with _dev_ann("fabtpu.sign_dispatch"):
            return sign_batch_limbs_jit(_shard(mesh, rows))

    if chunk and B0 > chunk:
        outs = []
        for lo, _hi, pad in _chunk_bounds(B0, chunk):
            # rows [lo, lo+pad) of the prepadded limb frame: exact
            # chunks hold the verify chunker's index invariant, the
            # tail absorbs the bucket padding rows
            outs.append(dispatch(limbs[lo:lo + pad]))
        dev = jnp.concatenate(outs)
    else:
        dev = dispatch(limbs)
    if hasattr(dev, "copy_to_host_async"):
        dev.copy_to_host_async()
    if rec is not None:
        rec.dispatched()
    return SignHandle(dev, B0, digests, ds, k_invs,
                      verify_after=verify_after, rec=rec)


def sign_digests(digests, key, **kw) -> list[tuple[int, int]]:
    """Synchronous convenience: ``sign_launch(...).fetch()``."""
    return sign_launch(digests, key, **kw).fetch()


def sign_host(digests, key) -> list[tuple[int, int]]:
    """The serial CPU oracle: per-lane RFC 6979 `ec_ref` signing with
    the same interface as ``sign_digests`` — the bit-equal fallback
    the device lane is diffed against (and the CPU backend the
    SignBatcher uses when ``sign_device`` is off)."""
    digests = [int(e) for e in digests]
    ds = ([int(key)] * len(digests) if isinstance(key, int)
          else [int(d) for d in key])
    return [
        ec_ref.SigningKey(d).sign_digest(e)
        for e, d in zip(digests, ds)
    ]

"""Batched endorsement-policy evaluation as array ops.

The reference evaluates each tx's policy tree sequentially over its
endorsements, verifying ECDSA signatures INSIDE the tree walk
(common/cauthdsl/cauthdsl.go:24-110 — each SignedBy leaf calls
SatisfiesPrincipal + Verify).  The TPU-first reordering (SURVEY §2.10
last row): verify ALL of the block's signatures in one batched kernel
(ops/p256), then evaluate every tx's policy as a boolean reduction
over the validity vector — compute first, control flow after.

Shapes: a block has T txs, each with up to S endorsement slots; the
channel's policies are compiled to BatchPlans (crypto/policy.py) whose
leaves reference principal columns.  Per tx we get

    sat[t, s, p]  =  principal-match (host MSP) for endorsement slot s
    valid[t, s]   =  batched signature validity (device)

and the kernel computes leaf truth  any_s(valid & sat)  then folds the
gate program — all [T, ...]-shaped elementwise ops, one dispatch per
distinct policy shape (policies are cached per channel+namespace like
the reference's PluginValidator cache, plugin_validator.go).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from functools import partial


@partial(jax.jit, static_argnames=("gates",))
def eval_plan_batch(valid, sat, leaf_principal, leaf_rank, gates):
    """Evaluate one policy plan over a batch of transactions.

    valid: [T, S] bool — signature validity per endorsement slot
        (False for empty slots).
    sat:   [T, S, P] bool — slot s satisfies principal column p.
    leaf_principal: [L] int32 — principal column per leaf.
    leaf_rank: [L] int32 — per-column evaluation-order rank, so the
        r-th leaf of a column needs r+1 matching signatures (the
        consumption budget; crypto/policy.BatchPlan.leaf_sat).
    gates: static tuple of (n, child_slots) — slots < L are leaves,
        slot L+i is gate i; last gate is the root.

    Returns ok [T] bool.
    """
    hit = valid[:, :, None] & sat  # [T, S, P]
    counts = jnp.sum(hit.astype(jnp.int32), axis=1)  # [T, P]
    leaf = jnp.take(counts, leaf_principal, axis=1) > leaf_rank[None, :]  # [T, L]
    vals = [leaf[:, i] for i in range(leaf.shape[1])]
    for n, children in gates:
        acc = jnp.zeros(valid.shape[0], jnp.int32)
        for c in children:
            acc = acc + vals[c].astype(jnp.int32)
        vals.append(acc >= n)
    return vals[-1]


def eval_block(plan, valid, sat):
    """Host wrapper: evaluate ``plan`` for every tx of a block.

    plan: crypto.policy.BatchPlan
    valid: [T, S] bool (numpy or device)
    sat: [T, S, P] bool principal-match tensor
    """
    gates = tuple((n, tuple(children)) for n, children in plan.gates)
    return eval_plan_batch(
        jnp.asarray(valid),
        jnp.asarray(sat),
        jnp.asarray(np.asarray(plan.leaf_principal, np.int32)),
        jnp.asarray(np.asarray(plan.leaf_rank, np.int32)),
        gates,
    )

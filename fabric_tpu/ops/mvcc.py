"""Batched MVCC read-set validation as a JAX/XLA TPU kernel.

The reference validates a block's transactions SERIALLY: for each tx in
order, every read (namespace, key, version) is compared against the
committed state version, reads of keys already written by an earlier
*valid* tx in the same block are conflicts, range-query results are
re-checked for phantoms, and the write-set of each valid tx is applied
so later txs see it (reference:
core/ledger/kvledger/txmgmt/validation/validator.go:81-118
`validateAndPrepareBatch`, `validateKVRead` :179-200, range/phantom
:205-247; bulk preload hint `preLoadCommittedVersionOfRSet` :27-78).

TPU-first reformulation (not a port — the serial loop doesn't map to
hardware):

1. **Version checks are embarrassingly parallel**: the host bulk-loads
   committed versions for every read key (one state-DB gather, as the
   reference already does), the kernel compares all [T, R] reads at
   once.
2. **Intra-block conflicts become one dense compare**: with block-local
   dense key ids, reader-vs-writer conflict is a [T, T] matrix computed
   by a broadcast equality over [T, T, R, W] (XLA fuses the reduce; at
   1000-tx blocks this is microseconds on the VPU).  Range-query
   phantom constraints fold into the same matrix because keys get ids
   in lexicographic order, so a range is an id interval.
3. **The sequential validity chain becomes a fixpoint**: valid[j] =
   ver_ok[j] ∧ ¬∃i<j (valid[i] ∧ conflict[j,i]).  Jacobi iteration
   from the optimistic assignment converges in max conflict-chain-depth
   rounds (each round one [T,T]·[T] matvec); the unique fixpoint equals
   the serial result because dependencies form a DAG over tx order.

Key-id space: the HOST assigns dense ids to the union of keys touched
by the block, sorted lexicographically per (namespace, key) — including
hashed private-collection keys, which get ids in a disjoint namespace
range (reference hashed-key checks: validator.go:249-283).  Versions
are (block_height, tx_num) uint32 pairs; absent keys carry a present
flag (nil-version semantics of validateKVRead).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp


def mvcc_validate(
    read_keys,      # [T, R] int32 block-local key ids; -1 = padding
    read_present,   # [T, R] bool: simulation saw the key as existing
    read_vers,      # [T, R, 2] uint32 (block, txnum) seen at simulation
    comm_present,   # [T, R] bool: key exists in committed state
    comm_vers,      # [T, R, 2] uint32 committed version
    write_keys,     # [T, W] int32 block-local key ids; -1 = padding
    rq_lo,          # [T, Q] int32 range-query id interval start; -1 = pad
    rq_hi,          # [T, Q] int32 exclusive interval end
    pre_ok,         # [T] bool: upstream validity (sigs, policy, structure)
):
    """Returns (valid [T] bool, conflict [T] bool, phantom [T] bool).

    `valid` matches the serial reference semantics exactly; `conflict`
    / `phantom` distinguish MVCC_READ_CONFLICT from
    PHANTOM_READ_CONFLICT for the TRANSACTIONS_FILTER codes.
    """
    # per-read version check vs committed state (parallel over all);
    # the conflict matrices + fixpoint live in mvcc_validate_hostver
    pad = read_keys < 0
    ver_eq = jnp.all(read_vers == comm_vers, axis=-1)
    ok = jnp.where(
        read_present & comm_present,
        ver_eq,
        read_present == comm_present,  # both absent ok; presence flip = stale
    )
    ver_ok = jnp.all(ok | pad, axis=-1)  # [T]
    return mvcc_validate_hostver(
        read_keys, ver_ok, write_keys, rq_lo, rq_hi, pre_ok
    )


mvcc_validate_jit = jax.jit(mvcc_validate)


def mvcc_validate_hostver(
    read_keys,      # [T, R] int32 block-local key ids; -1 = padding
    ver_ok_host,    # [T] bool: per-tx committed-version check, HOST-side
    write_keys,     # [T, W] int32 block-local key ids; -1 = padding
    rq_lo,          # [T, Q] int32 range-query id interval start; -1 = pad
    rq_hi,          # [T, Q] int32 exclusive interval end
    pre_ok,         # [T] bool: upstream validity (sigs, policy, structure)
):
    """``mvcc_validate`` with the per-read committed-version compare
    done on HOST (StaticBlock.host_ver_ok): the compare is elementwise
    and state-dependent, so shipping the committed presence/version
    arrays to the device bought nothing but two launch-time H2D
    transfers over a latency-bound tunnel.  The device keeps what it is
    uniquely good at — the [T,T] conflict matrices and the validity
    fixpoint (validator.go:81-118's serial loop, reformulated)."""
    T = read_keys.shape[0]
    ver_ok = ver_ok_host & pre_ok

    w_valid = (write_keys >= 0)[None, :, None, :]
    r_valid = (read_keys >= 0)[:, None, :, None]
    eq = (
        read_keys[:, None, :, None] == write_keys[None, :, None, :]
    ) & w_valid & r_valid
    direct = jnp.any(eq, axis=(2, 3))

    q_valid = (rq_lo >= 0)[:, None, :, None]
    in_range = (
        (write_keys[None, :, None, :] >= rq_lo[:, None, :, None])
        & (write_keys[None, :, None, :] < rq_hi[:, None, :, None])
        & w_valid & q_valid
    )
    phantom_m = jnp.any(in_range, axis=(2, 3))

    order = jnp.tril(jnp.ones((T, T), jnp.bool_), k=-1)
    direct = direct & order
    phantom_m = phantom_m & order
    conflict_m = (direct | phantom_m).astype(jnp.float32)

    def body(state):
        v, _, it = state
        hit = conflict_m @ v.astype(jnp.float32) > 0
        return ver_ok & ~hit, v, it + 1

    def cond(state):
        v, prev, it = state
        return jnp.any(v != prev) & (it <= T + 1)

    valid, _, _ = jax.lax.while_loop(cond, body, (ver_ok, ~ver_ok, jnp.int32(0)))

    vf = valid.astype(jnp.float32)
    conflict = (direct.astype(jnp.float32) @ vf > 0) & ver_ok
    phantom = (phantom_m.astype(jnp.float32) @ vf > 0) & ver_ok
    return valid, conflict, phantom


def mvcc_in_shardings(mesh, arrays, *, trailing: int = 1):
    """Partition-rule shardings for a ``jax.jit(mvcc_validate, ...)``
    dispatch: one ``"mvcc_frame"`` NamedSharding per operand (axis 0 —
    the tx lane — split over the mesh data axis, trailing dims
    replicated), plus ``trailing`` extra 1-D frames for ``pre_ok``-style
    tail operands.

    This is the declarative replacement for hand-built
    ``batch_sharding`` tuples: every MVCC launch frame routes through
    the same PartitionRules family, so the rules table (and the FT019
    unruled-sharding check) see one canonical construction site.
    Returns ``None`` when ``mesh`` is None (unsharded dispatch).
    """
    if mesh is None:
        return None
    from fabric_tpu.parallel.mesh import sharding_for

    specs = tuple(sharding_for(mesh, "mvcc_frame", a.ndim) for a in arrays)
    specs += tuple(
        sharding_for(mesh, "mvcc_frame", 1) for _ in range(trailing)
    )
    return specs


# ---------------------------------------------------------------------------
# Host-side block preparation


@dataclass
class TxRWSet:
    """One transaction's read/write set in host form.

    reads: list of (key, version | None) — version is (block, txnum),
        None means the key was absent at simulation time.
    writes: list of keys written (values don't matter for validation).
    range_reads: list of (start_key, end_key_exclusive) phantom
        constraints; the per-result version checks ride in `reads`.
    Keys are arbitrary hashable tuples, e.g. (namespace, key) or
    (namespace, collection, key_hash).
    """

    reads: list
    writes: list
    range_reads: list


@dataclass
class StaticBlock:
    """State-INDEPENDENT device arrays for one block + the recipe to
    fill the committed-version arrays later.

    The split exists for the commit pipeline: everything here can be
    built in the prefetch thread while the previous block is still on
    device; only `fill_committed` (a gather against the state DB) must
    wait for the predecessor's state commit."""

    read_keys: np.ndarray      # [T, R] int32
    read_present: np.ndarray   # [T, R] bool
    read_vers: np.ndarray      # [T, R, 2] uint32
    write_keys: np.ndarray     # [T, W] int32
    rq_lo: np.ndarray          # [T, Q] int32
    rq_hi: np.ndarray          # [T, Q] int32
    read_fill: list            # [(j, a, key)] for committed-array fill
    read_key_set: set          # union of read keys
    _jnp: tuple = None         # uploaded static arrays (see upload())

    def fill_committed(self, committed: dict):
        """→ (comm_present [T,R] bool, comm_vers [T,R,2] uint32)."""
        T, R = self.read_keys.shape
        comm_present = np.zeros((T, R), bool)
        comm_vers = np.zeros((T, R, 2), np.uint32)
        for j, a, k in self.read_fill:
            cv = committed.get(k)
            if cv is not None:
                comm_present[j, a] = True
                comm_vers[j, a] = cv
        return comm_present, comm_vers

    def upload(self) -> None:
        """Push the state-independent arrays to device NOW — called
        from the prefetch thread so launch-time H2D is only the two
        committed-version arrays (tunnel transfers are latency-bound,
        so moving them off the critical path matters more than their
        size suggests)."""
        if self._jnp is None:
            self._jnp = (
                jnp.asarray(self.read_keys), jnp.asarray(self.read_present),
                jnp.asarray(self.read_vers), jnp.asarray(self.write_keys),
                jnp.asarray(self.rq_lo), jnp.asarray(self.rq_hi),
            )

    def device_args(self, committed: dict):
        """Assemble the full `mvcc_validate` argument tuple (minus
        pre_ok) in signature order."""
        comm_present, comm_vers = self.fill_committed(committed)
        self.upload()
        a = self._jnp
        return (
            a[0], a[1], a[2], jnp.asarray(comm_present),
            jnp.asarray(comm_vers), a[3], a[4], a[5],
        )

    def host_ver_ok(self, committed: dict) -> np.ndarray:
        """[T] bool: the per-read committed-version compare of
        ``mvcc_validate`` done on host numpy — bit-identical to the
        kernel's reduction (validateKVRead semantics: version equality
        when both present, presence flip = stale, padding inert)."""
        comm_present, comm_vers = self.fill_committed(committed)
        pad = self.read_keys < 0
        ver_eq = (self.read_vers == comm_vers).all(axis=-1)
        ok = np.where(
            self.read_present & comm_present,
            ver_eq,
            self.read_present == comm_present,
        )
        return np.logical_or(ok, pad).all(axis=-1)

    def device_args_hostver(self, committed: dict):
        """`mvcc_validate_hostver` argument tuple (minus pre_ok):
        static uploaded arrays + the ONE state-dependent [T] bool."""
        return self.device_args_verok(self.host_ver_ok(committed))

    def device_args_verok(self, ver_ok: np.ndarray):
        """`mvcc_validate_hostver` args from an already-computed [T]
        host version check."""
        self.upload()
        a = self._jnp
        return (a[0], jnp.asarray(ver_ok), a[3], a[4], a[5])

    @property
    def dims(self) -> tuple:
        """(R, W, Q) — the packed-static column split."""
        return (self.read_keys.shape[1], self.write_keys.shape[1],
                self.rq_lo.shape[1])

    def packed_static(self):
        """[T, R+W+2Q] int32 on device — read_keys | write_keys |
        rq_lo | rq_hi in ONE H2D transfer (the stage-2 hostver path
        slices by static offsets inside the jit)."""
        p = getattr(self, "_packed", None)
        if p is None:
            p = self._packed = jnp.asarray(np.concatenate(
                [self.read_keys, self.write_keys, self.rq_lo, self.rq_hi],
                axis=1,
            ))
        return p

    def packed_read_pv(self):
        """[T, R, 3] int32 on device — (read_present, read_ver_block,
        read_ver_txnum) per read slot, the EXPECTED side of the
        per-read committed-version compare.  State-INDEPENDENT, so the
        device-resident state path (fabric_tpu/state) uploads it from
        the prefetch thread; the committed side is then gathered from
        the resident version table INSIDE the fused stage-2 program
        instead of being host-filled per block.  Versions ride as raw
        int32 bit patterns (equality-only compare — exact)."""
        p = getattr(self, "_packed_rpv", None)
        if p is None:
            T, R = self.read_keys.shape
            rpv = np.zeros((T, R, 3), np.int32)
            rpv[:, :, 0] = self.read_present
            rpv[:, :, 1:3] = self.read_vers.view(np.int32)
            p = self._packed_rpv = jnp.asarray(rpv)
        return p


def prepare_block_static(txs: list[TxRWSet], bucketed: bool = False) -> StaticBlock:
    """Build the state-independent device arrays for `mvcc_validate`.

    Key ids are assigned in lexicographic key order so range bounds map
    to id intervals over the block's key universe (sufficient for
    in-block phantom detection: only in-block writes can phantom a
    range within a block).

    bucketed: round T/R/W/Q up to powers of two so consecutive blocks
    of similar shape share one compiled executable (padding rows carry
    key id −1 and are inert).
    """
    from fabric_tpu.utils.batching import next_pow2

    universe = set()
    read_key_set = set()
    for tx in txs:
        for k, _ in tx.reads:
            universe.add(k)
            read_key_set.add(k)
        universe.update(tx.writes)
    for tx in txs:
        for lo, hi in tx.range_reads:
            universe.add(lo)  # ids for bounds; hi handled via bisect below
    skeys = sorted(universe)
    kid = {k: i for i, k in enumerate(skeys)}

    import bisect

    T = len(txs)
    R = max(1, max((len(t.reads) for t in txs), default=1))
    W = max(1, max((len(t.writes) for t in txs), default=1))
    Q = max(1, max((len(t.range_reads) for t in txs), default=1))
    if bucketed:
        T = max(16, next_pow2(T))
        R, W, Q = next_pow2(R), next_pow2(W), next_pow2(Q)

    read_keys = np.full((T, R), -1, np.int32)
    read_present = np.zeros((T, R), bool)
    read_vers = np.zeros((T, R, 2), np.uint32)
    write_keys = np.full((T, W), -1, np.int32)
    rq_lo = np.full((T, Q), -1, np.int32)
    rq_hi = np.full((T, Q), -1, np.int32)
    read_fill: list = []

    for j, tx in enumerate(txs):
        for a, (k, ver) in enumerate(tx.reads):
            read_keys[j, a] = kid[k]
            if ver is not None:
                read_present[j, a] = True
                read_vers[j, a] = ver
            read_fill.append((j, a, k))
        for a, k in enumerate(tx.writes):
            write_keys[j, a] = kid[k]
        for a, (lo, hi) in enumerate(tx.range_reads):
            rq_lo[j, a] = bisect.bisect_left(skeys, lo)
            rq_hi[j, a] = bisect.bisect_left(skeys, hi)

    return StaticBlock(
        read_keys=read_keys, read_present=read_present, read_vers=read_vers,
        write_keys=write_keys, rq_lo=rq_lo, rq_hi=rq_hi,
        read_fill=read_fill, read_key_set=read_key_set,
    )


def prepare_block(txs: list[TxRWSet], committed: dict, bucketed: bool = False):
    """Build the full device-array tuple for `mvcc_validate` (static
    arrays + committed-version fill in one go)."""
    return prepare_block_static(txs, bucketed=bucketed).device_args(committed)


@dataclass
class VecStaticBlock(StaticBlock):
    """StaticBlock variant fed by the native mvcc_prep flat arrays:
    committed-version fill is a numpy gather over per-unique-key
    arrays instead of a per-read Python loop.  Key-id ORDER is
    arbitrary (hash interning) — valid because blocks with range
    queries never take this path (mvccprep.cpp forces the Python
    fallback for them)."""

    r_rows: np.ndarray = None   # [nr] tx row per flat read
    r_cols: np.ndarray = None   # [nr] slot per flat read
    r_uid: np.ndarray = None    # [nr] unique-key id per flat read
    u_composite: list = None    # [n_keys] composite mvcc keys
    u_pairs: list = None        # [n_keys] (ns, key) pairs (validator)

    def fill_committed(self, committed: dict):
        U = len(self.u_composite)
        up = np.zeros(U, bool)
        uv = np.zeros((U, 2), np.uint32)
        for u, k in enumerate(self.u_composite):
            cv = committed.get(k)
            if cv is not None:
                up[u] = True
                uv[u] = cv
        T, R = self.read_keys.shape
        comm_present = np.zeros((T, R), bool)
        comm_vers = np.zeros((T, R, 2), np.uint32)
        if len(self.r_rows):
            comm_present[self.r_rows, self.r_cols] = up[self.r_uid]
            comm_vers[self.r_rows, self.r_cols] = uv[self.r_uid]
        return comm_present, comm_vers

    def ver_ok_from_u(self, up: np.ndarray, uv: np.ndarray) -> np.ndarray:
        """[T] bool from per-UNIQUE-key committed (present, version)
        arrays — the flat path's host-side validateKVRead reduction
        (no [T,R] scatter, no composite-key dict)."""
        Tb = self.read_keys.shape[0]
        if not len(self.r_rows):
            return np.ones(Tb, bool)
        rp = self.read_present[self.r_rows, self.r_cols]
        rv = self.read_vers[self.r_rows, self.r_cols]
        cp = up[self.r_uid]
        ver_eq = (rv == uv[self.r_uid]).all(axis=1)
        okr = np.where(rp & cp, ver_eq, rp == cp)
        bad_per_tx = np.bincount(
            self.r_rows[~okr], minlength=Tb
        )
        return bad_per_tx == 0


def prepare_block_from_flat(n_txs: int, rwp, composite_keys: list) -> VecStaticBlock:
    """Native mvcc_prep flat arrays → device-static arrays with pure
    numpy scatters (no per-read Python loop).  ``composite_keys``:
    [n_keys] mvcc-form keys for state lookups."""
    from fabric_tpu.utils.batching import next_pow2

    Tb = max(16, next_pow2(max(1, n_txs)))
    nr, nw = rwp.n_reads, rwp.n_writes
    rc = rwp.r_count[:n_txs]
    wc = rwp.w_count[:n_txs]
    R = next_pow2(max(1, int(rc.max()) if n_txs else 1))
    W = next_pow2(max(1, int(wc.max()) if n_txs else 1))

    read_keys = np.full((Tb, R), -1, np.int32)
    read_present = np.zeros((Tb, R), bool)
    read_vers = np.zeros((Tb, R, 2), np.uint32)
    write_keys = np.full((Tb, W), -1, np.int32)
    rq_lo = np.full((Tb, 1), -1, np.int32)
    rq_hi = np.full((Tb, 1), -1, np.int32)

    if nr:
        r_rows = np.repeat(np.arange(n_txs), rc).astype(np.intp)
        r_cols = (np.arange(nr) - np.repeat(rwp.r_start[:n_txs], rc)).astype(np.intp)
        r_uid = rwp.r_uid[:nr]
        read_keys[r_rows, r_cols] = r_uid
        read_present[r_rows, r_cols] = rwp.r_has_ver[:nr].astype(bool)
        read_vers[r_rows, r_cols] = rwp.r_ver[:nr].astype(np.uint32)
    else:
        r_rows = np.zeros(0, np.intp)
        r_cols = np.zeros(0, np.intp)
        r_uid = np.zeros(0, np.int32)
    if nw:
        w_rows = np.repeat(np.arange(n_txs), wc).astype(np.intp)
        w_cols = (np.arange(nw) - np.repeat(rwp.w_start[:n_txs], wc)).astype(np.intp)
        write_keys[w_rows, w_cols] = rwp.w_uid[:nw]

    read_key_set = {composite_keys[u] for u in np.unique(r_uid)} if nr else set()
    return VecStaticBlock(
        read_keys=read_keys, read_present=read_present, read_vers=read_vers,
        write_keys=write_keys, rq_lo=rq_lo, rq_hi=rq_hi,
        read_fill=[], read_key_set=read_key_set,
        r_rows=r_rows, r_cols=r_cols, r_uid=r_uid,
        u_composite=composite_keys,
    )


def mvcc_validate_block(txs: list[TxRWSet], committed: dict, pre_ok=None):
    """End-to-end host helper: prepare + run kernel → numpy bools."""
    arrays = prepare_block(txs, committed)
    if pre_ok is None:
        pre_ok = np.ones(len(txs), bool)
    outs = mvcc_validate_jit(*arrays, jnp.asarray(pre_ok))
    for o in outs:
        if hasattr(o, "copy_to_host_async"):
            o.copy_to_host_async()  # overlap readback latency
    valid, conflict, phantom = outs
    return np.asarray(valid), np.asarray(conflict), np.asarray(phantom)


def mvcc_serial_reference(txs: list[TxRWSet], committed: dict, pre_ok=None):
    """Direct re-implementation of the reference's serial semantics
    (validator.go:81-118) — the oracle the kernel is property-tested
    against."""
    if pre_ok is None:
        pre_ok = [True] * len(txs)
    updates: set = set()
    out = []
    for tx, ok0 in zip(txs, pre_ok):
        ok = bool(ok0)
        if ok:
            for k, ver in tx.reads:
                if k in updates:
                    ok = False
                    break
                if committed.get(k) != ver:
                    ok = False
                    break
        if ok:
            for lo, hi in tx.range_reads:
                if any(lo <= w < hi for w in updates):
                    ok = False
                    break
        if ok:
            updates.update(tx.writes)
        out.append(ok)
    return out

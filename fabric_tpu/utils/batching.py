"""Batch-shape bucketing shared by the TPU data-plane kernels.

Kernels compile once per static shape; bucketing batch sizes to powers
of two bounds the number of compilations on the block-commit path
(block tx counts vary per block — reference:
orderer/common/blockcutter/blockcutter.go:74-130 cuts variable-size
batches).
"""

from __future__ import annotations


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1)."""
    return 1 << max(0, (n - 1)).bit_length()

"""Process-level XLA environment knobs.

Import-light on purpose (no jax import): callers must apply these
BEFORE jax initializes its backends (tests/conftest.py,
__graft_entry__.py).
"""

from __future__ import annotations

import os


def _jaxlib_version() -> tuple:
    try:
        import jaxlib.version  # import-light: version module only

        return tuple(
            int(p) for p in jaxlib.version.__version__.split(".")[:2]
        )
    except Exception:
        return (0, 0)


def ensure_cpu_compile_workaround() -> None:
    """Disable the jax 0.9 CPU fusion emitters.

    They blow up superlinearly on the deep uint32 dependency chains of
    the crypto kernels (a 64-round SHA-256 compression never finishes
    compiling on a 1-core host); the legacy emitter compiles it in ~2s.
    Harmless for the TPU backend.

    Version-gated: XLA ABORTS the whole process on an unknown flag at
    backend init, and ``--xla_cpu_use_fusion_emitters`` does not exist
    on the 0.4.x jaxlibs — setting it there turns every test run into
    a collection-time SIGABRT.  Older jaxlibs still run the legacy
    emitter by default, so skipping the flag loses nothing.
    """
    if _jaxlib_version() < (0, 5):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_use_fusion_emitters" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_use_fusion_emitters=false"
        ).strip()


def ensure_host_device_count(n: int) -> None:
    """Request ``n`` virtual host-platform devices (no-op if any count
    is already configured)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def enable_compile_cache(root: str | None = None) -> bool:
    """Point jax's persistent compile cache at the repo's shared
    ``.jax_cache`` so every process that validates (bench rounds, the
    sidecar server, CLI daemons) reuses one set of compiled verify
    graphs — a sidecar restart must re-attach in seconds, not
    re-compile for minutes while every tenant rides its CPU fallback.
    Returns False (after logging) when jax is absent or the config
    knobs are unavailable; the cache is an optimization, serving works
    without it."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(root, ".jax_cache")
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 2.0
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception as e:
        import logging

        logging.getLogger("fabric_tpu.xla_env").warning(
            "persistent compile cache unavailable (%s)", e
        )
        return False

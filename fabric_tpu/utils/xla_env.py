"""Process-level XLA environment knobs.

Import-light on purpose (no jax import): callers must apply these
BEFORE jax initializes its backends (tests/conftest.py,
__graft_entry__.py).
"""

from __future__ import annotations

import os


def _jaxlib_version() -> tuple:
    try:
        import jaxlib.version  # import-light: version module only

        return tuple(
            int(p) for p in jaxlib.version.__version__.split(".")[:2]
        )
    except Exception:
        return (0, 0)


def ensure_cpu_compile_workaround() -> None:
    """Disable the jax 0.9 CPU fusion emitters.

    They blow up superlinearly on the deep uint32 dependency chains of
    the crypto kernels (a 64-round SHA-256 compression never finishes
    compiling on a 1-core host); the legacy emitter compiles it in ~2s.
    Harmless for the TPU backend.

    Version-gated: XLA ABORTS the whole process on an unknown flag at
    backend init, and ``--xla_cpu_use_fusion_emitters`` does not exist
    on the 0.4.x jaxlibs — setting it there turns every test run into
    a collection-time SIGABRT.  Older jaxlibs still run the legacy
    emitter by default, so skipping the flag loses nothing.
    """
    if _jaxlib_version() < (0, 5):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_use_fusion_emitters" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_use_fusion_emitters=false"
        ).strip()


def ensure_host_device_count(n: int) -> None:
    """Request ``n`` virtual host-platform devices (no-op if any count
    is already configured)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

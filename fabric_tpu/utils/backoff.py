"""Capped exponential backoff with jitter — the one retry cadence the
commit path shares.

Two hot consumers: the deliver loop's orderer reconnects (which used
to spin on a fixed 0.2 s — an orderer outage turned every peer into a
connect storm) and the validator's device-verify retries (a transient
XLA launch failure deserves a brief, bounded pause, not a tight loop
against a wedged runtime).  Both want the same shape: delays that grow
``factor``× per consecutive failure, never exceed ``cap``, carry
full jitter (each delay is drawn uniformly from [delay·(1−jitter),
delay]) so a fleet of peers doesn't reconnect in lockstep, and reset
to ``base`` the moment progress happens.

The class only COMPUTES delays — callers sleep (``time.sleep`` on
worker threads, ``asyncio.sleep`` on the loop), so one implementation
serves both worlds.  Seedable for deterministic tests.
"""

from __future__ import annotations

import math
import random


class Backoff:
    """Capped exponential delay sequence with full jitter.

    >>> bo = Backoff(base=0.2, cap=5.0, rng=random.Random(0))
    >>> bo.next()  # ~0.2, then ~0.4, ~0.8 ... capped at 5.0
    """

    def __init__(self, base: float = 0.2, cap: float = 15.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: random.Random | None = None):
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError(
                f"Backoff(base={base}, cap={cap}, factor={factor}): "
                "need base > 0, cap >= base, factor >= 1"
            )
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"Backoff jitter {jitter}: must be in [0, 1]")
        self.base, self.cap, self.factor = base, cap, factor
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._attempt = 0
        # smallest exponent at which base*factor**k already reaches
        # cap: peek() clamps to it so a long outage (attempt ~1024 at
        # factor 2.0) cannot overflow float exponentiation
        self._exp_cap = (
            0 if factor == 1.0
            else math.ceil(math.log(cap / base, factor))
        )

    @property
    def attempt(self) -> int:
        """Consecutive failures since the last reset()."""
        return self._attempt

    def peek(self) -> float:
        """The un-jittered delay the next ``next()`` would scale."""
        return min(
            self.cap,
            self.base * self.factor ** min(self._attempt, self._exp_cap),
        )

    def next(self) -> float:
        """Record one failure and return the delay to sleep before the
        next attempt."""
        d = self.peek()
        self._attempt += 1
        if self.jitter:
            lo = d * (1.0 - self.jitter)
            d = lo + self._rng.random() * (d - lo)
        return d

    def reset(self) -> None:
        """Progress happened: the next failure starts from ``base``."""
        self._attempt = 0

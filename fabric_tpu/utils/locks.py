"""Async reader/writer lock for the endorsement-vs-commit seam.

The reference's transaction manager takes a SHARED lock for simulation
and an exclusive one for the committer
(core/ledger/kvledger/txmgmt/txmgr/lockbased_txmgr.go; endorser.go:379)
— so client endorsements proceed in parallel with each other and only
serialize against block commits.  Write-preferring: a waiting committer
blocks NEW readers, so a stream of endorsements cannot starve the
commit pipeline."""

from __future__ import annotations

import asyncio
from contextlib import asynccontextmanager


class AsyncRWLock:
    def __init__(self):
        self._readers = 0
        self._writer_active = False
        self._writers_waiting = 0
        self._cond: asyncio.Condition | None = None

    def _c(self) -> asyncio.Condition:
        # lazily bound to the running loop (nodes are constructed
        # before their event loop starts in some tests)
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    @asynccontextmanager
    async def reader(self):
        cond = self._c()
        async with cond:
            await cond.wait_for(
                lambda: not self._writer_active and not self._writers_waiting
            )
            self._readers += 1
        try:
            yield
        finally:
            async with cond:
                self._readers -= 1
                cond.notify_all()

    @asynccontextmanager
    async def writer(self):
        cond = self._c()
        async with cond:
            self._writers_waiting += 1
            try:
                await cond.wait_for(
                    lambda: not self._writer_active and self._readers == 0
                )
                self._writer_active = True
            finally:
                self._writers_waiting -= 1
        try:
            yield
        finally:
            async with cond:
                self._writer_active = False
                cond.notify_all()

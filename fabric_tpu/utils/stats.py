"""Tiny shared statistics helpers (stdlib-only).

One percentile convention for every stats surface the autopilot
reads: the sidecar scheduler's queue ages and the sign batcher's
wait/occupancy windows must not disagree on what "p99" means.
"""

from __future__ import annotations

import math


def nearest_rank(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of a pre-sorted list (0 < q <= 100):
    rank = ceil(q/100 * n).  (round(x + 0.5) is NOT ceil — banker's
    rounding sends exact .5 midpoints to the even rank.)"""
    if not sorted_vals:
        return 0.0
    rank = math.ceil(q / 100.0 * len(sorted_vals))
    return sorted_vals[max(0, min(len(sorted_vals) - 1, rank - 1))]

"""Gossip layer: membership, private-data dissemination, anti-entropy
state transfer, org-leader election.

Reference mapping (SURVEY §2.6):
* membership heartbeats (gossip/discovery/discovery_impl.go) →
  ``GossipPing`` probes refreshing alive/height in the PeerRegistry;
* pvtdata distribution at endorsement
  (gossip/privdata/distributor.go) → ``PvtPush`` into peers' transient
  stores; commit-time pulls (pull.go) → ``PvtPull`` answered from the
  transient store or the committed pvtdata store;
* state transfer / anti-entropy (gossip/state/state.go:584-610) → a
  per-channel task comparing heights with members and pulling missing
  block ranges over the peers' DeliverBlocks stream;
* leader election (gossip/election) → deterministic lowest-endpoint
  election among the org's ALIVE peers — the reference's static
  org-leader mode (useLeaderElection=false) made automatic.

Block dissemination itself stays pull-based (peers pull from the
orderer or from each other), which the reference also supports; the
epidemic push layer is intentionally replaced — on a TPU pod the
bottleneck is the commit pipeline, not fan-out bandwidth.
"""

from __future__ import annotations

import asyncio
import json
import logging

from fabric_tpu.comm.rpc import RpcClient

log = logging.getLogger("fabric_tpu.gossip")


def _enc_cleartext(cleartext: dict) -> dict:
    return {
        f"{ns}\x00{coll}": {
            k: (v.hex() if v is not None else None) for k, v in kv.items()
        }
        for (ns, coll), kv in cleartext.items()
    }


def _dec_cleartext(data: dict) -> dict:
    out = {}
    for nscoll, kv in data.items():
        ns, _, coll = nscoll.partition("\x00")
        out[(ns, coll)] = {
            k: (bytes.fromhex(v) if v is not None else None)
            for k, v in kv.items()
        }
    return out


class GossipService:
    def __init__(self, node):
        self.node = node
        self._tasks: list[asyncio.Task] = []
        self._clients: dict[tuple, RpcClient] = {}

    # -- wiring ------------------------------------------------------------

    def register(self) -> "GossipService":
        s = self.node.server
        s.register_unary("GossipPing", self._on_ping)
        s.register_unary("PvtPush", self._on_pvt_push)
        s.register_unary("PvtPull", self._on_pvt_pull)
        for chan in self.node.channels.values():
            chan.pvt_puller = self.pull_pvt_for(chan.id)
        return self

    def _ssl(self):
        tls = getattr(self.node, "tls", None)
        return tls.client_ctx() if tls else None

    async def _client(self, host, port) -> RpcClient:
        key = (host, port)
        cli = self._clients.get(key)
        if cli is None or cli.conn is None or cli.conn.closed.is_set():
            cli = RpcClient(host, port, ssl_ctx=self._ssl())
            await cli.connect()
            self._clients[key] = cli
        return cli

    async def stop(self):
        for t in self._tasks:
            t.cancel()
        for cli in self._clients.values():
            try:
                await cli.close()
            except (OSError, RuntimeError):
                pass  # peer already gone

    # -- membership --------------------------------------------------------

    async def _on_ping(self, req: bytes) -> bytes:
        return json.dumps({
            "alive": True,
            "id": self.node.id,
            "heights": {cid: ch.height for cid, ch in self.node.channels.items()},
        }).encode()

    async def probe_members(self) -> dict:
        """Ping every registered peer; refresh alive/height state —
        a failed probe marks the peer DEAD (the reference's alive/dead
        expiration, gossip/discovery/discovery_impl.go) so election
        and dissemination stop counting on it.
        → {(host, port): ping-result | None}."""
        out = {}
        loop = asyncio.get_event_loop()
        for org, peers in self.node.registry.peers.items():
            for p in peers:
                try:
                    cli = await self._client(p.host, p.port)
                    raw = await asyncio.wait_for(
                        cli.unary("GossipPing", b"{}"), 3.0
                    )
                    res = json.loads(raw)
                    p.heights = dict(res.get("heights", {}))
                    p.height = max(p.heights.values(), default=0)
                    p.alive = True
                    p.last_seen = loop.time()
                    out[(p.host, p.port)] = res
                except Exception:
                    p.alive = False
                    self._clients.pop((p.host, p.port), None)
                    out[(p.host, p.port)] = None
        return out

    def elect_leader(self, my_org_peers: list, my_endpoint: tuple) -> bool:
        """Deterministic org-leader election: lowest (host, port) among
        ALIVE org peers + self wins (gossip/election analog).  Peers
        whose last probe failed are excluded — a dead lowest-endpoint
        peer must not win forever (ADVICE r3)."""
        candidates = [my_endpoint] + [
            (p.host, p.port) for p in my_org_peers if p.alive is not False
        ]
        return min(candidates) == my_endpoint

    # -- pvtdata dissemination --------------------------------------------

    def _my_org(self) -> str | None:
        signer = getattr(self.node, "signer", None)
        return getattr(signer, "msp_id", None)

    @staticmethod
    def _members(chan, ns: str, coll: str, own_org: str | None) -> set:
        """Eligible orgs for a collection (distributor.go:180-235
        AccessFilter).  An UNDEFINED collection is maximally private:
        only the endorsing org itself may hold the cleartext — never
        'everyone', which would void the confidentiality feature."""
        cfg = chan.collection_config(ns, coll) if chan is not None else None
        if cfg is None:
            return {own_org} if own_org else set()
        return set(cfg.get("member_orgs", []))

    async def _on_pvt_push(self, req: bytes) -> bytes:
        q = json.loads(req)
        chan = self.node.channels.get(q["channel"])
        if chan is None:
            return b'{"status": 404}'
        # receiver-side eligibility: never STORE cleartext this org is
        # not a collection member of, whatever the sender claims
        my = self._my_org()
        data = {
            (ns, coll): kv
            for (ns, coll), kv in _dec_cleartext(q["data"]).items()
            if my in self._members(chan, ns, coll, my)
        }
        if not data:
            return b'{"status": 403}'
        chan.transient.persist(q["txid"], data, int(q.get("height", 0)))
        return b'{"status": 200}'

    @staticmethod
    def _pull_signable(q: dict) -> bytes:
        core = {k: v for k, v in q.items() if k not in ("sig",)}
        return json.dumps(core, sort_keys=True).encode()

    async def _on_pvt_pull(self, req: bytes) -> bytes:
        q = json.loads(req)
        chan = self.node.channels.get(q["channel"])
        if chan is None:
            return b'{"status": 404}'
        ns, coll = q["ns"], q["coll"]
        # caller eligibility: the pull is signed by the requesting
        # peer's identity; it must be a valid channel member of a
        # collection member org (pull.go access checks).  mTLS (comm
        # layer) binds the transport to the same identity.
        try:
            ident = chan.validator.msp.deserialize_identity(
                bytes.fromhex(q["identity"])
            )
            if not ident.is_valid:
                raise ValueError("invalid identity")
            if not ident.verify(
                self._pull_signable(q), bytes.fromhex(q["sig"])
            ):
                raise ValueError("bad signature")
            if ident.msp_id not in self._members(
                chan, ns, coll, self._my_org()
            ):
                raise ValueError("org not a collection member")
        except Exception as e:
            log.debug("pvt pull refused: %s", e)
            return b'{"status": 403}'
        # transient store first (endorsement-time data)
        clear = chan.transient.get(q["txid"]).get((ns, coll))
        if clear is None and "block" in q:
            stored = chan.ledger.pvtdata.get_pvt_data(int(q["block"])).get(
                (int(q["txnum"]), ns, coll)
            )
            if stored is not None:
                from fabric_tpu.peer.transient import decode_kv

                clear = decode_kv(stored)
        if clear is None:
            return b'{"status": 404}'
        return json.dumps({
            "status": 200,
            "data": {k: (v.hex() if v is not None else None)
                     for k, v in clear.items()},
        }).encode()

    async def push_pvt(self, channel: str, txid: str, cleartext: dict,
                       height: int) -> None:
        """Distribute endorsement-time pvt data to ELIGIBLE peers only
        (distributor.go:180-235: AccessFilter + required/maximum peer
        counts): per collection, push to member-org peers up to
        max_peer_count; fewer than required_peer_count successful
        deliveries is logged as a dissemination shortfall."""
        chan = self.node.channels.get(channel)
        my = self._my_org()
        for (ns, coll), kv in cleartext.items():
            members = self._members(chan, ns, coll, my)
            cfg = chan.collection_config(ns, coll) if chan else None
            max_peers = int((cfg or {}).get("max_peer_count", 0) or 0)
            required = int((cfg or {}).get("required_peer_count", 0) or 0)
            if cfg is not None and max_peers == 0:
                # maximumPeerCount 0 means NO endorsement-time
                # dissemination (reconciliation-only delivery), not
                # "unlimited" (pvtdata/distributor.go contract)
                if required > 0:
                    # misconfigured (reference rejects max < required
                    # at definition time): surface the zero-push risk
                    log.warning(
                        "collection %s/%s requires %d peers but "
                        "max_peer_count=0 disables eager push — "
                        "skipping dissemination", ns, coll, required,
                    )
                continue
            # alive members first (probe liveness); max_peer_count caps
            # SUCCESSFUL deliveries, not attempts — a dead peer must
            # not consume the cap while a live member goes untried
            targets = sorted(
                (p for org, peers in self.node.registry.peers.items()
                 if org in members for p in peers),
                key=lambda p: (p.alive is False, p.host, p.port),
            )
            payload = json.dumps({
                "channel": channel, "txid": txid, "height": height,
                "data": _enc_cleartext({(ns, coll): kv}),
            }).encode()
            acks = 0
            for p in targets:
                if max_peers > 0 and acks >= max_peers:
                    break
                try:
                    cli = await self._client(p.host, p.port)
                    res = json.loads(await asyncio.wait_for(
                        cli.unary("PvtPush", payload), 3.0
                    ))
                    if res.get("status") == 200:
                        acks += 1
                except Exception as e:
                    log.debug("pvt push to %s:%s failed: %s", p.host, p.port, e)
            if acks < required:
                log.warning(
                    "pvt dissemination shortfall for %s/%s: %d acks, "
                    "required %d", ns, coll, acks, required,
                )

    def pull_pvt_for(self, channel: str):
        signer = getattr(self.node, "signer", None)

        async def pull(txid, block_num, txnum, ns, coll):
            q = {
                "channel": channel, "txid": txid, "block": block_num,
                "txnum": txnum, "ns": ns, "coll": coll,
            }
            if signer is not None:
                q["identity"] = signer.serialized.hex()
                q["sig"] = signer.sign(self._pull_signable(q)).hex()
            req = json.dumps(q).encode()
            for org, peers in self.node.registry.peers.items():
                for p in peers:
                    try:
                        cli = await self._client(p.host, p.port)
                        raw = await asyncio.wait_for(
                            cli.unary("PvtPull", req), 3.0
                        )
                        res = json.loads(raw)
                        if res.get("status") == 200:
                            return {
                                k: (bytes.fromhex(v) if v is not None else None)
                                for k, v in res["data"].items()
                            }
                    except Exception as e:
                        log.debug("pvt pull from peer failed: %s", e)
                        continue
            return None

        return pull

    # -- anti-entropy state transfer ---------------------------------------

    async def _pull_blocks_from_peer(self, chan, host, port, stop_at: int):
        cli = RpcClient(host, port, ssl_ctx=self._ssl())
        await cli.connect()
        try:
            stream = await cli.open_stream("DeliverBlocks")
            await stream.send(json.dumps({
                "channel": chan.id, "start": chan.height, "stop": stop_at,
            }).encode())
            from fabric_tpu.protos import common_pb2

            async for raw in stream:
                blk = common_pb2.Block()
                blk.ParseFromString(raw)
                if blk.header.number < chan.height:
                    continue
                await chan.commit_block(blk)
        finally:
            await cli.close()

    def start_anti_entropy(self, channel: str, interval: float = 1.0):
        """Per-channel catch-up loop (state.go:584 antiEntropy): probe
        members; when behind, pull the missing range from the peer
        that has it.

        Anti-entropy commits through ``commit_block`` concurrently
        with the deliver driver, so the channel is pinned to SERIAL
        commit mode: a depth-2 deliver pipeline validates outside the
        commit lock, and a concurrent anti-entropy commit would race
        its state reads (and collide at the ledger with in-flight
        heights).  Serializing both paths through the writer lock is
        the safe composition."""
        chan = self.node.channels[channel]
        chan.pipeline_depth = 1

        async def loop():
            while True:
                try:
                    await asyncio.sleep(interval)
                    await self.probe_members()
                    best, best_h = None, chan.height
                    for org, peers in self.node.registry.peers.items():
                        for p in peers:
                            ph = p.heights.get(channel, 0)
                            if ph > best_h:
                                best, best_h = p, ph
                    if best is not None:
                        await self._pull_blocks_from_peer(
                            chan, best.host, best.port, best_h - 1
                        )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.debug("anti-entropy %s: %s", channel, e)

        task = asyncio.ensure_future(loop())
        self._tasks.append(task)
        return task

    def start_reconciler(self, channel: str, interval: float = 2.0):
        """Background pvtdata reconciler (reconcile.go): retry pulling
        collections recorded missing at commit time."""
        chan = self.node.channels[channel]
        pull = self.pull_pvt_for(channel)

        async def loop():
            while True:
                try:
                    await asyncio.sleep(interval)
                    missing = chan.ledger.pvtdata.missing_data(chan.height)
                    for block, txnum, ns, coll in missing:
                        blk = chan.ledger.blocks.get_block(block)
                        if blk is None:
                            continue
                        got = await pull("", block, txnum, ns, coll)
                        if got is None:
                            continue
                        ok = self._verify_and_apply(
                            chan, blk, block, txnum, ns, coll, got
                        )
                        if ok:
                            log.info(
                                "reconciled pvt (%d,%d,%s,%s)",
                                block, txnum, ns, coll,
                            )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.debug("reconciler %s: %s", channel, e)

        task = asyncio.ensure_future(loop())
        self._tasks.append(task)
        return task

    def _verify_and_apply(self, chan, blk, block, txnum, ns, coll, clear) -> bool:
        """Hash-verify pulled data against the committed block's rwset,
        then commit it to pvt state + pvtdata store."""
        import json as _json

        from fabric_tpu import protoutil
        from fabric_tpu.ledger.rwset import TxRWSet
        from fabric_tpu.ledger.statedb import UpdateBatch
        from fabric_tpu.peer.coordinator import _match_cleartext
        from fabric_tpu.protos import common_pb2

        try:
            env = protoutil.unmarshal(common_pb2.Envelope, blk.data.data[txnum])
            _, _, cap, prp, cca = protoutil.extract_action(env)
            rw = TxRWSet.from_bytes(cca.results)
        except Exception:
            return False
        writes = rw.ns.get(ns, None)
        if writes is None:
            return False
        hashed = writes.hashed.get(coll, {}).get("writes", {})
        kv = _match_cleartext(hashed, clear)
        if kv is None:
            return False
        batch = UpdateBatch()
        for key, value in kv.items():
            if value is None:
                batch.delete(f"{ns}${coll}", key, (block, txnum))
            else:
                batch.put(f"{ns}${coll}", key, value, (block, txnum))
        chan.ledger.state.apply_updates(batch, None)
        from fabric_tpu.peer.transient import encode_kv

        chan.ledger.pvtdata.resolve_missing(block, txnum, ns, coll, encode_kv(kv))
        return True

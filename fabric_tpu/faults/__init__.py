"""fabric_tpu.faults — deterministic fault injection (see plan.py)."""

from fabric_tpu.faults.plan import (  # noqa: F401
    ENV_SEED,
    ENV_SPEC,
    FaultPlan,
    FaultSpecError,
    InjectedFault,
    afire,
    configure,
    fire,
    install,
    on_crash,
    plan,
    remove_crash_hook,
    reset,
    shield,
)

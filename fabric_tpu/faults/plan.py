"""Deterministic fault-injection registry for the commit path.

The north-star deployment is one device fabric validating blocks for
many peers: a TPU launch failure, a wedged staging worker, or a crash
mid-fsync must degrade ONE block's latency, not tear down a channel.
Hardening that requires reproducing those failures on demand — this
module is the chaos harness: a seedable :class:`FaultPlan` mapping
**named injection points** in the hot path to **fault kinds**, armed
per process and consulted by tiny ``fire(point)`` hooks threaded
through the code that must survive:

==============================  ============================================
injection point                 fires
==============================  ============================================
``p256v3.verify_launch``        inside the ops-level verify dispatch
``validator.verify_launch``     DeviceLaneGuard's device-lane attempt
                                (BlockValidator AND the toy validators the
                                crypto-free chaos tests drive)
``validator.stage2``            the fused stage-2 dispatch/sync
``hostpool.task``               inside every HostStagePool worker task
``pipeline.prefetch``           CommitPipeline's prefetch-thread stage
``pipeline.launch``             CommitPipeline's caller-thread launch stage
``pipeline.commit``             CommitPipeline's committer-thread stage
``peer.ledger_commit``          PeerChannel._commit_inner, before the ledger
``ledger.fsync.before``         BlockStore, right before ``os.fsync``
``ledger.fsync.after``          BlockStore, right after ``os.fsync``
``deliver.read``                the deliver stream reader, per block
``rpc.frame``                   comm.rpc frame SEND (every framed-RPC link,
                                the sidecar stream included) — async-aware,
                                so latency slows one stream, not the loop
``sidecar.request``             sidecar server request admission, per batch
``sidecar.dispatch``            the sidecar scheduler's coalesced device
                                dispatch (cross-tenant batch group)
==============================  ============================================

Fault kinds:

* ``raise``      — raise :class:`InjectedFault` (a RuntimeError),
* ``latency``    — sleep ``ms`` milliseconds (device stall / slow disk),
* ``disconnect`` — raise ``ConnectionResetError`` (stream torn down),
* ``truncate``   — raise an ``asyncio.IncompleteReadError``-shaped
  ``ConnectionResetError`` (stream cut mid-frame),
* ``crash``      — ``os._exit(86)``: the kill-mid-fsync crash tests run
  this in a child process and assert the ledger replays to a
  consistent height on reopen.

Spec string (the ``FABTPU_FAULTS`` env var / nodeconfig ``faults``
knob)::

    point:kind[:p=0.5][:n=3][:after=2][:ms=50] [; more specs]

``p``     trigger probability per arrival (default 1.0; each rule draws
          from its OWN ``random.Random`` derived from (seed, point,
          kind) so a draw depends only on that rule's arrival count —
          seeded runs replay exactly even when OTHER points' arrivals
          interleave differently across threads between runs),
``n``     total trigger budget (default unlimited),
``after`` skip the first k arrivals at the point (deterministic
          placement: "the 6th block's launch fails"),
``ms``    sleep for ``latency``.

Example — three device-launch failures then one deliver disconnect::

    FABTPU_FAULTS='validator.verify_launch:raise:n=3;deliver.read:disconnect:n=1:after=5'

Everything defaults OFF: with no spec armed, ``fire()`` is one module
attribute read and a ``None`` check — tier-1 and production hosts pay
nothing.  Every triggered fault also rides the
``faults_injected_total{point,kind}`` counter so a chaos run's injected
load is observable next to the recovery metrics it provokes.

``shield()`` marks the current thread as running a RECOVERY path (the
degraded CPU fallback re-verifying a block the faulty device lane
dropped): arrivals from a shielded thread never trigger.  Without it a
persistent device fault would chase the fallback through the shared
ops entry points and no experiment could ever prove recovery.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time

_KINDS = ("raise", "latency", "disconnect", "truncate", "crash")

ENV_SPEC = "FABTPU_FAULTS"
ENV_SEED = "FABTPU_FAULTS_SEED"


class FaultSpecError(ValueError):
    """A malformed fault spec string, phrased for the operator."""


class InjectedFault(RuntimeError):
    """Raised by a ``raise``-kind injection point."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


class _Rule:
    __slots__ = ("point", "kind", "p", "n", "after", "ms", "arrivals",
                 "fired", "rng")

    def __init__(self, point: str, kind: str, p: float = 1.0,
                 n: int | None = None, after: int = 0, ms: float = 0.0):
        self.point, self.kind = point, kind
        self.p, self.n, self.after, self.ms = p, n, after, ms
        self.arrivals = 0  # times the point was reached for this rule
        self.fired = 0     # times the fault actually triggered
        self.rng: random.Random | None = None  # set by FaultPlan


class FaultPlan:
    """A parsed, armed set of injection rules (see module docstring).

    Thread-safe: budgets and the RNG are guarded by one lock, taken
    only at points that HAVE rules — unmatched points never lock.
    """

    def __init__(self, spec: str = "", seed: int | None = None):
        self.spec = spec
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: dict[str, list[_Rule]] = {}
        for i, rule in enumerate(self._parse(spec)):
            # per-rule RNG derived from (seed, point, kind, position):
            # a probability draw depends only on this rule's OWN
            # arrival count, never on how other points' arrivals
            # interleave across threads — so a seeded run replays even
            # under depth-2 scheduling noise.  (A str seed hashes via
            # sha512, stable across processes unlike hash().)
            rule.rng = (
                random.Random(f"{seed}:{rule.point}:{rule.kind}:{i}")
                if seed is not None else random.Random()
            )
            self._rules.setdefault(rule.point, []).append(rule)

    @staticmethod
    def _parse(spec: str) -> list[_Rule]:
        rules = []
        for part in (p.strip() for p in spec.split(";")):
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise FaultSpecError(
                    f"fault spec {part!r}: expected 'point:kind[:k=v...]'"
                )
            point, kind = fields[0].strip(), fields[1].strip()
            if kind not in _KINDS:
                raise FaultSpecError(
                    f"fault spec {part!r}: unknown kind {kind!r} "
                    f"(expected one of {', '.join(_KINDS)})"
                )
            kw: dict = {}
            for f in fields[2:]:
                k, _, v = f.partition("=")
                k = k.strip()
                try:
                    if k == "p":
                        kw["p"] = float(v)
                    elif k == "n":
                        kw["n"] = int(v)
                    elif k == "after":
                        kw["after"] = int(v)
                    elif k == "ms":
                        kw["ms"] = float(v)
                    else:
                        raise FaultSpecError(
                            f"fault spec {part!r}: unknown param {k!r} "
                            "(expected p/n/after/ms)"
                        )
                except ValueError as e:
                    if isinstance(e, FaultSpecError):
                        raise
                    raise FaultSpecError(
                        f"fault spec {part!r}: cannot parse '{k}={v}'"
                    ) from None
            if kw.get("p", 1.0) < 0 or kw.get("p", 1.0) > 1:
                raise FaultSpecError(
                    f"fault spec {part!r}: p must be in [0, 1]"
                )
            if kind == "latency" and kw.get("ms", 0.0) <= 0:
                raise FaultSpecError(
                    f"fault spec {part!r}: latency needs ms=<positive>"
                )
            rules.append(_Rule(point, kind, **kw))
        return rules

    @property
    def points(self) -> tuple[str, ...]:
        return tuple(sorted(self._rules))

    def _admit(self, rule: _Rule) -> bool:
        """One arrival against ``rule``'s budget/probability; True when
        the fault should trigger (and has been counted as fired)."""
        with self._lock:
            rule.arrivals += 1
            if rule.arrivals <= rule.after:
                return False
            if rule.n is not None and rule.fired >= rule.n:
                return False
            if rule.p < 1.0 and rule.rng.random() >= rule.p:
                return False
            rule.fired += 1
        _injected_counter().add(1, point=rule.point, kind=rule.kind)
        return True

    def fire(self, point: str, **ctx) -> None:
        """Arrival at ``point``: trigger any armed rule whose budget
        and probability allow.  May raise, sleep, or exit the process;
        returns normally otherwise."""
        rules = self._rules.get(point)
        if not rules:
            return
        if _shielded():
            return
        for rule in rules:
            if self._admit(rule):
                self._trigger(rule, point, ctx)

    async def afire(self, point: str, **ctx) -> None:
        """``fire`` for async-context points (``deliver.read``):
        latency faults await ``asyncio.sleep`` so an armed plan slows
        ONE stream instead of freezing the whole event loop."""
        rules = self._rules.get(point)
        if not rules:
            return
        if _shielded():
            return
        for rule in rules:
            if self._admit(rule):
                if rule.kind == "latency":
                    await asyncio.sleep(rule.ms / 1000.0)
                else:
                    self._trigger(rule, point, ctx)

    @staticmethod
    def _trigger(rule: _Rule, point: str, ctx: dict) -> None:
        if rule.kind == "latency":
            time.sleep(rule.ms / 1000.0)
            return
        if rule.kind == "raise":
            raise InjectedFault(point)
        if rule.kind == "disconnect":
            raise ConnectionResetError(f"injected disconnect at {point}")
        if rule.kind == "truncate":
            raise ConnectionResetError(
                f"injected truncated stream at {point}"
            )
        # crash: hard process death with NOTHING flushed — the
        # crash-consistency tests run this in a child process.
        # ``os._exit`` skips atexit by design (that is the point of the
        # fault), so pre-crash hooks (the black-box flight recorder's
        # last-gasp incident dump, observe/blackbox.py) run HERE, each
        # contained — a broken hook must not save the process from its
        # injected death
        for hook in list(_crash_hooks):
            try:
                hook(point)
            except Exception:  # fabtpu: noqa(FT005)
                pass  # dying anyway; the crash semantics win
        os._exit(86)

    def stats(self) -> dict:
        """{point: [{kind, arrivals, fired}]} — bench extras read this
        so a chaos run's JSON states exactly what was injected."""
        with self._lock:
            return {
                point: [
                    {"kind": r.kind, "arrivals": r.arrivals,
                     "fired": r.fired}
                    for r in rules
                ]
                for point, rules in sorted(self._rules.items())
            }

    def fired(self, point: str | None = None) -> int:
        with self._lock:
            rules = (
                self._rules.get(point, ()) if point is not None
                else [r for rs in self._rules.values() for r in rs]
            )
            return sum(r.fired for r in rules)


def _injected_counter():
    from fabric_tpu.ops_metrics import global_registry

    return global_registry().counter(
        "faults_injected_total", "chaos faults triggered by point and kind"
    )


# -- process-global plan ----------------------------------------------------

_plan: FaultPlan | None = None
_tl = threading.local()

#: pre-crash hooks: run (contained) right before a ``crash``-kind
#: fault's ``os._exit`` — the one edge atexit cannot see.  The
#: black-box recorder registers its incident dump here.
_crash_hooks: list = []


def on_crash(fn) -> None:
    """Register ``fn(point)`` to run immediately before an injected
    ``crash`` fault hard-exits the process.  Idempotent."""
    if fn not in _crash_hooks:
        _crash_hooks.append(fn)


def remove_crash_hook(fn) -> None:
    try:
        _crash_hooks.remove(fn)
    except ValueError:
        pass  # already removed — detach is idempotent


def _shielded() -> bool:
    return getattr(_tl, "shield", 0) > 0


class shield:
    """Context manager marking the current thread as a recovery path:
    injection points it passes never trigger (see module docstring)."""

    def __enter__(self):
        _tl.shield = getattr(_tl, "shield", 0) + 1
        return self

    def __exit__(self, *exc):
        _tl.shield -= 1
        return False


def configure(spec: str = "", seed: int | None = None) -> FaultPlan | None:
    """Arm the process-global plan from a spec string (empty = disarm).
    ``seed`` defaults to ``FABTPU_FAULTS_SEED`` so a peer whose config
    re-arms the plan (nodeconfig ``faults`` → PeerNode) keeps the
    env-requested deterministic replay instead of silently dropping it.
    Returns the installed plan (None when disarmed)."""
    global _plan
    if seed is None:
        seed_s = os.environ.get(ENV_SEED, "")
        seed = int(seed_s) if seed_s else None
    _plan = FaultPlan(spec, seed=seed) if spec else None
    return _plan


def install(plan: FaultPlan | None) -> None:
    """Install an already-built plan (tests hold the object to read
    stats)."""
    global _plan
    _plan = plan


def reset() -> None:
    global _plan
    _plan = None


def plan() -> FaultPlan | None:
    return _plan


def fire(point: str, **ctx) -> None:
    """The hot-path hook: one global read when no plan is armed."""
    p = _plan
    if p is not None:
        p.fire(point, **ctx)


async def afire(point: str, **ctx) -> None:
    """Async hook for event-loop call sites (guard with ``plan() is
    not None`` so the unarmed path stays coroutine-free)."""
    p = _plan
    if p is not None:
        await p.afire(point, **ctx)


def _init_from_env() -> None:
    """Arm from FABTPU_FAULTS at import so child processes (the crash
    tests) and bench runs need no explicit plumbing."""
    spec = os.environ.get(ENV_SPEC, "")
    if spec:
        seed_s = os.environ.get(ENV_SEED, "")
        configure(spec, seed=int(seed_s) if seed_s else None)


_init_from_env()

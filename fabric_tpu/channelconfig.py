"""Channel configuration: bundle, policy-manager tree, config updates.

The reference represents each channel's consensus-governed configuration
as a versioned tree of groups/values/policies (common/channelconfig,
``Bundle`` built at core/peer/peer.go:247), with a named-policy tree
(``/Channel/Application/Writers`` ..., common/policies/policy.go) whose
inner nodes may be IMPLICIT_META policies (ANY/ALL/MAJORITY over a
sub-policy of the child groups, common/policies/implicitmeta.go), and
validates config-update transactions by (a) read-set version match,
(b) computing the delta, (c) evaluating each modified element's
mod_policy against the update's signatures (common/configtx/update.go,
validator.go).

TPU-native stance: channel config is pure control plane — tiny, rare,
branchy — so it stays host-side Python; its *outputs* (policy ASTs,
capability flags, MSP sets) feed the batch compiler
(crypto/policy.compile_plan) that shapes the device kernels.
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import dataclass, field

from fabric_tpu import protoutil
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSP, MSPManager, policy_from_proto, policy_to_proto
from fabric_tpu.protos import common_pb2, configtx_pb2, policies_pb2

_log = logging.getLogger("fabric_tpu.channelconfig")

# capability strings (common/capabilities/application.go)
CAP_V2_0 = "V2_0"

# ---------------------------------------------------------------------------
# Policy tree


@dataclass(frozen=True)
class ImplicitMeta:
    """ANY/ALL/MAJORITY over ``sub_policy`` of the child groups."""

    rule: int  # policies_pb2.ImplicitMetaPolicy.ANY / ALL / MAJORITY
    sub_policy: str


def policy_from_config(cp: configtx_pb2.ConfigPolicy):
    """ConfigPolicy → signature-policy AST or ImplicitMeta."""
    p = cp.policy
    if p.type == policies_pb2.Policy.SIGNATURE:
        env = protoutil.unmarshal(policies_pb2.SignaturePolicyEnvelope, p.value)
        return policy_from_proto(env)
    if p.type == policies_pb2.Policy.IMPLICIT_META:
        im = protoutil.unmarshal(policies_pb2.ImplicitMetaPolicy, p.value)
        return ImplicitMeta(rule=im.rule, sub_policy=im.sub_policy)
    raise ValueError(f"unsupported policy type {p.type}")


def config_policy(ast_or_meta, mod_policy: str = "Admins") -> configtx_pb2.ConfigPolicy:
    cp = configtx_pb2.ConfigPolicy(mod_policy=mod_policy)
    if isinstance(ast_or_meta, ImplicitMeta):
        im = policies_pb2.ImplicitMetaPolicy(
            sub_policy=ast_or_meta.sub_policy, rule=ast_or_meta.rule
        )
        cp.policy.type = policies_pb2.Policy.IMPLICIT_META
        cp.policy.value = im.SerializeToString()
    else:
        env = policy_to_proto(ast_or_meta)
        cp.policy.type = policies_pb2.Policy.SIGNATURE
        cp.policy.value = env.SerializeToString()
    return cp


@dataclass
class SignedData:
    """One signature over a config update: (identity, msg, sig) — the
    protoutil.SignedData shape (protoutil/signeddata.go:25-31)."""

    identity: bytes
    data: bytes
    signature: bytes


class PolicyManager:
    """Named-policy tree over the config group hierarchy.

    ``get("/Channel/Application/Writers")`` resolves exactly like the
    reference's manager (common/policies/policy.go:132): path segments
    are group names, the leaf is the policy name in that group.
    """

    def __init__(self, root_group: configtx_pb2.ConfigGroup, msp_manager: MSPManager):
        self.root = root_group
        self.msp = msp_manager

    def _group(self, path: list[str]) -> configtx_pb2.ConfigGroup | None:
        g = self.root
        for seg in path:
            if seg not in g.groups:
                return None
            g = g.groups[seg]
        return g

    def get(self, path: str):
        """path: '/Channel/App.../Name' (leading '/Channel' optional).
        → (policy AST | ImplicitMeta, group holding it) or None."""
        segs = [s for s in path.split("/") if s]
        if segs and segs[0] == "Channel":
            segs = segs[1:]
        if not segs:
            return None
        *grp_path, name = segs
        g = self._group(grp_path)
        if g is None or name not in g.policies:
            return None
        return policy_from_config(g.policies[name]), g

    def evaluate(self, path: str, signed: list[SignedData]) -> bool:
        got = self.get(path)
        if got is None:
            return False
        rule, group = got
        return self._eval(rule, group, signed)

    def _eval(self, rule, group: configtx_pb2.ConfigGroup,
              signed: list[SignedData]) -> bool:
        if isinstance(rule, ImplicitMeta):
            sub = rule.sub_policy
            children = [
                (policy_from_config(cg.policies[sub]), cg)
                for cg in group.groups.values()
                if sub in cg.policies
            ]
            n = len(children)
            if n == 0:
                return False
            need = {
                policies_pb2.ImplicitMetaPolicy.ANY: 1,
                policies_pb2.ImplicitMetaPolicy.ALL: n,
                policies_pb2.ImplicitMetaPolicy.MAJORITY: n // 2 + 1,
            }[rule.rule]
            got_n = sum(1 for r, g in children if self._eval(r, g, signed))
            return got_n >= need
        # signature policy: dedup by identity, verify, consume-evaluate
        # (SignatureSetToValidIdentities, common/policies/policy.go:360)
        seen: set[bytes] = set()
        idents, valid = [], []
        for sd in signed:
            if sd.identity in seen:
                continue
            seen.add(sd.identity)
            try:
                ident = self.msp.deserialize_identity(sd.identity)
            except Exception as e:
                _log.debug("policy eval: undeserializable identity: %s", e)
                continue
            idents.append(ident)
            valid.append(ident.is_valid and ident.verify(sd.data, sd.signature))
        plan = pol.compile_plan(rule)
        m = pol.match_matrix(idents, plan.principals)
        if idents:
            import numpy as np

            m = m & np.asarray(valid, bool)[:, None]
        return pol.evaluate(rule, m)


# ---------------------------------------------------------------------------
# Bundle


class Bundle:
    """Immutable view over one channel's Config (channelconfig.Bundle).

    Exposes: policy manager, MSP manager, capabilities, orderer batch
    parameters, application namespaces' endorsement defaults.
    """

    def __init__(self, channel_id: str, config: configtx_pb2.Config):
        self.channel_id = channel_id
        self.config = config
        self.msp_manager = self._build_msps(config.channel_group)
        self.policy_manager = PolicyManager(config.channel_group, self.msp_manager)

    @property
    def sequence(self) -> int:
        return self.config.sequence

    @staticmethod
    def _build_msps(root: configtx_pb2.ConfigGroup) -> MSPManager:
        mgr = MSPManager()
        def walk(g: configtx_pb2.ConfigGroup):
            if "MSP" in g.values:
                cfg = protoutil.unmarshal(configtx_pb2.MSPConfig, g.values["MSP"].value)
                if cfg.type == 1:  # IDEMIX (msp/idemix.go)
                    from fabric_tpu.crypto.idemix import IdemixMSP

                    mgr.add(IdemixMSP.from_config(cfg.config))
                else:
                    mgr.add(MSP.from_proto(cfg))
            for child in g.groups.values():
                walk(child)
        walk(root)
        return mgr

    def _capabilities(self, group: configtx_pb2.ConfigGroup) -> set[str]:
        if "Capabilities" not in group.values:
            return set()
        caps = protoutil.unmarshal(
            configtx_pb2.Capabilities, group.values["Capabilities"].value
        )
        return set(caps.capabilities)

    def channel_capabilities(self) -> set[str]:
        return self._capabilities(self.config.channel_group)

    def application_capabilities(self) -> set[str]:
        app = self.config.channel_group.groups.get("Application")
        return self._capabilities(app) if app is not None else set()

    def application_orgs(self) -> list[str]:
        app = self.config.channel_group.groups.get("Application")
        return sorted(app.groups) if app is not None else []

    def orderer_value(self, name: str, msg_type):
        ord_grp = self.config.channel_group.groups.get("Orderer")
        if ord_grp is None or name not in ord_grp.values:
            return None
        return protoutil.unmarshal(msg_type, ord_grp.values[name].value)

    def application_policy(self, name: str):
        got = self.policy_manager.get(f"/Channel/Application/{name}")
        return got[0] if got else None

    def application_policy_ast(self, name: str):
        """Application policy as a pure signature-policy AST suitable
        for the batch-plan compiler: IMPLICIT_META nodes flatten into
        NOutOf over the child groups' sub-policies (ANY→1, ALL→n,
        MAJORITY→⌊n/2⌋+1).  Exact vs the manager's independent
        per-child evaluation whenever org principal sets are disjoint —
        the invariant of org-scoped endorsement policies."""
        got = self.policy_manager.get(f"/Channel/Application/{name}")
        if got is None:
            return None
        return self._flatten(got[0], got[1])

    def _flatten(self, rule, group: configtx_pb2.ConfigGroup):
        if not isinstance(rule, ImplicitMeta):
            return rule
        children = [
            (policy_from_config(cg.policies[rule.sub_policy]), cg)
            for cg in group.groups.values()
            if rule.sub_policy in cg.policies
        ]
        if not children:
            return None
        n = len(children)
        need = {
            policies_pb2.ImplicitMetaPolicy.ANY: 1,
            policies_pb2.ImplicitMetaPolicy.ALL: n,
            policies_pb2.ImplicitMetaPolicy.MAJORITY: n // 2 + 1,
        }[rule.rule]
        subs = tuple(self._flatten(r, g) for r, g in children)
        if any(s is None for s in subs):
            return None
        return pol.NOutOf(need, subs)

    def hash(self) -> bytes:
        return hashlib.sha256(self.config.SerializeToString()).digest()


# ---------------------------------------------------------------------------
# Config updates (common/configtx/update.go + validator.go)


def bundle_from_genesis(channel_id: str, genesis_block) -> "Bundle":
    """Extract the channel config from a genesis/config block's first
    envelope → Bundle (the join-time trust-anchor derivation both the
    peer and the orderer's broadcast filters use)."""
    from fabric_tpu import protoutil
    from fabric_tpu.protos import common_pb2

    env = protoutil.unmarshal(common_pb2.Envelope, genesis_block.data.data[0])
    payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
    cfg_env = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
    return Bundle(channel_id, cfg_env.config)


class ConfigUpdateError(Exception):
    pass


def _walk_elements(group: configtx_pb2.ConfigGroup, path: str = ""):
    """Yield (path, kind, name, element) for every group/value/policy."""
    for name, g in group.groups.items():
        yield (path, "group", name, g)
        yield from _walk_elements(g, f"{path}/{name}")
    for name, v in group.values.items():
        yield (path, "value", name, v)
    for name, p in group.policies.items():
        yield (path, "policy", name, p)


def _find(group: configtx_pb2.ConfigGroup, path: str, kind: str, name: str):
    g = group
    for seg in [s for s in path.split("/") if s]:
        if seg not in g.groups:
            return None
        g = g.groups[seg]
    coll = {"group": g.groups, "value": g.values, "policy": g.policies}[kind]
    return coll[name] if name in coll else None


def authorize_update(bundle: Bundle, update_env: configtx_pb2.ConfigUpdateEnvelope):
    """Authorize + apply a config update against the current bundle.

    Returns the new Config proto.  Raises ConfigUpdateError on version
    mismatch or unsatisfied mod_policy.  Semantics per
    common/configtx/update.go: read-set versions must match current;
    every write-set element whose version is bumped is 'modified' and
    its (current) mod_policy must be satisfied by the update's
    signatures; unmodified write-set elements must carry the current
    version.
    """
    update = protoutil.unmarshal(configtx_pb2.ConfigUpdate, update_env.config_update)
    if update.channel_id and update.channel_id != bundle.channel_id:
        raise ConfigUpdateError(
            f"update for channel {update.channel_id!r} applied to {bundle.channel_id!r}"
        )
    current = bundle.config.channel_group

    # read-set: every referenced element must exist at the same version
    for path, kind, name, elem in _walk_elements(update.read_set):
        cur = _find(current, path, kind, name)
        if cur is None or cur.version != elem.version:
            raise ConfigUpdateError(
                f"read-set version mismatch at {path}/{name} ({kind})"
            )

    signed = [
        SignedData(
            identity=protoutil.unmarshal(
                common_pb2.SignatureHeader, cs.signature_header
            ).creator,
            data=cs.signature_header + update_env.config_update,
            signature=cs.signature,
        )
        for cs in update_env.signatures
    ]

    # root group version: _walk_elements yields children only, so the
    # channel group itself is checked here — a root bump gates on the
    # root mod_policy and is what authorizes root-level deletions
    root_cur = current
    root_new = update.write_set
    if root_new.version not in (root_cur.version, root_cur.version + 1):
        raise ConfigUpdateError(
            f"root group version jump: {root_cur.version} → {root_new.version}"
        )
    if root_new.version == root_cur.version + 1:
        mp = root_cur.mod_policy or "Admins"
        if not _eval_mod_policy(bundle, "", mp, signed):
            raise ConfigUpdateError(
                f"mod_policy {mp!r} not satisfied for the channel group"
            )

    # write-set: detect modifications, enforce mod_policy per element
    for path, kind, name, elem in _walk_elements(update.write_set):
        cur = _find(current, path, kind, name)
        if cur is not None and elem.version == cur.version:
            if kind != "group" and elem.SerializeToString() != cur.SerializeToString():
                raise ConfigUpdateError(
                    f"write-set modifies {path}/{name} without version bump"
                )
            continue
        if cur is not None and elem.version != cur.version + 1:
            raise ConfigUpdateError(
                f"write-set version jump at {path}/{name}: "
                f"{cur.version} → {elem.version}"
            )
        if cur is None and elem.version != 0:
            raise ConfigUpdateError(
                f"new element {path}/{name} must start at version 0"
            )
        # mod_policy source: the existing element, else the nearest
        # existing ancestor group's mod_policy
        mod_policy = (cur.mod_policy if cur is not None else "") or _ancestor_mod_policy(
            current, path
        )
        # a GROUP's mod_policy resolves relative to the group ITSELF;
        # values/policies resolve relative to their containing group
        # (common/configtx policyForItem semantics)
        base = f"{path}/{name}" if kind == "group" and cur is not None else path
        if not _eval_mod_policy(bundle, base, mod_policy, signed):
            raise ConfigUpdateError(
                f"mod_policy {mod_policy!r} not satisfied for {path}/{name}"
            )

    new_config = configtx_pb2.Config()
    new_config.CopyFrom(bundle.config)
    new_config.sequence = bundle.config.sequence + 1
    root_bumped = update.write_set.version > bundle.config.channel_group.version
    new_config.channel_group.version = update.write_set.version
    _apply_write_set(
        new_config.channel_group, update.write_set, version_bumped=root_bumped
    )
    return new_config


def _ancestor_mod_policy(current: configtx_pb2.ConfigGroup, path: str) -> str:
    g, mp = current, current.mod_policy
    for seg in [s for s in path.split("/") if s]:
        if seg not in g.groups:
            break
        g = g.groups[seg]
        mp = g.mod_policy or mp
    return mp or "Admins"


def _eval_mod_policy(bundle: Bundle, path: str, mod_policy: str,
                     signed: list[SignedData]) -> bool:
    """Resolve a mod_policy name relative to its group path, walking up
    toward the channel root like the reference's manager."""
    if mod_policy.startswith("/"):
        return bundle.policy_manager.evaluate(mod_policy, signed)
    segs = [s for s in path.split("/") if s]
    for i in range(len(segs), -1, -1):
        p = "/".join(segs[:i] + [mod_policy])
        if bundle.policy_manager.get("/" + p) is not None:
            return bundle.policy_manager.evaluate("/" + p, signed)
    return False


def _apply_write_set(target: configtx_pb2.ConfigGroup,
                     write: configtx_pb2.ConfigGroup,
                     version_bumped: bool = False) -> None:
    """Merge a write set into the current group tree.

    Deletion semantics per the reference's configmap (common/configtx/
    update.go): when a group's version is BUMPED, the write set defines
    the group's exact membership — current children absent from the
    write group are removed.  An unbumped group only overlays the
    elements it names."""
    if version_bumped:
        for name in [n for n in target.groups if n not in write.groups]:
            del target.groups[name]
        for name in [n for n in target.values if n not in write.values]:
            del target.values[name]
        for name in [n for n in target.policies if n not in write.policies]:
            del target.policies[name]
    for name, g in write.groups.items():
        if name not in target.groups:
            target.groups[name].CopyFrom(g)
        else:
            tgt = target.groups[name]
            bumped = g.version > tgt.version
            tgt.version = g.version
            if g.mod_policy:
                tgt.mod_policy = g.mod_policy
            _apply_write_set(tgt, g, version_bumped=bumped)
    for name, v in write.values.items():
        target.values[name].CopyFrom(v)
    for name, p in write.policies.items():
        target.policies[name].CopyFrom(p)


# ---------------------------------------------------------------------------
# Config-tx processing on the commit path (v20/validator.go:397-419)


class ConfigTxProcessor:
    """Holds the live bundle for one channel; validates CONFIG
    envelopes on the commit path and applies them on commit.

    The validator calls ``validate_config_tx``; the peer channel calls
    ``apply(cfg_env)`` after the block commits (core/peer/peer.go
    BundleSource update semantics).
    """

    def __init__(self, bundle: Bundle):
        self.bundle = bundle
        self.listeners: list = []

    def validate_config_tx(self, ptx, cfg_env: configtx_pb2.ConfigEnvelope) -> int:
        from fabric_tpu.protos import transaction_pb2

        C = transaction_pb2.TxValidationCode
        try:
            proposed = self._authorized_config(cfg_env)
        except (ConfigUpdateError, Exception):
            return C.INVALID_OTHER_REASON
        if proposed.SerializeToString() != cfg_env.config.SerializeToString():
            return C.INVALID_OTHER_REASON
        return C.VALID

    def _authorized_config(self, cfg_env: configtx_pb2.ConfigEnvelope):
        if not cfg_env.HasField("last_update"):
            raise ConfigUpdateError("config envelope missing last_update")
        payload = protoutil.unmarshal(
            common_pb2.Payload, cfg_env.last_update.payload
        )
        upd_env = protoutil.unmarshal(
            configtx_pb2.ConfigUpdateEnvelope, payload.data
        )
        return authorize_update(self.bundle, upd_env)

    def apply(self, cfg_env: configtx_pb2.ConfigEnvelope) -> Bundle:
        new = Bundle(self.bundle.channel_id, cfg_env.config)
        self.bundle = new
        for fn in self.listeners:
            fn(new)
        return new

"""fabric_tpu — a TPU-native permissioned execute-order-validate ledger framework.

A from-scratch rebuild of the capabilities of Hyperledger Fabric
(reference: PM-Master/fabric), designed TPU-first: the block-commit data
plane (batched SHA-256, batched ECDSA-P256 endorsement-signature
verification, endorsement-policy reduction, and MVCC read-set conflict
checking) runs as JAX/XLA kernels on TPU, while the control plane
(ordering, membership, lifecycle, gossip, storage) is an idiomatic host
framework.

Layer map (mirrors SURVEY.md §1 of the reference analysis):
  crypto/   — BCCSP-style crypto SPI, MSP identities, policy compiler
  ops/      — TPU kernels: sha256, p256 field/point, ecdsa, mvcc, policy eval
  models/   — assembled jittable pipelines (the "flagship model" = block
              validation pipeline)
  parallel/ — mesh sharding of the data plane (signature fan-out, MVCC)
  protos/   — wire format (the architecture contract between layers)
  ledger/   — block store, state DB SPI, history, kvledger commit
  ordering/ — blockcutter, ordering service (solo, raft)
  peer/     — endorser, committer, chaincode runtime, peer assembly
  utils/    — logging, metrics, config
"""

__version__ = "0.1.0"

"""Mesh sharding of the block-validation data plane.

The reference parallelizes block validation with a goroutine-per-tx
worker pool on one host (core/committer/txvalidator/v20/validator.go:
193-208, pool size peer.validatorPoolSize).  The TPU-native analog
shards the *batch* dimension of the data-plane kernels (signature
verify, hashing, MVCC) across a device mesh: every chip verifies a
slice of the block's signatures, and the validity bits are gathered by
XLA collectives over ICI — the "N-of-M policy parallelism" row of the
reference's parallelism inventory (SURVEY.md §2.10).

One axis ("data") suffices for the commit path: block batches are
embarrassingly parallel and the reduction (per-tx policy evaluation)
is a tiny boolean tree evaluated after an all-gather.  Multi-host
deployments replicate the whole pipeline per peer (the reference's
distributed-replication model), so the mesh spans one peer's chips.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("data",))


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (the batch/tx dim) over "data"; replicate the rest."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def shard_args(mesh: Mesh, *arrays):
    """Device-put arrays with axis-0 sharded over the mesh."""
    return tuple(
        jax.device_put(a, batch_sharding(mesh, a.ndim)) for a in arrays
    )

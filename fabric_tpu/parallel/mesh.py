"""Mesh sharding of the block-validation data plane.

The reference parallelizes block validation with a goroutine-per-tx
worker pool on one host (core/committer/txvalidator/v20/validator.go:
193-208, pool size peer.validatorPoolSize).  The TPU-native analog
shards the *batch* dimension of the data-plane kernels (signature
verify, hashing, MVCC) across a device mesh: every chip verifies a
slice of the block's signatures, and the validity bits are gathered by
XLA collectives over ICI — the "N-of-M policy parallelism" row of the
reference's parallelism inventory (SURVEY.md §2.10).

One axis ("data") suffices for the commit path: block batches are
embarrassingly parallel and the reduction (per-tx policy evaluation)
is a tiny boolean tree evaluated after an all-gather.  Multi-host
deployments replicate the whole pipeline per peer (the reference's
distributed-replication model), so the mesh spans one peer's chips.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("data",))


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (the batch/tx dim) over "data"; replicate the rest."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def shard_args(mesh: Mesh, *arrays):
    """Device-put arrays with axis-0 sharded over the mesh."""
    return tuple(
        jax.device_put(a, batch_sharding(mesh, a.ndim)) for a in arrays
    )


def resolve_mesh(mesh_devices: int) -> Mesh | None:
    """Production knob → mesh (the nodeconfig ``mesh_devices`` knob).

    0  = sharding off (single-device dispatch — the safe default on
         CPU-only hosts, where a virtual mesh only adds partition
         overhead);
    -1 = auto: all local devices, None when only one exists;
    n  = first n local devices (clamped to what exists; None if that
         leaves fewer than 2 — a 1-device mesh is just overhead).
    """
    if mesh_devices == 0:
        return None
    devices = jax.devices()
    n = len(devices) if mesh_devices < 0 else min(mesh_devices, len(devices))
    if n < 2:
        return None
    return Mesh(np.asarray(devices[:n]), axis_names=("data",))


def shard_state_table(mesh: Mesh | None, table):
    """Axis-0 shard the device-resident MVCC version table
    (fabric_tpu/state/residency.py) — the resident cache is a stage-2
    operand like every other, so it lives under the SAME data-mesh
    sharding the fused program's launch/static lanes use.  The table's
    slot count is a power of two (ResidencyManager rounds its capacity
    down), so 2/4/8-chip meshes always divide it exactly; functional
    scatter updates (``table.at[idx].set``) preserve the layout, and
    an unmeshed host gets the plain single-device array."""
    return shard_batch(mesh, table)


def shard_batch(mesh: Mesh | None, arr):
    """Device-put ONE array with axis 0 sharded over the mesh.

    Falls back to the unsharded array when the mesh is None or axis 0
    does not divide evenly (ragged microbatch tails, sub-minimum
    buckets) — the caller's dispatch then runs single-device for that
    array, which is always correct, just not parallel.  All production
    batch shapes are bucketed to powers of two ≥ 16 or multiples of
    512, so 2/4/8-chip meshes divide them exactly."""
    if mesh is None:
        return arr
    n = arr.shape[0] if arr.ndim else 0
    if n == 0 or n % mesh.size != 0:
        return arr
    return jax.device_put(arr, batch_sharding(mesh, arr.ndim))

"""Declarative partition rules over a process-spanning device mesh.

The reference parallelizes block validation with a goroutine-per-tx
worker pool on one host (core/committer/txvalidator/v20/validator.go:
193-208, pool size peer.validatorPoolSize) and scales further only by
replicating whole peers.  The TPU-native analog shards the data plane
of the validation kernels across a device mesh — and this module is
the ONE place that knows how: a **partition-rule registry** maps every
stage-2 operand family (verify launch frames, packed read planes,
policy tables, the MVCC version frame, the device-resident state
table) to a ``PartitionSpec``, and every dispatch site asks the
registry instead of hand-rolling ``NamedSharding`` (the FT019
``unruled-sharding`` rule polices the boundary).

Mesh anatomy: axis 0 of the mesh is ``"data"`` — the batch/tx/lane
dimension every data-plane family shards — and an optional second
axis ``"replica"`` replicates the whole pipeline across device groups
(a 2x4 grid runs 2-way data sharding replicated on 4 groups).  The
mesh can span ``jax.distributed`` processes: ``resolve_fabric`` with
a distributed topology initializes the coordinator once, after which
``jax.devices()`` enumerates every process's chips and the SAME rule
table shards over all of them — the classic per-host mesh
(``resolve_mesh``) is the 1-process special case.

Key-range state partitioning: the device-resident MVCC version table
(``fabric_tpu/state/residency.py``) is NOT sharded by raw axis 0 of
whatever happens to be in it — the residency manager lays slots out
range-major (key range ids from ``blake2b`` top bits, contiguous
range blocks per shard), so the ``state_table`` rule's axis-0
partition physically places each key range on its owning device and
admission/eviction/commit scatters route to the owner's slot block.

Degrade story: every shard helper falls back to the unsharded array
when the mesh is off or axis 0 is ragged vs the data axis — always
correct, just not parallel.  The fallback is COUNTED
(``mesh_shard_fallback_total{reason=}``) and the launch ledger tags
the dispatch row ``sharded=false``, so a block silently running
unparallel shows up on /launches instead of reading as mystery
``device_wait``.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fabric_tpu.parallel.topology import MeshTopology, parse_mesh_shape

_log = logging.getLogger("fabric_tpu.parallel.mesh")

#: mesh axis every data-plane family shards its axis 0 over
DATA_AXIS = "data"
#: optional second mesh axis: whole-pipeline replication groups
REPLICA_AXIS = "replica"


# ---------------------------------------------------------------------------
# the partition-rule registry


@dataclass(frozen=True)
class PartitionRule:
    """One operand family's partition law: which mesh axes its leading
    array dimensions map to (``()`` = fully replicated).  ``spec(ndim)``
    pads the tail with None — trailing dims always replicate (they are
    per-lane payload, never batch)."""

    family: str
    axes: tuple
    description: str

    def spec(self, ndim: int) -> P:
        names = list(self.axes[:ndim])
        names += [None] * (ndim - len(names))
        return P(*names)

    @property
    def replicated(self) -> bool:
        return not self.axes


_RULES: dict[str, PartitionRule] = {}


def register_rule(family: str, axes: tuple, description: str) -> PartitionRule:
    rule = PartitionRule(family, tuple(axes), description)
    _RULES[family] = rule
    return rule


# The rule table — every stage-2 operand family the fused dispatch
# uploads, plus the stage-1 verify frames and the sign lane.  Axis 0
# over "data" throughout is not an accident: every family's leading
# dim is the per-tx / per-endorsement / per-lane batch dim, and the
# reductions that cross it (policy scatter-min, the MVCC fixpoint
# matvec) are integer/boolean — exact in any collective order, which
# is what makes sharded ≡ unsharded bit-equality provable.
register_rule(
    "verify_lanes", (DATA_AXIS,),
    "packed ECDSA verify wire frames (ops/p256v3) — one row per "
    "signature lane",
)
register_rule(
    "sign_rows", (DATA_AXIS,),
    "sign-kernel limb rows (ops/p256sign) — one row per digest",
)
register_rule(
    "launch_frame", (DATA_AXIS,),
    "per-tx launch vector [T, 3] (creator | structural | ver_ok)",
)
register_rule(
    "policy_table", (DATA_AXIS,),
    "packed endorsement/policy planes [Eb, S*P + S + 1] (match | "
    "endo_idx | tx_of)",
)
register_rule(
    "static_pack", (DATA_AXIS,),
    "packed MVCC static block [T, R + W + 2Q] (read/write keys, "
    "range-query bounds)",
)
register_rule(
    "mvcc_frame", (DATA_AXIS,),
    "standalone MVCC version-frame operands (ops/mvcc prepared "
    "planes; per-tx rows)",
)
register_rule(
    "read_versions", (DATA_AXIS,),
    "expected per-read committed versions [T, R, 3] for the resident "
    "on-device compare",
)
register_rule(
    "state_table", (DATA_AXIS,),
    "device-resident MVCC version table [cap, 3] — KEY-RANGE "
    "partitioned: the residency manager lays slots out range-major, "
    "so this axis-0 split places each key range on its owning shard",
)
register_rule(
    "unique_read_pack", (),
    "per-unique-key slot/host-lane frame [Ub, 4] — tiny and gathered "
    "from every shard, so it replicates",
)


def rule_for(family: str) -> PartitionRule:
    """Registry probe — an unknown family is a programming error, not
    a silent replication."""
    try:
        return _RULES[family]
    except KeyError:
        raise KeyError(
            f"no partition rule for operand family {family!r} — "
            f"register it in fabric_tpu/parallel/mesh.py "
            f"(known: {sorted(_RULES)})"
        ) from None


def rules_table() -> list[dict]:
    """The rule table as rows (the dryrun/ops printout)."""
    return [
        {
            "family": r.family,
            "spec": "replicated" if r.replicated
            else " × ".join(r.axes) + " × …",
            "description": r.description,
        }
        for r in _RULES.values()
    ]


def spec_for(family: str, ndim: int) -> P:
    return rule_for(family).spec(ndim)


def sharding_for(mesh: Mesh, family: str, ndim: int) -> NamedSharding:
    """Family rule + mesh → the NamedSharding a jit ``in_shardings``
    slot or ``device_put`` wants."""
    return NamedSharding(mesh, spec_for(family, ndim))


# ---------------------------------------------------------------------------
# fallback accounting (the silent-unparallel counter)

_fb_lock = threading.Lock()
_fb_counts: dict[str, int] = {}
_fb_ctr = None  # lazy metrics counter


def _note_fallback(reason: str, family: str) -> None:
    global _fb_ctr
    with _fb_lock:
        _fb_counts[reason] = _fb_counts.get(reason, 0) + 1
        if _fb_ctr is None:
            from fabric_tpu.ops_metrics import global_registry

            _fb_ctr = global_registry().counter(
                "mesh_shard_fallback_total",
                "sharded device_puts that silently degraded to a "
                "single-device array (the dispatch stays correct but "
                "runs unparallel), by reason",
            )
    _fb_ctr.add(1, reason=reason, family=family)


def fallback_stats() -> dict:
    """Cumulative fallback counts by reason (bench extras / tests)."""
    with _fb_lock:
        return dict(_fb_counts)


# ---------------------------------------------------------------------------
# mesh construction


def data_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=(DATA_AXIS,))


def data_axis_size(mesh: Mesh | None) -> int:
    """Shards along the batch axis (1 = unsharded/no mesh)."""
    if mesh is None:
        return 1
    try:
        return int(dict(mesh.shape).get(DATA_AXIS, mesh.size))
    except Exception:
        return int(getattr(mesh, "size", 1) or 1)


def resolve_mesh(mesh_devices: int) -> Mesh | None:
    """Production knob → mesh (the nodeconfig ``mesh_devices`` knob) —
    the 1-process special case of :func:`resolve_fabric`.

    0  = sharding off (single-device dispatch — the safe default on
         CPU-only hosts, where a virtual mesh only adds partition
         overhead);
    -1 = auto: all local devices, None when only one exists;
    n  = first n local devices (clamped to what exists; None if that
         leaves fewer than 2 — a 1-device mesh is just overhead).
    """
    if mesh_devices == 0:
        return None
    devices = jax.devices()
    n = len(devices) if mesh_devices < 0 else min(mesh_devices, len(devices))
    if n < 2:
        return None
    return Mesh(np.asarray(devices[:n]), axis_names=(DATA_AXIS,))


_distributed_lock = threading.Lock()
_distributed_up = False


def _init_distributed(topo: MeshTopology) -> bool:
    """One-shot ``jax.distributed.initialize`` (idempotent per
    process).  Failure degrades to the local mesh with a warning —
    a fabric that cannot form must not take the validator down."""
    global _distributed_up
    with _distributed_lock:
        if _distributed_up:
            return True
        try:
            jax.distributed.initialize(
                coordinator_address=topo.coordinator,
                num_processes=int(topo.num_processes),
                process_id=int(topo.process_id),
            )
            _distributed_up = True
            _log.info(
                "joined distributed fabric: coordinator=%s process "
                "%d/%d", topo.coordinator, topo.process_id,
                topo.num_processes,
            )
            return True
        except Exception as e:
            _log.warning(
                "jax.distributed.initialize failed (%s) — degrading "
                "to the local per-process mesh", e,
            )
            return False


def resolve_fabric(topo: MeshTopology | int,
                   mesh_shape: str = "",
                   distributed: bool = False,
                   coordinator: str = "",
                   process_id: int = 0,
                   num_processes: int = 1) -> Mesh | None:
    """Mesh topology → the fabric mesh every partition rule applies
    over, or None (sharding off).

    Accepts a :class:`MeshTopology` or the bare ``mesh_devices`` int
    plus keyword knobs.  Resolution order:

    1. ``distributed`` arms ``jax.distributed.initialize`` (once);
       after that ``jax.devices()`` spans every process.
    2. ``mesh_shape`` ("8", "2x4") builds the data×replica grid over
       the first ``prod(shape)`` devices; a grid that does not fit the
       available devices degrades to the local auto mesh (warned, and
       visible as a smaller ``data`` axis on /launches rows).
    3. Otherwise the classic ``mesh_devices`` count — the 1-process
       special case (:func:`resolve_mesh`).

    A resolution whose data axis is < 2 returns None: a 1-wide data
    axis is partition overhead with no parallelism.
    """
    if isinstance(topo, MeshTopology):
        t = topo
    else:
        t = MeshTopology(devices=int(topo), shape=mesh_shape,
                         distributed=distributed,
                         coordinator=coordinator,
                         process_id=process_id,
                         num_processes=num_processes)
    if not t.configured:
        return None
    if t.distributed:
        _init_distributed(t)
    if t.shape:
        dims = parse_mesh_shape(t.shape)
        want = 1
        for d in dims:
            want *= d
        devices = jax.devices()
        if want > len(devices):
            _log.warning(
                "mesh_shape %s wants %d devices, %d available — "
                "degrading to the local auto mesh",
                t.shape, want, len(devices),
            )
            return resolve_mesh(-1 if t.devices == 0 else t.devices)
        if dims[0] < 2:
            return None
        grid = np.asarray(devices[:want]).reshape(dims)
        names = (DATA_AXIS,) if len(dims) == 1 else (DATA_AXIS,
                                                     REPLICA_AXIS)
        return Mesh(grid, axis_names=names)
    return resolve_mesh(t.devices)


# ---------------------------------------------------------------------------
# applying rules to arrays


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (the batch/tx dim) over "data"; replicate the rest
    (including any replica axis)."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def shard_args(mesh: Mesh, *arrays):
    """Device-put arrays with axis-0 sharded over the mesh."""
    return tuple(
        jax.device_put(a, batch_sharding(mesh, a.ndim)) for a in arrays
    )


def will_shard(mesh: Mesh | None, arr) -> bool:
    """Whether :func:`shard` will actually partition ``arr`` (False =
    the unsharded fallback; the caller's ledger row should say so)."""
    if mesh is None:
        return False
    n = arr.shape[0] if getattr(arr, "ndim", 0) else 0
    d = data_axis_size(mesh)
    return n > 0 and d > 1 and n % d == 0


def shard(mesh: Mesh | None, family: str, arr):
    """Device-put ONE array under its family's partition rule.

    Replicated families pass through untouched (jit commits them to
    every device; an explicit broadcast put would only add a copy).
    Data-sharded families fall back to the unsharded array when the
    mesh is off, axis 0 is empty, or axis 0 does not divide the data
    axis (ragged microbatch tails, sub-minimum buckets) — the
    dispatch then runs single-device for that array, which is always
    correct, just not parallel.  Fallbacks on a LIVE mesh are counted
    (``mesh_shard_fallback_total{reason=}``) — all production batch
    shapes are bucketed to powers of two ≥ 16 or multiples of 512, so
    2/4/8-way data axes divide them exactly and a nonzero counter
    means a shape regression, not noise."""
    rule = rule_for(family)
    if mesh is None or rule.replicated:
        return arr
    n = arr.shape[0] if getattr(arr, "ndim", 0) else 0
    d = data_axis_size(mesh)
    if d < 2:
        return arr
    if n == 0:
        _note_fallback("empty_axis0", family)
        return arr
    if n % d != 0:
        _note_fallback("ragged_axis0", family)
        return arr
    return jax.device_put(arr, NamedSharding(mesh, rule.spec(arr.ndim)))


def shard_batch(mesh: Mesh | None, arr):
    """Back-compat alias: axis-0 shard one array under the generic
    verify-lane rule (the pre-registry call sites all meant "shard the
    batch dim"; new call sites should name their family via
    :func:`shard`)."""
    return shard(mesh, "verify_lanes", arr)


def shard_state_table(mesh: Mesh | None, table):
    """Shard the device-resident MVCC version table under the
    ``state_table`` rule.  The residency manager lays slots out
    range-major in per-shard blocks (capacity is a power of two, so
    2/4/8-way data axes divide it exactly), which makes this axis-0
    partition a KEY-RANGE partition: each range's slots land on its
    owning device, and functional scatter updates
    (``table.at[idx].set``) preserve the layout.  An unmeshed host
    gets the plain single-device array."""
    return shard(mesh, "state_table", table)

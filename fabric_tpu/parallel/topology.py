"""Mesh topology description: the nodeconfig mesh knobs as one value.

A :class:`MeshTopology` carries everything the partition-rule layer
(:mod:`fabric_tpu.parallel.mesh`) needs to build the device mesh a
validator or sidecar dispatches over — the classic per-host
``mesh_devices`` count, the pod-scale ``mesh_shape`` grid, and the
``jax.distributed`` process-spanning knobs (coordinator address,
process id/count).  It deliberately imports NO jax: nodeconfig, the
CLI and the peer node pass topologies around on jax-free import
paths, and only :meth:`resolve` (called once, behind the knob check)
touches the device stack.
"""

from __future__ import annotations

from dataclasses import dataclass


def parse_mesh_shape(shape: str) -> tuple[int, ...]:
    """``"8"`` → ``(8,)``; ``"2x4"`` → ``(2, 4)``.  Axis 0 is the
    batch ("data") axis; a second axis replicates ("replica").
    Raises ``ValueError`` on anything else — nodeconfig surfaces it
    as a ConfigError naming the key."""
    try:
        dims = tuple(int(d) for d in shape.lower().split("x"))
    except ValueError:
        dims = ()
    if not (1 <= len(dims) <= 2) or any(d < 1 for d in dims):
        raise ValueError(
            f"mesh_shape {shape!r}: want 'N' or 'NxM' with N, M >= 1"
        )
    return dims


@dataclass(frozen=True)
class MeshTopology:
    """One validator/sidecar's mesh configuration (see module doc).

    ``devices`` is the classic ``mesh_devices`` knob (0 = off, -1 =
    all local, n = first n local) and stays the 1-process special
    case: a topology with only ``devices`` set resolves exactly like
    ``resolve_mesh(devices)`` always has.  ``shape`` names a device
    grid ("8", "2x4" — data×replica); ``distributed`` arms
    ``jax.distributed.initialize`` against ``coordinator`` so the
    grid can span processes, at which point jax.devices() enumerates
    every process's chips and the SAME rule table shards over all of
    them."""

    devices: int = 0
    shape: str = ""
    distributed: bool = False
    coordinator: str = ""
    process_id: int = 0
    num_processes: int = 1

    @property
    def configured(self) -> bool:
        return bool(self.devices or self.shape or self.distributed)

    @classmethod
    def from_config(cls, cfg) -> "MeshTopology":
        """PeerConfig (nodeconfig) → topology."""
        return cls(
            devices=int(getattr(cfg, "mesh_devices", 0)),
            shape=str(getattr(cfg, "mesh_shape", "")),
            distributed=bool(getattr(cfg, "mesh_distributed", False)),
            coordinator=str(getattr(cfg, "mesh_coordinator", "")),
            process_id=int(getattr(cfg, "mesh_process_id", 0)),
            num_processes=int(getattr(cfg, "mesh_num_processes", 1)),
        )

    def resolve(self):
        """→ jax Mesh | None.  The only jax-importing path here."""
        if not self.configured:
            return None
        from fabric_tpu.parallel.mesh import resolve_fabric

        return resolve_fabric(self)

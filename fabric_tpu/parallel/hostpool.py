"""Host staging worker pool: sharding the per-block HOST pipeline
across cores.

PR 3 made the device lane mesh-parallel, but the host side of every
1000-tx block (envelope parse, per-signature admission + Montgomery
batch inversion + residue dgemm, device-path preprocessing) stayed a
single thread feeding a now-parallel device — the classic host-bound
input pipeline every accelerator stack solves with a worker pool ahead
of the device (tf.data prefetch workers; the batched-ECDSA GPU
literature's CPU staging pools).  This module is that pool, shaped for
this repo's staging work:

* threads by DEFAULT — the hot loops are numpy dgemms, ``hashlib``,
  the native C pre-parser, and ``int.to_bytes`` batches, all of which
  release the GIL, so threads scale on the very loops that matter
  without pickling block-sized arrays across process boundaries;
* an optional PROCESS mode behind the ``mode`` knob for workloads that
  really are Python-bound — tasks submitted there must be picklable
  top-level functions (the validator keeps its bound-method fan-out on
  threads and says so);
* slice helpers that shard a batch's lane axis at bucket boundaries
  (multiples of ``align``) into per-worker contiguous ranges, so the
  per-shard outputs CONCATENATE back bit-trivially — every staged lane
  is lane-independent, which is what pins pooled ≡ serial the same way
  sharded ≡ single-device is pinned on the mesh;
* per-task telemetry: ``host_stage_pool_seconds{stage,worker}`` rides
  the process metrics registry so the pool's occupancy is observable
  next to the validator stage histograms.

The knob (nodeconfig ``host_stage_workers``) resolves exactly like
``mesh_devices``: 0 = off (serial staging — the safe default, CPU-only
hosts pay nothing), -1 = one worker per core, n = n workers; a
resolution below 2 returns None because a 1-worker pool is only queue
overhead.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor


def clamp_workers(n: int, cores: int | None = None) -> int:
    """The ONE resize-clamp rule (shared by ``set_workers`` and the
    validator's post-swap size prediction, so the two can never
    drift): a pool runs at least 2 workers and at most the core
    count — dropping below 2 is a close, not a resize."""
    if cores is None:
        cores = os.cpu_count() or 1
    return max(2, min(int(n), max(2, cores)))


def _pool_hist():
    from fabric_tpu.ops_metrics import global_registry

    return global_registry().histogram(
        "host_stage_pool_seconds",
        "host staging pool task time (s) by stage and worker",
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 1.0, float("inf")),
    )


def _pool_tracer():
    from fabric_tpu.observe import global_tracer

    return global_tracer()


def _label_task_error(e: BaseException, stage: str, worker: str) -> None:
    """Attach the failing stage/worker to a task exception in place —
    the TYPE is preserved (callers catch specific exceptions) and the
    first string arg gains a ``[host pool stage=… worker=…]`` suffix so
    logs name the slot.  Idempotent across re-submission layers."""
    if getattr(e, "fab_stage", None) is not None:
        return
    try:
        e.fab_stage = stage
        e.fab_worker = worker
        if e.args and isinstance(e.args[0], str):
            e.args = (
                f"{e.args[0]} [host pool stage={stage} worker={worker}]",
            ) + e.args[1:]
    except Exception as label_err:
        # frozen/slots exception types: labels are best-effort — the
        # original error still propagates unlabeled
        import logging

        logging.getLogger("fabric_tpu.hostpool").debug(
            "could not label task error: %s", label_err
        )


class HostStagePool:
    """Persistent staging worker pool (see module docstring).

    Construct via :func:`resolve_host_pool`; the pool is created once
    per validator and reused for every block — worker spin-up must not
    ride the per-block critical path.
    """

    def __init__(self, workers: int, mode: str = "thread"):
        if workers < 2:
            raise ValueError("HostStagePool needs >= 2 workers "
                             "(resolve_host_pool returns None below that)")
        if mode not in ("thread", "process"):
            raise ValueError(f"host pool mode {mode!r}: "
                             "expected 'thread' or 'process'")
        self.workers = int(workers)
        self.mode = mode
        if mode == "process":
            import multiprocessing as mp

            # spawn, not fork: this process is multithreaded the
            # moment jax loads, and forking a threaded process can
            # deadlock the child in a held allocator/runtime lock
            self._ex = ProcessPoolExecutor(
                self.workers, mp_context=mp.get_context("spawn")
            )
        else:
            self._ex = ThreadPoolExecutor(
                self.workers, thread_name_prefix="fabtpu-hoststage"
            )
        self._hist = _pool_hist()
        self._trc = _pool_tracer()
        # recent per-task durations for the bench's host_stage
        # sub-breakdown (p50 per shard) — bounded, lock-guarded
        self._durs: deque = deque(maxlen=1024)
        self._lock = threading.Lock()
        self._tasks = 0
        # runtime resize (the autopilot's host_stage_workers
        # actuator): set_workers latches a target; the swap happens at
        # a TASK BOUNDARY — the next submit that finds the pool idle
        # (no in-flight tasks) drains the old executor and rebuilds.
        # ``_active`` counts in-flight tasks; both are guarded by the
        # same lock as the telemetry so a submitter can never hand a
        # task to an executor mid-teardown.
        self._active = 0
        self._pending_workers: int | None = None

    # -- submission --------------------------------------------------------

    def _observe(self, stage: str, worker: str, dt: float) -> None:
        self._hist.observe(dt, stage=stage, worker=worker)
        with self._lock:
            self._durs.append(dt)
            self._tasks += 1

    def _timed(self, fn, stage: str, parent):
        """Wrap ``fn`` to observe its duration from INSIDE the worker
        (thread mode) so the worker label names the executing slot.
        ``parent`` is the SUBMITTING thread's current tracer span,
        captured at submit time — the worker adopts it so its task
        span lands in the right block tree (the explicit cross-thread
        handoff; thread-locals do not follow executor tasks).

        A task exception is ANNOTATED with the failing stage/worker
        before it propagates (``fab_stage``/``fab_worker`` attributes
        plus a message suffix): by the time the ordered ``map`` gather
        re-raises it on the submitting thread, the executing slot is
        long gone — without the labels a one-in-N shard failure is
        undebuggable.  The ``hostpool.task`` fault-injection point
        fires here so a chaos plan can kill exactly one worker task."""
        trc = self._trc

        def run(*args, **kwargs):
            from fabric_tpu import faults as _faults

            name = threading.current_thread().name
            worker = name.rsplit("_", 1)[-1] if "_" in name else name
            t0 = time.perf_counter()
            try:
                with trc.span(stage, parent=parent, worker=worker):
                    _faults.fire("hostpool.task", stage=stage)
                    return fn(*args, **kwargs)
            except BaseException as e:
                _label_task_error(e, stage, worker)
                raise
            finally:
                self._observe(stage, worker, time.perf_counter() - t0)
        return run

    # -- runtime resize (autopilot actuator) -------------------------------

    def set_workers(self, n: int) -> None:
        """Request a new worker count, applied drain-and-rebuild at
        the next task boundary: the first ``submit`` that finds the
        pool IDLE swaps in a fresh executor (the old one, empty, shuts
        down instantly).  In-flight tasks always finish on the
        executor that started them — a resize can never strand or
        interleave a shard.  ``n`` clamps via :func:`clamp_workers`
        (a pool below 2 workers is not a pool; dropping to 0 is a
        close, not a resize)."""
        n = clamp_workers(n)
        with self._lock:
            self._pending_workers = None if n == self.workers else n

    def _maybe_resize_locked(self):
        """Caller holds the lock.  Returns the executor a new task
        must be submitted to (post-swap when a pending resize applies
        at this idle boundary)."""
        n = self._pending_workers
        if n is None or self._active > 0:
            return self._ex
        self._pending_workers = None
        old = self._ex
        if self.mode == "process":
            import multiprocessing as mp

            self._ex = ProcessPoolExecutor(
                n, mp_context=mp.get_context("spawn")
            )
        else:
            self._ex = ThreadPoolExecutor(
                n, thread_name_prefix="fabtpu-hoststage"
            )
        self.workers = n
        # idle by the _active==0 guard: shutdown returns immediately
        old.shutdown(wait=False)
        return self._ex

    def _task_done(self, _fut) -> None:
        with self._lock:
            self._active -= 1

    def submit(self, fn, *args, stage: str = "task", **kwargs):
        """Submit one task; returns a Future.  Thread mode times the
        task inside its worker; process mode times submit→done in the
        parent (the child's registry is not this process's)."""
        with self._lock:
            ex = self._maybe_resize_locked()
            # counted BEFORE the lock releases: a concurrent resize
            # check can never see the pool idle while this task is on
            # its way to ``ex``
            self._active += 1
        try:
            if self.mode == "process":
                t0 = time.perf_counter()
                fut = ex.submit(fn, *args, **kwargs)
                fut.add_done_callback(
                    lambda f: self._observe(stage, "proc",
                                            time.perf_counter() - t0)
                )
            else:
                fut = ex.submit(
                    self._timed(fn, stage, self._trc.current()),
                    *args, **kwargs
                )
        except BaseException:
            with self._lock:
                self._active -= 1
            raise
        fut.add_done_callback(self._task_done)
        return fut

    def map(self, fn, items, stage: str = "task") -> list:
        """Ordered parallel map: fan every item out, gather in order.
        The FIRST task exception (submission order) propagates at the
        gather with the failing stage/worker labels attached — never a
        wedged gather, never a silently dropped shard; the remaining
        futures still run to completion (staging tasks are short and
        side-effect-free)."""
        futs = [self.submit(fn, it, stage=stage) for it in items]
        out = []
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:
                # thread mode labeled inside the worker; process mode
                # (exception pickled back from the child) labels here
                _label_task_error(e, stage, "proc")
                raise
        return out

    # -- lane-axis sharding ------------------------------------------------

    def slice_bounds(self, n: int, align: int = 1) -> list[tuple[int, int]]:
        """Split [0, n) into ≤ ``workers`` contiguous ranges whose
        boundaries are multiples of ``align`` (bucket boundaries —
        MIN_BUCKET for signature columns), so each worker stages a
        self-contained slab and concatenation needs no re-bucketing.
        The tail range absorbs the remainder."""
        if n <= 0:
            return []
        per = -(-n // self.workers)
        per = -(-per // align) * align  # round the stride UP to align
        out = []
        lo = 0
        while lo < n:
            hi = min(n, lo + per)
            out.append((lo, hi))
            lo = hi
        return out

    def map_slices(self, n: int, fn, stage: str = "task",
                   align: int = 1) -> list:
        """``fn(lo, hi)`` over :meth:`slice_bounds`, ordered results."""
        return self.map(lambda b: fn(*b), self.slice_bounds(n, align),
                        stage=stage)

    # -- introspection / lifecycle ----------------------------------------

    def stats(self) -> dict:
        """Pool occupancy summary for bench extras: worker count and
        the p50 of recent per-task (per-shard) durations in ms."""
        with self._lock:
            durs = sorted(self._durs)
            tasks = self._tasks
            pending = self._pending_workers
        p50 = durs[len(durs) // 2] if durs else 0.0
        return {
            "workers": self.workers,
            "mode": self.mode,
            "tasks": tasks,
            "per_shard_p50_ms": round(p50 * 1000.0, 3),
            **({"pending_workers": pending} if pending is not None
               else {}),
        }

    def shutdown(self) -> None:
        self._ex.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown()
        return False


def resolve_host_pool(workers: int, mode: str = "thread") -> HostStagePool | None:
    """Production knob → pool (the nodeconfig ``host_stage_workers``
    knob; mirrors parallel.mesh.resolve_mesh):

    0  = staging pool off (serial host staging — the safe default);
    -1 = one worker per core;
    n  = n workers (clamped to the core count; below 2 → None, a
         1-worker pool is queue overhead with no parallelism).
    """
    if workers == 0:
        return None
    cores = os.cpu_count() or 1
    n = cores if workers < 0 else min(workers, cores)
    if n < 2:
        return None
    return HostStagePool(n, mode=mode)

"""fabric-tpu operator CLI (the cmd/{peer,orderer,configtxgen,
cryptogen,osnadmin,discover,ledgerutil} surface in one binary).

Usage: python -m fabric_tpu.cli <command> ...

Commands:
  cryptogen     generate org crypto material onto disk
  configtxgen   genesis block from a JSON profile
  orderer       run an ordering node (JSON config)
  peer          run a peer node (JSON config)
  sidecar-serve run a standalone validation sidecar (one device
                fabric serving many peers' signature batches)
  osnadmin      orderer channel participation (join)
  invoke/query  gateway client round trips
  snapshot      request a ledger snapshot from a peer
  ledgerutil    verify / compare ledger directories offline
  discover      discovery queries against a peer

Configs are JSON (the reference's YAML surface maps 1:1; no external
YAML dependency)."""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _cmd_cryptogen(args):
    from fabric_tpu.crypto import cryptogen as cg

    for spec in args.org:
        msp_id, _, domain = spec.partition(":")
        org = cg.generate_org(
            msp_id, domain or f"{msp_id.lower()}.example.com",
            peers=args.peers, orderers=args.orderers, users=args.users,
        )
        out = cg.write_org(org, args.output)
        print(f"wrote {msp_id} material to {out}")


def _cmd_configtxgen(args):
    from fabric_tpu.crypto import cryptogen as cg
    from fabric_tpu.tools import configtxgen as ctg

    with open(args.profile) as f:
        prof = json.load(f)
    app_orgs = [
        ctg.OrgProfile(o["msp_id"], cg.load_org_msp(o["dir"]),
                       [tuple(a) for a in o.get("anchor_peers", [])])
        for o in prof.get("application_orgs", [])
    ]
    orderer_orgs = [
        ctg.OrgProfile(o["msp_id"], cg.load_org_msp(o["dir"]), [])
        for o in prof.get("orderer_orgs", [])
    ]
    profile = ctg.Profile(
        prof["channel"], application_orgs=app_orgs,
        orderer_orgs=orderer_orgs,
        consensus_type=prof.get("consensus", "raft"),
        raft_consenters=[tuple(c) for c in prof.get("consenters", [])],
        max_message_count=prof.get("max_message_count", 500),
        batch_timeout_ms=prof.get("batch_timeout_ms", 200),
    )
    blk = ctg.genesis_block(profile)
    with open(args.output, "wb") as f:
        f.write(blk.SerializeToString())
    print(f"wrote genesis block for {prof['channel']} to {args.output}")


def _node_tls(cfg):
    """Node mTLS material from the typed ``tls`` section (cryptogen's
    nodes/<name>/tls layout)."""
    t = cfg.tls
    if t is None or not t.cert:
        return None
    from fabric_tpu.comm.rpc import TlsProfile

    return TlsProfile.load(t.cert, t.key, t.ca)


async def _run_orderer(cfg):
    from fabric_tpu.crypto import cryptogen as cg
    from fabric_tpu.nodeconfig import OrdererConfig
    from fabric_tpu.ordering.blockcutter import BatchConfig
    from fabric_tpu.ordering.node import OrdererNode
    from fabric_tpu.protos import common_pb2

    assert isinstance(cfg, OrdererConfig)
    signer = None
    if cfg.msp_dir:
        signer = cg.load_signing_identity(cfg.msp_dir, cfg.msp_id)
    node = OrdererNode(
        cfg.id, cfg.data_dir, cfg.cluster,
        host=cfg.host, port=cfg.port,
        batch_config=BatchConfig(
            max_message_count=cfg.max_message_count,
            batch_timeout_s=cfg.batch_timeout_s,
        ),
        consensus=cfg.consensus, view_timeout=cfg.view_timeout,
        signer=signer,
        tls=_node_tls(cfg),
    )
    node.broadcast_rate = cfg.broadcast_rate
    await node.start(operations_port=cfg.operations_port)
    print(f"orderer {node.id} serving on :{node.port}", flush=True)
    for ch in cfg.channels:
        name = ch if isinstance(ch, str) else ch.name
        genesis = None
        if not isinstance(ch, str) and ch.genesis:
            genesis = common_pb2.Block()
            with open(ch.genesis, "rb") as f:
                genesis.ParseFromString(f.read())
        chain = node.join_channel(name, genesis)
        chain.wal_retention = cfg.wal_retention
    await asyncio.Event().wait()


def _build_peer(cfg):
    """Construct the PeerNode from a validated PeerConfig — shared by
    the serving ``peer`` command and the offline ``replay`` catch-up
    (which never starts the server)."""
    from fabric_tpu.crypto import cryptogen as cg
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.nodeconfig import PeerConfig
    from fabric_tpu.parallel.topology import MeshTopology
    from fabric_tpu.peer.ccaas import CCaaSProxy
    from fabric_tpu.peer.chaincode import ChaincodeRuntime
    from fabric_tpu.peer.node import PeerNode

    assert isinstance(cfg, PeerConfig)
    signer = cg.load_signing_identity(cfg.msp_dir, cfg.msp_id)
    mgr = MSPManager()
    for org_dir in cfg.org_msps:
        mgr.add(cg.load_org_msp(org_dir))
    runtime = ChaincodeRuntime()
    for cc in cfg.chaincodes:
        runtime.register(cc.name, CCaaSProxy(cc.name, cc.host, cc.port))
    return PeerNode(
        cfg.id, cfg.data_dir, mgr, signer, runtime,
        host=cfg.host, port=cfg.port,
        tls=_node_tls(cfg),
        max_package_size=cfg.max_package_size,
        install_require_admin=cfg.install_require_admin,
        pipeline_depth=cfg.pipeline_depth,
        verify_chunk=cfg.verify_chunk,
        mesh_devices=cfg.mesh_devices,
        mesh_topology=MeshTopology.from_config(cfg),
        coalesce_blocks=cfg.coalesce_blocks,
        host_stage_workers=cfg.host_stage_workers,
        recode_device=cfg.recode_device,
        host_stage_mode=cfg.host_stage_mode,
        trace_ring_blocks=cfg.trace_ring_blocks,
        trace_slow_factor=cfg.trace_slow_factor,
        slos=cfg.slos,
        vitals_interval_s=cfg.vitals_interval_s,
        vitals_retention=cfg.vitals_retention,
        blackbox_dir=cfg.blackbox_dir,
        device_ledger=cfg.device_ledger,
        autopilot=cfg.autopilot,
        autopilot_tick_s=cfg.autopilot_tick_s,
        autopilot_knobs=cfg.autopilot_knobs,
        sign_device=cfg.sign_device,
        sign_batch_max=cfg.sign_batch_max,
        sign_batch_wait_ms=cfg.sign_batch_wait_ms,
        sign_self_check=cfg.sign_self_check,
        device_fail_threshold=cfg.device_fail_threshold,
        device_retries=cfg.device_retries,
        device_recovery_s=cfg.device_recovery_s,
        verify_deadline_ms=cfg.verify_deadline_ms,
        state_resident=cfg.state_resident,
        state_resident_mb=cfg.state_resident_mb,
        state_resident_range_bits=cfg.state_resident_range_bits,
        faults=cfg.faults,
        sidecar_endpoint=cfg.sidecar_endpoint,
        sidecar_weight=cfg.sidecar_weight,
        sidecar_recovery_s=cfg.sidecar_recovery_s,
        sidecar_listen=cfg.sidecar_listen,
        sidecar_queue_blocks=cfg.sidecar_queue_blocks,
        sidecar_coalesce=cfg.sidecar_coalesce,
        async_commit=cfg.async_commit,
        apply_queue_blocks=cfg.apply_queue_blocks,
        tx_flow=cfg.tx_flow,
    )


def _join_config_channel(node, cfg, ch):
    """Join one configured channel (genesis / snapshot anchored) and
    apply the per-channel ledger knobs."""
    from fabric_tpu.protos import common_pb2

    name = ch if isinstance(ch, str) else ch.name
    genesis = None
    if not isinstance(ch, str) and ch.genesis:
        genesis = common_pb2.Block()
        with open(ch.genesis, "rb") as f:
            genesis.ParseFromString(f.read())
    chan = node.join_channel(
        name, genesis_block=genesis,
        snapshot_dir=(None if isinstance(ch, str) or not ch.snapshot_dir
                      else ch.snapshot_dir),
    )
    chan.ledger.blocks.group_commit = cfg.group_commit
    chan.transient_retention = cfg.transient_retention
    return chan


async def _run_peer(cfg):
    from fabric_tpu.discovery import PeerInfo

    node = _build_peer(cfg)
    await node.start(operations_port=cfg.operations_port)
    print(f"peer {node.id} serving on :{node.port}", flush=True)
    for p in cfg.peers:
        node.registry.add(PeerInfo(p.msp_id, p.host, p.port))
    for ch in cfg.channels:
        name = ch if isinstance(ch, str) else ch.name
        chan = _join_config_channel(node, cfg, ch)
        if not isinstance(ch, str) and ch.replay_from:
            # local catch-up BEFORE the deliver loop attaches: replay
            # the staged block store at full pipeline depth
            # (peer/replay.py) — a killed start resumes from the
            # committed height on the next boot
            stats = await chan.replay_local(ch.replay_from)
            print(f"channel {name} replayed {stats['blocks']} blocks "
                  f"to height {chan.height} "
                  f"({stats['blocks_per_s']} blocks/s)", flush=True)
        orderers = ([] if isinstance(ch, str)
                    else [tuple(o) for o in ch.orderers])
        if orderers:
            chan.start_deliver(
                orderers,
                censorship_check_s=cfg.deliver_censorship_check_s,
            )
        if not isinstance(ch, str) and ch.anti_entropy:
            node.gossip_service.start_anti_entropy(name)
        node.gossip_service.start_reconciler(name)
    await asyncio.Event().wait()


def _cmd_node(args, runner):
    from fabric_tpu.nodeconfig import (
        ConfigError, load_orderer_config, load_peer_config,
    )

    loader = load_peer_config if runner is _run_peer else load_orderer_config
    try:
        cfg = loader(args.config)
    except ConfigError as e:
        print(f"config error: {e}", file=sys.stderr)
        sys.exit(2)
    try:
        asyncio.run(runner(cfg))
    except KeyboardInterrupt:
        pass


async def _run_sidecar(args):
    """Standalone validation sidecar: one device fabric serving many
    peer processes (fabric_tpu/sidecar — the PAPER.md north-star
    deployment shape).  Peers attach by setting ``sidecar_endpoint``
    in their node config."""
    from fabric_tpu.sidecar.server import SidecarServer
    from fabric_tpu.sidecar.client import parse_endpoint

    from fabric_tpu.utils.xla_env import enable_compile_cache

    enable_compile_cache()

    if args.slos:
        from fabric_tpu.observe import slo as slo_mod

        slo_mod.configure(args.slos)
    if args.vitals_interval_s > 0 or args.blackbox_dir:
        # flight-data recorder on the sidecar process: trailing metric
        # series at /vitals plus black-box bundles on incident edges
        # (shed decisions, SLO fast burns) — default OFF
        from fabric_tpu.observe import timeseries as ts_mod

        ts_mod.configure(interval_s=args.vitals_interval_s,
                         retention=args.vitals_retention)
    if args.device_ledger:
        # device-time launch ledger on the sidecar process: every
        # coalesced cross-tenant dispatch reports compile/queue/
        # execute/transfer at /launches (default ON, like the peer)
        from fabric_tpu.observe import ledger as ledger_mod

        ledger_mod.configure()
    ssl_ctx = None
    if args.tls_cert and args.tls_key:
        from fabric_tpu.comm.rpc import make_server_tls

        with open(args.tls_cert, "rb") as f:
            cert = f.read()
        with open(args.tls_key, "rb") as f:
            key = f.read()
        ca = None
        if args.tls_ca:
            with open(args.tls_ca, "rb") as f:
                ca = f.read()
        ssl_ctx = make_server_tls(cert, key, ca)
    host, port = parse_endpoint(args.listen)
    from fabric_tpu.parallel.topology import MeshTopology

    topo = MeshTopology(
        devices=args.mesh_devices, shape=args.mesh_shape,
        distributed=args.mesh_distributed,
        coordinator=args.mesh_coordinator,
        process_id=args.mesh_process_id,
        num_processes=args.mesh_num_processes,
    )
    srv = SidecarServer(
        host, port, mesh_devices=args.mesh_devices,
        mesh_topology=topo if topo.configured else None,
        verify_chunk=args.verify_chunk,
        recode_device=args.recode_device,
        queue_blocks=args.queue_blocks, coalesce=args.coalesce,
        ssl_ctx=ssl_ctx,
    )
    await srv.start()
    print(f"validation sidecar serving on {srv.host}:{srv.port}",
          flush=True)
    if args.vitals_interval_s > 0 or args.blackbox_dir:
        from fabric_tpu.observe import blackbox as bb_mod

        # armed after start so bundles carry the live scheduler stats
        bb_mod.configure(out_dir=args.blackbox_dir,
                         scheduler=srv.scheduler)
    if args.autopilot:
        # SERVER-SIDE knob actuation: a sidecar-serve-local autopilot
        # reads its OWN scheduler's queue-age/BUSY telemetry and the
        # global SLO engine, and actuates the sidecar's own knobs —
        # cross-tenant coalescing and the device microbatch chunk via
        # the dispatcher-drain-boundary setters, plus tenant shed/
        # weights on the live scheduler.  (The peer-side controller
        # actuates pipeline knobs; this one owns the dispatch.)
        from fabric_tpu.control import Autopilot, set_global
        from fabric_tpu.observe.slo import global_engine

        def _apply(knob, value):
            if knob == "coalesce_blocks":
                srv.set_coalesce(int(value))
            elif knob == "verify_chunk":
                srv.set_verify_chunk(int(value))
            # pipeline_depth / host_stage_workers have no sidecar
            # meaning; their signals never fire here (no block roots)

        ap = Autopilot(
            args.autopilot_knobs or None, _apply,
            set_weight=srv.scheduler.set_weight,
            set_shed=srv.scheduler.set_shed,
            slo=global_engine(), scheduler=srv.scheduler,
            tick_s=args.autopilot_tick_s,
            initial={"coalesce_blocks": args.coalesce,
                     "verify_chunk": args.verify_chunk},
        )
        srv.autopilot = ap
        set_global(ap)
        ap.start()
        print("sidecar autopilot armed", flush=True)
    if args.operations_port is not None:
        from fabric_tpu.opsserver import HealthRegistry, OperationsServer

        health = HealthRegistry()
        health.register("sidecar", srv.health_check)
        ops = await OperationsServer(
            port=args.operations_port, health=health
        ).start()
        print(f"operations on :{ops.port}", flush=True)
    await asyncio.Event().wait()


async def _run_chaincode(args):
    from fabric_tpu.peer.ccaas import ChaincodeServer
    from fabric_tpu.peer.chaincode import KVContract, MarblesContract

    server = ChaincodeServer(port=args.port)
    await server.start()
    contract = {"kv": KVContract, "marbles": MarblesContract}[args.contract]()
    server.register(args.name, contract)
    print(f"chaincode {args.name} ({args.contract}) serving on :{server.port}",
          flush=True)
    await asyncio.Event().wait()


def _cli_ssl(args):
    """Client-side TLS context from the global --tls-* flags (mutual
    when a cert/key pair is given), or None for plaintext."""
    if not getattr(args, "tls_ca", None):
        return None
    from fabric_tpu.comm.rpc import make_client_tls

    with open(args.tls_ca, "rb") as f:
        ca = f.read()
    cert = key = None
    if getattr(args, "tls_cert", None) and getattr(args, "tls_key", None):
        with open(args.tls_cert, "rb") as f:
            cert = f.read()
        with open(args.tls_key, "rb") as f:
            key = f.read()
    return make_client_tls(ca, cert, key)


def _cmd_osnadmin(args):
    from fabric_tpu.comm.rpc import RpcClient
    from fabric_tpu.protos import common_pb2

    async def go():
        cli = RpcClient(args.host, args.port, ssl_ctx=_cli_ssl(args))
        await cli.connect()
        blk = b""
        if args.genesis:
            with open(args.genesis, "rb") as f:
                blk = f.read()
        hdr = json.dumps({"channel": args.channel}).encode()
        raw = await cli.unary(
            "Join", len(hdr).to_bytes(4, "big") + hdr + blk
        )
        await cli.close()
        print(raw.decode())

    asyncio.run(go())


def _cmd_invoke(args, evaluate=False):
    from fabric_tpu.crypto import cryptogen as cg
    from fabric_tpu.peer.gateway import GatewayClient

    signer = cg.load_signing_identity(args.msp_dir, args.msp_id)

    async def go():
        gw = GatewayClient(args.host, args.port, signer, ssl_ctx=_cli_ssl(args))
        try:
            cc_args = [a.encode() for a in args.args]
            if evaluate:
                resp = await gw.evaluate(args.channel, args.chaincode, cc_args)
                print(json.dumps({
                    "status": resp.status,
                    "payload": resp.payload.decode("utf-8", "replace"),
                }))
            else:
                tx_id, status = await gw.submit_transaction(
                    args.channel, args.chaincode, cc_args
                )
                print(json.dumps({"tx_id": tx_id, **(status or {})}))
        finally:
            await gw.close()

    asyncio.run(go())


def _cmd_ccpackage(args):
    from fabric_tpu.peer import ccpackage

    raw = ccpackage.package_ccaas(args.label, args.address)
    with open(args.output, "wb") as f:
        f.write(raw)
    print(json.dumps({
        "package_id": ccpackage.package_id(args.label, raw),
        "path": args.output,
    }))


def _cmd_ccinstall(args):
    from fabric_tpu.comm.rpc import RpcClient

    with open(args.package, "rb") as f:
        raw = f.read()
    if args.sign_msp_dir:
        # the admin-signed envelope install_require_admin peers demand
        if not args.sign_msp_id:
            print("ccinstall: --sign-msp-dir requires --sign-msp-id "
                  "(an identity without its MSP id can never validate)",
                  file=sys.stderr)
            sys.exit(2)
        from fabric_tpu.crypto.cryptogen import load_signing_identity

        signer = load_signing_identity(args.sign_msp_dir, args.sign_msp_id)
        raw = json.dumps({
            "package": raw.hex(),
            "identity": signer.serialized.hex(),
            "signature": signer.sign(raw).hex(),
        }).encode()

    async def go():
        cli = RpcClient(args.host, args.port, ssl_ctx=_cli_ssl(args))
        await cli.connect()
        res = await cli.unary("InstallChaincode", raw, timeout=60.0)
        await cli.close()
        print(res.decode())

    asyncio.run(go())


def _cmd_ccqueryinstalled(args):
    from fabric_tpu.comm.rpc import RpcClient

    async def go():
        cli = RpcClient(args.host, args.port, ssl_ctx=_cli_ssl(args))
        await cli.connect()
        res = await cli.unary("QueryInstalled", b"{}")
        await cli.close()
        print(res.decode())

    asyncio.run(go())


def _cmd_ledgerutil(args):
    from fabric_tpu.tools import ledgerutil as lu

    if args.action == "verify":
        res = lu.verify_ledger(args.dirs[0])
        print(json.dumps({"height": res.height, "ok": res.ok,
                          "errors": res.errors}))
        sys.exit(0 if res.ok else 1)
    res = lu.compare_ledgers(args.dirs[0], args.dirs[1])
    print(json.dumps(res))
    sys.exit(0 if res["identical"] else 1)


def _cmd_replay(args):
    """Offline catch-up (peer/replay.py): validate a staged block
    store into one configured channel's ledger at full pipeline depth,
    print the replay stats as JSON, and exit.  Composes with a
    ``snapshot_dir`` channel config: the snapshot bootstraps state at
    H, this replays H+1.. — and a killed run resumes from the
    committed height."""
    from fabric_tpu.nodeconfig import ConfigError, load_peer_config

    try:
        cfg = load_peer_config(args.config)
    except ConfigError as e:
        print(f"config error: {e}", file=sys.stderr)
        sys.exit(2)

    async def go():
        node = _build_peer(cfg)
        ref = None
        for ch in cfg.channels:
            if (ch if isinstance(ch, str) else ch.name) == args.channel:
                ref = ch
                break
        if ref is None:
            print(f"channel {args.channel} not in config",
                  file=sys.stderr)
            sys.exit(2)
        src = args.source or (
            "" if isinstance(ref, str) else ref.replay_from
        )
        if not src:
            print("no replay source: pass --source or set the "
                  "channel's replay_from", file=sys.stderr)
            sys.exit(2)
        chan = _join_config_channel(node, cfg, ref)
        try:
            stats = await chan.replay_local(src, depth=args.depth)
            stats["height"] = chan.height
            print(json.dumps(stats))
        finally:
            chan.stop()

    asyncio.run(go())


def _cmd_snapshot(args):
    from fabric_tpu.comm.rpc import RpcClient

    async def go():
        cli = RpcClient(args.host, args.port, ssl_ctx=_cli_ssl(args))
        await cli.connect()
        raw = await cli.unary("Snapshot", json.dumps(
            {"channel": args.channel, "out_dir": args.output}
        ).encode(), timeout=600.0)
        await cli.close()
        print(raw.decode())

    asyncio.run(go())


def _cmd_discover(args):
    from fabric_tpu.comm.rpc import RpcClient

    async def go():
        cli = RpcClient(args.host, args.port, ssl_ctx=_cli_ssl(args))
        await cli.connect()
        q = {"query": args.query, "channel": args.channel}
        if args.chaincode:
            q["chaincode"] = args.chaincode
        raw = await cli.unary("Discover", json.dumps(q).encode())
        await cli.close()
        print(raw.decode())

    asyncio.run(go())


def main(argv=None):
    p = argparse.ArgumentParser(prog="fabric-tpu")
    p.add_argument("--tls-ca", help="trusted TLS CA bundle (enables TLS)")
    p.add_argument("--tls-cert", help="client TLS certificate (mTLS)")
    p.add_argument("--tls-key", help="client TLS key (mTLS)")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("cryptogen", help="generate org crypto material")
    c.add_argument("--org", action="append", required=True,
                   metavar="MSPID:domain")
    c.add_argument("--peers", type=int, default=1)
    c.add_argument("--orderers", type=int, default=0)
    c.add_argument("--users", type=int, default=1)
    c.add_argument("--output", default="crypto-config")

    c = sub.add_parser("configtxgen", help="genesis block from profile")
    c.add_argument("--profile", required=True)
    c.add_argument("--output", required=True)

    c = sub.add_parser("orderer", help="run an ordering node")
    c.add_argument("--config", required=True)

    c = sub.add_parser("peer", help="run a peer node")
    c.add_argument("--config", required=True)

    c = sub.add_parser("sidecar-serve",
                       help="run a standalone validation sidecar")
    c.add_argument("--listen", default="127.0.0.1:7054",
                   help="host:port to serve the validate stream on")
    c.add_argument("--mesh-devices", type=int, default=0,
                   help="device-mesh sharding (-1 = all local devices)")
    c.add_argument("--mesh-shape", default="",
                   help="device grid, 'N' or 'NxM' (data x replica); "
                        "overrides --mesh-devices")
    c.add_argument("--mesh-distributed", action="store_true",
                   help="span the mesh across jax.distributed "
                        "processes (requires --mesh-coordinator)")
    c.add_argument("--mesh-coordinator", default="",
                   help="host:port rendezvous for the distributed mesh")
    c.add_argument("--mesh-process-id", type=int, default=0,
                   help="this process's rank in the distributed mesh")
    c.add_argument("--mesh-num-processes", type=int, default=1,
                   help="total process count in the distributed mesh")
    c.add_argument("--verify-chunk", type=int, default=0)
    c.add_argument("--recode-device", action="store_true")
    c.add_argument("--queue-blocks", type=int, default=8,
                   help="per-tenant admission queue bound (BUSY past it)")
    c.add_argument("--coalesce", type=int, default=4,
                   help="max cross-tenant batches per device dispatch")
    c.add_argument("--operations-port", type=int, default=None)
    c.add_argument("--slos", default="",
                   help="SLO spec string (observe/slo.py), e.g. "
                        "'req:latency:ms=50;busy:busy:pct=5' — served "
                        "at /slo on the operations port")
    c.add_argument("--vitals-interval-s", type=float, default=0.0,
                   help="flight-data recorder sample interval "
                        "(seconds; 0 = recorder off)")
    c.add_argument("--vitals-retention", type=int, default=240,
                   help="points retained per metric series")
    c.add_argument("--blackbox-dir", default="",
                   help="directory for black-box incident bundles "
                        "('' = in-memory index only)")
    c.add_argument("--device-ledger", type=int, default=1,
                   help="per-launch device-time ledger (1 = on, the "
                        "default): compile/queue/execute/transfer "
                        "attribution at /launches on the operations "
                        "port")
    c.add_argument("--autopilot", action="store_true",
                   help="arm a sidecar-local traffic autopilot "
                        "actuating coalesce/verify_chunk (drain-"
                        "boundary setters) + tenant shed/weights off "
                        "this scheduler's own stats")
    c.add_argument("--autopilot-tick-s", type=float, default=1.0)
    c.add_argument("--autopilot-knobs", default="",
                   help="per-knob min/max clamp spec "
                        "(control/autopilot.py parse_knob_specs)")

    c = sub.add_parser("chaincode", help="run a sample ccaas chaincode server")
    c.add_argument("--name", required=True)
    c.add_argument("--port", type=int, default=0)
    c.add_argument("--contract", default="kv", choices=["kv", "marbles"])

    c = sub.add_parser("osnadmin", help="orderer channel participation")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, required=True)
    c.add_argument("--channel", required=True)
    c.add_argument("--genesis")

    for name in ("invoke", "query"):
        c = sub.add_parser(name, help=f"gateway {name}")
        c.add_argument("--host", default="127.0.0.1")
        c.add_argument("--port", type=int, required=True)
        c.add_argument("--channel", required=True)
        c.add_argument("--chaincode", required=True)
        c.add_argument("--msp-dir", required=True)
        c.add_argument("--msp-id", required=True)
        c.add_argument("args", nargs="+")

    c = sub.add_parser("ccpackage",
                       help="build a ccaas chaincode package")
    c.add_argument("--label", required=True)
    c.add_argument("--address", required=True,
                   help="ccaas endpoint host:port (connection.json)")
    c.add_argument("--output", required=True)

    c = sub.add_parser("ccinstall",
                       help="install a chaincode package on a peer")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, required=True)
    c.add_argument("--package", required=True)
    c.add_argument("--sign-msp-dir", default=None,
                   help="admin MSP dir: sign the install request "
                        "(required when the peer enforces "
                        "install_require_admin)")
    c.add_argument("--sign-msp-id", default=None,
                   help="MSP id of the signing admin identity")

    c = sub.add_parser("ccqueryinstalled",
                       help="list packages installed on a peer")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, required=True)

    c = sub.add_parser("ledgerutil", help="offline ledger forensics")
    c.add_argument("action", choices=["verify", "compare"])
    c.add_argument("dirs", nargs="+")

    c = sub.add_parser("snapshot", help="request a ledger snapshot")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, required=True)
    c.add_argument("--channel", required=True)
    c.add_argument("--output", required=True)

    c = sub.add_parser("replay",
                       help="offline catch-up: validate a staged "
                            "block store into a channel's ledger at "
                            "full pipeline depth")
    c.add_argument("--config", required=True,
                   help="peer config (the channel's genesis/snapshot "
                        "anchors and pipeline knobs come from here)")
    c.add_argument("--channel", required=True)
    c.add_argument("--source",
                   help="block-store directory to replay from "
                        "(default: the channel's replay_from)")
    c.add_argument("--depth", type=int, default=None,
                   help="pipeline depth override for the replay "
                        "(default: the config's pipeline_depth)")

    c = sub.add_parser("discover", help="discovery queries")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, required=True)
    c.add_argument("--channel", required=True)
    c.add_argument("--query", default="peers",
                   choices=["peers", "config", "endorsers"])
    c.add_argument("--chaincode")

    c = sub.add_parser("configtxlator",
                       help="config proto<->JSON + update deltas")
    c.add_argument("action",
                   choices=["proto_decode", "proto_encode", "compute_update"])
    c.add_argument("--type", help="message type, e.g. common.Config")
    c.add_argument("--input", help="input file (proto or JSON)")
    c.add_argument("--original", help="compute_update: original config pb")
    c.add_argument("--updated", help="compute_update: updated config pb")
    c.add_argument("--channel", help="compute_update: channel id")
    c.add_argument("--output", help="output file (default stdout)")

    c = sub.add_parser("node",
                       help="offline channel ops on a STOPPED peer")
    c.add_argument("action",
                   choices=["reset", "rollback", "unjoin", "rebuild-dbs"])
    c.add_argument("--channel-dir", required=True)
    c.add_argument("--block-number", type=int,
                   help="rollback: last block to keep")

    args = p.parse_args(argv)
    if args.cmd == "cryptogen":
        _cmd_cryptogen(args)
    elif args.cmd == "configtxgen":
        _cmd_configtxgen(args)
    elif args.cmd == "orderer":
        from fabric_tpu import cli as _self  # noqa: F401

        _cmd_node(args, _run_orderer)
    elif args.cmd == "peer":
        _cmd_node(args, _run_peer)
    elif args.cmd == "sidecar-serve":
        try:
            asyncio.run(_run_sidecar(args))
        except KeyboardInterrupt:
            pass
    elif args.cmd == "chaincode":
        try:
            asyncio.run(_run_chaincode(args))
        except KeyboardInterrupt:
            pass
    elif args.cmd == "osnadmin":
        _cmd_osnadmin(args)
    elif args.cmd == "invoke":
        _cmd_invoke(args)
    elif args.cmd == "query":
        _cmd_invoke(args, evaluate=True)
    elif args.cmd == "ccpackage":
        _cmd_ccpackage(args)
    elif args.cmd == "ccinstall":
        _cmd_ccinstall(args)
    elif args.cmd == "ccqueryinstalled":
        _cmd_ccqueryinstalled(args)
    elif args.cmd == "ledgerutil":
        _cmd_ledgerutil(args)
    elif args.cmd == "snapshot":
        _cmd_snapshot(args)
    elif args.cmd == "replay":
        _cmd_replay(args)
    elif args.cmd == "discover":
        _cmd_discover(args)
    elif args.cmd == "configtxlator":
        _cmd_configtxlator(args)
    elif args.cmd == "node":
        _cmd_nodeops(args)


def _cmd_configtxlator(args):
    from fabric_tpu.tools import configtxlator as ctl

    def out(data: bytes):
        if args.output:
            with open(args.output, "wb") as f:
                f.write(data)
        else:
            sys.stdout.buffer.write(data)
            if not data.endswith(b"\n"):
                sys.stdout.buffer.write(b"\n")

    if args.action == "proto_decode":
        with open(args.input, "rb") as f:
            out(ctl.proto_decode(args.type, f.read()).encode())
    elif args.action == "proto_encode":
        with open(args.input, "rb") as f:
            out(ctl.proto_encode(args.type, f.read().decode()))
    else:  # compute_update
        with open(args.original, "rb") as f:
            original = f.read()
        with open(args.updated, "rb") as f:
            updated = f.read()
        out(ctl.compute_update(args.channel, original, updated))


def _cmd_nodeops(args):
    from fabric_tpu.tools import nodeops

    if args.action == "reset":
        res = nodeops.reset(args.channel_dir)
    elif args.action == "rebuild-dbs":
        res = nodeops.rebuild_dbs(args.channel_dir)
    elif args.action == "unjoin":
        res = nodeops.unjoin(args.channel_dir)
    else:  # rollback
        if args.block_number is None:
            print("rollback requires --block-number", file=sys.stderr)
            sys.exit(2)
        res = nodeops.rollback(args.channel_dir, args.block_number)
    print(json.dumps(res))


if __name__ == "__main__":
    main()

"""Discovery service logic: peer membership, config queries, and
endorsement descriptors (layouts).

Reference: discovery/ — notably endorsement.go:84-217
``PeersForEndorsement``: given a chaincode's policy, compute the
*layouts* (minimal combinations of org-grouped endorsers that satisfy
the policy) a client can use to target endorsement requests.  The
gateway's endorse path consumes the same computation
(internal/pkg/gateway/endorse.go:170).

Here the policy AST is walked directly into org-quantity layouts; the
per-org peer lists come from the registry the node maintains (static
wiring or anchor-peer config — the gossip-membership analog)."""

from __future__ import annotations

from dataclasses import dataclass, field
from itertools import combinations

from fabric_tpu.crypto import policy as pol

MAX_LAYOUTS = 16


def layouts_for_policy(rule) -> list[dict[str, int]]:
    """→ list of {msp_id: required_count} minimal satisfying layouts.

    Walks the AST: a SignedBy leaf needs one signature from its org;
    NOutOf(n, rules) takes every n-subset of child layouts (capped at
    MAX_LAYOUTS, like the reference caps its layout enumeration)."""

    def merge(a: dict, b: dict) -> dict:
        out = dict(a)
        for k, v in b.items():
            out[k] = out.get(k, 0) + v
        return out

    def walk(node) -> list[dict]:
        if isinstance(node, pol.SignedBy):
            return [{node.principal.msp_id: 1}]
        assert isinstance(node, pol.NOutOf)
        child_layouts = [walk(r) for r in node.rules]
        out: list[dict] = []
        for subset in combinations(range(len(node.rules)), node.n):
            partial = [{}]
            for idx in subset:
                partial = [
                    merge(p, c) for p in partial for c in child_layouts[idx]
                ][:MAX_LAYOUTS]
            out.extend(partial)
            if len(out) >= MAX_LAYOUTS:
                break
        # dedupe
        seen, uniq = set(), []
        for layout in out:
            key = tuple(sorted(layout.items()))
            if key not in seen:
                seen.add(key)
                uniq.append(layout)
        return uniq[:MAX_LAYOUTS]

    return walk(rule)


@dataclass
class PeerInfo:
    msp_id: str
    host: str
    port: int
    height: int = 0              # max across channels (legacy/display)
    heights: dict = field(default_factory=dict)  # channel -> height
    # liveness (gossip/discovery alive/dead expiration analog): a peer
    # is a candidate for election/dissemination only while alive.
    # None = never probed — treated alive so static wirings (tests,
    # fresh registries) work before the first probe round.
    alive: bool | None = None
    last_seen: float = 0.0


@dataclass
class PeerRegistry:
    """Known endorsing peers by org (gossip-membership analog; fed by
    static wiring or by anchor peers from the channel config)."""

    peers: dict = field(default_factory=dict)  # msp_id -> [PeerInfo]

    def add(self, info: PeerInfo) -> None:
        self.peers.setdefault(info.msp_id, []).append(info)

    def for_org(self, msp_id: str) -> list[PeerInfo]:
        return list(self.peers.get(msp_id, []))

    def from_anchor_peers(self, bundle) -> None:
        """Seed from the channel config's AnchorPeers values."""
        from fabric_tpu import protoutil
        from fabric_tpu.protos import configtx_pb2

        app = bundle.config.channel_group.groups.get("Application")
        if app is None:
            return
        for org_name, grp in app.groups.items():
            if "AnchorPeers" not in grp.values:
                continue
            ap = protoutil.unmarshal(
                configtx_pb2.AnchorPeers, grp.values["AnchorPeers"].value
            )
            for a in ap.anchor_peers:
                self.add(PeerInfo(org_name, a.host, a.port))


class DiscoveryService:
    """Query surface (discovery/service.go analog): peers, config,
    endorsement descriptors."""

    def __init__(self, registry: PeerRegistry, bundle_for=None,
                 policy_for=None):
        """bundle_for(channel) -> channelconfig.Bundle | None;
        policy_for(channel, chaincode) -> policy AST | None."""
        self.registry = registry
        self.bundle_for = bundle_for or (lambda ch: None)
        self.policy_for = policy_for or (lambda ch, cc: None)

    def peers(self, channel: str) -> list[dict]:
        return [
            {"msp_id": p.msp_id, "host": p.host, "port": p.port,
             "height": p.height}
            for org in sorted(self.registry.peers)
            for p in self.registry.for_org(org)
        ]

    def config(self, channel: str) -> dict | None:
        bundle = self.bundle_for(channel)
        if bundle is None:
            return None
        return {
            "msps": sorted(bundle.msp_manager.msps),
            "orderers": [],
            "application_orgs": bundle.application_orgs(),
            "capabilities": sorted(bundle.application_capabilities()),
        }

    def endorsement_descriptor(self, channel: str, chaincode: str) -> dict | None:
        """The PeersForEndorsement analog: layouts + per-org peers."""
        rule = self.policy_for(channel, chaincode)
        if rule is None:
            return None
        layouts = layouts_for_policy(rule)
        orgs = sorted({org for lay in layouts for org in lay})
        return {
            "chaincode": chaincode,
            "layouts": layouts,
            "peers_by_org": {
                org: [
                    {"host": p.host, "port": p.port, "msp_id": org}
                    for p in self.registry.for_org(org)
                ]
                for org in orgs
            },
        }

"""Metrics SPI + registry (the vendored fabric-lib-go metrics analog).

Reference: metrics.Provider → Counter/Gauge/Histogram with
``With(label pairs...)`` (fabric-lib-go/common/metrics/provider.go),
~80 documented metrics (docs/source/metrics_reference.rst), exposed by
the operations server at /metrics (core/operations/system.go:89-209).

Design: one process-wide registry of typed instruments; label variants
materialize lazily.  Rendering follows the Prometheus text exposition
format, so any Prometheus scraper works against the operations server
(fabric_tpu.opsserver).  No external client library — the framework is
dependency-free here by design.
"""

from __future__ import annotations

import math
import threading
import time
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field
from itertools import accumulate


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = registry._lock

    def add(self, delta: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + delta

    def add_locked(self, delta: float, key: tuple) -> None:
        """``add`` with the registry lock HELD by the caller and the
        label key precomputed — every instrument of one registry
        shares the lock, so a multi-instrument batch (the tx-flow
        cohort publish) pays ONE acquisition."""
        self._values[key] = self._values.get(key, 0.0) + delta

    def value(self, **labels) -> float:
        # under the registry lock: an unlocked read can observe a dict
        # mid-resize from a concurrent add() on another thread
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def snapshot(self) -> dict[tuple, float]:
        """Consistent copy of every label variant (render//trace)."""
        with self._lock:
            return dict(self._values)


class Gauge:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._lock = registry._lock

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, delta: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + delta

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def snapshot(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._values)


_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, math.inf,
)


@dataclass
class _Hist:
    #: RAW per-bucket counts (first bucket the value fits) — one
    #: bisect + one increment per observe instead of walking every
    #: bucket; the read accessors cumulate (Prometheus ``le`` form)
    counts: list = field(default_factory=lambda: [0] * len(_DEFAULT_BUCKETS))
    total: float = 0.0
    n: int = 0


class Histogram:
    def __init__(self, name: str, help_: str, registry: "Registry",
                 buckets=_DEFAULT_BUCKETS, exemplars: int = 0):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self._values: dict[tuple, _Hist] = {}
        self._lock = registry._lock
        # trace exemplars: a bounded last-K ring of (value, trace ref)
        # per label variant, recorded when the observer passes an
        # ``exemplar=`` ref — so a p99 spike on /vitals links to the
        # exact block's trace tree.  0 (the default) keeps observe()
        # byte-for-byte on today's path.
        self.exemplar_k = int(exemplars)
        self._exemplars: dict[tuple, deque] = {}

    def observe(self, value: float, *, exemplar=None, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            h = self._values.get(k)
            if h is None:
                h = self._values[k] = _Hist(counts=[0] * len(self.buckets))
            h.total += value
            h.n += 1
            # first bucket that fits; a value past every bucket (no
            # +Inf tail) counts toward sum/count but no bucket, same
            # as the Prometheus cumulative form
            i = bisect_left(self.buckets, value)
            if i < len(h.counts):
                h.counts[i] += 1
            if self.exemplar_k and exemplar is not None:
                ring = self._exemplars.get(k)
                if ring is None:
                    ring = self._exemplars[k] = deque(
                        maxlen=self.exemplar_k
                    )
                ring.append((value, str(exemplar)))

    def observe_repeat(self, value: float, n: int, *, exemplar=None,
                       **labels) -> None:
        """``n`` identical observations in O(buckets) under ONE lock
        acquisition — the tx-flow journal's per-block cohort publish
        (every tx of a block shares the included→applied interval, so
        a 1000-tx block costs the same as a 1-tx one).  Bit-equal to
        calling ``observe(value)`` n times; at most one exemplar is
        recorded for the whole batch."""
        n = int(n)
        if n <= 0:
            return
        k = _label_key(labels)
        with self._lock:
            self.observe_repeat_locked(value, n, k, exemplar=exemplar)

    def observe_repeat_locked(self, value: float, n: int, key: tuple,
                              exemplar=None) -> None:
        """Body of :meth:`observe_repeat` with the registry lock HELD
        by the caller and the label key precomputed — every instrument
        of one registry shares the lock, so a multi-instrument batch
        (the tx-flow cohort publish: stages + e2e + lag + counter)
        pays ONE acquisition for the whole block."""
        h = self._values.get(key)
        if h is None:
            h = self._values[key] = _Hist(counts=[0] * len(self.buckets))
        h.total += value * n
        h.n += n
        i = bisect_left(self.buckets, value)
        if i < len(h.counts):
            h.counts[i] += n
        if self.exemplar_k and exemplar is not None:
            ring = self._exemplars.get(key)
            if ring is None:
                ring = self._exemplars[key] = deque(
                    maxlen=self.exemplar_k
                )
            ring.append((value, str(exemplar)))

    def value(self, **labels) -> dict | None:
        """Locked read of ONE label variant: {"counts" (cumulative per
        bucket), "sum", "count"} or None if never observed.  Histograms
        had no read accessor at all before — reaching into ``_values``
        raced ``observe`` mid-update (counts bumped, total not yet)."""
        with self._lock:
            h = self._values.get(_label_key(labels))
            if h is None:
                return None
            return {"counts": list(accumulate(h.counts)),
                    "sum": h.total, "count": h.n}

    def snapshot(self) -> dict[tuple, dict]:
        """Consistent copy of every label variant (render//trace)."""
        with self._lock:
            return {
                k: {"counts": list(accumulate(h.counts)), "sum": h.total,
                    "count": h.n}
                for k, h in self._values.items()
            }

    def exemplar_snapshot(self) -> dict[tuple, list]:
        """Locked copy of every variant's exemplar ring: {label key:
        [(value, trace ref), ...]} — empty when exemplars are unarmed."""
        with self._lock:
            return {k: list(r) for k, r in self._exemplars.items() if r}

    def time(self, **labels):
        """Context manager observing elapsed seconds."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, **labels)
                return False

        return _Timer()


class Registry:
    """Process-local metric registry; render() emits Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, help_, Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, help_, Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets=None, exemplars: int | None = None) -> Histogram:
        """``buckets``/``exemplars`` apply on FIRST registration only
        (a metric's bucket layout and exemplar capacity are fixed for
        its lifetime); later callers get the existing instrument
        regardless."""
        kwargs: dict = {} if buckets is None else {"buckets": buckets}
        if exemplars is not None:
            kwargs["exemplars"] = exemplars
        return self._get(name, help_, Histogram, **kwargs)

    def _get(self, name, help_, cls, **kwargs):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, help_, self, **kwargs)
        if not isinstance(m, cls):
            raise TypeError(f"metric {name} already registered as {type(m).__name__}")
        return m

    @staticmethod
    def _fmt_labels(key: tuple, extra: str = "") -> str:
        parts = [f'{k}="{v}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def metric(self, name: str):
        """Registered instrument by name, or None (locked lookup — the
        /trace summary reads selected metrics through their locked
        snapshot() accessors rather than reaching into ``_values``)."""
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> list[tuple[str, object]]:
        """Sorted copy of the live metric table (the flight-data
        recorder's sampler walks this, then reads each instrument
        through its own locked ``snapshot()`` — the registry lock is
        held only for the table copy, exactly like ``render``)."""
        with self._lock:
            return sorted(self._metrics.items())

    def render(self) -> str:
        # take the registry lock only to copy the metric table; each
        # instrument's snapshot() then takes the (same, non-reentrant)
        # lock itself — so render sees per-metric-consistent values
        # without racing concurrent observe()/add() mid-update
        with self._lock:
            metrics = sorted(self._metrics.items())
        out = []
        for name, m in metrics:
            if m.help:
                out.append(f"# HELP {name} {m.help}")
            if isinstance(m, Counter):
                out.append(f"# TYPE {name} counter")
                for k, v in sorted(m.snapshot().items()):
                    out.append(f"{name}{self._fmt_labels(k)} {v}")
            elif isinstance(m, Gauge):
                out.append(f"# TYPE {name} gauge")
                for k, v in sorted(m.snapshot().items()):
                    out.append(f"{name}{self._fmt_labels(k)} {v}")
            elif isinstance(m, Histogram):
                out.append(f"# TYPE {name} histogram")
                for k, h in sorted(m.snapshot().items()):
                    for b, c in zip(m.buckets, h["counts"]):
                        le = "+Inf" if math.isinf(b) else repr(b)
                        # hoisted: a backslash inside an f-string
                        # expression is a SyntaxError before 3.12
                        le_label = 'le="%s"' % le
                        out.append(
                            f"{name}_bucket"
                            f"{self._fmt_labels(k, le_label)} {c}"
                        )
                    out.append(f"{name}_sum{self._fmt_labels(k)} {h['sum']}")
                    out.append(f"{name}_count{self._fmt_labels(k)} {h['count']}")
        return "\n".join(out) + "\n"


def exemplars_report(registry: "Registry",
                     metric: str | None = None) -> dict:
    """{metric: {label_str: [[value, trace_ref], ...]}} over every
    histogram with a non-empty exemplar ring — the /vitals and
    black-box-bundle surface.  Bounded by construction (each ring is
    last-K)."""
    out: dict = {}
    for name, m in registry.metrics():
        if metric is not None and name != metric:
            continue
        if not isinstance(m, Histogram) or not m.exemplar_k:
            continue
        snap = m.exemplar_snapshot()
        if not snap:
            continue
        out[name] = {
            (",".join(f"{k}={v}" for k, v in key) or "_"): [
                [round(v, 9), ref] for v, ref in ring
            ]
            for key, ring in sorted(snap.items())
        }
    return out


_global = Registry()


def global_registry() -> Registry:
    return _global

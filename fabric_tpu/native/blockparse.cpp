// Native block pre-parser for the commit hot path.
//
// The peer's validator needs, per envelope: header spans (creator,
// nonce, tx_id, channel, type), the creator-signature item
// (sha256(payload), r, s), every endorsement's item
// (sha256(prp ‖ endorser), r, s) plus identity spans, the
// tx_id binding digest sha256(nonce ‖ creator), and the rwset span.
// Doing that in Python costs ~6 protobuf unmarshals + 3 hashes per tx;
// this module does the whole block in ONE C call over the raw wire
// format (the fabric envelope encoding is the compatibility contract,
// so the field numbers below are stable by construction).
//
// Scope note: unusual envelopes (config txs, malformed bytes) are
// reported with ok=0 and re-parsed by the Python slow path — this
// fast path only needs to cover the standard endorser transaction.
//
// Built on demand with g++ (see fabric_tpu/native/__init__.py); no
// external dependencies — SHA-256 is implemented from FIPS 180-4.

#include <cstdint>
#include <cstring>
#include <string_view>
#include <unordered_map>
#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define BP_HAVE_SHANI_COMPILE 1
#endif

namespace {

#ifdef BP_HAVE_SHANI_COMPILE
// SHA-NI compress function (Intel SHA extensions): ~10× the scalar
// path; the commit pre-parser hashes ~4.5 MB per 1000-tx block, so
// this is a double-digit-ms saving per block on a single core.
// Structure follows Intel's published reference sequence.
__attribute__((target("sha,sse4.1,ssse3")))
static void sha256_block_ni(uint32_t h[8], const uint8_t* p) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&h[0]));
  __m128i STATE1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&h[4]));
  TMP = _mm_shuffle_epi32(TMP, 0xB1);        // CDAB
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);  // EFGH
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);  // ABEF
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);       // CDGH
  const __m128i ABEF_SAVE = STATE0, CDGH_SAVE = STATE1;
  __m128i MSG, MSG0, MSG1, MSG2, MSG3;

  // rounds 0-3
  MSG0 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 0)), MASK);
  MSG = _mm_add_epi32(
      MSG0, _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

  // rounds 4-7
  MSG1 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 16)), MASK);
  MSG = _mm_add_epi32(
      MSG1, _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

  // rounds 8-11
  MSG2 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 32)), MASK);
  MSG = _mm_add_epi32(
      MSG2, _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

  // rounds 12-15
  MSG3 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 48)), MASK);
  MSG = _mm_add_epi32(
      MSG3, _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
  MSG0 = _mm_add_epi32(MSG0, TMP);
  MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

  // rounds 16-19
  MSG = _mm_add_epi32(
      MSG0, _mm_set_epi64x(0x240CA1CC0FC19DC6ULL, 0xEFBE4786E49B69C1ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG0, MSG3, 4);
  MSG1 = _mm_add_epi32(MSG1, TMP);
  MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

  // rounds 20-23
  MSG = _mm_add_epi32(
      MSG1, _mm_set_epi64x(0x76F988DA5CB0A9DCULL, 0x4A7484AA2DE92C6FULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG1, MSG0, 4);
  MSG2 = _mm_add_epi32(MSG2, TMP);
  MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

  // rounds 24-27
  MSG = _mm_add_epi32(
      MSG2, _mm_set_epi64x(0xBF597FC7B00327C8ULL, 0xA831C66D983E5152ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG2, MSG1, 4);
  MSG3 = _mm_add_epi32(MSG3, TMP);
  MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

  // rounds 28-31
  MSG = _mm_add_epi32(
      MSG3, _mm_set_epi64x(0x1429296706CA6351ULL, 0xD5A79147C6E00BF3ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
  MSG0 = _mm_add_epi32(MSG0, TMP);
  MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

  // rounds 32-35
  MSG = _mm_add_epi32(
      MSG0, _mm_set_epi64x(0x53380D134D2C6DFCULL, 0x2E1B213827B70A85ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG0, MSG3, 4);
  MSG1 = _mm_add_epi32(MSG1, TMP);
  MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

  // rounds 36-39
  MSG = _mm_add_epi32(
      MSG1, _mm_set_epi64x(0x92722C8581C2C92EULL, 0x766A0ABB650A7354ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG1, MSG0, 4);
  MSG2 = _mm_add_epi32(MSG2, TMP);
  MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

  // rounds 40-43
  MSG = _mm_add_epi32(
      MSG2, _mm_set_epi64x(0xC76C51A3C24B8B70ULL, 0xA81A664BA2BFE8A1ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG2, MSG1, 4);
  MSG3 = _mm_add_epi32(MSG3, TMP);
  MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

  // rounds 44-47
  MSG = _mm_add_epi32(
      MSG3, _mm_set_epi64x(0x106AA070F40E3585ULL, 0xD6990624D192E819ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
  MSG0 = _mm_add_epi32(MSG0, TMP);
  MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

  // rounds 48-51
  MSG = _mm_add_epi32(
      MSG0, _mm_set_epi64x(0x34B0BCB52748774CULL, 0x1E376C0819A4C116ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG0, MSG3, 4);
  MSG1 = _mm_add_epi32(MSG1, TMP);
  MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
  MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

  // rounds 52-55
  MSG = _mm_add_epi32(
      MSG1, _mm_set_epi64x(0x682E6FF35B9CCA4FULL, 0x4ED8AA4A391C0CB3ULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG1, MSG0, 4);
  MSG2 = _mm_add_epi32(MSG2, TMP);
  MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

  // rounds 56-59
  MSG = _mm_add_epi32(
      MSG2, _mm_set_epi64x(0x8CC7020884C87814ULL, 0x78A5636F748F82EEULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  TMP = _mm_alignr_epi8(MSG2, MSG1, 4);
  MSG3 = _mm_add_epi32(MSG3, TMP);
  MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

  // rounds 60-63
  MSG = _mm_add_epi32(
      MSG3, _mm_set_epi64x(0xC67178F2BEF9A3F7ULL, 0xA4506CEB90BEFFFAULL));
  STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
  MSG = _mm_shuffle_epi32(MSG, 0x0E);
  STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);

  TMP = _mm_shuffle_epi32(STATE0, 0x1B);      // FEBA
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);   // DCHG
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);       // DCBA
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);          // HGFE
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&h[0]), STATE0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&h[4]), STATE1);
}

static bool shani_available() {
  static const bool ok = __builtin_cpu_supports("sha");
  return ok;
}
#endif  // BP_HAVE_SHANI_COMPILE

// ---------------------------------------------------------------- sha256
struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len = 0;
  unsigned fill = 0;

  static constexpr uint32_t K[64] = {
      0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
      0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
      0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
      0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
      0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
      0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
      0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
      0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
      0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
      0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
      0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

  Sha256() { reset(); }
  void reset() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
    len = 0;
    fill = 0;
  }
  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }
  void block(const uint8_t* p) {
#ifdef BP_HAVE_SHANI_COMPILE
    if (shani_available()) { sha256_block_ni(h, p); return; }
#endif
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  void update(const uint8_t* p, size_t n) {
    len += n;
    if (fill) {
      while (n && fill < 64) { buf[fill++] = *p++; n--; }
      if (fill == 64) { block(buf); fill = 0; }
    }
    while (n >= 64) { block(p); p += 64; n -= 64; }
    while (n) { buf[fill++] = *p++; n--; }
  }
  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 56) update(&z, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};
constexpr uint32_t Sha256::K[64];

static void sha2(const uint8_t* a, size_t an, const uint8_t* b, size_t bn,
                 uint8_t out[32]) {
  Sha256 s;
  s.update(a, an);
  if (b) s.update(b, bn);
  s.final(out);
}

// ------------------------------------------------------------- wire walk
struct Span {
  const uint8_t* p = nullptr;
  size_t n = 0;
  bool ok = false;
};

static bool varint(const uint8_t*& p, const uint8_t* end, uint64_t& out) {
  out = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    out |= uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
  }
  return false;
}

// LAST occurrence of length-delimited field `field` — protobuf
// last-field-wins semantics, matching the Python decoder exactly (a
// duplicate-field envelope must not validate differently on the two
// parse paths).
//
// All length checks compare the attacker-controlled varint length
// against the REMAINING size (`len > uint64_t(end - p)`) — never
// `p + len > end`, whose pointer arithmetic is UB and wraps for huge
// lengths, letting a crafted envelope pass the check with an
// out-of-bounds span.
static Span field_bytes(const uint8_t* p, size_t n, uint32_t field) {
  const uint8_t* end = p + n;
  Span found{};
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return {};
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return {};  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return {};
      if (f == field) found = {p, size_t(len), true};
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return {};
      (void)v;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return {};
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return {};
      p += 8;
    } else {
      return {};
    }
  }
  return found;
}

static bool field_varint(const uint8_t* p, size_t n, uint32_t field,
                         uint64_t& out) {
  const uint8_t* end = p + n;
  bool got = false;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return false;  // upb rejects field number 0
    if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
      if (f == field) { out = v; got = true; }  // last wins
    } else if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      p += len;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return got;
}

// DER ECDSA-Sig-Value -> r,s as 32-byte big-endian; false on oversize
static bool der_sig(const uint8_t* p, size_t n, uint8_t r[32], uint8_t s[32]) {
  const uint8_t* end = p + n;
  auto read_len = [&](const uint8_t*& q, size_t& len) -> bool {
    if (q >= end) return false;
    uint8_t b = *q++;
    if (b < 0x80) { len = b; return true; }
    int cnt = b & 0x7f;
    if (cnt < 1 || cnt > 2 || cnt > end - q) return false;
    len = 0;
    while (cnt--) len = (len << 8) | *q++;
    return true;
  };
  auto read_int = [&](const uint8_t*& q, uint8_t out[32]) -> bool {
    if (q >= end || *q++ != 0x02) return false;
    size_t len;
    if (!read_len(q, len) || len == 0 || len > size_t(end - q)) return false;
    const uint8_t* v = q;
    q += len;
    if (v[0] & 0x80) return false;              // negative: invalid
    if (len > 1 && v[0] == 0 && !(v[1] & 0x80))
      return false;                             // non-minimal encoding
    size_t skip = (len > 1 && v[0] == 0) ? 1 : 0;
    if (len - skip > 32) return false;
    memset(out, 0, 32);
    memcpy(out + (32 - (len - skip)), v + skip, len - skip);
    return true;
  };
  if (n < 2 || *p != 0x30) return false;
  const uint8_t* q = p + 1;
  size_t total;
  if (!read_len(q, total)) return false;
  if (total != size_t(end - q)) return false;   // exact outer length
  if (!read_int(q, r) || !read_int(q, s)) return false;
  return q == end;                              // no trailing bytes
}

static void put_span(int64_t* arr, int i, const uint8_t* base, Span s) {
  arr[2 * i] = s.ok ? (s.p - base) : -1;
  arr[2 * i + 1] = s.ok ? int64_t(s.n) : 0;
}

// upb rejects invalid UTF-8 in proto3 STRING fields; anything the
// Python parser would refuse must leave the fast path, or peers built
// with and without the toolchain would fork on the same block.
static bool valid_utf8(const uint8_t* p, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t c = p[i];
    if (c < 0x80) { i++; continue; }
    int extra;
    uint32_t cp, min;
    if ((c & 0xE0) == 0xC0) { extra = 1; cp = c & 0x1F; min = 0x80; }
    else if ((c & 0xF0) == 0xE0) { extra = 2; cp = c & 0x0F; min = 0x800; }
    else if ((c & 0xF8) == 0xF0) { extra = 3; cp = c & 0x07; min = 0x10000; }
    else return false;
    if (i + extra >= n) return false;
    for (int k = 1; k <= extra; k++) {
      uint8_t cc = p[i + k];
      if ((cc & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (cp < min || cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF))
      return false;
    i += extra + 1;
  }
  return true;
}

// one-level wire-framing walk: true iff every field's framing parses
// (the acceptance bar upb applies to every submessage it decodes —
// unknown fields with VALID framing are fine, torn ones are not)
static bool frame_ok(const uint8_t* p, size_t n) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    if ((key >> 3) == 0) return false;  // upb rejects field number 0
    uint32_t wt = uint32_t(key & 7);
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

// TODO(cleanup): the strict helpers below share one wire-walk
// skeleton; consolidating them onto a visitor template (the
// mvccprep.cpp walk() shape) would remove the duplication.  Deferred
// deliberately: their behavior is pinned by the randomized fuzz +
// equivalence sweep (tests/test_native_fuzz.py), and a mechanical
// refactor of the adversarial-input parser is higher risk than the
// duplication it removes.
//
// occurrences of length-delimited field `field` — upb MERGES duplicate
// singular submessages (their repeated subfields concatenate), which
// last-occurrence extraction cannot replicate: any submessage the fast
// path descends into must appear exactly once or the envelope takes
// the python lane
static int count_wt2(const uint8_t* p, size_t n, uint32_t field) {
  const uint8_t* end = p + n;
  int cnt = 0;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return -1;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return -1;
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return -1;
      if (f == field) cnt++;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return -1;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return -1;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return -1;
      p += 8;
    } else {
      return -1;
    }
  }
  return cnt;
}

// ChannelHeader strictness: upb validates the Timestamp submessage's
// framing (field 3) and the UTF-8 of channel_id(4) / tx_id(5)
static bool chdr_strict(const uint8_t* p, size_t n) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return false;  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      if (f == 3 && !frame_ok(p, size_t(len))) return false;
      if ((f == 4 || f == 5) && !valid_utf8(p, size_t(len))) return false;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

// ChaincodeAction strictness: Response(3) framing + message UTF-8,
// ChaincodeID(4) framing + path/name/version UTF-8 — all parsed by
// the Python lane's ChaincodeAction unmarshal
static bool strings_strict(const uint8_t* p, size_t n, uint32_t lo,
                           uint32_t hi) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return false;  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      if (f >= lo && f <= hi && !valid_utf8(p, size_t(len))) return false;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

static bool cca_strict(const uint8_t* p, size_t n) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return false;  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      if (f == 3 && !strings_strict(p, size_t(len), 2, 2)) return false;
      if (f == 4 && !strings_strict(p, size_t(len), 1, 3)) return false;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

// Transaction strictness: python uses actions[0] (FIRST, not last) and
// upb validates the framing of EVERY action — return the first
// action's span iff all actions frame-parse
static Span first_action_strict(const uint8_t* p, size_t n) {
  const uint8_t* end = p + n;
  Span first{};
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return {};
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return {};  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return {};
      if (f == 1) {
        if (!frame_ok(p, size_t(len))) return {};
        if (!first.ok) first = {p, size_t(len), true};
      }
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return {};
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return {};
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return {};
      p += 8;
    } else {
      return {};
    }
  }
  return first;
}

}  // namespace

extern "C" {

// Test hook: digest arbitrary bytes (exercises the SHA-NI dispatch on
// every padding/length boundary from Python property tests).
void sha256_test(const uint8_t* p, int64_t n, uint8_t out[32]) {
  sha2(p, size_t(n), nullptr, 0, out);
}

// Parse n envelopes (spans into blob).  Per-env outputs; endorsements
// flatten into the e_* arrays (capacity cap_endo).  Returns total
// endorsement count, or -1 if a capacity was too small.
//
// ok[i]: 1 = standard endorser tx fully parsed; 0 = slow-path needed
// (the Python validator re-parses those envelopes).
//
// Identity INTERNING: creators/endorsers are deduped block-wide into
// ident_span (uid → span); creator_uid / e_uid reference it and
// e_dup marks repeat endorsers WITHIN a tx — the Python loop then
// resolves each distinct identity exactly once (a block re-presents
// the same few certs thousands of times).
int64_t parse_block(
    const uint8_t* blob, const int64_t* env_off, const int64_t* env_len,
    int64_t n, int64_t cap_endo, int64_t cap_ids,
    // per-envelope outputs
    uint8_t* ok, int64_t* ch_type,
    int64_t* txid_span, int64_t* channel_span, int64_t* creator_span,
    int64_t* nonce_span, int64_t* results_span, int64_t* events_span,
    uint8_t* payload_digest,       // [n,32] sha256(env.payload)
    uint8_t* txid_digest,          // [n,32] sha256(nonce ‖ creator)
    uint8_t* creator_sig_ok, uint8_t* creator_r, uint8_t* creator_s,
    int64_t* endo_start, int64_t* endo_count,
    // flat endorsement outputs
    int64_t* e_endorser_span, uint8_t* e_digest, uint8_t* e_r, uint8_t* e_s,
    uint8_t* e_ok,
    // identity interning outputs
    int32_t* creator_uid,          // [n]; -1 = none
    int32_t* e_uid, uint8_t* e_dup,  // [cap_endo]
    int64_t* ident_span,           // [cap_ids, 2]
    int64_t* n_ids_out) {
  int64_t ne = 0;
  std::unordered_map<std::string_view, int32_t> ids;
  int32_t next_id = 0;
  auto intern = [&](const uint8_t* p, size_t len) -> int32_t {
    std::string_view k(reinterpret_cast<const char*>(p), len);
    auto it = ids.find(k);
    if (it != ids.end()) return it->second;
    if (next_id >= cap_ids) return -2;  // capacity: caller falls back
    ident_span[2 * next_id] = p - blob;
    ident_span[2 * next_id + 1] = int64_t(len);
    ids.emplace(k, next_id);
    return next_id++;
  };
  for (int64_t i = 0; i < n; i++) {
    ok[i] = 0;
    ch_type[i] = -1;
    endo_start[i] = ne;
    endo_count[i] = 0;
    creator_sig_ok[i] = 0;
    put_span(txid_span, i, blob, {});
    put_span(channel_span, i, blob, {});
    put_span(creator_span, i, blob, {});
    put_span(nonce_span, i, blob, {});
    put_span(results_span, i, blob, {});
    put_span(events_span, i, blob, {});
    const uint8_t* env = blob + env_off[i];
    size_t len = size_t(env_len[i]);
    if (!len) continue;

    Span payload = field_bytes(env, len, 1);
    Span sig = field_bytes(env, len, 2);
    if (!payload.ok) continue;
    Span header = field_bytes(payload.p, payload.n, 1);
    Span data = field_bytes(payload.p, payload.n, 2);
    if (!header.ok) continue;
    // Payload.header is a SUBMESSAGE: duplicates merge under upb
    if (count_wt2(payload.p, payload.n, 1) != 1) continue;
    Span chdr = field_bytes(header.p, header.n, 1);
    Span shdr = field_bytes(header.p, header.n, 2);
    if (!chdr.ok || !shdr.ok) continue;
    // upb parses the SignatureHeader as part of the structural
    // BAD_PAYLOAD gate — a torn one must take the python lane, not
    // ride on with empty creator/nonce spans
    if (!frame_ok(shdr.p, shdr.n)) continue;
    uint64_t type = 0;
    field_varint(chdr.p, chdr.n, 1, type);
    ch_type[i] = int64_t(type);
    if (!chdr_strict(chdr.p, chdr.n)) continue;  // python lane decides
    Span channel = field_bytes(chdr.p, chdr.n, 4);
    Span txid = field_bytes(chdr.p, chdr.n, 5);
    Span creator = field_bytes(shdr.p, shdr.n, 1);
    Span nonce = field_bytes(shdr.p, shdr.n, 2);
    put_span(txid_span, i, blob, txid);
    put_span(channel_span, i, blob, channel);
    put_span(creator_span, i, blob, creator);
    put_span(nonce_span, i, blob, nonce);
    creator_uid[i] = -1;
    if (creator.ok) {
      int32_t uid = intern(creator.p, creator.n);
      if (uid == -2) return -1;
      creator_uid[i] = uid;
    }

    // creator signature item: digest of the raw payload bytes
    sha2(payload.p, payload.n, nullptr, 0, payload_digest + 32 * i);
    // absent fields are empty in proto3 — hash exactly what Python's
    // compute_tx_id(sh.nonce, sh.creator) hashes
    sha2(nonce.ok ? nonce.p : blob, nonce.ok ? nonce.n : 0,
         creator.ok ? creator.p : blob, creator.ok ? creator.n : 0,
         txid_digest + 32 * i);
    if (sig.ok &&
        der_sig(sig.p, sig.n, creator_r + 32 * i, creator_s + 32 * i))
      creator_sig_ok[i] = 1;

    if (type != 3 /* ENDORSER_TRANSACTION */ || !data.ok) continue;
    // FIRST action (python semantics), with every action frame-checked
    Span action = first_action_strict(data.p, data.n);
    if (!action.ok) continue;
    Span cap = field_bytes(action.p, action.n, 2);  // TransactionAction.payload
    if (!cap.ok) continue;
    Span cea = field_bytes(cap.p, cap.n, 2);  // ChaincodeActionPayload.action
    if (!cea.ok) continue;
    // .action is a SUBMESSAGE: duplicate occurrences would merge
    // (endorsements concatenating across them) under upb
    if (count_wt2(cap.p, cap.n, 2) != 1) continue;
    Span prp = field_bytes(cea.p, cea.n, 1);
    if (!prp.ok) continue;
    Span cca = field_bytes(prp.p, prp.n, 2);  // prp.extension
    if (!cca.ok) continue;
    if (!cca_strict(cca.p, cca.n)) continue;  // Response/ChaincodeID
    Span results = field_bytes(cca.p, cca.n, 1);
    Span events = field_bytes(cca.p, cca.n, 2);
    put_span(results_span, i, blob, results);
    put_span(events_span, i, blob, events);

    // endorsements: iterate repeated field 2 of ChaincodeEndorsedAction
    const uint8_t* p = cea.p;
    const uint8_t* cend = cea.p + cea.n;
    bool endo_fail = false;
    while (p < cend) {
      uint64_t key;
      if (!varint(p, cend, key)) { endo_fail = true; break; }
      uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
      if (f == 0) { endo_fail = true; break; }  // upb rejects field number 0
      if (wt != 2) {
        uint64_t v;
        if (wt == 0) { if (!varint(p, cend, v)) { endo_fail = true; break; } continue; }
        if (wt == 5) { if (uint64_t(cend - p) < 4) { endo_fail = true; break; } p += 4; continue; }
        if (wt == 1) { if (uint64_t(cend - p) < 8) { endo_fail = true; break; } p += 8; continue; }
        endo_fail = true;  // malformed framing: upb rejects the WHOLE
        break;             // ChaincodeActionPayload — python lane decides
      }
      uint64_t flen;
      if (!varint(p, cend, flen) || flen > uint64_t(cend - p)) {
        endo_fail = true;
        break;
      }
      const uint8_t* fp = p;
      p += flen;
      if (f != 2) continue;
      if (ne >= cap_endo) return -1;
      Span endorser = field_bytes(fp, flen, 1);
      Span esig = field_bytes(fp, flen, 2);
      put_span(e_endorser_span, ne, blob, endorser);
      e_uid[ne] = -1;
      e_dup[ne] = 0;
      if (endorser.ok) {
        int32_t uid = intern(endorser.p, endorser.n);
        if (uid == -2) return -1;
        e_uid[ne] = uid;
        for (int64_t k = endo_start[i]; k < ne; k++)
          if (e_uid[k] == uid) { e_dup[ne] = 1; break; }
      }
      e_ok[ne] = 0;
      if (endorser.ok && esig.ok &&
          der_sig(esig.p, esig.n, e_r + 32 * ne, e_s + 32 * ne)) {
        // message = prp_bytes ‖ endorser_bytes
        sha2(prp.p, prp.n, endorser.p, endorser.n, e_digest + 32 * ne);
        e_ok[ne] = 1;
      } else {
        endo_fail = true;
      }
      ne++;
      endo_count[i]++;
    }
    if (endo_fail) continue;  // slow path sorts out the odd endorsement
    ok[i] = 1;
  }
  *n_ids_out = next_id;
  return ne;
}

}  // extern "C"

"""Native (C++) runtime components, built on demand with g++.

The reference's runtime is compiled Go; the equivalent here is a thin
C++ layer for the host-side hot paths that Python cannot make fast —
currently the block pre-parser (blockparse.cpp): one C call per block
replaces ~6 protobuf unmarshals + 3 SHA-256 calls per transaction on
the commit path.  Build artifacts cache under _build/; when no
compiler is available the callers fall back to the pure-Python paths,
so the framework never hard-requires a toolchain at run time."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

log = logging.getLogger("fabric_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))

_libs: dict = {}       # name → CDLL
_lib_failed: set = set()


_FLAGS = ["-O3", "-shared", "-fPIC", "-std=c++17"]


def _build(src: str, so: str) -> bool:
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", *_FLAGS, src, "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, so)  # atomic: concurrent builders can't corrupt
        with open(so + ".flags", "w") as f:
            f.write(" ".join(_FLAGS))
        return True
    except Exception as e:
        log.warning("native %s build failed (%s); using Python path", src, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _fresh(src: str, so: str) -> bool:
    """Artifact is current iff newer than the source AND built with the
    current flag set (a flag change must invalidate cached .so files)."""
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        return False
    try:
        with open(so + ".flags") as f:
            return f.read() == " ".join(_FLAGS)
    except OSError:
        return False


def _load(name: str):
    """Build-if-stale + dlopen fabric_tpu/native/<name>.cpp → CDLL or
    None (callers fall back to their pure-Python paths)."""
    if name in _libs:
        return _libs[name]
    if name in _lib_failed:
        return None
    src = os.path.join(_DIR, f"{name}.cpp")
    so = os.path.join(_DIR, "_build", f"lib{name}.so")
    if not _fresh(src, so) and not _build(src, so):
        _lib_failed.add(name)
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log.warning("native %s load failed (%s)", name, e)
        _lib_failed.add(name)
        return None
    _libs[name] = lib
    return lib


def blockparse_lib():
    """→ ctypes CDLL with parse_block, or None (Python fallback)."""
    lib = _load("blockparse")
    if lib is not None:
        lib.parse_block.restype = ctypes.c_int64
    return lib


def mvccprep_lib():
    """→ ctypes CDLL with mvcc_prep (rwset wire parse + key interning
    into flat arrays), or None (Python fallback)."""
    lib = _load("mvccprep")
    if lib is not None:
        lib.mvcc_prep.restype = ctypes.c_int64
    return lib


def ecprep_lib():
    """→ ctypes CDLL with ec_prepare (batch u1/u2 window recoding +
    admission flags) and ec_prepare_pack (strided int16 digits/limbs
    straight into the packed launch frame), or None (Python
    fallback).  ``ec_prepare_pack`` may be absent from a stale cached
    .so — callers hasattr-guard it and fall back to ec_prepare."""
    lib = _load("ecprep")
    if lib is not None:
        lib.ec_prepare.restype = None
        try:
            lib.ec_prepare_pack.restype = None
        except AttributeError:  # stale artifact predating the symbol
            pass
    return lib

"""Native (C++) runtime components, built on demand with g++.

The reference's runtime is compiled Go; the equivalent here is a thin
C++ layer for the host-side hot paths that Python cannot make fast —
currently the block pre-parser (blockparse.cpp): one C call per block
replaces ~6 protobuf unmarshals + 3 SHA-256 calls per transaction on
the commit path.  Build artifacts cache under _build/; when no
compiler is available the callers fall back to the pure-Python paths,
so the framework never hard-requires a toolchain at run time."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

log = logging.getLogger("fabric_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "blockparse.cpp")
_SO = os.path.join(_DIR, "_build", "libblockparse.so")

_lib = None
_lib_failed = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO)  # atomic: concurrent builders can't corrupt
        return True
    except Exception as e:
        log.warning("native blockparse build failed (%s); using Python path", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def blockparse_lib():
    """→ ctypes CDLL with parse_block, or None (Python fallback)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    fresh = os.path.exists(_SO) and (
        os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    )
    if not fresh and not _build():
        _lib_failed = True
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        log.warning("native blockparse load failed (%s)", e)
        _lib_failed = True
        return None
    lib.parse_block.restype = ctypes.c_int64
    _lib = lib
    return _lib

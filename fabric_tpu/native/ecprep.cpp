// Native ECDSA verify preparation for the commit hot path.
//
// The TPU kernel (ops/p256v3.py) receives 4-bit window digits of
// u1 = e·s⁻¹ mod n and u2 = r·s⁻¹ mod n; computing them for a block's
// ~3000 signatures in Python costs tens of ms of bigint loops under
// the GIL (round-3 bench: the single largest host phase).  This module
// does the whole batch in one C call — Montgomery batch inversion
// (one Fermat exponentiation + 3(B−1) modmuls, the same algorithm as
// p256v3._batch_inv_mod_n) over 4×64-limb arithmetic — and ctypes
// releases the GIL for the duration, so the work also overlaps the
// commit pipeline's other host phases.
//
// Semantics pinned to ops/p256v3.prepare_cols (and transitively to the
// reference accept set, bccsp/sw/ecdsa.go:41-58): admission is
// 0 < r < n ∧ 0 < s ≤ n/2; rows failing 0 < s < n invert s = 1 so the
// batch product stays invertible; rpn_ok ⇔ r + n < p.
//
// Built on demand with g++ (see fabric_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

namespace {

typedef unsigned __int128 u128;

struct U256 {
  uint64_t w[4];  // little-endian limbs
};

// P-256 group order n and field prime p
static const U256 ORDER_N = {{0xf3b9cac2fc632551ull, 0xbce6faada7179e84ull,
                              0xffffffffffffffffull, 0xffffffff00000000ull}};
static const U256 PRIME_P = {{0xffffffffffffffffull, 0x00000000ffffffffull,
                              0x0000000000000000ull, 0xffffffff00000001ull}};

static int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; i--) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

static bool is_zero(const U256& a) {
  return !(a.w[0] | a.w[1] | a.w[2] | a.w[3]);
}

// a - b, returns borrow
static uint64_t sub(U256& out, const U256& a, const U256& b) {
  uint64_t borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)a.w[i] - b.w[i] - borrow;
    out.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return borrow;
}

// a + b, returns carry
static uint64_t add(U256& out, const U256& a, const U256& b) {
  uint64_t carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 s = (u128)a.w[i] + b.w[i] + carry;
    out.w[i] = (uint64_t)s;
    carry = (uint64_t)(s >> 64);
  }
  return carry;
}

// Montgomery context for one odd 256-bit modulus (R = 2^256)
struct Mont {
  U256 mod;
  uint64_t n0;  // -mod^{-1} mod 2^64
  U256 R2;      // 2^512 mod mod

  void init(const U256& m) {
    mod = m;
    // Newton iteration for mod^{-1} mod 2^64, then negate
    uint64_t inv = m.w[0];
    for (int i = 0; i < 6; i++) inv *= 2 - m.w[0] * inv;
    n0 = (uint64_t)(0 - inv);
    // R2 = 2^512 mod m by 512 modular doublings of 1
    U256 x = {{1, 0, 0, 0}};
    for (int i = 0; i < 512; i++) {
      uint64_t carry = add(x, x, x);
      if (carry || cmp(x, mod) >= 0) sub(x, x, mod);
    }
    R2 = x;
  }

  // CIOS Montgomery multiplication: a·b·2^{-256} mod m.
  // Safe for any a, b < 2^256 (output < m + small overflow handled by
  // the final conditional subtract; garbage-in rows are masked by the
  // kernel's pre_ok anyway).
  U256 mul(const U256& a, const U256& b) const {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
      uint64_t carry = 0;
      for (int j = 0; j < 4; j++) {
        u128 s = (u128)t[j] + (u128)a.w[i] * b.w[j] + carry;
        t[j] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
      }
      u128 s = (u128)t[4] + carry;
      t[4] = (uint64_t)s;
      t[5] = (uint64_t)(s >> 64);

      uint64_t mfac = t[0] * n0;
      carry = 0;
      for (int j = 0; j < 4; j++) {
        u128 s2 = (u128)t[j] + (u128)mfac * mod.w[j] + carry;
        t[j] = (uint64_t)s2;
        carry = (uint64_t)(s2 >> 64);
      }
      s = (u128)t[4] + carry;
      t[4] = (uint64_t)s;
      t[5] += (uint64_t)(s >> 64);
      // shift right one limb
      t[0] = t[1]; t[1] = t[2]; t[2] = t[3]; t[3] = t[4]; t[4] = t[5];
      t[5] = 0;
    }
    U256 r = {{t[0], t[1], t[2], t[3]}};
    if (t[4] || cmp(r, mod) >= 0) sub(r, r, mod);
    return r;
  }

  U256 to_mont(const U256& a) const { return mul(a, R2); }

  // x^(mod-2) in Montgomery domain (Fermat inverse for prime modulus)
  U256 inv_mont(const U256& x) const {
    U256 e;
    sub(e, mod, U256{{2, 0, 0, 0}});
    U256 one_m = to_mont(U256{{1, 0, 0, 0}});
    U256 acc = one_m;
    for (int i = 255; i >= 0; i--) {
      acc = mul(acc, acc);
      if ((e.w[i / 64] >> (i % 64)) & 1) acc = mul(acc, x);
    }
    return acc;
  }
};

static U256 load_be(const uint8_t* p) {
  U256 v;
  for (int i = 0; i < 4; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | p[8 * i + j];
    v.w[3 - i] = w;
  }
  return v;
}

// 4-bit window digits, MSB-first (matches p256v3._windows)
static void windows_of(const U256& v, int32_t* out) {
  for (int i = 0; i < 32; i++) {
    int byte = 31 - i;  // big-endian byte order
    uint64_t b = (v.w[byte / 8] >> (8 * (byte % 8))) & 0xff;
    out[2 * i] = (int32_t)(b >> 4);
    out[2 * i + 1] = (int32_t)(b & 0xf);
  }
}

// int16 window digits for the packed launch frame (same layout)
static void windows16_of(const U256& v, int16_t* out) {
  for (int i = 0; i < 32; i++) {
    int byte = 31 - i;
    uint64_t b = (v.w[byte / 8] >> (8 * (byte % 8))) & 0xff;
    out[2 * i] = (int16_t)(b >> 4);
    out[2 * i + 1] = (int16_t)(b & 0xf);
  }
}

// 16 BIG-endian 16-bit limbs (matches p256v3._limbs16 /
// windows_to_limbs: limb j carries window digits 4j..4j+3 MSB-first)
static void limbs16_of(const U256& v, int16_t* out) {
  for (int i = 0; i < 16; i++) {
    int byte_hi = 31 - 2 * i;  // big-endian byte pair
    uint64_t hi = (v.w[byte_hi / 8] >> (8 * (byte_hi % 8))) & 0xff;
    uint64_t lo = (v.w[(byte_hi - 1) / 8] >> (8 * ((byte_hi - 1) % 8))) & 0xff;
    out[i] = (int16_t)((hi << 8) | lo);
  }
}

}  // namespace

extern "C" {

// Batch scalar preparation: e, r, s are [B, 32] big-endian byte rows.
// Outputs: w1/w2 [B, 64] int32 window digits of u1 = e·s⁻¹, u2 = r·s⁻¹
// (mod n); flags [B] uint8 with bit0 = admission ok
// (0 < r < n ∧ 0 < s ≤ n/2), bit1 = rpn_ok (r + n < p).
void ec_prepare(const uint8_t* e_b, const uint8_t* r_b, const uint8_t* s_b,
                int64_t B, int32_t* w1, int32_t* w2, uint8_t* flags) {
  if (B <= 0) return;
  // magic static: thread-safe one-time init (ctypes releases the GIL,
  // so concurrent first calls from prefetch threads are real)
  static const Mont M = [] { Mont m; m.init(ORDER_N); return m; }();

  U256 half_n;  // n >> 1  (n odd → floor(n/2))
  for (int i = 0; i < 4; i++)
    half_n.w[i] = (ORDER_N.w[i] >> 1) |
                  (i < 3 ? (ORDER_N.w[i + 1] << 63) : 0);
  U256 p_minus_n;
  sub(p_minus_n, PRIME_P, ORDER_N);

  U256* s_hat = new U256[B];   // ŝ = s·R (s forced to 1 when out of range)
  U256* pref = new U256[B + 1];
  U256 one_m = M.to_mont(U256{{1, 0, 0, 0}});

  for (int64_t i = 0; i < B; i++) {
    U256 r = load_be(r_b + 32 * i);
    U256 s = load_be(s_b + 32 * i);
    bool r_ok = !is_zero(r) && cmp(r, ORDER_N) < 0;
    bool s_ok = !is_zero(s) && cmp(s, half_n) <= 0;
    bool s_invertible = !is_zero(s) && cmp(s, ORDER_N) < 0;
    uint8_t f = (r_ok && s_ok) ? 1 : 0;
    if (cmp(r, p_minus_n) < 0) f |= 2;  // r + n < p
    flags[i] = f;
    s_hat[i] = M.to_mont(s_invertible ? s : U256{{1, 0, 0, 0}});
  }

  pref[0] = one_m;
  for (int64_t i = 0; i < B; i++) pref[i + 1] = M.mul(pref[i], s_hat[i]);
  U256 inv_all = M.inv_mont(pref[B]);
  for (int64_t i = B - 1; i >= 0; i--) {
    U256 sinv_m = M.mul(pref[i], inv_all);  // (s_i)⁻¹·R
    inv_all = M.mul(inv_all, s_hat[i]);
    U256 e = load_be(e_b + 32 * i);
    U256 r = load_be(r_b + 32 * i);
    // mont_mul(plain, x̂) = plain·x mod n — one step, no extra domain hop
    U256 u1 = M.mul(e, sinv_m);
    U256 u2 = M.mul(r, sinv_m);
    windows_of(u1, w1 + 64 * i);
    windows_of(u2, w2 + 64 * i);
  }
  delete[] s_hat;
  delete[] pref;
}

// Strided int16 variant for the single-pass packed staging path
// (ops/p256v3.prepare_cols_packed): the window planes land DIRECTLY in
// the caller's int16 launch frame — row i of w1/w2 is written at
// w1 + i*stride (stride in int16 ELEMENTS, i.e. the frame's full row
// width), so no intermediate int32 digit arrays and no second
// pack-copy exist at all.  ``limb_mode`` != 0 emits 16 big-endian
// 16-bit limbs per row (the recode-on-device wire form, identical to
// windows_to_limbs(host windows)); 0 emits the 64 int16 window
// digits.  Admission/rpn flags are byte-identical to ec_prepare.
void ec_prepare_pack(const uint8_t* e_b, const uint8_t* r_b,
                     const uint8_t* s_b, int64_t B, int16_t* w1,
                     int16_t* w2, int64_t stride, int32_t limb_mode,
                     uint8_t* flags) {
  if (B <= 0) return;
  static const Mont M = [] { Mont m; m.init(ORDER_N); return m; }();

  U256 half_n;
  for (int i = 0; i < 4; i++)
    half_n.w[i] = (ORDER_N.w[i] >> 1) |
                  (i < 3 ? (ORDER_N.w[i + 1] << 63) : 0);
  U256 p_minus_n;
  sub(p_minus_n, PRIME_P, ORDER_N);

  U256* s_hat = new U256[B];
  U256* pref = new U256[B + 1];
  U256 one_m = M.to_mont(U256{{1, 0, 0, 0}});

  for (int64_t i = 0; i < B; i++) {
    U256 r = load_be(r_b + 32 * i);
    U256 s = load_be(s_b + 32 * i);
    bool r_ok = !is_zero(r) && cmp(r, ORDER_N) < 0;
    bool s_ok = !is_zero(s) && cmp(s, half_n) <= 0;
    bool s_invertible = !is_zero(s) && cmp(s, ORDER_N) < 0;
    uint8_t f = (r_ok && s_ok) ? 1 : 0;
    if (cmp(r, p_minus_n) < 0) f |= 2;
    flags[i] = f;
    s_hat[i] = M.to_mont(s_invertible ? s : U256{{1, 0, 0, 0}});
  }

  pref[0] = one_m;
  for (int64_t i = 0; i < B; i++) pref[i + 1] = M.mul(pref[i], s_hat[i]);
  U256 inv_all = M.inv_mont(pref[B]);
  for (int64_t i = B - 1; i >= 0; i--) {
    U256 sinv_m = M.mul(pref[i], inv_all);
    inv_all = M.mul(inv_all, s_hat[i]);
    U256 e = load_be(e_b + 32 * i);
    U256 r = load_be(r_b + 32 * i);
    U256 u1 = M.mul(e, sinv_m);
    U256 u2 = M.mul(r, sinv_m);
    if (limb_mode) {
      limbs16_of(u1, w1 + stride * i);
      limbs16_of(u2, w2 + stride * i);
    } else {
      windows16_of(u1, w1 + stride * i);
      windows16_of(u2, w2 + stride * i);
    }
  }
  delete[] s_hat;
  delete[] pref;
}

}  // extern "C"

"""Python binding for the native block pre-parser.

``parse_envelopes(env_list)`` → ParsedBlock (numpy arrays over one
shared blob) or None when the native library is unavailable.  Spans
index into ``blob``; per-envelope ``ok`` distinguishes fast-path
endorser txs from envelopes the caller must re-parse in Python."""

from __future__ import annotations

import ctypes
from dataclasses import dataclass

import numpy as np

from fabric_tpu.native import blockparse_lib


@dataclass
class ParsedBlock:
    blob: bytes
    ok: np.ndarray            # [n] uint8
    ch_type: np.ndarray       # [n] int64
    txid_span: np.ndarray     # [n,2]
    channel_span: np.ndarray
    creator_span: np.ndarray
    nonce_span: np.ndarray
    results_span: np.ndarray
    events_span: np.ndarray
    payload_digest: np.ndarray   # [n,32]
    txid_digest: np.ndarray      # [n,32]
    creator_sig_ok: np.ndarray   # [n]
    creator_r: np.ndarray        # [n,32]
    creator_s: np.ndarray        # [n,32]
    endo_start: np.ndarray
    endo_count: np.ndarray
    e_endorser_span: np.ndarray  # [m,2]
    e_digest: np.ndarray         # [m,32]
    e_r: np.ndarray
    e_s: np.ndarray
    e_ok: np.ndarray
    # block-wide identity interning (creators + endorsers deduped)
    creator_uid: np.ndarray      # [n] int32; -1 = none
    e_uid: np.ndarray            # [m] int32
    e_dup: np.ndarray            # [m] uint8: repeat endorser in its tx
    ident_span: np.ndarray       # [n_ids, 2]
    n_ids: int

    def span(self, arr: np.ndarray, i: int) -> bytes | None:
        off, ln = int(arr[i, 0]), int(arr[i, 1])
        if off < 0:
            return None
        return self.blob[off:off + ln]


def parse_envelopes(envs: list[bytes]) -> ParsedBlock | None:
    lib = blockparse_lib()
    if lib is None or not envs:
        return None
    n = len(envs)
    blob = b"".join(envs)
    # offs/lens bookkeeping without a Python loop: lens via fromiter,
    # offs as the exclusive prefix sum (replay runs this per block
    # back-to-back, so the O(n) interpreter loop was measurable)
    lens = np.fromiter((len(e) for e in envs), np.int64, count=n)
    offs = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=offs[1:])

    cap = max(8, 8 * n)
    cap_ids = cap + n
    out = ParsedBlock(
        blob=blob,
        ok=np.zeros(n, np.uint8),
        ch_type=np.zeros(n, np.int64),
        txid_span=np.zeros((n, 2), np.int64),
        channel_span=np.zeros((n, 2), np.int64),
        creator_span=np.zeros((n, 2), np.int64),
        nonce_span=np.zeros((n, 2), np.int64),
        results_span=np.zeros((n, 2), np.int64),
        events_span=np.zeros((n, 2), np.int64),
        payload_digest=np.zeros((n, 32), np.uint8),
        txid_digest=np.zeros((n, 32), np.uint8),
        creator_sig_ok=np.zeros(n, np.uint8),
        creator_r=np.zeros((n, 32), np.uint8),
        creator_s=np.zeros((n, 32), np.uint8),
        endo_start=np.zeros(n, np.int64),
        endo_count=np.zeros(n, np.int64),
        e_endorser_span=np.zeros((cap, 2), np.int64),
        e_digest=np.zeros((cap, 32), np.uint8),
        e_r=np.zeros((cap, 32), np.uint8),
        e_s=np.zeros((cap, 32), np.uint8),
        e_ok=np.zeros(cap, np.uint8),
        creator_uid=np.full(n, -1, np.int32),
        e_uid=np.full(cap, -1, np.int32),
        e_dup=np.zeros(cap, np.uint8),
        ident_span=np.zeros((cap_ids, 2), np.int64),
        n_ids=0,
    )
    n_ids = np.zeros(1, np.int64)

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    ne = lib.parse_block(
        ctypes.c_char_p(blob), ptr(offs), ptr(lens),
        ctypes.c_int64(n), ctypes.c_int64(cap), ctypes.c_int64(cap_ids),
        ptr(out.ok), ptr(out.ch_type),
        ptr(out.txid_span), ptr(out.channel_span), ptr(out.creator_span),
        ptr(out.nonce_span), ptr(out.results_span), ptr(out.events_span),
        ptr(out.payload_digest), ptr(out.txid_digest),
        ptr(out.creator_sig_ok), ptr(out.creator_r), ptr(out.creator_s),
        ptr(out.endo_start), ptr(out.endo_count),
        ptr(out.e_endorser_span), ptr(out.e_digest), ptr(out.e_r),
        ptr(out.e_s), ptr(out.e_ok),
        ptr(out.creator_uid), ptr(out.e_uid), ptr(out.e_dup),
        ptr(out.ident_span), ptr(n_ids),
    )
    if ne < 0:
        return None  # a capacity was exceeded — python path
    out.n_ids = int(n_ids[0])
    return out

"""Python binding for the native rwset/MVCC preparation.

``prep(parsed_block, use)`` → MvccPrep (flat arrays over the shared
blob) or None when the native library is unavailable.  Per-tx
``status``: 0 = fast arrays valid, 1 = the tx needs the Python rwset
path, 2 = not used (use[i] was 0)."""

from __future__ import annotations

import ctypes
from dataclasses import dataclass

import numpy as np

from fabric_tpu.native import mvccprep_lib


@dataclass
class MvccPrep:
    blob: bytes
    status: np.ndarray        # [n] uint8
    tx_ns_start: np.ndarray   # [n]
    tx_ns_count: np.ndarray
    ns_ids_flat: np.ndarray   # [.] int32
    r_start: np.ndarray
    r_count: np.ndarray
    w_start: np.ndarray
    w_count: np.ndarray
    r_uid: np.ndarray         # [nr] int32
    r_has_ver: np.ndarray     # [nr] uint8
    r_ver: np.ndarray         # [nr, 2] uint64
    w_uid: np.ndarray
    w_is_del: np.ndarray
    w_key_span: np.ndarray    # [nw, 2]
    w_val_span: np.ndarray
    ns_of_ukey: np.ndarray    # [n_keys] int32
    ns_span: np.ndarray       # [n_ns, 2]
    ukey_span: np.ndarray     # [n_keys, 2]
    n_ns: int
    n_keys: int
    n_reads: int
    n_writes: int

    def ns_names(self) -> list:
        return [
            self.blob[self.ns_span[i, 0]:
                      self.ns_span[i, 0] + self.ns_span[i, 1]].decode()
            for i in range(self.n_ns)
        ]

    def ukey_strs(self) -> list:
        """[n_keys] decoded key strings (UTF-8 guaranteed by the
        native parser's validation)."""
        return [
            self.blob[self.ukey_span[i, 0]:
                      self.ukey_span[i, 0] + self.ukey_span[i, 1]].decode()
            for i in range(self.n_keys)
        ]


def prep(pb, use: np.ndarray) -> MvccPrep | None:
    lib = mvccprep_lib()
    if lib is None:
        return None
    n = len(use)
    total_len = int(pb.results_span[:, 1].clip(min=0).sum())
    cap = max(64, total_len // 4 + 8 * n)
    cap_ns = 1024
    use8 = np.ascontiguousarray(use.astype(np.uint8))
    rs = np.ascontiguousarray(pb.results_span)
    out = MvccPrep(
        blob=pb.blob,
        status=np.zeros(n, np.uint8),
        tx_ns_start=np.zeros(n, np.int64),
        tx_ns_count=np.zeros(n, np.int64),
        ns_ids_flat=np.zeros(cap, np.int32),
        r_start=np.zeros(n, np.int64), r_count=np.zeros(n, np.int64),
        w_start=np.zeros(n, np.int64), w_count=np.zeros(n, np.int64),
        r_uid=np.zeros(cap, np.int32),
        r_has_ver=np.zeros(cap, np.uint8),
        r_ver=np.zeros((cap, 2), np.uint64),
        w_uid=np.zeros(cap, np.int32),
        w_is_del=np.zeros(cap, np.uint8),
        w_key_span=np.zeros((cap, 2), np.int64),
        w_val_span=np.zeros((cap, 2), np.int64),
        ns_of_ukey=np.zeros(cap, np.int32),
        ns_span=np.zeros((cap_ns, 2), np.int64),
        ukey_span=np.zeros((cap, 2), np.int64),
        n_ns=0, n_keys=0, n_reads=0, n_writes=0,
    )
    counts = np.zeros(4, np.int64)

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    lib.mvcc_prep(
        ctypes.c_char_p(pb.blob), ptr(rs), ptr(use8),
        ctypes.c_int64(n), ctypes.c_int64(cap), ctypes.c_int64(cap_ns),
        ctypes.c_int64(cap),
        ptr(out.status), ptr(out.tx_ns_start), ptr(out.tx_ns_count),
        ptr(out.ns_ids_flat),
        ptr(out.r_start), ptr(out.r_count), ptr(out.w_start), ptr(out.w_count),
        ptr(out.r_uid), ptr(out.r_has_ver), ptr(out.r_ver),
        ptr(out.w_uid), ptr(out.w_is_del), ptr(out.w_key_span),
        ptr(out.w_val_span),
        ptr(out.ns_of_ukey), ptr(out.ns_span), ptr(out.ukey_span),
        ptr(counts),
    )
    out.n_ns, out.n_keys, out.n_reads, out.n_writes = (int(c) for c in counts)
    return out

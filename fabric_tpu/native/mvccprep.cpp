// Native rwset parse + key interning for the commit hot path.
//
// The Python path pays ~140 ms/block (1000 txs) parsing
// TxReadWriteSet protos into dicts (ledger/rwset.py) and re-flattening
// them into the MVCC kernel's arrays (ops/mvcc.prepare_block_static).
// This module walks the raw wire format (same stability argument as
// blockparse.cpp: the rwset encoding IS the compatibility contract —
// fabric_tpu/protos/rwset.proto, reference rwsetutil), interns
// (namespace, key) pairs into dense ids, dedups repeated keys with
// last-wins dict semantics, and emits flat arrays the Python side
// scatters into device arrays with pure numpy.
//
// Scope: the fast path covers public reads/writes (KVRWSet fields 1
// and 3).  Range queries, hashed private collections, or malformed
// bytes mark the tx python-needed (status 1) and the validator falls
// back to the exact Python path for the block — key-id ORDER is
// irrelevant here precisely because range intervals (the only
// order-sensitive consumer) force that fallback.  metadata_writes are
// skipped: neither MVCC nor the update batch consumes them (matching
// mvcc_form/_build_updates).
//
// Built on demand with g++ (see fabric_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>

namespace {

struct Span {
  const uint8_t* p = nullptr;
  size_t n = 0;
  bool ok = false;
};

static bool varint(const uint8_t*& p, const uint8_t* end, uint64_t& out) {
  out = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    out |= uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
  }
  return false;
}

// Walk one message's fields; calls visit(field, wire_type, span_or_value).
// Returns false on malformed wire data.
template <typename F>
static bool walk(const uint8_t* p, size_t n, F&& visit) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint64_t key;
    if (!varint(p, end, key)) return false;
    uint32_t f = uint32_t(key >> 3), wt = uint32_t(key & 7);
    if (f == 0) return false;  // upb rejects field number 0
    if (wt == 2) {
      uint64_t len;
      if (!varint(p, end, len) || len > uint64_t(end - p)) return false;
      if (!visit(f, 2, Span{p, size_t(len), true}, 0)) return false;
      p += len;
    } else if (wt == 0) {
      uint64_t v;
      if (!varint(p, end, v)) return false;
      if (!visit(f, 0, Span{}, v)) return false;
    } else if (wt == 5) {
      if (uint64_t(end - p) < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (uint64_t(end - p) < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

// Strict UTF-8 check (no overlongs, no surrogates, max U+10FFFF): the
// Python protobuf parser REJECTS invalid UTF-8 in string fields, so a
// key the fast path accepted but Python would refuse (BAD_RWSET) is a
// fast/slow verdict divergence — such txs must take the python path.
static bool utf8_valid(const uint8_t* p, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t c = p[i];
    if (c < 0x80) { i++; continue; }
    int extra;
    uint32_t cp;
    if ((c & 0xe0) == 0xc0) { extra = 1; cp = c & 0x1f; }
    else if ((c & 0xf0) == 0xe0) { extra = 2; cp = c & 0x0f; }
    else if ((c & 0xf8) == 0xf0) { extra = 3; cp = c & 0x07; }
    else return false;
    if (i + size_t(extra) >= n) return false;
    for (int k = 1; k <= extra; k++) {
      if ((p[i + k] & 0xc0) != 0x80) return false;
      cp = (cp << 6) | (p[i + k] & 0x3f);
    }
    if (extra == 1 && cp < 0x80) return false;
    if (extra == 2 && cp < 0x800) return false;
    if (extra == 3 && cp < 0x10000) return false;
    if (cp > 0x10ffff || (cp >= 0xd800 && cp <= 0xdfff)) return false;
    i += extra + 1;
  }
  return true;
}

struct Interner {
  std::unordered_map<std::string, int32_t> map;
  int32_t next = 0;
  // Returns the id, or -1 when interning a FRESH entry would exceed
  // cap — the map is left untouched so out_counts never exceeds the
  // caller-allocated table sizes (the tx falls back to Python).
  int32_t get(int32_t ns_id, const uint8_t* key, size_t klen,
              bool& fresh, int64_t cap) {
    std::string k;
    k.reserve(4 + klen);
    k.append(reinterpret_cast<const char*>(&ns_id), 4);
    k.append(reinterpret_cast<const char*>(key), klen);
    auto it = map.find(k);
    if (it != map.end()) { fresh = false; return it->second; }
    if (next >= cap) { fresh = false; return -1; }
    fresh = true;
    map.emplace(std::move(k), next);
    return next++;
  }
};

}  // namespace

extern "C" {

// See file comment.  Outputs are caller-allocated; out_counts returns
// [n_ns, n_ukeys, n_reads, n_writes].  Always returns 0: a tx whose
// data exceeds a cap is marked python-needed (status 1), never lost.
int64_t mvcc_prep(
    const uint8_t* blob, const int64_t* results_span, const uint8_t* use,
    int64_t n, int64_t cap_entries, int64_t cap_ns, int64_t cap_keys,
    uint8_t* status,                       // [n] 0 fast / 1 python / 2 unused
    int64_t* tx_ns_start, int64_t* tx_ns_count,
    int32_t* ns_ids_flat,                  // [cap_entries]
    int64_t* r_start, int64_t* r_count,
    int64_t* w_start, int64_t* w_count,
    int32_t* r_uid, uint8_t* r_has_ver, uint64_t* r_ver,   // [cap],[cap],[cap,2]
    int32_t* w_uid, uint8_t* w_is_del,
    int64_t* w_key_span, int64_t* w_val_span,              // [cap,2] each
    int32_t* ns_of_ukey,                   // [cap_keys]
    int64_t* ns_span,                      // [cap_ns,2]
    int64_t* ukey_span,                    // [cap_keys,2]
    int64_t* out_counts) {
  Interner ns_intern, key_intern;
  int64_t nr = 0, nw = 0, nns_flat = 0;

  for (int64_t i = 0; i < n; i++) {
    status[i] = 2;
    tx_ns_start[i] = nns_flat; tx_ns_count[i] = 0;
    r_start[i] = nr; r_count[i] = 0;
    w_start[i] = nw; w_count[i] = 0;
    if (!use[i]) continue;
    int64_t off = results_span[2 * i], len = results_span[2 * i + 1];
    if (off < 0) continue;
    const uint8_t* rw = blob + off;

    bool bad = false;
    int64_t tx_r0 = nr, tx_w0 = nw, tx_ns0 = nns_flat;

    // TxReadWriteSet: field 2 = repeated NsReadWriteSet
    bool ok = walk(rw, size_t(len), [&](uint32_t f, int wt, Span s,
                                        uint64_t) -> bool {
      if (f != 2 || wt != 2) return true;  // data_model etc: skip
      int32_t ns_id = -1;
      Span ns_name{}, kvset{};
      bool ok2 = walk(s.p, s.n, [&](uint32_t f2, int wt2, Span s2,
                                    uint64_t) -> bool {
        if (f2 == 1 && wt2 == 2) ns_name = s2;
        else if (f2 == 2 && wt2 == 2) kvset = s2;
        else if (f2 == 3) bad = true;  // hashed collections → python
        return true;
      });
      if (!ok2 || bad || !ns_name.ok ||
          !utf8_valid(ns_name.p, ns_name.n)) { bad = true; return true; }
      bool fresh;
      ns_id = ns_intern.get(0, ns_name.p, ns_name.n, fresh, cap_ns);
      if (ns_id < 0) { bad = true; return true; }
      if (fresh) {
        ns_span[2 * ns_id] = ns_name.p - blob;
        ns_span[2 * ns_id + 1] = int64_t(ns_name.n);
      }
      // per-tx ns dedup (same ns may repeat; Python merges)
      bool seen_ns = false;
      for (int64_t k = tx_ns0; k < nns_flat; k++)
        if (ns_ids_flat[k] == ns_id) { seen_ns = true; break; }
      if (!seen_ns) {
        if (nns_flat >= cap_entries) { bad = true; return true; }
        ns_ids_flat[nns_flat++] = ns_id;
      }
      if (!kvset.ok) return true;  // empty KVRWSet

      // KVRWSet: 1 reads, 2 range (→python), 3 writes, 4 metadata (skip)
      bool ok3 = walk(kvset.p, kvset.n, [&](uint32_t f3, int wt3, Span s3,
                                            uint64_t) -> bool {
        // range queries (2) and metadata writes (4) → python path
        // (ranges are order-sensitive; metadata strings need the
        // Python parser's full checks)
        if (f3 == 2 || f3 == 4) { bad = true; return true; }
        if (wt3 != 2) return true;
        if (f3 == 1) {  // KVRead{1 key, 2 Version{1 block, 2 tx}}
          Span key{}, ver{};
          bool has_ver = false;
          if (!walk(s3.p, s3.n, [&](uint32_t f4, int wt4, Span s4,
                                    uint64_t) -> bool {
                if (f4 == 1 && wt4 == 2) key = s4;
                if (f4 == 2 && wt4 == 2) { ver = s4; has_ver = true; }
                return true;
              })) { bad = true; return true; }
          uint64_t vb = 0, vt = 0;
          if (has_ver &&
              !walk(ver.p, ver.n, [&](uint32_t f5, int wt5, Span,
                                      uint64_t v) -> bool {
                if (wt5 == 0 && f5 == 1) vb = v;
                if (wt5 == 0 && f5 == 2) vt = v;
                return true;
              })) { bad = true; return true; }
          if (key.ok && !utf8_valid(key.p, key.n)) { bad = true; return true; }
          bool fresh2;
          int32_t uid = key_intern.get(ns_id, key.ok ? key.p : blob,
                                       key.ok ? key.n : 0, fresh2, cap_keys);
          if (uid < 0) { bad = true; return true; }
          if (fresh2) {
            ns_of_ukey[uid] = ns_id;
            ukey_span[2 * uid] = key.ok ? (key.p - blob) : 0;
            ukey_span[2 * uid + 1] = key.ok ? int64_t(key.n) : 0;
          }
          // dict semantics: repeated read of a key — last wins
          for (int64_t k = tx_r0; k < nr; k++)
            if (r_uid[k] == uid) {
              r_has_ver[k] = has_ver ? 1 : 0;
              r_ver[2 * k] = vb; r_ver[2 * k + 1] = vt;
              return true;
            }
          if (nr >= cap_entries) { bad = true; return true; }
          r_uid[nr] = uid;
          r_has_ver[nr] = has_ver ? 1 : 0;
          r_ver[2 * nr] = vb; r_ver[2 * nr + 1] = vt;
          nr++;
        } else if (f3 == 3) {  // KVWrite{1 key, 2 is_delete, 3 value}
          Span key{}, val{};
          uint64_t is_del = 0;
          if (!walk(s3.p, s3.n, [&](uint32_t f4, int wt4, Span s4,
                                    uint64_t v) -> bool {
                if (f4 == 1 && wt4 == 2) key = s4;
                if (f4 == 2 && wt4 == 0) is_del = v;
                if (f4 == 3 && wt4 == 2) val = s4;
                return true;
              })) { bad = true; return true; }
          if (key.ok && !utf8_valid(key.p, key.n)) { bad = true; return true; }
          bool fresh2;
          int32_t uid = key_intern.get(ns_id, key.ok ? key.p : blob,
                                       key.ok ? key.n : 0, fresh2, cap_keys);
          if (uid < 0) { bad = true; return true; }
          if (fresh2) {
            ns_of_ukey[uid] = ns_id;
            ukey_span[2 * uid] = key.ok ? (key.p - blob) : 0;
            ukey_span[2 * uid + 1] = key.ok ? int64_t(key.n) : 0;
          }
          for (int64_t k = tx_w0; k < nw; k++)
            if (w_uid[k] == uid) {  // last write wins
              w_is_del[k] = is_del ? 1 : 0;
              w_val_span[2 * k] = val.ok ? (val.p - blob) : -1;
              w_val_span[2 * k + 1] = val.ok ? int64_t(val.n) : 0;
              return true;
            }
          if (nw >= cap_entries) { bad = true; return true; }
          w_uid[nw] = uid;
          w_is_del[nw] = is_del ? 1 : 0;
          w_key_span[2 * nw] = key.ok ? (key.p - blob) : 0;
          w_key_span[2 * nw + 1] = key.ok ? int64_t(key.n) : 0;
          w_val_span[2 * nw] = val.ok ? (val.p - blob) : -1;
          w_val_span[2 * nw + 1] = val.ok ? int64_t(val.n) : 0;
          nw++;
        }
        return true;
      });
      if (!ok3) bad = true;
      return true;
    });

    if (!ok || bad) {
      // rewind this tx's contributions; python path re-parses it
      nr = tx_r0; nw = tx_w0; nns_flat = tx_ns0;
      status[i] = 1;
      tx_ns_count[i] = 0; r_count[i] = 0; w_count[i] = 0;
      continue;
    }
    status[i] = 0;
    tx_ns_count[i] = nns_flat - tx_ns0;
    r_count[i] = nr - tx_r0;
    w_count[i] = nw - tx_w0;
  }
  out_counts[0] = ns_intern.next;
  out_counts[1] = key_intern.next;
  out_counts[2] = nr;
  out_counts[3] = nw;
  return 0;
}

}  // extern "C"

"""Overlap-coverage analyzer: is ``device_wait`` actually hidden?

The depth-N commit pipeline's whole premise is that block k's device
time is covered by HOST work of its neighbors — prefetch(k+1) parsing,
commit(k−1) fsyncing, launch(k+1) staging.  The ROADMAP acceptance for
deep pipelining ("a trace where device_wait(k) is fully covered by
host stages of k±2") was a manual Perfetto read; this module turns it
into a tracked number:

    coverage(k) = |device_wait(k) ∩ ⋃ host-spans(j), 0 < |j−k| ≤ w|
                  ─────────────────────────────────────────────────
                                |device_wait(k)|

computed from finished span trees (fabric_tpu.observe.tracer).  A
span counts as *host work* unless it is a pure wait or a container
that includes device time — the exclusion set below — so fsync(k−1)
on the committer thread and parse(k+1) on the prefetch thread both
count, while commit_wait / prefetch_wait (blocking) and finish (which
contains the device sync itself) do not.  Intervals are unioned, so
nested spans never double-count.

Three input forms, matching the tracer's three export surfaces:

* live :class:`~fabric_tpu.observe.tracer.Span` roots
  (``Tracer.recent_roots()``) — absolute ``perf_counter`` seconds;
* ``/trace`` JSON trees — per-block-relative ``start_ms`` anchored by
  the ``t0_s`` field ``Tracer.blocks()`` emits;
* Chrome trace-event lists (``Tracer.export_chrome`` output) —
  absolute microsecond timestamps with the block number in ``args``.

Surfaced at ``/trace`` (``pipeline_overlap_coverage`` in the index
payload), in ``scripts/traceview.py --coverage``, and as the
``pipeline_overlap_coverage`` bench extra.
"""

from __future__ import annotations

#: span names that are NOT host work: the root container, pure
#: blocking waits, the device sync itself, and the finish container
#: (it nests device_wait).  Everything else — prefetch, launch,
#: commit, ledger_commit, fsync, the validator's stage spans, pool
#: worker tasks, verify_chunk staging — counts toward coverage.
NON_HOST = {
    "block", "finish", "device_wait", "commit_wait", "prefetch_wait",
    "queue_wait",
}

#: default neighbor window (blocks either side): ±2 matches depth-3
#: pipelining (k−2 fsyncing, k−1 committing, k+1 prefetching, k+2
#: staged); pass ``window=depth−1`` to match a configured depth.
DEFAULT_WINDOW = 2


def spans_from_root(root):
    """One finished Span tree → ``(block, name, t0, t1)`` rows in
    absolute seconds (the live-tracer input form)."""
    block = root.attrs.get("block")
    out = []

    def walk(sp):
        if sp.t1 is not None:
            out.append((block, sp.name, sp.t0, sp.t1))
        for c in sp.children:
            walk(c)

    walk(root)
    return out


def spans_from_tree_dict(d: dict):
    """One ``/trace`` block tree (``Tracer.blocks()`` output) →
    ``(block, name, t0, t1)`` rows, or None when the dump predates the
    ``t0_s`` anchor (per-block-relative times cannot be compared
    across blocks without it)."""
    base = d.get("t0_s")
    if base is None:
        return None
    block = d.get("block")
    out = []

    def walk(sp):
        t0 = base + float(sp.get("start_ms", 0.0)) / 1000.0
        out.append((block, sp.get("name", "?"), t0,
                    t0 + float(sp.get("dur_ms", 0.0)) / 1000.0))
        for c in sp.get("children", ()):
            walk(c)

    walk(d)
    return out


def spans_from_chrome(events) -> list:
    """Chrome trace-event list → ``(block, name, t0, t1)`` rows
    (absolute seconds; only complete "X" events carry duration)."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        block = (e.get("args") or {}).get("block")
        t0 = float(e.get("ts", 0.0)) / 1e6
        out.append((block, e.get("name", "?"), t0,
                    t0 + float(e.get("dur", 0.0)) / 1e6))
    return out


def _union(ivals: list) -> list:
    """Sorted disjoint union of [t0, t1) intervals."""
    ivals = sorted(i for i in ivals if i[1] > i[0])
    out: list = []
    for t0, t1 in ivals:
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _overlap_len(a: list, b: list) -> float:
    """Total length of the intersection of two DISJOINT-sorted
    interval lists (linear sweep)."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def coverage_from_spans(rows, window: int = DEFAULT_WINDOW) -> dict:
    """``(block, name, t0, t1)`` rows → the coverage report.

    Returns ``{"window", "blocks_measured", "mean", "p50", "min",
    "per_block": [{"block", "device_wait_ms", "covered_ms",
    "coverage"}, ...]}`` — ``blocks_measured`` counts blocks that have
    any ``device_wait`` at all AND at least one in-window neighbor on
    either side (edge blocks of a short trace have nothing to hide
    behind and would read as spurious misses)."""
    dev: dict = {}    # block → [intervals]
    host: dict = {}   # block → [intervals]
    for block, name, t0, t1 in rows:
        if block is None or t1 <= t0:
            continue
        if name == "device_wait":
            dev.setdefault(block, []).append((t0, t1))
        elif name not in NON_HOST:
            host.setdefault(block, []).append((t0, t1))
    known = sorted(set(dev) | set(host))
    per_block = []
    for k in sorted(dev):
        neighbors = [j for j in known
                     if j != k and abs(j - k) <= window]
        if not neighbors:
            continue  # nothing in the window to hide behind
        dk = _union(dev[k])
        cover = _union([iv for j in neighbors
                        for iv in host.get(j, ())])
        total = sum(t1 - t0 for t0, t1 in dk)
        covered = _overlap_len(dk, cover)
        per_block.append({
            "block": k,
            "device_wait_ms": round(total * 1000.0, 3),
            "covered_ms": round(covered * 1000.0, 3),
            "coverage": round(covered / total, 4) if total > 0 else 1.0,
        })
    fracs = sorted(b["coverage"] for b in per_block)
    n = len(fracs)
    return {
        "window": int(window),
        "blocks_measured": n,
        "mean": round(sum(fracs) / n, 4) if n else None,
        "p50": fracs[n // 2] if n else None,
        "min": fracs[0] if n else None,
        "per_block": per_block,
    }


def coverage_from_roots(roots, window: int = DEFAULT_WINDOW) -> dict:
    """Live Span roots (``Tracer.recent_roots()``) → coverage report."""
    rows: list = []
    for r in roots:
        rows.extend(spans_from_root(r))
    return coverage_from_spans(rows, window=window)


def coverage_from_trace_dump(data, window: int = DEFAULT_WINDOW):
    """A ``/trace`` index payload (or list of block trees) → coverage
    report, or None when the dump carries no ``t0_s`` anchors."""
    if isinstance(data, dict):
        trees = {b.get("block"): b for b in data.get("recent_blocks", ())}
        for b in data.get("slow_blocks", ()):
            trees.setdefault(b.get("block"), b)
        trees = list(trees.values())
    else:
        trees = list(data)
    rows: list = []
    anchored = False
    for t in trees:
        got = spans_from_tree_dict(t)
        if got is not None:
            anchored = True
            rows.extend(got)
    if not anchored:
        return None
    return coverage_from_spans(rows, window=window)

"""fabric_tpu.observe — block-commit span tracing (tracer.py) and the
latency/error SLO burn-rate engine (slo.py)."""

from fabric_tpu.observe.tracer import (  # noqa: F401
    DEFAULT_RING_BLOCKS,
    DEFAULT_SLOW_FACTOR,
    Span,
    Tracer,
    configure,
    device_annotation,
    format_block,
    global_tracer,
    span_from_dict,
)

"""fabric_tpu.observe — block-commit span tracing (tracer.py), the
latency/error SLO burn-rate engine (slo.py), the pipeline
overlap-coverage analyzer (overlap.py), the flight-data recorder:
metrics time-series trails (timeseries.py) + black-box incident
bundles (blackbox.py), served at ``/vitals`` — and the per-launch
device-time ledger (ledger.py) decomposing device_wait into
compile / queue / execute / transfer, served at ``/launches`` — and
the per-transaction flow journal (txflow.py) attributing each tx's
end-to-end latency across endorse / submit / order / durable / apply
milestones on one monotonic clock, served at ``/txflow``."""

from fabric_tpu.observe.overlap import (  # noqa: F401
    coverage_from_roots,
    coverage_from_spans,
    coverage_from_trace_dump,
)
from fabric_tpu.observe.tracer import (  # noqa: F401
    DEFAULT_RING_BLOCKS,
    DEFAULT_SLOW_FACTOR,
    Span,
    Tracer,
    configure,
    device_annotation,
    format_block,
    global_tracer,
    span_from_dict,
)

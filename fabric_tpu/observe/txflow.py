"""Per-transaction flow journal: end-to-end latency attribution from
endorse to state-apply, on one monotonic clock.

Every other observability surface in the repo is block- or
device-centric: the tracer records block waterfalls, the launch ledger
decomposes device_wait, the SLO engine burns against per-block commit
latency.  But a *user's* unit of latency is one transaction —
endorse → sign flush → submit → order → validate → durable append →
state visibility — and since the decoupled committer
(ledger/committer.py) split durable append from state apply, nothing
could answer "when did my tx become readable?".  This journal closes
that gap: each layer stamps named milestones against the journal's
single monotonic clock, keyed by tx_id.

Milestones (each stamped at most once; the FIRST stamp wins):

===============  ============================================which layer
``endorse_begin``  gateway Endorse entered (proposal parsed)
``endorse_end``    endorsement collected / failed
``submit``         gateway Submit entered (envelope received)
``broadcast``      orderer broadcast acknowledged
``included``       block carrying the tx reached ``CommitPipeline``
                   commit, with its validation verdict
``durable``        the block's append survived the fsync fence
                   (``blocks.sync`` / the applier's ``ensure_synced``)
``applied``        state apply (+ history) for the block completed —
                   the tx's writes are READABLE
===============  ============================================

Stage decomposition telescopes over the milestones that actually
landed, so the identity ``sum(stages) == e2e`` holds EXACTLY (one
clock, adjacent differences) for full and partial records alike:
``endorse`` = endorse_begin→endorse_end, ``submit`` =
endorse_end→broadcast (client think time + broadcast wall), ``order``
= broadcast→included, ``durable`` = included→durable, ``apply`` =
durable→applied.  A missing milestone merges its interval into the
next present stage — never fabricated.  ``visibility_lag`` =
applied − durable is the async committer's read-your-writes window,
recorded only when BOTH fences were observed.

Orderer-side txs never seen endorse-side (deliver-only peers, bench
streams, replay) enter at ``included`` and complete as PARTIAL
records — but they pay NO per-tx bookkeeping: ``included`` /
``durable`` / ``applied`` are per-BLOCK events, so a block's partial
flows share its timestamps by construction and ride one per-block
COHORT (a single ring record expanded to per-tx rows at read time,
O(1) batched instrument updates per block).  Only gateway-origin
flows, whose endorse/submit stamps genuinely differ per tx, live in
the bounded in-flight LRU.  Replayed blocks (peer/replay.py) record
inclusion→apply only and are tagged ``origin="replay"`` — a replay
must never fake endorse stages, even when a colliding tx_id is in
flight.

The sign lane's coalescing wait rides the existing
``SignBatcher.observer`` hook (:func:`sign_observer`).  The observer
carries no tx_id — the wait is INSIDE the endorse stage — so it feeds
the ``sign_wait`` stage histogram without attaching to a flow.

Three surfaces, all derived from this one journal (no second
bookkeeping path): registry histograms ``tx_flow_stage_seconds{stage}``
/ ``tx_flow_e2e_seconds{outcome}`` / ``tx_flow_visibility_lag_seconds``
with trace exemplars (/vitals trails ride free), the ``/txflow`` ops
endpoint (opsserver.py), and a per-completed-flow commit SLO feed
(``slo.DEFAULT_COMMIT_SLOS`` on the ``commit`` channel via
``slo_feed``).

Default ON in production (nodeconfig ``tx_flow``) but structurally
zero-cost when disarmed: every hook is one module-global read + None
check — no thread, no registry instruments, no state.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import Counter, OrderedDict, deque
from operator import itemgetter

_log = logging.getLogger("fabric_tpu.observe.txflow")

#: completed flows retained for /txflow and the bench extras
DEFAULT_RING = 256

#: bounded LRU of in-flight (not yet applied) flows — an abandoned
#: flow (endorse that never ordered, an orphaned submit) is evicted
#: oldest-first rather than leaking
DEFAULT_INFLIGHT = 4096

#: blocks whose included-but-not-yet-applied txid sets are held for
#: the durable/apply fence stamps (the apply queue is ~4 deep)
DEFAULT_BLOCKS = 128

#: trace exemplars armed per histogram label variant
DEFAULT_EXEMPLARS = 8

_HIST_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 2.5, 10.0, 30.0, float("inf"))

#: milestone order; stage names are keyed by the milestone that ENDS
#: them (telescoping adjacent differences — see module docstring)
MILESTONES = ("endorse_begin", "endorse_end", "submit", "broadcast",
              "included", "durable", "applied")
_STAGE_END = {
    "endorse_end": "endorse",
    "broadcast": "submit",
    "included": "order",
    "durable": "durable",
    "applied": "apply",
}
STAGES = ("endorse", "submit", "order", "durable", "apply")


_code_names: dict[int, str] = {0: "VALID"}
#: precomputed instrument label keys (ops_metrics ``_label_key``
#: form) — the cohort publish batches its updates through the locked
#: fast path, which takes the key rather than kwargs
_STAGE_KEYS = {s: (("stage", s),) for s in
               ("endorse", "submit", "order", "durable", "apply")}
_outcome_keys: dict[int, tuple] = {}


def _code_name(code: int) -> str:
    """Verdict label for the e2e histogram / rows: the proto enum name
    when resolvable, else ``code<N>`` (contained — attribution must
    not die of a label; memoized — cohort expansion resolves per tx)."""
    code = int(code)
    name = _code_names.get(code)
    if name is None:
        try:
            from fabric_tpu.protos import transaction_pb2

            name = transaction_pb2.TxValidationCode.Name(code)
        except Exception:
            name = f"code{code}"
        _code_names[code] = name
    return name


def _outcome_key(code: int) -> tuple:
    k = _outcome_keys.get(code)
    if k is None:
        k = _outcome_keys[code] = (("outcome", _code_name(code)),)
    return k


class FlowJournal:
    """See module docstring.  One process-global instance in
    production (:func:`global_journal`); tests construct their own
    with an injected clock and a private registry."""

    def __init__(self, registry=None, tracer=None,
                 clock=time.perf_counter, ring: int = DEFAULT_RING,
                 inflight: int = DEFAULT_INFLIGHT,
                 blocks: int = DEFAULT_BLOCKS,
                 exemplars: int = DEFAULT_EXEMPLARS):
        self.clock = clock
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self.registry = registry
        if tracer is None:
            from fabric_tpu.observe.tracer import global_tracer

            tracer = global_tracer()
        self.tracer = tracer
        self._lock = threading.Lock()
        self._inflight_max = max(1, int(inflight))
        self._blocks_max = max(1, int(blocks))
        #: tx_id → flow entry {"t": {milestone: ts}, ...} (LRU order).
        #: GATEWAY-origin flows only — commit-side txs never open
        #: per-tx entries (they ride block cohorts, below), so a
        #: commit-heavy peer's armed cost stays O(1) per block
        self._inflight: OrderedDict[str, dict] = OrderedDict()
        #: block num → cohort awaiting the durable/apply fences:
        #: {"num", "channel", "origin", "t_inc", "t_dur", "known":
        #: [tx_id with a live gateway entry], "partial": [(tx_id,
        #: code) first seen at inclusion — they share the block's
        #: included/durable/applied timestamps by construction]}
        self._blocks: OrderedDict[int, dict] = OrderedDict()
        self._done: deque = deque(maxlen=max(1, int(ring)))
        #: recent sign-lane waits (ms) — histogram-only feed (no
        #: tx_id on the flusher thread), summarized in stats()
        self._sign_waits: deque = deque(maxlen=max(1, int(ring)))
        self._evicted = 0
        #: per-completed-flow SLO feed — ``feed(e2e_s, valid)``; set
        #: by the arming layer (peer/node.py wires
        #: ``slo.commit_feed``), called outside the journal lock
        self.slo_feed = None
        kw = dict(buckets=_HIST_BUCKETS, exemplars=int(exemplars))
        self._stage_h = registry.histogram(
            "tx_flow_stage_seconds",
            "per-tx flow stage durations (s) by stage, telescoped "
            "over the journal's monotonic milestones",
            **kw,
        )
        self._e2e_h = registry.histogram(
            "tx_flow_e2e_seconds",
            "per-tx end-to-end wall (s; first milestone → applied) "
            "by validation outcome",
            **kw,
        )
        self._lag_h = registry.histogram(
            "tx_flow_visibility_lag_seconds",
            "apply-visible minus durable-append per tx (s) — the "
            "async committer's read-your-writes window",
            **kw,
        )
        self._flows_ctr = registry.counter(
            "tx_flow_flows_total",
            "completed tx flows by origin (gateway/commit/replay)",
        )
        self._evicted_ctr = registry.counter(
            "tx_flow_evicted_total",
            "abandoned in-flight flows evicted by the LRU bound",
        )
        #: every instrument of one registry shares its lock — the
        #: cohort publish batches all its updates under ONE
        #: acquisition of it (observe_repeat_locked / add_locked)
        self._metrics_lock = self._stage_h._lock

    # -- entry management (callers hold self._lock) -------------------------

    def _entry(self, tx_id: str, origin: str) -> dict:
        ent = self._inflight.get(tx_id)
        if ent is None:
            ent = {"tx_id": tx_id, "t": {}, "origin": origin}
            self._inflight[tx_id] = ent
            while len(self._inflight) > self._inflight_max:
                self._inflight.popitem(last=False)
                self._evicted += 1
                self._evicted_ctr.add(1)
        else:
            self._inflight.move_to_end(tx_id)
        return ent

    @staticmethod
    def _stamp(ent: dict, milestone: str, t: float) -> None:
        ent["t"].setdefault(milestone, t)

    # -- milestone hooks ----------------------------------------------------

    def endorse_begin(self, tx_id: str) -> None:
        t = self.clock()
        with self._lock:
            self._stamp(self._entry(tx_id, "gateway"), "endorse_begin", t)

    def endorse_end(self, tx_id: str, ok: bool = True) -> None:
        t = self.clock()
        done = None
        with self._lock:
            ent = self._entry(tx_id, "gateway")
            self._stamp(ent, "endorse_end", t)
            if not ok:
                # a failed endorsement is the flow's terminal event —
                # complete now (outcome endorse_error) instead of
                # waiting for an inclusion that can never come
                self._inflight.pop(tx_id, None)
                done = self._complete_locked(ent, t,
                                             outcome="ENDORSE_ERROR")
        if done is not None:
            self._publish(*done, valid=False)

    def submit_begin(self, tx_id: str) -> None:
        t = self.clock()
        with self._lock:
            self._stamp(self._entry(tx_id, "gateway"), "submit", t)

    def broadcast_done(self, tx_id: str) -> None:
        t = self.clock()
        with self._lock:
            self._stamp(self._entry(tx_id, "gateway"), "broadcast", t)

    def sign_event(self, wait_ms, busy: bool) -> None:
        """One sign-lane request event (``SignBatcher.observer``
        contract): flushed requests carry their coalescing-window
        wait; BUSY bounces carry None and are not a latency sample.
        No tx attribution — the wait is inside the endorse stage."""
        if busy or wait_ms is None:
            return
        self._sign_waits.append(round(float(wait_ms), 4))
        self._stage_h.observe(float(wait_ms) / 1000.0, stage="sign_wait")

    def block_included(self, num: int, txs, channel: str = "",
                       replay: bool = False) -> None:
        """One block reached commit: stamp inclusion + verdict for
        every ``(tx_id, code)`` in ``txs``.  The journal takes
        OWNERSHIP of ``txs`` (callers build it fresh per block, as
        the pipeline hook does) and empty tx_ids must already be
        filtered out.  Unknown tx_ids open PARTIAL records; under
        ``replay`` every record is opened fresh — replayed blocks
        must never inherit (or fake) endorse stamps from a colliding
        live flow."""
        t = self.clock()
        num_i = int(num)
        with self._lock:
            known: list = []
            if replay or not self._inflight:
                # the commit-heavy fast path: no gateway flow can
                # match (replay must not match even if one could), so
                # every tx shares the block's timestamps — no per-tx
                # walk, no per-tx entries, no LRU traffic
                partial = txs
            else:
                partial = []
                inflight = self._inflight
                for tp in txs:
                    tx_id = tp[0]
                    if not tx_id:
                        continue
                    ent = inflight.get(tx_id)
                    if ent is None:
                        partial.append(tp)
                        continue
                    tt = ent["t"]
                    if "included" not in tt:
                        tt["included"] = t
                    ent["block"] = num_i
                    ent["code"] = int(tp[1])
                    if channel:
                        ent["channel"] = channel
                    known.append(tx_id)
            if known or partial:
                self._blocks[num_i] = {
                    "num": num_i, "channel": channel,
                    "origin": "replay" if replay else "commit",
                    "t_inc": t, "t_dur": None,
                    "known": known, "partial": partial,
                }
                while len(self._blocks) > self._blocks_max:
                    self._blocks.popitem(last=False)

    def block_durable(self, num: int) -> None:
        """The block's append crossed the fsync fence (serial
        ``blocks.sync`` or the applier's ``ensure_synced``) —
        idempotent, first fence wins."""
        t = self.clock()
        with self._lock:
            c = self._blocks.get(int(num))
            if c is None:
                return
            if c["t_dur"] is None:
                c["t_dur"] = t
            for tx_id in c["known"]:
                ent = self._inflight.get(tx_id)
                if ent is not None:
                    self._stamp(ent, "durable", t)

    def block_applied(self, num: int) -> None:
        """State apply (+ history) for the block completed: every
        included tx of the block becomes READABLE — complete its
        flows, record histograms, feed the commit SLOs.  Gateway
        flows complete per tx (their endorse/submit stamps differ);
        the partial cohort completes as ONE ring record + O(1)
        batched instrument updates — every member shares the block's
        included/durable/applied interval by construction."""
        t = self.clock()
        completed = []
        crow_pub = None
        with self._lock:
            c = self._blocks.pop(int(num), None)
            if c is None:
                return
            for tx_id in c["known"]:
                ent = self._inflight.pop(tx_id, None)
                if ent is None:
                    continue
                self._stamp(ent, "applied", t)
                completed.append(self._complete_locked(ent, t))
            if c["partial"]:
                crow_pub = self._complete_cohort_locked(c, t)
        for row, pub in completed:
            self._publish(row, pub, valid=row["code"] == 0)
        if crow_pub is not None:
            self._publish_cohort(*crow_pub)

    # -- completion ---------------------------------------------------------

    def _complete_locked(self, ent: dict, t_end: float,
                         outcome: str | None = None):
        """Telescope the present milestones into stages (identity:
        stages sum EXACTLY to e2e — one clock, adjacent differences)
        and append the completed row.  Caller holds the lock.
        Returns ``(row, pub)`` — ``pub`` carries the raw-seconds
        values for :meth:`_publish`, kept OFF the ring row so a
        publish never mutates a dict a reader may be copying."""
        ts = ent["t"]
        present = [(m, ts[m]) for m in MILESTONES if m in ts]
        t0 = present[0][1]
        stages = {}
        prev = t0
        for m, t in present[1:]:
            stage = _STAGE_END.get(m)
            if stage is not None:
                stages[stage] = max(0.0, t - prev)
                prev = t
        e2e = max(0.0, t_end - t0)
        code = int(ent.get("code", -1))
        lag = None
        if "durable" in ts and "applied" in ts:
            lag = max(0.0, ts["applied"] - ts["durable"])
        row = {
            "t_s": round(self.clock(), 6),
            "tx_id": ent["tx_id"],
            "origin": ent.get("origin", "commit"),
            "outcome": outcome if outcome is not None else _code_name(code),
            "code": code,
            "block": ent.get("block"),
            "channel": ent.get("channel", ""),
            "e2e_ms": round(e2e * 1000.0, 4),
            "stages_ms": {k: round(v * 1000.0, 4)
                          for k, v in stages.items()},
            "visibility_lag_ms": (None if lag is None
                                  else round(lag * 1000.0, 4)),
            "milestones": {m: round(t - t0, 6) for m, t in present},
            "partial": "endorse_begin" not in ts,
        }
        self._done.append(row)
        return row, (stages, e2e, lag)

    def _complete_cohort_locked(self, c: dict, t_app: float):
        """One completed-COHORT ring record for a block's partial
        flows: they were all first seen at inclusion, so every member
        shares included/durable/applied — per-tx rows are expanded
        lazily by the readers (:meth:`_expand_cohort`).  Caller holds
        the lock.  Returns ``(crow, pub)`` for
        :meth:`_publish_cohort`."""
        t_inc = c["t_inc"]
        t_dur = c["t_dur"]
        stages = {}
        lag = None
        if t_dur is not None:
            stages["durable"] = max(0.0, t_dur - t_inc)
            stages["apply"] = max(0.0, t_app - t_dur)
            lag = max(0.0, t_app - t_dur)
        else:
            stages["apply"] = max(0.0, t_app - t_inc)
        e2e = max(0.0, t_app - t_inc)
        milestones = {"included": 0.0}
        if t_dur is not None:
            milestones["durable"] = round(t_dur - t_inc, 6)
        milestones["applied"] = round(t_app - t_inc, 6)
        crow = {
            "_cohort": True,
            # verdict counts, computed ONCE here (before the record
            # is reachable from the ring) — publish and stats() both
            # read them instead of re-walking the tx list
            "codes": dict(Counter(map(itemgetter(1), c["partial"]))),
            "t_s": round(t_app, 6),
            "origin": c["origin"],
            "block": c["num"],
            "channel": c["channel"],
            "e2e_ms": round(e2e * 1000.0, 4),
            "stages_ms": {k: round(v * 1000.0, 4)
                          for k, v in stages.items()},
            "visibility_lag_ms": (None if lag is None
                                  else round(lag * 1000.0, 4)),
            "milestones": milestones,
            "partial": True,
            "txs": c["partial"],
            "n": len(c["partial"]),
        }
        self._done.append(crow)
        return crow, (stages, e2e, lag)

    @staticmethod
    def _expand_cohort(crow: dict) -> list:
        """Per-tx rows from one cohort record (read-time only — the
        hot path never pays for this)."""
        shared = {k: v for k, v in crow.items()
                  if k not in ("_cohort", "txs", "n", "codes")}
        out = []
        for tx_id, code in crow["txs"]:
            r = dict(shared)
            r["tx_id"] = tx_id
            r["code"] = int(code)
            r["outcome"] = _code_name(int(code))
            out.append(r)
        return out

    def _publish(self, row: dict, pub, valid: bool) -> None:
        """Registry + SLO side effects of one completed flow, OUTSIDE
        the journal lock (histograms and the SLO engine take their
        own locks)."""
        stages, e2e, lag = pub
        blk = row.get("block")
        chan = row.get("channel", "")
        ref = None if blk is None else (f"{chan}:{blk}" if chan else str(blk))
        for stage, dur in stages.items():
            self._stage_h.observe(dur, exemplar=ref, stage=stage)
        self._e2e_h.observe(e2e, exemplar=ref, outcome=row["outcome"])
        if lag is not None:
            self._lag_h.observe(lag, exemplar=ref)
        self._flows_ctr.add(1, origin=row["origin"])
        feed = self.slo_feed
        if feed is not None:
            try:
                feed(e2e, valid)
            except Exception as e:
                _log.debug("commit SLO feed failed: %s", e)

    def _publish_cohort(self, crow: dict, pub) -> None:
        """Batched registry + SLO side effects for a whole partial
        cohort, OUTSIDE the journal lock: O(1) instrument updates per
        block regardless of its tx count (observe_repeat), one
        exemplar per block — this is what keeps the default-ON armed
        cost flat on the commit path."""
        stages, e2e, lag = pub
        n = crow["n"]
        codes = crow["codes"]
        blk = crow["block"]
        chan = crow["channel"]
        ref = f"{chan}:{blk}" if chan else str(blk)
        with self._metrics_lock:
            for stage, dur in stages.items():
                self._stage_h.observe_repeat_locked(
                    dur, n, _STAGE_KEYS[stage], exemplar=ref
                )
            for code, cnt in codes.items():
                self._e2e_h.observe_repeat_locked(
                    e2e, cnt, _outcome_key(code), exemplar=ref
                )
            if lag is not None:
                self._lag_h.observe_repeat_locked(lag, n, (), exemplar=ref)
            self._flows_ctr.add_locked(n, (("origin", crow["origin"]),))
        feed = self.slo_feed
        if feed is not None:
            try:
                for code, cnt in codes.items():
                    feed(e2e, code == 0, cnt)
            except Exception as e:
                _log.debug("commit SLO feed failed: %s", e)

    # -- readers ------------------------------------------------------------

    @staticmethod
    def _pcts(vals: list) -> dict | None:
        if not vals:
            return None
        from fabric_tpu.utils.stats import nearest_rank

        vals = sorted(vals)
        return {
            "n": len(vals),
            "p50": round(nearest_rank(vals, 50), 4),
            "p99": round(nearest_rank(vals, 99), 4),
            "max": round(vals[-1], 4),
        }

    def stats(self) -> dict:
        """Stage / e2e / visibility-lag percentiles over the retained
        completed flows — the /txflow summary and the bench
        ``extras.tx_flow`` payload."""
        with self._lock:
            rows = list(self._done)
            inflight = len(self._inflight)
            evicted = self._evicted
            sign_waits = list(self._sign_waits)
        stages: dict[str, list] = {}
        e2e: dict[str, list] = {}
        lags: list = []
        partial = replayed = total = 0
        for r in rows:
            if r.get("_cohort"):
                n = r["n"]
                total += n
                partial += n
                if r["origin"] == "replay":
                    replayed += n
                for k, v in r["stages_ms"].items():
                    stages.setdefault(k, []).extend([v] * n)
                for code, cnt in r["codes"].items():
                    e2e.setdefault(_code_name(code), []).extend(
                        [r["e2e_ms"]] * cnt
                    )
                if r["visibility_lag_ms"] is not None:
                    lags.extend([r["visibility_lag_ms"]] * n)
                continue
            total += 1
            for k, v in r["stages_ms"].items():
                stages.setdefault(k, []).append(v)
            e2e.setdefault(r["outcome"], []).append(r["e2e_ms"])
            if r["visibility_lag_ms"] is not None:
                lags.append(r["visibility_lag_ms"])
            if r["partial"]:
                partial += 1
            if r["origin"] == "replay":
                replayed += 1
        return {
            "flows_completed": total,
            "flows_inflight": inflight,
            "flows_evicted": evicted,
            "flows_partial": partial,
            "flows_replayed": replayed,
            "stages_ms": {s: self._pcts(stages[s])
                          for s in sorted(stages)},
            "e2e_ms": {o: self._pcts(e2e[o]) for o in sorted(e2e)},
            "visibility_lag_ms": self._pcts(lags),
            "sign_wait_ms": self._pcts(sign_waits),
        }

    def rows(self, n: int | None = None) -> list[dict]:
        """The newest ``n`` completed flows (oldest first), cohort
        records expanded to per-tx rows at read time; ``n <= 0``
        means none — NOT everything (``rows[-0:]`` would invert the
        bound)."""
        with self._lock:
            raw = list(self._done)
        rows: list[dict] = []
        for r in raw:
            if r.get("_cohort"):
                rows.extend(self._expand_cohort(r))
            else:
                rows.append(r)
        if n is not None:
            rows = rows[-n:] if n > 0 else []
        return rows

    def lookup(self, tx_id: str) -> dict | None:
        """One flow's full milestone record: a completed row when the
        flow finished (cohort members expanded on the fly), else a
        live in-flight snapshot — a gateway entry, or a cohort member
        between inclusion and apply."""
        with self._lock:
            for r in reversed(self._done):
                if r.get("_cohort"):
                    for tx, code in r["txs"]:
                        if tx == tx_id:
                            row = {k: v for k, v in r.items()
                                   if k not in ("_cohort", "txs", "n",
                                                "codes")}
                            row["tx_id"] = tx_id
                            row["code"] = int(code)
                            row["outcome"] = _code_name(int(code))
                            return row
                elif r["tx_id"] == tx_id:
                    return dict(r)
            ent = self._inflight.get(tx_id)
            if ent is not None:
                ts = ent["t"]
                present = [(m, ts[m]) for m in MILESTONES if m in ts]
                t0 = present[0][1] if present else 0.0
                return {
                    "tx_id": tx_id,
                    "origin": ent.get("origin", "commit"),
                    "block": ent.get("block"),
                    "channel": ent.get("channel", ""),
                    "code": ent.get("code"),
                    "inflight": True,
                    "milestones": {m: round(t - t0, 6)
                                   for m, t in present},
                }
            for num in reversed(self._blocks):
                c = self._blocks[num]
                for tx, code in c["partial"]:
                    if tx == tx_id:
                        ms = {"included": 0.0}
                        if c["t_dur"] is not None:
                            ms["durable"] = round(
                                c["t_dur"] - c["t_inc"], 6
                            )
                        return {
                            "tx_id": tx_id,
                            "origin": c["origin"],
                            "block": c["num"],
                            "channel": c["channel"],
                            "code": int(code),
                            "inflight": True,
                            "milestones": ms,
                        }
        return None

    def report(self, rows: int = 16) -> dict:
        out = self.stats()
        out["recent"] = self.rows(rows)
        return out


# -- process-global handle + the layer hooks ---------------------------------

_global: FlowJournal | None = None
#: refcount for component lifecycles (acquire/release) — colocated
#: nodes share ONE journal and only the last release disarms
_refs = 0


def global_journal() -> FlowJournal | None:
    return _global


def enabled() -> bool:
    """One module-global read: callers that must build per-tx payloads
    for a hook (the pipeline's verdict list) gate on this so the
    disarmed path stays structurally zero."""
    return _global is not None


# Each hook is written out longhand (one global read, one None check,
# a direct method call inside a containment try) rather than through a
# generic getattr dispatcher — these sit on the endorse and commit hot
# paths, and a commit/endorse must never die of its own attribution.


def endorse_begin(tx_id: str) -> None:
    j = _global
    if j is None:
        return
    try:
        j.endorse_begin(tx_id)
    except Exception as e:
        _log.debug("txflow endorse_begin hook failed: %s", e)


def endorse_end(tx_id: str, ok: bool = True) -> None:
    j = _global
    if j is None:
        return
    try:
        j.endorse_end(tx_id, ok)
    except Exception as e:
        _log.debug("txflow endorse_end hook failed: %s", e)


def submit_begin(tx_id: str) -> None:
    j = _global
    if j is None:
        return
    try:
        j.submit_begin(tx_id)
    except Exception as e:
        _log.debug("txflow submit_begin hook failed: %s", e)


def broadcast_done(tx_id: str) -> None:
    j = _global
    if j is None:
        return
    try:
        j.broadcast_done(tx_id)
    except Exception as e:
        _log.debug("txflow broadcast_done hook failed: %s", e)


def block_included(num: int, txs, channel: str = "",
                   replay: bool = False) -> None:
    j = _global
    if j is None:
        return
    try:
        j.block_included(num, txs, channel=channel, replay=replay)
    except Exception as e:
        _log.debug("txflow block_included hook failed: %s", e)


def block_durable(num: int) -> None:
    j = _global
    if j is None:
        return
    try:
        j.block_durable(num)
    except Exception as e:
        _log.debug("txflow block_durable hook failed: %s", e)


def block_applied(num: int) -> None:
    j = _global
    if j is None:
        return
    try:
        j.block_applied(num)
    except Exception as e:
        _log.debug("txflow block_applied hook failed: %s", e)


def sign_observer():
    """→ a ``SignBatcher.observer`` callable feeding the journal's
    ``sign_wait`` stage.  Resolves the global per CALL, so the same
    attached observer goes quiet when the journal disarms (one global
    read + None check per event, like every other hook)."""

    def observer(wait_ms, busy):
        j = _global
        if j is None:
            return
        try:
            j.sign_event(wait_ms, busy)
        except Exception as e:
            _log.debug("txflow sign observer failed: %s", e)

    return observer


def acquire(**kw) -> FlowJournal:
    """Refcounted arming (PeerNode start/stop pairs this with
    :func:`release`): the first acquire builds the journal with its
    :func:`configure` kwargs; later acquires REUSE the live instance
    (first-arm wins), and only the last release disarms."""
    global _refs
    j = _global if _global is not None else configure(**kw)
    _refs += 1
    return j


def release() -> None:
    """Drop one :func:`acquire` hold; the last one out disarms."""
    global _refs
    if _refs > 0:
        _refs -= 1
        if _refs == 0:
            configure(enabled=False)


def configure(enabled: bool = True, registry=None, tracer=None,
              clock=time.perf_counter, ring: int = DEFAULT_RING,
              inflight: int = DEFAULT_INFLIGHT,
              blocks: int = DEFAULT_BLOCKS,
              exemplars: int = DEFAULT_EXEMPLARS,
              ) -> FlowJournal | None:
    """Arm (or, with ``enabled=False``, disarm) the process-global
    journal — the nodeconfig ``tx_flow`` knob lands here.  Disarming
    zeroes the acquire refcount (the hard OFF)."""
    global _global, _refs
    if not enabled:
        _refs = 0
        _global = None
        return None
    _global = FlowJournal(registry=registry, tracer=tracer, clock=clock,
                          ring=ring, inflight=inflight, blocks=blocks,
                          exemplars=exemplars)
    return _global

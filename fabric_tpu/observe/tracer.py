"""Block-commit span tracer: flight recorder, Perfetto export,
slow-block watchdog.

The metrics registry (fabric_tpu.ops_metrics) answers *distribution*
questions — ``commit_pipeline_stage_seconds`` says what finish usually
costs — but cannot answer "why was block 4217 slow?" or "did
device_pre(k) actually overlap parse(k+1)?".  This module records a
per-block *timeline*: a tree of spans rooted at one span per committed
block, crossing every thread the commit path touches (deliver feeder,
prefetch thread, committer thread, host staging pool workers).

Design constraints (the telemetry convention of this repo):

* **always-on and cheap** — a span is a perf_counter pair plus one
  list append; the only lock is taken once per block at finalize (ring
  append + watchdog median).  ``trace_ring_blocks=0`` turns the whole
  thing into no-ops for overhead measurement.
* **explicit handles across threads** — contextvars do NOT follow
  ThreadPoolExecutor tasks, so spans are passed (``parent=``) or
  adopted (``attach``/``detach``) explicitly.  Each thread keeps a
  thread-local *current* span; ``span()``/``add()`` default their
  parent to it, so instrumented leaf code (validator stage timers,
  pool workers) needs no plumbing — the pipeline attaches the right
  parent at each thread boundary.
* **dependency-free** — stdlib only; the optional
  :func:`device_annotation` bridges to ``jax.profiler`` when jax is
  importable so host spans line up with XLA timelines on real-TPU
  runs.

Three export surfaces:

* :meth:`Tracer.export_chrome` — Chrome trace-event JSON, loadable in
  Perfetto / ``chrome://tracing`` (one row per thread/worker);
* the ``/trace`` endpoint on the operations server
  (fabric_tpu.opsserver) serving the flight recorder as JSON trees;
* ``scripts/traceview.py`` — a text waterfall for containers with no
  browser.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

_log = logging.getLogger("fabric_tpu.observe")

#: defaults for the nodeconfig knobs (PeerConfig.trace_ring_blocks /
#: trace_slow_factor) — one definition so config and tracer agree
DEFAULT_RING_BLOCKS = 32
DEFAULT_SLOW_FACTOR = 5.0

#: watchdog arms only after this many committed blocks — the first
#: blocks of a stream eat compiles and cache warms, and a median of two
#: samples is noise
_WATCHDOG_MIN_SAMPLES = 8

_USE_CURRENT = object()  # sentinel: "parent argument not given"

#: Chrome trace-event color names for the launch ledger's device-lane
#: spans (observe/ledger.py): compile stalls render visually distinct
#: from queue waits and execute
_DEV_SPAN_COLORS = {
    "dev:compile": "terrible",
    "dev:queue": "bad",
    "dev:execute": "good",
}


class Span:
    """One timed region.  ``t0``/``t1`` are ``perf_counter`` seconds;
    ``thread`` is the name of the thread that STARTED the span (the
    Chrome row it renders on).  ``children`` appends are GIL-atomic, so
    concurrent pool workers may add children to a shared parent without
    a lock.  ``root`` points at the block root the span hangs under
    (set by the tracer at creation — how a leaf instrumentation site,
    e.g. the sidecar client, finds the block it is part of without a
    parent chain), and ``proc`` names the PROCESS a stitched remote
    span ran in (None = this process; the Chrome export renders one
    pid row per proc)."""

    __slots__ = ("name", "t0", "t1", "thread", "attrs", "children",
                 "events", "root", "proc")

    def __init__(self, name: str, t0: float, thread: str, attrs: dict):
        self.name = name
        self.t0 = t0
        self.t1: float | None = None
        self.thread = thread
        self.attrs = attrs
        self.children: list[Span] = []
        self.events: list[tuple] = []  # (name, t, attrs)
        self.root: Span | None = None
        self.proc: str | None = None

    @property
    def dur(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0

    def to_dict(self, base: float) -> dict:
        """JSON-able tree, times in ms relative to ``base``."""
        d = {
            "name": self.name,
            "start_ms": round((self.t0 - base) * 1000.0, 3),
            "dur_ms": round(self.dur * 1000.0, 3),
            "thread": self.thread,
        }
        if self.proc:
            d["proc"] = self.proc
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = [
                {"name": n, "at_ms": round((t - base) * 1000.0, 3),
                 **({"attrs": a} if a else {})}
                for n, t, a in self.events
            ]
        if self.children:
            d["children"] = [c.to_dict(base) for c in self.children]
        return d


class _SpanCtx:
    """Context manager for one live span: starts on __enter__, attaches
    as the thread's current, restores + ends on __exit__.  A None span
    (disabled tracer / no parent) makes every step a no-op."""

    __slots__ = ("_tracer", "_name", "_parent", "_attrs", "_span", "_tok")

    def __init__(self, tracer, name, parent, attrs):
        self._tracer = tracer
        self._name = name
        self._parent = parent
        self._attrs = attrs

    def __enter__(self):
        sp = self._tracer.start(self._name, self._parent, **self._attrs)
        self._span = sp
        self._tok = self._tracer.attach(sp) if sp is not None else None
        return sp

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._tracer.detach(self._tok)
            self._tracer.end(self._span)
        return False


class Tracer:
    """Span recorder + bounded flight recorder + slow-block watchdog.

    One process-global instance (:func:`global_tracer`) backs the
    production commit path; tests construct their own.  ``clock`` is
    injectable so watchdog behavior is testable without sleeping.
    """

    def __init__(self, ring_blocks: int = DEFAULT_RING_BLOCKS,
                 slow_factor: float = DEFAULT_SLOW_FACTOR,
                 clock=time.perf_counter):
        self.clock = clock
        self._local = threading.local()
        self._lock = threading.Lock()
        self._listeners: list = []
        self.configure(ring_blocks=ring_blocks, slow_factor=slow_factor)

    def configure(self, ring_blocks: int | None = None,
                  slow_factor: float | None = None) -> None:
        """Re-size the flight recorder / re-arm the watchdog; recent
        trees survive a resize (truncated to the new capacity)."""
        with self._lock:
            if ring_blocks is not None:
                self.ring_blocks = int(ring_blocks)
                cap = max(1, self.ring_blocks)
                # one ring PER NAMESPACE: peer block trees live in the
                # default "" ring, a colocated sidecar's request trees
                # in "sidecar" — a request storm can no longer evict
                # real block trees, and /trace?block=N cannot collide
                old = getattr(self, "_rings", None) or {"": deque()}
                self._rings: dict[str, deque] = {
                    ns: deque(list(ring)[-cap:], maxlen=cap)
                    for ns, ring in old.items()
                }
                self._rings.setdefault("", deque(maxlen=cap))
                self._slow: deque = deque(
                    list(getattr(self, "_slow", ())), maxlen=16
                )
                # watchdog medians are per-namespace too: sidecar
                # requests (~ms) and block commits (~100ms) are
                # different populations, and mixing them would poison
                # the trailing median both ways
                if not hasattr(self, "_durs"):
                    self._durs: dict[str, deque] = {}
            if slow_factor is not None:
                self.slow_factor = float(slow_factor)

    @property
    def _ring(self) -> deque:
        """The default-namespace ring (peer block trees)."""
        return self._rings[""]

    # -- finished-block listeners (the SLO engine subscribes) --------------

    def add_listener(self, fn) -> None:
        """``fn(root_span)`` runs after every :meth:`finish_block`
        (outside the tracer lock, on the finishing thread).  Exceptions
        are contained — a broken listener cannot take down the commit
        path."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass  # already removed — detach is idempotent

    @property
    def enabled(self) -> bool:
        return self.ring_blocks > 0

    # -- recording (hot path: no locks) ------------------------------------

    def begin_block(self, number: int, ns: str = "", **attrs):
        """Root span for one block's trip through the commit pipeline
        (submit → commit complete).  ``ns`` names the flight-recorder
        ring the tree finalizes into ("" = peer blocks; the sidecar
        server uses "sidecar" so request trees never evict or collide
        with block trees).  Returns None when disabled — every other
        method tolerates a None span/parent as a no-op."""
        if not self.enabled:
            return None
        attrs["block"] = int(number)
        if ns:
            attrs["ns"] = str(ns)
        sp = Span("block", self.clock(),
                  threading.current_thread().name, attrs)
        sp.root = sp
        return sp

    def start(self, name: str, parent, **attrs):
        """Explicit span start under ``parent`` (a handle passed across
        a thread boundary).  None parent → no-op (returns None)."""
        if parent is None:
            return None
        sp = Span(name, self.clock(), threading.current_thread().name,
                  attrs)
        sp.root = parent.root if parent.root is not None else parent
        parent.children.append(sp)
        return sp

    def end(self, span) -> None:
        if span is not None:
            span.t1 = self.clock()

    def span(self, name: str, parent=_USE_CURRENT, **attrs) -> _SpanCtx:
        """``with tracer.span("launch", parent=root):`` — the span
        becomes the thread's *current* for its extent, so nested
        ``add()``/``span()`` calls with no explicit parent land under
        it.  Default parent is the thread's current span."""
        if parent is _USE_CURRENT:
            parent = self.current()
        return _SpanCtx(self, name, parent, attrs)

    def add(self, name: str, t0: float, t1: float, parent=_USE_CURRENT,
            thread: str | None = None, **attrs) -> None:
        """Record an already-measured span [t0, t1] (retro form for
        code that times stages anyway, e.g. BlockValidator._t).
        ``thread`` overrides the row name — the launch ledger files
        its ``dev:*`` spans on a synthetic ``device:<lane>`` row so
        /trace and the Perfetto export grow a device lane instead of
        mixing device time into the recording thread's row."""
        if parent is _USE_CURRENT:
            parent = self.current()
        if parent is None:
            return
        sp = Span(name, t0,
                  thread or threading.current_thread().name, attrs)
        sp.t1 = t1
        sp.root = parent.root if parent.root is not None else parent
        parent.children.append(sp)

    def event(self, name: str, parent=_USE_CURRENT, **attrs) -> None:
        """Zero-duration annotation (barrier redo, stale-prefetch
        re-parse, coalesced-group membership)."""
        if parent is _USE_CURRENT:
            parent = self.current()
        if parent is None:
            return
        parent.events.append((name, self.clock(), attrs))

    @staticmethod
    def set_attrs(span, **attrs) -> None:
        if span is not None:
            span.attrs.update(attrs)

    # -- thread-local current span -----------------------------------------

    def attach(self, span):
        """Adopt ``span`` as this thread's current; returns a token for
        :meth:`detach`.  This is how a pool/executor task inherits the
        submitting thread's span across the thread boundary."""
        prev = getattr(self._local, "cur", None)
        self._local.cur = span
        return prev

    def detach(self, token) -> None:
        self._local.cur = token

    def current(self):
        return getattr(self._local, "cur", None)

    # -- finalize: ring + watchdog (the one lock per block) ----------------

    def finish_block(self, root) -> None:
        if root is None:
            return
        if root.t1 is None:
            root.t1 = self.clock()
        dur = root.dur
        ns = root.attrs.get("ns", "")
        slow = False
        with self._lock:
            ring = self._rings.get(ns)
            if ring is None:
                ring = self._rings[ns] = deque(
                    maxlen=max(1, self.ring_blocks)
                )
            ring.append(root)
            durs = self._durs.get(ns)
            if durs is None:
                durs = self._durs[ns] = deque(maxlen=128)
            if (len(durs) >= _WATCHDOG_MIN_SAMPLES
                    and self.slow_factor > 0):
                med = sorted(durs)[len(durs) // 2]
                if med > 0 and dur > self.slow_factor * med:
                    slow = True
                    self._slow.append(root)
            durs.append(dur)
        if slow:
            root.attrs["slow"] = True
            from fabric_tpu.ops_metrics import global_registry

            global_registry().counter(
                "trace_slow_blocks_total",
                "blocks flagged by the slow-block watchdog",
            ).add(1, channel=str(root.attrs.get("channel", "")))
            _log.warning(
                "slow block %s: %.1f ms (> %.1fx trailing median "
                "%.1f ms)\n%s",
                root.attrs.get("block"), dur * 1000.0, self.slow_factor,
                med * 1000.0, format_block(root),
            )
        for fn in list(self._listeners):
            try:
                fn(root)
            except Exception as e:  # a listener must never kill commit
                _log.debug("tracer listener %r failed: %s", fn, e)

    # -- readers (flight recorder) -----------------------------------------

    def blocks(self, n: int | None = None, ns: str = "") -> list[dict]:
        """Most recent block trees (oldest first), as JSON-able dicts."""
        with self._lock:
            roots = list(self._rings.get(ns, ()))
        if n is not None:
            roots = roots[-n:]
        return [self._root_dict(r) for r in roots]

    def block(self, number: int, ns: str = "") -> dict | None:
        with self._lock:
            roots = list(self._rings.get(ns, ()))
        for r in reversed(roots):
            if r.attrs.get("block") == number:
                return self._root_dict(r)
        return None

    def namespaces(self) -> dict[str, int]:
        """{ns: trees currently held} for every non-empty ring."""
        with self._lock:
            return {ns: len(r) for ns, r in self._rings.items() if r}

    def slow_blocks(self) -> list[dict]:
        with self._lock:
            roots = list(self._slow)
        return [self._root_dict(r) for r in roots]

    def recent_roots(self, ns: str = "") -> list:
        """The flight recorder's live Span roots (oldest first) — the
        overlap-coverage analyzer (observe/overlap.py) walks these
        directly; the trees are finished, so reading them lock-free
        after the snapshot copy is safe."""
        with self._lock:
            return list(self._rings.get(ns, ()))

    @staticmethod
    def _root_dict(root) -> dict:
        d = root.to_dict(root.t0)
        d["block"] = root.attrs.get("block")
        # absolute perf_counter base: start_ms values are per-block
        # relative, and cross-BLOCK consumers (overlap coverage) need
        # a common timeline to compare neighbors on
        d["t0_s"] = root.t0
        return d

    # -- Chrome trace-event export -----------------------------------------

    def chrome_events(self) -> list[dict]:
        """Flight recorder → Chrome trace-event list ("X" complete
        events + "i" instants + thread_name/process_name metadata),
        one tid per thread/worker name so Perfetto renders one row
        each.  Stitched remote spans (``Span.proc`` set — the sidecar
        subtree the client merged in) get their own pid, so the
        cross-process waterfall renders on distinct process rows.
        Every namespace's ring is exported (peer blocks + sidecar
        request trees in a colocated process)."""
        with self._lock:
            roots = [r for ring in self._rings.values() for r in ring]
        roots.sort(key=lambda r: r.t0)
        pids: dict[str, int] = {"local": 0}
        tids: dict[tuple, int] = {}
        events: list[dict] = []

        def pid(proc: str) -> int:
            p = pids.get(proc)
            if p is None:
                p = pids[proc] = len(pids)
            return p

        def tid(p: int, name: str) -> int:
            t = tids.get((p, name))
            if t is None:
                t = tids[(p, name)] = sum(
                    1 for k in tids if k[0] == p
                ) + 1
            return t

        def walk(sp: Span, block: int) -> None:
            p = pid(sp.proc or "local")
            row = tid(p, sp.thread)
            # the root's block number is the grouping key and always
            # wins — a stitched remote subtree's own ids must not
            # shadow it (its request id rides as args["req"])
            ev = {
                "name": sp.name, "cat": "fabtpu", "ph": "X",
                "ts": sp.t0 * 1e6,
                "dur": max(0.0, sp.dur) * 1e6,
                "pid": p, "tid": row,
                "args": {**sp.attrs, "block": block},
            }
            # ledger device-lane spans: color-code so a compile stall
            # reads differently from execute at a glance in Perfetto
            cname = _DEV_SPAN_COLORS.get(sp.name)
            if cname is not None:
                ev["cname"] = cname
            events.append(ev)
            for n, t, a in sp.events:
                events.append({
                    "name": n, "cat": "fabtpu", "ph": "i", "s": "t",
                    "ts": t * 1e6, "pid": p, "tid": row,
                    "args": {"block": block, **a},
                })
            for c in sp.children:
                walk(c, block)

        for root in roots:
            walk(root, int(root.attrs.get("block", -1)))
        meta = [
            {"name": "process_name", "ph": "M", "pid": p, "tid": 0,
             "args": {"name": proc}}
            for proc, p in pids.items()
        ]
        meta += [
            {"name": "thread_name", "ph": "M", "pid": p, "tid": t,
             "args": {"name": n}}
            for (p, n), t in tids.items()
        ]
        return meta + events

    def export_chrome(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(),
                       "displayTimeUnit": "ms"}, f)


def format_block(root) -> str:
    """Compact indented breakdown of one block tree — the watchdog's
    WARN payload (scripts/traceview.py renders the richer waterfall)."""
    base = root.t0
    lines: list[str] = []

    def walk(sp: Span, depth: int) -> None:
        row = f"{sp.proc}:{sp.thread}" if sp.proc else sp.thread
        lines.append(
            "%s%-24s %8.2f ms @ %7.2f ms  [%s]" % (
                "  " * depth, sp.name, sp.dur * 1000.0,
                (sp.t0 - base) * 1000.0, row,
            )
        )
        for n, t, _a in sp.events:
            lines.append("%s! %s @ %.2f ms" % (
                "  " * (depth + 1), n, (t - base) * 1000.0,
            ))
        for c in sp.children:
            walk(c, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def span_from_dict(d: dict, offset_s: float = 0.0,
                   proc: str | None = None) -> Span:
    """Reconstruct a :class:`Span` tree from ``Span.to_dict(0.0)``
    output — the wire form a sidecar ships its finished request
    subtree back in.  Times in the dict are absolute ms on the REMOTE
    process's clock; ``offset_s`` (remote − local, the NTP-style
    estimate from the request/response timestamp midpoints) is
    subtracted so the tree lands on the local timeline.  ``proc``
    labels every reconstructed span's process row."""
    sp = Span(
        str(d.get("name", "?")),
        float(d.get("start_ms", 0.0)) / 1000.0 - offset_s,
        str(d.get("thread", "?")),
        dict(d.get("attrs") or {}),
    )
    sp.t1 = sp.t0 + max(0.0, float(d.get("dur_ms", 0.0))) / 1000.0
    sp.proc = proc
    for ev in d.get("events", ()):
        sp.events.append((
            str(ev.get("name", "?")),
            float(ev.get("at_ms", 0.0)) / 1000.0 - offset_s,
            dict(ev.get("attrs") or {}),
        ))
    for c in d.get("children", ()):
        child = span_from_dict(c, offset_s, proc)
        child.root = sp
        sp.children.append(child)
    return sp


_global = Tracer()


def global_tracer() -> Tracer:
    return _global


def configure(ring_blocks: int | None = None,
              slow_factor: float | None = None) -> Tracer:
    """Configure the process-global tracer (the nodeconfig knobs
    ``trace_ring_blocks`` / ``trace_slow_factor`` land here)."""
    _global.configure(ring_blocks=ring_blocks, slow_factor=slow_factor)
    return _global


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()
_jax_annotation = None


def device_annotation(name: str):
    """Optional jax.profiler.TraceAnnotation around a device dispatch —
    when a jax profiler trace is being captured (real-TPU runs), the
    host-side dispatch spans line up with the XLA timeline.  No-op (and
    import-free after the first call) when jax is unavailable."""
    global _jax_annotation
    if _jax_annotation is None:
        try:
            from jax.profiler import TraceAnnotation

            _jax_annotation = TraceAnnotation
        except Exception as e:  # no jax in this interpreter
            _log.debug("jax profiler annotations unavailable: %s", e)
            _jax_annotation = False
    if _jax_annotation is False:
        return _NULL_CTX
    return _jax_annotation(name)

"""Per-launch device-time ledger: decomposing ``device_wait`` into
compile / queue / execute / transfer, with HBM accounting.

Every surface before this module stops at the dispatch boundary: the
tracer records how long the host WAITED on the device
(``device_wait``), the SLO engine burns against it, the flight-data
recorder trails it — but none of them can say whether those
milliseconds were a cold XLA compile, queueing behind a prior launch,
kernel execute, or host↔device transfer.  The ledger closes that gap
by wrapping every device dispatch in the system (stage-2 verify/MVCC,
the sign-kernel flush, resident-table scatters, sidecar-dispatched
batches) in a :class:`LaunchRecord` that brackets the dispatch call
and the fetch-side sync and attributes the wall between them.

Attribution model (host-visible quantities only — no profiler, no
device events, honest about what that means):

* **compile** — the duration of the dispatch call itself on a
  program-cache MISS (jax traces + compiles synchronously inside the
  first call; on a hit the same interval is ~free dispatch overhead,
  kept in the row as ``dispatch_ms``).  Cache hit/miss is exact where
  the caller owns the cache (stage-2's program cache) and first-seen
  per structural key otherwise.
* **queue** — ``max(0, prior-launch completion − enqueue)`` per
  *device lane*: a launch cannot start before the previous launch on
  the same device finished, so bracketing the sync against the lane's
  last completion attributes depth-N overlap queueing honestly (the
  launch that waited behind its predecessor reports the wait as
  queue, not execute).
* **execute** — estimated completion minus estimated start.
  Completion is the sync's return time when the sync genuinely
  blocked, else the sync's entry time (the device finished earlier
  than the host looked; the gap is host time, not device time, and is
  deliberately NOT attributed to execute beyond that bound).
* **transfer** — h2d bytes/seconds noted by the caller at staging
  time (the packed launch frame, the resident-state miss fill — the
  existing ``h2d_state_bytes_per_block`` accounting folds in here)
  plus d2h bytes observed at fetch.

The identity ``compile + queue + execute + transfer ≈ wall`` (wall =
noted h2d time + dispatch start → estimated completion) holds to
within the dispatch overhead of cache-hit rows; the fake-backend
battery pins it at ±5%.

Rows land on three surfaces: bounded per-kernel histograms + counters
in the metrics registry (with trace exemplars armed, so a p99 spike
links to the exact block's trace tree), child spans under whatever
span was current at dispatch time (``dev:compile`` / ``dev:queue`` /
``dev:execute`` on a ``device:<lane>`` thread row — /trace and the
Perfetto export grow a device lane per kernel), and the ``/launches``
operations endpoint (per-kernel percentiles, cache hit rates, HBM
watermarks, the last-N raw rows).

HBM accounting: owners (resident table / comb table / launch frames /
outputs) report their pinned bytes via :func:`account_hbm`; the
ledger keeps current + watermark per owner, and
:func:`live_device_bytes` samples ``jax.live_arrays()`` on demand
(never on the hot path) for the ground-truth total.

Default ON in production (nodeconfig ``device_ledger``) but
near-zero-cost when disarmed: every hook is one module-global read +
None check (the blackbox ``notify()`` pattern) — no thread, no
instruments, no state on tier-1 CPU hosts that never arm it.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

_log = logging.getLogger("fabric_tpu.observe.ledger")

#: completed rows retained for /launches and the trailing signals
DEFAULT_RING = 256

#: trace exemplars armed on each ledger histogram (per label variant)
DEFAULT_EXEMPLARS = 8

#: a sync shorter than this is "the device was already done" — the
#: completion estimate then uses the sync's entry time, so host lag
#: between device completion and the fetch call is not booked as
#: execute beyond that bound
SYNC_BLOCKED_EPS_S = 0.0002

#: seconds of trailing rows the device_queue signal aggregates over
SIGNAL_WINDOW_S = 30.0

_HIST_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 2.5, 10.0, float("inf"))


class LaunchRecord:
    """One in-flight device launch.  Created by
    :meth:`LaunchLedger.launch` BEFORE the dispatch call; the caller
    marks :meth:`dispatched` right after the dispatch returns,
    brackets the fetch-side sync with :meth:`sync_begin` /
    :meth:`sync_end`, or calls :meth:`complete` for enqueue-only
    launches whose completion is never awaited (functional scatters).
    Every method is idempotent-safe: a double fetch completes once."""

    __slots__ = ("ledger", "kernel", "lane", "key", "lanes", "compiled",
                 "sharded", "t0", "t1", "t_sync0", "h2d_bytes", "h2d_s",
                 "d2h_bytes", "_parent", "_ref", "_done",
                 "_dispatch_marked", "_pins")

    def __init__(self, ledger: "LaunchLedger", kernel: str, lane: str,
                 compiled: bool, lanes: int, parent, ref,
                 sharded: bool | None = None):
        self.ledger = ledger
        self.kernel = kernel
        self.lane = lane
        self.lanes = int(lanes)
        self.compiled = bool(compiled)
        #: None = no mesh configured (untagged row); True/False = a
        #: mesh WAS configured and the dispatch did / did not shard —
        #: False is the silent-unparallel signal /launches surfaces
        self.sharded = sharded
        self.t0 = ledger.clock()
        self.t1: float | None = None
        self.t_sync0: float | None = None
        self.h2d_bytes = 0
        self.h2d_s = 0.0
        self.d2h_bytes = 0
        self._parent = parent
        self._ref = ref
        self._done = False
        self._dispatch_marked = False
        self._pins: list = []

    def note_h2d(self, nbytes: int, seconds: float = 0.0) -> None:
        """Count host→device upload bytes (and, when the caller timed
        the staging, seconds) toward this launch's transfer lane."""
        self.h2d_bytes += int(nbytes)
        self.h2d_s += float(seconds)

    def begin_dispatch(self) -> None:
        """Re-anchor the record's start at the ACTUAL dispatch call
        (first call wins).  Callers that stage on the host between
        opening the record and dispatching (the verify wire-frame
        pack) mark this boundary so host staging is never booked as
        compile on a miss or dispatch overhead on a hit; callers that
        never call it keep the open-time anchor (over-approximate,
        the safe direction)."""
        if not self._dispatch_marked:
            self._dispatch_marked = True
            self.t0 = self.ledger.clock()

    def pin_hbm(self, owner: str, nbytes: int) -> None:
        """Account transient device bytes (launch frames, outputs)
        pinned by THIS launch: the level is ADDITIVE across concurrent
        depth-N launches — so the watermark records the true
        concurrent peak, not the largest single block — and released
        when the record completes."""
        nbytes = int(nbytes)
        self._pins.append((owner, nbytes))
        self.ledger.adjust_hbm(owner, nbytes)

    def dispatched(self) -> None:
        """The dispatch call returned — the launch is enqueued.  On a
        program-cache miss the interval since :meth:`launch` is the
        compile."""
        if self.t1 is None:
            self.t1 = self.ledger.clock()

    def sync_begin(self) -> None:
        if self.t_sync0 is None:
            self.t_sync0 = self.ledger.clock()

    def sync_end(self, d2h_bytes: int = 0) -> None:
        """The fetch returned — the launch (and its d2h readback) is
        complete; the ledger attributes and records the row."""
        if self._done:
            return
        self._done = True
        self.d2h_bytes += int(d2h_bytes)
        if self.t1 is None:
            self.t1 = self.ledger.clock()
        t2 = self.t_sync0 if self.t_sync0 is not None else self.t1
        t3 = self.ledger.clock()
        self.ledger._complete(self, t2, t3)

    def complete(self) -> None:
        """Enqueue-only completion: the caller never syncs (functional
        scatter updates).  The row records compile/dispatch/transfer;
        queue and execute stay None and the device lane's completion
        estimate is untouched."""
        if self._done:
            return
        self._done = True
        if self.t1 is None:
            self.t1 = self.ledger.clock()
        self.ledger._complete(self, None, None)


class LaunchLedger:
    """See module docstring.  One process-global instance in
    production (:func:`global_ledger`); tests construct their own with
    an injected clock and a private registry."""

    def __init__(self, registry=None, tracer=None,
                 clock=time.perf_counter, ring: int = DEFAULT_RING,
                 exemplars: int = DEFAULT_EXEMPLARS):
        self.clock = clock
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self.registry = registry
        if tracer is None:
            from fabric_tpu.observe.tracer import global_tracer

            tracer = global_tracer()
        self.tracer = tracer
        self._lock = threading.Lock()
        self._rows: deque = deque(maxlen=max(1, int(ring)))
        #: lane → estimated completion time of the newest finished
        #: launch — the queue-attribution bracket
        self._lane_done: dict[str, float] = {}
        #: (kernel, key) structural keys already dispatched — the
        #: first-seen cache-miss inference for callers that do not own
        #: their program cache
        self._seen: set = set()
        #: owner → [current_bytes, watermark_bytes]
        self._hbm: dict[str, list] = {}
        self._launch_ctr = registry.counter(
            "device_launches_total",
            "device launches recorded by the launch ledger, by kernel "
            "and program-cache outcome",
        )
        kw = dict(buckets=_HIST_BUCKETS, exemplars=int(exemplars))
        self._compile_h = registry.histogram(
            "device_launch_compile_seconds",
            "per-launch program compile time (s; cache misses only)",
            **kw,
        )
        self._queue_h = registry.histogram(
            "device_launch_queue_seconds",
            "per-launch device-lane queue wait (s): enqueue until the "
            "prior launch on the same lane completed",
            **kw,
        )
        self._execute_h = registry.histogram(
            "device_launch_execute_seconds",
            "per-launch device execute time (s; estimated completion "
            "minus estimated start)",
            **kw,
        )
        self._h2d_ctr = registry.counter(
            "device_launch_h2d_bytes_total",
            "host→device bytes uploaded per kernel (launch frames, "
            "state miss fills, scatter rows)",
        )
        self._d2h_ctr = registry.counter(
            "device_launch_d2h_bytes_total",
            "device→host bytes fetched per kernel",
        )
        self._hbm_gauge = registry.gauge(
            "device_ledger_hbm_bytes",
            "device-memory bytes currently pinned, by owner tag",
        )
        self._hbm_peak_gauge = registry.gauge(
            "device_ledger_hbm_watermark_bytes",
            "device-memory high-watermark bytes, by owner tag",
        )

    # -- recording ---------------------------------------------------------

    def launch(self, kernel: str, *, key=None, lane: str = "dev",
               lanes: int = 0, compiled: bool | None = None,
               h2d_bytes: int = 0,
               sharded: bool | None = None) -> LaunchRecord:
        """Open a record for one device dispatch.  ``compiled`` is the
        caller's exact program-cache verdict where it owns the cache;
        None infers miss-on-first-sight of ``(kernel, key)``.
        ``sharded`` tags the row when a device mesh is configured:
        False marks a dispatch whose operands fell back to unsharded
        (ragged axis 0 — see parallel.mesh ``shard``), the
        silent-unparallel case /launches must surface.  The tracer's
        thread-current span is captured as the parent the device
        child spans land under (None off traced paths)."""
        if compiled is None:
            k = (kernel, key)
            with self._lock:
                compiled = k not in self._seen
                self._seen.add(k)
        parent = self.tracer.current()
        ref = None
        if parent is not None and parent.root is not None:
            a = parent.root.attrs
            blk = a.get("block")
            if blk is not None:
                ns = a.get("ns", "")
                ref = f"{ns}:{blk}" if ns else str(blk)
        rec = LaunchRecord(self, kernel, lane, compiled, lanes,
                           parent, ref, sharded=sharded)
        if h2d_bytes:
            rec.note_h2d(h2d_bytes)
        return rec

    def _complete(self, rec: LaunchRecord, t2, t3) -> None:
        t0, t1 = rec.t0, rec.t1
        dispatch_s = max(0.0, t1 - t0)
        compile_s = dispatch_s if rec.compiled else 0.0
        queue_s = execute_s = None
        f = None
        if t3 is not None:
            # completion estimate: the sync's return when it genuinely
            # blocked, its entry otherwise (see module docstring)
            f = t3 if (t3 - t2) > SYNC_BLOCKED_EPS_S else t2
            f = max(f, t1)
        with self._lock:
            if f is not None:
                prev = self._lane_done.get(rec.lane, float("-inf"))
                start = min(f, max(t1, prev))
                queue_s = max(0.0, start - t1)
                execute_s = max(0.0, f - start)
                if f > prev:
                    self._lane_done[rec.lane] = f
            row = {
                "t_s": round(self.clock(), 6),
                "kernel": rec.kernel,
                "lane": rec.lane,
                "lanes": rec.lanes,
                "cache": "miss" if rec.compiled else "hit",
                "dispatch_ms": round(dispatch_s * 1000.0, 4),
                "compile_ms": round(compile_s * 1000.0, 4),
                "queue_ms": (None if queue_s is None
                             else round(queue_s * 1000.0, 4)),
                "execute_ms": (None if execute_s is None
                               else round(execute_s * 1000.0, 4)),
                "h2d_bytes": rec.h2d_bytes,
                "h2d_ms": round(rec.h2d_s * 1000.0, 4),
                "d2h_bytes": rec.d2h_bytes,
                "wall_ms": (None if f is None else
                            round((rec.h2d_s + f - t0) * 1000.0, 4)),
            }
            if rec.sharded is not None:
                row["sharded"] = rec.sharded
            if rec._ref is not None:
                row["block"] = rec._ref
            self._rows.append(row)
        k = rec.kernel
        self._launch_ctr.add(1, kernel=k, cache=row["cache"])
        if rec.compiled:
            self._compile_h.observe(compile_s, exemplar=rec._ref,
                                    kernel=k)
        if queue_s is not None:
            self._queue_h.observe(queue_s, exemplar=rec._ref, kernel=k)
            self._execute_h.observe(execute_s, exemplar=rec._ref,
                                    kernel=k)
        if rec.h2d_bytes:
            self._h2d_ctr.add(rec.h2d_bytes, kernel=k)
        if rec.d2h_bytes:
            self._d2h_ctr.add(rec.d2h_bytes, kernel=k)
        for owner, nbytes in rec._pins:
            # transient pins (launch frames, outputs) release when the
            # launch completes — the level tracks what is pinned NOW
            self.adjust_hbm(owner, -nbytes)
        self._spans(rec, t1, queue_s, execute_s, f)

    def _spans(self, rec: LaunchRecord, t1, queue_s, execute_s, f):
        """Device-lane child spans under the span that was current at
        dispatch time — /trace and the Perfetto export grow a
        ``device:<lane>`` row per kernel."""
        parent = rec._parent
        if parent is None or not self.tracer.enabled:
            return
        th = f"device:{rec.lane}"
        if rec.compiled:
            self.tracer.add("dev:compile", rec.t0, t1, parent=parent,
                            thread=th, kernel=rec.kernel)
        if queue_s is not None and queue_s > 0:
            self.tracer.add("dev:queue", t1, t1 + queue_s,
                            parent=parent, thread=th, kernel=rec.kernel)
        if execute_s is not None:
            self.tracer.add("dev:execute", f - execute_s, f,
                            parent=parent, thread=th, kernel=rec.kernel,
                            lanes=rec.lanes)

    # -- HBM accounting ----------------------------------------------------

    def account_hbm(self, owner: str, nbytes: int) -> None:
        """A PERSISTENT owner tag (resident_table / comb_table)
        reports its currently-pinned device bytes as a level; the
        ledger keeps the level and the high watermark.  Transient
        per-launch pins (launch frames, outputs) go through
        :meth:`LaunchRecord.pin_hbm` instead — additive across
        concurrent launches, released at completion."""
        nbytes = int(nbytes)
        with self._lock:
            ent = self._hbm.get(owner)
            if ent is None:
                ent = self._hbm[owner] = [0, 0]
            ent[0] = nbytes
            ent[1] = max(ent[1], nbytes)
            peak = ent[1]
        self._hbm_gauge.set(nbytes, owner=owner)
        self._hbm_peak_gauge.set(peak, owner=owner)

    def adjust_hbm(self, owner: str, delta: int) -> None:
        """Additive form for transient pins: concurrent depth-N
        launches SUM their frames, so the watermark records the true
        concurrent peak rather than the largest single block."""
        with self._lock:
            ent = self._hbm.get(owner)
            if ent is None:
                ent = self._hbm[owner] = [0, 0]
            ent[0] = max(0, ent[0] + int(delta))
            ent[1] = max(ent[1], ent[0])
            level, peak = ent
        self._hbm_gauge.set(level, owner=owner)
        self._hbm_peak_gauge.set(peak, owner=owner)

    # -- readers -----------------------------------------------------------

    @staticmethod
    def _pcts(vals: list) -> dict | None:
        if not vals:
            return None
        from fabric_tpu.utils.stats import nearest_rank

        vals = sorted(vals)
        return {
            "n": len(vals),
            "p50": round(nearest_rank(vals, 50), 4),
            "p99": round(nearest_rank(vals, 99), 4),
            "max": round(vals[-1], 4),
        }

    def stats(self) -> dict:
        """Per-kernel decomposition over the retained rows + HBM
        watermarks — the /launches summary and the bench
        ``extras.device_ledger`` payload."""
        with self._lock:
            rows = list(self._rows)
            hbm = {o: {"current_bytes": c, "watermark_bytes": w}
                   for o, (c, w) in sorted(self._hbm.items())}
        kernels: dict[str, dict] = {}
        for r in rows:
            k = kernels.setdefault(r["kernel"], {
                "launches": 0, "cache_misses": 0, "unsharded": 0,
                "compile_ms": [], "queue_ms": [], "execute_ms": [],
                "h2d_bytes": 0, "d2h_bytes": 0,
            })
            k["launches"] += 1
            if r.get("sharded") is False:
                k["unsharded"] += 1
            if r["cache"] == "miss":
                k["cache_misses"] += 1
                k["compile_ms"].append(r["compile_ms"])
            if r["queue_ms"] is not None:
                k["queue_ms"].append(r["queue_ms"])
            if r["execute_ms"] is not None:
                k["execute_ms"].append(r["execute_ms"])
            k["h2d_bytes"] += r["h2d_bytes"]
            k["d2h_bytes"] += r["d2h_bytes"]
        out: dict[str, dict] = {}
        for name, k in sorted(kernels.items()):
            n = k["launches"]
            out[name] = {
                "launches": n,
                "cache_misses": k["cache_misses"],
                "cache_hit_rate": round((n - k["cache_misses"]) / n, 4),
                # mesh-configured dispatches that silently ran
                # unparallel (parallel.mesh shard fallback) — nonzero
                # here explains mystery device_wait before anyone
                # reads per-row tags
                "unsharded_launches": k["unsharded"],
                "compile_ms": self._pcts(k["compile_ms"]),
                "queue_ms": self._pcts(k["queue_ms"]),
                "execute_ms": self._pcts(k["execute_ms"]),
                "h2d_bytes": k["h2d_bytes"],
                "d2h_bytes": k["d2h_bytes"],
            }
        return {"kernels": out, "hbm": hbm, "rows_retained": len(rows)}

    def rows(self, n: int | None = None,
             kernel: str | None = None) -> list[dict]:
        """The newest ``n`` raw rows (oldest first); ``n <= 0`` means
        none — NOT everything (``rows[-0:]`` would invert the bound)."""
        with self._lock:
            rows = list(self._rows)
        if kernel is not None:
            rows = [r for r in rows if r["kernel"] == kernel]
        if n is not None:
            rows = rows[-n:] if n > 0 else []
        return rows

    def report(self, rows: int = 16, kernel: str | None = None) -> dict:
        out = self.stats()
        out["recent"] = self.rows(rows, kernel=kernel)
        return out

    def queue_p99_ms(self, window_s: float = SIGNAL_WINDOW_S):
        """Trailing queue-wait p99 (ms) across kernels — the
        autopilot's ``device_queue_ms`` signal, the honest replacement
        for inferring device pressure from launch-span p99.  None when
        the window holds no synced rows."""
        horizon = self.clock() - window_s
        with self._lock:
            vals = sorted(
                r["queue_ms"] for r in self._rows
                if r["queue_ms"] is not None and r["t_s"] >= horizon
            )
        if not vals:
            return None
        from fabric_tpu.utils.stats import nearest_rank

        return float(nearest_rank(vals, 99))


def live_device_bytes() -> int | None:
    """Ground-truth total of live device-buffer bytes from
    ``jax.live_arrays()`` — sampled on demand (/launches, bench
    extras), NEVER per launch.  None when jax is unavailable or the
    runtime refuses."""
    try:
        import jax

        return int(sum(
            getattr(a, "nbytes", 0) for a in jax.live_arrays()
        ))
    except Exception as e:
        _log.debug("live_arrays sample unavailable: %s", e)
        return None


# -- process-global handle + the dispatch hooks ------------------------------

_global: LaunchLedger | None = None
#: refcount for component lifecycles (acquire/release) — colocated
#: nodes share ONE ledger and only the last release disarms it
_refs = 0


def global_ledger() -> LaunchLedger | None:
    return _global


def launch(kernel: str, **kw) -> LaunchRecord | None:
    """The dispatch-site hook: one module-global read + None check
    when no ledger is armed; contained — a dispatch must never die of
    its own attribution."""
    led = _global
    if led is None:
        return None
    try:
        return led.launch(kernel, **kw)
    except Exception as e:
        _log.debug("launch record for %r failed: %s", kernel, e)
        return None


def note_h2d(kernel: str, nbytes: int) -> None:
    """Record standalone h2d bytes against ``kernel`` (the resident
    state path's per-block miss-fill/frame accounting folds in here)."""
    led = _global
    if led is None:
        return
    try:
        led._h2d_ctr.add(int(nbytes), kernel=kernel)
    except Exception as e:
        _log.debug("h2d note for %r failed: %s", kernel, e)


def account_hbm(owner: str, nbytes: int) -> None:
    """Owner-tag HBM hook: one global read + None check unarmed."""
    led = _global
    if led is None:
        return
    try:
        led.account_hbm(owner, nbytes)
    except Exception as e:
        _log.debug("hbm account for %r failed: %s", owner, e)


def acquire(**kw) -> LaunchLedger:
    """Refcounted arming (PeerNode start/stop pairs this with
    :func:`release`): the first acquire builds the ledger with its
    :func:`configure` kwargs; later acquires REUSE the live instance
    (first-arm wins — replacing it would discard the first holder's
    rows and lane state), and only the last release disarms."""
    global _refs
    led = _global if _global is not None else configure(**kw)
    _refs += 1
    return led


def release() -> None:
    """Drop one :func:`acquire` hold; the last one out disarms."""
    global _refs
    if _refs > 0:
        _refs -= 1
        if _refs == 0:
            configure(enabled=False)


def configure(enabled: bool = True, registry=None, tracer=None,
              clock=time.perf_counter, ring: int = DEFAULT_RING,
              exemplars: int = DEFAULT_EXEMPLARS,
              ) -> LaunchLedger | None:
    """Arm (or, with ``enabled=False``, disarm) the process-global
    ledger — the nodeconfig ``device_ledger`` knob lands here.
    Disarming zeroes the acquire refcount (the hard OFF)."""
    global _global, _refs
    if not enabled:
        _refs = 0
        _global = None
        return None
    _global = LaunchLedger(registry=registry, tracer=tracer,
                           clock=clock, ring=ring, exemplars=exemplars)
    return _global

"""Latency/error SLOs with rolling-window burn rates over the
tracer's finished-block stream.

The span tracer answers "why was block 4217 slow?"; this module
answers the question a multi-tenant operator actually pages on:
"which tenant is burning its latency budget, and how fast?".  An
**objective** declares what a *good* event is (a block committed
under ``ms`` milliseconds; a sidecar request answered without BUSY)
and what fraction of events must be good (``target``, e.g. 0.99 → a
1% error budget).  The engine consumes the tracer's finished-block
stream (``Tracer.add_listener``), buckets events per (objective,
channel), and computes the classic SRE **burn rate** over rolling
windows:

    burn = bad_fraction_in_window / (1 - target)

Burn 1.0 means the budget is being spent exactly as fast as it
accrues; sustained burn > 1 means the SLO will be violated; a burn
over the ``fast`` threshold on the SHORTEST window (default 14 — the
multi-window alerting convention) is the page-now signal, surfaced as
a WARN (rate-limited to one per window per series) and a
``slo_fast_burn_total`` counter.  Gauges ``slo_burn_rate{slo,window,
channel}`` track every series continuously; the ``/slo`` endpoint on
the operations server serves :meth:`SloEngine.report`.

Objectives are declared with a faults-style spec string (the
nodeconfig ``slos`` knob / ``FABTPU_SLOS``):

    name:kind[:k=v ...][; more objectives]

kinds:

* ``latency`` — good = the block root's duration ≤ ``ms=<float>``
  milliseconds.  Applies per channel (the root's ``channel`` attr):
  peer block trees and sidecar request trees alike (a sidecar
  request's channel is ``sidecar:<tenant>``; BUSY replies are not
  latency samples and are skipped).
* ``busy`` — good = a sidecar request was NOT answered BUSY.
  ``pct=<float>`` is the allowed BUSY percentage (target = 1−pct/100).
  Only sidecar request trees (``ns == "sidecar"``) count.

common keys: ``target=`` overrides the good-fraction objective
(latency default 0.99), ``windows=<s1>,<s2>,...`` the rolling windows
in seconds (default 60,300; the shortest is the fast-burn window),
``fast=`` the fast-burn threshold (default 14.0; 0 disables the
WARN), ``channel=`` restricts the objective to one channel/tenant,
``min_events=`` the per-window cold-start floor (default 5): a window
holding fewer events reports burn ``None`` — one bad block on a
freshly started peer is statistically nothing, and it must not fire a
fast-burn WARN (or trip the traffic autopilot) before the window has
a real sample.  Set ``min_events=1`` to restore the raw behavior.

The engine is stdlib-only, locked, and clock-injectable (tests drive
burn-up and recovery without sleeping).  Like the tracer it rides,
it only sees blocks the tracer finalizes — ``trace_ring_blocks=0``
silences SLOs too (documented on the knob).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_log = logging.getLogger("fabric_tpu.observe.slo")

DEFAULT_WINDOWS = (60.0, 300.0)
DEFAULT_TARGET = 0.99
DEFAULT_FAST_BURN = 14.0
#: cold-start floor: a window holding fewer events than this reports
#: burn None — one bad block in a near-empty window must not read as
#: burn ≥ 1 (or WARN) on a freshly started peer
DEFAULT_MIN_EVENTS = 5
_KINDS = ("latency", "busy")

#: events retained per (objective, channel) series — bounds memory
#: under a storm; at 1k blocks/s a 4096-event series still spans the
#: default 60s fast window's most recent slice, which is the window
#: fast-burn alerting reads
MAX_EVENTS = 4096


class SloError(ValueError):
    """A malformed SLO spec, phrased so the operator can fix it."""


@dataclass(frozen=True)
class Objective:
    """One declared objective (see module docstring)."""

    name: str
    kind: str                    # "latency" | "busy"
    ms: float = 0.0              # latency threshold (latency kind)
    target: float = DEFAULT_TARGET
    windows: tuple = DEFAULT_WINDOWS
    fast: float = DEFAULT_FAST_BURN
    channel: str = ""            # "" = every channel
    min_events: int = DEFAULT_MIN_EVENTS  # per-window cold-start floor

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def parse_slos(spec: str) -> list[Objective]:
    """``'commit:latency:ms=250;busy:busy:pct=5'`` → objectives."""
    out: list[Objective] = []
    seen: set[str] = set()
    for part in str(spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise SloError(
                f"slo spec {part!r}: expected 'name:kind[:k=v...]'"
            )
        name, kind = fields[0].strip(), fields[1].strip()
        if kind not in _KINDS:
            raise SloError(
                f"slo spec {part!r}: unknown kind {kind!r} "
                f"(expected one of {', '.join(_KINDS)})"
            )
        if name in seen:
            raise SloError(f"slo spec: duplicate objective {name!r}")
        seen.add(name)
        kw: dict = {}
        pct = None
        for f in fields[2:]:
            k, sep, v = f.partition("=")
            k = k.strip()
            if not sep:
                raise SloError(
                    f"slo spec {part!r}: expected k=v, got {f!r}"
                )
            try:
                if k == "ms":
                    kw["ms"] = float(v)
                elif k == "pct":
                    pct = float(v)
                elif k == "target":
                    kw["target"] = float(v)
                elif k == "fast":
                    kw["fast"] = float(v)
                elif k == "windows":
                    kw["windows"] = tuple(
                        sorted(float(w) for w in v.split(",") if w)
                    )
                elif k == "channel":
                    kw["channel"] = v.strip()
                elif k == "min_events":
                    kw["min_events"] = int(v)
                else:
                    raise SloError(
                        f"slo spec {part!r}: unknown key {k!r}"
                    )
            except ValueError as e:
                if isinstance(e, SloError):
                    raise
                raise SloError(
                    f"slo spec {part!r}: cannot parse {f!r}: {e}"
                ) from None
        if kind == "latency":
            if kw.get("ms", 0.0) <= 0:
                raise SloError(
                    f"slo spec {part!r}: latency needs ms=<positive>"
                )
        else:  # busy
            if pct is None or not (0 < pct < 100):
                raise SloError(
                    f"slo spec {part!r}: busy needs pct=<0..100>"
                )
            kw.setdefault("target", 1.0 - pct / 100.0)
        windows = kw.get("windows", DEFAULT_WINDOWS)
        if not windows or any(w <= 0 for w in windows):
            raise SloError(
                f"slo spec {part!r}: windows must be positive seconds"
            )
        if not (0 < kw.get("target", DEFAULT_TARGET) < 1):
            raise SloError(
                f"slo spec {part!r}: target must be in (0, 1)"
            )
        if kw.get("min_events", DEFAULT_MIN_EVENTS) < 1:
            raise SloError(
                f"slo spec {part!r}: min_events must be >= 1"
            )
        out.append(Objective(name=name, kind=kind, **kw))
    return out


@dataclass
class _Series:
    """One (objective, channel) event stream."""

    events: deque = field(
        default_factory=lambda: deque(maxlen=MAX_EVENTS)
    )  # (t, good) pairs, t on the engine clock
    last_warn: float = float("-inf")


class SloEngine:
    """See module docstring.  ``on_block`` is the tracer listener;
    ``record`` is the direct feed for tests and custom signals."""

    def __init__(self, objectives=(), clock=time.monotonic,
                 registry=None):
        self.objectives: tuple = tuple(objectives)
        self.clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._burn_gauge = registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective, window and channel "
            "(1.0 = budget spent exactly as fast as it accrues)",
        )
        self._fast_ctr = registry.counter(
            "slo_fast_burn_total",
            "fast-burn threshold trips per objective and channel",
        )

    def set_objectives(self, objectives) -> None:
        with self._lock:
            self.objectives = tuple(objectives)
            self._series.clear()

    # -- the tracer feed ---------------------------------------------------

    def on_block(self, root) -> None:
        """Tracer listener: classify one finished root span against
        every matching objective."""
        if not self.objectives:
            return
        attrs = root.attrs
        channel = str(attrs.get("channel", "") or "")
        ns = attrs.get("ns", "")
        if ns == "autopilot":
            # controller decision events ride the tracer for the
            # actuation trail — they are control plane, not traffic,
            # and must not dilute any latency series
            return
        if ns == "sign":
            # sign-flush roots (peer/signlane.py) exist for the device
            # ledger's /trace?ns=sign waterfall; the sign lane already
            # feeds the endorse SLOs per-request through its observer,
            # so counting flush roots here would double-book them
            return
        busy = bool(attrs.get("busy"))
        dur_ms = root.dur * 1000.0
        for o in self.objectives:
            if o.channel and o.channel != channel:
                continue
            if o.kind == "busy":
                if ns != "sidecar":
                    continue
                self.record(o, channel, good=not busy)
            else:  # latency
                if busy:
                    continue  # a BUSY reply is not a latency sample
                self.record(o, channel, good=dur_ms <= o.ms)

    def record(self, objective: Objective, channel: str,
               good: bool) -> None:
        now = self.clock()
        key = (objective.name, channel)
        fast_burn = None
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _Series()
            s.events.append((now, bool(good)))
            burns = _burns(objective, s.events, now)
            fast_w = objective.windows[0]
            b = burns.get(fast_w)
            if (objective.fast > 0 and b is not None
                    and b >= objective.fast
                    and now - s.last_warn >= fast_w):
                s.last_warn = now
                fast_burn = b
        for w, b in burns.items():
            # None (empty window) exports as 0.0 — no traffic is not a
            # violation, and the gauge must not freeze at a stale value
            self._burn_gauge.set(
                0.0 if b is None else round(b, 4), slo=objective.name,
                window=_wlabel(w), channel=channel,
            )
        if fast_burn is not None:
            self._fast_ctr.add(1, slo=objective.name, channel=channel)
            # incident edge: the fast-burn WARN is rate-limited to one
            # per window already, so the black-box hook inherits that
            # cadence (plus its own per-kind limit)
            from fabric_tpu.observe import blackbox

            blackbox.notify(
                "slo_fast_burn", slo=objective.name, channel=channel,
                burn=round(fast_burn, 4),
                window_s=objective.windows[0],
            )
            _log.warning(
                "SLO %s fast burn on channel %r: burn rate %.1f over "
                "the %s window (threshold %.1f, budget %.2f%%) — the "
                "error budget is being spent %.0fx faster than it "
                "accrues",
                objective.name, channel, fast_burn,
                _wlabel(objective.windows[0]), objective.fast,
                objective.budget * 100.0, fast_burn,
            )

    # -- burn computation --------------------------------------------------

    def burn(self, name: str, channel: str,
             window: float | None = None) -> float | None:
        """Current burn rate of one series (recomputed at call time,
        so recovery decays without new traffic rolling in)."""
        o = next((o for o in self.objectives if o.name == name), None)
        if o is None:
            return None
        window = o.windows[0] if window is None else float(window)
        now = self.clock()
        with self._lock:
            s = self._series.get((name, channel))
            if s is None:
                return None
            return _burns(o, s.events, now).get(window)

    def burns(self, window: float | None = None) -> dict:
        """{(objective_name, channel): burn | None} across every live
        series, recomputed at call time on the fast (or given)
        window — the traffic autopilot's error-signal read.  Cheap:
        one lock to snapshot, per-series reverse walk bounded by the
        window."""
        now = self.clock()
        with self._lock:
            objectives = self.objectives
            series = {
                k: list(s.events) for k, s in self._series.items()
            }
        out: dict = {}
        for o in objectives:
            w = o.windows[0] if window is None else float(window)
            floor = max(1, o.min_events)
            for (name, channel), events in series.items():
                if name != o.name:
                    continue
                lo = now - w
                total = bad = 0
                for t, good in reversed(events):
                    if t < lo:
                        break
                    total += 1
                    if not good:
                        bad += 1
                out[(name, channel)] = (
                    (bad / total / o.budget) if total >= floor else None
                )
        return out

    def report(self) -> dict:
        """JSON-able snapshot (the ``/slo`` endpoint and bench extras):
        every objective, per-channel window burns recomputed at call
        time, and a status roll-up (ok | burning | fast_burn)."""
        now = self.clock()
        with self._lock:
            objectives = self.objectives
            series = {
                k: list(s.events) for k, s in self._series.items()
            }
        out: dict = {"objectives": [], "clock_s": round(now, 3)}
        for o in objectives:
            entry = {
                "name": o.name, "kind": o.kind,
                "target": o.target, "budget": round(o.budget, 6),
                "windows_s": list(o.windows), "fast_burn": o.fast,
                "channels": {},
            }
            if o.kind == "latency":
                entry["ms"] = o.ms
            if o.channel:
                entry["channel_filter"] = o.channel
            for (name, channel), events in sorted(series.items()):
                if name != o.name:
                    continue
                burns = {}
                total = bad = 0
                lo = now - max(o.windows)
                for t, good in events:
                    if t < lo:
                        continue
                    total += 1
                    if not good:
                        bad += 1
                for w, b in _burns(o, events, now).items():
                    burns[_wlabel(w)] = (
                        None if b is None else round(b, 4)
                    )
                    # refresh the exported gauge too: a channel whose
                    # traffic stopped must decay on the scrape path,
                    # not freeze at its last mid-incident value
                    self._burn_gauge.set(
                        0.0 if b is None else round(b, 4),
                        slo=o.name, window=_wlabel(w), channel=channel,
                    )
                fast = burns.get(_wlabel(o.windows[0]))
                status = "ok"
                if fast is not None and o.fast > 0 and fast >= o.fast:
                    status = "fast_burn"
                elif any(b is not None and b >= 1.0
                         for b in burns.values()):
                    status = "burning"
                entry["channels"][channel] = {
                    "events": total, "bad": bad,
                    "burn": burns, "status": status,
                }
            out["objectives"].append(entry)
        return out


def _burns(o: Objective, events, now: float) -> dict:
    """{window_s: burn | None} over one series — None when the window
    holds fewer than ``o.min_events`` events (no traffic is not a
    violation, and a near-empty window is no sample: one bad block on
    a freshly started peer must not read as burn ≥ 1)."""
    out: dict = {}
    floor = max(1, o.min_events)
    for w in o.windows:
        lo = now - w
        total = bad = 0
        for t, good in reversed(events):
            if t < lo:
                break
            total += 1
            if not good:
                bad += 1
        out[w] = (bad / total / o.budget) if total >= floor else None
    return out


def _wlabel(w: float) -> str:
    return f"{int(w)}s" if float(w).is_integer() else f"{w}s"


# -- endorse-side objectives (the sign lane's SLO feed) ----------------------

#: the default endorse objective pair a peer arms when it runs BOTH an
#: SLO spec and the sign lane (peer/node.py): ``endorse:latency`` —
#: good = a sign request waited ≤ ms in the batcher's coalescing
#: window before its device flush — and ``endorse_busy:busy`` — good =
#: the request was admitted rather than bounced with SignBusy.  Both
#: ride the dedicated ``endorse`` channel so the commit-path latency
#: series stays undiluted, and both surface in ``/slo`` and
#: :meth:`SloEngine.burns` like any other objective (the autopilot's
#: burn map carries them under the ``endorse`` channel).
DEFAULT_ENDORSE_SLOS = (
    "endorse:latency:ms=25:channel=endorse;"
    "endorse_busy:busy:pct=5:channel=endorse"
)

ENDORSE_CHANNEL = "endorse"


def endorse_observer(engine: SloEngine):
    """→ the ``SignBatcher.observer`` callable that classifies the
    sign lane's per-request telemetry — the same wait values feeding
    the ``sign_batch_wait_seconds`` histogram, and the same admission
    edges feeding ``sign_busy_total`` — into the engine's endorse
    objectives.  Objectives are resolved at CALL time, so a
    ``set_objectives`` rotation never strands a stale closure.

    Contract: ``observer(wait_ms: float | None, busy: bool)`` — BUSY
    bounces carry ``wait_ms=None`` (a bounced request has no wait
    sample; it is not a latency event, exactly like the tracer feed's
    BUSY exclusion)."""

    def observer(wait_ms, busy):
        for o in engine.objectives:
            if o.channel != ENDORSE_CHANNEL:
                continue
            if o.kind == "busy":
                engine.record(o, ENDORSE_CHANNEL, good=not busy)
            elif not busy and wait_ms is not None:
                engine.record(o, ENDORSE_CHANNEL,
                              good=wait_ms <= o.ms)

    return observer


# -- commit-path objectives (the tx-flow journal's SLO feed) -----------------

#: the default commit objective pair a peer arms when it runs BOTH an
#: SLO spec and the tx-flow journal (peer/node.py): ``commit_e2e:
#: latency`` — good = a completed flow's end-to-end wall (first
#: milestone → state-apply visibility) came in under ms — and
#: ``commit_valid:busy`` — good = the tx validated VALID (the "bad
#: event" is an invalidated tx, exactly like a bounced sign request).
#: Unlike the per-block tracer feed, these are CLIENT-VISIBLE
#: latencies: one event per transaction, measured to the instant the
#: write became readable, so the autopilot's burn-rate signals track
#: what a user experiences rather than a per-block proxy.  They ride
#: the dedicated ``commit`` channel next to ``endorse``.
DEFAULT_COMMIT_SLOS = (
    "commit_e2e:latency:ms=1000:channel=commit;"
    "commit_valid:busy:pct=5:channel=commit"
)

COMMIT_CHANNEL = "commit"


def commit_feed(engine: SloEngine):
    """→ the ``FlowJournal.slo_feed`` callable that classifies each
    completed tx flow into the engine's commit objectives.  Contract:
    ``feed(e2e_s: float, valid: bool, n: int = 1)`` — called outside
    the journal lock; ``n`` > 1 batches the journal's per-block cohort
    publish (every orderer-side tx of a block shares one e2e/verdict)
    into n identical events.  Objectives are resolved at CALL time, so
    a ``set_objectives`` rotation never strands a stale closure (same
    discipline as :func:`endorse_observer`)."""

    def feed(e2e_s, valid, n=1):
        e2e_ms = float(e2e_s) * 1000.0
        for o in engine.objectives:
            if o.channel != COMMIT_CHANNEL:
                continue
            good = bool(valid) if o.kind == "busy" else e2e_ms <= o.ms
            for _ in range(int(n)):
                engine.record(o, COMMIT_CHANNEL, good)

    return feed


_global = SloEngine()
_attached = False


def global_engine() -> SloEngine:
    return _global


def configure(spec: str | None = None, objectives=None) -> SloEngine:
    """Arm the process-global engine (the nodeconfig ``slos`` knob
    lands here) and attach it to the process-global tracer's
    finished-block stream.  An empty spec detaches nothing — the
    listener is a no-op with no objectives."""
    global _attached
    if objectives is None:
        objectives = parse_slos(spec or "")
    _global.set_objectives(objectives)
    if not _attached:
        from fabric_tpu.observe.tracer import global_tracer

        global_tracer().add_listener(_global.on_block)
        _attached = True
    return _global

"""Black-box incident recorder: one bundle per incident, written at
the moment the incident EDGE fires.

The time-series sampler (observe/timeseries.py) keeps the trailing
trails; this module decides *when a moment matters* and freezes
everything diagnostic about it into one JSON bundle — so the first
overload on a real accelerator round explains itself instead of
leaving an operator to reconstruct it from whatever was scraped.

Incident edges (each calls :func:`notify`, which is one module
attribute read when no recorder is armed):

* ``degrade_latch`` — DeviceLaneGuard latches the CPU fallback
  (peer/degrade.py);
* ``autopilot_shed`` — the traffic autopilot puts a tenant in shed
  mode (control/autopilot.py);
* ``slo_fast_burn`` — an SLO series trips its fast-burn WARN
  (observe/slo.py);
* ``pipeline_fail_closed`` — a CommitPipeline stage exception fails
  the pipe closed (peer/pipeline.py);
* ``injected_crash`` — a FaultPlan ``crash`` fault is about to
  ``os._exit``: the recorder's last-gasp hook (``faults.on_crash`` —
  the one edge atexit can never see) dumps the bundle synchronously
  before the process dies, and an ``atexit`` handler additionally
  flushes a final ``fault_stats_at_exit`` bundle when an armed chaos
  plan fired during a process that otherwise recorded nothing.

Bundle anatomy (sections resolved lazily from the process globals, so
arming order never matters): the incident ``kind`` + ``detail``, the
trailing metric series from the sampler, recent trace trees from
every flight-recorder namespace, the autopilot decision log, the
sidecar scheduler's ``stats()``, the SLO burn snapshot, and the fault
plan's injection stats.

Bounded on every axis: bundles are rate-limited per kind
(``min_interval_s``), size-bounded (over ``max_bytes`` the heaviest
sections are dropped, named in ``truncated``), and both the in-memory
index and the on-disk files keep only the newest ``max_bundles``.

Default OFF: nothing is constructed until :func:`configure` arms the
recorder (the nodeconfig ``blackbox_dir`` knob, or the flight-data
recorder arming it alongside the sampler), and every edge's
``notify`` call costs one global read + None check when unarmed.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

_log = logging.getLogger("fabric_tpu.observe.blackbox")

#: bundles retained (memory ring AND on-disk files)
DEFAULT_MAX_BUNDLES = 16

#: seconds between bundles of the SAME kind — an incident storm (a
#: latch that flaps, a shed per tick) must not bury the first bundle
#: under near-identical successors
DEFAULT_MIN_INTERVAL_S = 30.0

#: serialized-bundle size cap; over it, heavy sections drop in
#: _DROP_ORDER until the bundle fits
DEFAULT_MAX_BYTES = 1_500_000

#: trace trees shipped per namespace
TRACE_TREES_PER_NS = 4

#: points of each metric series frozen into a bundle
SERIES_POINTS = 64

_DROP_ORDER = ("traces", "vitals", "launches", "tx_flow", "exemplars", "slo",
               "scheduler", "autopilot")

#: launch-ledger rows frozen into a bundle
LEDGER_ROWS = 8


class BlackBox:
    """See module docstring.  ``record`` is synchronous and contained
    by every caller (incidents are rare; the dump is off every hot
    path by construction)."""

    def __init__(self, out_dir: str = "",
                 max_bundles: int = DEFAULT_MAX_BUNDLES,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 sampler=None, tracer=None, scheduler=None,
                 autopilot=None, slo=None, registry=None,
                 commit_source=None, clock=time.monotonic):
        self.out_dir = str(out_dir or "")
        self.max_bundles = max(1, int(max_bundles))
        self.min_interval_s = float(min_interval_s)
        self.max_bytes = int(max_bytes)
        # explicit sources win; None = resolve the process global at
        # record time (a recorder armed before the autopilot still
        # captures its decision log)
        self._sampler = sampler
        self._tracer = tracer
        self.scheduler = scheduler
        self._autopilot = autopilot
        self._slo = slo
        # commit-engine postmortem source: anything with report() →
        # per-channel apply-queue stats + applied-vs-appended heights
        # (PeerNode wires its channels; absent on engine-less hosts)
        self.commit_source = commit_source
        self.clock = clock
        self._lock = threading.Lock()
        self._bundles: deque = deque(maxlen=self.max_bundles)
        self._files: deque = deque()
        self._last: dict[str, float] = {}
        # resume numbering after a restart: the recorder exists for
        # crash-then-restart flows, and a fresh process restarting at
        # seq 1 would overwrite the crashed run's postmortem evidence
        # (and never prune prior-run files against max_bundles)
        self._seq = 0
        if self.out_dir:
            try:
                prior = sorted(
                    (int(name.split("-")[1]),
                     os.path.join(self.out_dir, name))
                    for name in os.listdir(self.out_dir)
                    if name.startswith("blackbox-")
                    and name.endswith(".json")
                    and name.split("-")[1].isdigit()
                )
                if prior:
                    self._seq = prior[-1][0]
                    self._files.extend(p for _s, p in prior)
            except OSError:
                pass  # dir not created yet — _write makes it
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._registry = registry
        self._bundle_ctr = registry.counter(
            "blackbox_bundles_total",
            "black-box incident bundles recorded by kind",
        )
        self._limited_ctr = registry.counter(
            "blackbox_rate_limited_total",
            "black-box incidents suppressed by the per-kind rate limit",
        )

    # -- source resolution (lazy: process globals) -------------------------

    def _sources(self):
        sampler = self._sampler
        if sampler is None:
            from fabric_tpu.observe import timeseries

            sampler = timeseries.global_sampler()
        tracer = self._tracer
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        autopilot = self._autopilot
        if autopilot is None:
            from fabric_tpu.control import global_autopilot

            autopilot = global_autopilot()
        slo = self._slo
        if slo is None:
            from fabric_tpu.observe.slo import global_engine

            slo = global_engine()
        from fabric_tpu.observe import ledger as _ledger
        from fabric_tpu.observe import txflow as _txflow

        return (sampler, tracer, autopilot, slo,
                _ledger.global_ledger(), _txflow.global_journal())

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **detail) -> dict | None:
        """Build + store one incident bundle; None when the per-kind
        rate limit suppressed it.  Every section is independently
        contained — a broken source yields an absent section, never a
        lost bundle."""
        now = self.clock()
        with self._lock:
            last = self._last.get(kind, float("-inf"))
            if now - last < self.min_interval_s:
                limited = True
            else:
                limited = False
                self._last[kind] = now
                self._seq += 1
                seq = self._seq
        if limited:
            self._limited_ctr.add(1, kind=kind)
            return None
        bundle = self._build(kind, detail, now, seq)
        with self._lock:
            self._bundles.append(bundle)
        self._bundle_ctr.add(1, kind=kind)
        path = self._write(bundle)
        _log.warning(
            "black-box bundle #%d recorded for incident %r%s",
            seq, kind, f" -> {path}" if path else "",
        )
        return bundle

    def _build(self, kind: str, detail: dict, now: float,
               seq: int) -> dict:
        sampler, tracer, autopilot, slo, launches, txflow = self._sources()
        bundle: dict = {
            "seq": seq,
            "kind": kind,
            "t_s": round(now, 3),
            "wall_s": round(time.time(), 3),
            "detail": {k: _jsonable(v) for k, v in detail.items()},
        }
        sections: dict = {}

        def grab(name, fn):
            try:
                sections[name] = fn()
            except Exception as e:
                sections[name] = None
                _log.debug("blackbox %s section failed: %s", name, e)

        if sampler is not None:
            grab("vitals", lambda: sampler.series(points=SERIES_POINTS))
        if tracer is not None and tracer.enabled:
            grab("traces", lambda: {
                ns or "_": tracer.blocks(TRACE_TREES_PER_NS, ns=ns)
                for ns in tracer.namespaces()
            })
        if autopilot is not None:
            grab("autopilot", autopilot.report)
        if launches is not None:
            # the device-time ledger: per-kernel decomposition + the
            # last few raw rows — the "was device_wait a compile?"
            # question answered inside the postmortem itself
            grab("launches", lambda: launches.report(rows=LEDGER_ROWS))
        if txflow is not None:
            # the per-tx flow journal: stage decomposition + the last
            # few completed flows — "where did the p99 tx spend its
            # second?" answered inside the postmortem itself
            grab("tx_flow", lambda: txflow.report(rows=LEDGER_ROWS))
        if sampler is not None or launches is not None:
            from fabric_tpu.ops_metrics import exemplars_report

            grab("exemplars",
                 lambda: exemplars_report(self._registry) or None)
        if self.scheduler is not None:
            grab("scheduler", self.scheduler.stats)
        if self.commit_source is not None:
            # the decoupled committer's last word: how far state apply
            # trailed the appended chain when the incident fired
            grab("commit_engine", self.commit_source.report)
        if slo is not None and getattr(slo, "objectives", ()):
            grab("slo", slo.report)
        from fabric_tpu import faults

        plan = faults.plan()
        if plan is not None:
            grab("faults", plan.stats)
        bundle.update(
            {k: v for k, v in sections.items() if v is not None}
        )
        return self._bound(bundle)

    def _bound(self, bundle: dict) -> dict:
        """Enforce ``max_bytes``: drop the heaviest sections in a
        fixed order until the serialized bundle fits, naming what was
        dropped so a truncated bundle is honest about it."""
        dropped = []
        for name in ("",) + _DROP_ORDER:
            if name:
                if name not in bundle:
                    continue
                bundle.pop(name)
                dropped.append(name)
                bundle["truncated"] = list(dropped)
            try:
                size = len(json.dumps(bundle))
            except (TypeError, ValueError):
                # a non-serializable detail slipped in: stringify it
                bundle["detail"] = {
                    k: str(v) for k, v in bundle.get("detail", {}).items()
                }
                size = len(json.dumps(bundle))
            if size <= self.max_bytes:
                break
        return bundle

    def _write(self, bundle: dict) -> str | None:
        if not self.out_dir:
            return None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"blackbox-{bundle['seq']:04d}-{bundle['kind']}.json",
            )
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1)
            with self._lock:
                self._files.append(path)
                doomed = []
                while len(self._files) > self.max_bundles:
                    doomed.append(self._files.popleft())
            for old in doomed:
                try:
                    os.remove(old)
                except OSError:
                    pass  # already gone — the bound is best-effort
            return path
        except OSError as e:
            _log.warning("black-box bundle write failed: %s", e)
            return None

    # -- readers (the /vitals incident index) ------------------------------

    def bundles(self) -> list[dict]:
        """Index entries (newest last): seq/kind/time + sizes, never
        the full payloads."""
        with self._lock:
            bundles = list(self._bundles)
        out = []
        for b in bundles:
            out.append({
                "seq": b["seq"],
                "kind": b["kind"],
                "t_s": b["t_s"],
                "wall_s": b.get("wall_s"),
                "detail": b.get("detail", {}),
                "sections": sorted(
                    k for k in b
                    if k in ("vitals", "traces", "autopilot",
                             "scheduler", "slo", "faults", "launches",
                             "tx_flow", "exemplars", "commit_engine")
                ),
                "truncated": b.get("truncated", []),
            })
        return out

    def bundle(self, seq: int) -> dict | None:
        with self._lock:
            for b in self._bundles:
                if b["seq"] == seq:
                    return b
        return None


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


# -- process-global handle + the incident-edge hook --------------------------

_global: BlackBox | None = None
_hooks_installed = False
#: refcount for component lifecycles (acquire/release) — colocated
#: nodes share ONE recorder and only the last release disarms it
_refs = 0


def global_blackbox() -> BlackBox | None:
    return _global


def acquire(**kw) -> BlackBox:
    """Refcounted arming (PeerNode start/stop pairs this with
    :func:`release`): the first acquire builds the recorder with its
    ``configure`` kwargs; later acquires REUSE the live instance
    (first-arm wins for out_dir/source wiring — replacing it would
    discard the first holder's incident index), and only the last
    release disarms."""
    global _refs
    bb = _global if _global is not None else configure(**kw)
    _refs += 1
    return bb


def release() -> None:
    """Drop one :func:`acquire` hold; the last one out disarms."""
    global _refs
    if _refs > 0:
        _refs -= 1
        if _refs == 0:
            configure(enabled=False)


def notify(kind: str, **detail) -> None:
    """The incident-edge hook: one global read + None check when no
    recorder is armed; contained — an edge must never die of its own
    diagnostics."""
    bb = _global
    if bb is None:
        return
    try:
        bb.record(kind, **detail)
    except Exception as e:
        _log.warning("black-box record for %r failed: %s", kind, e)


def _on_injected_crash(point: str) -> None:
    """``faults.on_crash`` hook: last-gasp dump before ``os._exit``."""
    bb = _global
    if bb is not None:
        bb.record("injected_crash", point=point)


def _on_interpreter_exit() -> None:
    """atexit: a chaos-armed process that fired faults but recorded no
    bundle still leaves ONE final stats bundle behind (a crashed child
    never gets here — that is what the pre-crash hook is for)."""
    bb = _global
    if bb is None:
        return
    try:
        from fabric_tpu import faults

        plan = faults.plan()
        if plan is None or plan.fired() == 0:
            return
        with bb._lock:
            recorded = len(bb._bundles)
        if recorded == 0:
            bb.record("fault_stats_at_exit")
    except Exception:  # fabtpu: noqa(FT005)
        pass  # interpreter teardown: nothing left to warn with


def configure(out_dir: str = "",
              max_bundles: int = DEFAULT_MAX_BUNDLES,
              min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
              max_bytes: int = DEFAULT_MAX_BYTES,
              sampler=None, tracer=None, scheduler=None,
              autopilot=None, slo=None, registry=None,
              commit_source=None, clock=time.monotonic,
              enabled: bool = True,
              ) -> BlackBox | None:
    """Arm (or, with ``enabled=False``, disarm) the process-global
    recorder — the nodeconfig ``blackbox_dir`` knob lands here.  The
    crash hook and the atexit flush install once per process.
    Disarming zeroes the acquire refcount (the hard OFF)."""
    global _global, _hooks_installed, _refs
    if not enabled:
        _refs = 0
        _global = None
        return None
    _global = BlackBox(
        out_dir=out_dir, max_bundles=max_bundles,
        min_interval_s=min_interval_s, max_bytes=max_bytes,
        sampler=sampler, tracer=tracer, scheduler=scheduler,
        autopilot=autopilot, slo=slo, registry=registry,
        commit_source=commit_source, clock=clock,
    )
    if not _hooks_installed:
        import atexit

        from fabric_tpu import faults

        faults.on_crash(_on_injected_crash)
        atexit.register(_on_interpreter_exit)
        _hooks_installed = True
    return _global

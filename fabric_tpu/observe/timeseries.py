"""Metrics time-series sampler: the flight-data recorder's trails.

Every observability surface before this module is point-in-time:
``/metrics`` renders the registry *now*, ``/slo`` and ``/autopilot``
report the current burn and knob vector, and the tracer's flight
recorder holds individual block trees.  A degrade latch, a shed
incident, or a bench regression therefore leaves no history to
attribute unless a human was polling at the right moment.
:class:`MetricsSampler` closes that gap: a periodic walker over the
metrics :class:`~fabric_tpu.ops_metrics.Registry` that records, per
metric and label variant, a bounded ring of ``(t, value)`` points —
the trailing series ``/vitals`` serves, the black-box recorder
(observe/blackbox.py) snapshots into incident bundles, and
``FABTPU_BENCH_VITALS`` dumps into BENCH_*.json extras.

Delta semantics per metric kind (raw monotones are useless trails):

* **counter** — each point is the DELTA since the previous sample
  (``rate()`` at read time divides by the sample spacing), so a
  trail reads as traffic, not as an ever-growing line.  A counter
  reset (process restart behind the same registry object cannot
  happen, but a negative delta is clamped) records the new raw value.
* **gauge** — the raw value (gauges are levels already).
* **histogram** — per-interval ``{n, sum}`` deltas plus an
  approximate interval p99 read off the BUCKET deltas (the smallest
  bucket bound covering 99% of the interval's observations), so a
  latency histogram's trail shows *this interval's* tail, not the
  lifetime-cumulative one.

Locking discipline: one sample pass takes the registry lock only to
copy the metric table (``Registry.metrics()``), then each
instrument's own ``snapshot()`` — never longer than a snapshot copy,
exactly the ``render()`` contract.  The sampler's own series dict is
guarded by its own lock (readers copy under it).

Default OFF everywhere: ``interval_s=0`` means no sampler thread
exists and :func:`configure` leaves the process-global handle None —
tier-1/CPU hosts and the unarmed hot path are unchanged.  Like the
SLO engine and the autopilot, the clock is injectable and tests
drive :meth:`MetricsSampler.sample` directly.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque

_log = logging.getLogger("fabric_tpu.observe.vitals")

#: default points retained per (metric, label-variant) series — at the
#: default 5s interval this is a 20-minute trail
DEFAULT_RETENTION = 240

#: default seconds between sample passes when armed
DEFAULT_INTERVAL_S = 5.0


class _Series:
    """One (metric, label variant) trail."""

    __slots__ = ("kind", "points", "last")

    def __init__(self, kind: str, retention: int):
        self.kind = kind                      # counter|gauge|histogram
        self.points: deque = deque(maxlen=retention)  # (t, value)
        # previous raw reading (counter float / histogram dict) for
        # the delta computation; gauges keep None
        self.last = None


def _hist_point(prev: dict | None, cur: dict, buckets: tuple) -> dict:
    """Interval delta of one histogram variant: {n, sum, p99} where
    p99 is the smallest bucket bound covering 99% of THIS interval's
    observations (None when the interval saw nothing)."""
    if prev is None:
        dn = cur["count"]
        dsum = cur["sum"]
        dcounts = list(cur["counts"])
    else:
        dn = cur["count"] - prev["count"]
        dsum = cur["sum"] - prev["sum"]
        dcounts = [c - p for c, p in zip(cur["counts"], prev["counts"])]
    if dn <= 0:
        return {"n": 0, "sum": 0.0, "p99": None}
    want = math.ceil(0.99 * dn)
    p99 = None
    for b, c in zip(buckets, dcounts):
        if c >= want:  # counts are cumulative per bucket
            p99 = None if math.isinf(b) else b
            break
    return {"n": dn, "sum": round(dsum, 9), "p99": p99}


class MetricsSampler:
    """See module docstring.  ``start()`` runs a daemon sample thread;
    tests drive :meth:`sample` directly with an injected clock."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 retention: int = DEFAULT_RETENTION, registry=None,
                 clock=time.monotonic):
        if interval_s < 0:
            raise ValueError(f"interval_s must be >= 0, got {interval_s}")
        if retention < 1:
            raise ValueError(f"retention must be >= 1, got {retention}")
        self.interval_s = float(interval_s)
        self.retention = int(retention)
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self.registry = registry
        self.clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def configure(self, interval_s: float | None = None,
                  retention: int | None = None) -> None:
        """Re-knob the sampler; a retention change RESIZES every live
        ring in place (truncated to the newest points)."""
        if interval_s is not None:
            if interval_s < 0:
                raise ValueError(
                    f"interval_s must be >= 0, got {interval_s}"
                )
            self.interval_s = float(interval_s)
        if retention is not None:
            if retention < 1:
                raise ValueError(
                    f"retention must be >= 1, got {retention}"
                )
            with self._lock:
                self.retention = int(retention)
                for s in self._series.values():
                    s.points = deque(
                        list(s.points)[-self.retention:],
                        maxlen=self.retention,
                    )

    def start(self) -> "MetricsSampler":
        if self._thread is not None or self.interval_s <= 0:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception as e:  # the trail must never die
                    _log.warning("vitals sample pass failed: %s", e)

        self._thread = threading.Thread(
            target=run, name="fabtpu-vitals", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- sampling ----------------------------------------------------------

    def sample(self) -> float:
        """One pass over the registry: append one point per known
        label variant.  Returns the sample timestamp."""
        from fabric_tpu.ops_metrics import Counter, Gauge, Histogram

        now = self.clock()
        # the registry lock is held only inside metrics()/snapshot();
        # everything below runs on already-copied data
        table = self.registry.metrics()
        with self._lock:
            for name, m in table:
                if isinstance(m, Counter):
                    for key, raw in m.snapshot().items():
                        s = self._get_series(name, key, "counter")
                        prev = s.last
                        s.last = raw
                        delta = raw if prev is None else raw - prev
                        if delta < 0:  # reset: record the new level
                            delta = raw
                        s.points.append((now, round(delta, 9)))
                elif isinstance(m, Gauge):
                    for key, raw in m.snapshot().items():
                        s = self._get_series(name, key, "gauge")
                        s.points.append((now, raw))
                elif isinstance(m, Histogram):
                    for key, raw in m.snapshot().items():
                        s = self._get_series(name, key, "histogram")
                        prev = s.last
                        s.last = raw
                        s.points.append(
                            (now, _hist_point(prev, raw, m.buckets))
                        )
            self._samples += 1
        return now

    def _get_series(self, name: str, key: tuple, kind: str) -> _Series:
        s = self._series.get((name, key))
        if s is None:
            s = self._series[(name, key)] = _Series(kind, self.retention)
        return s

    # -- readers -----------------------------------------------------------

    @staticmethod
    def _label_str(key: tuple) -> str:
        return ",".join(f"{k}={v}" for k, v in key) or "_"

    def series(self, metric: str | None = None,
               points: int | None = None) -> dict:
        """{metric: {label_str: {kind, points: [[t, value], ...]}}} —
        the full trails (``/vitals?metric=N`` and the bench extras
        dump).  ``points`` truncates each series to its newest N."""
        with self._lock:
            snap = {
                (name, key): (s.kind, list(s.points))
                for (name, key), s in self._series.items()
                if metric is None or name == metric
            }
        out: dict = {}
        for (name, key), (kind, pts) in sorted(snap.items()):
            if points is not None:
                pts = pts[-points:]
            out.setdefault(name, {})[self._label_str(key)] = {
                "kind": kind,
                "points": [
                    [round(t, 3), v] for t, v in pts
                ],
            }
        return out

    def rate(self, metric: str, window: int = 12, **labels) -> float | None:
        """Mean per-second rate of one COUNTER variant over its newest
        ``window`` points, or None (unknown series / too few points /
        not a counter).  The read-time division keeps stored points as
        plain deltas."""
        from fabric_tpu.ops_metrics import _label_key

        with self._lock:
            s = self._series.get((metric, _label_key(labels)))
            if s is None or s.kind != "counter":
                return None
            pts = list(s.points)[-max(2, window):]
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return sum(v for _t, v in pts[1:]) / dt

    def report(self, spark: int = 24) -> dict:
        """JSON-able index (the ``/vitals`` landing payload): per
        metric and label variant, a sparkline-style summary — the
        newest ``spark`` scalar values (histograms contribute their
        interval p99s), plus last/min/max over the retained ring."""
        with self._lock:
            snap = {
                (name, key): (s.kind, list(s.points))
                for (name, key), s in self._series.items()
            }
            samples = self._samples
        metrics: dict = {}
        for (name, key), (kind, pts) in sorted(snap.items()):
            if kind == "histogram":
                scalars = [
                    p["p99"] for _t, p in pts if p["p99"] is not None
                ]
            else:
                scalars = [v for _t, v in pts]
            entry = {
                "kind": kind,
                "n_points": len(pts),
                "spark": [round(v, 6) for v in scalars[-spark:]],
            }
            if scalars:
                entry["last"] = round(scalars[-1], 6)
                entry["min"] = round(min(scalars), 6)
                entry["max"] = round(max(scalars), 6)
            if kind == "histogram" and pts:
                entry["last_interval"] = pts[-1][1]
            metrics.setdefault(name, {})[self._label_str(key)] = entry
        return {
            "interval_s": self.interval_s,
            "retention": self.retention,
            "samples": samples,
            "series_count": len(snap),
            "metrics": metrics,
        }


# -- process-global handle (what /vitals serves by default) ------------------

_global: MetricsSampler | None = None
#: refcount for component lifecycles (acquire/release): the sampler
#: stops only when the LAST colocated holder releases — neither the
#: creator nor a later arriver stopping first may strand the survivor
_refs = 0


def global_sampler() -> MetricsSampler | None:
    return _global


def acquire(interval_s: float,
            retention: int = DEFAULT_RETENTION,
            registry=None, clock=time.monotonic,
            ) -> MetricsSampler | None:
    """Refcounted arming (PeerNode start/stop pairs this with
    :func:`release`): the first acquire builds the sampler, later
    acquires REUSE it untouched — first-arm wins for interval and
    retention, because reconfiguring would truncate the first
    holder's live rings and change its cadence under it — and only
    the last release tears it down.  ``interval_s <= 0`` returns None
    without touching the count."""
    global _refs
    if interval_s <= 0:
        return None
    s = _global
    if s is None:
        s = configure(interval_s, retention, registry=registry,
                      clock=clock)
    _refs += 1
    return s


def release() -> None:
    """Drop one :func:`acquire` hold; the last one out disarms."""
    global _refs
    if _refs > 0:
        _refs -= 1
        if _refs == 0:
            configure(0)


def configure(interval_s: float = 0.0,
              retention: int = DEFAULT_RETENTION,
              registry=None, clock=time.monotonic,
              start: bool = True) -> MetricsSampler | None:
    """Arm (or disarm) the process-global sampler — the nodeconfig
    ``vitals_interval_s`` / ``vitals_retention`` knobs land here.
    ``interval_s <= 0`` stops and clears any armed sampler (and zeroes
    the acquire refcount — the hard OFF) and returns None: the
    recorder's OFF state really is no thread and no state."""
    global _global, _refs
    if interval_s <= 0:
        _refs = 0
        old, _global = _global, None
        if old is not None:
            old.stop()
        return None
    if _global is not None:
        _global.configure(interval_s=interval_s, retention=retention)
        if start:
            _global.start()
        return _global
    _global = MetricsSampler(
        interval_s=interval_s, retention=retention, registry=registry,
        clock=clock,
    )
    if start:
        _global.start()
    return _global

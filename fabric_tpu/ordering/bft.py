"""BFT consensus for the ordering service (the SmartBFT-consenter
analog, orderer/consensus/smartbft/chain.go — a view-based PBFT with
signed messages, running 3f+1 nodes and tolerating f byzantine).

The reference outsources BFT to the hyperledger-labs/SmartBFT library
and wraps it in a Chain that assembles proposals into blocks and
verifies quorum signatures on deliver (chain.go:360, verifier.go).
This module implements the consensus core directly — same stance as
ordering/raft.py for the CFT case:

* **Normal case** (PBFT): leader(view) assigns sequence numbers and
  broadcasts PRE-PREPARE(view, seq, payload); replicas PREPARE on a
  valid pre-prepare; 2f matching PREPAREs → COMMIT; 2f+1 COMMITs →
  apply.  Entries apply strictly in sequence order.
* **Authentication**: every message carries an ECDSA-P256 signature by
  the sending node over the canonical message bytes; receivers verify
  against the cluster's known certs (the consenter-set identities from
  channel config).  Unsigned/forged traffic is dropped — this is what
  upgrades crash-fault raft to byzantine fault tolerance.
* **View change**: replicas that see no progress on pending requests
  start VIEW-CHANGE(v+1) carrying their prepared set; 2f+1 view-change
  messages install the new view, whose leader re-proposes the highest
  prepared-but-uncommitted entries (PBFT §4.4 simplified for
  sequential commitment).
* **WAL**: applied entries persist via ordering.raft.WAL (term=view,
  index=seq) for restart recovery.

Interface-compatible with RaftNode (state/leader_id/propose/handle/
wait_applied/start/stop), so OrderingChain swaps consenters via a
constructor flag — the consensus.Chain SPI seam of the reference
(orderer/consensus/consensus.go:57).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
from dataclasses import dataclass, field

from fabric_tpu.ordering.raft import WAL, Entry

log = logging.getLogger("fabric_tpu.ordering.bft")

PRE_PREPARE = "bft_pre_prepare"
PREPARE = "bft_prepare"
COMMIT = "bft_commit"
VIEW_CHANGE = "bft_view_change"
NEW_VIEW = "bft_new_view"


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def _signable(msg: dict) -> bytes:
    """Canonical bytes covered by the message signature."""
    core = {k: v for k, v in msg.items() if k not in ("sig", "from_cert")}
    return json.dumps(core, sort_keys=True).encode()


@dataclass
class _SlotState:
    payload: bytes | None = None
    pre_prepared: bool = False
    view: int = -1                                    # pre-prepare's view
    prepares: dict = field(default_factory=dict)      # node -> digest
    prepare_msgs: dict = field(default_factory=dict)  # node -> signed msg
    commits: dict = field(default_factory=dict)       # node -> (view, digest)
    commit_msgs: dict = field(default_factory=dict)   # node -> signed msg
    committed: bool = False


class BFTNode:
    """One cluster member's consensus state machine for one channel."""

    def __init__(self, node_id: str, peers: list[str], wal: WAL,
                 apply_cb, send_cb, signer=None, verifiers=None,
                 view_timeout: float = 2.0, catchup_cb=None,
                 catchup_gap: int = 8):
        """peers: ALL cluster node ids (including self).
        signer: SigningIdentity for outbound messages (None = unsigned
        dev mode, only acceptable in tests).
        verifiers: {node_id: Identity-like with .verify(msg, sig)}.
        catchup_cb(target_seq, view): the replica detected a sequence
        gap it cannot close from live traffic (messages ``catchup_gap``
        past its application point, or a new-view base beyond it) —
        the chain pulls the missing BLOCKS from cluster peers,
        verifies their 2f+1 attestations, and calls install_snapshot
        (the SmartBFT synchronizer.go:40 Sync analog)."""
        self.id = node_id
        self.peers = sorted(set(peers) | {node_id})
        self.n = len(self.peers)
        self.f = (self.n - 1) // 3
        self.quorum = 2 * self.f + 1
        self.wal = wal
        self.apply_cb = apply_cb
        self.send_cb = send_cb
        self.signer = signer
        self.verifiers = verifiers or {}
        self.view_timeout = view_timeout
        self.catchup_cb = catchup_cb
        self.catchup_gap = max(1, catchup_gap)

        self.view = 0
        # a compacted WAL restarts with everything <= snap_index
        # materialized by the chain already
        self.next_seq = wal.snap_index + 1  # leader's next sequence
        self.last_applied = wal.snap_index
        self.slots: dict[int, _SlotState] = {}
        self.view_changes: dict[int, dict] = {}  # new_view -> {node: vc}
        self._applied_digest: dict[int, str] = {}  # seq -> payload digest
        self._commit_proofs: dict[int, list] = {}  # seq -> quorum COMMITs
        self._applied_ev: dict[int, asyncio.Event] = {}
        self._progress_task: asyncio.Task | None = None
        self._pending_since: float | None = None
        self._stopped = True

    # -- identity/roles ----------------------------------------------------

    @property
    def leader_id(self) -> str:
        return self.peers[self.view % self.n]

    @property
    def state(self) -> str:
        return "leader" if self.leader_id == self.id else "follower"

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._stopped = False
        # recover applied entries from the WAL, RE-FIRING apply_cb for
        # each (the chain counts recovered batches and skips the ones
        # already materialized as blocks — same contract as raft replay)
        for e in self.wal.entries:
            if e.index == self.last_applied + 1:
                self.last_applied = e.index
                self.view = max(self.view, e.term)
                self.apply_cb(e)
        self.next_seq = self.last_applied + 1
        self._progress_task = asyncio.ensure_future(self._progress_loop())

    def stop(self):
        self._stopped = True
        if self._progress_task:
            self._progress_task.cancel()

    # -- outbound ----------------------------------------------------------

    def _sign(self, msg: dict) -> dict:
        if self.signer is not None:
            msg["sig"] = self.signer.sign(_signable(msg)).hex()
        return msg

    def _bcast(self, msg: dict):
        msg = self._sign(msg)
        for p in self.peers:
            if p != self.id:
                self.send_cb(p, msg)
        # loopback: a node is a voter in its own quorum
        self.handle(dict(msg), verified=True)

    def _verify(self, msg: dict) -> bool:
        sender = msg.get("from")
        if sender == self.id:
            # a NETWORK message claiming to be from this very node
            # (loopback passes verified=True and never lands here) —
            # e.g. a byzantine leader fabricating a prepare "by us"
            # inside a view-change certificate.  Verify against our own
            # identity instead of rubber-stamping.
            if self.signer is None:
                return True
            sig = msg.get("sig")
            if not sig:
                return False
            try:
                return self.signer.identity.verify(
                    _signable(msg), bytes.fromhex(sig)
                )
            except Exception:
                return False
        ver = self.verifiers.get(sender)
        if ver is None:
            # dev mode: no verifier registry → accept (tests);
            # production always configures the consenter identity set
            return not self.verifiers
        sig = msg.get("sig")
        if not sig:
            return False
        try:
            return ver.verify(_signable(msg), bytes.fromhex(sig))
        except Exception:
            return False

    # -- client entry ------------------------------------------------------

    def propose(self, payload: bytes) -> int | None:
        """Leader assigns the next sequence and drives agreement."""
        if self.state != "leader" or self._stopped:
            return None
        seq = self.next_seq
        self.next_seq += 1
        self._bcast({
            "type": PRE_PREPARE, "from": self.id, "view": self.view,
            "seq": seq, "payload": payload.hex(),
        })
        return seq

    async def wait_applied(self, seq: int, digest: str | None = None) -> bool:
        """Wait for seq to apply; with ``digest``, additionally confirm
        THE CALLER'S payload is what got applied — after a view change
        sequences are reassigned, and an ack for a different payload
        would make the client drop a tx that was never ordered."""
        if seq > self.last_applied:
            ev = self._applied_ev.setdefault(seq, asyncio.Event())
            await ev.wait()
        if digest is None:
            return True
        return self._applied_digest.get(seq) == digest

    # -- message handling --------------------------------------------------

    def handle(self, msg: dict, verified: bool = False):
        if self._stopped:
            return
        if not verified and not self._verify(msg):
            log.debug("%s: dropping unauthenticated %s from %s",
                      self.id, msg.get("type"), msg.get("from"))
            return
        t = msg.get("type")
        # schema guard: malformed fields from a byzantine sender must
        # be dropped, not allowed to raise mid-dispatch (the Step
        # stream handler would tear down on an escaped exception)
        if t in (PRE_PREPARE, PREPARE, COMMIT):
            if not isinstance(msg.get("seq"), int) or not isinstance(
                msg.get("view"), int
            ):
                return
        if t == PRE_PREPARE:
            self._on_pre_prepare(msg)
        elif t == PREPARE:
            self._on_prepare(msg)
        elif t == COMMIT:
            self._on_commit(msg)
        elif t == VIEW_CHANGE:
            self._on_view_change(msg)
        elif t == NEW_VIEW:
            self._on_new_view(msg)

    def _slot(self, seq: int) -> _SlotState:
        return self.slots.setdefault(seq, _SlotState())

    def _on_pre_prepare(self, msg):
        if msg["view"] != self.view or msg["from"] != self.leader_id:
            return
        seq = msg["seq"]
        if seq <= self.last_applied:
            return
        payload = bytes.fromhex(msg["payload"])
        # new-view re-proposal discipline: after a justified view
        # change, the first seqs are RESERVED for the certified
        # prepared entries every replica re-derived from the 2f+1
        # VIEW-CHANGEs (PBFT §4.4) — a new leader that substitutes a
        # different payload there (or drops one, shifting later
        # payloads into its slot) is refused
        exp = getattr(self, "_expected_repro", None)
        if exp:
            want = exp.get(seq)
            if want is not None:
                if want != _digest(payload):
                    log.warning(
                        "%s: view %d leader %s violated the new-view "
                        "re-proposal set at seq %d — refusing",
                        self.id, self.view, msg["from"], seq,
                    )
                    return
                del exp[seq]
        slot = self._slot(seq)
        if slot.pre_prepared and slot.payload != payload:
            return  # equivocating leader: keep the first, view change fixes
        slot.payload = payload
        slot.pre_prepared = True
        slot.view = self.view
        self._pending_since = self._pending_since or asyncio.get_event_loop().time()
        self._bcast({
            "type": PREPARE, "from": self.id, "view": self.view,
            "seq": seq, "digest": _digest(payload),
        })

    def _on_prepare(self, msg):
        if msg["view"] != self.view:
            return
        slot = self._slot(msg["seq"])
        slot.prepares[msg["from"]] = msg["digest"]
        slot.prepare_msgs[msg["from"]] = msg  # retained for VC certificates
        if slot.payload is None or slot.committed:
            return
        d = _digest(slot.payload)
        if sum(1 for v in slot.prepares.values() if v == d) >= self.quorum \
                and self.id not in slot.commits:
            commit = {
                "type": COMMIT, "from": self.id, "view": self.view,
                "seq": msg["seq"], "digest": d,
            }
            if self.signer is not None:
                # identity rides along (excluded from the signed bytes)
                # so deliver-side quorum verification can resolve the
                # sender without a consenter-identity registry
                commit["from_cert"] = self.signer.serialized.hex()
            self._bcast(commit)

    def _on_commit(self, msg):
        # commits are STORED regardless of view (a lagging replica must
        # not discard votes it can only count after catching up); the
        # PBFT committed predicate — 2f+1 commits matching the view the
        # slot was pre-prepared in — is enforced at counting time
        slot = self._slot(msg["seq"])
        slot.commits[msg["from"]] = (msg.get("view"), msg["digest"])
        slot.commit_msgs[msg["from"]] = msg
        self._try_apply()
        self._maybe_catchup(msg["from"], msg["seq"])

    def _maybe_catchup(self, sender: str, seq_seen: int) -> None:
        """Cluster traffic references sequences well past our
        application point while the next-in-line slot has no payload:
        the pre-prepares we're missing may be gone forever (view
        changes drop uncommitted slots; the WAL compacts), so pull
        the committed BLOCKS instead (synchronizer.go:40 Sync).

        The trigger needs f+1 DISTINCT consenters claiming such
        sequences — a single byzantine node must not be able to keep
        every replica running bogus pull tasks (the synchronizer's
        corroboration requirement).  The target is the (f+1)-th
        largest claim: at least one honest node vouches for it."""
        if self.catchup_cb is None:
            return
        claims = getattr(self, "_seq_claims", None)
        if claims is None:
            claims = self._seq_claims = {}
        claims[sender] = max(claims.get(sender, 0), seq_seen)
        vouched = self._vouched_seq()
        if vouched <= self.last_applied + self.catchup_gap:
            return
        nxt = self.slots.get(self.last_applied + 1)
        if nxt is not None and nxt.payload is not None:
            return  # live traffic can still close the gap
        self.catchup_cb(vouched - 1, self.view)

    def _vouched_seq(self) -> int:
        """The highest sequence at least one HONEST consenter has
        referenced: the (f+1)-th largest per-sender claim."""
        claims = getattr(self, "_seq_claims", {})
        tops = sorted(claims.values(), reverse=True)
        return tops[self.f] if len(tops) > self.f else 0

    def install_snapshot(self, index: int, term: int) -> None:
        """The chain materialized verified blocks through sequence
        ``index`` out-of-band (catch-up pull): fast-forward the
        consensus state so agreement resumes after it — the BFT mirror
        of RaftNode.install_snapshot."""
        if index <= self.last_applied:
            return
        self.wal.install_snapshot(index, term)
        self.view = max(self.view, term)
        self.last_applied = index
        self.next_seq = max(self.next_seq, index + 1)
        self._pending_since = None
        for seq in list(self.slots):
            if seq <= index:
                del self.slots[seq]
        for seq in [s for s in self._applied_ev if s <= index]:
            # waiters learn the seq applied; digest confirmation will
            # report False (the payload identity is unknown after a
            # block-level catch-up), which the broadcast path treats
            # as an unconfirmed ack — fail-safe for the client
            self._applied_ev.pop(seq).set()
        self._try_apply()  # buffered votes past the snapshot may apply
        # residual gap: a vouched-for sequence just above the snapshot
        # whose pre-prepare is gone stalls until traffic exceeds the
        # catchup gap again — re-pull NOW rather than sit blocks
        # behind while the channel is quiet
        vouched = self._vouched_seq()
        nxt = self.slots.get(self.last_applied + 1)
        if (
            self.catchup_cb is not None
            and vouched > self.last_applied
            and (nxt is None or nxt.payload is None)
        ):
            self.catchup_cb(vouched - 1, self.view)

    def _try_apply(self):
        while True:
            seq = self.last_applied + 1
            slot = self.slots.get(seq)
            if slot is None or slot.payload is None or slot.committed:
                return
            d = _digest(slot.payload)
            votes = [
                n for n, (v, dg) in slot.commits.items()
                if dg == d and v == slot.view
            ]
            if len(votes) < self.quorum:
                return
            slot.committed = True
            entry = Entry(term=slot.view, index=seq, data=slot.payload)
            # persist the quorum COMMIT proof BEFORE the WAL entry: on
            # restart the WAL replay re-materializes the block, and a
            # proof lost to a crash window would leave that block
            # unverifiable at every peer forever
            proof = [
                slot.commit_msgs[n] for n in votes if n in slot.commit_msgs
            ]
            self._persist_proof(seq, proof)
            self.wal.append([entry])
            self._applied_digest[seq] = d
            self._commit_proofs[seq] = proof
            if len(self._applied_digest) > 4096:
                for old in sorted(self._applied_digest)[:2048]:
                    del self._applied_digest[old]
                for old in sorted(self._commit_proofs)[:2048]:
                    self._commit_proofs.pop(old, None)
            self.last_applied = seq
            self._pending_since = None
            self.apply_cb(entry)
            ev = self._applied_ev.pop(seq, None)
            if ev:
                ev.set()

    def _proof_path(self, seq: int) -> str:
        import os

        d = os.path.join(self.wal.dir, "proofs")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{seq}.json")

    def _persist_proof(self, seq: int, proof: list) -> None:
        import os

        path = self._proof_path(seq)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(proof, f)
            f.flush()
            os.fsync(f.fileno())  # the WAL append that follows is
            # fsynced; the proof must be durable FIRST or a crash
            # window leaves a replayed block permanently unattestable
        os.replace(tmp, path)
        # prune far-stale proof files (blocks are materialized at
        # apply time, so anything this old is long since embedded)
        if seq > 8192 and seq % 512 == 0:
            import glob

            for old in glob.glob(os.path.join(self.wal.dir, "proofs", "*.json")):
                try:
                    if int(os.path.basename(old).split(".")[0]) < seq - 8192:
                        os.unlink(old)
                except (ValueError, OSError):
                    pass

    def update_peers(self, peers: list[str]) -> None:
        """Consenter-set change from a committed config block: refresh
        the membership and the derived fault/quorum thresholds."""
        self.peers = sorted(set(peers) | {self.id})
        self.n = len(self.peers)
        self.f = (self.n - 1) // 3
        self.quorum = 2 * self.f + 1
        # removed consenters' catch-up claims must not keep vouching
        claims = getattr(self, "_seq_claims", None)
        if claims:
            self._seq_claims = {
                k: v for k, v in claims.items() if k in self.peers
            }

    def commit_proof(self, seq: int) -> list | None:
        """The 2f+1 signed COMMIT messages that committed ``seq`` —
        the quorum attestation the block carries to peers (SmartBFT's
        signature aggregation, chain.go:360).  Survives restart via the
        WAL-side proof files (a WAL replay must re-materialize blocks
        WITH their attestation)."""
        got = self._commit_proofs.get(seq)
        if got is not None:
            return got
        try:
            with open(self._proof_path(seq)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- view change -------------------------------------------------------

    async def _progress_loop(self):
        """Replica-side failure detector: pending agreement with no
        progress for view_timeout → demand a view change."""
        while not self._stopped:
            try:
                await asyncio.sleep(self.view_timeout / 4)
                if self._pending_since is None:
                    continue
                now = asyncio.get_event_loop().time()
                if now - self._pending_since > self.view_timeout:
                    self._pending_since = now  # rate-limit re-sends
                    # escalate past consecutive dead leaders: each timer
                    # expiry targets one view further (PBFT's doubling
                    # timer serves the same liveness purpose)
                    self._vc_target = max(
                        getattr(self, "_vc_target", self.view), self.view
                    ) + 1
                    self._start_view_change(self._vc_target)
            except asyncio.CancelledError:
                return

    def note_client_request(self):
        """A client demand exists (follower got a broadcast): start the
        progress clock so a dead leader triggers a view change."""
        if self._pending_since is None:
            self._pending_since = asyncio.get_event_loop().time()

    def request_view_change(self):
        """Explicit trigger (e.g. broadcast timeout at a follower)."""
        self._start_view_change(self.view + 1)

    def _start_view_change(self, new_view: int):
        self._vc_sent = getattr(self, "_vc_sent", set())
        self._vc_sent.add(new_view)
        # only PREPARED entries (2f+1 matching signed PREPAREs — the
        # certificate) ride the view change: an uncertified claim must
        # not be able to override what another node already committed
        prepared = {}
        for seq, s in self.slots.items():
            if not (s.pre_prepared and seq > self.last_applied and s.payload):
                continue
            d = _digest(s.payload)
            cert = [m for m in s.prepare_msgs.values() if m.get("digest") == d]
            if len(cert) >= self.quorum:
                prepared[str(seq)] = {
                    "payload": s.payload.hex(), "view": self.view,
                    "cert": cert,
                }
        self._bcast({
            "type": VIEW_CHANGE, "from": self.id, "new_view": new_view,
            "last_applied": self.last_applied, "prepared": prepared,
        })

    def _cert_valid(self, seq: int, payload: bytes, cert: list) -> bool:
        """2f+1 distinct, correctly signed PREPAREs for (seq, digest)."""
        d = _digest(payload)
        senders = set()
        for m in cert:
            if not isinstance(m, dict) or m.get("type") != PREPARE:
                continue
            if m.get("seq") != seq or m.get("digest") != d:
                continue
            if m.get("from") in senders:
                continue
            # NO self bypass: a fabricated unsigned PREPARE claiming to
            # be "ours" must not strengthen a certificate (_verify
            # checks self-attributed messages against our own identity)
            if self._verify(m):
                senders.add(m.get("from"))
        return len(senders) >= self.quorum

    def _on_view_change(self, msg):
        nv = msg["new_view"]
        if nv <= self.view:
            return
        self.view_changes.setdefault(nv, {})[msg["from"]] = msg
        vcs = self.view_changes[nv]
        # PBFT liveness (§4.5.2): seeing f+1 distinct view-changes for
        # a higher view proves at least one honest node timed out —
        # join even if my own clock never started
        if len(vcs) > self.f and nv not in getattr(self, "_vc_sent", set()):
            self._start_view_change(nv)
        if len(vcs) >= self.quorum and self.peers[nv % self.n] == self.id:
            # I lead the new view: install + re-propose the certified
            # prepared entries; the NEW_VIEW carries the 2f+1 signed
            # VIEW-CHANGE messages as justification so every replica
            # re-derives (and will enforce) the same re-proposal set
            self._install_view(nv)
            base, repro = self._derive_reproposals(vcs.values())
            self._bcast({
                "type": NEW_VIEW, "from": self.id, "view": nv,
                "vcs": dict(vcs),
            })
            self.next_seq = base
            for _old_seq, payload in repro:
                s = self.next_seq
                self.next_seq += 1
                self._bcast({
                    "type": PRE_PREPARE, "from": self.id, "view": nv,
                    "seq": s, "payload": payload.hex(),
                })

    def _derive_reproposals(self, vcs) -> tuple:
        """→ (base_seq, certified prepared entries) a new view MUST
        re-propose: per sequence above the quorum's highest claimed
        last_applied, the highest-view entry backed by a valid 2f+1
        prepare certificate, in old-sequence order (PBFT §4.4).

        EVERYTHING here derives from the view-change set itself — never
        from this node's own last_applied — so the leader and every
        replica verifying the NEW_VIEW compute the SAME (base, repro)
        mapping even when their application states diverge.  The base
        is the (f+1)-th LARGEST claimed last_applied: at least one
        honest node vouches for it (a single byzantine consenter
        inflating its claim cannot move it), and sequential commitment
        makes every honestly-committed entry above it a certified
        prefix that re-lands on its original sequence numbers.  A node
        whose last_applied lags base has a gap it can only close by
        catch-up (see the raft follower-chain work)."""
        vcs = list(vcs)
        claims = sorted(
            (int(vc.get("last_applied", 0)) for vc in vcs), reverse=True
        )
        L = claims[self.f] if len(claims) > self.f else (
            claims[-1] if claims else 0
        )
        repro: dict[int, tuple[int, bytes]] = {}
        for vc in vcs:
            for seq_s, info in vc.get("prepared", {}).items():
                seq = int(seq_s)
                if seq <= L:
                    continue  # committed somewhere per the quorum claims
                try:
                    payload = bytes.fromhex(info["payload"])
                    cview = int(info.get("view", 0))
                except (KeyError, ValueError, TypeError):
                    continue
                if not self._cert_valid(seq, payload, info.get("cert", [])):
                    continue
                cur = repro.get(seq)
                if cur is None or cview > cur[0]:
                    repro[seq] = (cview, payload)
        return L + 1, [(seq, repro[seq][1]) for seq in sorted(repro)]

    def _on_new_view(self, msg):
        """Install a higher view ONLY on proof: the NEW_VIEW must carry
        2f+1 correctly signed VIEW-CHANGE messages for that view.  The
        replica re-derives the certified re-proposal set from them and
        _on_pre_prepare enforces that the new leader neither drops nor
        substitutes a certified prepared entry (reference: SmartBFT's
        view-change verification, orderer/consensus/smartbft/
        verifier.go; PBFT §4.4)."""
        v = msg["view"]
        if v <= self.view or msg["from"] != self.peers[v % self.n]:
            return
        valid = {}
        for node, vc in (msg.get("vcs") or {}).items():
            if not isinstance(vc, dict) or vc.get("type") != VIEW_CHANGE:
                continue
            if vc.get("from") != node or vc.get("new_view") != v:
                continue
            if self._verify(vc):
                valid[node] = vc
        if len(valid) < self.quorum:
            log.warning(
                "%s: NEW_VIEW %d from %s lacks a 2f+1 view-change "
                "justification — refusing to install",
                self.id, v, msg["from"],
            )
            return
        base, repro = self._derive_reproposals(valid.values())
        self._install_view(v)
        self._expected_repro = {
            base + off: _digest(payload)
            for off, (_seq, payload) in enumerate(repro)
        }
        if base > self.last_applied + 1 and self.catchup_cb is not None:
            # the quorum's claims prove sequences up to base-1 are
            # committed somewhere, and we missed them — the re-proposal
            # set will never include them, so block catch-up is the
            # ONLY way back (the gap the round-4 docstring documented)
            self.catchup_cb(base - 1, v)

    def _install_view(self, view: int):
        self.view = view
        self._vc_target = view
        self._pending_since = None
        # stale reservations from an earlier view change must not block
        # this view's sequences (set fresh by the new-view handler)
        self._expected_repro = {}
        # drop uncommitted slot votes from the old view (re-proposals
        # will rebuild them under the new view's sequences)
        for seq in list(self.slots):
            if seq > self.last_applied:
                del self.slots[seq]
        self.view_changes = {
            v: vcs for v, vcs in self.view_changes.items() if v > view
        }

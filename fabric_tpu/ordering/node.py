"""Orderer node assembly: registrar + RPC services.

The analog of orderer/common/server/main.go:69-222 plus the
multichannel registrar (registrar.go:93): one process hosts N
channels, each with its own raft chain; exposed services:

* ``Broadcast``  — submit an envelope to a channel (unary; non-leader
  answers 503 with a leader hint and the client retries there).
* ``Deliver``    — stream blocks from a seek position (server-stream).
* ``Step``       — orderer↔orderer raft transport (fire-and-forget
  messages; the cluster-comm analog, orderer/common/cluster/comm.go).
* ``Join``       — channel participation: create a chain from a
  genesis block (channelparticipation/restapi.go analog).

Wire format: tiny JSON headers + raw envelope/block bytes — the
content payloads themselves are the canonical protos.
"""

from __future__ import annotations

import asyncio
import json

from fabric_tpu.comm.rpc import RpcClient, RpcServer
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.chain import MsgProcessor, OrderingChain
from fabric_tpu.protos import common_pb2


class OrdererNode:
    def __init__(self, node_id: str, data_dir: str,
                 cluster: dict[str, tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 batch_config: BatchConfig | None = None,
                 msp_manager=None, consensus: str = "raft",
                 signer=None, verifiers=None, view_timeout: float = 2.0,
                 tls=None):
        self.id = node_id
        self.dir = data_dir
        self.cluster = dict(cluster)  # node_id -> (host, port)
        self.host, self.port = host, port
        self.batch_config = batch_config or BatchConfig()
        self.msp = msp_manager
        self.consensus = consensus
        self.broadcast_rate = 0.0  # msgs/s per channel; 0 = unthrottled
        self._throttle: dict[str, list] = {}  # channel -> [tokens, last_ts]
        self.signer = signer
        self.verifiers = verifiers or {}
        self.view_timeout = view_timeout
        self.tls = tls  # comm.rpc.TlsProfile: mTLS on every surface
        self.chains: dict[str, OrderingChain] = {}
        self.server = RpcServer(
            host, port, ssl_ctx=tls.server_ctx() if tls else None
        )
        self._peer_clients: dict[str, RpcClient] = {}
        self._bg: set = set()  # strong refs: GC destroys weakly-held tasks

    # -- raft transport -------------------------------------------------------

    def _send(self, channel: str):
        def send(peer_id: str, msg: dict):
            t = asyncio.ensure_future(self._send_async(peer_id, channel, msg))
            self._bg.add(t)
            t.add_done_callback(self._bg.discard)
        return send

    async def _peer_client(self, peer_id: str) -> RpcClient:
        """Connect-once per peer: the dict holds a Task so concurrent
        senders (a heartbeat round fans out) share ONE connection
        instead of racing to create and leak several."""
        task = self._peer_clients.get(peer_id)
        if task is None:
            addr = self.cluster[peer_id]

            async def connect():
                cli = RpcClient(
                    *addr,
                    ssl_ctx=self.tls.client_ctx() if self.tls else None,
                )
                await cli.connect()
                return cli

            task = asyncio.ensure_future(connect())
            self._peer_clients[peer_id] = task
        return await asyncio.shield(task)

    async def _send_async(self, peer_id: str, channel: str, msg: dict):
        if peer_id not in self.cluster:
            return
        try:
            cli = await self._peer_client(peer_id)
            st = await cli.open_stream("Step")
            await st.send(json.dumps({"channel": channel, "msg": msg}).encode())
            await st.end()
            st.dispose()  # fire-and-forget: the peer never answers
        except (OSError, ConnectionError):
            task = self._peer_clients.pop(peer_id, None)
            if task is not None and task.done() and not task.cancelled():
                try:
                    cli = task.result()
                except Exception:
                    cli = None
                if cli is not None:
                    try:
                        await cli.close()
                    except (OSError, RuntimeError):
                        pass  # peer already gone

    # -- channel lifecycle ------------------------------------------------------

    def join_channel(self, channel_id: str,
                     genesis_block: common_pb2.Block | None = None,
                     start: bool = True) -> OrderingChain:
        if channel_id in self.chains:
            return self.chains[channel_id]
        # broadcast signature filter: with a genesis config the channel
        # Writers policy gates every submitted envelope (sigfilter,
        # orderer/common/msgprocessor/standardchannel.go:100); dev
        # channels without a genesis degrade to size-only admission
        msgproc = MsgProcessor(self.batch_config, self.msp)
        if genesis_block is not None:
            try:
                from fabric_tpu.channelconfig import bundle_from_genesis

                bundle = bundle_from_genesis(channel_id, genesis_block)
                msgproc = MsgProcessor(
                    self.batch_config, bundle.msp_manager,
                    policy_eval=lambda sds: bundle.policy_manager.evaluate(
                        "/Channel/Writers", sds
                    ),
                )
            except Exception:
                import logging

                logging.getLogger("fabric_tpu.orderer").exception(
                    "%s: genesis config unusable for the broadcast "
                    "signature filter on %s — size-only admission",
                    self.id, channel_id,
                )
        chain = OrderingChain(
            channel_id, self.id, list(self.cluster),
            data_dir=f"{self.dir}/{channel_id}",
            send_cb=self._send(channel_id),
            config=self.batch_config,
            msgproc=msgproc,
            genesis_block=genesis_block,
            consensus=self.consensus, signer=self.signer,
            verifiers=self.verifiers, view_timeout=self.view_timeout,
            block_puller=self._pull_blocks,
            on_consenters=self._on_consenters,
        )
        self.chains[channel_id] = chain
        if start:
            chain.start()
        return chain

    def _on_consenters(self, addr_map: dict) -> None:
        """Committed consenter-set change: make new members reachable.
        The cluster map is NODE-wide (shared by every channel this
        registrar hosts), so entries are only added/updated here —
        per-channel membership exclusion happens in each chain's
        update_peers, never by dropping another channel's transport."""
        for nid, addr in addr_map.items():
            self.cluster[nid] = tuple(addr)

    async def _pull_blocks(self, channel: str, start: int, stop: int):
        """Pull serialized blocks [start, stop] from ANY cluster peer's
        Deliver — the follower-chain catch-up source
        (orderer/common/follower/follower_chain.go)."""
        hdr = json.dumps(
            {"channel": channel, "start": start, "stop": stop}
        ).encode()
        for peer_id in list(self.cluster):
            if peer_id == self.id:
                continue
            try:
                cli = await self._peer_client(peer_id)
                st = await cli.open_stream("Deliver")
                await st.send(hdr)
                got = False
                async for raw in st:
                    got = True
                    yield raw
                if got:
                    return
            except Exception as e:
                _log.debug("block pull from %s failed: %s", peer_id, e)
                continue

    # -- services -----------------------------------------------------------------

    async def start(self, operations_port: int | None = None):
        self.server.register_unary("Broadcast", self._on_broadcast)
        self.server.register("Deliver", self._on_deliver)
        self.server.register("Step", self._on_step)
        self.server.register_unary("Join", self._on_join)
        self.server.register_unary("Info", self._on_info)
        await self.server.start()
        self.port = self.server.port
        self.operations = None
        if operations_port is not None:
            from fabric_tpu.opsserver import HealthRegistry, OperationsServer

            health = HealthRegistry()

            def _chains():  # evaluated per check: covers late joins
                for cid, chain in self.chains.items():
                    if chain.raft.state not in ("leader", "follower", "candidate"):
                        return f"consensus {cid} stopped"
                return None

            health.register("consensus", _chains)
            self.operations = await OperationsServer(
                port=operations_port, health=health
            ).start()
        return self

    async def stop(self):
        if getattr(self, "operations", None) is not None:
            await self.operations.stop()
        for chain in self.chains.values():
            chain.stop()
        for task in self._peer_clients.values():
            if task.done() and not task.cancelled():
                try:
                    await task.result().close()
                except (OSError, RuntimeError):
                    pass  # already closed
            else:
                task.cancel()
        await self.server.stop()

    def _throttled(self, channel: str) -> bool:
        """Token-bucket broadcast rate limit per channel
        (orderer/common/throttle/ratelimit.go)."""
        if self.broadcast_rate <= 0:
            return False
        now = asyncio.get_event_loop().time()
        cap = max(1.0, self.broadcast_rate)  # rates < 1/s must still pass
        bucket = self._throttle.setdefault(channel, [cap, now])
        tokens, last = bucket
        tokens = min(cap, tokens + (now - last) * self.broadcast_rate)
        if tokens < 1.0:
            bucket[0], bucket[1] = tokens, now
            return True
        bucket[0], bucket[1] = tokens - 1.0, now
        return False

    async def _on_broadcast(self, req: bytes) -> bytes:
        hdr_len = int.from_bytes(req[:4], "big")
        hdr = json.loads(req[4:4 + hdr_len])
        env = req[4 + hdr_len:]
        chain = self.chains.get(hdr["channel"])
        if chain is None:
            return json.dumps({"status": 404, "info": "no such channel"}).encode()
        if self._throttled(hdr["channel"]):
            return json.dumps(
                {"status": 429, "info": "broadcast rate limit"}
            ).encode()
        res = await chain.broadcast(env)
        if res.get("leader") and res["leader"] in self.cluster:
            res["leader_addr"] = list(self.cluster[res["leader"]])
        return json.dumps(res).encode()

    async def _on_deliver(self, stream):
        req = await stream.__anext__()
        hdr = json.loads(req)
        chain = self.chains.get(hdr["channel"])
        if chain is None:
            await stream.error("no such channel")
            return
        start = hdr.get("start", 0)
        stop = hdr.get("stop")
        async for blk in chain.deliver(start, stop):
            await stream.send(blk)
        await stream.end()

    async def _on_step(self, stream):
        async for payload in stream:
            msg = json.loads(payload)
            chain = self.chains.get(msg["channel"])
            if chain is not None:
                chain.raft.handle(msg["msg"])

    async def _on_join(self, req: bytes) -> bytes:
        hdr_len = int.from_bytes(req[:4], "big")
        hdr = json.loads(req[4:4 + hdr_len])
        blk_bytes = req[4 + hdr_len:]
        genesis = None
        if blk_bytes:
            genesis = common_pb2.Block()
            genesis.ParseFromString(blk_bytes)
        self.join_channel(hdr["channel"], genesis)
        return json.dumps({"status": 201}).encode()

    async def _on_info(self, req: bytes) -> bytes:
        hdr = json.loads(req)
        chain = self.chains.get(hdr["channel"])
        if chain is None:
            return json.dumps({"status": 404}).encode()
        return json.dumps({
            "status": 200, "height": chain.height,
            "state": chain.raft.state, "leader": chain.raft.leader_id,
        }).encode()


class BroadcastClient:
    """Client-side submit with leader-redirect retry (the SDK-facing
    behavior the reference gets from leader forwarding)."""

    def __init__(self, endpoints: list[tuple[str, int]], ssl_ctx=None):
        self.endpoints = list(endpoints)
        self.ssl_ctx = ssl_ctx
        self._clients: dict[tuple[str, int], RpcClient] = {}

    async def _client(self, addr) -> RpcClient:
        addr = tuple(addr)
        cli = self._clients.get(addr)
        if cli is None:
            cli = RpcClient(*addr, ssl_ctx=self.ssl_ctx)
            await cli.connect()
            self._clients[addr] = cli
        return cli

    async def broadcast(self, channel: str, env_bytes: bytes,
                        retries: int = 20) -> dict:
        hdr = json.dumps({"channel": channel}).encode()
        req = len(hdr).to_bytes(4, "big") + hdr + env_bytes
        last = {"status": 503, "info": "no endpoints"}
        hint = None  # leader address learned from the last redirect
        for attempt in range(retries):
            addr = hint or self.endpoints[attempt % len(self.endpoints)]
            hint = None
            try:
                cli = await self._client(addr)
                resp = json.loads(await cli.unary("Broadcast", req, timeout=15))
            except Exception as e:  # connection refused / reset / rpc error
                self._clients.pop(tuple(addr), None)
                last = {"status": 503, "info": str(e)}
                await asyncio.sleep(0.1)
                continue
            if resp["status"] == 200:
                return resp
            if 400 <= resp["status"] < 500 and resp["status"] != 429:
                return resp  # deterministic rejection — retrying can't help
            if resp["status"] == 429:  # backpressure: retry after a beat
                last = resp
                await asyncio.sleep(0.1 * min(attempt + 1, 6))
                continue
            if resp.get("leader_addr"):
                hint = tuple(resp["leader_addr"])
            last = resp
            if resp["status"] == 503:
                await asyncio.sleep(0.05 * min(attempt + 1, 6))
        return last

    async def close(self):
        for cli in self._clients.values():
            await cli.close()


class DeliverClient:
    """Pull a block stream from an orderer (peer side)."""

    def __init__(self, host: str, port: int, ssl_ctx=None):
        self.addr = (host, port)
        self.ssl_ctx = ssl_ctx

    async def blocks(self, channel: str, start: int = 0, stop: int | None = None):
        cli = RpcClient(*self.addr, ssl_ctx=self.ssl_ctx)
        await cli.connect()
        try:
            st = await cli.open_stream("Deliver")
            await st.send(json.dumps(
                {"channel": channel, "start": start, "stop": stop}
            ).encode())
            async for payload in st:
                blk = common_pb2.Block()
                blk.ParseFromString(payload)
                yield blk
        finally:
            await cli.close()

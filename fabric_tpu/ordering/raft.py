"""Minimal Raft consensus for the ordering service.

The reference embeds etcd/raft as an in-process library and drives it
from `Chain.run` (orderer/consensus/etcdraft/chain.go:614,
node.go:23); this image ships no raft library, so the algorithm core
is implemented here directly — elections, log replication, commitment,
and a write-ahead log, per the Raft paper's §5 rules.  Scope matches
what the orderer needs: crash-fault tolerance on a small static
cluster with deterministic apply order; reconfiguration and snapshot
transfer ride on top (chain-level catch-up pulls blocks, as the
reference's follower chain does, orderer/common/follower).

Transport is injected (fabric_tpu.comm RPC in production, direct
queues in tests).  Timers are asyncio-based; all state transitions run
on the event loop, so there is no locking.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import struct
from dataclasses import dataclass

MSG_VOTE = "vote"
MSG_VOTE_RESP = "vote_resp"
MSG_APPEND = "append"
MSG_APPEND_RESP = "append_resp"
MSG_SNAP_HINT = "snap_hint"  # leader compacted past the follower

_LEN = struct.Struct(">I")


@dataclass
class Entry:
    term: int
    index: int
    data: bytes


class WAL:
    """Append-only entry log + term/vote metadata, fsync'd.

    Layout: meta.json {term, voted_for}; wal.bin frames of
    [u32 len | u64 term | u64 index | data].  Torn tails are truncated
    on open (same recovery stance as the blockstore)."""

    def __init__(self, dirpath: str):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.meta_path = os.path.join(dirpath, "meta.json")
        self.wal_path = os.path.join(dirpath, "wal.bin")
        self.term = 0
        self.voted_for: str | None = None
        # compaction watermark: entries <= snap_index are gone from the
        # log (their effects live in the materialized block store —
        # the reference's WAL+snapshot split, etcdraft/storage.go)
        self.snap_index = 0
        self.snap_term = 0
        self.entries: list[Entry] = []
        self._load()
        self._f = open(self.wal_path, "ab")

    def _load(self):
        if os.path.exists(self.meta_path):
            with open(self.meta_path) as f:
                meta = json.load(f)
            self.term = meta.get("term", 0)
            self.voted_for = meta.get("voted_for")
            self.snap_index = meta.get("snap_index", 0)
            self.snap_term = meta.get("snap_term", 0)
        if not os.path.exists(self.wal_path):
            return
        good = 0
        with open(self.wal_path, "rb") as f:
            blob = f.read()
        off = 0
        while off + 20 <= len(blob):
            (ln,) = _LEN.unpack(blob[off:off + 4])
            term, index = struct.unpack(">QQ", blob[off + 4:off + 20])
            if off + 20 + ln > len(blob):
                break  # torn write
            data = blob[off + 20:off + 20 + ln]
            ent = Entry(term, index, data)
            # replace-from semantics: an entry with index i overwrites
            # any previously-read suffix from i (leader change rewrote it)
            while self.entries and self.entries[-1].index >= index:
                self.entries.pop()
            if index > self.snap_index:  # compacted entries are gone
                self.entries.append(ent)
            off += 20 + ln
            good = off
        if good != len(blob):
            with open(self.wal_path, "r+b") as f:
                f.truncate(good)

    def save_meta(self, term: int, voted_for: str | None):
        self.term, self.voted_for = term, voted_for
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "term": term, "voted_for": voted_for,
                "snap_index": self.snap_index, "snap_term": self.snap_term,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.meta_path)

    def _rewrite(self):
        self._f.close()
        tmp = self.wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for e in self.entries:
                f.write(_LEN.pack(len(e.data))
                        + struct.pack(">QQ", e.term, e.index) + e.data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.wal_path)
        self._f = open(self.wal_path, "ab")

    def compact_to(self, index: int) -> int:
        """Drop entries <= index from the log (they are materialized in
        the block store); records the (snap_index, snap_term)
        watermark.  → number of entries dropped."""
        if index <= self.snap_index:
            return 0
        dropped = 0
        term = self.snap_term
        for e in self.entries:
            if e.index <= index:
                dropped += 1
                term = e.term
        if not dropped:
            return 0
        self.entries = [e for e in self.entries if e.index > index]
        self.snap_index = index
        self.snap_term = term
        self.save_meta(self.term, self.voted_for)  # watermark FIRST
        self._rewrite()
        return dropped

    def install_snapshot(self, index: int, term: int) -> None:
        """Out-of-band catch-up installed state through ``index`` (the
        chain pulled the blocks): the log restarts after it."""
        self.entries = [e for e in self.entries if e.index > index]
        self.snap_index = index
        self.snap_term = term
        self.save_meta(self.term, self.voted_for)
        self._rewrite()

    def append(self, entries: list[Entry]):
        for e in entries:
            self._f.write(_LEN.pack(len(e.data)) + struct.pack(">QQ", e.term, e.index) + e.data)
        self._f.flush()
        os.fsync(self._f.fileno())
        self.entries.extend(entries)

    def truncate_from(self, index: int):
        """Drop log entries >= index (conflict rewrite).  Rewrites the
        file — raft conflicts are rare, and compaction keeps the log
        short, so the rewrite is bounded by the retention window."""
        self.entries = [e for e in self.entries if e.index < index]
        self._rewrite()

    def close(self):
        self._f.close()


class RaftNode:
    """One member of a static cluster.

    apply_cb(entry) fires exactly once per committed entry, in index
    order, on every live node.  send_cb(peer_id, msg_dict) delivers a
    message (fire-and-forget; loss tolerated)."""

    def __init__(self, node_id: str, peers: list[str], wal: WAL,
                 apply_cb, send_cb,
                 election_timeout: tuple[float, float] = (0.15, 0.30),
                 heartbeat: float = 0.05, catchup_cb=None):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.wal = wal
        self.apply_cb = apply_cb
        self.send_cb = send_cb
        # catchup_cb(snap_index, snap_term): the leader compacted past
        # this follower — pull state out-of-band (blocks from the
        # cluster, the follower-chain pattern) then install_snapshot
        self.catchup_cb = catchup_cb
        self.election_timeout = election_timeout
        self.heartbeat = heartbeat

        self.state = "follower"
        self.leader_id: str | None = None
        # a compacted WAL restarts with everything <= snap_index
        # already materialized by the chain
        self.commit_index = wal.snap_index
        self.last_applied = wal.snap_index
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self.votes: set[str] = set()
        self._timer: asyncio.TimerHandle | None = None
        self._hb_task: asyncio.Task | None = None
        self._stopped = False
        self._apply_waiters: list = []

    # -- log helpers -------------------------------------------------------

    @property
    def last_index(self) -> int:
        return self.wal.entries[-1].index if self.wal.entries else self.wal.snap_index

    @property
    def last_term(self) -> int:
        return self.wal.entries[-1].term if self.wal.entries else self.wal.snap_term

    def _entry(self, index: int) -> Entry | None:
        if not self.wal.entries:
            return None
        base = self.wal.entries[0].index
        i = index - base
        if 0 <= i < len(self.wal.entries):
            return self.wal.entries[i]
        return None

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self._reset_election_timer()
        # replay committed state is the chain's job (it persists blocks)

    def stop(self):
        self._stopped = True
        if self._timer:
            self._timer.cancel()
        if self._hb_task:
            self._hb_task.cancel()

    # -- timers --------------------------------------------------------------

    def _reset_election_timer(self):
        if self._timer:
            self._timer.cancel()
        if self._stopped:
            return
        delay = random.uniform(*self.election_timeout)
        self._timer = asyncio.get_event_loop().call_later(delay, self._election_timeout)

    def _election_timeout(self):
        if self._stopped or self.state == "leader":
            return
        self._start_election()

    def _start_election(self):
        self.state = "candidate"
        self.wal.save_meta(self.wal.term + 1, self.id)
        self.votes = {self.id}
        self.leader_id = None
        self._reset_election_timer()
        for p in self.peers:
            self.send_cb(p, {
                "type": MSG_VOTE, "term": self.wal.term, "from": self.id,
                "last_index": self.last_index, "last_term": self.last_term,
            })
        self._maybe_win()

    def _maybe_win(self):
        if self.state == "candidate" and len(self.votes) * 2 > len(self.peers) + 1:
            self._become_leader()

    def _become_leader(self):
        self.state = "leader"
        self.leader_id = self.id
        for p in self.peers:
            self.next_index[p] = self.last_index + 1
            self.match_index[p] = 0
        if self._timer:
            self._timer.cancel()
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def _heartbeat_loop(self):
        while not self._stopped and self.state == "leader":
            for p in self.peers:
                self._send_append(p)
            await asyncio.sleep(self.heartbeat)

    # -- client API ----------------------------------------------------------

    def propose(self, data: bytes) -> int | None:
        """Leader-only: append + replicate; → assigned index or None."""
        if self.state != "leader":
            return None
        ent = Entry(self.wal.term, self.last_index + 1, data)
        self.wal.append([ent])
        self.match_index[self.id] = ent.index
        for p in self.peers:
            self._send_append(p)
        self._advance_commit()
        return ent.index

    async def wait_applied(self, index: int, digest: str | None = None):
        # raft never reassigns indices (leader-append-only log), so the
        # digest confirmation the BFT consenter needs is a no-op here
        if self.last_applied >= index:
            return
        ev = asyncio.Event()
        tup = (index, ev)
        self._apply_waiters.append(tup)
        try:
            await ev.wait()
        finally:
            # cancelled waiters (deposed-leader broadcast timeouts)
            # must not pile up in the list forever
            try:
                self._apply_waiters.remove(tup)
            except ValueError:
                pass

    # -- message handling ------------------------------------------------------

    def handle(self, msg: dict):
        if self._stopped:
            return
        t = msg["term"]
        if t > self.wal.term:
            self.wal.save_meta(t, None)
            if self.state == "leader" and self._hb_task:
                self._hb_task.cancel()
            self.state = "follower"
            self._reset_election_timer()
        kind = msg["type"]
        if kind == MSG_VOTE:
            self._on_vote(msg)
        elif kind == MSG_VOTE_RESP:
            self._on_vote_resp(msg)
        elif kind == MSG_APPEND:
            self._on_append(msg)
        elif kind == MSG_APPEND_RESP:
            self._on_append_resp(msg)
        elif kind == MSG_SNAP_HINT:
            self._on_snap_hint(msg)

    def _on_snap_hint(self, msg):
        # term ordering must not gate the catch-up ACTION: a follower
        # whose term churned above the leader's (election storms while
        # partitioned) would otherwise discard the only message kind
        # the leader sends it (next_index < snap_index ⇒ hints, never
        # AppendEntries) and keep churning until vote traffic happens
        # to converge the terms.  Acting on a stale-term hint is safe —
        # catchup_cb pulls SIGNED blocks and verifies them before
        # installing — so only the election-timer reset (a leadership
        # claim) stays term-gated.
        if msg["snap_index"] <= self.last_applied:
            return
        if msg["term"] >= self.wal.term:
            self._reset_election_timer()
        if self.catchup_cb is not None:
            self.catchup_cb(msg["snap_index"], msg["snap_term"])

    def install_snapshot(self, index: int, term: int) -> None:
        """The chain pulled and materialized blocks through raft index
        ``index`` out-of-band: fast-forward the log state so
        replication resumes after it."""
        if index <= self.last_applied:
            return
        self.wal.install_snapshot(index, term)
        self.commit_index = max(self.commit_index, index)
        self.last_applied = max(self.last_applied, index)
        if self._apply_waiters:
            rest = []
            for idx, ev in self._apply_waiters:
                if self.last_applied >= idx:
                    ev.set()
                else:
                    rest.append((idx, ev))
            self._apply_waiters = rest

    def update_peers(self, peers: list[str]) -> None:
        """Consenter-set change from a committed config block (the
        etcdraft reconfiguration path, chain.go:1115; single-server
        changes at a time, as etcd applies them)."""
        self.peers = [p for p in peers if p != self.id]
        for p in self.peers:
            self.next_index.setdefault(p, self.last_index + 1)
            self.match_index.setdefault(p, 0)
        for gone in set(self.next_index) - set(self.peers):
            self.next_index.pop(gone, None)
            self.match_index.pop(gone, None)

    def _on_vote(self, msg):
        grant = False
        if msg["term"] == self.wal.term and self.wal.voted_for in (None, msg["from"]):
            up_to_date = (msg["last_term"], msg["last_index"]) >= (self.last_term, self.last_index)
            if up_to_date:
                grant = True
                self.wal.save_meta(self.wal.term, msg["from"])
                self._reset_election_timer()
        self.send_cb(msg["from"], {
            "type": MSG_VOTE_RESP, "term": self.wal.term,
            "from": self.id, "granted": grant,
        })

    def _on_vote_resp(self, msg):
        if self.state == "candidate" and msg["term"] == self.wal.term and msg["granted"]:
            self.votes.add(msg["from"])
            self._maybe_win()

    def _send_append(self, peer: str):
        ni = self.next_index.get(peer, self.last_index + 1)
        if ni <= self.wal.snap_index:
            # the entries this follower needs are compacted away: it
            # must catch up from the block store (follower_chain.go),
            # then resume replication after the snapshot watermark
            self.send_cb(peer, {
                "type": MSG_SNAP_HINT, "term": self.wal.term,
                "from": self.id, "snap_index": self.wal.snap_index,
                "snap_term": self.wal.snap_term,
            })
            return
        prev = self._entry(ni - 1)
        prev_term = prev.term if prev else (
            self.wal.snap_term if ni - 1 == self.wal.snap_index else 0
        )
        ents = []
        idx = ni
        while True:
            e = self._entry(idx)
            if e is None or len(ents) >= 64:
                break
            ents.append({"term": e.term, "index": e.index, "data": e.data.hex()})
            idx += 1
        self.send_cb(peer, {
            "type": MSG_APPEND, "term": self.wal.term, "from": self.id,
            "prev_index": ni - 1, "prev_term": prev_term,
            "entries": ents, "commit": self.commit_index,
        })

    def _on_append(self, msg):
        ok = False
        if msg["term"] == self.wal.term:
            if self.state != "follower":
                if self._hb_task:
                    self._hb_task.cancel()
                self.state = "follower"
            self.leader_id = msg["from"]
            self._reset_election_timer()
            prev_i, prev_t = msg["prev_index"], msg["prev_term"]
            prev = self._entry(prev_i)
            if prev_i == 0 or (prev is not None and prev.term == prev_t) or (
                prev_i == self.wal.snap_index
                and prev_t == self.wal.snap_term
            ):
                ok = True
                new = []
                for em in msg["entries"]:
                    mine = self._entry(em["index"])
                    if mine is not None and mine.term != em["term"]:
                        self.wal.truncate_from(em["index"])
                        mine = None
                    if mine is None:
                        new.append(Entry(em["term"], em["index"], bytes.fromhex(em["data"])))
                if new:
                    self.wal.append(new)
                if msg["commit"] > self.commit_index:
                    self.commit_index = min(msg["commit"], self.last_index)
                    self._apply_committed()
        self.send_cb(msg["from"], {
            "type": MSG_APPEND_RESP, "term": self.wal.term, "from": self.id,
            "ok": ok, "last_index": self.last_index,
            "prev_index": msg["prev_index"], "n": len(msg["entries"]),
        })

    def _on_append_resp(self, msg):
        if self.state != "leader" or msg["term"] != self.wal.term:
            return
        peer = msg["from"]
        if msg["ok"]:
            mi = msg["prev_index"] + msg["n"]
            self.match_index[peer] = max(self.match_index.get(peer, 0), mi)
            self.next_index[peer] = self.match_index[peer] + 1
            self._advance_commit()
            if self.next_index[peer] <= self.last_index:
                self._send_append(peer)
        else:
            self.next_index[peer] = max(1, self.next_index.get(peer, 1) - 1)
            self._send_append(peer)

    def _advance_commit(self):
        n = len(self.peers) + 1
        for idx in range(self.commit_index + 1, self.last_index + 1):
            e = self._entry(idx)
            if e is None or e.term != self.wal.term:
                continue  # §5.4.2: only current-term entries commit by count
            votes = 1 + sum(1 for p in self.peers if self.match_index.get(p, 0) >= idx)
            if votes * 2 > n:
                self.commit_index = idx
        self._apply_committed()

    def _apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            e = self._entry(self.last_applied)
            self.apply_cb(e)
        if self._apply_waiters:
            rest = []
            for idx, ev in self._apply_waiters:
                if self.last_applied >= idx:
                    ev.set()
                else:
                    rest.append((idx, ev))
            self._apply_waiters = rest

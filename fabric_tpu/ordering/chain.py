"""Per-channel ordering chain: broadcast → filters → blockcutter →
raft → deterministic block assembly → deliver.

Reference shape: `Chain.run` propose/apply loop
(orderer/consensus/etcdraft/chain.go:614), broadcast filter chain
(orderer/common/msgprocessor/standardchannel.go:100), block writer
(orderer/common/multichannel/blockwriter.go).  Re-design notes:

* Raft entries are BATCHES (lists of envelopes), not blocks: every
  node assembles the block from the committed batch DETERMINISTICALLY
  (number = height, prev_hash = own chain tip) so the chain of blocks
  is identical on all nodes without shipping headers through raft.
* The batch timeout rides the leader's event loop; followers redirect
  Broadcast callers to the leader (the reference forwards instead —
  a client-visible difference kept deliberately: retry-with-redirect
  is simpler and the SDK contract allows it).
* Deliver is a height-watched block stream off the block store, the
  seek semantics of common/deliver/deliver.go:158.

Durability coupling: the orderer's BlockStore runs with
``group_commit=1`` (fsync every block) — broadcast ACKs a batch once
raft commits it, and the block files are what WAL compaction trusts:
``_apply`` compacts the WAL back to ``wal_retention`` entries behind
the tip, so any block the store could lose in a crash must be
re-derivable from WAL replay or cluster pull.  A grouped fsync window
larger than ``wal_retention`` (an operator-set FABTPU_WAL_RETENTION
can be small) would let a single-node chain drop ACKed blocks with no
recovery source.  Keep ``group_commit=1`` here unless compaction
learns to lag the unsynced window.
"""

from __future__ import annotations

import asyncio
import json

from fabric_tpu import protoutil
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.ordering.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.ordering.raft import Entry, RaftNode, WAL
from fabric_tpu.protos import common_pb2


class MsgProcessor:
    """Broadcast admission: size cap + the signature filter
    (sigfilter/sizefilter analogs, orderer/common/msgprocessor).

    ``policy_eval(signed_data_list) -> bool`` evaluates the channel's
    /Channel/Writers policy (wired from the genesis bundle by
    join_channel); with only an MSP manager the filter degrades to a
    bare valid-identity signature check; with neither (dev assemblies)
    admission is size-only."""

    def __init__(self, config: BatchConfig, msp_manager=None, policy=None,
                 policy_eval=None):
        self.config = config
        self.msp = msp_manager
        self.policy = policy
        self.policy_eval = policy_eval

    def check(self, env_bytes: bytes) -> str | None:
        """→ None if admitted, else reject reason."""
        if not env_bytes:
            return "empty envelope"
        if len(env_bytes) > self.config.absolute_max_bytes:
            return "message too large"
        if self.policy_eval is not None:
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                sd = protoutil.envelope_as_signed_data(env)
                if not self.policy_eval([sd]):
                    return "Writers policy not satisfied"
            except Exception as e:
                return f"bad envelope: {e}"
        elif self.msp is not None and self.policy is not None:
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                sd = protoutil.envelope_as_signed_data(env)
                ident = self.msp.deserialize_identity(sd.identity)
                if not ident.is_valid or not ident.verify(sd.data, sd.signature):
                    return "signature check failed"
            except Exception as e:
                return f"bad envelope: {e}"
        return None


class OrderingChain:
    """One channel's chain on one orderer node."""

    def __init__(self, channel_id: str, node_id: str, peers: list[str],
                 data_dir: str, send_cb, config: BatchConfig | None = None,
                 msgproc: MsgProcessor | None = None,
                 genesis_block: common_pb2.Block | None = None,
                 consensus: str = "raft", signer=None, verifiers=None,
                 view_timeout: float = 2.0, block_puller=None,
                 on_consenters=None, wal_retention: int = 256):
        self.channel = channel_id
        self.config = config or BatchConfig()
        self.cutter = BlockCutter(self.config)
        self.msgproc = msgproc or MsgProcessor(self.config)
        self.signer = signer  # block attestation (blockwriter.go)
        # block_puller(channel, start, stop) → async iterator of
        # serialized blocks from cluster peers (snapshot catch-up);
        # on_consenters({id: (host, port)}) → transport re-wiring after
        # a committed consenter-set change
        self.block_puller = block_puller
        self.on_consenters = on_consenters
        self.wal_retention = wal_retention
        # group_commit=1: ACKed blocks must hit disk before WAL
        # compaction can outrun them (see module docstring)
        self.blocks = BlockStore(f"{data_dir}/chains", group_commit=1)
        if self.blocks.height == 0 and genesis_block is not None:
            self.blocks.add_block(genesis_block)
        # consenter selection — the consensus.Chain SPI seam
        # (consensus.go:57; registry main.go:635: etcdraft | BFT)
        if consensus == "bft":
            from fabric_tpu.ordering.bft import BFTNode

            self.raft = BFTNode(
                node_id, peers, WAL(f"{data_dir}/wal"),
                apply_cb=self._apply, send_cb=send_cb,
                signer=signer, verifiers=verifiers,
                view_timeout=view_timeout,
                catchup_cb=self._on_snapshot_hint,
            )
        else:
            self.raft = RaftNode(
                node_id, peers, WAL(f"{data_dir}/wal"),
                apply_cb=self._apply, send_cb=send_cb,
                catchup_cb=self._on_snapshot_hint,
            )
        self.consenter = self.raft  # canonical name; raft kept for compat
        self._offset = 0  # block number of raft entry 1, set at start()
        self._catchup_task: asyncio.Task | None = None
        self._timer_task: asyncio.Task | None = None
        self._height_changed = asyncio.Event()

    # -- lifecycle ----------------------------------------------------------

    def _derive_offset(self) -> int:
        """Block number of raft entry 1.  Batch blocks carry ORDERER
        consensus metadata; a genesis/config block 0 doesn't — that
        distinguishes the two layouts (re-derived after catch-up too,
        in case block 0 arrived out-of-band)."""
        if self.blocks.height == 0:
            return 0
        idx = common_pb2.BlockMetadataIndex.ORDERER
        b0 = self.blocks.get_block(0)
        has_meta = len(b0.metadata.metadata) > idx and b0.metadata.metadata[idx]
        return 0 if has_meta else 1

    def start(self):
        # Map raft entry indices to block numbers so WAL replay skips
        # entries already materialized.
        self._offset = self._derive_offset()
        # committed membership changes must survive restart: the WAL
        # replay skips already-materialized entries (including config
        # blocks), so re-derive the consenter set from the chain
        self._reapply_config_membership()
        self.raft.start()

    def _reapply_config_membership(self) -> None:
        """Scan the chain tip-down for the most recent CONFIG block
        carrying a consenter set and re-apply it — restart replay and
        snapshot catch-up bypass _apply for materialized blocks, and a
        reverted membership would diverge from the cluster."""
        for num in range(self.blocks.height - 1, -1, -1):
            blk = self.blocks.get_block(num)
            if blk is None:
                return
            if self._maybe_reconfigure(list(blk.data.data)):
                return

    @property
    def _materialized(self) -> int:
        """Highest raft entry index already materialized as a block."""
        return max(0, self.blocks.height - self._offset)

    def stop(self):
        self.raft.stop()
        if self._timer_task:
            self._timer_task.cancel()
        self.blocks.close()

    # -- broadcast ----------------------------------------------------------

    @staticmethod
    def _is_config(env_bytes: bytes) -> bool:
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            ch = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            return ch.type == common_pb2.HeaderType.CONFIG
        except Exception:
            return False

    async def broadcast(self, env_bytes: bytes) -> dict:
        """→ {status} or {status, info/redirect}."""
        reason = self.msgproc.check(env_bytes)
        if reason is not None:
            return {"status": 400, "info": reason}
        if self.raft.state != "leader":
            # BFT: a client knocking on a follower while the leader is
            # dead is the liveness signal for a view change
            if hasattr(self.raft, "note_client_request"):
                self.raft.note_client_request()
            return {"status": 503, "info": "not leader",
                    "leader": self.raft.leader_id}
        if self._is_config(env_bytes):
            # config messages cut into their OWN single-envelope block
            # (standardchannel.go): pending normal traffic flushes
            # first, and the apply path only scans 1-envelope batches
            # for consenter changes
            batches = [b for b in (self.cutter.cut(),) if b] + [[env_bytes]]
            pending = False
        else:
            batches, pending = self.cutter.ordered(env_bytes)
        last_index = None
        for batch in batches:
            last_index = self._propose_batch(batch)
        if pending:
            self._arm_timer()
        elif self._timer_task:
            self._timer_task.cancel()
            self._timer_task = None
        if last_index is not None:
            try:
                confirmed = await asyncio.wait_for(
                    self.raft.wait_applied(last_index, digest=self._last_digest),
                    timeout=10.0,
                )
            except asyncio.TimeoutError:
                return {"status": 500, "info": "commit timeout"}
            if confirmed is False:
                # a view change reassigned the sequence: this batch was
                # NOT ordered — the client must resubmit
                return {"status": 503, "info": "reordered during view change"}
        return {"status": 200}

    def _propose_batch(self, batch: list[bytes]) -> int | None:
        import hashlib

        payload = json.dumps([b.hex() for b in batch]).encode()
        self._last_digest = hashlib.sha256(payload).hexdigest()
        return self.raft.propose(payload)

    def _arm_timer(self):
        if self._timer_task is not None and not self._timer_task.done():
            return

        async def fire():
            await asyncio.sleep(self.config.batch_timeout_s)
            if self.raft.state == "leader":
                batch = self.cutter.cut()
                if batch:
                    self._propose_batch(batch)

        self._timer_task = asyncio.ensure_future(fire())

    # -- raft apply → block assembly -----------------------------------------

    def _apply(self, entry: Entry):
        batch = [bytes.fromhex(h) for h in json.loads(entry.data.decode())]
        if entry.index <= self._materialized:
            return  # already materialized (restart replay / catch-up)
        prev = (
            protoutil.block_header_hash(
                self.blocks.get_block(self.blocks.height - 1).header
            )
            if self.blocks.height
            else b"\x00" * 32
        )
        blk = protoutil.new_block(self.blocks.height, prev)
        for env in batch:
            blk.data.data.append(env)
        blk = protoutil.finalize_block(blk)
        # orderer metadata: consensus term/index; for BFT, the 2f+1
        # signed COMMIT proof binding (view, seq, digest) — the quorum
        # attestation peers check at deliver (verifier_assembler.go)
        idx = common_pb2.BlockMetadataIndex.ORDERER
        while len(blk.metadata.metadata) <= idx:
            blk.metadata.metadata.append(b"")
        meta = {"term": entry.term, "index": entry.index}
        proof_of = getattr(self.raft, "commit_proof", None)
        if proof_of is not None:
            proof = proof_of(entry.index)
            if proof is not None:
                meta["bft_proof"] = proof
        blk.metadata.metadata[idx] = json.dumps(meta).encode()
        # sign the assembled block: deliver-side verification against
        # the channel's BlockValidation policy depends on it
        if self.signer is not None:
            protoutil.sign_block(blk, self.signer)
        self.blocks.add_block(blk)
        self._height_changed.set()
        self._height_changed = asyncio.Event()
        # consenter-set changes ride committed CONFIG envelopes
        # (etcdraft reconfiguration, chain.go:1115)
        self._maybe_reconfigure(batch)
        # WAL compaction at the retention boundary: everything this far
        # back lives in the block store (etcdraft/storage.go)
        cadence = max(1, min(64, self.wal_retention))
        if entry.index % cadence == 0 and entry.index > self.wal_retention:
            wal = getattr(self.raft, "wal", None)
            if wal is not None:
                wal.compact_to(entry.index - self.wal_retention)

    def _maybe_reconfigure(self, batch: list[bytes]) -> bool:
        """Single-envelope batches only (broadcast isolates CONFIG
        messages into their own batch, the standardchannel.go stance):
        a CONFIG envelope carrying a new ConsensusType consenter set
        applies membership + transport changes (one-server-at-a-time,
        as etcd applies them).  → True iff a consenter set was found."""
        from fabric_tpu.protos import configtx_pb2, orderer_pb2

        if len(batch) != 1:
            return False
        for env_bytes in batch:
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                ch = protoutil.unmarshal(
                    common_pb2.ChannelHeader, payload.header.channel_header
                )
                if ch.type != common_pb2.HeaderType.CONFIG:
                    continue
                cfg_env = protoutil.unmarshal(
                    configtx_pb2.ConfigEnvelope, payload.data
                )
                ordg = cfg_env.config.channel_group.groups.get("Orderer")
                if ordg is None or "ConsensusType" not in ordg.values:
                    continue
                ct = protoutil.unmarshal(
                    orderer_pb2.ConsensusType, ordg.values["ConsensusType"].value
                )
                meta = protoutil.unmarshal(
                    orderer_pb2.RaftConfigMetadata, ct.metadata
                )
                ids = [c.id for c in meta.consenters if c.id]
                if not ids:
                    continue
                addr_map = {
                    c.id: (c.host, c.port)
                    for c in meta.consenters if c.id
                }
                cur = sorted({self.raft.id, *self.raft.peers})
                if sorted(ids) != cur:
                    if self.on_consenters is not None:
                        self.on_consenters(addr_map)
                    self.raft.update_peers(ids)
                    # rotate the BFT message-verifier registry with the
                    # membership: an added consenter authenticates by
                    # the identity the config block carries; a removed
                    # one loses its vote (smartbft configverifier.go)
                    vers = getattr(self.raft, "verifiers", None)
                    if vers:
                        from fabric_tpu.crypto.identity import Identity

                        for c in meta.consenters:
                            if c.id and c.identity and c.id not in vers:
                                try:
                                    ident = Identity.from_serialized(
                                        bytes(c.identity)
                                    )
                                    ident.is_valid = True
                                    vers[c.id] = ident
                                except Exception:
                                    import logging

                                    logging.getLogger(
                                        "fabric_tpu.orderer"
                                    ).warning(
                                        "%s: bad identity for added "
                                        "consenter %s", self.channel, c.id,
                                    )
                        for nid in list(vers):
                            if nid not in ids:
                                vers.pop(nid)
                return True
            except Exception:
                import logging

                logging.getLogger("fabric_tpu.orderer").exception(
                    "%s: consenter reconfiguration from config block "
                    "failed", self.channel,
                )
        return False

    # -- snapshot catch-up (follower_chain.go) -----------------------------

    def _on_snapshot_hint(self, snap_index: int, snap_term: int) -> None:
        """The leader compacted past us (raft) or the cluster vouched
        for sequences we missed (BFT): pull the missing BLOCKS, then
        fast-forward the consensus log state.  Hints arriving while a
        pull is in flight raise the pending target instead of being
        dropped — install_snapshot itself may re-hint for a residual
        gap, and that must not be swallowed by the running-task
        guard."""
        if self.block_puller is None:
            return
        self._catchup_pending = max(
            getattr(self, "_catchup_pending", 0), snap_index
        )
        self._catchup_term = snap_term
        if self._catchup_task is not None and not self._catchup_task.done():
            return

        async def go():
            import logging

            log = logging.getLogger("fabric_tpu.orderer")
            while True:
                target = self._catchup_pending
                term = getattr(self, "_catchup_term", snap_term)
                target_height = self._offset + target
                h_before = self.blocks.height
                try:
                    async for raw in self.block_puller(
                        self.channel, self.blocks.height, target_height - 1
                    ):
                        blk = common_pb2.Block()
                        blk.ParseFromString(raw)
                        if blk.header.number != self.blocks.height:
                            continue
                        if not self._catchup_block_ok(blk):
                            log.warning(
                                "%s: catch-up block %d failed attestation "
                                "— refusing", self.channel,
                                blk.header.number,
                            )
                            break
                        self.blocks.add_block(blk)
                        self._height_changed.set()
                        self._height_changed = asyncio.Event()
                        # a pulled CONFIG block rotates membership (and
                        # the BFT verifier registry) AT ITS HEIGHT, so
                        # later blocks verify against the consenter set
                        # actually in effect when they were attested
                        self._maybe_reconfigure(list(blk.data.data))
                    # block 0 may have arrived out-of-band: refresh the
                    # entry→block mapping and re-derive membership from
                    # the newest materialized config block
                    self._offset = self._derive_offset()
                    self._reapply_config_membership()
                    if self._materialized >= target:
                        self.raft.install_snapshot(target, term)
                except Exception as e:
                    log.warning(
                        "%s: snapshot catch-up to %d failed: %s",
                        self.channel, target_height, e,
                    )
                if (
                    self._catchup_pending <= target
                    or self.blocks.height == h_before
                ):
                    # no higher hint, or no progress (blocks not yet
                    # available anywhere) — stop; the next vouched
                    # claim re-triggers
                    return

        self._catchup_task = asyncio.ensure_future(go())

    def _catchup_block_ok(self, blk) -> bool:
        """Pulled blocks must carry the attestation this round's
        deliver-side verification demands: under BFT (a byzantine
        cluster peer is IN the fault model) the 2f+1 commit proof over
        the batch digest, verified against the consenter identity
        registry; prev-hash chaining is enforced by add_block either
        way.  CFT raft trusts cluster peers for catch-up, as the
        reference's follower chain does."""
        verifiers = getattr(self.raft, "verifiers", None)
        if not verifiers:
            return True  # raft / dev mode
        import hashlib

        from fabric_tpu.ordering.bft import COMMIT, _signable

        try:
            idx = common_pb2.BlockMetadataIndex.ORDERER
            meta = json.loads(bytes(blk.metadata.metadata[idx]))
            proof = meta["bft_proof"]
            payload = json.dumps(
                [bytes(e).hex() for e in blk.data.data]
            ).encode()
            want = hashlib.sha256(payload).hexdigest()
            quorum = getattr(self.raft, "quorum", 1)
            good = set()
            for m in proof:
                if not isinstance(m, dict) or m.get("type") != COMMIT:
                    continue
                if m.get("digest") != want:
                    continue
                sender = m.get("from")
                ver = verifiers.get(sender)
                sig = m.get("sig")
                if sender in good or ver is None or not sig:
                    continue
                if ver.verify(_signable(m), bytes.fromhex(sig)):
                    good.add(sender)
            return len(good) >= quorum
        except Exception:
            return False

    # -- deliver --------------------------------------------------------------

    async def deliver(self, start: int, stop: int | None = None):
        """Async iterator of serialized blocks [start, stop]; blocks at
        the tip until new blocks are cut (deliver.go:158 seek
        semantics: stop=None streams forever)."""
        num = start
        while stop is None or num <= stop:
            if num < self.blocks.height:
                blk = self.blocks.get_block(num)
                yield blk.SerializeToString()
                num += 1
            else:
                # single event loop: no await between the height check
                # and this wait, so no wakeup can be missed (_apply
                # sets the event then replaces it)
                await self._height_changed.wait()

    @property
    def height(self) -> int:
        return self.blocks.height

"""Per-channel ordering chain: broadcast → filters → blockcutter →
raft → deterministic block assembly → deliver.

Reference shape: `Chain.run` propose/apply loop
(orderer/consensus/etcdraft/chain.go:614), broadcast filter chain
(orderer/common/msgprocessor/standardchannel.go:100), block writer
(orderer/common/multichannel/blockwriter.go).  Re-design notes:

* Raft entries are BATCHES (lists of envelopes), not blocks: every
  node assembles the block from the committed batch DETERMINISTICALLY
  (number = height, prev_hash = own chain tip) so the chain of blocks
  is identical on all nodes without shipping headers through raft.
* The batch timeout rides the leader's event loop; followers redirect
  Broadcast callers to the leader (the reference forwards instead —
  a client-visible difference kept deliberately: retry-with-redirect
  is simpler and the SDK contract allows it).
* Deliver is a height-watched block stream off the block store, the
  seek semantics of common/deliver/deliver.go:158.
"""

from __future__ import annotations

import asyncio
import json

from fabric_tpu import protoutil
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.ordering.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.ordering.raft import Entry, RaftNode, WAL
from fabric_tpu.protos import common_pb2


class MsgProcessor:
    """Broadcast admission: size cap + the signature filter
    (sigfilter/sizefilter analogs, orderer/common/msgprocessor).

    ``policy_eval(signed_data_list) -> bool`` evaluates the channel's
    /Channel/Writers policy (wired from the genesis bundle by
    join_channel); with only an MSP manager the filter degrades to a
    bare valid-identity signature check; with neither (dev assemblies)
    admission is size-only."""

    def __init__(self, config: BatchConfig, msp_manager=None, policy=None,
                 policy_eval=None):
        self.config = config
        self.msp = msp_manager
        self.policy = policy
        self.policy_eval = policy_eval

    def check(self, env_bytes: bytes) -> str | None:
        """→ None if admitted, else reject reason."""
        if not env_bytes:
            return "empty envelope"
        if len(env_bytes) > self.config.absolute_max_bytes:
            return "message too large"
        if self.policy_eval is not None:
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                sd = protoutil.envelope_as_signed_data(env)
                if not self.policy_eval([sd]):
                    return "Writers policy not satisfied"
            except Exception as e:
                return f"bad envelope: {e}"
        elif self.msp is not None and self.policy is not None:
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                sd = protoutil.envelope_as_signed_data(env)
                ident = self.msp.deserialize_identity(sd.identity)
                if not ident.is_valid or not ident.verify(sd.data, sd.signature):
                    return "signature check failed"
            except Exception as e:
                return f"bad envelope: {e}"
        return None


class OrderingChain:
    """One channel's chain on one orderer node."""

    def __init__(self, channel_id: str, node_id: str, peers: list[str],
                 data_dir: str, send_cb, config: BatchConfig | None = None,
                 msgproc: MsgProcessor | None = None,
                 genesis_block: common_pb2.Block | None = None,
                 consensus: str = "raft", signer=None, verifiers=None,
                 view_timeout: float = 2.0):
        self.channel = channel_id
        self.config = config or BatchConfig()
        self.cutter = BlockCutter(self.config)
        self.msgproc = msgproc or MsgProcessor(self.config)
        self.signer = signer  # block attestation (blockwriter.go)
        self.blocks = BlockStore(f"{data_dir}/chains")
        if self.blocks.height == 0 and genesis_block is not None:
            self.blocks.add_block(genesis_block)
        # consenter selection — the consensus.Chain SPI seam
        # (consensus.go:57; registry main.go:635: etcdraft | BFT)
        if consensus == "bft":
            from fabric_tpu.ordering.bft import BFTNode

            self.raft = BFTNode(
                node_id, peers, WAL(f"{data_dir}/wal"),
                apply_cb=self._apply, send_cb=send_cb,
                signer=signer, verifiers=verifiers,
                view_timeout=view_timeout,
            )
        else:
            self.raft = RaftNode(
                node_id, peers, WAL(f"{data_dir}/wal"),
                apply_cb=self._apply, send_cb=send_cb,
            )
        self.consenter = self.raft  # canonical name; raft kept for compat
        self._applied_batches = 0
        self._recovered_batches = 0
        self._timer_task: asyncio.Task | None = None
        self._height_changed = asyncio.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        # Re-derive how many raft entries are already materialized as
        # blocks so WAL replay doesn't re-append them.  Batch blocks
        # carry ORDERER consensus metadata; a genesis/config block 0
        # doesn't — that distinguishes the two layouts on restart.
        h = self.blocks.height
        offset = 0
        if h > 0:
            idx = common_pb2.BlockMetadataIndex.ORDERER
            b0 = self.blocks.get_block(0)
            has_meta = len(b0.metadata.metadata) > idx and b0.metadata.metadata[idx]
            offset = 0 if has_meta else 1
        self._recovered_batches = max(0, h - offset)
        self._applied_batches = 0
        self.raft.start()

    def stop(self):
        self.raft.stop()
        if self._timer_task:
            self._timer_task.cancel()
        self.blocks.close()

    # -- broadcast ----------------------------------------------------------

    async def broadcast(self, env_bytes: bytes) -> dict:
        """→ {status} or {status, info/redirect}."""
        reason = self.msgproc.check(env_bytes)
        if reason is not None:
            return {"status": 400, "info": reason}
        if self.raft.state != "leader":
            # BFT: a client knocking on a follower while the leader is
            # dead is the liveness signal for a view change
            if hasattr(self.raft, "note_client_request"):
                self.raft.note_client_request()
            return {"status": 503, "info": "not leader",
                    "leader": self.raft.leader_id}
        batches, pending = self.cutter.ordered(env_bytes)
        last_index = None
        for batch in batches:
            last_index = self._propose_batch(batch)
        if pending:
            self._arm_timer()
        elif self._timer_task:
            self._timer_task.cancel()
            self._timer_task = None
        if last_index is not None:
            try:
                confirmed = await asyncio.wait_for(
                    self.raft.wait_applied(last_index, digest=self._last_digest),
                    timeout=10.0,
                )
            except asyncio.TimeoutError:
                return {"status": 500, "info": "commit timeout"}
            if confirmed is False:
                # a view change reassigned the sequence: this batch was
                # NOT ordered — the client must resubmit
                return {"status": 503, "info": "reordered during view change"}
        return {"status": 200}

    def _propose_batch(self, batch: list[bytes]) -> int | None:
        import hashlib

        payload = json.dumps([b.hex() for b in batch]).encode()
        self._last_digest = hashlib.sha256(payload).hexdigest()
        return self.raft.propose(payload)

    def _arm_timer(self):
        if self._timer_task is not None and not self._timer_task.done():
            return

        async def fire():
            await asyncio.sleep(self.config.batch_timeout_s)
            if self.raft.state == "leader":
                batch = self.cutter.cut()
                if batch:
                    self._propose_batch(batch)

        self._timer_task = asyncio.ensure_future(fire())

    # -- raft apply → block assembly -----------------------------------------

    def _apply(self, entry: Entry):
        batch = [bytes.fromhex(h) for h in json.loads(entry.data.decode())]
        self._applied_batches += 1
        if self._applied_batches <= self._recovered_batches:
            return  # already materialized before restart
        prev = (
            protoutil.block_header_hash(
                self.blocks.get_block(self.blocks.height - 1).header
            )
            if self.blocks.height
            else b"\x00" * 32
        )
        blk = protoutil.new_block(self.blocks.height, prev)
        for env in batch:
            blk.data.data.append(env)
        blk = protoutil.finalize_block(blk)
        # orderer metadata: consensus term/index; for BFT, the 2f+1
        # signed COMMIT proof binding (view, seq, digest) — the quorum
        # attestation peers check at deliver (verifier_assembler.go)
        idx = common_pb2.BlockMetadataIndex.ORDERER
        while len(blk.metadata.metadata) <= idx:
            blk.metadata.metadata.append(b"")
        meta = {"term": entry.term, "index": entry.index}
        proof_of = getattr(self.raft, "commit_proof", None)
        if proof_of is not None:
            proof = proof_of(entry.index)
            if proof is not None:
                meta["bft_proof"] = proof
        blk.metadata.metadata[idx] = json.dumps(meta).encode()
        # sign the assembled block: deliver-side verification against
        # the channel's BlockValidation policy depends on it
        if self.signer is not None:
            protoutil.sign_block(blk, self.signer)
        self.blocks.add_block(blk)
        self._height_changed.set()
        self._height_changed = asyncio.Event()

    # -- deliver --------------------------------------------------------------

    async def deliver(self, start: int, stop: int | None = None):
        """Async iterator of serialized blocks [start, stop]; blocks at
        the tip until new blocks are cut (deliver.go:158 seek
        semantics: stop=None streams forever)."""
        num = start
        while stop is None or num <= stop:
            if num < self.blocks.height:
                blk = self.blocks.get_block(num)
                yield blk.SerializeToString()
                num += 1
            else:
                # single event loop: no await between the height check
                # and this wait, so no wakeup can be missed (_apply
                # sets the event then replaces it)
                await self._height_changed.wait()

    @property
    def height(self) -> int:
        return self.blocks.height

from fabric_tpu.ordering.blockcutter import BatchConfig, BlockCutter  # noqa: F401
from fabric_tpu.ordering.chain import MsgProcessor, OrderingChain  # noqa: F401
from fabric_tpu.ordering.node import (  # noqa: F401
    BroadcastClient,
    DeliverClient,
    OrdererNode,
)
from fabric_tpu.ordering.raft import RaftNode, WAL  # noqa: F401

"""Blockcutter: batch envelopes into block payloads.

Same cutting rules as the reference (orderer/common/blockcutter/
blockcutter.go:74-130 `Ordered`):

* an envelope larger than PreferredMaxBytes is cut into its OWN batch
  (isolated), flushing any pending batch first;
* if appending would exceed PreferredMaxBytes, the pending batch is
  cut and the envelope starts a new one;
* reaching MaxMessageCount cuts immediately;
* `pending` exposes whether a BatchTimeout timer should be running —
  the chain owns the actual timer (etcdraft/chain.go timer handling).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BatchConfig:
    max_message_count: int = 500
    preferred_max_bytes: int = 2 * 1024 * 1024
    absolute_max_bytes: int = 10 * 1024 * 1024
    batch_timeout_s: float = 2.0


@dataclass
class BlockCutter:
    config: BatchConfig = field(default_factory=BatchConfig)
    _pending: list = field(default_factory=list)
    _pending_bytes: int = 0

    def ordered(self, env_bytes: bytes) -> tuple[list[list[bytes]], bool]:
        """→ (batches_cut_now, pending_remains)."""
        cfg = self.config
        cut: list[list[bytes]] = []
        size = len(env_bytes)

        if size > cfg.preferred_max_bytes:
            # isolated oversize message: flush pending, own batch
            if self._pending:
                cut.append(self._flush())
            cut.append([env_bytes])
            return cut, False

        if self._pending_bytes + size > cfg.preferred_max_bytes and self._pending:
            cut.append(self._flush())

        self._pending.append(env_bytes)
        self._pending_bytes += size

        if len(self._pending) >= cfg.max_message_count:
            cut.append(self._flush())

        return cut, bool(self._pending)

    def cut(self) -> list[bytes]:
        """Force-cut the pending batch (timeout expiry / config msg)."""
        return self._flush() if self._pending else []

    def _flush(self) -> list[bytes]:
        batch, self._pending, self._pending_bytes = self._pending, [], 0
        return batch

    @property
    def pending(self) -> bool:
        return bool(self._pending)

"""Idemix: anonymous-credential MSP (reference: msp/idemix.go wrapping
IBM/idemix).

The reference vendors a pairing-based BBS+ construction; this image has
no pairing library, so this module implements the ORIGINAL idemix
scheme — Camenisch–Lysyanskaya signatures over a strong-RSA group
(CL01), which the IBM identity mixer shipped for years before the
pairing curves — with the same capability surface:

* an issuer certifies a credential over (master-secret, OU, role)
  without learning the master secret (blind issuance with a Schnorr
  proof of the commitment);
* the holder signs messages by presenting a FRESH zero-knowledge proof
  of possession per signature (randomized A', Fiat–Shamir over the
  message): signatures by the same holder are UNLINKABLE, while the
  org (issuer key) and the disclosed OU/role remain verifiable;
* verification is a handful of modexps on host — the anonymous path is
  for client creators (the reference's stance: peers/orderers stay
  X.509, idemix identities cannot endorse), so it rides the
  validator's host lane, not the TPU batch.

Math. Issuer key: modulus n = pq (safe-ish primes), random quadratic
residues S, Z, R_sk, R_ou, R_role.  Credential: (A, e, v) with

    A^e · S^v · R_sk^sk · R_ou^m_ou · R_role^m_role ≡ Z  (mod n)

where e is prime.  Presentation for message M: A' = A·S^r, v' = v−e·r,
then a Σ-protocol proof of (e, v', sk) for

    A'^e · S^{v'} · R_sk^sk ≡ Z / (R_ou^m_ou · R_role^m_role),

made non-interactive with c = H(ipk, A', t, disclosed, nonce, M).
"""

from __future__ import annotations

import hashlib
import json
import secrets

# parameter lengths (bits); l_n is set per issuer.  The CL soundness
# analysis needs e to live in a NARROW interval around a large power of
# two — e ∈ [2^(L_E-1), 2^(L_E-1) + 2^(L_E_PRIME)] — so the Σ-protocol
# can prove the range: the response is computed over the offset
# e' = e − 2^(L_E-1), and the verifier's bound on s_e guarantees
# |e'| < 2^(L_E_PRIME+L_C+L_STAT+2) ≪ 2^(L_E-2), hence e is genuinely
# huge (no e=1 forgeries).  That requires L_E_PRIME+L_C+L_STAT+2 < L_E-2,
# which the classic idemix parameter set (l_e=597, l_e'=120) satisfies.
L_M = 256        # attribute size
L_E = 597        # total bit-length of the prime exponent e
L_E_PRIME = 120  # width of the interval e ranges over
L_STAT = 80      # statistical hiding slack
L_C = 256        # Fiat–Shamir challenge


def _attr_int(value: str) -> int:
    return int.from_bytes(
        hashlib.sha256(value.encode()).digest(), "big"
    ) % (1 << L_M)


def _rand_bits(bits: int) -> int:
    return secrets.randbits(bits)


def _is_probable_prime(x: int, rounds: int = 40) -> bool:
    if x < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if x % p == 0:
            return x == p
    d, r = x - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(x - 3) + 2
        y = pow(a, d, x)
        if y in (1, x - 1):
            continue
        for _ in range(r - 1):
            y = pow(y, 2, x)
            if y == x - 1:
                break
        else:
            return False
    return True


def _gen_prime(bits: int) -> int:
    while True:
        x = _rand_bits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(x):
            return x


def _gen_cred_exponent() -> int:
    """A prime in [2^(L_E-1), 2^(L_E-1) + 2^(L_E_PRIME)] — the narrow
    window the presentation proof's range bound certifies."""
    base = 1 << (L_E - 1)
    while True:
        x = base + (_rand_bits(L_E_PRIME) | 1)
        if _is_probable_prime(x):
            return x


def _fs_challenge(*parts) -> int:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, int):
            p = p.to_bytes((p.bit_length() + 7) // 8 or 1, "big")
        elif isinstance(p, str):
            p = p.encode()
        h.update(len(p).to_bytes(4, "big"))
        h.update(p)
    return int.from_bytes(h.digest(), "big") % (1 << L_C)


class IssuerPublicKey:
    """(n, S, Z, R_sk, R_ou, R_role, R_epoch) plus the revocation
    authority's ECDSA public point — everything a verifier needs."""

    __slots__ = ("n", "S", "Z", "R_sk", "R_ou", "R_role", "R_epoch",
                 "ra_pub", "_key_digest")

    def __init__(self, n, S, Z, R_sk, R_ou, R_role, R_epoch, ra_pub):
        self.n, self.S, self.Z = n, S, Z
        self.R_sk, self.R_ou, self.R_role = R_sk, R_ou, R_role
        self.R_epoch = R_epoch
        self.ra_pub = tuple(ra_pub)
        self._key_digest = None  # lazy sha256(to_json()) — see key_digest

    def key_digest(self) -> bytes:
        """sha256 over the full key JSON, computed once — the
        EpochRecord verification cache compares this per presentation,
        so it must stay an attribute read, not a re-serialization.
        Safe to memoize: every field is set once in __init__."""
        if self._key_digest is None:
            self._key_digest = hashlib.sha256(
                self.to_json().encode()
            ).digest()
        return self._key_digest

    def to_json(self) -> str:
        d = {
            k: hex(getattr(self, k))
            for k in ("n", "S", "Z", "R_sk", "R_ou", "R_role", "R_epoch")
        }
        d["ra_pub"] = [hex(self.ra_pub[0]), hex(self.ra_pub[1])]
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "IssuerPublicKey":
        # R_epoch and ra_pub are REQUIRED: a degenerate epoch generator
        # (e.g. 1) would make every epoch claim satisfy the proof —
        # legacy keys must be re-issued, not silently weakened
        d = json.loads(raw)
        ra = d.pop("ra_pub")
        return cls(**{k: int(v, 16) for k, v in d.items()},
                   ra_pub=(int(ra[0], 16), int(ra[1], 16)))

    def _digest_parts(self):
        return (self.n, self.S, self.Z, self.R_sk, self.R_ou,
                self.R_role, self.R_epoch)


class Credential:
    __slots__ = ("A", "e", "v", "sk", "ou", "role", "epoch")

    def __init__(self, A, e, v, sk, ou, role, epoch=0):
        self.A, self.e, self.v = A, e, v
        self.sk, self.ou, self.role = sk, ou, role
        self.epoch = epoch


class EpochRecord:
    """The revocation authority's signed epoch statement — the CRI
    analog of the reference's vendored idemix revocation handler:
    verifiers require presentations to DISCLOSE the current epoch, and
    revocation works by advancing the epoch and re-issuing credentials
    to every still-authorized holder (a revoked holder cannot obtain
    the new epoch, so its old credentials stop verifying the moment
    the verifier learns the new record)."""

    __slots__ = ("epoch", "r", "s", "_ok_for")

    def __init__(self, epoch: int, r: int, s: int):
        self.epoch, self.r, self.s = epoch, r, s
        # digest of the FULL issuer public key JSON the signature
        # verified against — keying on ipk.n alone would let a record
        # re-verify against a different key sharing the modulus but
        # carrying different generators/ra_pub
        self._ok_for = None

    def to_json(self) -> str:
        return json.dumps(
            {"epoch": self.epoch, "r": hex(self.r), "s": hex(self.s)},
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, raw: str) -> "EpochRecord":
        d = json.loads(raw)
        return cls(int(d["epoch"]), int(d["r"], 16), int(d["s"], 16))

    def digest(self, ipk: "IssuerPublicKey") -> int:
        return int.from_bytes(hashlib.sha256(
            b"idemix-epoch|" + ipk.to_json().encode()
            + b"|%d" % self.epoch
        ).digest(), "big")

    def verify(self, ipk: "IssuerPublicKey") -> bool:
        # cache per issuer: the record is static between adoptions, and
        # a pure-Python P-256 verify on EVERY presentation would tax
        # the validator's host lane for nothing
        ipk_digest = ipk.key_digest()
        if self._ok_for == ipk_digest:
            return True
        from fabric_tpu.crypto import ec_ref

        try:
            ok = ec_ref.verify_digest(
                ipk.ra_pub, self.digest(ipk), self.r, self.s
            )
        except Exception:
            return False
        if ok:
            self._ok_for = ipk_digest
        return ok


class IdemixIssuer:
    """Issuer: keygen + blind issuance (msp/idemix.go's issuer side)."""

    def __init__(self, msp_id: str, bits: int = 2048):
        """``bits``: strong-RSA modulus size.  2048 is the production
        floor (1024-bit moduli are within reach of well-funded
        factoring); tests pass 1024 explicitly for speed."""
        self.msp_id = msp_id
        self.bits = bits
        p = _gen_prime(bits // 2)
        q = _gen_prime(bits // 2)
        while q == p:
            q = _gen_prime(bits // 2)
        self.n = p * q
        self._phi = (p - 1) * (q - 1)
        def qr():
            x = secrets.randbelow(self.n - 2) + 2
            return pow(x, 2, self.n)
        from fabric_tpu.crypto import ec_ref

        self._ra_key = ec_ref.SigningKey.generate()
        self.ipk = IssuerPublicKey(
            self.n, qr(), qr(), qr(), qr(), qr(), qr(),
            ra_pub=self._ra_key.public,
        )
        # revocation state: epoch counter + revoked handle set.  A
        # handle identifies a HOLDER to the issuer only (assigned at
        # first issuance); it never appears in presentations, so
        # unlinkability is untouched.
        self.epoch = 0
        self._revoked: set = set()
        self._epoch_record = self._sign_epoch()

    def _sign_epoch(self) -> EpochRecord:
        rec = EpochRecord(self.epoch, 0, 0)
        rec.r, rec.s = self._ra_key.sign_digest(rec.digest(self.ipk))
        return rec

    @property
    def epoch_record(self) -> EpochRecord:
        return self._epoch_record

    def revoke(self, handle) -> None:
        """Mark a holder revoked and ADVANCE THE EPOCH: every
        still-authorized holder re-issues into the new epoch; the
        revoked one cannot, so its credentials die with the old
        epoch everywhere the new record propagates."""
        self._revoked.add(handle)
        self.epoch += 1
        self._epoch_record = self._sign_epoch()

    def is_revoked(self, handle) -> bool:
        return handle in self._revoked

    def issue(self, commitment: int, proof: dict, ou: str, role: str,
              handle=None):
        """Blind issuance: the holder supplies U = R_sk^sk · S^v_u with
        a Schnorr proof of representation; the issuer never sees sk.
        → (A, e, v_issuer) to be combined holder-side.  ``handle``:
        the issuer-side holder identifier for revocation — issuance
        (and epoch re-issuance) is refused for revoked handles.
        Binding a handle to the actual holder is the enrollment
        layer's job (the fabric-ca registration step); once ANY
        revocation exists, anonymous issuance is refused outright so
        a revoked holder cannot re-enroll by simply omitting its
        handle."""
        if self._revoked and handle is None:
            raise ValueError(
                "revocation is active on this issuer: issuance requires "
                "a holder handle"
            )
        if handle is not None and handle in self._revoked:
            raise ValueError(f"holder {handle!r} is revoked")
        ipk = self.ipk
        # verify PoK of (sk, v_u) for U
        c = _fs_challenge(ipk.to_json(), commitment, proof["t"], "issue")
        lhs = (pow(ipk.R_sk, proof["s_sk"], ipk.n)
               * pow(ipk.S, proof["s_v"], ipk.n)
               * pow(commitment, -c, ipk.n)) % ipk.n
        if lhs != proof["t"] % ipk.n:
            raise ValueError("bad commitment proof")
        e = _gen_cred_exponent()
        v_i = _rand_bits(self.bits + L_STAT)
        m_ou, m_role = _attr_int(ou), _attr_int(role)
        base = (commitment * pow(ipk.S, v_i, ipk.n)
                * pow(ipk.R_ou, m_ou, ipk.n)
                * pow(ipk.R_role, m_role, ipk.n)
                * pow(ipk.R_epoch, self.epoch, ipk.n)) % ipk.n
        e_inv = pow(e, -1, self._phi)
        A = pow((ipk.Z * pow(base, -1, ipk.n)) % ipk.n, e_inv, ipk.n)
        return A, e, v_i


class IdemixHolder:
    """Credential holder: commitment, credential assembly, signing."""

    def __init__(self, ipk: IssuerPublicKey):
        self.ipk = ipk
        self.sk = _rand_bits(L_M)
        self._v_u = None

    def commitment(self):
        ipk = self.ipk
        v_u = _rand_bits(ipk.n.bit_length() + L_STAT)
        self._v_u = v_u
        U = (pow(ipk.R_sk, self.sk, ipk.n) * pow(ipk.S, v_u, ipk.n)) % ipk.n
        r_sk = _rand_bits(L_M + L_C + L_STAT)
        r_v = _rand_bits(ipk.n.bit_length() + L_STAT + L_C + L_STAT)
        t = (pow(ipk.R_sk, r_sk, ipk.n) * pow(ipk.S, r_v, ipk.n)) % ipk.n
        c = _fs_challenge(ipk.to_json(), U, t, "issue")
        return U, {"t": t, "s_sk": r_sk + c * self.sk, "s_v": r_v + c * v_u}

    def assemble(self, A: int, e: int, v_i: int, ou: str, role: str,
                 epoch: int = 0) -> Credential:
        cred = Credential(A, e, v_i + self._v_u, self.sk, ou, role,
                          epoch=epoch)
        ipk = self.ipk
        # sanity: A^e S^v R_sk^sk R_ou^ou R_role^role R_epoch^epoch == Z
        lhs = (pow(A, e, ipk.n) * pow(ipk.S, cred.v, ipk.n)
               * pow(ipk.R_sk, self.sk, ipk.n)
               * pow(ipk.R_ou, _attr_int(ou), ipk.n)
               * pow(ipk.R_role, _attr_int(role), ipk.n)
               * pow(ipk.R_epoch, epoch, ipk.n)) % ipk.n
        if lhs != ipk.Z % ipk.n:
            raise ValueError("credential does not verify")
        return cred


def sign(ipk: IssuerPublicKey, cred: Credential, msg: bytes) -> bytes:
    """A FRESH presentation proof over ``msg`` — the idemix signature.
    Unlinkable: every call randomizes A' and all proof values."""
    n = ipk.n
    r = _rand_bits(n.bit_length() + L_STAT)
    A2 = (cred.A * pow(ipk.S, r, n)) % n
    v2 = cred.v - cred.e * r  # integer (may be negative)

    # the Σ-protocol runs over the OFFSET e' = e − 2^(L_E-1); the
    # verifier folds the fixed 2^(L_E-1) back in, so the range bound on
    # s_e pins e to its prime window (no small-exponent forgeries)
    e_off = cred.e - (1 << (L_E - 1))
    r_e = _rand_bits(L_E_PRIME + L_C + L_STAT)
    r_v = _rand_bits(n.bit_length() + 2 * L_STAT + L_C + L_E)
    r_sk = _rand_bits(L_M + L_C + L_STAT)
    t = (pow(A2, r_e, n) * pow(ipk.S, r_v, n)
         * pow(ipk.R_sk, r_sk, n)) % n
    nonce = secrets.token_hex(16)
    c = _fs_challenge(ipk.to_json(), A2, t, cred.ou, cred.role,
                      cred.epoch, nonce, msg)
    return json.dumps({
        "A2": hex(A2), "c": hex(c), "nonce": nonce,
        "epoch": cred.epoch,
        "s_e": hex(r_e + c * e_off),
        "s_v": hex(r_v + c * v2) if r_v + c * v2 >= 0
               else "-" + hex(-(r_v + c * v2)),
        "s_sk": hex(r_sk + c * cred.sk),
    }).encode()


def _parse_signed(h: str) -> int:
    return -int(h[1:], 16) if h.startswith("-") else int(h, 16)


def verify(ipk: IssuerPublicKey, ou: str, role: str, msg: bytes,
           sig: bytes, epoch_record: "EpochRecord | None" = None) -> bool:
    """Verify a presentation proof: a few modexps on host (the
    batched-TPU path is pointless here — idemix creators are rare and
    cannot endorse).

    ``epoch_record``: the latest RA-signed epoch statement the
    verifier holds.  When given, the presentation must DISCLOSE that
    exact epoch — the revocation check: a revoked holder is frozen
    out of new epochs at re-issuance, so its credentials only prove
    stale epochs.  The disclosed epoch is bound by the credential
    equation itself (R_epoch^epoch folds into the proof), so lying
    about it fails the Σ-protocol."""
    try:
        d = json.loads(sig)
        n = ipk.n
        A2, c = int(d["A2"], 16), int(d["c"], 16)
        s_e = int(d["s_e"], 16)
        s_v = _parse_signed(d["s_v"])
        s_sk = int(d["s_sk"], 16)
        nonce = d["nonce"]
        epoch = int(d.get("epoch", 0))
        if epoch_record is not None:
            if not epoch_record.verify(ipk):
                return False
            if epoch != epoch_record.epoch:
                return False
        if not (0 < A2 < n):
            return False
        # soundness range bound: s_e certifies the OFFSET e' = e−2^(L_E-1),
        # so extraction yields |e'| < 2^(L_E_PRIME+L_C+L_STAT+2) ≪ 2^(L_E-2)
        # and e = 2^(L_E-1) + e' is provably in its huge prime window —
        # an adversary cannot use e=1 (or any small e) because the fixed
        # A2^(c·2^(L_E-1)) factor below would demand a genuine large-e
        # root (strong-RSA hard)
        if not (0 <= s_e < 1 << (L_E_PRIME + L_C + L_STAT + 1)):
            return False
        z_d = (ipk.Z * pow(ipk.R_ou, -_attr_int(ou), n)
               * pow(ipk.R_role, -_attr_int(role), n)
               * pow(ipk.R_epoch, -epoch, n)) % n
        t_hat = (pow(A2, s_e + (c << (L_E - 1)), n) * pow(ipk.S, s_v, n)
                 * pow(ipk.R_sk, s_sk, n) * pow(z_d, -c, n)) % n
        return _fs_challenge(
            ipk.to_json(), A2, t_hat, ou, role, epoch, nonce, msg
        ) == c
    except Exception:
        return False


# ---------------------------------------------------------------------------
# MSP integration (the msp.MSP duck type the manager expects)


class IdemixIdentity:
    """Identity-like wrapper: msp_id/role/ous/is_valid/verify — but NO
    public_numbers: the validator's batch lane raises and falls back to
    host verification for these creators."""

    def __init__(self, msp_id: str, ou: str, role: str, ipk: IssuerPublicKey,
                 serialized: bytes, is_valid: bool, epoch_record=None):
        self.msp_id = msp_id
        self.ou_value = ou
        self.ous = (ou,)
        self.role = role
        self.ipk = ipk
        self.serialized = serialized
        self.is_valid = is_valid
        self.epoch_record = epoch_record

    @property
    def public_numbers(self):
        raise ValueError("idemix identities carry no EC public key")

    def verify(self, message: bytes, sig: bytes) -> bool:
        return verify(self.ipk, self.ou_value, self.role, message, sig,
                      epoch_record=self.epoch_record)


class IdemixSigningIdentity:
    """Holder-side signer (the SigningIdentity duck type)."""

    def __init__(self, msp_id: str, ipk: IssuerPublicKey, cred: Credential):
        self.msp_id = msp_id
        self.ipk = ipk
        self.cred = cred

    @property
    def serialized(self) -> bytes:
        from fabric_tpu.protos import common_pb2

        return common_pb2.SerializedIdentity(
            mspid=self.msp_id,
            id_bytes=json.dumps({
                "type": "idemix", "ou": self.cred.ou, "role": self.cred.role,
            }, sort_keys=True).encode(),
        ).SerializeToString()

    def sign(self, message: bytes) -> bytes:
        return sign(self.ipk, self.cred, message)

    @property
    def identity(self) -> IdemixIdentity:
        return IdemixIdentity(
            self.msp_id, self.cred.ou, self.cred.role, self.ipk,
            self.serialized, True,
        )


class IdemixMSP:
    """MSP duck type backed by an issuer public key (msp/idemix.go).

    Serialized idemix identities disclose only (OU, role); org
    membership and attribute truth are proven per SIGNATURE by the
    presentation proof, so deserialization validates shape and the
    proof check rides Identity.verify."""

    def __init__(self, msp_id: str, ipk: IssuerPublicKey,
                 epoch_record: EpochRecord | None = None):
        self.msp_id = msp_id
        self.ipk = ipk
        # the newest RA-signed epoch statement this MSP has learned;
        # None = revocation not yet configured (epoch 0 accepted)
        self.epoch_record = epoch_record

    def set_epoch_record(self, rec: EpochRecord) -> None:
        """Adopt a newer epoch statement (monotonic: a replayed OLD
        record must not re-admit a revoked holder's credentials)."""
        if not rec.verify(self.ipk):
            raise ValueError("epoch record does not verify")
        if self.epoch_record is None or rec.epoch > self.epoch_record.epoch:
            self.epoch_record = rec

    def deserialize_identity(self, serialized: bytes):
        from fabric_tpu.protos import common_pb2

        pb = common_pb2.SerializedIdentity()
        pb.ParseFromString(serialized)
        try:
            d = json.loads(pb.id_bytes)
            ok = d.get("type") == "idemix" and "ou" in d and "role" in d
        except Exception:
            d, ok = {}, False
        return IdemixIdentity(
            pb.mspid, d.get("ou", ""), d.get("role", "client"),
            self.ipk, serialized, ok, epoch_record=self.epoch_record,
        )

    def satisfies_principal(self, ident, principal) -> bool:
        from fabric_tpu.crypto import policy as pol

        if isinstance(principal, pol.Principal):
            return principal.matched_by(ident)
        return False

    # -- config plumbing ---------------------------------------------------

    def to_proto(self):
        """configtx.MSPConfig (type 1 = IDEMIX) for the channel config
        (the duck method configtxgen's _org_group calls); the payload
        is the issuer public key."""
        return self.to_config()

    def to_config(self):
        """configtx.MSPConfig (type 1 = IDEMIX) for the channel
        config; the payload is the issuer public key."""
        from fabric_tpu.protos import configtx_pb2

        return configtx_pb2.MSPConfig(
            type=1,
            config=json.dumps({
                "msp_id": self.msp_id, "ipk": json.loads(self.ipk.to_json()),
                "epoch_record": (
                    json.loads(self.epoch_record.to_json())
                    if self.epoch_record is not None else None
                ),
            }, sort_keys=True).encode(),
        )

    @classmethod
    def from_config(cls, cfg_bytes: bytes) -> "IdemixMSP":
        """Channel-config ingestion.  The record is RA-verified here
        (fail closed on a forged one); ORDERING protection across
        configs comes from the channel-config machinery itself — a
        config update must advance the sequence through the authorized
        update path, so a node cannot be walked back to an older
        MSPConfig (and thus an older epoch) without forging a whole
        config chain.  set_epoch_record covers out-of-band record
        distribution between config updates, monotonically."""
        d = json.loads(cfg_bytes)
        ipk = IssuerPublicKey.from_json(json.dumps(d["ipk"]))
        rec = None
        if d.get("epoch_record"):
            rec = EpochRecord.from_json(json.dumps(d["epoch_record"]))
            if not rec.verify(ipk):
                raise ValueError("idemix epoch record does not verify")
        return cls(d["msp_id"], ipk, epoch_record=rec)

"""Membership Service Provider: cert-chain validation, roles, principals.

Analog of the reference's msp/ package (bccspmsp.Setup mspimpl.go:251,
DeserializeIdentity :380, SatisfiesPrincipal :425), X.509 only (idemix
is a separate provider).  Differences from the reference are
deliberate and TPU-motivated:

* Validation/classification results are cached per SerializedIdentity
  (the reference adds a cache layer, msp/cache) and exposed batch-wise:
  ``match_matrix`` classifies every distinct endorser of a block once,
  producing the [signers × principals] boolean matrix the policy
  kernel consumes (fabric_tpu.peer.device_block).
* Chain validation is explicit two-level (root → [intermediate] →
  leaf) path checking via issuer signature verification + validity
  windows + CRL serial check — the reference delegates to Go's x509
  verifier with the same effective checks.

NodeOUs (role from OU attribute) follow msp/mspimplsetup.go semantics:
when enabled, every identity must carry exactly one of the configured
role OUs; admins may additionally come from the explicit admin list.
"""

from __future__ import annotations

import datetime

from cryptography import x509
from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric import ec, padding

from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.identity import (
    ROLE_ADMIN,
    ROLE_CLIENT,
    ROLE_ORDERER,
    ROLE_PEER,
    Identity,
)
from fabric_tpu.protos import configtx_pb2, policies_pb2

_ROLE_BY_ENUM = {
    policies_pb2.MSPRole.MEMBER: "member",
    policies_pb2.MSPRole.ADMIN: ROLE_ADMIN,
    policies_pb2.MSPRole.CLIENT: ROLE_CLIENT,
    policies_pb2.MSPRole.PEER: ROLE_PEER,
    policies_pb2.MSPRole.ORDERER: ROLE_ORDERER,
}


def _verify_issued_by(cert: x509.Certificate, issuer: x509.Certificate) -> bool:
    if cert.issuer != issuer.subject:
        return False
    pub = issuer.public_key()
    try:
        if isinstance(pub, ec.EllipticCurvePublicKey):
            pub.verify(
                cert.signature, cert.tbs_certificate_bytes,
                ec.ECDSA(cert.signature_hash_algorithm),
            )
        else:
            pub.verify(
                cert.signature, cert.tbs_certificate_bytes,
                padding.PKCS1v15(), cert.signature_hash_algorithm,
            )
        return True
    except InvalidSignature:
        return False


class MSP:
    """One organization's membership provider."""

    def __init__(
        self,
        msp_id: str,
        root_certs: list[bytes],
        intermediate_certs: list[bytes] = (),
        admins: list[bytes] = (),
        revoked_serials: set[int] | None = None,
        node_ous: bool = True,
        ou_identifiers: dict[str, str] | None = None,
    ):
        self.msp_id = msp_id
        self.roots = [x509.load_pem_x509_certificate(c) for c in root_certs]
        self.intermediates = [
            x509.load_pem_x509_certificate(c) for c in intermediate_certs or ()
        ]
        self.admin_pems = {bytes(a) for a in (admins or ())}
        self.revoked_serials = revoked_serials or set()
        self.node_ous = node_ous
        # role -> OU string (defaults mirror cryptogen's config.yaml)
        self.ou_identifiers = ou_identifiers or {
            ROLE_CLIENT: "client",
            ROLE_PEER: "peer",
            ROLE_ADMIN: "admin",
            ROLE_ORDERER: "orderer",
        }
        self._cache: dict[bytes, Identity] = {}

    # -- config plumbing ---------------------------------------------------

    @classmethod
    def from_proto(cls, cfg: configtx_pb2.MSPConfig) -> "MSP":
        fab = configtx_pb2.FabricMSPConfig()
        fab.ParseFromString(cfg.config)
        ous = None
        if fab.fabric_node_ous.enable:
            ous = {
                ROLE_CLIENT: fab.fabric_node_ous.client_ou_identifier.organizational_unit_identifier or "client",
                ROLE_PEER: fab.fabric_node_ous.peer_ou_identifier.organizational_unit_identifier or "peer",
                ROLE_ADMIN: fab.fabric_node_ous.admin_ou_identifier.organizational_unit_identifier or "admin",
                ROLE_ORDERER: fab.fabric_node_ous.orderer_ou_identifier.organizational_unit_identifier or "orderer",
            }
        return cls(
            msp_id=fab.name,
            root_certs=list(fab.root_certs),
            intermediate_certs=list(fab.intermediate_certs),
            admins=list(fab.admins),
            node_ous=fab.fabric_node_ous.enable,
            ou_identifiers=ous,
        )

    def to_proto(self) -> configtx_pb2.MSPConfig:
        from cryptography.hazmat.primitives import serialization

        fab = configtx_pb2.FabricMSPConfig(name=self.msp_id)
        for c in self.roots:
            fab.root_certs.append(c.public_bytes(serialization.Encoding.PEM))
        for c in self.intermediates:
            fab.intermediate_certs.append(c.public_bytes(serialization.Encoding.PEM))
        for a in sorted(self.admin_pems):
            fab.admins.append(a)
        fab.fabric_node_ous.enable = self.node_ous
        fab.fabric_node_ous.client_ou_identifier.organizational_unit_identifier = self.ou_identifiers[ROLE_CLIENT]
        fab.fabric_node_ous.peer_ou_identifier.organizational_unit_identifier = self.ou_identifiers[ROLE_PEER]
        fab.fabric_node_ous.admin_ou_identifier.organizational_unit_identifier = self.ou_identifiers[ROLE_ADMIN]
        fab.fabric_node_ous.orderer_ou_identifier.organizational_unit_identifier = self.ou_identifiers[ROLE_ORDERER]
        return configtx_pb2.MSPConfig(type=0, config=fab.SerializeToString())

    # -- identity deserialization + validation -----------------------------

    def deserialize_identity(self, serialized: bytes) -> Identity:
        """Parse + validate + classify, memoized (analog msp/cache)."""
        hit = self._cache.get(serialized)
        if hit is not None:
            return hit
        ident = Identity.from_serialized(serialized)
        if ident.msp_id == self.msp_id:
            self._validate(ident)
        self._cache[serialized] = ident
        return ident

    def _cert_ok(self, cert: x509.Certificate, now) -> bool:
        """Validity window + revocation — applied to EVERY cert in the
        chain, not just the leaf (the reference's Go x509 verifier
        checks windows chain-wide; CRLs apply per issuing CA)."""
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return False
        return cert.serial_number not in self.revoked_serials

    def _chain_ok(self, cert: x509.Certificate) -> bool:
        """ANY fully valid chain accepts the cert — a failing candidate
        chain (e.g. an expired intermediate whose renewed reissue is
        also configured, as after CA rotation) must not preempt a valid
        alternate path."""
        now = datetime.datetime.now(datetime.timezone.utc)
        if not self._cert_ok(cert, now):
            return False

        def root_anchored(c: x509.Certificate) -> bool:
            return any(
                _verify_issued_by(c, root) and self._cert_ok(root, now)
                for root in self.roots
            )

        for ca in self.intermediates:
            if (
                _verify_issued_by(cert, ca)
                and self._cert_ok(ca, now)
                and root_anchored(ca)
            ):
                return True
        return root_anchored(cert)

    def _validate(self, ident: Identity) -> None:
        ident.is_valid = self._chain_ok(ident.cert)
        if not ident.is_valid:
            return
        sid = ident.serialized
        from fabric_tpu.protos import common_pb2

        pb = common_pb2.SerializedIdentity()
        pb.ParseFromString(sid)
        if self.node_ous:
            role_ous = {v: k for k, v in self.ou_identifiers.items()}
            roles = [role_ous[ou] for ou in ident.ous if ou in role_ous]
            if len(roles) != 1:
                # NodeOUs demands exactly one role OU (mspimplsetup.go)
                ident.is_valid = False
                return
            ident.role = roles[0]
        else:
            ident.role = ROLE_ADMIN if pb.id_bytes in self.admin_pems else ROLE_CLIENT
        if pb.id_bytes in self.admin_pems:
            ident.role = ROLE_ADMIN

    # -- principals --------------------------------------------------------

    def satisfies_principal(self, ident: Identity, principal: policies_pb2.MSPPrincipal) -> bool:
        cls = principal.principal_classification
        if cls == policies_pb2.MSPPrincipal.ROLE:
            role = policies_pb2.MSPRole()
            role.ParseFromString(principal.principal)
            if role.msp_identifier != ident.msp_id or not ident.is_valid:
                return False
            want = _ROLE_BY_ENUM[role.role]
            if want == "member":
                return True
            return ident.role == want
        if cls == policies_pb2.MSPPrincipal.ORGANIZATION_UNIT:
            ou = policies_pb2.OrganizationUnit()
            ou.ParseFromString(principal.principal)
            return (
                ident.is_valid
                and ou.msp_identifier == ident.msp_id
                and ou.organizational_unit_identifier in ident.ous
            )
        if cls == policies_pb2.MSPPrincipal.IDENTITY:
            return bytes(principal.principal) == ident.serialized and ident.is_valid
        return False


class MSPManager:
    """Channel-wide registry: msp_id → MSP (analog msp/mspmgrimpl.go).

    Deserialization is memoized by the serialized-identity bytes — the
    reference's msp/cache layer: a 1000-tx block re-presents the same
    handful of certs ~4000 times, and an x509 parse + chain validation
    per presentation would dominate the host side of the commit path.
    Membership changes invalidate by REPLACEMENT: a committed config
    update builds a fresh Bundle (fresh MSPManager, empty cache) and
    the peer swaps the validator onto it (peer/node.py _post_commit);
    direct mutation via ``add()`` also clears the cache."""

    CACHE_MAX = 4096

    def __init__(self, msps: dict[str, MSP] | None = None):
        self.msps = dict(msps or {})
        self._ident_cache: dict[bytes, Identity] = {}

    def add(self, msp: MSP) -> None:
        self.msps[msp.msp_id] = msp
        self.invalidate_cache()

    def invalidate_cache(self) -> None:
        self._ident_cache.clear()

    def deserialize_identity(self, serialized: bytes) -> Identity:
        got = self._ident_cache.get(serialized)
        if got is not None:
            return got
        ident = self._deserialize_uncached(serialized)
        if len(self._ident_cache) >= self.CACHE_MAX:
            self._ident_cache.clear()
        self._ident_cache[serialized] = ident
        return ident

    def _deserialize_uncached(self, serialized: bytes) -> Identity:
        from fabric_tpu.protos import common_pb2

        pb = common_pb2.SerializedIdentity()
        pb.ParseFromString(serialized)
        msp = self.msps.get(pb.mspid)
        if msp is None:
            ident = Identity.from_serialized(serialized)
            ident.is_valid = False
            return ident
        return msp.deserialize_identity(serialized)

    def satisfies_principal(self, ident: Identity, principal) -> bool:
        msp = self.msps.get(ident.msp_id)
        return bool(msp and msp.satisfies_principal(ident, principal))

    # -- batch glue for the policy kernel ----------------------------------

    def match_matrix(self, serialized_ids: list[bytes], principals: list) -> "np.ndarray":
        """[S, P] bool principal-match matrix for a block's endorsers.

        principals: list of policies_pb2.MSPPrincipal OR
        crypto.policy.Principal (duck-typed via matched_by)."""
        import numpy as np

        idents = [self.deserialize_identity(s) for s in serialized_ids]
        out = np.zeros((len(idents), len(principals)), bool)
        for i, ident in enumerate(idents):
            for j, p in enumerate(principals):
                if isinstance(p, pol.Principal):
                    out[i, j] = p.matched_by(ident)
                else:
                    out[i, j] = self.satisfies_principal(ident, p)
        return out


def principal_from_proto(p: policies_pb2.MSPPrincipal) -> pol.Principal:
    """Proto ROLE principal → the policy engine's host Principal."""
    if p.principal_classification != policies_pb2.MSPPrincipal.ROLE:
        raise ValueError("only ROLE principals map to policy.Principal")
    role = policies_pb2.MSPRole()
    role.ParseFromString(p.principal)
    return pol.Principal(role.msp_identifier, _ROLE_BY_ENUM[role.role])


def policy_from_proto(env: policies_pb2.SignaturePolicyEnvelope):
    """SignaturePolicyEnvelope → crypto.policy AST (the compiler input).

    Contrast cauthdsl.go:24-110 which compiles to closures; here the
    proto becomes a plain AST that compile_plan flattens to arrays."""

    def walk(rule: policies_pb2.SignaturePolicy):
        kind = rule.WhichOneof("Type")
        if kind == "signed_by":
            return pol.SignedBy(principal_from_proto(env.identities[rule.signed_by]))
        n = rule.n_out_of
        return pol.NOutOf(n.n, tuple(walk(r) for r in n.rules))

    return walk(env.rule)


def policy_to_proto(rule) -> policies_pb2.SignaturePolicyEnvelope:
    env = policies_pb2.SignaturePolicyEnvelope(version=0)
    pindex: dict = {}

    def principal_idx(principal: pol.Principal) -> int:
        if principal not in pindex:
            pindex[principal] = len(env.identities)
            role_enum = {v: k for k, v in _ROLE_BY_ENUM.items()}[principal.role]
            mrole = policies_pb2.MSPRole(
                msp_identifier=principal.msp_id, role=role_enum
            )
            env.identities.add(
                principal_classification=policies_pb2.MSPPrincipal.ROLE,
                principal=mrole.SerializeToString(),
            )
        return pindex[principal]

    def walk(node) -> policies_pb2.SignaturePolicy:
        out = policies_pb2.SignaturePolicy()
        if isinstance(node, pol.SignedBy):
            out.signed_by = principal_idx(node.principal)
        else:
            out.n_out_of.n = node.n
            for r in node.rules:
                out.n_out_of.rules.append(walk(r))
        return out

    env.rule.CopyFrom(walk(rule))
    return env

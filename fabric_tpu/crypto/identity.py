"""X.509 identities: parsing, signing, verification glue.

Host-side identity handling (analog of msp/identities.go).  The
expensive part — ECDSA verification — is NOT done here per-identity:
identities expose their public-key coordinates so the commit pipeline
can feed the whole block's (digest, r, s, qx, qy) tuples to the batched
TPU kernel (fabric_tpu.ops.p256).  ``verify`` below is the host
fallback (reference semantics: msp/identities.go:170-199 — SHA-256 the
message, then ECDSA-verify with low-S enforcement per
bccsp/sw/ecdsa.go:41-58).

Signatures are DER-encoded (r, s) with low-S normalization at signing,
exactly like the reference's SW BCCSP signer.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import cached_property

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

from fabric_tpu.crypto import ec_ref
from fabric_tpu.protos import common_pb2

ROLE_CLIENT = "client"
ROLE_PEER = "peer"
ROLE_ADMIN = "admin"
ROLE_ORDERER = "orderer"


def sig_to_ints(der_sig: bytes) -> tuple[int, int]:
    return decode_dss_signature(der_sig)


def ints_to_sig(r: int, s: int) -> bytes:
    return encode_dss_signature(r, s)


def low_s(s: int) -> int:
    return ec_ref.N - s if s > ec_ref.HALF_N else s


@dataclass
class Identity:
    """A deserialized (mspid, certificate) pair."""

    msp_id: str
    cert: x509.Certificate
    serialized: bytes  # the SerializedIdentity bytes (cache key)
    # filled by MSP.validate:
    is_valid: bool = False
    role: str = ROLE_CLIENT
    ous: tuple = ()

    @classmethod
    def from_serialized(cls, data: bytes) -> "Identity":
        sid = common_pb2.SerializedIdentity()
        sid.ParseFromString(data)
        cert = x509.load_pem_x509_certificate(sid.id_bytes)
        ident = cls(msp_id=sid.mspid, cert=cert, serialized=data)
        ident.ous = tuple(
            a.value
            for a in cert.subject.get_attributes_for_oid(
                x509.NameOID.ORGANIZATIONAL_UNIT_NAME
            )
        )
        return ident

    @cached_property
    def public_numbers(self):
        pub = self.cert.public_key()
        if not isinstance(pub, ec.EllipticCurvePublicKey):
            raise ValueError("only EC public keys supported")
        n = pub.public_numbers()
        return (n.x, n.y)

    @cached_property
    def rns_pub(self):
        """(qx_residues, qy_residues) [2n] int32 — cached per identity
        so the commit path's signature-batch assembly is a numpy gather
        over the block's (few) distinct endorser keys, not a per-item
        bigint→residue conversion (a block re-presents the same certs
        thousands of times)."""
        from fabric_tpu.ops import rns

        qx, qy = self.public_numbers
        res = rns.ints_to_rns([qx, qy])
        return res[0], res[1]

    def verify_item(self, message: bytes, der_sig: bytes):
        """→ (digest_int, r, s, qx, qy) for the batched TPU verifier."""
        r, s = decode_dss_signature(der_sig)
        qx, qy = self.public_numbers
        return (int.from_bytes(hashlib.sha256(message).digest(), "big"), r, s, qx, qy)

    def verify(self, message: bytes, der_sig: bytes) -> bool:
        """Host verify via OpenSSL (the reference's SW-BCCSP speed
        class) with the exact reference accept set: low-S enforced on
        top of the raw curve check (bccsp/sw/ecdsa.go:41-58)."""
        try:
            r, s = decode_dss_signature(der_sig)
        except Exception:
            return False
        if not (0 < r < ec_ref.N and 0 < s <= ec_ref.HALF_N):
            return False
        try:
            self.cert.public_key().verify(der_sig, message, ec.ECDSA(hashes.SHA256()))
            return True
        except Exception:
            return False


class SigningIdentity:
    """Private key + cert: the local signer (analog of
    msp.signingidentity; low-S normalization as in bccsp/sw signer)."""

    def __init__(self, msp_id: str, key: ec.EllipticCurvePrivateKey, cert: x509.Certificate):
        if not isinstance(key.curve, ec.SECP256R1):
            raise ValueError("P-256 keys only")
        self.msp_id = msp_id
        self.key = key
        self.cert = cert

    @classmethod
    def from_pem(cls, msp_id: str, key_pem: bytes, cert_pem: bytes) -> "SigningIdentity":
        key = serialization.load_pem_private_key(key_pem, password=None)
        cert = x509.load_pem_x509_certificate(cert_pem)
        return cls(msp_id, key, cert)

    @cached_property
    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    @cached_property
    def serialized(self) -> bytes:
        return common_pb2.SerializedIdentity(
            mspid=self.msp_id, id_bytes=self.cert_pem
        ).SerializeToString()

    def sign(self, message: bytes) -> bytes:
        der = self.key.sign(message, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        return encode_dss_signature(r, low_s(s))

    @property
    def identity(self) -> Identity:
        ident = Identity.from_serialized(self.serialized)
        ident.is_valid = True
        return ident

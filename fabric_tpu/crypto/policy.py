"""Signature-policy engine: AST, DSL, compiler → batch plan, interpreter.

The reference compiles a SignaturePolicyEnvelope proto into a tree of
Go closures evaluated per transaction with short-circuiting and
signature *consumption* (each endorsement satisfies at most one
SignedBy leaf) — common/cauthdsl/cauthdsl.go:24-110, policy.go:86 —
plus a text DSL ``AND('Org1.member', ...)`` (common/policydsl).

The TPU-first redesign flattens the tree into a *batch plan*: a list of
principals (leaf columns) plus a post-order gate array, so that policy
evaluation over a whole block becomes array ops on the boolean
signature-validity vector produced by the batched ECDSA kernel
(fabric_tpu.ops.p256) — see fabric_tpu.peer.device_block.

Two evaluators:

* ``evaluate`` — exact sequential interpreter with the reference's
  greedy consumption semantics (the oracle, and the fallback for
  adversarial cases where one signature satisfies multiple leaves).
* the batch kernel path — exact whenever no signature satisfies two
  distinct leaf principals (the overwhelming case: org-scoped
  endorsement policies).  ``plan.consumption_safe(match)`` checks this
  per transaction at run time, so the fast path is taken per-tx, never
  unsoundly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


# ---------------------------------------------------------------------------
# Principals (subset mirroring msp.MSPPrincipal: ROLE / OU / IDENTITY)

ROLE_MEMBER = "member"
ROLE_ADMIN = "admin"
ROLE_CLIENT = "client"
ROLE_PEER = "peer"
ROLE_ORDERER = "orderer"
_ROLES = {ROLE_MEMBER, ROLE_ADMIN, ROLE_CLIENT, ROLE_PEER, ROLE_ORDERER}


@dataclass(frozen=True)
class Principal:
    """msp_id + role principal (msp/mspimpl.go:425 SatisfiesPrincipal)."""

    msp_id: str
    role: str = ROLE_MEMBER

    def matched_by(self, identity) -> bool:
        """identity: any object with .msp_id, .role ('admin'/'client'/
        'peer'/...), and .is_valid (cert-chain validity)."""
        if identity.msp_id != self.msp_id or not getattr(identity, "is_valid", True):
            return False
        if self.role == ROLE_MEMBER:
            return True
        return getattr(identity, "role", None) == self.role


# ---------------------------------------------------------------------------
# Policy AST


@dataclass(frozen=True)
class SignedBy:
    principal: Principal


@dataclass(frozen=True)
class NOutOf:
    n: int
    rules: tuple

    def __post_init__(self):
        if not (0 <= self.n <= len(self.rules)):
            raise ValueError(f"NOutOf({self.n}) over {len(self.rules)} rules")


def And(*rules):
    return NOutOf(len(rules), tuple(rules))


def Or(*rules):
    return NOutOf(1, tuple(rules))


# ---------------------------------------------------------------------------
# Text DSL: AND('Org1.member', OR('Org2.admin', 'Org3.peer')),
# OutOf(2, 'A.member', 'B.member', 'C.member')  (common/policydsl grammar)

_PRINCIPAL_RE = re.compile(r"^([A-Za-z0-9._-]+)\.(\w+)$")


def from_dsl(text: str):
    """Parse the policydsl grammar into the AST."""
    text = text.strip()
    tokens = re.findall(r"[A-Za-z]+\(|\)|,|'[^']*'|\"[^\"]*\"|\d+", text)
    pos = 0

    def parse():
        nonlocal pos
        tok = tokens[pos]
        if tok.endswith("("):
            op = tok[:-1].upper()
            pos += 1
            args = []
            while tokens[pos] != ")":
                if tokens[pos] == ",":
                    pos += 1
                    continue
                args.append(parse())
            pos += 1  # consume ')'
            if op == "AND":
                return And(*args)
            if op == "OR":
                return Or(*args)
            if op == "OUTOF":
                n = args[0]
                if not isinstance(n, int):
                    raise ValueError("OutOf needs integer first arg")
                return NOutOf(n, tuple(args[1:]))
            raise ValueError(f"unknown op {op}")
        if tok.isdigit():
            pos += 1
            return int(tok)
        if tok[0] in "'\"":
            pos += 1
            m = _PRINCIPAL_RE.match(tok[1:-1])
            if not m:
                raise ValueError(f"bad principal {tok}")
            msp_id, role = m.groups()
            if role not in _ROLES:
                raise ValueError(f"bad role {role}")
            return SignedBy(Principal(msp_id, role))
        raise ValueError(f"unexpected token {tok}")

    rule = parse()
    if pos != len(tokens) or isinstance(rule, int):
        raise ValueError(f"trailing tokens in policy: {text}")
    return rule


# ---------------------------------------------------------------------------
# Batch plan: flattened post-order gate program


@dataclass
class BatchPlan:
    """Flattened policy for array evaluation.

    principals: leaf columns, deduplicated.
    leaf_principal: for each leaf node, its column in ``principals``.
    gates: post-order list of (n, child_slots) where child_slots index
        into the value vector: slots [0, n_leaves) are leaves, then one
        slot per gate in order.  The last gate is the root.
    A tree that is a bare SignedBy gets a single 1-of-1 gate.
    """

    principals: list = field(default_factory=list)
    leaf_principal: list = field(default_factory=list)
    leaf_rank: list = field(default_factory=list)
    gates: list = field(default_factory=list)

    @property
    def n_leaves(self) -> int:
        return len(self.leaf_principal)

    def leaf_sat(self, match):
        """match: [S, P] bool (sig × principal) → [n_leaves] bool.

        Leaf truth under consumption: the r-th leaf (in evaluation
        order) referencing principal column p is satisfied iff at least
        r+1 signatures match p — so repeated-principal policies like
        ``OutOf(2, 'Org1.member', 'Org1.member')`` need two DISTINCT
        signatures (cauthdsl.go greedy consumption; exact whenever
        ``consumption_safe``)."""
        import numpy as np

        return self.leaf_sat_batch(np.asarray(match)[None])[0]

    def leaf_sat_batch(self, m3):
        """[T, S, P] bool → [T, n_leaves] bool — the single source of
        truth for count-based leaf semantics (the scalar APIs and the
        device kernel in peer/device_block mirror THIS; a cross-check
        test pins them together)."""
        import numpy as np

        m3 = np.asarray(m3, bool)
        T = m3.shape[0]
        if self.n_leaves == 0:
            return np.zeros((T, 0), bool)
        counts = m3.sum(axis=1)  # [T, P] distinct sigs per column
        cols = np.asarray(self.leaf_principal, int)
        ranks = np.asarray(self.leaf_rank, int)
        return ranks[None, :] < counts[:, cols]

    def evaluate_counts(self, match) -> bool:
        """Count-based evaluation: exact when ``consumption_safe``."""
        import numpy as np

        return bool(self.evaluate_counts_batch(np.asarray(match)[None])[0])

    def evaluate_counts_batch(self, m3):
        """[T, S, P] → [T] bool, vectorized gate walk."""
        import numpy as np

        m3 = np.asarray(m3, bool)
        T = m3.shape[0]
        leaf = self.leaf_sat_batch(m3)
        vals = [leaf[:, i] for i in range(self.n_leaves)]
        for n, children in self.gates:
            acc = np.zeros(T, int)
            for c in children:
                acc += vals[c].astype(int)
            vals.append(acc >= n)
        return vals[-1]

    def consumption_safe(self, match) -> bool:
        """True if no signature satisfies two distinct leaf principals
        (then count semantics == the reference's consumption
        semantics)."""
        import numpy as np

        return bool(self.consumption_safe_batch(np.asarray(match)[None])[0])

    def consumption_safe_batch(self, m3):
        """[T, S, P] → [T] bool."""
        import numpy as np

        m3 = np.asarray(m3, bool)
        if m3.size == 0:
            return np.ones(m3.shape[0], bool)
        cols = np.asarray(sorted(set(self.leaf_principal)), int)
        return (m3[:, :, cols].sum(axis=2) <= 1).all(axis=1)


def compile_plan(rule) -> BatchPlan:
    """Flatten the AST into a BatchPlan (contrast cauthdsl's closure
    compiler: the output is data, not code)."""
    plan = BatchPlan()
    pindex: dict = {}

    def leaf_col(principal: Principal) -> int:
        if principal not in pindex:
            pindex[principal] = len(plan.principals)
            plan.principals.append(principal)
        return pindex[principal]

    col_uses: dict = {}

    # first pass: count leaves to lay out slots
    def walk(node):
        if isinstance(node, SignedBy):
            slot = plan.n_leaves
            col = leaf_col(node.principal)
            plan.leaf_principal.append(col)
            # rank of this leaf among leaves of the same column, in
            # evaluation (DFS, left-to-right) order — consumption's
            # per-column signature budget index
            plan.leaf_rank.append(col_uses.get(col, 0))
            col_uses[col] = col_uses.get(col, 0) + 1
            return ("leaf", slot)
        if isinstance(node, NOutOf):
            children = [walk(r) for r in node.rules]
            return ("gate", node.n, children)
        raise TypeError(f"bad policy node {node!r}")

    tree = walk(rule)
    n_leaves = plan.n_leaves

    def emit(node) -> int:
        if node[0] == "leaf":
            return node[1]
        _, n, children = node
        slots = [emit(c) for c in children]
        plan.gates.append((n, slots))
        return n_leaves + len(plan.gates) - 1

    root = emit(tree)
    if not plan.gates or root != n_leaves + len(plan.gates) - 1:
        # bare SignedBy root: wrap in a 1-of-1 gate
        plan.gates.append((1, [root]))
    return plan


# ---------------------------------------------------------------------------
# Exact interpreter (the reference's consumption semantics)


def evaluate(rule, match) -> bool:
    """Evaluate with greedy signature consumption.

    match: [S, P_all] bool where columns follow ``compile_plan(rule)
    .principals`` — use ``match_matrix`` to build it.  Mirrors
    cauthdsl.go:39-110: SignedBy consumes the first unused matching
    signature; NOutOf evaluates ALL children left-to-right (no
    short-circuit — every satisfied child consumes its signature) and
    compares the count against n.
    """
    import numpy as np

    plan = compile_plan(rule)
    pindex = {p: i for i, p in enumerate(plan.principals)}
    m = np.asarray(match)
    S = m.shape[0] if m.size else 0
    used = [False] * S

    def ev(node) -> bool:
        if isinstance(node, SignedBy):
            col = pindex[node.principal]
            for s in range(S):
                if not used[s] and m[s, col]:
                    used[s] = True
                    return True
            return False
        count = 0
        for r in node.rules:
            if ev(r):
                count += 1
        return count >= node.n

    root = rule if isinstance(rule, NOutOf) else NOutOf(1, (rule,))
    return ev(root)


def match_matrix(identities, principals) -> "np.ndarray":
    """[S, P] bool: identity s satisfies principal p (host-side MSP
    SatisfiesPrincipal batch)."""
    import numpy as np

    return np.array(
        [[p.matched_by(ident) for p in principals] for ident in identities],
        dtype=bool,
    ).reshape(len(identities), len(principals))

"""Dev-network crypto material generator (analog of the reference's
cryptogen tool, internal/cryptogen, and tlsgen, common/crypto/tlsgen).

Generates per-org ECDSA-P256 CAs and node/user certificates with
NodeOUs role OUs in the subject, in-memory or onto disk in an
msp-directory layout.  TLS material (separate CA, SAN=localhost) backs
the gRPC mutual-TLS transport (fabric_tpu/rpc).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from dataclasses import dataclass, field

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

ONE_DAY = datetime.timedelta(days=1)
TEN_YEARS = datetime.timedelta(days=3650)


def _name(cn: str, org: str, ou: str | None = None) -> x509.Name:
    attrs = [
        x509.NameAttribute(NameOID.COUNTRY_NAME, "US"),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, cn),
    ]
    if ou:
        attrs.insert(2, x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou))
    return x509.Name(attrs)


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _pem_cert(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


@dataclass
class CA:
    """Self-signed ECDSA CA."""

    org: str
    cn: str
    key: ec.EllipticCurvePrivateKey
    cert: x509.Certificate

    @classmethod
    def create(cls, org: str, cn: str | None = None) -> "CA":
        cn = cn or f"ca.{org}"
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        name = _name(cn, org)
        cert = (
            x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - ONE_DAY)
            .not_valid_after(now + TEN_YEARS)
            .add_extension(x509.BasicConstraints(ca=True, path_length=1), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True, crl_sign=True,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False,
                ),
                critical=True,
            )
            .sign(key, hashes.SHA256())
        )
        return cls(org=org, cn=cn, key=key, cert=cert)

    @property
    def cert_pem(self) -> bytes:
        return _pem_cert(self.cert)

    def issue(
        self,
        cn: str,
        ou: str | None = None,
        sans: list[str] | None = None,
        ca: bool = False,
    ) -> "Enrollment":
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(_name(cn, self.org, ou))
            .issuer_name(self.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - ONE_DAY)
            .not_valid_after(now + TEN_YEARS)
            .add_extension(x509.BasicConstraints(ca=ca, path_length=None), critical=True)
        )
        if sans:
            alt = []
            for s in sans:
                try:
                    alt.append(x509.IPAddress(ipaddress.ip_address(s)))
                except ValueError:
                    alt.append(x509.DNSName(s))
            builder = builder.add_extension(
                x509.SubjectAlternativeName(alt), critical=False
            )
        cert = builder.sign(self.key, hashes.SHA256())
        return Enrollment(key=key, cert=cert, ca_cert=self.cert)


@dataclass
class Enrollment:
    key: ec.EllipticCurvePrivateKey
    cert: x509.Certificate
    ca_cert: x509.Certificate

    @property
    def key_pem(self) -> bytes:
        return _pem_key(self.key)

    @property
    def cert_pem(self) -> bytes:
        return _pem_cert(self.cert)

    @property
    def ca_pem(self) -> bytes:
        return _pem_cert(self.ca_cert)


@dataclass
class OrgMaterial:
    """All crypto material for one org: signing CA, TLS CA, nodes, users."""

    msp_id: str
    domain: str
    ca: CA
    tls_ca: CA
    nodes: dict = field(default_factory=dict)  # name -> Enrollment (sign)
    tls: dict = field(default_factory=dict)    # name -> Enrollment (tls)
    users: dict = field(default_factory=dict)

    def msp(self):
        from fabric_tpu.crypto.msp import MSP

        return MSP(
            msp_id=self.msp_id,
            root_certs=[self.ca.cert_pem],
            node_ous=True,
        )


def generate_org(
    msp_id: str,
    domain: str,
    peers: int = 1,
    orderers: int = 0,
    users: int = 1,
    admin: bool = True,
) -> OrgMaterial:
    """One org's full material (cryptogen `generate` equivalent)."""
    ca = CA.create(domain)
    tls_ca = CA.create(domain, cn=f"tlsca.{domain}")
    org = OrgMaterial(msp_id=msp_id, domain=domain, ca=ca, tls_ca=tls_ca)
    for i in range(peers):
        name = f"peer{i}.{domain}"
        org.nodes[name] = ca.issue(name, ou="peer")
        org.tls[name] = tls_ca.issue(name, sans=[name, "localhost", "127.0.0.1"])
    for i in range(orderers):
        name = f"orderer{i}.{domain}"
        org.nodes[name] = ca.issue(name, ou="orderer")
        org.tls[name] = tls_ca.issue(name, sans=[name, "localhost", "127.0.0.1"])
    if admin:
        org.users[f"Admin@{domain}"] = ca.issue(f"Admin@{domain}", ou="admin")
    for i in range(users):
        name = f"User{i + 1}@{domain}"
        org.users[name] = ca.issue(name, ou="client")
    return org


def signing_identity(org: OrgMaterial, name: str):
    """SigningIdentity for a node or user of the org."""
    from fabric_tpu.crypto.identity import SigningIdentity

    enr = org.nodes.get(name) or org.users.get(name)
    if enr is None:
        raise KeyError(name)
    return SigningIdentity(org.msp_id, enr.key, enr.cert)


def write_msp_dir(base: str, enr: Enrollment, ca_pem: bytes) -> None:
    """cryptogen-style msp/ directory layout."""
    for sub in ("cacerts", "keystore", "signcerts"):
        os.makedirs(os.path.join(base, sub), exist_ok=True)
    with open(os.path.join(base, "cacerts", "ca.pem"), "wb") as f:
        f.write(ca_pem)
    with open(os.path.join(base, "keystore", "key.pem"), "wb") as f:
        f.write(enr.key_pem)
    with open(os.path.join(base, "signcerts", "cert.pem"), "wb") as f:
        f.write(enr.cert_pem)


def write_org(org: OrgMaterial, base: str) -> str:
    """Full cryptogen output layout for one org:
    <base>/<domain>/{ca/, msp/cacerts/, peers|orderers|users/<name>/msp/}.
    Returns the org directory."""
    root = os.path.join(base, org.domain)
    os.makedirs(os.path.join(root, "ca"), exist_ok=True)
    with open(os.path.join(root, "ca", "ca-cert.pem"), "wb") as f:
        f.write(org.ca.cert_pem)
    with open(os.path.join(root, "ca", "ca-key.pem"), "wb") as f:
        f.write(_pem_key(org.ca.key))
    os.makedirs(os.path.join(root, "msp", "cacerts"), exist_ok=True)
    with open(os.path.join(root, "msp", "cacerts", "ca.pem"), "wb") as f:
        f.write(org.ca.cert_pem)
    with open(os.path.join(root, "msp", "config.json"), "w") as f:
        import json

        json.dump({"msp_id": org.msp_id, "node_ous": True}, f)
    for group, members in (("nodes", org.nodes), ("users", org.users)):
        for name, enr in members.items():
            write_msp_dir(os.path.join(root, group, name, "msp"),
                          enr, org.ca.cert_pem)
    # TLS material: org TLS-CA cert + per-node server cert/key — the
    # mTLS profile every listener/dialer loads (cryptogen tls layout)
    os.makedirs(os.path.join(root, "tlsca"), exist_ok=True)
    with open(os.path.join(root, "tlsca", "tlsca-cert.pem"), "wb") as f:
        f.write(org.tls_ca.cert_pem)
    for name, enr in org.tls.items():
        tdir = os.path.join(root, "nodes", name, "tls")
        os.makedirs(tdir, exist_ok=True)
        with open(os.path.join(tdir, "server.pem"), "wb") as f:
            f.write(enr.cert_pem)
        with open(os.path.join(tdir, "key.pem"), "wb") as f:
            f.write(enr.key_pem)
        with open(os.path.join(tdir, "ca.pem"), "wb") as f:
            f.write(org.tls_ca.cert_pem)
    return root


def load_tls_profile(org_dir: str, node_name: str, ca_bundle: bytes | None = None):
    """comm.rpc.TlsProfile for one node from a write_org directory.
    ``ca_bundle``: concatenated trusted TLS-CA certs (defaults to this
    org's own TLS CA — pass the union for cross-org networks)."""
    import os as _os

    from fabric_tpu.comm.rpc import TlsProfile

    tdir = _os.path.join(org_dir, "nodes", node_name, "tls")
    with open(_os.path.join(tdir, "server.pem"), "rb") as f:
        cert = f.read()
    with open(_os.path.join(tdir, "key.pem"), "rb") as f:
        key = f.read()
    if ca_bundle is None:
        with open(_os.path.join(tdir, "ca.pem"), "rb") as f:
            ca_bundle = f.read()
    return TlsProfile(cert, key, ca_bundle)


def load_org_msp(org_dir: str):
    """→ crypto.msp.MSP from a write_org directory."""
    import json

    from fabric_tpu.crypto.msp import MSP

    with open(os.path.join(org_dir, "msp", "config.json")) as f:
        cfg = json.load(f)
    with open(os.path.join(org_dir, "msp", "cacerts", "ca.pem"), "rb") as f:
        root_pem = f.read()
    return MSP(msp_id=cfg["msp_id"], root_certs=[root_pem],
               node_ous=bool(cfg.get("node_ous", True)))


def load_signing_identity(msp_dir: str, msp_id: str):
    """→ SigningIdentity from an msp/ directory (keystore + signcerts)."""
    from fabric_tpu.crypto.identity import SigningIdentity

    with open(os.path.join(msp_dir, "keystore", "key.pem"), "rb") as f:
        key_pem = f.read()
    with open(os.path.join(msp_dir, "signcerts", "cert.pem"), "rb") as f:
        cert_pem = f.read()
    return SigningIdentity.from_pem(msp_id, key_pem, cert_pem)

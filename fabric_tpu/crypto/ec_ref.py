"""Pure-Python NIST P-256 reference implementation (correctness oracle).

This is the host-side oracle the TPU kernel (`fabric_tpu.ops.p256`) is
tested bit-exactly against, and the arithmetic backing for key/cert
generation where the `cryptography` package is not used.  Semantics
mirror the reference's SW BCCSP verifier: ECDSA P-256 with SHA-256
digests and the low-S rule (reference: bccsp/sw/ecdsa.go:41-58 —
signatures with s > n/2 are rejected; signing normalizes s to low-S).

Python ints only; NOT constant-time; verify-only paths don't need to be.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
from dataclasses import dataclass

# NIST P-256 (secp256r1) domain parameters.
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = N >> 1

INF = None  # point at infinity


def is_on_curve(pt) -> bool:
    if pt is INF:
        return True
    x, y = pt
    return (y * y - (x * x * x + A * x + B)) % P == 0


def pt_add(p1, p2):
    if p1 is INF:
        return p2
    if p2 is INF:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return INF
        return pt_double(p1)
    lam = ((y2 - y1) * pow(x2 - x1, -1, P)) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def pt_double(pt):
    if pt is INF:
        return INF
    x, y = pt
    if y == 0:
        return INF
    lam = ((3 * x * x + A) * pow(2 * y, -1, P)) % P
    x3 = (lam * lam - 2 * x) % P
    y3 = (lam * (x - x3) - y) % P
    return (x3, y3)


def pt_mul(k: int, pt):
    k %= N
    acc = INF
    addend = pt
    while k:
        if k & 1:
            acc = pt_add(acc, addend)
        addend = pt_double(addend)
        k >>= 1
    return acc


G = (GX, GY)


# ---------------------------------------------------------------------------
# RFC 6979 deterministic nonce derivation (HMAC-SHA256, qlen = 256).
#
# This is the nonce contract shared by the serial signer below and the
# device batch-sign lane (fabric_tpu.ops.p256sign): both derive k from
# (d, e) with the exact HMAC_DRBG construction of RFC 6979 §3.2, so a
# signature is a pure function of (key, digest) — seeded replay works,
# and the device lane has a bit-equal CPU oracle to diff against.
# Pinned against the RFC's published A.2.5 P-256/SHA-256 vectors in
# tests/test_p256sign.py.

_QLEN_BYTES = 32  # qlen = 256 bits; SHA-256 ⇒ holen = 32 too


def rfc6979_candidates(d: int, e: int):
    """Successive RFC 6979 §3.2 nonce candidates for P-256/SHA-256.

    ``d``: private scalar in [1, n−1].  ``e``: the message digest as a
    256-bit integer (``digest_int``) — re-serialized to the 32 bytes
    H(m) so the derivation matches the RFC byte for byte.  With
    qlen == hlen == 256, bits2int is the identity and bits2octets is
    one reduction mod n.  Yields k values in [1, n−1]; the caller
    advances past a candidate only when it degenerates (r or s zero,
    the RFC's step h.3 retry — probability ≈ 2⁻²⁵⁶)."""
    if not (1 <= d < N):
        raise ValueError("private scalar out of range")
    x_oct = int(d).to_bytes(_QLEN_BYTES, "big")          # int2octets(x)
    h_oct = (int(e) % N).to_bytes(_QLEN_BYTES, "big")    # bits2octets
    V = b"\x01" * 32
    K = b"\x00" * 32
    mac = lambda key, msg: hmac.new(key, msg, hashlib.sha256).digest()
    K = mac(K, V + b"\x00" + x_oct + h_oct)
    V = mac(K, V)
    K = mac(K, V + b"\x01" + x_oct + h_oct)
    V = mac(K, V)
    while True:
        V = mac(K, V)
        k = int.from_bytes(V, "big")  # T is exactly qlen bits
        if 1 <= k < N:
            yield k
        K = mac(K, V + b"\x00")
        V = mac(K, V)


def rfc6979_k(d: int, e: int) -> int:
    """First RFC 6979 nonce candidate — THE deterministic k for
    (d, e) in every practical case (later candidates exist only for
    the 2⁻²⁵⁶ degenerate-signature retry)."""
    return next(rfc6979_candidates(d, e))


# ---------------------------------------------------------------------------
# Minimal DER (r, s) codec — the SW BCCSP signature wire form, pure
# Python so the sign lane (and its tests) run without `cryptography`.
# P-256 r/s are < 2^256, so every length fits the short form.


def _der_int(v: int) -> bytes:
    b = int(v).to_bytes((v.bit_length() + 8) // 8 or 1, "big")
    return b"\x02" + bytes([len(b)]) + b


def der_encode_sig(r: int, s: int) -> bytes:
    """(r, s) → DER ECDSA-Sig-Value (SEQUENCE of two INTEGERs)."""
    if not (0 < r < N and 0 < s < N):
        raise ValueError("r/s out of range")
    body = _der_int(r) + _der_int(s)
    return b"\x30" + bytes([len(body)]) + body


def der_decode_sig(der: bytes) -> tuple[int, int]:
    """DER ECDSA-Sig-Value → (r, s); strict short-form parse."""
    if len(der) < 8 or der[0] != 0x30 or der[1] != len(der) - 2:
        raise ValueError("bad DER signature envelope")
    out = []
    off = 2
    for _ in range(2):
        if off + 2 > len(der) or der[off] != 0x02:
            raise ValueError("bad DER integer tag")
        ln = der[off + 1]
        off += 2
        if ln == 0 or off + ln > len(der) or ln > 33:
            raise ValueError("bad DER integer length")
        out.append(int.from_bytes(der[off:off + ln], "big"))
        off += ln
    if off != len(der):
        raise ValueError("trailing DER bytes")
    return out[0], out[1]


@dataclass(frozen=True)
class SigningKey:
    d: int  # private scalar in [1, n-1]

    @property
    def public(self):
        return pt_mul(self.d, G)

    @classmethod
    def generate(cls) -> "SigningKey":
        return cls(d=secrets.randbelow(N - 1) + 1)

    def sign_digest(self, e: int, k: int | None = None) -> tuple[int, int]:
        """ECDSA sign; returns low-S normalized (r, s).

        ``k`` None derives the nonce DETERMINISTICALLY per RFC 6979
        (``rfc6979_k``) — a signature is then a pure function of
        (d, e): replayable, and the bit-equal oracle the device batch
        signer (fabric_tpu.ops.p256sign) is diffed against.  An
        explicit ``k`` is for tests/vectors only; r == 0 or s == 0
        with a fixed k raises instead of looping."""
        fixed = k is not None
        cands = iter([k]) if fixed else rfc6979_candidates(self.d, e)
        for kk in cands:
            x1, _ = pt_mul(kk, G)
            r = x1 % N
            s = (pow(kk, -1, N) * (e + r * self.d)) % N if r else 0
            if r == 0 or s == 0:
                if fixed:
                    raise ValueError("bad fixed k")
                continue  # RFC 6979 step h.3: next candidate
            if s > HALF_N:
                s = N - s  # low-S normalization (bccsp/sw/ecdsa.go ToLowS)
            return r, s
        raise ValueError("bad fixed k")  # exhausted the fixed candidate

    def sign(self, msg: bytes) -> tuple[int, int]:
        return self.sign_digest(digest_int(msg))


def digest_int(msg: bytes) -> int:
    return int.from_bytes(hashlib.sha256(msg).digest(), "big")


def verify_digest(pub, e: int, r: int, s: int) -> bool:
    """Reference verify incl. Fabric's low-S rule."""
    if pub is INF or not (0 <= pub[0] < P and 0 <= pub[1] < P) or not is_on_curve(pub):
        return False
    if not (1 <= r < N and 1 <= s < N):
        return False
    if s > HALF_N:  # low-S enforcement per bccsp/sw/ecdsa.go:41-58
        return False
    w = pow(s, -1, N)
    u1 = (e * w) % N
    u2 = (r * w) % N
    pt = pt_add(pt_mul(u1, G), pt_mul(u2, pub))
    if pt is INF:
        return False
    return pt[0] % N == r % N


def verify(pub, msg: bytes, r: int, s: int) -> bool:
    return verify_digest(pub, digest_int(msg), r, s)
